"""Bucketed gradient comm for the dist KVStore (docs/PERF.md §11).

The reference KVStore's whole point at L5 was *overlap*: ``push(priority=)``
let layer N's gradient ride ps-lite while layer N-1's backward was still
running (kvstore_dist.h:275-313 sharded big arrays across servers by hand).
The first SPMD port dropped that — every push round re-concatenated every key
into a fresh flat buffer and ran one end-of-backward collective. This module
restores the overlap design TPU-natively:

* **Static bucket plan** — built ONCE from the first dist push round: keys
  are packed, in arrival (reverse-topo) order, into per-dtype buckets of
  ``MXNET_KVSTORE_BUCKET_MB`` (default 25 MB). Offsets are fixed forever, so
  the per-step variable-length ``jnp.concatenate`` + fresh ``device_put`` +
  retrace-prone shape wobble disappear: each bucket owns ONE compiled pack
  executable (concat+cast+pad fused by XLA) and ONE compiled collective.
* **Asynchronous flush** — a push writes its slot (functionally: the grad
  array is referenced, copy happens inside the compiled pack) and the bucket
  *flushes* — dispatches its collective via JAX async dispatch, non-blocking
  — the moment its last slot fills. Push order is reverse-topo (last layer
  first, ``kvstore_helper.update_params_on_kvstore``), so the deepest
  buckets' collectives are in flight while the host is still issuing the
  shallow layers' pushes; ``pull`` finalizes only its own key's bucket.
* **Sharded weight update** (``MXNET_KVSTORE_UPDATE=sharded``) — following
  "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
  Training" (PAPERS.md): reduce-scatter + per-shard optimizer update +
  all-gather replaces all-reduce + W-way replicated optimizer math. The
  jitted flat updater (``optimizer.flat_update_spec``) runs on this worker's
  1/W shard INSIDE the same compiled program as both collectives, cutting
  replicated update FLOPs/bytes W-fold and fusing update into the comm
  executable. Wire bytes drop from 2(W-1)/W·N (all-reduce) to the same
  2(W-1)/W·N but the optimizer reads/writes N/W instead of N.
* **Wire compression** (``MXNET_KVSTORE_COMM_DTYPE=bf16``) — fp32 buckets
  cast to bf16 at the pack, halving comm-buffer bytes; the compiled
  collective upcasts to fp32 before accumulating (sum never runs in bf16).

Telemetry (docs/OBSERVABILITY.md): ``kvstore.bucket_flushes`` /
``kvstore.bucket_flush_bytes`` counters, per-transport byte counters
(``kvstore.bytes.allreduce|reduce_scatter|all_gather``), the
``kvstore.overlap_ratio`` gauge (fraction of the push→pull round a
dispatched collective was in flight while the host did other work) and
``kvstore.bucket_flush`` spans.
"""
from __future__ import annotations

import hashlib
import logging
import os
import time
from collections import namedtuple
from typing import Dict, List, Optional

import numpy as np

from .base import MXNetError
from . import telemetry as _tm
from .ndarray import NDArray

__all__ = ["BucketPlan", "BucketSpec", "Slot", "BucketEngine",
           "bucket_bytes", "update_mode", "comm_dtype_for",
           "verify_digest_across_workers"]

log = logging.getLogger("mxnet_tpu.kvstore")

DEFAULT_BUCKET_MB = 25.0
# cross-worker key-set/order verification runs for the first N push rounds
DEFAULT_CHECK_ROUNDS = 3

# one contiguous piece of one key inside one bucket. Keys larger than the
# bucket cap split into parts across consecutive buckets — the reference's
# big-array sharding across servers (kvstore_dist.h:275-313) made literal:
# each part's collective dispatches independently, so a huge key's comm
# pipelines instead of serializing through one giant transfer.
#   offset   — element offset inside the bucket's flat buffer
#   src_off  — element offset inside the key's own flat data
#   part/n_parts — this piece's index / the key's total piece count
Slot = namedtuple("Slot", ["key", "offset", "size", "shape", "dtype",
                           "src_off", "part", "n_parts"])


def bucket_bytes() -> int:
    """Bucket capacity in bytes from MXNET_KVSTORE_BUCKET_MB (docs/ENV_VARS.md)."""
    raw = os.environ.get("MXNET_KVSTORE_BUCKET_MB", "")
    try:
        mb = float(raw) if raw else DEFAULT_BUCKET_MB
        if mb <= 0:
            raise ValueError(mb)
    except ValueError:
        log.warning("MXNET_KVSTORE_BUCKET_MB=%r is not a positive number; "
                    "using %g", raw, DEFAULT_BUCKET_MB)
        mb = DEFAULT_BUCKET_MB
    return max(1, int(mb * 1e6))


def update_mode() -> str:
    """MXNET_KVSTORE_UPDATE=replicated|sharded (docs/ENV_VARS.md)."""
    raw = os.environ.get("MXNET_KVSTORE_UPDATE", "replicated").lower()
    if raw in ("replicated", "sharded"):
        return raw
    log.warning("MXNET_KVSTORE_UPDATE=%r unknown (replicated|sharded); "
                "using replicated", raw)
    return "replicated"


def comm_dtype_for(dtype) -> str:
    """Wire dtype for a bucket of ``dtype`` under MXNET_KVSTORE_COMM_DTYPE.
    Only fp32 buckets compress (bf16 wire, fp32 accumulate); everything else
    ships as-is."""
    raw = os.environ.get("MXNET_KVSTORE_COMM_DTYPE", "").lower()
    if raw in ("", "0", "none", "off"):
        return str(dtype)
    if raw in ("bf16", "bfloat16"):
        return "bfloat16" if str(dtype) == "float32" else str(dtype)
    log.warning("MXNET_KVSTORE_COMM_DTYPE=%r unknown (bf16); ignoring", raw)
    return str(dtype)


class BucketSpec:
    """One bucket: a fixed window of keys at fixed offsets in a flat comm
    buffer. ``total`` is padded to a multiple of ``n_workers`` so the sharded
    update's reduce-scatter splits evenly."""

    def __init__(self, index, dtype, comm_dtype, slots, n_workers, priority):
        self.index = index
        self.dtype = str(dtype)           # parameter/accumulate dtype
        self.comm_dtype = str(comm_dtype)  # wire/pack dtype
        self.slots = list(slots)
        self.priority = priority           # max key priority (dispatch order)
        used = self.slots[-1].offset + self.slots[-1].size if self.slots else 0
        self.total = -(-used // n_workers) * n_workers  # ceil to W multiple
        self.pad = self.total - used

    @property
    def keys(self):
        return [s.key for s in self.slots]

    def describe(self):
        return {"index": self.index, "dtype": self.dtype,
                "comm_dtype": self.comm_dtype, "total": self.total,
                "pad": self.pad, "priority": self.priority,
                "slots": [tuple(s) for s in self.slots]}


class BucketPlan:
    """Deterministic one-time packing of a push round's keys into buckets.

    Built from the FIRST dist push round's arrival sequence (which
    ``update_params_on_kvstore`` emits in reverse-topo order with
    ``priority=-index``), then frozen: every process derives the identical
    plan from the identical sequence — verified by the cross-worker hash
    check in the engine."""

    def __init__(self, buckets, bucket_cap, n_workers):
        self.buckets: List[BucketSpec] = buckets
        self.bucket_cap = bucket_cap
        self.n_workers = n_workers
        # key -> [(bucket, slot), ...] in part order (len > 1: split key)
        self.key_to_slots: Dict = {}
        for b in buckets:
            for s in b.slots:
                self.key_to_slots.setdefault(s.key, []).append((b, s))
        for parts in self.key_to_slots.values():
            parts.sort(key=lambda bs: bs[1].part)
        self.hash = hashlib.sha1(
            repr([(b.dtype, b.comm_dtype, b.total,
                   [tuple(s) for s in b.slots]) for b in buckets]).encode()
        ).hexdigest()

    @staticmethod
    def build(records, n_workers, bucket_cap=None) -> "BucketPlan":
        """``records``: [(key, shape, dtype_str, priority)] in arrival order.
        Keys pack greedily per dtype in arrival order; a bucket closes when
        the next key would overflow ``bucket_cap`` bytes. A key LARGER than
        the cap splits into cap-sized parts across consecutive buckets (see
        ``Slot``): measured on the 8-process CPU fabric, chunked collectives
        pipeline where one monolithic transfer falls off gloo's throughput
        cliff (docs/PERF.md §11), and on ICI the same chunking bounds each
        executable's comm-buffer footprint."""
        if bucket_cap is None:
            bucket_cap = bucket_bytes()
        by_dtype: Dict[str, list] = {}
        order: List[str] = []
        for key, shape, dtype, priority in records:
            dt = str(dtype)
            if dt not in by_dtype:
                by_dtype[dt] = []
                order.append(dt)
            by_dtype[dt].append((key, tuple(shape), priority))
        buckets = []
        for dt in order:
            comm_dt = comm_dtype_for(dt)
            itemsize = np.dtype(comm_dt).itemsize
            cap_elems = max(n_workers, bucket_cap // itemsize)
            cur, cur_elems, cur_prio = [], 0, None

            def close():
                nonlocal cur, cur_elems, cur_prio
                if cur:
                    buckets.append(BucketSpec(len(buckets), dt, comm_dt, cur,
                                              n_workers, cur_prio))
                    cur, cur_elems, cur_prio = [], 0, None

            for key, shape, priority in by_dtype[dt]:
                size = int(np.prod(shape)) if shape else 1
                n_parts = -(-size // cap_elems)
                if n_parts == 1:
                    if cur_elems + size > cap_elems:
                        close()
                    offset = cur[-1].offset + cur[-1].size if cur else 0
                    cur.append(Slot(key, offset, size, shape, dt, 0, 0, 1))
                    cur_elems += size
                else:
                    # oversize key: split into cap-sized parts, each opening
                    # a fresh bucket; the tail part's bucket stays open for
                    # the following keys
                    close()
                    for part in range(n_parts):
                        src_off = part * cap_elems
                        psize = min(cap_elems, size - src_off)
                        cur.append(Slot(key, 0, psize, shape, dt,
                                        src_off, part, n_parts))
                        cur_elems = psize
                        cur_prio = priority
                        if part != n_parts - 1:
                            close()
                cur_prio = priority if cur_prio is None else max(cur_prio,
                                                                 priority)
            close()
        return BucketPlan(buckets, bucket_cap, n_workers)

    def describe(self):
        return {"hash": self.hash, "bucket_cap": self.bucket_cap,
                "n_workers": self.n_workers,
                "buckets": [b.describe() for b in self.buckets]}

    def describe_portable(self):
        """JSON-round-trippable describe() (tuples -> lists) — the slot map
        a checkpoint manifest records so any-world loaders can re-flatten
        the shard set (mxnet_tpu.checkpoint.per_key_states)."""
        d = self.describe()
        for b in d["buckets"]:
            b["slots"] = [[s[0], s[1], s[2], list(s[3])] + list(s[4:])
                          for s in b["slots"]]
        return d


# --------------------------------------------------------------------- flat
# The jittable flat optimizer kernels moved to ``optimizer.FLAT_KERNELS``
# so the row-sparse lazy update (optimizer.update_row_sparse,
# docs/SPARSE.md) and this engine's fused sharded update share ONE
# expression tree — sharded, replicated and lazy-sparse land within
# reassociation drift of each other. Re-exported under the old name for
# existing imports/tests.
from .optimizer import FLAT_KERNELS as _FLAT_KERNELS  # noqa: E402


class _BucketState:
    """Runtime state of one bucket within the current push round."""

    def __init__(self, spec):
        self.spec = spec
        self.slots: Dict = {}        # key -> flat jax array (this round)
        self.result = None            # dispatched collective output(s)
        self.t_dispatch = None
        self.partial = False          # flushed with missing slots

    def reset(self):
        self.slots.clear()
        self.result = None
        self.t_dispatch = None
        self.partial = False


class BucketEngine:
    """Per-KVStore comm engine: records the first push round, commits the
    plan, then runs every later round through compiled per-bucket
    collectives with async flush + per-bucket finalize."""

    def __init__(self, kv):
        self._kv = kv
        self._collective = None
        self.plan: Optional[BucketPlan] = None
        self._recording: List = []    # (key, merged NDArray, priority)
        self._states: Dict[int, _BucketState] = {}
        self._packs: Dict[int, object] = {}      # bucket idx -> jitted pack
        self._sharded_step: Dict[int, object] = {}
        self._sharded_state: Dict[int, dict] = {}
        self._mode = update_mode()
        self._mode_reason = None
        self._plan_records = None     # committed plan's records (for replan)
        self._preloaded_shards = {}   # bucket idx -> [np local state shards]
        self._pending_parts: Dict = {}  # split-key segments awaiting assembly
        self._ticked = set()          # keys whose update count ticked (round)
        self._round_seq: List = []    # (key, shape, dtype) arrival this round
        self._round_t0 = None
        self._round_flushes = []      # (t_dispatch, t_finalize) closed windows
        self._rounds_done = 0
        self._check_rounds = self._env_check_rounds()
        self._legacy_warned = False

    @staticmethod
    def _env_check_rounds():
        raw = os.environ.get("MXNET_KVSTORE_CHECK_STEPS", "")
        try:
            return int(raw) if raw else DEFAULT_CHECK_ROUNDS
        except ValueError:
            log.warning("MXNET_KVSTORE_CHECK_STEPS=%r not an int; using %d",
                        raw, DEFAULT_CHECK_ROUNDS)
            return DEFAULT_CHECK_ROUNDS

    # ------------------------------------------------------------------ util
    def _coll(self):
        if self._collective is None:
            from .kvstore import _Collective

            self._collective = _Collective.get()
        return self._collective

    @property
    def mode(self) -> str:
        """Effective update mode AFTER capability resolution ('sharded' only
        when the optimizer has a flat lowering and the store updates)."""
        return self._resolve_mode()

    def _resolve_mode(self):
        if self._mode != "sharded":
            return "replicated"
        if self._mode_reason is not None:
            return "replicated"
        opt = getattr(self._kv, "_optimizer", None)
        upd = getattr(self._kv, "_updater", None)
        if upd is None or opt is None:
            self._mode_reason = ("no kvstore optimizer (update_on_kvstore "
                                 "is off) — sharded update needs the "
                                 "updater to run inside the collective")
        elif opt.flat_update_spec() is None:
            self._mode_reason = ("optimizer %s has no flat_update_spec()"
                                 % type(opt).__name__)
        else:
            # per-key lr/wd mults DO work: they fold into the lr/wd segment
            # vectors gathered inside the compiled program
            return "sharded"
        log.warning("MXNET_KVSTORE_UPDATE=sharded unavailable: %s; "
                    "falling back to replicated", self._mode_reason)
        return "replicated"

    # ------------------------------------------------------------------ push
    def push(self, keys, merged_list, priority):
        """One push call's keys (already locally reduced), in order."""
        now = time.perf_counter()
        if self._round_t0 is None:
            self._round_t0 = now
        if self._rounds_done <= self._check_rounds:
            # consumed only inside the verify window — not worth per-step
            # host allocations for the rest of the job
            for k, m in zip(keys, merged_list):
                self._round_seq.append((k, tuple(m.shape), str(m.dtype)))
        if self.plan is None:
            recorded = {r[0] for r in self._recording}
            if not any(k in recorded for k in keys):
                for k, m in zip(keys, merged_list):
                    # snapshot the (immutable) jax buffer NOW: the caller may
                    # legally overwrite its NDArray between push and the
                    # plan-committing pull, and recording defers the read
                    self._recording.append(
                        (k, NDArray(m._jax(), ctx=m.context), priority))
                return
            # a key repeated before any pull: the round ended without a
            # read — commit what we have and continue bucketed below
            self._commit_plan()
        self._push_bucketed(keys, merged_list, priority)

    def _push_bucketed(self, keys, merged_list, priority):
        legacy_k, legacy_m = [], []
        for k, m in zip(keys, merged_list):
            parts = self.plan.key_to_slots.get(k)
            if parts is None:
                legacy_k.append(k)
                legacy_m.append(m)
                continue
            flat = None
            # a new push of this key opens a new round FOR THIS KEY: its
            # update count must tick again even if the previous round never
            # fully closed (subset pulls leave buckets in flight)
            self._ticked.discard(k)
            for bucket, slot in parts:
                st = self._states[bucket.index]
                sid = (k, slot.part)
                if sid in st.slots or st.result is not None:
                    # round restart for this bucket: drain it first — a
                    # not-yet-dispatched bucket must flush (partial) so the
                    # earlier push's gradient reduces+applies rather than
                    # being silently overwritten (reference: one updater
                    # application per push)
                    if st.result is None:
                        self._flush(st)
                    self._finalize(st)
                if flat is None:
                    flat = m._jax().reshape(-1)
                st.slots[sid] = (flat if slot.n_parts == 1 else
                                 flat[slot.src_off:slot.src_off + slot.size])
                if len(st.slots) == len(bucket.slots):
                    self._flush(st)
        if legacy_k:
            self._legacy_round(legacy_k, legacy_m)

    def before_read(self, keys):
        """Pull-side sync: commit the plan if still recording, then finalize
        ONLY the buckets the requested keys live in (plus flush any of their
        partially-filled buckets) — other buckets' collectives stay in
        flight."""
        if self.plan is None and self._recording:
            self._commit_plan()
        if self.plan is None:
            return
        touched = []
        for k in keys:
            for b, _slot in self.plan.key_to_slots.get(k, ()):
                if b.index not in touched:
                    touched.append(b.index)
        # deterministic flush order for not-yet-dispatched partial buckets:
        # priority desc, then plan order — identical on every worker
        pending = [self._states[i] for i in touched]
        for st in sorted((s for s in pending if s.result is None and s.slots),
                         key=lambda s: (-s.spec.priority, s.spec.index)):
            self._flush(st)
        for i in touched:
            self._finalize(self._states[i])
        if not any(s.result is not None or s.slots
                   for s in self._states.values()):
            self._close_round()

    def finalize_all(self):
        """Drain every in-flight/partial bucket (barrier, checkpoint...)."""
        if self.plan is None:
            if self._recording:
                self._commit_plan()
            else:
                return
        for st in sorted((s for s in self._states.values()
                          if s.result is None and s.slots),
                         key=lambda s: (-s.spec.priority, s.spec.index)):
            self._flush(st)
        for st in self._states.values():
            self._finalize(st)
        self._close_round()

    # ---------------------------------------------------------- resume/reform
    def preload_flat_shards(self, shards):
        """Seed the NEXT flat-state build from checkpoint shards: ``shards``
        maps bucket index -> [np local 1/W state slices] (this worker's).
        The live sharded state (if any) is dropped so the next flush
        rebuilds from the preload — the same-W shard-direct resume path of
        mxnet_tpu.checkpoint (momentum bit-parity: the exact bytes the
        checkpoint captured device_put straight back)."""
        self._preloaded_shards = dict(shards)
        self._sharded_state.clear()
        self._sharded_step.clear()
        # a load clears any prior capability veto: the caller proved the
        # optimizer/world alignment by matching the manifest digest
        if self._mode_reason and "partial push round" not in self._mode_reason:
            self._mode_reason = None

    def reseed_updater_states(self):
        """Drop flat sharded state so the next flush re-seeds from the
        per-key Updater states (the different-W / re-flattened resume path;
        also used after load_optimizer_states mid-run)."""
        self._preloaded_shards.clear()
        self._sharded_state.clear()
        self._sharded_step.clear()

    def reform(self, records=None):
        """Rebuild this engine for the CURRENT world (after an elastic
        re-form changed the process set, docs/FAULT_TOLERANCE.md): drop
        every compiled executable, collective handle and in-flight bucket,
        then re-plan the committed key sequence for the new worker count.
        The cross-worker plan-digest allgather re-verifies agreement, and
        the first-N round checks re-arm — a re-formed job gets the same
        validation a fresh one does."""
        records = records if records is not None else self._plan_records
        self._collective = None     # _Collective.get() re-keys on the backend
        self._states = {}
        self._packs = {}
        self._sharded_step = {}
        self._sharded_state = {}
        self._preloaded_shards = {}
        self._pending_parts = {}
        self._ticked = set()
        self._round_seq = []
        self._round_t0 = None
        self._round_flushes = []
        self.rearm_verify()
        self._mode = update_mode()
        self._mode_reason = None
        self.plan = None
        self._recording = []
        if records is not None:
            self._plan_records = list(records)
            self.plan = BucketPlan.build(records, self._coll().n_workers)
            self._states = {b.index: _BucketState(b)
                            for b in self.plan.buckets}
            log.info("KVStore bucket plan re-formed: %d keys -> %d "
                     "bucket(s) over %d worker(s), hash %s",
                     len(records), len(self.plan.buckets),
                     self._coll().n_workers, self.plan.hash[:12])
            self._verify_across_workers("plan:" + self.plan.hash)

    # ------------------------------------------------------------------ plan
    def _commit_plan(self):
        records = [(k, tuple(m.shape), str(m.dtype), p)
                   for k, m, p in self._recording]
        self._plan_records = records
        self.plan = BucketPlan.build(records, self._coll().n_workers)
        self._states = {b.index: _BucketState(b) for b in self.plan.buckets}
        log.info("KVStore bucket plan: %d keys -> %d bucket(s), cap %.1f MB, "
                 "update=%s, hash %s",
                 len(records), len(self.plan.buckets),
                 self.plan.bucket_cap / 1e6, self.mode, self.plan.hash[:12])
        self._verify_across_workers("plan:" + self.plan.hash)
        # a committed plan changes every subsequent round's wire layout:
        # re-open the first-N digest window over the new plan
        self.rearm_verify()
        # replay the recorded round through the fresh buckets (bypassing
        # push(): the round sequence already logged these keys)
        recorded, self._recording = self._recording, []
        for k, m, p in recorded:
            self._push_bucketed([k], [m], p)

    # ----------------------------------------------------------------- flush
    def _pack(self, st):
        """Compiled concat+cast+pad for one bucket (traced once: slot count,
        shapes, dtypes are all static)."""
        import jax
        import jax.numpy as jnp

        spec = st.spec
        fn = self._packs.get(spec.index)
        if fn is None:
            comm_dt = jnp.dtype(spec.comm_dtype)
            pad = spec.pad
            if (len(spec.slots) == 1 and not pad
                    and spec.comm_dtype == spec.slots[0].dtype):
                # single whole-bucket key, nothing to cast or pad: the row is
                # a metadata-only reshape, no executable needed
                fn = lambda f: f.reshape(1, -1)  # noqa: E731
            else:
                def pack(*flats):
                    parts = [f.astype(comm_dt) for f in flats]
                    if pad:
                        parts.append(jnp.zeros((pad,), comm_dt))
                    out = (jnp.concatenate(parts) if len(parts) > 1
                           else parts[0])
                    return out.reshape(1, -1)

                fn = jax.jit(pack)
            self._packs[spec.index] = fn
        flats = []
        for s in spec.slots:
            got = st.slots.get((s.key, s.part))
            if got is None:
                got = jnp.zeros((s.size,), jnp.dtype(s.dtype))
                st.partial = True
            flats.append(got)
        return fn(*flats)

    def _flush(self, st):
        """Dispatch this bucket's collective — non-blocking (JAX async
        dispatch): the call returns as soon as the executable is enqueued,
        and the host goes back to issuing the remaining pushes."""
        spec = st.spec
        coll = self._coll()
        wire = int(2 * (coll.n_workers - 1) / coll.n_workers * spec.total
                   * np.dtype(spec.comm_dtype).itemsize)
        row = self._pack(st)  # sets st.partial; span attrs must see it
        if self.mode == "sharded" and st.partial:
            # a missing slot means that key was not pushed this round; the
            # fused update would still apply wd/momentum to it — semantics
            # the replicated path does not have. Downgrade the ENGINE to
            # replicated FOR GOOD (a split key's state spans buckets, so a
            # per-bucket downgrade could leave a key half-sharded), seeding
            # the per-key updater states from the flat shards so momentum
            # history survives. Deterministic: 'partial' is SPMD-symmetric,
            # every worker downgrades together.
            self._downgrade_sharded()
        mode = self.mode
        sp = _tm.NULL_SPAN
        if _tm.enabled():
            _tm.counter("kvstore.bucket_flushes").inc()
            _tm.counter("kvstore.bucket_flush_bytes").inc(wire)
            sp = _tm.span("kvstore.bucket_flush", bucket=spec.index,
                          nkeys=len(spec.slots), bytes=wire,
                          priority=spec.priority, mode=mode,
                          comm_dtype=spec.comm_dtype,
                          partial=st.partial)
        with sp:
            if mode == "sharded":
                st.result = ("sharded", self._dispatch_sharded(st, row))
                if _tm.enabled():
                    _tm.counter("kvstore.bytes.reduce_scatter").inc(wire // 2)
                    _tm.counter("kvstore.bytes.all_gather").inc(wire // 2)
            else:
                st.result = ("replicated", coll.allreduce_rows(
                    row, acc_dtype=spec.dtype))
                if _tm.enabled():
                    _tm.counter("kvstore.bytes.allreduce").inc(wire)
        st.t_dispatch = time.perf_counter()

    def _gather_per_key_states(self):
        """All-gather every bucket's 1/W flat state shards and stitch them
        into per-key HOST arrays: ``(n_states, {key: [np, ...]})``. Split
        keys stitch their per-bucket segments; parts whose bucket never
        dispatched shardedly contribute zeros (the state a fresh Updater
        would lazily create). The all-gather is a COLLECTIVE — every
        current member must call this together. Read-only: the live
        sharded state is untouched."""
        if not self._sharded_state:
            return 0, {}
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        coll = self._coll()
        gather = jax.jit(lambda x: x,
                         out_shardings=NamedSharding(coll.mesh, P()))
        n_states = 0
        pending: Dict = {}  # key -> {part: [np state segments]}
        for spec in (s.spec for s in self._states.values()):
            sstate = self._sharded_state.get(spec.index)
            if sstate is None or not sstate["states"]:
                continue
            n_states = len(sstate["states"])
            full = [np.asarray(gather(s).addressable_data(0))
                    for s in sstate["states"]]
            for s in spec.slots:
                pending.setdefault(s.key, {})[s.part] = [
                    fs[s.offset:s.offset + s.size] for fs in full]
        if not n_states:
            return 0, {}
        out = {}
        for key, parts in pending.items():
            slots = [sl for _, sl in self.plan.key_to_slots[key]]
            segs = []
            for sl in slots:  # zeros for parts whose bucket never dispatched
                segs.append(parts.get(sl.part,
                                      [np.zeros((sl.size,),
                                                np.dtype(sl.dtype))
                                       for _ in range(n_states)]))
            shape = slots[0].shape
            out[key] = [np.concatenate([p[i] for p in segs]).reshape(shape)
                        if len(segs) > 1 else segs[0][i].reshape(shape)
                        for i in range(n_states)]
        return n_states, out

    def export_per_key_states(self):
        """Per-key optimizer states from the live flat shards, on host —
        the pause-time snapshot elastic recovery seeds from when no
        complete checkpoint exists (the all-gather path,
        docs/FAULT_TOLERANCE.md). Collective: requires the full CURRENT
        membership still participating (i.e. a DRAINING departure, not a
        crash). Finalizes in-flight buckets first. ``{}`` when the engine
        holds no flat state (replicated mode)."""
        self.finalize_all()
        _, states = self._gather_per_key_states()
        return states

    def _downgrade_sharded(self):
        """Move the WHOLE engine from the fused sharded update back to
        replicated, without losing optimizer history: drain any in-flight
        sharded buckets, all-gather every bucket's 1/W flat state shards,
        and seed the per-key Updater states the replicated path reads from
        now on."""
        if self._mode_reason is not None:
            return
        self._mode_reason = ("partial push round — bucket keys were not all "
                             "pushed; replicated from here on")
        # in-flight sharded results still need their sstate to finalize
        for st in self._states.values():
            if st.result is not None and st.result[0] == "sharded":
                self._finalize(st)
        if not self._sharded_state:
            return
        import jax.numpy as jnp

        log.warning(
            "KVStore: partial push round under MXNET_KVSTORE_UPDATE=sharded "
            "— downgrading to the replicated update (per-key optimizer "
            "states seeded from the flat shards; momentum history preserved)")
        n_states, per_key = self._gather_per_key_states()
        self._sharded_state.clear()
        self._sharded_step.clear()
        if not n_states:
            return
        upd = self._kv._updater
        for key, arrs in per_key.items():
            ctx = self._kv._store[key].context
            nds = [NDArray(jnp.asarray(a), ctx=ctx) for a in arrs]
            upd.states[key] = nds[0] if n_states == 1 else tuple(nds)

    # -------------------------------------------------------------- finalize
    def _finalize(self, st):
        if st.result is None:
            return
        kind, payload = st.result
        t_fin = time.perf_counter()
        self._round_flushes.append((st.t_dispatch, t_fin))
        spec = st.spec
        if kind == "sharded":
            w_full = payload[0]
            loc = w_full.addressable_data(0)
            sstate = self._sharded_state[spec.index]
            sstate["w_full"] = w_full
            sstate["states"] = payload[1:]
            for s in spec.slots:
                if s.offset == 0 and s.size == spec.total:
                    seg = loc
                else:
                    seg = loc[s.offset:s.offset + s.size]
                self._deliver(s, seg, is_weight=True)
        else:
            loc = payload.addressable_data(0)
            import jax.numpy as jnp

            dt = jnp.dtype(spec.dtype)
            for s in spec.slots:
                if (s.key, s.part) not in st.slots:
                    continue  # not pushed this round (partial flush)
                if s.offset == 0 and s.size == spec.total:
                    seg = loc  # whole-bucket slot: no slice dispatch
                else:
                    seg = loc[s.offset:s.offset + s.size]
                if seg.dtype != dt:
                    seg = seg.astype(dt)
                self._deliver(s, seg, is_weight=False)
        st.reset()

    def _deliver(self, slot, seg, is_weight):
        """Land one finalized slot. Whole keys apply immediately; a split
        key waits until every part's bucket finalized, then assembles."""
        kv = self._kv
        if slot.n_parts > 1:
            parts = self._pending_parts.setdefault(slot.key, {})
            parts[slot.part] = seg
            if len(parts) < slot.n_parts:
                return
            import jax.numpy as jnp

            seg = jnp.concatenate([parts[p] for p in range(slot.n_parts)])
            del self._pending_parts[slot.key]
        value = NDArray(seg.reshape(slot.shape),
                        ctx=kv._store[slot.key].context)
        if is_weight or kv._updater is None:
            kv._store[slot.key] = value
        else:
            kv._updater(slot.key, value, kv._store[slot.key])

    def _close_round(self):
        """End-of-round bookkeeping: overlap telemetry + first-N verify."""
        if self._round_t0 is None:
            return
        if self._round_flushes and _tm.enabled():
            t_end = max(f[1] for f in self._round_flushes)
            span = t_end - self._round_t0
            inflight = sum(f[1] - f[0] for f in self._round_flushes)
            ratio = min(1.0, inflight / span) if span > 0 else 0.0
            _tm.gauge("kvstore.overlap_ratio").set(round(ratio, 4))
            _tm.timer("kvstore.comm_inflight").add(inflight)
        seq, self._round_seq = self._round_seq, []
        self._round_t0 = None
        self._round_flushes = []
        self._ticked.clear()
        self._rounds_done += 1
        if self._rounds_done <= self._check_rounds:
            self._verify_across_workers(repr(seq))

    # ------------------------------------------------------------ validation
    def rearm_verify(self):
        """Re-open the first-N digest window: the next
        MXNET_KVSTORE_CHECK_STEPS rounds allgather-verify the key sequence
        again. Called after anything that can desynchronize the workers'
        push streams — an elastic ``reform``, a bucket re-plan — so a
        divergence the change introduced fails loudly instead of
        deadlocking inside a later collective."""
        self._rounds_done = 0

    def _verify_across_workers(self, payload: str):
        """Cheap cross-worker agreement check: allgather a 4-byte digest of
        this round's key sequence (or the plan hash) and compare. Catches
        mismatched key sets/orders that would otherwise deadlock or silently
        misreduce inside the collective. Gated to the first
        MXNET_KVSTORE_CHECK_STEPS rounds — steady state costs nothing."""
        verify_digest_across_workers(payload, self._check_rounds,
                                     self._allgather_digest)

    @staticmethod
    def _allgather_digest(arr):
        from jax.experimental.multihost_utils import process_allgather

        return np.asarray(process_allgather(arr)).reshape(-1)

    # ---------------------------------------------------------------- legacy
    def _legacy_round(self, keys, merged_list):
        """Keys outside the committed plan (pushed for the first time after
        round 1): immediate batched collective, the pre-bucket path."""
        kv = self._kv
        if not self._legacy_warned:
            log.info("KVStore: %d key(s) outside the bucket plan (first seen "
                     "after the planning round) ride the unbucketed "
                     "collective: %s", len(keys), keys[:4])
            self._legacy_warned = True
        reduced = kv._allreduce_batch(merged_list)
        for k, merged in zip(keys, reduced):
            if kv._updater is not None:
                kv._updater(k, merged, kv._store[k])
            else:
                kv._store[k] = merged

    # --------------------------------------------------------------- sharded
    def _dispatch_sharded(self, st, row):
        """Fused reduce-scatter + 1/W-shard optimizer update + all-gather,
        ONE compiled program per bucket."""
        spec = st.spec
        step = self._sharded_step.get(spec.index)
        if step is None:
            step = self._build_sharded(spec)
            self._sharded_step[spec.index] = step
        sstate = self._sharded_state[spec.index]
        lr_seg, wd_seg = self._lr_wd_segments(spec)
        coll = self._coll()
        g_rows = coll.make_global_rows(row)
        return step["fn"](g_rows, sstate["w_full"], *sstate["states"],
                          lr_seg, wd_seg, sstate["idx"])

    def _lr_wd_segments(self, spec):
        """Per-unique-(lr,wd) segment values for this flush. The bucket's
        static uint8 index map gathers them to per-element vectors inside
        the compiled program; only these tiny arrays cross host->device per
        step, and the host also ticks the per-key update counts so lr
        schedules stay bit-identical with the replicated path."""
        opt = self._kv._optimizer
        kind, hyper, _ = opt.flat_update_spec()
        per_key = []
        for s in spec.slots:
            if s.key not in self._ticked:
                # once per key per ROUND (a split key's other parts flush
                # from other buckets and must see the same count)
                opt._update_count(s.key)
                self._ticked.add(s.key)
            lr, wd = opt._get_lr(s.key), opt._get_wd(s.key)
            if kind == "adam":
                # keyed on the SPEC kind, not the class name: Adam
                # subclasses inheriting the adam flat kernel need the same
                # host-side bias-correction fold Adam.update applies
                import math

                t = opt._index_update_count[s.key]
                lr *= (math.sqrt(1.0 - hyper["beta2"] ** t)
                       / (1.0 - hyper["beta1"] ** t))
            per_key.append((lr, wd))
        uniq = {}
        for lw in per_key:
            uniq.setdefault(lw, len(uniq))
        lr_seg = np.zeros((len(uniq),), np.float32)
        wd_seg = np.zeros((len(uniq),), np.float32)
        for (lr, wd), i in uniq.items():
            lr_seg[i], wd_seg[i] = lr, wd
        sstate = self._sharded_state[spec.index]
        ordinals = tuple(uniq[lw] for lw in per_key)
        if sstate.get("idx_ordinals") != ordinals:
            sstate["idx"] = self._build_idx(spec, ordinals)
            sstate["idx_ordinals"] = ordinals
        return lr_seg, wd_seg

    def _build_idx(self, spec, ordinals):
        """Static per-element key-segment map, sharded over workers (uint8:
        ≤256 distinct (lr,wd) segments per bucket — 1/4 the footprint of a
        per-element fp32 lr vector)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if len(set(ordinals)) > 256:
            raise MXNetError("bucket %d has >256 distinct (lr,wd) segments"
                             % spec.index)
        coll = self._coll()
        idx = np.zeros((spec.total,), np.uint8)
        for s, o in zip(spec.slots, ordinals):
            idx[s.offset:s.offset + s.size] = o
        shard = spec.total // coll.n_workers
        r = coll.rank
        local = jax.device_put(idx[r * shard:(r + 1) * shard],
                               coll.my_device)
        return jax.make_array_from_single_device_arrays(
            (spec.total,), NamedSharding(coll.mesh, P("worker")), [local])

    def _build_sharded(self, spec):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .parallel.mesh import shard_map_compat

        coll = self._coll()
        opt = self._kv._optimizer
        kind, hyper, n_states = opt.flat_update_spec()
        kernel = _FLAT_KERNELS[kind](hyper)
        mesh = coll.mesh
        W = coll.n_workers
        shard = spec.total // W
        acc_dt = jnp.dtype(spec.dtype)

        def body(g_rows, w_full, *rest):
            states = rest[:n_states]
            lr_seg, wd_seg, idx = rest[n_states:]
            g = g_rows.reshape(-1).astype(acc_dt)
            g = jax.lax.psum_scatter(g, "worker", scatter_dimension=0,
                                     tiled=True)
            r = jax.lax.axis_index("worker")
            w = jax.lax.dynamic_slice(w_full, (r * shard,), (shard,))
            lr = lr_seg[idx]
            wd = wd_seg[idx]
            w_new, new_states = kernel(w, g, states, lr, wd)
            w_gathered = jax.lax.all_gather(w_new, "worker", tiled=True)
            return (w_gathered,) + tuple(new_states)

        in_specs = ((P("worker", None), P(None))
                    + (P("worker"),) * n_states
                    + (P(None), P(None), P("worker")))
        out_specs = (P(None),) + (P("worker"),) * n_states
        fn = jax.jit(shard_map_compat(body, mesh, in_specs=in_specs,
                                      out_specs=out_specs))
        # persistent flat weight (replicated) + optimizer state (sharded).
        # States seed, in priority order, from (1) a preloaded checkpoint
        # shard (same-W shard-direct resume, mxnet_tpu.checkpoint — this
        # worker's 1/W slice device_puts straight in, bit-parity by
        # construction), (2) the per-key Updater states when present (a
        # resume via load_optimizer_states must not silently restart
        # momentum at zero), else (3) zeros — what a fresh Updater would
        # lazily create.
        preloaded = self._preloaded_shards.pop(spec.index, None)
        states = []
        for i in range(n_states):
            if preloaded is not None:
                loc = np.asarray(preloaded[i]).reshape(-1)
                if loc.shape[0] != shard:
                    raise MXNetError(
                        "preloaded checkpoint shard for bucket %d has %d "
                        "elements, expected %d — plan/world mismatch "
                        "(the manifest digest guard should have caught this)"
                        % (spec.index, loc.shape[0], shard))
                host_local = loc
            else:
                host = np.zeros((spec.total,), spec.dtype)
                for s in spec.slots:
                    loaded = self._kv._updater.states.get(s.key)
                    if loaded is None:
                        continue
                    if n_states > 1 and not isinstance(loaded, (tuple, list)):
                        continue  # foreign-optimizer state layout: start fresh
                    part = loaded if n_states == 1 else loaded[i]
                    flat_part = np.asarray(part._jax()).reshape(-1)
                    host[s.offset:s.offset + s.size] = \
                        flat_part[s.src_off:s.src_off + s.size]
                host_local = host[coll.rank * shard:(coll.rank + 1) * shard]
            s_local = jax.device_put(
                jnp.asarray(host_local, dtype=acc_dt), coll.my_device)
            states.append(jax.make_array_from_single_device_arrays(
                (spec.total,), NamedSharding(mesh, P("worker")), [s_local]))
        self._sharded_state[spec.index] = {
            "w_full": self._weights_from_store(spec),
            "states": tuple(states)}
        return {"fn": fn, "n_states": n_states}

    def _weights_from_store(self, spec):
        """Assemble the bucket's persistent flat weight buffer (replicated
        global array) from the current store values."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        coll = self._coll()
        kv = self._kv
        w_parts = [np.asarray(kv._store[s.key]._jax()).reshape(-1)
                   [s.src_off:s.src_off + s.size].astype(spec.dtype)
                   for s in spec.slots]
        if spec.pad:
            w_parts.append(np.zeros((spec.pad,), spec.dtype))
        w_host = np.concatenate(w_parts) if len(w_parts) > 1 else w_parts[0]
        w_local = jax.device_put(jnp.asarray(w_host), coll.my_device)
        return jax.make_array_from_single_device_arrays(
            (spec.total,), NamedSharding(coll.mesh, P()), [w_local])


def verify_digest_across_workers(payload: str, check_rounds: int,
                                 allgather) -> None:
    """Allgather a 4-byte sha1 of ``payload`` and require every rank to
    agree — the shared core of the BucketEngine round/plan checks and the
    monolithic ``KVStore._verify_push_round`` window."""
    import jax

    if jax.process_count() == 1:
        return
    # uint32: jax's 32-bit default would silently truncate a wider
    # digest inside the allgather and fail the compare on matching keys
    digest = hashlib.sha1(payload.encode()).digest()[:4]
    mine = np.frombuffer(digest, dtype=np.uint32)
    theirs = allgather(mine)
    if not (theirs == mine[0]).all():
        bad = {int(r): hex(int(v)) for r, v in enumerate(theirs)}
        raise MXNetError(
            "dist KVStore workers disagree on the pushed key "
            "set/order this round (digest by rank: %s). Every worker "
            "must push the same keys in the same order — check for "
            "rank-dependent branches around kv.push. (Verified for the "
            "first %d rounds; set MXNET_KVSTORE_CHECK_STEPS to tune.)"
            % (bad, check_rounds))
