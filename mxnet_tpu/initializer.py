"""Weight initializers.

Counterpart of the reference's python/mxnet/initializer.py: an Initializer is
called with (InitDesc/name, NDArray) and dispatches on the name suffix
(weight/bias/gamma/beta/moving_* ...), with ``__init__`` attrs on variables
overriding the default (attr-driven dispatch, initializer.py InitDesc).
Random draws go through the framework PRNG (mx.random), so seeding is
reproducible the JAX way.
"""
from __future__ import annotations

import json
import logging
import re

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from . import random as _random

__all__ = [
    "InitDesc",
    "Initializer",
    "Uniform",
    "Normal",
    "Zero",
    "One",
    "Constant",
    "Orthogonal",
    "Xavier",
    "MSRAPrelu",
    "Bilinear",
    "LSTMBias",
    "FusedRNN",
    "Mixed",
    "Load",
    "register",
    "create",
]

_INIT_REGISTRY = {}


def register(klass):
    _INIT_REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, *args, **kwargs):
    if isinstance(name, Initializer):
        return name
    if name.lower() not in _INIT_REGISTRY:
        raise MXNetError("unknown initializer %r" % name)
    return _INIT_REGISTRY[name.lower()](*args, **kwargs)


class InitDesc(str):
    """Name + attrs descriptor handed to initializers (reference: InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base: dispatch by variable-name convention, like the reference."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, str):
            raise TypeError("desc must be a string or InitDesc")
        if isinstance(desc, InitDesc) and desc.attrs.get("__init__"):
            klass, kwargs = json.loads(desc.attrs["__init__"])
            create(klass, **kwargs)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("upsampling"):
            self._init_bilinear(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("moving_var"):
            self._init_one(desc, arr)
        elif name.endswith("moving_inv_var"):
            self._init_zero(desc, arr)
        elif name.endswith("moving_avg"):
            self._init_zero(desc, arr)
        elif name.endswith("label"):
            # a label variable bound as a param (Module(label_names=None) for
            # inference, the reference's benchmark_score pattern) — zeros
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    # --- leaf initializers ------------------------------------------------
    def _init_bilinear(self, _, arr):
        # separable triangle filter over the trailing H×W plane, tiled over
        # the leading dims (vectorized; the reference fills element-wise)
        h, w = arr.shape[2], arr.shape[3]
        f = np.ceil(w / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        wx = 1.0 - np.abs(np.arange(w) / f - c)
        wy = 1.0 - np.abs(np.arange(h) / f - c)
        arr[:] = np.broadcast_to(np.outer(wy, wx), arr.shape)

    # constant-fill family (aux moving stats, biases, BN gamma/beta): one
    # factory, six bindings — subclasses may still override any name
    def _const_fill(value):  # noqa: N805 — class-body factory, not a method
        def _impl(self, _desc, arr):
            arr[:] = value

        return _impl

    _init_zero = _init_bias = _init_beta = _const_fill(0.0)
    _init_one = _init_gamma = _const_fill(1.0)
    del _const_fill

    def _init_weight(self, name, arr):
        raise NotImplementedError("Must override it")

    def _init_default(self, name, arr):
        raise ValueError(
            "Unknown initialization pattern for %s. Default initialization is now "
            "limited to %r. Name a variable with one of those suffixes or set its "
            "init attr explicitly." % (name, '"weight", "bias", "gamma", "beta"')
        )


@register
class Load:
    """Init from a dict of arrays (checkpoint), falling back to ``default_init``."""

    def __init__(self, param, default_init=None, verbose=False):
        self.param = {
            k[4:] if k.startswith("arg:") or k.startswith("aux:") else k: v
            for k, v in param.items()
        }
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            if tuple(arr.shape) != tuple(self.param[name].shape):
                raise MXNetError(
                    "Parameter %s cannot be initialized from loading: shape %s vs %s"
                    % (name, arr.shape, self.param[name].shape)
                )
            arr[:] = self.param[name]
            if self.verbose:
                logging.info("Initialized %s by loading", name)
        else:
            if self.default_init is None:
                raise MXNetError(
                    "Cannot Initialize %s. Not found in loaded param and no default init" % name
                )
            self.default_init(name, arr)


@register
class Mixed:
    """Regex-pattern → initializer table (reference: Mixed)."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise MXNetError("patterns and initializers must have the same length")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise MXNetError("Parameter name %s did not match any pattern" % name)


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 0.0

    def _init_default(self, _, arr):
        arr[:] = 0.0


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 1.0

    def _init_default(self, _, arr):
        arr[:] = 1.0


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        arr[:] = self.value

    def _init_default(self, _, arr):
        arr[:] = self.value


@register
class Uniform(Initializer):
    """U(-scale, scale) weights (reference: Uniform)."""

    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        arr[:] = nd.random_uniform(low=-self.scale, high=self.scale, shape=arr.shape, ctx=arr.context)


@register
class Normal(Initializer):
    """N(0, sigma) weights (reference: Normal)."""

    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        arr[:] = nd.random_normal(loc=0.0, scale=self.sigma, shape=arr.shape, ctx=arr.context)


@register
class Orthogonal(Initializer):
    """Orthogonal matrix init via SVD/QR (reference: Orthogonal, Saxe et al.)."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        rs = np.random.RandomState(_random._next_seed())
        if self.rand_type == "uniform":
            tmp = rs.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = rs.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = (self.scale * q).reshape(arr.shape).astype(np.float32)


@register
class Xavier(Initializer):
    """Glorot init (reference: initializer.py:344)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type, magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = np.prod(shape[2:]) if len(shape) > 2 else 1.0
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factors = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in,
                   "out": fan_out}
        if self.factor_type not in factors:
            raise ValueError("Incorrect factor type")
        scale = np.sqrt(self.magnitude / factors[self.factor_type])
        if self.rnd_type == "uniform":
            arr[:] = nd.random_uniform(low=-scale, high=scale, shape=arr.shape, ctx=arr.context)
        elif self.rnd_type == "gaussian":
            arr[:] = nd.random_normal(loc=0.0, scale=scale, shape=arr.shape, ctx=arr.context)
        else:
            raise ValueError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    """Kaiming/He init for PReLU nets (reference: MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, _, arr):
        self._init_bilinear(_, arr)


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (reference: LSTMBias); gate order [i, f, c, o]."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = np.zeros(arr.shape, dtype=np.float32)
        num_hidden = int(b.shape[0] / 4)
        b[num_hidden : 2 * num_hidden] = self.forget_bias
        arr[:] = b

    def _init_default(self, name, arr):
        self._init_weight(name, arr)


@register
class FusedRNN(Initializer):
    """Init the packed parameter vector of the fused RNN op by unpacking it,
    running ``init`` per block, and repacking (reference: FusedRNN)."""

    def __init__(self, init, num_hidden, num_layers, mode, bidirectional=False, forget_bias=1.0):
        if isinstance(init, str):
            klass, kwargs = json.loads(init)
            init = create(klass, **kwargs)
        super().__init__(
            init=init.dumps() if init is not None else None,
            num_hidden=num_hidden,
            num_layers=num_layers,
            mode=mode,
            bidirectional=bidirectional,
            forget_bias=forget_bias,
        )
        self._init = init
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        from .rnn.rnn_cell import FusedRNNCell

        cell = FusedRNNCell(
            self._num_hidden,
            self._num_layers,
            self._mode,
            self._bidirectional,
            forget_bias=self._forget_bias,
            prefix="",
        )
        args = cell.unpack_weights({"parameters": arr.copy()})
        for name in args:
            desc_i = InitDesc(name, getattr(desc, "attrs", {}))
            if self._mode == "lstm" and name.endswith("_f_bias"):
                args[name][:] = self._forget_bias
            elif self._init is not None:
                self._init(desc_i, args[name])
        arr[:] = cell.pack_weights(args)["parameters"]
