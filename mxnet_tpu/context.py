"""Device contexts.

TPU-native re-design of the reference's ``Context`` (include/mxnet/base.h:116-207,
python/mxnet/context.py). A ``Context`` names a logical device: ``cpu(i)``,
``tpu(i)``, or ``gpu(i)``. On this build the accelerator is a TPU; ``gpu(i)``
is accepted for script compatibility and resolves to the TPU chip when no GPU
exists, so reference training scripts run unmodified with their ``--gpus`` flags.

Each Context resolves lazily to a concrete ``jax.Device``. ``cpu(i)`` for i>0
maps onto virtual host devices when ``--xla_force_host_platform_device_count``
is set (the multi-device-without-hardware test trick, SURVEY.md §4), else all
cpu ids alias device 0 — same semantics as the reference where cpu dev_id is a
hint (include/mxnet/base.h:141-143).
"""
from __future__ import annotations

import threading

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context", "num_gpus", "num_tpus"]


class Context:
    """Logical device context, usable as a ``with`` scope like the reference."""

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 4: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "tpu": 4}
    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            if device_type not in Context.devstr2type:
                raise MXNetError("unknown device type %r" % (device_type,))
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx = None

    @property
    def device_type(self) -> str:
        return Context.devtype2str[self.device_typeid]

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    def __enter__(self):
        self._old_ctx = getattr(Context._default_ctx, "value", None)
        Context._default_ctx.value = self
        return self

    def __exit__(self, ptype, value, trace):
        Context._default_ctx.value = self._old_ctx

    # -- JAX resolution ----------------------------------------------------
    @property
    def jax_device(self):
        """Resolve to a concrete jax.Device."""
        import jax

        if self.device_type in ("cpu", "cpu_pinned"):
            # local_devices: in a multi-process job each process may only
            # address its own devices (jax.devices() lists the whole job's)
            devs = [d for d in jax.local_devices() if d.platform == "cpu"]
            if not devs:
                try:
                    devs = jax.local_devices(backend="cpu")
                except RuntimeError:
                    devs = jax.devices("cpu")
            return devs[self.device_id % len(devs)]
        accels = _accelerator_devices()
        if not accels:
            if self.device_type == "gpu":
                raise MXNetError("no GPU/TPU device available for %r" % self)
            raise MXNetError("no TPU device available")
        return accels[self.device_id % len(accels)]

    def empty_cache(self):  # parity with later mxnet; no-op under PJRT
        pass


def _accelerator_devices():
    import jax

    try:
        devs = jax.local_devices()
    except RuntimeError:
        return []
    return [d for d in devs if d.platform != "cpu"]


def cpu(device_id=0):
    return Context("cpu", device_id)


def gpu(device_id=0):
    """GPU context; resolves to the TPU on GPU-less TPU hosts (compat shim)."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    return Context("tpu", device_id)


def num_gpus():
    return len(_accelerator_devices())


def num_tpus():
    return len(_accelerator_devices())


def current_context() -> Context:
    ctx = getattr(Context._default_ctx, "value", None)
    if ctx is None:
        import os

        forced = os.environ.get("MXNET_DEFAULT_CONTEXT", "")
        if forced:
            name, _, idx = forced.partition(":")
            ctx = Context(name, int(idx or 0))
        else:
            # TPU-first: default to the accelerator when present, else cpu.
            ctx = tpu(0) if _accelerator_devices() else cpu(0)
        Context._default_ctx.value = ctx
    return ctx
