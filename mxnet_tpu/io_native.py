"""ctypes bindings for the native IO runtime (src/io_native.cc).

The library is compiled on first use with the system toolchain and cached
under ``build/``; every consumer (recordio readers, MNISTIter) falls back to
the pure-python implementations when no compiler is available, so the
framework never hard-requires the native path — it's the throughput path
(threaded read-ahead off the GIL), mirroring the reference's PrefetcherIter
(src/io/iter_prefetcher.h:28).
"""
from __future__ import annotations

import ctypes
import os
import threading

import numpy as np

__all__ = ["available", "NativeRecordIOReader", "NativePrefetchReader", "read_idx"]

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_ROOT, "src", "io_native.cc")

_lib = None
_lock = threading.Lock()
_build_failed = False


def _load():
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        from ._native_build import build_lib

        path = build_lib(_SRC, "libmxtpu_io.so")
        if path is None:
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(path)
        except Exception:
            _build_failed = True
            return None
        lib.mxio_recordio_open.restype = ctypes.c_void_p
        lib.mxio_recordio_open.argtypes = [ctypes.c_char_p]
        lib.mxio_recordio_next.restype = ctypes.c_int
        lib.mxio_recordio_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
            ctypes.POINTER(ctypes.c_uint64)]
        lib.mxio_recordio_close.argtypes = [ctypes.c_void_p]
        lib.mxio_prefetch_open.restype = ctypes.c_void_p
        lib.mxio_prefetch_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.mxio_prefetch_next.restype = ctypes.c_int
        lib.mxio_prefetch_next.argtypes = lib.mxio_recordio_next.argtypes
        lib.mxio_prefetch_close.argtypes = [ctypes.c_void_p]
        lib.mxio_free.argtypes = [ctypes.c_void_p]
        lib.mxio_idx_read.restype = ctypes.c_int
        lib.mxio_idx_read.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int64)]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


class _Reader:
    _OPEN = None
    _NEXT = None
    _CLOSE = None

    def __init__(self, path, *open_args):
        lib = _load()
        if lib is None:
            raise RuntimeError("native IO library unavailable")
        self._lib = lib
        self._handle = getattr(lib, self._OPEN)(path.encode(), *open_args)
        if not self._handle:
            raise IOError("cannot open %s" % path)

    def read(self):
        """Next record as bytes, or None at EOF."""
        data = ctypes.POINTER(ctypes.c_char)()
        size = ctypes.c_uint64()
        ok = getattr(self._lib, self._NEXT)(self._handle, ctypes.byref(data),
                                            ctypes.byref(size))
        if not ok:
            return None
        try:
            return ctypes.string_at(data, size.value)
        finally:
            self._lib.mxio_free(data)

    def __iter__(self):
        while True:
            rec = self.read()
            if rec is None:
                return
            yield rec

    def close(self):
        if self._handle:
            getattr(self._lib, self._CLOSE)(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeRecordIOReader(_Reader):
    """Sequential native reader."""

    _OPEN = "mxio_recordio_open"
    _NEXT = "mxio_recordio_next"
    _CLOSE = "mxio_recordio_close"


class NativePrefetchReader(_Reader):
    """Reader with a background producer thread + bounded queue."""

    _OPEN = "mxio_prefetch_open"
    _NEXT = "mxio_prefetch_next"
    _CLOSE = "mxio_prefetch_close"

    def __init__(self, path, capacity=16):
        super().__init__(path, capacity)


def read_idx(path):
    """Parse an MNIST idx file into a numpy uint8 array (native fast path;
    reference: src/io/iter_mnist.cc LoadImg/LoadLabel)."""
    lib = _load()
    if lib is None:
        return _read_idx_py(path)
    out = ctypes.POINTER(ctypes.c_ubyte)()
    size = ctypes.c_uint64()
    ndim = ctypes.c_int()
    dims = (ctypes.c_int64 * 4)()
    ok = lib.mxio_idx_read(path.encode(), ctypes.byref(out), ctypes.byref(size),
                           ctypes.byref(ndim), dims)
    if not ok:
        raise IOError("cannot parse idx file %s" % path)
    try:
        shape = tuple(dims[i] for i in range(ndim.value))
        arr = np.ctypeslib.as_array(out, shape=(size.value,)).copy()
    finally:
        lib.mxio_free(out)
    return arr.reshape(shape)


def _read_idx_py(path):
    with open(path, "rb") as f:
        magic = f.read(4)
        n = magic[3]
        shape = tuple(int.from_bytes(f.read(4), "big") for _ in range(n))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)
