"""Matrix-factorization recommender (counterpart of the reference-era
example/recommenders): user/item ``Embedding`` rows multiplied and summed
to predict a rating, trained with ``LinearRegressionOutput``. Exercises
the two-Embedding + elementwise-reduce composition and an RMSE metric —
regression, where every other example classifies.

Synthetic low-rank data: ratings come from hidden rank-``k`` user/item
factors plus noise, so the model's achievable RMSE is the noise floor.

    MXNET_DEFAULT_CONTEXT=cpu python example/recommenders/matrix_fact.py
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
import mxnet_tpu as mx


def make_ratings(n_users, n_items, n_obs, rank, noise, rs):
    # factor scale rank**-0.25 → ratings come out unit-variance, which keeps
    # the regression gradients at a healthy magnitude for plain SGD/Adam
    u_f = (rs.randn(n_users, rank) * rank ** -0.25).astype("float32")
    i_f = (rs.randn(n_items, rank) * rank ** -0.25).astype("float32")
    users = rs.randint(0, n_users, n_obs).astype("float32")
    items = rs.randint(0, n_items, n_obs).astype("float32")
    r = (u_f[users.astype(int)] * i_f[items.astype(int)]).sum(axis=1)
    r = r + rs.randn(n_obs).astype("float32") * noise
    return users, items, r.astype("float32")


def build_symbol(n_users, n_items, rank):
    user = mx.sym.Variable("user")
    item = mx.sym.Variable("item")
    score = mx.sym.Variable("score_label")
    u = mx.sym.Embedding(user, input_dim=n_users, output_dim=rank, name="u_emb")
    v = mx.sym.Embedding(item, input_dim=n_items, output_dim=rank, name="i_emb")
    pred = mx.sym.sum(u * v, axis=1)
    return mx.sym.LinearRegressionOutput(pred, label=score, name="lr")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--users", type=int, default=300)
    ap.add_argument("--items", type=int, default=200)
    ap.add_argument("--rank", type=int, default=8)
    ap.add_argument("--noise", type=float, default=0.05)
    ap.add_argument("--num-obs", type=int, default=20000)
    ap.add_argument("--num-epochs", type=int, default=15)
    ap.add_argument("--lr", type=float, default=0.02)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    rs = np.random.RandomState(17)
    users, items, r = make_ratings(args.users, args.items, args.num_obs,
                                   args.rank, args.noise, rs)
    n_tr = int(args.num_obs * 0.9)
    train = mx.io.NDArrayIter(
        {"user": users[:n_tr], "item": items[:n_tr]},
        {"score_label": r[:n_tr]}, batch_size=args.batch_size, shuffle=True,
        last_batch_handle="discard")
    val = mx.io.NDArrayIter(
        {"user": users[n_tr:], "item": items[n_tr:]},
        {"score_label": r[n_tr:]}, batch_size=args.batch_size,
        last_batch_handle="discard")

    net = build_symbol(args.users, args.items, args.rank)
    mod = mx.mod.Module(net, data_names=("user", "item"),
                        label_names=("score_label",))
    mod.fit(train, eval_data=val, eval_metric=mx.metric.RMSE(),
            optimizer="adam", optimizer_params={"learning_rate": args.lr},
            initializer=mx.init.Normal(0.3),
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 100))
    score = mod.score(val, mx.metric.RMSE())
    print("validation RMSE %.4f (noise floor %.2f)" % (score[0][1], args.noise))


if __name__ == "__main__":
    main()
