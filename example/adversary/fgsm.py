"""Fast-gradient-sign adversarial examples (counterpart of the reference's
example/adversary): train a small conv net, then perturb inputs by
``eps * sign(dL/dx)`` and measure the accuracy collapse. The API exercise
is ``inputs_need_grad=True`` + ``get_input_grads()`` on a Module bound for
training — the input-gradient path used here to attack rather than to
chain modules (as the GAN example does).

Synthetic, egress-free data: two-class 16x16 images whose class is the
sign of a fixed low-frequency template's correlation — easy to learn,
and the FGSM direction is exactly the template, so the attack works at
small eps.

    MXNET_DEFAULT_CONTEXT=cpu python example/adversary/fgsm.py
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
import mxnet_tpu as mx


def make_images(n, size, rs):
    yy, xx = np.mgrid[0:size, 0:size].astype("float32") / size
    template = np.sin(2 * np.pi * yy) * np.cos(2 * np.pi * xx)
    template /= np.sqrt((template ** 2).sum())
    coef = rs.randn(n).astype("float32")
    x = coef[:, None, None] * template[None] + rs.randn(n, size, size).astype("float32") * 0.3
    y = (coef > 0).astype("float32")
    return x[:, None, :, :], y


def build_symbol():
    data = mx.sym.Variable("data")
    h = mx.sym.Activation(mx.sym.Convolution(
        data, num_filter=8, kernel=(3, 3), pad=(1, 1), name="c1"),
        act_type="relu")
    h = mx.sym.Pooling(h, pool_type="max", kernel=(2, 2), stride=(2, 2))
    h = mx.sym.Activation(mx.sym.Convolution(
        h, num_filter=16, kernel=(3, 3), pad=(1, 1), name="c2"),
        act_type="relu")
    h = mx.sym.Pooling(h, pool_type="max", kernel=(2, 2), stride=(2, 2))
    fc = mx.sym.FullyConnected(h, num_hidden=2, name="fc")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def accuracy(mod, x, y, batch):
    # BaseModule.score over an iterator — callers pass full-batch-sized
    # arrays (see _trim) so no pad rows enter the metric
    it = mx.io.NDArrayIter(x, y, batch_size=batch)
    return mod.score(it, mx.metric.Accuracy())[0][1]


def _trim(x, y, batch):
    n = (x.shape[0] // batch) * batch
    return x[:n], y[:n]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--size", type=int, default=16)
    ap.add_argument("--eps", type=float, default=0.15)
    ap.add_argument("--num-epochs", type=int, default=4)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--train-size", type=int, default=2048)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    rs = np.random.RandomState(31)
    x, y = make_images(args.train_size, args.size, rs)
    vx, vy = make_images(512, args.size, rs)
    train = mx.io.NDArrayIter(x, y, batch_size=args.batch_size, shuffle=True,
                              last_batch_handle="discard")

    mod = mx.mod.Module(build_symbol())
    # inputs_need_grad so backward() also fills dL/dx — the attack direction
    mod.bind(data_shapes=train.provide_data, label_shapes=train.provide_label,
             inputs_need_grad=True)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})
    metric = mx.metric.Accuracy()
    for ep in range(args.num_epochs):
        train.reset()
        metric.reset()
        for batch in train:
            mod.forward_backward(batch)
            mod.update()
            mod.update_metric(metric, batch.label)
        logging.info("epoch %d train-acc %.3f", ep, metric.get()[1])

    vx, vy = _trim(vx, vy, args.batch_size)  # keep clean/adv sets identical
    clean_acc = accuracy(mod, vx, vy, args.batch_size)

    # FGSM: one forward/backward per batch with the TRUE labels, then step
    # the input against the gradient sign
    adv = np.empty_like(vx)
    B = args.batch_size
    for k in range(vx.shape[0] // B):
        s = slice(k * B, (k + 1) * B)
        batch = mx.io.DataBatch(data=[mx.nd.array(vx[s])],
                                label=[mx.nd.array(vy[s])])
        mod.forward(batch, is_train=True)
        mod.backward()
        gx = mod.get_input_grads()[0].asnumpy()
        adv[s] = vx[s] + args.eps * np.sign(gx)
    adv_acc = accuracy(mod, adv, vy, B)

    print("clean accuracy %.3f → adversarial (eps=%.2f) %.3f"
          % (clean_acc, args.eps, adv_acc))
    assert clean_acc > 0.85 and adv_acc < clean_acc - 0.2, \
        "FGSM should collapse accuracy on this template task"


if __name__ == "__main__":
    main()
