"""Undercomplete MLP autoencoder (counterpart of the reference-era
example/autoencoder): encode → bottleneck → decode, trained with
``LinearRegressionOutput`` against the input itself. The check is
structural: data living on a k-dim manifold embedded in D dims must
reconstruct through a k-wide bottleneck (RMSE → noise floor) but NOT
through random projections — verified by comparing against the
untrained model's RMSE.

Synthetic, egress-free data: points on a ``k``-dim linear manifold in
``D``-dim space plus noise (the classic PCA-recoverable case — a linear
AE provably converges to the principal subspace).

    MXNET_DEFAULT_CONTEXT=cpu python example/autoencoder/manifold_ae.py
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
import mxnet_tpu as mx


def make_manifold(n, dim, k, noise, rs):
    basis = np.linalg.qr(rs.randn(dim, k))[0].astype("float32")  # (D, k)
    z = rs.randn(n, k).astype("float32")
    # scale so each ambient dim is ~unit variance — healthy gradient scale
    x = z @ basis.T * np.sqrt(dim / k) + rs.randn(n, dim).astype("float32") * noise
    return x.astype("float32")


def build_symbol(dim, bottleneck):
    """Linear encoder/decoder around the bottleneck: for data on a linear
    manifold a linear AE provably converges to the principal subspace, so
    the example is self-checking; swap in Activation layers to explore
    nonlinear codes."""
    data = mx.sym.Variable("data")
    target = mx.sym.Variable("target_label")
    code = mx.sym.FullyConnected(data, num_hidden=bottleneck, name="code")
    out = mx.sym.FullyConnected(code, num_hidden=dim, name="dec")
    return mx.sym.LinearRegressionOutput(out, label=target, name="recon")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--bottleneck", type=int, default=4)
    ap.add_argument("--noise", type=float, default=0.05)
    ap.add_argument("--num-epochs", type=int, default=15)
    ap.add_argument("--lr", type=float, default=0.03)
    ap.add_argument("--train-size", type=int, default=4096)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    rs = np.random.RandomState(23)
    # ONE manifold: train and validation must share the basis (a fresh
    # make_manifold draw is a different subspace — nothing generalizes there)
    allx = make_manifold(args.train_size + 512, args.dim, args.bottleneck,
                         args.noise, rs)
    x, vx = allx[:args.train_size], allx[args.train_size:]
    train = mx.io.NDArrayIter({"data": x}, {"target_label": x},
                              batch_size=args.batch_size, shuffle=True,
                              last_batch_handle="discard")
    val = mx.io.NDArrayIter({"data": vx}, {"target_label": vx},
                            batch_size=args.batch_size,
                            last_batch_handle="discard")

    net = build_symbol(args.dim, args.bottleneck)
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("target_label",))
    mod.bind(data_shapes=train.provide_data, label_shapes=train.provide_label)
    mod.init_params(mx.init.Xavier())
    untrained = mod.score(val, mx.metric.RMSE())[0][1]
    mod.fit(train, eval_data=val, eval_metric=mx.metric.RMSE(),
            optimizer="adam", optimizer_params={"learning_rate": args.lr},
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50))
    final = mod.score(val, mx.metric.RMSE())[0][1]
    print("reconstruction RMSE: untrained %.3f → trained %.3f "
          "(noise floor %.2f)" % (untrained, final, args.noise))


if __name__ == "__main__":
    main()
