"""DCGAN with two adversarially-wired Modules (counterpart of the
reference-era example/gan; the training loop is the API exercise here:
``forward``/``backward``/``update`` driven manually, with
``get_input_grads()`` carrying the discriminator's input gradient back
into the generator — the one Module idiom no other example uses).

Data is synthetic (egress-free): "real" images are 32x32 renders of a
Gaussian blob at a random position — a structured distribution the
generator must match. Losses are logged; after training, the script prints
the discriminator's real/fake accuracy (≈0.5 when the generator is doing
its job).

    MXNET_DEFAULT_CONTEXT=cpu python example/gan/dcgan.py --num-epochs 3
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
import mxnet_tpu as mx


def make_blobs(n, size, rs):
    """Gaussian blob at a random center; unit-ish contrast, (n,1,size,size)."""
    yy, xx = np.mgrid[0:size, 0:size].astype("float32")
    cx = rs.uniform(size * 0.25, size * 0.75, (n, 1, 1))
    cy = rs.uniform(size * 0.25, size * 0.75, (n, 1, 1))
    sig = rs.uniform(2.0, 4.0, (n, 1, 1))
    img = np.exp(-((xx - cx) ** 2 + (yy - cy) ** 2) / (2 * sig ** 2))
    return (img[:, None, :, :] * 2.0 - 1.0).astype("float32")


def generator(ngf, nz):
    z = mx.sym.Variable("z")                                    # (B, nz)
    h = mx.sym.FullyConnected(z, num_hidden=ngf * 4 * 4 * 4, name="g_fc")
    h = mx.sym.Reshape(h, shape=(-1, ngf * 4, 4, 4))
    h = mx.sym.Activation(mx.sym.BatchNorm(h, name="g_bn0"), act_type="relu")
    h = mx.sym.Deconvolution(h, num_filter=ngf * 2, kernel=(4, 4),
                             stride=(2, 2), pad=(1, 1), name="g_dc1")  # 8x8
    h = mx.sym.Activation(mx.sym.BatchNorm(h, name="g_bn1"), act_type="relu")
    h = mx.sym.Deconvolution(h, num_filter=ngf, kernel=(4, 4),
                             stride=(2, 2), pad=(1, 1), name="g_dc2")  # 16x16
    h = mx.sym.Activation(mx.sym.BatchNorm(h, name="g_bn2"), act_type="relu")
    h = mx.sym.Deconvolution(h, num_filter=1, kernel=(4, 4),
                             stride=(2, 2), pad=(1, 1), name="g_dc3")  # 32x32
    return mx.sym.Activation(h, act_type="tanh", name="g_out")


def discriminator(ndf):
    x = mx.sym.Variable("data")                                 # (B,1,32,32)
    h = mx.sym.LeakyReLU(mx.sym.Convolution(
        x, num_filter=ndf, kernel=(4, 4), stride=(2, 2), pad=(1, 1),
        name="d_c1"), slope=0.2)                                # 16x16
    h = mx.sym.LeakyReLU(mx.sym.BatchNorm(mx.sym.Convolution(
        h, num_filter=ndf * 2, kernel=(4, 4), stride=(2, 2), pad=(1, 1),
        name="d_c2"), name="d_bn2"), slope=0.2)                 # 8x8
    h = mx.sym.LeakyReLU(mx.sym.BatchNorm(mx.sym.Convolution(
        h, num_filter=ndf * 4, kernel=(4, 4), stride=(2, 2), pad=(1, 1),
        name="d_c3"), name="d_bn3"), slope=0.2)                 # 4x4
    h = mx.sym.FullyConnected(h, num_hidden=1, name="d_fc")
    return mx.sym.LogisticRegressionOutput(h, name="d_loss")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--nz", type=int, default=16)
    ap.add_argument("--ngf", type=int, default=16)
    ap.add_argument("--ndf", type=int, default=16)
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--batches-per-epoch", type=int, default=40)
    ap.add_argument("--lr", type=float, default=2e-4)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    rs = np.random.RandomState(5)
    B, size = args.batch_size, 32
    ctx = mx.current_context()

    gmod = mx.mod.Module(generator(args.ngf, args.nz), data_names=("z",),
                         label_names=(), context=ctx)
    gmod.bind(data_shapes=[("z", (B, args.nz))], inputs_need_grad=False)
    gmod.init_params(mx.init.Normal(0.02))
    gmod.init_optimizer(optimizer="adam",
                        optimizer_params={"learning_rate": args.lr,
                                          "beta1": 0.5})

    dmod = mx.mod.Module(discriminator(args.ndf), data_names=("data",),
                         label_names=("d_loss_label",), context=ctx)
    dmod.bind(data_shapes=[("data", (B, 1, size, size))],
              label_shapes=[("d_loss_label", (B, 1))],
              inputs_need_grad=True)   # grads flow back into the generator
    dmod.init_params(mx.init.Normal(0.02))
    # D learns this easy distribution much faster than G renders it —
    # throttle D so the minimax stays in play at toy scale
    dmod.init_optimizer(optimizer="adam",
                        optimizer_params={"learning_rate": args.lr * 0.2,
                                          "beta1": 0.5})

    ones = mx.nd.array(np.ones((B, 1), "float32"), ctx=ctx)
    zeros = mx.nd.array(np.zeros((B, 1), "float32"), ctx=ctx)

    def dbatch(x, y):
        return mx.io.DataBatch(data=[x], label=[y])

    for epoch in range(args.num_epochs):
        dl = gl = dacc = 0.0
        for _ in range(args.batches_per_epoch):
            z = mx.nd.array(rs.randn(B, args.nz).astype("float32"), ctx=ctx)
            real = mx.nd.array(make_blobs(B, size, rs), ctx=ctx)

            # G(z) once per step
            gmod.forward(dbatch(z, None), is_train=True)
            fake = gmod.get_outputs()[0]

            # --- D step: real→1, fake→0
            dmod.forward(dbatch(real, ones), is_train=True)
            pr = dmod.get_outputs()[0].asnumpy()
            dmod.backward()
            dmod.update()
            dmod.forward(dbatch(fake.copy(), zeros), is_train=True)
            pf = dmod.get_outputs()[0].asnumpy()
            dmod.backward()
            dmod.update()
            dacc += 0.5 * ((pr > 0.5).mean() + (pf < 0.5).mean())
            dl += -0.5 * (np.log(pr + 1e-8).mean() +
                          np.log(1 - pf + 1e-8).mean())

            # --- G steps: D(G(z)) labeled REAL; input grad rides into G.
            # Two per D step — the blob distribution is easy for D, and an
            # unthrottled D saturates before G moves (classic imbalance)
            for gi in range(2):
                if gi:
                    z = mx.nd.array(rs.randn(B, args.nz).astype("float32"),
                                    ctx=ctx)
                    gmod.forward(dbatch(z, None), is_train=True)
                    fake = gmod.get_outputs()[0]
                dmod.forward(dbatch(fake, ones), is_train=True)
                pg = dmod.get_outputs()[0].asnumpy()
                dmod.backward()
                gmod.backward(dmod.get_input_grads())
                gmod.update()
            gl += -np.log(pg + 1e-8).mean()
        k = args.batches_per_epoch
        logging.info("epoch %d  d_loss=%.3f  g_loss=%.3f  d_acc=%.3f",
                     epoch, dl / k, gl / k, dacc / k)

    print("final discriminator accuracy (≈0.5 is a healthy GAN): %.3f"
          % (dacc / args.batches_per_epoch))


if __name__ == "__main__":
    main()
