"""Sequence-parallel transformer-LM training with ring attention.

Long-context training the reference never had (SURVEY.md §5.7: its only
answer to sequence length was bucketing): the sequence axis is SHARDED
over the mesh — each device holds T/sp tokens of every batch row — and
self-attention runs as RING attention (parallel/ring_attention.py): queries
stay put while k/v blocks rotate over the mesh via ppermute, softmax
accumulated online, so no device ever materializes more than (T/sp)^2
scores. The MultiHeadAttention op dispatches to the ring automatically when
the SPMD step's mesh has a 'seq' axis; ShardingRules(seq_axis="seq")
shards the (B, T) token inputs so activations enter the network
seq-sharded end-to-end.

Task (self-checking, synthetic): induction-head copying — the sequence is
two repetitions of the same random half, so predicting token t >= T/2
requires attending T/2 positions back: solvable ONLY if attention really
spans the full (sharded) sequence. A model whose attention were local to
its shard could not beat chance.

Run on the 8-device virtual CPU mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    MXNET_DEFAULT_CONTEXT=cpu python ring_attention_lm.py
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import models, parallel  # noqa: E402
from mxnet_tpu.ops import attention as attn_op  # noqa: E402


def make_batch(rs, batch, seq_len, vocab):
    half = rs.randint(2, vocab, (batch, seq_len // 2))
    seq = np.concatenate([half, half], axis=1).astype("float32")
    # next-token targets; the second half is fully predictable
    y = np.roll(seq, -1, axis=1)
    y[:, -1] = 1
    return seq, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--sp", type=int, default=4)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=32)
    ap.add_argument("--steps", type=int, default=150)
    ap.add_argument("--lr", type=float, default=3e-3)
    args = ap.parse_args()

    import jax

    assert len(jax.devices()) >= args.dp * args.sp, (
        "need %d devices (set --xla_force_host_platform_device_count)"
        % (args.dp * args.sp))
    mesh = parallel.make_mesh({"data": args.dp, "seq": args.sp},
                              devices=jax.devices()[: args.dp * args.sp])
    net = models.transformer.get_symbol(
        vocab_size=args.vocab, num_layers=2, num_heads=4, model_dim=64,
        ffn_dim=128, seq_len=args.seq_len)
    trainer = parallel.SPMDTrainer(
        net, mesh, optimizer="adam", optimizer_params={"learning_rate": args.lr},
        rules=parallel.ShardingRules(mesh, seq_axis="seq"))
    trainer.init_params({"data": (args.batch, args.seq_len)},
                        {"softmax_label": (args.batch, args.seq_len)}, seed=0)

    rs = np.random.RandomState(0)
    before = attn_op.DISPATCH_COUNTS["ring"]
    losses = []
    for step in range(args.steps):
        x, y = make_batch(rs, args.batch, args.seq_len, args.vocab)
        outs = trainer.step({"data": x}, {"softmax_label": y})
        prob = np.asarray(outs[0]).reshape(args.batch, args.seq_len, -1)
        # score ONLY the second half (the copy): demands full-length attention
        tgt = y[:, args.seq_len // 2:-1].astype(int)
        p = prob[:, args.seq_len // 2:-1]
        nll = -np.log(p[np.arange(args.batch)[:, None],
                        np.arange(tgt.shape[1])[None, :], tgt] + 1e-9).mean()
        losses.append(nll)
        if step % 25 == 0:
            print("step %3d  copy-half nll %.4f" % (step, nll), flush=True)

    assert attn_op.DISPATCH_COUNTS["ring"] > before, \
        "ring attention did not engage"
    acc = (p.argmax(-1) == tgt).mean()
    chance = 1.0 / args.vocab
    print("ring dispatches: %d"
          % (attn_op.DISPATCH_COUNTS["ring"] - before))
    print("final copy-half nll %.4f (start %.4f), copy accuracy %.3f "
          "(chance %.3f)" % (losses[-1], losses[0], acc, chance))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    assert acc > 5 * chance, (acc, chance)
    print("OK")


if __name__ == "__main__":
    main()
