"""Noise-contrastive estimation for embeddings (counterpart of the
reference's example/nce-loss): instead of a full-vocabulary softmax, each
center word is scored against 1 true context + k noise words, trained as
1-vs-k logistic regression — the classic word2vec trick, expressed here
with two Embedding tables, a broadcast dot product, and
``LogisticRegressionOutput``.

Synthetic, egress-free corpus: the vocabulary splits into clusters and
words only co-occur within their cluster. Learned embeddings must end up
with higher within-cluster than cross-cluster cosine similarity — checked
at the end.

    MXNET_DEFAULT_CONTEXT=cpu python example/nce-loss/nce_word2vec.py
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
import mxnet_tpu as mx


def make_pairs(n, vocab, clusters, k_neg, rs):
    """center (n,), candidates (n, 1+k) [true context first], labels."""
    per = vocab // clusters
    center = rs.randint(0, vocab, n)
    cluster = center // per
    context = cluster * per + rs.randint(0, per, n)
    negs = rs.randint(0, vocab, (n, k_neg))
    cands = np.concatenate([context[:, None], negs], axis=1)
    labels = np.zeros((n, 1 + k_neg), "float32")
    labels[:, 0] = 1.0
    return center.astype("float32"), cands.astype("float32"), labels


def build_symbol(vocab, dim, k_neg):
    center = mx.sym.Variable("center")        # (B,)
    cands = mx.sym.Variable("candidates")     # (B, 1+k)
    labels = mx.sym.Variable("nce_label")     # (B, 1+k)
    emb_in = mx.sym.Embedding(center, input_dim=vocab, output_dim=dim,
                              name="in_emb")                   # (B, D)
    emb_out = mx.sym.Embedding(cands, input_dim=vocab, output_dim=dim,
                               name="out_emb")                 # (B, 1+k, D)
    ctr = mx.sym.Reshape(emb_in, shape=(-1, 1, dim))
    scores = mx.sym.sum(mx.sym.broadcast_mul(emb_out, ctr), axis=2)
    return mx.sym.LogisticRegressionOutput(scores, label=labels, name="nce")


def cluster_similarity(emb, clusters):
    emb = emb / (np.linalg.norm(emb, axis=1, keepdims=True) + 1e-8)
    per = emb.shape[0] // clusters
    sims = emb @ emb.T
    within, cross, nw, nc = 0.0, 0.0, 0, 0
    for i in range(emb.shape[0]):
        for j in range(i + 1, emb.shape[0]):
            if i // per == j // per:
                within += sims[i, j]; nw += 1
            else:
                cross += sims[i, j]; nc += 1
    return within / nw, cross / nc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=60)
    ap.add_argument("--clusters", type=int, default=6)
    ap.add_argument("--dim", type=int, default=16)
    ap.add_argument("--k-neg", type=int, default=5)
    ap.add_argument("--num-epochs", type=int, default=8)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--train-size", type=int, default=8192)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    if args.vocab % args.clusters:
        ap.error("--vocab must be divisible by --clusters (cluster "
                 "membership is index // (vocab/clusters))")
    rs = np.random.RandomState(37)
    center, cands, labels = make_pairs(args.train_size, args.vocab,
                                       args.clusters, args.k_neg, rs)
    train = mx.io.NDArrayIter({"center": center, "candidates": cands},
                              {"nce_label": labels},
                              batch_size=args.batch_size, shuffle=True,
                              last_batch_handle="discard")

    net = build_symbol(args.vocab, args.dim, args.k_neg)
    mod = mx.mod.Module(net, data_names=("center", "candidates"),
                        label_names=("nce_label",))
    mod.fit(train, eval_metric=mx.metric.MSE(),
            optimizer="adam", optimizer_params={"learning_rate": args.lr},
            initializer=mx.init.Normal(0.1),
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50))

    emb = mod.get_params()[0]["in_emb_weight"].asnumpy()
    within, cross = cluster_similarity(emb, args.clusters)
    print("embedding cosine: within-cluster %.3f vs cross-cluster %.3f"
          % (within, cross))
    assert within > cross + 0.2, "NCE failed to separate the clusters"


if __name__ == "__main__":
    main()
