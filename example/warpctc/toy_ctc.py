"""Toy sequence-labeling with CTC (counterpart of the reference's
example/warpctc/toy_ctc.py, which trained an unrolled LSTM + WarpCTC on
synthetic digit strips via the warp-ctc CUDA plugin).

Here the task is the same shape but everything is TPU-native: a synthetic
"strip" of T frames encodes a variable-length digit sequence (each digit
holds a run of noisy one-hot frames separated by blank noise), a fused
``RNN`` op (lax.scan LSTM) reads the strip, a per-frame FC scores the
alphabet, and ``WarpCTC`` — the log-space alpha recursion in
mxnet_tpu/ops/ctc.py — provides loss and gradient. Greedy best-path
decoding (collapse repeats, drop blanks) reports sequence accuracy.

Runs on CPU in under a minute:
    MXNET_DEFAULT_CONTEXT=cpu python example/warpctc/toy_ctc.py
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
import mxnet_tpu as mx

BLANK = 0


def make_strips(n, T, max_len, alphabet, rs):
    """Synthesize (data, label): data (n, T, alphabet) noisy one-hot frames,
    label (n, max_len) digit ids padded with the blank (0)."""
    data = rs.randn(n, T, alphabet).astype("float32") * 0.3
    label = np.zeros((n, max_len), dtype="float32")
    for i in range(n):
        length = rs.randint(1, max_len + 1)
        seq = []
        while len(seq) < length:
            d = rs.randint(1, alphabet)
            if not seq or d != seq[-1]:  # no adjacent repeats → always feasible
                seq.append(d)
        label[i, :length] = seq
        # each digit occupies a run of frames; runs are spaced so blanks remain
        starts = np.sort(rs.choice(T - 2, size=length, replace=False))
        for pos, d in zip(starts, seq):
            run = rs.randint(1, 3)
            data[i, pos:pos + run, d] += 3.0
    return data, label


def greedy_decode(scores, T, batch):
    """Best path: argmax per frame → collapse repeats → strip blanks.
    scores is the WarpCTC forward output, (T*batch, alphabet) time-major."""
    path = scores.reshape(T, batch, -1).argmax(axis=2)  # (T, B)
    out = []
    for b in range(batch):
        seq, prev = [], -1
        for s in path[:, b]:
            if s != prev and s != BLANK:
                seq.append(int(s))
            prev = int(s)
        out.append(seq)
    return out


class SeqAccuracy(mx.metric.EvalMetric):
    """Fraction of samples whose decoded sequence matches the label exactly."""

    def __init__(self, T, batch):
        super().__init__("seq_acc")
        self.T, self.batch = T, batch

    def update(self, labels, preds):
        lab = labels[0].asnumpy()
        decoded = greedy_decode(preds[0].asnumpy(), self.T, self.batch)
        for b in range(lab.shape[0]):
            truth = [int(v) for v in lab[b] if v != BLANK]
            self.sum_metric += float(decoded[b] == truth)
            self.num_inst += 1


def build_symbol(T, max_len, alphabet, hidden):
    from mxnet_tpu.initializer import Uniform
    from mxnet_tpu.ops.rnn import rnn_param_size

    data = mx.sym.Variable("data")                       # (B, T, alphabet)
    label = mx.sym.Variable("ctc_label")                 # (B, max_len)
    tm = mx.sym.SwapAxis(data=data, dim1=0, dim2=1)      # (T, B, F)
    params = mx.sym.Variable(
        "lstm_parameters",
        shape=(rnn_param_size(1, alphabet, hidden, False, "lstm"),),
        init=Uniform(0.1))
    # iterator-fed states arrive batch-major (B, 1, H) — NDArrayIter slices
    # axis 0 — and are swapped here to the RNN's (layers, B, H)
    init_h = mx.sym.SwapAxis(data=mx.sym.Variable("init_h_in"), dim1=0, dim2=1)
    init_c = mx.sym.SwapAxis(data=mx.sym.Variable("init_c_in"), dim1=0, dim2=1)
    out = mx.sym.RNN(data=tm, parameters=params, state=init_h,
                     state_cell=init_c, mode="lstm", state_size=hidden,
                     num_layers=1, state_outputs=False, name="lstm")
    out = mx.sym.Reshape(data=out, shape=(-1, hidden))   # (T*B, H) time-major
    pred = mx.sym.FullyConnected(data=out, num_hidden=alphabet, name="pred")
    return mx.sym.WarpCTC(data=pred, label=label, input_length=T,
                          label_length=max_len, name="ctc")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--frames", type=int, default=20)
    ap.add_argument("--max-len", type=int, default=4)
    ap.add_argument("--alphabet", type=int, default=11, help="incl. blank 0")
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=10)
    ap.add_argument("--lr", type=float, default=0.02)
    ap.add_argument("--train-size", type=int, default=1600)
    ap.add_argument("--val-size", type=int, default=320)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    rs = np.random.RandomState(7)
    Xtr, Ytr = make_strips(args.train_size, args.frames, args.max_len,
                           args.alphabet, rs)
    Xva, Yva = make_strips(args.val_size, args.frames, args.max_len,
                           args.alphabet, rs)
    zeros = lambda n: np.zeros((n, 1, args.hidden), "float32")
    # init states ride the iterator as extra data (reference init_states
    # pattern); NDArrayIter slices their batch axis, RNN wants (layers, B, H)
    # so the symbol sees them via batch-major (B, layers, H) → SwapAxis
    train = mx.io.NDArrayIter(
        {"data": Xtr, "init_h_in": zeros(args.train_size),
         "init_c_in": zeros(args.train_size)},
        {"ctc_label": Ytr}, batch_size=args.batch_size, shuffle=True,
        last_batch_handle="discard")
    val = mx.io.NDArrayIter(
        {"data": Xva, "init_h_in": zeros(args.val_size),
         "init_c_in": zeros(args.val_size)},
        {"ctc_label": Yva}, batch_size=args.batch_size,
        last_batch_handle="discard")

    sym = build_symbol(args.frames, args.max_len, args.alphabet, args.hidden)
    mod = mx.mod.Module(sym, data_names=("data", "init_h_in", "init_c_in"),
                        label_names=("ctc_label",))
    mod.fit(train, eval_data=val,
            eval_metric=SeqAccuracy(args.frames, args.batch_size),
            optimizer="adam", optimizer_params={"learning_rate": args.lr},
            initializer=mx.init.Xavier(), num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 10))

    score = mod.score(val, SeqAccuracy(args.frames, args.batch_size))
    print("final validation %s=%.3f" % score[0])


if __name__ == "__main__":
    main()
