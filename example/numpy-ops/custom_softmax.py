"""Train an MLP whose LOSS LAYER is a numpy CustomOp (counterpart of the
reference's example/numpy-ops/custom_softmax.py): softmax + cross-entropy
gradient written by hand in numpy, registered via ``mx.operator``, and
dropped into a Symbol graph like any built-in op.

What this demonstrates: the CustomOp host-callback path (pure_callback +
custom_vjp under the hood) composing with `simple_bind`'s single fused
XLA computation — the numpy code runs on host per step, everything else
stays compiled. A loss layer needs ``need_top_grad=False`` (it is its own
head, like SoftmaxOutput).

    MXNET_DEFAULT_CONTEXT=cpu python example/numpy-ops/custom_softmax.py
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
import mxnet_tpu as mx
from mxnet_tpu import operator as op


@op.register("numpy_softmax_loss")
class NumpySoftmaxProp(op.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)  # loss head: no incoming grad

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        label_shape = (in_shape[0][0],)
        return [data_shape, label_shape], [data_shape], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return NumpySoftmax()


class NumpySoftmax(op.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        e = np.exp(x - x.max(axis=1, keepdims=True))
        self.assign(out_data[0], req[0], e / e.sum(axis=1, keepdims=True))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        p = out_data[0].asnumpy().copy()
        lab = in_data[1].asnumpy().astype("int64")
        p[np.arange(p.shape[0]), lab] -= 1.0
        self.assign(in_grad[0], req[0], p / p.shape[0])


def make_spirals(n, rs):
    """Two interleaved spirals — linearly inseparable 2-class toy data."""
    m = n // 2
    t = rs.uniform(0.25, 3.0, m).astype("float32")
    x0 = np.stack([t * np.cos(3 * t), t * np.sin(3 * t)], axis=1)
    x1 = np.stack([t * np.cos(3 * t + np.pi), t * np.sin(3 * t + np.pi)], axis=1)
    x = np.concatenate([x0, x1]) + rs.randn(2 * m, 2).astype("float32") * 0.05
    y = np.concatenate([np.zeros(m), np.ones(m)]).astype("float32")
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=15)
    ap.add_argument("--lr", type=float, default=1e-3)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    rs = np.random.RandomState(21)
    x, y = make_spirals(2048, rs)
    vx, vy = make_spirals(512, rs)
    train = mx.io.NDArrayIter(x, y, batch_size=args.batch_size, shuffle=True,
                              last_batch_handle="discard")
    val = mx.io.NDArrayIter(vx, vy, batch_size=args.batch_size,
                            last_batch_handle="discard")

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    h = mx.sym.Activation(mx.sym.FullyConnected(data, num_hidden=args.hidden,
                                                name="fc1"), act_type="relu")
    h = mx.sym.Activation(mx.sym.FullyConnected(h, num_hidden=args.hidden,
                                                name="fc2"), act_type="relu")
    logits = mx.sym.FullyConnected(h, num_hidden=2, name="fc3")
    net = mx.sym.Custom(data=logits, label=label,
                        op_type="numpy_softmax_loss", name="softmax")

    mod = mx.mod.Module(net)
    mod.fit(train, eval_data=val, eval_metric="acc",
            optimizer="adam", optimizer_params={"learning_rate": args.lr},
            initializer=mx.init.Xavier(),
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50))
    score = mod.score(val, mx.metric.Accuracy())
    print("spiral accuracy with numpy loss op: %.3f" % score[0][1])


if __name__ == "__main__":
    main()
