"""Faster-RCNN-style end-to-end training (reference: example/rcnn/
train_end2end.py + rcnn/symbol/symbol_vgg.py get_vgg_train).

Compact two-stage detector exercising the full region pipeline on one XLA
step: conv backbone → RPN (objectness + box-delta conv heads with
MultiBoxTarget-assigned anchor targets) → ``Proposal`` (NMS'd region
proposals, contrib op) → ``ProposalTarget`` (a python CustomOp, like the
reference's rcnn/symbol/proposal_target.py) → ``ROIPooling`` → FC head with
per-class softmax + smooth-L1 box regression.

Runs on the synthetic rectangle detection set (no egress); the point is the
end-to-end graph, every op of the reference's RCNN path trained together.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
import mxnet_tpu as mx

logging.basicConfig(level=logging.INFO)

FEAT_STRIDE = 8
IMG = 128
N_ROIS = 32          # rois sampled per image by ProposalTarget
RPN_POST_NMS = 64


# --------------------------------------------------------- ProposalTarget
@mx.operator.register("proposal_target")
class ProposalTargetProp(mx.operator.CustomOpProp):
    """Sample proposals and assign classification/box-regression targets
    (reference: rcnn/symbol/proposal_target.py, also a python CustomOp)."""

    def __init__(self, num_classes="4", fg_fraction="0.5"):
        super().__init__(need_top_grad=False)
        self._num_classes = int(num_classes)
        self._fg = float(fg_fraction)

    def list_arguments(self):
        return ["rois", "gt_boxes"]

    def list_outputs(self):
        return ["rois_out", "label", "bbox_target", "bbox_weight"]

    def infer_shape(self, in_shape):
        n = N_ROIS
        c = self._num_classes + 1
        return (in_shape,
                [[n, 5], [n], [n, 4 * c], [n, 4 * c]], [])

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return ProposalTargetOp(self._num_classes, self._fg)


class ProposalTargetOp(mx.operator.CustomOp):
    def __init__(self, num_classes, fg_fraction):
        self._nc = num_classes
        self._fg = fg_fraction
        self._rng = np.random.RandomState(0)

    @staticmethod
    def _iou(rois, gt):
        x1 = np.maximum(rois[:, None, 0], gt[None, :, 0])
        y1 = np.maximum(rois[:, None, 1], gt[None, :, 1])
        x2 = np.minimum(rois[:, None, 2], gt[None, :, 2])
        y2 = np.minimum(rois[:, None, 3], gt[None, :, 3])
        inter = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
        a = np.maximum(rois[:, 2] - rois[:, 0], 0) * np.maximum(rois[:, 3] - rois[:, 1], 0)
        b = np.maximum(gt[:, 2] - gt[:, 0], 0) * np.maximum(gt[:, 3] - gt[:, 1], 0)
        union = a[:, None] + b[None, :] - inter
        return np.where(union > 0, inter / union, 0)

    def forward(self, is_train, req, in_data, out_data, aux):
        rois = in_data[0].asnumpy()          # (R, 5) [batch, x1,y1,x2,y2]
        gt = in_data[1].asnumpy()[0]         # (M, 5) [cls, x1,y1,x2,y2], px
        valid = gt[:, 0] >= 0
        gt = gt[valid]
        n = N_ROIS
        out_rois = np.zeros((n, 5), np.float32)
        labels = np.zeros((n,), np.float32)
        btarget = np.zeros((n, 4 * (self._nc + 1)), np.float32)
        bweight = np.zeros_like(btarget)
        boxes = rois[:, 1:5]
        if len(gt):
            iou = self._iou(boxes, gt[:, 1:5])
            best = iou.argmax(axis=1)
            best_iou = iou.max(axis=1)
        else:
            best = np.zeros(len(boxes), np.int64)
            best_iou = np.zeros(len(boxes))
        fg_idx = np.where(best_iou >= 0.5)[0]
        bg_idx = np.where(best_iou < 0.5)[0]
        n_fg = min(len(fg_idx), int(self._fg * n))
        fg_idx = self._rng.permutation(fg_idx)[:n_fg]
        bg_take = self._rng.permutation(bg_idx)[: n - n_fg]
        keep = np.concatenate([fg_idx, bg_take]).astype(np.int64)
        if len(keep) < n:  # degenerate: repeat
            keep = np.resize(keep, n)
        out_rois[:] = rois[keep]
        for slot, ri in enumerate(keep):
            if slot < n_fg and len(gt):
                g = gt[best[ri]]
                cls = int(g[0]) + 1
                labels[slot] = cls
                bx = boxes[ri]
                bw = max(bx[2] - bx[0], 1e-3)
                bh = max(bx[3] - bx[1], 1e-3)
                gw = max(g[3] - g[1], 1e-3)
                gh = max(g[4] - g[2], 1e-3)
                t = [((g[1] + g[3]) / 2 - (bx[0] + bx[2]) / 2) / bw,
                     ((g[2] + g[4]) / 2 - (bx[1] + bx[3]) / 2) / bh,
                     np.log(gw / bw), np.log(gh / bh)]
                btarget[slot, 4 * cls:4 * cls + 4] = t
                bweight[slot, 4 * cls:4 * cls + 4] = 1.0
        self.assign(out_data[0], req[0], mx.nd.array(out_rois))
        self.assign(out_data[1], req[1], mx.nd.array(labels))
        self.assign(out_data[2], req[2], mx.nd.array(btarget))
        self.assign(out_data[3], req[3], mx.nd.array(bweight))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        for g, r in zip(in_grad, req):  # targets are constants
            self.assign(g, r, mx.nd.zeros(g.shape))


# ----------------------------------------------------------------- symbol
def conv_relu(data, name, nf, stride=(1, 1)):
    c = mx.sym.Convolution(data=data, num_filter=nf, kernel=(3, 3),
                           pad=(1, 1), stride=stride, name="conv" + name)
    return mx.sym.Activation(c, act_type="relu", name="relu" + name)


def get_rcnn_train(num_classes, num_anchors=9):
    data = mx.sym.Variable("data")
    im_info = mx.sym.Variable("im_info")
    gt_boxes = mx.sym.Variable("gt_boxes")
    rpn_label = mx.sym.Variable("rpn_label")           # (B, A*H*W)
    rpn_bbox_target = mx.sym.Variable("rpn_bbox_target")
    rpn_bbox_weight = mx.sym.Variable("rpn_bbox_weight")

    # backbone: stride-8 feature map (the reference's conv5 relu at /16)
    net = conv_relu(data, "1", 16)
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = conv_relu(net, "2", 32)
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = conv_relu(net, "3", 64)
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    feat = conv_relu(net, "4", 64)

    # RPN heads (reference: symbol_vgg.py get_vgg_rpn)
    rpn_conv = conv_relu(feat, "_rpn", 64)
    rpn_cls = mx.sym.Convolution(rpn_conv, kernel=(1, 1),
                                 num_filter=2 * num_anchors, name="rpn_cls_score")
    rpn_bbox = mx.sym.Convolution(rpn_conv, kernel=(1, 1),
                                  num_filter=4 * num_anchors, name="rpn_bbox_pred")
    # the reference's reshape dance: (B,2A,H,W) -> (B,2,A*H,W) for the
    # channel softmax, back to (B,2A,H,W) for Proposal (symbol_vgg.py:220)
    rpn_cls_rs = mx.sym.Reshape(rpn_cls, shape=(0, 2, -1, 0), name="rpn_cls_rs")
    rpn_cls_prob = mx.sym.SoftmaxOutput(
        data=rpn_cls_rs, label=rpn_label, multi_output=True, use_ignore=True,
        ignore_label=-1, normalization="valid", name="rpn_cls_prob")
    rpn_bbox_flat = mx.sym.Reshape(rpn_bbox, shape=(0, -1), name="rpn_bbox_flat")
    rpn_bbox_loss_ = rpn_bbox_weight * mx.sym.smooth_l1(
        data=(rpn_bbox_flat - rpn_bbox_target), scalar=3.0, name="rpn_l1")
    rpn_bbox_loss = mx.sym.MakeLoss(rpn_bbox_loss_, grad_scale=1.0 / RPN_POST_NMS,
                                    name="rpn_bbox_loss")

    # proposals from the (stop-grad) RPN outputs (reference: Proposal op)
    score_act = mx.sym.SoftmaxActivation(data=rpn_cls_rs, mode="channel",
                                         name="rpn_prob_act")
    score_act = mx.sym.Reshape(score_act, shape=(0, 2 * num_anchors, -1, 0),
                               name="rpn_prob_rs")
    rois = mx.sym.Proposal(
        mx.sym.BlockGrad(score_act), mx.sym.BlockGrad(rpn_bbox),
        im_info, feature_stride=FEAT_STRIDE, scales=(2.0, 4.0, 8.0),
        ratios=(0.5, 1.0, 2.0), rpn_pre_nms_top_n=256,
        rpn_post_nms_top_n=RPN_POST_NMS, threshold=0.7, rpn_min_size=4,
        name="rois")

    # sample + target assignment (python CustomOp, like the reference)
    group = mx.sym.Custom(rois=rois, gt_boxes=gt_boxes, op_type="proposal_target",
                          num_classes=str(num_classes), name="ptarget")
    rois_s, label, bbox_target, bbox_weight = (group[0], group[1], group[2], group[3])

    # RCNN head over pooled regions (reference: ROIPooling + fc6/fc7)
    pooled = mx.sym.ROIPooling(feat, mx.sym.BlockGrad(rois_s), pooled_size=(6, 6),
                               spatial_scale=1.0 / FEAT_STRIDE, name="roi_pool")
    flat = mx.sym.Flatten(pooled)
    fc = mx.sym.Activation(mx.sym.FullyConnected(flat, num_hidden=128, name="fc6"),
                           act_type="relu")
    cls_score = mx.sym.FullyConnected(fc, num_hidden=num_classes + 1, name="cls_score")
    cls_prob = mx.sym.SoftmaxOutput(data=cls_score, label=mx.sym.BlockGrad(label),
                                    normalization="batch", name="cls_prob")
    bbox_pred = mx.sym.FullyConnected(fc, num_hidden=4 * (num_classes + 1),
                                      name="bbox_pred")
    bbox_loss_ = mx.sym.BlockGrad(bbox_weight) * mx.sym.smooth_l1(
        data=(bbox_pred - mx.sym.BlockGrad(bbox_target)), scalar=1.0, name="rcnn_l1")
    bbox_loss = mx.sym.MakeLoss(bbox_loss_, grad_scale=1.0 / N_ROIS,
                                name="bbox_loss")
    return mx.sym.Group([rpn_cls_prob, rpn_bbox_loss, cls_prob, bbox_loss])


# -------------------------------------------------------------- data + fit
def rpn_targets(gt, anchors, n_anchors_total, hw):
    """Anchor-level RPN targets via IoU (the reference's AnchorLoader).
    Box targets are laid out to match the flattened (4A, H, W) conv output:
    flat[(4a+c)*HW + pos] for anchor index i = a*HW + pos."""
    labels = -np.ones((n_anchors_total,), np.float32)
    btarget = np.zeros((n_anchors_total * 4,), np.float32)
    bweight = np.zeros_like(btarget)
    valid = gt[gt[:, 0] >= 0][:, 1:5]
    if len(valid):
        x1 = np.maximum(anchors[:, None, 0], valid[None, :, 0])
        y1 = np.maximum(anchors[:, None, 1], valid[None, :, 1])
        x2 = np.minimum(anchors[:, None, 2], valid[None, :, 2])
        y2 = np.minimum(anchors[:, None, 3], valid[None, :, 3])
        inter = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
        aa = np.maximum(anchors[:, 2] - anchors[:, 0], 0) * np.maximum(anchors[:, 3] - anchors[:, 1], 0)
        ab = np.maximum(valid[:, 2] - valid[:, 0], 0) * np.maximum(valid[:, 3] - valid[:, 1], 0)
        iou = np.where(aa[:, None] + ab[None] - inter > 0,
                       inter / (aa[:, None] + ab[None] - inter), 0)
        best_iou = iou.max(axis=1)
        best_gt = iou.argmax(axis=1)
        labels[best_iou >= 0.6] = 1
        labels[best_iou < 0.3] = 0
        pos = np.where(labels == 1)[0]
        for i in pos:
            g = valid[best_gt[i]]
            a = anchors[i]
            aw, ah = max(a[2] - a[0], 1e-3), max(a[3] - a[1], 1e-3)
            gw, gh = max(g[2] - g[0], 1e-3), max(g[3] - g[1], 1e-3)
            t = [((g[0] + g[2]) / 2 - (a[0] + a[2]) / 2) / aw,
                 ((g[1] + g[3]) / 2 - (a[1] + a[3]) / 2) / ah,
                 np.log(gw / aw), np.log(gh / ah)]
            ai, pos_i = i // hw, i % hw
            for c in range(4):
                btarget[(4 * ai + c) * hw + pos_i] = t[c]
                bweight[(4 * ai + c) * hw + pos_i] = 1.0
    return labels, btarget, bweight


def make_anchors(fm, stride, scales=(2.0, 4.0, 8.0), ratios=(0.5, 1.0, 2.0)):
    """All anchors of the feature map in 'a-major' flat order matching the
    (A*H*W) reshape of the RPN heads."""
    out = []
    for s in scales:
        for r in ratios:
            w = stride * s * np.sqrt(1.0 / r)
            h = stride * s * np.sqrt(r)
            for y in range(fm):
                for x in range(fm):
                    cx, cy = (x + 0.5) * stride, (y + 0.5) * stride
                    out.append([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2])
    return np.asarray(out, np.float32)


class SyntheticRCNNIter(mx.io.DataIter):
    """Single-image batches of colored rectangles with full RPN targets."""

    def __init__(self, num_classes, num_batches=24, seed=0):
        super().__init__(1)
        fm = IMG // FEAT_STRIDE
        self.anchors = make_anchors(fm, FEAT_STRIDE)
        na = len(self.anchors)
        rs = np.random.RandomState(seed)
        self.batches = []
        for _ in range(num_batches):
            img = np.zeros((1, 3, IMG, IMG), np.float32)
            gt = -np.ones((1, 3, 5), np.float32)
            for j in range(rs.randint(1, 3)):
                cls = rs.randint(0, num_classes)
                x0, y0 = rs.randint(0, IMG // 2, 2)
                w, h = rs.randint(IMG // 4, IMG // 2, 2)
                x1, y1 = min(x0 + w, IMG - 1), min(y0 + h, IMG - 1)
                img[0, cls % 3, y0:y1, x0:x1] = 1.0
                gt[0, j] = [cls, x0, y0, x1, y1]
            lab, bt, bw = rpn_targets(gt[0], self.anchors, na, fm * fm)
            self.batches.append(mx.io.DataBatch(
                data=[mx.nd.array(img),
                      mx.nd.array([[IMG, IMG, 1.0]]),
                      mx.nd.array(gt)],
                label=[mx.nd.array(lab.reshape(1, -1, fm)),
                       mx.nd.array(bt[None]),
                       mx.nd.array(bw[None])],
                pad=0))
        self.cur = 0
        fmsz = fm * fm
        self.provide_data = [
            mx.io.DataDesc("data", (1, 3, IMG, IMG)),
            mx.io.DataDesc("im_info", (1, 3)),
            mx.io.DataDesc("gt_boxes", (1, 3, 5))]
        A = na // fmsz
        # label shaped to the (B,2,A*H,W) softmax view; flat order matches
        # make_anchors' a-major enumeration
        self.provide_label = [
            mx.io.DataDesc("rpn_label", (1, A * fm, fm)),
            mx.io.DataDesc("rpn_bbox_target", (1, na * 4)),
            mx.io.DataDesc("rpn_bbox_weight", (1, na * 4))]

    def next(self):
        if self.cur >= len(self.batches):
            raise StopIteration
        b = self.batches[self.cur]
        self.cur += 1
        return b

    def reset(self):
        self.cur = 0


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description="train Faster-RCNN (compact)")
    parser.add_argument("--num-classes", type=int, default=3)
    parser.add_argument("--num-epochs", type=int, default=2)
    parser.add_argument("--lr", type=float, default=0.005)
    args = parser.parse_args()

    net = get_rcnn_train(args.num_classes)
    it = SyntheticRCNNIter(args.num_classes)
    mod = mx.mod.Module(net, data_names=("data", "im_info", "gt_boxes"),
                        label_names=("rpn_label", "rpn_bbox_target",
                                     "rpn_bbox_weight"),
                        context=mx.current_context())
    mod.fit(it, num_epoch=args.num_epochs, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "wd": 5e-4},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.Loss(),
            batch_end_callback=mx.callback.Speedometer(1, 8))
    print("RCNN end-to-end training finished")
