"""Multi-task learning: one trunk, two loss heads (counterpart of the
reference-era example/multi-task, which trained digit-class + odd/even
heads). A Group symbol carries BOTH losses — the executor backpropagates
their sum — and a ``CompositeEvalMetric`` scores each head with its own
metric, fed per-head via a small adapter (the reference used the same
pattern with Accuracy on output 0 and 1).

Synthetic task: inputs on a 2-D ring; head A classifies the quadrant
(softmax), head B regresses the radius (linear regression). Shared trunk
features must serve both.

    MXNET_DEFAULT_CONTEXT=cpu python example/multi-task/multi_task.py
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
import mxnet_tpu as mx


def make_data(n, rs):
    theta = rs.uniform(0, 2 * np.pi, n).astype("float32")
    radius = rs.uniform(0.5, 2.0, n).astype("float32")
    x = np.stack([radius * np.cos(theta), radius * np.sin(theta)], axis=1)
    x = x + rs.randn(n, 2).astype("float32") * 0.02
    quadrant = ((theta // (np.pi / 2)) % 4).astype("float32")
    return x, quadrant, radius


def build_symbol(hidden):
    data = mx.sym.Variable("data")
    cls_label = mx.sym.Variable("cls_label")
    rad_label = mx.sym.Variable("rad_label")
    h = mx.sym.Activation(mx.sym.FullyConnected(data, num_hidden=hidden,
                                                name="trunk1"), act_type="relu")
    h = mx.sym.Activation(mx.sym.FullyConnected(h, num_hidden=hidden,
                                                name="trunk2"), act_type="relu")
    cls = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, num_hidden=4, name="cls_fc"),
        label=cls_label, name="softmax")
    rad = mx.sym.LinearRegressionOutput(
        mx.sym.FullyConnected(h, num_hidden=1, name="rad_fc"),
        label=rad_label, name="rad", grad_scale=0.5)
    return mx.sym.Group([cls, rad])


class HeadMetric(mx.metric.EvalMetric):
    """Route one (label, pred) pair of a multi-output module into an inner
    metric — the adapter that lets CompositeEvalMetric score heads
    independently."""

    def __init__(self, inner, head):
        super().__init__("%s[%d]" % (inner.name, head))
        self.inner, self.head = inner, head

    def reset(self):
        super().reset()
        if hasattr(self, "inner"):
            self.inner.reset()

    def update(self, labels, preds):
        self.inner.update([labels[self.head]], [preds[self.head]])

    def get(self):
        name, value = self.inner.get()
        return self.name, value


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=10)
    ap.add_argument("--lr", type=float, default=5e-3)
    ap.add_argument("--train-size", type=int, default=4096)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    rs = np.random.RandomState(29)
    x, q, r = make_data(args.train_size, rs)
    vx, vq, vr = make_data(512, rs)
    train = mx.io.NDArrayIter({"data": x},
                              {"cls_label": q, "rad_label": r},
                              batch_size=args.batch_size, shuffle=True,
                              last_batch_handle="discard")
    val = mx.io.NDArrayIter({"data": vx},
                            {"cls_label": vq, "rad_label": vr},
                            batch_size=args.batch_size,
                            last_batch_handle="discard")

    metric = mx.metric.CompositeEvalMetric(
        [HeadMetric(mx.metric.Accuracy(), 0),
         HeadMetric(mx.metric.RMSE(), 1)])

    net = build_symbol(args.hidden)
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("cls_label", "rad_label"))
    mod.fit(train, eval_data=val, eval_metric=metric,
            optimizer="adam", optimizer_params={"learning_rate": args.lr},
            initializer=mx.init.Xavier(),
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50))
    scores = dict(mod.score(val, metric))
    print("quadrant accuracy %.3f | radius RMSE %.3f"
          % (scores["accuracy[0]"], scores["rmse[1]"]))


if __name__ == "__main__":
    main()
