"""Bucketed LSTM language model (config 3 in BASELINE.json).

Counterpart of the reference's example/rnn/lstm_bucketing.py: a
BucketSentenceIter feeds variable-length sentences into a BucketingModule
whose sym_gen unrolls LSTM cells per bucket length. TPU economics are the
same as the reference's executor-per-bucket design — one compiled XLA
executable per bucket shape, all sharing parameters.

Reads PTB-style text from --data-train if it exists (one sentence per line,
space-separated tokens); otherwise generates a synthetic Zipf corpus so the
script runs without egress.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
import mxnet_tpu as mx

logging.basicConfig(level=logging.DEBUG)

parser = argparse.ArgumentParser(
    description="Train an LSTM language model with bucketing",
    formatter_class=argparse.ArgumentDefaultsHelpFormatter)
parser.add_argument("--data-train", type=str, default="./data/ptb.train.txt")
parser.add_argument("--num-hidden", type=int, default=200)
parser.add_argument("--num-embed", type=int, default=200)
parser.add_argument("--num-layers", type=int, default=2)
parser.add_argument("--num-epochs", type=int, default=25)
parser.add_argument("--lr", type=float, default=0.01)
parser.add_argument("--optimizer", type=str, default="sgd")
parser.add_argument("--mom", type=float, default=0.0)
parser.add_argument("--wd", type=float, default=1e-5)
parser.add_argument("--batch-size", type=int, default=32)
parser.add_argument("--disp-batches", type=int, default=50)
parser.add_argument("--kv-store", type=str, default="local")
parser.add_argument("--num-sentences", type=int, default=2000,
                    help="synthetic corpus size when --data-train is absent")

BUCKETS = [10, 20, 30, 40, 50, 60]
START_LABEL = 1
INVALID_LABEL = 0


def _simple_tokenize(fname):
    """Line-per-sentence text → int id lists (the reference's tokenize_text)."""
    with open(fname) as f:
        lines = [row.split() for row in f if row.strip()]
    vocab = {}
    sentences = []
    for words in lines:
        ids = []
        for w in words:
            if w not in vocab:
                vocab[w] = len(vocab) + START_LABEL + 1
            ids.append(vocab[w])
        sentences.append(ids)
    return sentences, vocab


def _synthetic_corpus(n_sentences, vocab_size=500, seed=0):
    rs = np.random.RandomState(seed)
    # Zipf-ish token frequencies, bucket-spread sentence lengths
    probs = 1.0 / np.arange(2, vocab_size + 2)
    probs /= probs.sum()
    sentences = []
    for _ in range(n_sentences):
        length = int(rs.choice(BUCKETS)) - rs.randint(0, 5)
        toks = rs.choice(np.arange(2, vocab_size + 2), size=max(length, 3), p=probs)
        sentences.append(toks.tolist())
    return sentences, vocab_size + 2


if __name__ == "__main__":
    args = parser.parse_args()

    if os.path.exists(args.data_train):
        train_sent, vocab = _simple_tokenize(args.data_train)
        vocab_size = len(vocab) + START_LABEL + 1
    else:
        logging.warning("%r not found — using a synthetic Zipf corpus", args.data_train)
        train_sent, vocab_size = _synthetic_corpus(args.num_sentences)

    data_train = mx.rnn.BucketSentenceIter(
        train_sent, args.batch_size, buckets=BUCKETS, invalid_label=INVALID_LABEL)

    stack = mx.rnn.SequentialRNNCell()
    for i in range(args.num_layers):
        stack.add(mx.rnn.LSTMCell(num_hidden=args.num_hidden, prefix="lstm_l%d_" % i))

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data=data, input_dim=vocab_size,
                                 output_dim=args.num_embed, name="embed")
        stack.reset()
        outputs, states = stack.unroll(
            seq_len, inputs=embed, merge_outputs=True,
            begin_state=stack.begin_state(batch_size=args.batch_size))
        pred = mx.sym.Reshape(outputs, shape=(-1, args.num_hidden))
        pred = mx.sym.FullyConnected(data=pred, num_hidden=vocab_size, name="pred")
        label = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(data=pred, label=label, name="softmax")
        return pred, ("data",), ("softmax_label",)

    model = mx.mod.BucketingModule(
        sym_gen=sym_gen,
        default_bucket_key=data_train.default_bucket_key,
        context=mx.current_context())

    model.fit(
        train_data=data_train,
        eval_metric=mx.metric.Perplexity(INVALID_LABEL),
        kvstore=args.kv_store,
        optimizer=args.optimizer,
        optimizer_params={"learning_rate": args.lr, "momentum": args.mom, "wd": args.wd},
        initializer=mx.init.Xavier(factor_type="in", magnitude=2.34),
        num_epoch=args.num_epochs,
        batch_end_callback=mx.callback.Speedometer(args.batch_size, args.disp_batches),
    )
