"""Sort a sequence with a bidirectional LSTM (counterpart of the
reference-era example/bi-lstm-sort): the model reads T numbers and predicts,
at every position t, the t-th smallest — solvable only because the
bidirectional unroll gives each position the whole sequence. Exercises
``rnn.BidirectionalCell`` (the one cell no other example touches), cell
``unroll`` with per-step symbols, and position-wise classification.

    MXNET_DEFAULT_CONTEXT=cpu python example/rnn/bi_lstm_sort.py
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
import mxnet_tpu as mx


def make_data(n, seq_len, vocab, rs):
    x = rs.randint(0, vocab, (n, seq_len)).astype("float32")
    y = np.sort(x, axis=1)
    return x, y


def build_symbol(seq_len, vocab, num_embed, num_hidden):
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=num_embed,
                             name="embed")                        # (B,T,E)
    cell = mx.rnn.BidirectionalCell(
        mx.rnn.LSTMCell(num_hidden, prefix="l_"),
        mx.rnn.LSTMCell(num_hidden, prefix="r_"))
    outputs, _ = cell.unroll(seq_len, inputs=embed, layout="NTC",
                             begin_state=cell.begin_state(batch_size=1),
                             merge_outputs=True)                  # (B,T,2H)
    pred = mx.sym.Reshape(outputs, shape=(-1, 2 * num_hidden))
    pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name="pred")
    label_flat = mx.sym.Reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(pred, label=label_flat, name="softmax")


class PositionAccuracy(mx.metric.EvalMetric):
    """Per-position accuracy; flattens the (B, T) label against the
    (B*T, vocab) position-wise predictions."""

    def __init__(self):
        super().__init__("pos_acc")

    def update(self, labels, preds):
        lab = labels[0].asnumpy().astype("int64").ravel()
        pred = preds[0].asnumpy().argmax(axis=1)
        self.sum_metric += float((lab == pred).sum())
        self.num_inst += len(lab)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--seq-len", type=int, default=6)
    ap.add_argument("--vocab", type=int, default=20)
    ap.add_argument("--num-embed", type=int, default=32)
    ap.add_argument("--num-hidden", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=6)
    ap.add_argument("--lr", type=float, default=5e-3)
    ap.add_argument("--train-size", type=int, default=4096)
    ap.add_argument("--val-size", type=int, default=512)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    rs = np.random.RandomState(9)
    x, y = make_data(args.train_size, args.seq_len, args.vocab, rs)
    vx, vy = make_data(args.val_size, args.seq_len, args.vocab, rs)
    train = mx.io.NDArrayIter(x, {"softmax_label": y},
                              batch_size=args.batch_size, shuffle=True,
                              last_batch_handle="discard")
    val = mx.io.NDArrayIter(vx, {"softmax_label": vy},
                            batch_size=args.batch_size,
                            last_batch_handle="discard")

    net = build_symbol(args.seq_len, args.vocab, args.num_embed,
                       args.num_hidden)
    mod = mx.mod.Module(net)
    mod.fit(train, eval_data=val, eval_metric=PositionAccuracy(),
            optimizer="adam", optimizer_params={"learning_rate": args.lr},
            initializer=mx.init.Xavier(),
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50))
    score = mod.score(val, PositionAccuracy())
    print("per-position sort accuracy: %.3f" % score[0][1])


if __name__ == "__main__":
    main()
