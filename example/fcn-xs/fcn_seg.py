"""Fully-convolutional segmentation (counterpart of the reference's
example/fcn-xs, which fine-tuned VGG into FCN-32s/16s/8s on PASCAL): a
small conv encoder downsamples 4x, ``UpSampling`` (nearest) brings the
score map back to input resolution, and ``SoftmaxOutput(multi_output=True)``
trains per-pixel — the op combination unique to dense prediction.

Synthetic, egress-free task: images contain a bright disc on a noisy
background; the label is the per-pixel disc mask. Reports per-pixel
accuracy and foreground IoU (the metric that exposes trivial all-background
solutions).

    MXNET_DEFAULT_CONTEXT=cpu python example/fcn-xs/fcn_seg.py
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
import mxnet_tpu as mx


def make_scenes(n, size, rs):
    yy, xx = np.mgrid[0:size, 0:size].astype("float32")
    img = rs.randn(n, 1, size, size).astype("float32") * 0.3
    mask = np.zeros((n, size, size), "float32")
    for i in range(n):
        cx, cy = rs.uniform(size * 0.25, size * 0.75, 2)
        rad = rs.uniform(size * 0.12, size * 0.25)
        m = ((xx - cx) ** 2 + (yy - cy) ** 2) <= rad * rad
        img[i, 0][m] += 1.0
        mask[i][m] = 1.0
    return img, mask


def build_symbol(num_classes=2):
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("seg_label")      # (B, H, W)
    h = mx.sym.Activation(mx.sym.Convolution(
        data, num_filter=16, kernel=(3, 3), pad=(1, 1), name="c1"),
        act_type="relu")
    h = mx.sym.Pooling(h, pool_type="max", kernel=(2, 2), stride=(2, 2))
    h = mx.sym.Activation(mx.sym.Convolution(
        h, num_filter=32, kernel=(3, 3), pad=(1, 1), name="c2"),
        act_type="relu")
    h = mx.sym.Pooling(h, pool_type="max", kernel=(2, 2), stride=(2, 2))
    h = mx.sym.Activation(mx.sym.Convolution(
        h, num_filter=32, kernel=(3, 3), pad=(1, 1), name="c3"),
        act_type="relu")
    score = mx.sym.Convolution(h, num_filter=num_classes, kernel=(1, 1),
                               name="score")
    up = mx.sym.UpSampling(score, scale=4, sample_type="nearest",
                           num_args=1, name="up")     # (B, C, H, W)
    return mx.sym.SoftmaxOutput(up, label=label, multi_output=True,
                                use_ignore=False, name="softmax")


def evaluate(mod, x, y, batch):
    inter = union = correct = total = 0
    for k in range(x.shape[0] // batch):
        s = slice(k * batch, (k + 1) * batch)
        mod.forward(mx.io.DataBatch(data=[mx.nd.array(x[s])], label=None),
                    is_train=False)
        prob = mod.get_outputs()[0].asnumpy()          # (B, C, H, W)
        pred = prob.argmax(axis=1)
        truth = y[s].astype(int)
        correct += (pred == truth).sum()
        total += truth.size
        inter += ((pred == 1) & (truth == 1)).sum()
        union += ((pred == 1) | (truth == 1)).sum()
    return correct / total, inter / max(union, 1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--size", type=int, default=32)
    ap.add_argument("--num-epochs", type=int, default=6)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--train-size", type=int, default=1024)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    rs = np.random.RandomState(41)
    x, y = make_scenes(args.train_size, args.size, rs)
    vx, vy = make_scenes(256, args.size, rs)
    train = mx.io.NDArrayIter({"data": x}, {"seg_label": y},
                              batch_size=args.batch_size, shuffle=True,
                              last_batch_handle="discard")

    mod = mx.mod.Module(build_symbol(), data_names=("data",),
                        label_names=("seg_label",))
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})
    for ep in range(args.num_epochs):
        train.reset()
        for batch in train:
            mod.forward_backward(batch)
            mod.update()
        acc, iou = evaluate(mod, vx, vy, args.batch_size)
        logging.info("epoch %d pixel-acc %.3f disc-IoU %.3f", ep, acc, iou)

    print("final pixel accuracy %.3f, foreground IoU %.3f" % (acc, iou))
    assert iou > 0.5, "segmentation failed to localize the disc"


if __name__ == "__main__":
    main()
