"""Transformer-base MT (BASELINE.md stretch config) on a synthetic
sequence-reversal "translation" task — the standard egress-free stand-in:
the model must learn src → reversed(src), which exercises the full
encoder/decoder/cross-attention data flow (a copy task would let the
decoder cheat with position-local attention).

Teacher-forced training via Module.fit; greedy decoding re-feeds the
growing prefix through the fixed-shape decoder (the causal mask makes the
padded future positions irrelevant), then reports exact-sequence accuracy.

    MXNET_DEFAULT_CONTEXT=cpu python example/nmt/train_transformer_mt.py \
        --num-layers 2 --model-dim 64 --num-epochs 5
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
import mxnet_tpu as mx
from mxnet_tpu import models

BOS = 1  # 0 is padding/ignore


def make_pairs(n, seq_len, vocab, rs):
    """src: random tokens in [2, vocab); tgt = reversed(src).
    dec_data is tgt shifted right with BOS (teacher forcing)."""
    src = rs.randint(2, vocab, (n, seq_len)).astype("float32")
    tgt = src[:, ::-1].copy()
    dec = np.concatenate([np.full((n, 1), BOS, "float32"), tgt[:, :-1]], axis=1)
    return src, dec, tgt


def greedy_decode(mod, src, seq_len, batch_size):
    """Argmax decoding, one position per pass through the fixed-shape
    decoder."""
    n = src.shape[0]
    # pad up to a whole number of batches: predict's per-batch pad trimming
    # assumes batch-row outputs, but this model emits batch*seq_len rows per
    # batch, so a partial final batch would misalign the concatenation
    n_pad = (-n) % batch_size
    src = np.concatenate([src, np.repeat(src[:1], n_pad, axis=0)]) \
        if n_pad else src
    dec = np.full((n + n_pad, seq_len), BOS, dtype="float32")
    out = np.zeros((n, seq_len), dtype="int64")
    for t in range(seq_len):
        it = mx.io.NDArrayIter({"data": src, "dec_data": dec},
                               batch_size=batch_size,
                               last_batch_handle="discard")
        scores = mod.predict(it).asnumpy()
        step = scores.reshape(n + n_pad, seq_len, -1)[:n, t, :].argmax(axis=1)
        out[:, t] = step
        if t + 1 < seq_len:
            dec[:n, t + 1] = step
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--seq-len", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=32)
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--num-heads", type=int, default=4)
    ap.add_argument("--model-dim", type=int, default=64)
    ap.add_argument("--ffn-dim", type=int, default=128)
    ap.add_argument("--num-epochs", type=int, default=8)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--train-size", type=int, default=4096)
    ap.add_argument("--val-size", type=int, default=256)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    rs = np.random.RandomState(11)
    src, dec, tgt = make_pairs(args.train_size, args.seq_len, args.vocab, rs)
    vsrc, vdec, vtgt = make_pairs(args.val_size, args.seq_len, args.vocab, rs)

    train = mx.io.NDArrayIter({"data": src, "dec_data": dec},
                              {"softmax_label": tgt},
                              batch_size=args.batch_size, shuffle=True,
                              last_batch_handle="discard")
    val = mx.io.NDArrayIter({"data": vsrc, "dec_data": vdec},
                            {"softmax_label": vtgt},
                            batch_size=args.batch_size,
                            last_batch_handle="discard")

    net = models.get_symbol(
        "transformer_mt", vocab_size=args.vocab, num_layers=args.num_layers,
        num_heads=args.num_heads, model_dim=args.model_dim,
        ffn_dim=args.ffn_dim, src_len=args.seq_len, tgt_len=args.seq_len)
    mod = mx.mod.Module(net, data_names=("data", "dec_data"),
                        label_names=("softmax_label",))
    mod.fit(train, eval_data=val, eval_metric=mx.metric.Perplexity(None),
            optimizer="adam", optimizer_params={"learning_rate": args.lr},
            initializer=mx.init.Xavier(factor_type="avg", magnitude=2.34),
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 25))

    decoded = greedy_decode(mod, vsrc, args.seq_len, args.batch_size)
    acc = float((decoded == vtgt.astype("int64")).all(axis=1).mean())
    print("greedy-decode exact-sequence accuracy: %.3f" % acc)


if __name__ == "__main__":
    main()
