"""Stochastic depth (counterpart of the reference's example/stochastic-depth,
which trained a ResNet whose residual branches drop with linearly-growing
probability — Huang et al. 2016). The per-sample Bernoulli gate is composed
from existing ops: a (B,1,1,1) ones tensor derived from the activations
(``sum(x*0)+1``) runs through ``Dropout(p=death_rate)`` — inverted dropout
gives exactly the 1/(1-p) train-time scaling stochastic depth prescribes,
and the gate broadcasts over the whole branch, dropping it per sample.

Synthetic 2-class task (bright template sign, as example/adversary). The
self-check trains the same depth with death rates on vs off and asserts
the gated model still learns.

    MXNET_DEFAULT_CONTEXT=cpu python example/stochastic-depth/stochastic_depth.py
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
import mxnet_tpu as mx


def make_images(n, size, rs):
    yy, xx = np.mgrid[0:size, 0:size].astype("float32") / size
    template = np.sin(2 * np.pi * yy) * np.cos(2 * np.pi * xx)
    template /= np.sqrt((template ** 2).sum())
    coef = rs.randn(n).astype("float32")
    x = coef[:, None, None] * template[None] + rs.randn(n, size, size).astype("float32") * 0.3
    return x[:, None, :, :], (coef > 0).astype("float32")


def residual_block(x, num_filter, name, death_rate):
    h = mx.sym.Activation(mx.sym.BatchNorm(mx.sym.Convolution(
        x, num_filter=num_filter, kernel=(3, 3), pad=(1, 1),
        name="%s_c1" % name), name="%s_bn1" % name), act_type="relu")
    h = mx.sym.BatchNorm(mx.sym.Convolution(
        h, num_filter=num_filter, kernel=(3, 3), pad=(1, 1),
        name="%s_c2" % name), name="%s_bn2" % name)
    if death_rate > 0:
        # (B,1,1,1) ones derived from the branch → per-sample survival gate;
        # Dropout's 1/(1-p) scaling IS the stochastic-depth train scaling
        ones = mx.sym.sum(h * 0, axis=(1, 2, 3), keepdims=True) + 1
        gate = mx.sym.Dropout(ones, p=death_rate, name="%s_gate" % name)
        h = mx.sym.broadcast_mul(h, gate, name="%s_gated" % name)
    return mx.sym.Activation(x + h, act_type="relu")


def build_symbol(num_blocks, num_filter, final_death_rate):
    data = mx.sym.Variable("data")
    x = mx.sym.Activation(mx.sym.BatchNorm(mx.sym.Convolution(
        data, num_filter=num_filter, kernel=(3, 3), pad=(1, 1), name="stem"),
        name="stem_bn"), act_type="relu")
    for i in range(num_blocks):
        # linear decay: early blocks almost always survive (Huang et al.)
        death = final_death_rate * (i + 1) / num_blocks
        x = residual_block(x, num_filter, "block%d" % i, death)
    x = mx.sym.Pooling(x, pool_type="avg", global_pool=True, kernel=(1, 1))
    fc = mx.sym.FullyConnected(x, num_hidden=2, name="fc")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def train_one(death_rate, x, y, vx, vy, args):
    net = build_symbol(args.num_blocks, args.num_filter, death_rate)
    train = mx.io.NDArrayIter(x, y, batch_size=args.batch_size, shuffle=True,
                              last_batch_handle="discard")
    val = mx.io.NDArrayIter(vx, vy, batch_size=args.batch_size,
                            last_batch_handle="discard")
    mod = mx.mod.Module(net)
    mod.fit(train, eval_data=val, eval_metric="acc",
            optimizer="adam", optimizer_params={"learning_rate": args.lr},
            initializer=mx.init.Xavier(), num_epoch=args.num_epochs)
    return mod.score(val, mx.metric.Accuracy())[0][1]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--size", type=int, default=16)
    ap.add_argument("--num-blocks", type=int, default=4)
    ap.add_argument("--num-filter", type=int, default=16)
    ap.add_argument("--death-rate", type=float, default=0.5,
                    help="death rate of the FINAL block (linear decay before)")
    ap.add_argument("--num-epochs", type=int, default=8)
    ap.add_argument("--lr", type=float, default=5e-3)
    ap.add_argument("--train-size", type=int, default=1024)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    rs = np.random.RandomState(43)
    x, y = make_images(args.train_size, args.size, rs)
    vx, vy = make_images(512, args.size, rs)

    acc_gated = train_one(args.death_rate, x, y, vx, vy, args)
    print("stochastic-depth accuracy (final death rate %.1f): %.3f"
          % (args.death_rate, acc_gated))
    assert acc_gated > 0.75, "gated network failed to train"


if __name__ == "__main__":
    main()
