"""Convolutional text classification (counterpart of the reference-era
example/cnn_text_classification, the Kim-2014 architecture): embedded
tokens → parallel conv branches with filter widths 3/4/5 → max-over-time
pooling → concat → dropout → FC. Exercises multi-branch Concat and
full-width Pooling, which no other example composes.

Synthetic, egress-free task: a sentence is "positive" iff it contains the
bigram (7, 7) anywhere — detectable only by a filter spanning adjacent
positions, so a bag-of-words shortcut cannot solve it.

    MXNET_DEFAULT_CONTEXT=cpu python example/cnn_text_classification/text_cnn.py
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
import mxnet_tpu as mx


def make_sentences(n, seq_len, vocab, rs):
    x = rs.randint(1, vocab, (n, seq_len)).astype("float32")
    # plant the (7,7) bigram in half the rows; scrub it from the rest
    y = np.zeros((n,), "float32")
    for i in range(n):
        if rs.rand() < 0.5:
            p = rs.randint(0, seq_len - 1)
            x[i, p:p + 2] = 7
            y[i] = 1
        else:
            hits = np.where((x[i, :-1] == 7) & (x[i, 1:] == 7))[0]
            for p in hits:
                x[i, p + 1] = 8 if x[i, p + 1] == 7 else x[i, p + 1]
    return x, y


def build_symbol(seq_len, vocab, num_embed, num_filter, widths, dropout):
    data = mx.sym.Variable("data")
    embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=num_embed,
                             name="embed")                     # (B,T,E)
    # conv wants NCHW: 1 input channel over a (T, E) image
    x = mx.sym.Reshape(embed, shape=(-1, 1, seq_len, num_embed))
    branches = []
    for w in widths:
        c = mx.sym.Convolution(x, num_filter=num_filter, kernel=(w, num_embed),
                               name="conv%d" % w)              # (B,F,T-w+1,1)
        c = mx.sym.Activation(c, act_type="relu")
        c = mx.sym.Pooling(c, pool_type="max",
                           kernel=(seq_len - w + 1, 1))        # max over time
        branches.append(mx.sym.Reshape(c, shape=(-1, num_filter)))
    h = mx.sym.Concat(*branches, dim=1, num_args=len(branches))
    h = mx.sym.Dropout(h, p=dropout)
    fc = mx.sym.FullyConnected(h, num_hidden=2, name="fc")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--seq-len", type=int, default=24)
    ap.add_argument("--vocab", type=int, default=50)
    ap.add_argument("--num-embed", type=int, default=32)
    ap.add_argument("--num-filter", type=int, default=32)
    ap.add_argument("--dropout", type=float, default=0.3)
    ap.add_argument("--num-epochs", type=int, default=5)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--train-size", type=int, default=4096)
    ap.add_argument("--val-size", type=int, default=512)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    rs = np.random.RandomState(13)
    x, y = make_sentences(args.train_size, args.seq_len, args.vocab, rs)
    vx, vy = make_sentences(args.val_size, args.seq_len, args.vocab, rs)
    train = mx.io.NDArrayIter(x, y, batch_size=args.batch_size, shuffle=True,
                              last_batch_handle="discard")
    val = mx.io.NDArrayIter(vx, vy, batch_size=args.batch_size,
                            last_batch_handle="discard")

    net = build_symbol(args.seq_len, args.vocab, args.num_embed,
                       args.num_filter, (3, 4, 5), args.dropout)
    mod = mx.mod.Module(net)
    mod.fit(train, eval_data=val, eval_metric="acc",
            optimizer="adam", optimizer_params={"learning_rate": args.lr},
            initializer=mx.init.Xavier(),
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50))
    score = mod.score(val, mx.metric.Accuracy())
    print("bigram-detection accuracy: %.3f" % score[0][1])


if __name__ == "__main__":
    main()
