"""SSD single-shot detector training (config 4 in BASELINE.json).

Compact counterpart of the reference's example/ssd/ app (train.py +
symbol/symbol_builder.py): a conv backbone with multi-scale feature maps,
per-scale class/location conv heads, MultiBoxPrior anchors, MultiBoxTarget
training targets, and the reference's SSD loss (SoftmaxOutput with ignore
label for classes + smooth-l1 MakeLoss for box offsets). The whole multi-loss
graph lowers to one XLA computation per step.

Runs on a synthetic detection set (random rectangles of `num-classes` colors)
since this environment has no egress; point --data-train at a .rec produced
by tools/im2rec.py --pack-label for real data.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
import mxnet_tpu as mx

logging.basicConfig(level=logging.DEBUG)


def conv_act(data, name, num_filter, kernel=(3, 3), pad=(1, 1), stride=(1, 1)):
    c = mx.sym.Convolution(data=data, num_filter=num_filter, kernel=kernel,
                           pad=pad, stride=stride, name="conv" + name)
    bn = mx.sym.BatchNorm(data=c, name="bn" + name)
    return mx.sym.Activation(data=bn, act_type="relu", name="relu" + name)


def multi_layer_feature(data):
    """Backbone producing 3 feature scales (reference: symbol_builder's
    multi_layer_feature over a VGG body)."""
    b1 = conv_act(conv_act(data, "1_1", 32), "1_2", 32)
    p1 = mx.sym.Pooling(data=b1, kernel=(2, 2), stride=(2, 2), pool_type="max")
    b2 = conv_act(conv_act(p1, "2_1", 64), "2_2", 64)
    p2 = mx.sym.Pooling(data=b2, kernel=(2, 2), stride=(2, 2), pool_type="max")
    b3 = conv_act(conv_act(p2, "3_1", 128), "3_2", 128)
    p3 = mx.sym.Pooling(data=b3, kernel=(2, 2), stride=(2, 2), pool_type="max")
    b4 = conv_act(p3, "4_1", 128)
    return [b2, b3, b4]


def get_ssd_symbol(num_classes):
    """Mini synthetic-data SSD: small backbone + the library's shared head
    and loss builders (mxnet_tpu.models.vgg16_ssd multibox_layer/ssd_losses)."""
    from mxnet_tpu.models.vgg16_ssd import multibox_layer, ssd_losses

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    layers = multi_layer_feature(data)
    sizes = [(0.2, 0.3), (0.4, 0.5), (0.7, 0.9)]
    ratios = [(1.0, 2.0, 0.5)] * 3
    cls_preds, loc_preds, anchors = multibox_layer(layers, num_classes, sizes, ratios)
    return ssd_losses(cls_preds, loc_preds, anchors, label)


class SyntheticDetIter(mx.io.DataIter):
    """Random colored rectangles with box labels in the reference's SSD
    label layout: (B, max_objects, 5) rows of [cls, xmin, ymin, xmax, ymax]."""

    def __init__(self, batch_size, data_shape, num_classes, num_batches=20,
                 max_objects=4, seed=0):
        super().__init__(batch_size)
        self.num_batches = num_batches
        self.cur = 0
        rs = np.random.RandomState(seed)
        b, c, h, w = (batch_size,) + data_shape
        imgs = np.zeros((b, c, h, w), np.float32)
        labels = -np.ones((b, max_objects, 5), np.float32)
        for i in range(b):
            for j in range(rs.randint(1, max_objects + 1)):
                cls = rs.randint(0, num_classes)
                x0, y0 = rs.uniform(0, 0.6, 2)
                x1, y1 = x0 + rs.uniform(0.2, 0.4), y0 + rs.uniform(0.2, 0.4)
                x1, y1 = min(x1, 1.0), min(y1, 1.0)
                imgs[i, cls % c, int(y0 * h):int(y1 * h), int(x0 * w):int(x1 * w)] = 1.0
                labels[i, j] = [cls, x0, y0, x1, y1]
        self.data, self.label = mx.nd.array(imgs), mx.nd.array(labels)
        self.provide_data = [mx.io.DataDesc("data", (b, c, h, w))]
        self.provide_label = [mx.io.DataDesc("label", labels.shape)]

    def next(self):
        if self.cur >= self.num_batches:
            raise StopIteration
        self.cur += 1
        return mx.io.DataBatch(data=[self.data], label=[self.label], pad=0)

    def reset(self):
        self.cur = 0


if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="train SSD", formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--network", type=str, default="vgg16-ssd-300",
                        choices=["vgg16-ssd-300", "mini"],
                        help="'vgg16-ssd-300' (reference parity, 300x300 "
                             "input) or 'mini' (small synthetic-data net)")
    parser.add_argument("--num-classes", type=int, default=20)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--data-shape", type=int, default=0,
                        help="input size; defaults to 300 for vgg16-ssd-300, "
                             "64 for mini")
    parser.add_argument("--num-epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=0.01)
    parser.add_argument("--kv-store", type=str, default="local")
    args = parser.parse_args()

    if args.network == "vgg16-ssd-300":
        from mxnet_tpu.models import vgg16_ssd

        net = vgg16_ssd.get_symbol_train(num_classes=args.num_classes)
        shape = args.data_shape or 300
    else:
        net = get_ssd_symbol(args.num_classes)
        shape = args.data_shape or 64
    train_iter = SyntheticDetIter(args.batch_size, (3, shape, shape),
                                  args.num_classes)

    mod = mx.mod.Module(net, data_names=("data",), label_names=("label",),
                        context=mx.current_context())
    mod.fit(
        train_iter,
        eval_metric=mx.metric.Loss(),
        kvstore=args.kv_store,
        optimizer="sgd",
        optimizer_params={"learning_rate": args.lr, "momentum": 0.9, "wd": 5e-4},
        initializer=mx.init.Xavier(),
        num_epoch=args.num_epochs,
        batch_end_callback=mx.callback.Speedometer(args.batch_size, 10),
    )
    print("SSD training finished")
