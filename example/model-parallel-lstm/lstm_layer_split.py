"""Model-parallel LSTM: layers placed on different devices via ctx groups
(counterpart of the reference's example/model-parallel-lstm, which pinned
each LSTM layer to its own GPU). Each of the two stacked LSTM layers lives
in its own ``ctx_group``; ``group2ctx`` maps the groups to devices and the
executor inserts the boundary copies — on a TPU pod those are ICI
transfers, here they run on the CPU mesh (``mx.cpu(0)``/``mx.cpu(1)``,
the reference's own multi-device-without-GPUs test trick).

A same-seed single-device run must produce identical losses — asserted at
the end, making the example self-checking.

    MXNET_DEFAULT_CONTEXT=cpu python example/model-parallel-lstm/lstm_layer_split.py
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
import mxnet_tpu as mx


def build_symbol(seq_len, vocab, num_embed, num_hidden):
    """Two LSTM layers, each in its own ctx group; heads in group 'dev2'."""
    with mx.AttrScope(ctx_group="dev1"):
        data = mx.sym.Variable("data")
        embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=num_embed,
                                 name="embed")
        c1 = mx.rnn.LSTMCell(num_hidden, prefix="l1_")
        l1, _ = c1.unroll(seq_len, inputs=embed, layout="NTC",
                          begin_state=c1.begin_state(batch_size=1),
                          merge_outputs=True)
    with mx.AttrScope(ctx_group="dev2"):
        c2 = mx.rnn.LSTMCell(num_hidden, prefix="l2_")
        l2, _ = c2.unroll(seq_len, inputs=l1, layout="NTC",
                          begin_state=c2.begin_state(batch_size=1),
                          merge_outputs=True)
        pred = mx.sym.Reshape(l2, shape=(-1, num_hidden))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name="pred")
        label = mx.sym.Reshape(mx.sym.Variable("softmax_label"), shape=(-1,))
        return mx.sym.SoftmaxOutput(pred, label=label, name="softmax")


def train(net, x, y, group2ctx, epochs, lr, batch):
    exe = net.simple_bind(mx.cpu(0), grad_req="write", group2ctx=group2ctx,
                          data=(batch, x.shape[1]),
                          softmax_label=(batch, x.shape[1]))
    rs = np.random.RandomState(3)
    for name, arr in sorted(exe.arg_dict.items()):
        if name not in ("data", "softmax_label"):
            arr[:] = rs.uniform(-0.1, 0.1, arr.shape).astype("float32")
    losses = []
    nb = x.shape[0] // batch
    for ep in range(epochs):
        tot = 0.0
        for k in range(nb):
            s = slice(k * batch, (k + 1) * batch)
            exe.arg_dict["data"][:] = x[s]
            exe.arg_dict["softmax_label"][:] = y[s]
            out = exe.forward(is_train=True)[0].asnumpy()
            flat = y[s].reshape(-1).astype(int)
            tot += -np.log(out[np.arange(len(flat)), flat] + 1e-8).mean()
            exe.backward()
            for name, g in exe.grad_dict.items():
                if g is not None and name not in ("data", "softmax_label"):
                    exe.arg_dict[name][:] = exe.arg_dict[name] - lr * g
        losses.append(tot / nb)
        logging.info("%s epoch %d loss %.4f",
                     "split" if len(group2ctx or {}) > 1 else "single",
                     ep, losses[-1])
    return losses


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=30)
    ap.add_argument("--num-embed", type=int, default=24)
    ap.add_argument("--num-hidden", type=int, default=32)
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--train-size", type=int, default=256)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO)
    rs = np.random.RandomState(7)
    x = rs.randint(1, args.vocab, (args.train_size, args.seq_len)).astype("float32")
    y = np.roll(x, -1, axis=1)  # next-token task

    net = build_symbol(args.seq_len, args.vocab, args.num_embed,
                       args.num_hidden)
    split = train(net, x, y, {"dev1": mx.cpu(0), "dev2": mx.cpu(1)},
                  args.num_epochs, args.lr, args.batch_size)
    single = train(net, x, y, None, args.num_epochs, args.lr,
                   args.batch_size)
    drift = max(abs(a - b) for a, b in zip(split, single))
    print("max |split - single| loss drift: %.2e (same math, different "
          "placement)" % drift)
    # fp reduction order differs across placements and compounds over SGD
    # steps; 1e-2 on a converging run separates reorder noise from real
    # placement bugs (a wrong boundary copy shows up at epoch 0, at O(1))
    assert abs(split[0] - single[0]) < 1e-4, "placement changed step-0 math!"
    assert drift < 1e-2, "model-parallel placement diverged beyond fp noise"


if __name__ == "__main__":
    main()
