"""Shared training harness for the image-classification examples.

Counterpart of the reference's example/image-classification/common/fit.py:
same CLI surface (`add_fit_args`) and the same `fit(args, network,
data_loader)` orchestration — kvstore creation, lr schedule from epoch
boundaries, checkpoint save/resume, Speedometer logging, optional monitor —
re-expressed over the TPU-native Module stack (every epoch step runs as one
fused XLA computation; `--kv-store dist_tpu_sync` rides ICI/DCN collectives
instead of a parameter server).
"""
import argparse
import logging
import math
import os
import time

import mxnet_tpu as mx


def _get_lr_scheduler(args, kv):
    """Build (lr, scheduler) from --lr/--lr-factor/--lr-step-epochs, scaled to
    steps the way the reference computes epoch_size from num-examples."""
    if not args.lr_factor or args.lr_factor >= 1:
        return (args.lr, None)
    epoch_size = args.num_examples // args.batch_size
    if "dist" in args.kv_store:
        epoch_size //= kv.num_workers
    begin_epoch = args.load_epoch if args.load_epoch else 0
    step_epochs = [int(l) for l in args.lr_step_epochs.split(",")]
    lr = args.lr
    for s in step_epochs:
        if begin_epoch >= s:
            lr *= args.lr_factor
    if lr != args.lr:
        logging.info("Adjust learning rate to %e for epoch %d", lr, begin_epoch)
    steps = [epoch_size * (x - begin_epoch) for x in step_epochs if x - begin_epoch > 0]
    if not steps:
        return (lr, None)
    return (lr, mx.lr_scheduler.MultiFactorScheduler(step=steps, factor=args.lr_factor))


def _load_model(args, rank=0):
    if args.load_epoch is None or args.model_prefix is None:
        return (None, None, None)
    model_prefix = args.model_prefix
    if rank > 0 and os.path.exists("%s-%d-symbol.json" % (model_prefix, rank)):
        model_prefix += "-%d" % rank
    sym, arg_params, aux_params = mx.model.load_checkpoint(model_prefix, args.load_epoch)
    logging.info("Loaded model %s_%04d.params", model_prefix, args.load_epoch)
    return (sym, arg_params, aux_params)


def _save_model(args, rank=0):
    if args.model_prefix is None:
        return None
    dst_dir = os.path.dirname(args.model_prefix)
    if dst_dir and not os.path.isdir(dst_dir):
        os.makedirs(dst_dir, exist_ok=True)
    prefix = args.model_prefix if rank == 0 else args.model_prefix + "-%d" % rank
    return mx.callback.do_checkpoint(prefix)


def add_fit_args(parser):
    """Same flag set as the reference's common/fit.py add_fit_args."""
    train = parser.add_argument_group("Training", "model training")
    train.add_argument("--network", type=str, help="the neural network to use")
    train.add_argument("--num-layers", type=int, help="number of layers in the neural network")
    train.add_argument("--gpus", type=str,
                       help="device list, e.g. 0 or 0,2,5 (kept for script parity; "
                            "on TPU, devices come from the JAX runtime)")
    train.add_argument("--kv-store", type=str, default="local",
                       help="key-value store type (local|device|dist_tpu_sync)")
    train.add_argument("--num-epochs", type=int, default=100, help="max num of epochs")
    train.add_argument("--lr", type=float, default=0.1, help="initial learning rate")
    train.add_argument("--lr-factor", type=float, default=0.1,
                       help="the ratio to reduce lr on each step")
    train.add_argument("--lr-step-epochs", type=str, help="the epochs to reduce the lr, e.g. 30,60")
    train.add_argument("--optimizer", type=str, default="sgd", help="the optimizer type")
    train.add_argument("--mom", type=float, default=0.9, help="momentum for sgd")
    train.add_argument("--wd", type=float, default=0.0001, help="weight decay for sgd")
    train.add_argument("--batch-size", type=int, default=128, help="the batch size")
    train.add_argument("--disp-batches", type=int, default=20,
                       help="show progress for every n batches")
    train.add_argument("--model-prefix", type=str, help="model checkpoint prefix")
    train.add_argument("--monitor", dest="monitor", type=int, default=0,
                       help="log network parameters every N iters if larger than 0")
    train.add_argument("--load-epoch", type=int, help="load the model on an epoch using the model-prefix")
    train.add_argument("--top-k", type=int, default=0,
                       help="report the top-k accuracy. 0 means no report.")
    train.add_argument("--test-io", type=int, default=0,
                       help="1 means test reading speed without training")
    train.add_argument("--dtype", type=str, default="float32",
                       help="precision: float32 or bfloat16 (the TPU-native fp16)")
    return train


def fit(args, network, data_loader, **kwargs):
    """Train `network` with the iterators from `data_loader(args, kv)`
    (reference: common/fit.py fit)."""
    kv = mx.kvstore.create(args.kv_store)

    head = "%(asctime)-15s Node[" + str(kv.rank) + "] %(message)s"
    logging.basicConfig(level=logging.DEBUG, format=head)
    logging.info("start with arguments %s", args)

    (train, val) = data_loader(args, kv)
    if args.test_io:
        tic = time.time()
        for i, batch in enumerate(train):
            for j in batch.data:
                j.wait_to_read()
            if (i + 1) % args.disp_batches == 0:
                logging.info("Batch [%d]\tSpeed: %.2f samples/sec", i,
                             args.disp_batches * args.batch_size / (time.time() - tic))
                tic = time.time()
        return

    sym, arg_params, aux_params = _load_model(args, kv.rank)
    if sym is not None:
        assert sym.tojson() == network.tojson()

    checkpoint = _save_model(args, kv.rank)

    # devices: TPU chips come from the JAX runtime; --gpus kept for parity
    if args.gpus is None or args.gpus == "":
        devs = mx.current_context()
    else:
        devs = [mx.gpu(int(i)) for i in args.gpus.split(",")]

    lr, lr_scheduler = _get_lr_scheduler(args, kv)

    model = mx.mod.Module(context=devs, symbol=network)

    optimizer_params = {
        "learning_rate": lr,
        "wd": args.wd,
        "lr_scheduler": lr_scheduler,
    }
    if args.optimizer in ("sgd", "nag"):
        optimizer_params["momentum"] = args.mom

    monitor = mx.mon.Monitor(args.monitor, pattern=".*") if args.monitor > 0 else None

    if args.network and args.network == "alexnet":
        initializer = mx.init.Normal()
    else:
        initializer = mx.init.Xavier(rnd_type="gaussian", factor_type="in", magnitude=2)

    eval_metrics = ["accuracy"]
    if args.top_k > 0:
        eval_metrics.append(mx.metric.create("top_k_accuracy", top_k=args.top_k))

    batch_end_callbacks = [mx.callback.Speedometer(args.batch_size, args.disp_batches)]

    model.fit(
        train,
        begin_epoch=args.load_epoch if args.load_epoch else 0,
        num_epoch=args.num_epochs,
        eval_data=val,
        eval_metric=eval_metrics,
        kvstore=kv,
        optimizer=args.optimizer,
        optimizer_params=optimizer_params,
        initializer=initializer,
        arg_params=arg_params,
        aux_params=aux_params,
        batch_end_callback=batch_end_callbacks,
        epoch_end_callback=checkpoint,
        allow_missing=True,
        monitor=monitor,
        **kwargs,
    )
