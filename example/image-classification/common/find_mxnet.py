"""Put the in-tree mxnet_tpu package on sys.path (reference:
example/image-classification/common/find_mxnet.py does the same for mxnet)."""
import os
import sys

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..", ".."))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

import mxnet_tpu as mx  # noqa: E402,F401
