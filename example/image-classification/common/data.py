"""Data loaders for the image-classification examples.

Counterpart of the reference's example/image-classification/common/data.py
(`add_data_args`, `get_rec_iter`) plus its `--benchmark` synthetic mode
(train_imagenet.py --benchmark 1). Since this environment has no network
egress, every loader falls back to an in-memory synthetic set with the same
shapes when the real files are absent — the reference's own benchmark mode
does exactly this (random data, fixed label) to measure compute throughput.
"""
import logging
import os

import numpy as np

import mxnet_tpu as mx


def add_data_args(parser):
    data = parser.add_argument_group("Data", "the input images")
    data.add_argument("--data-train", type=str, help="the training data (.rec)")
    data.add_argument("--data-val", type=str, help="the validation data (.rec)")
    data.add_argument("--rgb-mean", type=str, default="123.68,116.779,103.939",
                      help="a tuple of size 3 for the mean rgb")
    data.add_argument("--pad-size", type=int, default=0, help="padding size")
    data.add_argument("--image-shape", type=str,
                      help="the image shape feed into the network, e.g. (3,224,224)")
    data.add_argument("--num-classes", type=int, help="the number of classes")
    data.add_argument("--num-examples", type=int, help="the number of training examples")
    data.add_argument("--data-nthreads", type=int, default=4,
                      help="number of threads for data decoding")
    data.add_argument("--benchmark", type=int, default=0,
                      help="if 1, then feed the network with synthetic data")
    return data


def add_data_aug_args(parser):
    aug = parser.add_argument_group("Augmentation", "image augmentation")
    aug.add_argument("--random-crop", type=int, default=1,
                     help="if or not randomly crop the image")
    aug.add_argument("--random-mirror", type=int, default=1,
                     help="if or not randomly flip horizontally")
    aug.add_argument("--max-random-h", type=int, default=0, help="max change of hue")
    aug.add_argument("--max-random-s", type=int, default=0, help="max change of saturation")
    aug.add_argument("--max-random-l", type=int, default=0, help="max change of lightness")
    aug.add_argument("--max-random-aspect-ratio", type=float, default=0,
                     help="max change of aspect ratio")
    aug.add_argument("--max-random-rotate-angle", type=int, default=0,
                     help="max angle to rotate")
    aug.add_argument("--max-random-shear-ratio", type=float, default=0,
                     help="max ratio to shear")
    aug.add_argument("--max-random-scale", type=float, default=1,
                     help="max ratio to scale")
    aug.add_argument("--min-random-scale", type=float, default=1,
                     help="min ratio to scale")
    return aug


def set_data_aug_level(aug, level):
    if level >= 1:
        aug.set_defaults(random_crop=1, random_mirror=1)
    if level >= 2:
        aug.set_defaults(max_random_h=36, max_random_s=50, max_random_l=50)
    if level >= 3:
        aug.set_defaults(max_random_rotate_angle=10, max_random_shear_ratio=0.1,
                         max_random_aspect_ratio=0.25)


class SyntheticDataIter(mx.io.DataIter):
    """Random images + labels, generated once and replayed — the reference's
    `--benchmark 1` feeding strategy (train_imagenet.py)."""

    def __init__(self, num_classes, data_shape, num_batches=50, dtype="float32"):
        super().__init__(data_shape[0])
        self.num_batches = num_batches
        self.cur_batch = 0
        rs = np.random.RandomState(0)
        label = rs.randint(0, num_classes, (data_shape[0],)).astype(dtype)
        data = rs.uniform(-1, 1, data_shape).astype(dtype)
        self.data = mx.nd.array(data)
        self.label = mx.nd.array(label)
        self.provide_data = [mx.io.DataDesc("data", data_shape)]
        self.provide_label = [mx.io.DataDesc("softmax_label", (data_shape[0],))]

    def next(self):
        if self.cur_batch >= self.num_batches:
            raise StopIteration
        self.cur_batch += 1
        return mx.io.DataBatch(data=[self.data], label=[self.label], pad=0)

    def reset(self):
        self.cur_batch = 0


def get_rec_iter(args, kv=None):
    """ImageRecordIter pair over --data-train/--data-val; synthetic fallback
    when --benchmark 1 or the .rec files are missing (no egress here)."""
    image_shape = tuple(int(l) for l in args.image_shape.split(","))
    if "benchmark" in args and args.benchmark:
        data_shape = (args.batch_size,) + image_shape
        train = SyntheticDataIter(args.num_classes, data_shape)
        return (train, None)
    if not args.data_train or not os.path.exists(args.data_train):
        logging.warning("training .rec %r not found — using synthetic data "
                        "(reference --benchmark mode)", args.data_train)
        data_shape = (args.batch_size,) + image_shape
        return (SyntheticDataIter(args.num_classes, data_shape), None)

    rank, nworker = (kv.rank, kv.num_workers) if kv else (0, 1)
    rgb_mean = [float(i) for i in args.rgb_mean.split(",")]
    train = mx.img.ImageRecordIter(
        path_imgrec=args.data_train,
        data_shape=image_shape,
        batch_size=args.batch_size,
        shuffle=True,
        rand_crop=bool(getattr(args, "random_crop", 0)),
        rand_mirror=bool(getattr(args, "random_mirror", 0)),
        mean_r=rgb_mean[0], mean_g=rgb_mean[1], mean_b=rgb_mean[2],
        pad=args.pad_size,
        num_parts=nworker, part_index=rank,
        preprocess_threads=args.data_nthreads,
    )
    if args.data_val is None or not os.path.exists(args.data_val):
        return (train, None)
    val = mx.img.ImageRecordIter(
        path_imgrec=args.data_val,
        data_shape=image_shape,
        batch_size=args.batch_size,
        shuffle=False,
        rand_crop=False, rand_mirror=False,
        mean_r=rgb_mean[0], mean_g=rgb_mean[1], mean_b=rgb_mean[2],
        num_parts=nworker, part_index=rank,
        preprocess_threads=args.data_nthreads,
    )
    return (train, val)
