"""Train on CIFAR-10.

Counterpart of the reference's example/image-classification/train_cifar10.py:
same CLI and defaults (resnet-110 class of model on 3x28x28 crops, .rec
input with synthetic fallback — see common/data.py).
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(__file__))
from common import find_mxnet  # noqa: F401
import mxnet_tpu as mx  # noqa: F401
from common import data, fit

logging.basicConfig(level=logging.DEBUG)

if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="train cifar10",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    fit.add_fit_args(parser)
    data.add_data_args(parser)
    aug = data.add_data_aug_args(parser)
    data.set_data_aug_level(aug, 1)
    parser.set_defaults(
        network="resnet",
        num_layers=18,
        num_classes=10,
        num_examples=50000,
        image_shape="3,28,28",
        pad_size=4,
        batch_size=128,
        num_epochs=300,
        lr=0.05,
        lr_step_epochs="200,250",
    )
    args = parser.parse_args()

    from mxnet_tpu import models

    sym = models.get_symbol(
        args.network,
        num_classes=args.num_classes,
        num_layers=args.num_layers,
        image_shape=args.image_shape,
    )

    fit.fit(args, sym, data.get_rec_iter)
