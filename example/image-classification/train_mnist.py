"""Train on MNIST (config 1 in BASELINE.json).

Counterpart of the reference's example/image-classification/train_mnist.py:
same CLI, same default mlp network, same NDArrayIter feeding. The reference
downloads MNIST from the web; here the loader reads local idx files when
present (``data/train-images-idx3-ubyte`` etc., plain or .gz) and otherwise
trains on a deterministic synthetic digit set so the script always runs in
an egress-free environment.

Usage:
    python train_mnist.py                     # mlp
    python train_mnist.py --network lenet     # conv net
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(__file__))
from common import find_mxnet  # noqa: F401  (puts the in-tree package on sys.path)
import mxnet_tpu as mx
from common import fit

logging.basicConfig(level=logging.DEBUG)


def read_data(label_path, image_path):
    """Read one MNIST idx (label, image) pair from local files."""
    from mxnet_tpu.io import _read_idx_file

    label = _read_idx_file(label_path)
    image = _read_idx_file(image_path)
    return (label, image)


def to4d(img):
    return img.reshape(img.shape[0], 1, 28, 28).astype(np.float32) / 255


def _synthetic_mnist(n, num_classes, seed):
    """Deterministic stand-in when the real idx files are absent: each class
    is a distinct blocky template + noise, so models actually converge (the
    templates are fixed across train/val; only the noise seed differs)."""
    templates = np.random.RandomState(12345).rand(num_classes, 28, 28) > 0.7
    rs = np.random.RandomState(seed)
    labels = rs.randint(0, num_classes, (n,)).astype(np.float32)
    imgs = templates[labels.astype(int)].astype(np.float32) * 255
    imgs += rs.normal(0, 32, imgs.shape)
    return labels, np.clip(imgs, 0, 255).astype(np.uint8)


def get_mnist_iter(args, kv):
    data_dir = getattr(args, "data_dir", "data")
    names = {
        "train_lbl": "train-labels-idx1-ubyte", "train_img": "train-images-idx3-ubyte",
        "val_lbl": "t10k-labels-idx1-ubyte", "val_img": "t10k-images-idx3-ubyte",
    }
    def resolve(p):
        # prefer the plain idx file, fall back to the gzipped download name
        return p if os.path.exists(p) else (p + ".gz" if os.path.exists(p + ".gz") else None)

    paths = {k: resolve(os.path.join(data_dir, v)) for k, v in names.items()}
    if all(p is not None for p in paths.values()):
        train_lbl, train_img = read_data(paths["train_lbl"], paths["train_img"])
        val_lbl, val_img = read_data(paths["val_lbl"], paths["val_img"])
    else:
        logging.warning("MNIST idx files not found under %r — using synthetic digits",
                        data_dir)
        train_lbl, train_img = _synthetic_mnist(args.num_examples, args.num_classes, 0)
        val_lbl, val_img = _synthetic_mnist(args.num_examples // 6, args.num_classes, 1)
    train = mx.io.NDArrayIter(to4d(train_img), train_lbl, args.batch_size, shuffle=True)
    val = mx.io.NDArrayIter(to4d(val_img), val_lbl, args.batch_size)
    return (train, val)


if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="train mnist", formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--num-classes", type=int, default=10, help="the number of classes")
    parser.add_argument("--num-examples", type=int, default=60000,
                        help="the number of training examples")
    parser.add_argument("--data-dir", type=str, default="data",
                        help="directory holding the MNIST idx files")
    fit.add_fit_args(parser)
    parser.set_defaults(
        network="mlp",
        num_epochs=10,
        lr=0.05,
        lr_step_epochs="10",
    )
    args = parser.parse_args()

    from mxnet_tpu import models

    if args.network == "mlp":
        sym = models.get_symbol("mlp", num_classes=args.num_classes)
    else:
        sym = models.get_symbol(args.network, num_classes=args.num_classes,
                                image_shape="1,28,28")

    fit.fit(args, sym, get_mnist_iter)
