"""Inference throughput over the model zoo.

Counterpart of the reference's example/image-classification/benchmark_score.py:
scores each network on synthetic data across batch sizes and prints img/s.
Here each network is one compiled XLA executable; the first call per (net,
batch) pays compilation, so timing starts after warmup.

Usage: python benchmark_score.py [--networks resnet-50,inception-bn] [--batch-sizes 1,32,64]
"""
import argparse
import logging
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(__file__))
from common import find_mxnet  # noqa: F401
import mxnet_tpu as mx
from mxnet_tpu import models

logging.basicConfig(level=logging.INFO)


def score(network, batch_size, image_shape=(3, 224, 224), num_batches=10):
    sym = models.get_symbol(network, num_classes=1000,
                            image_shape=",".join(str(i) for i in image_shape))
    data_shape = [("data", (batch_size,) + image_shape)]
    mod = mx.mod.Module(symbol=sym, label_names=None)
    mod.bind(for_training=False, data_shapes=data_shape)
    mod.init_params(initializer=mx.init.Xavier(magnitude=2.0))
    rs = np.random.RandomState(0)
    batch = mx.io.DataBatch(
        data=[mx.nd.array(rs.rand(batch_size, *image_shape).astype(np.float32))],
        label=None, pad=0)
    # warmup: compile + settle
    for _ in range(3):
        mod.forward(batch, is_train=False)
    for o in mod.get_outputs():
        o.wait_to_read()
    tic = time.time()
    for _ in range(num_batches):
        mod.forward(batch, is_train=False)
    for o in mod.get_outputs():
        o.wait_to_read()
    return num_batches * batch_size / (time.time() - tic)


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description="benchmark inference throughput")
    parser.add_argument("--networks", type=str,
                        default="alexnet,vgg16,inception-bn,inception-v3,resnet-50")
    parser.add_argument("--batch-sizes", type=str, default="1,32")
    parser.add_argument("--image-shape", type=str, default=None,
                        help="e.g. 3,224,224; default: per-net canonical "
                             "shape (224, but 299 for inception-v3)")
    args = parser.parse_args()

    base_shape = (tuple(int(i) for i in args.image_shape.split(","))
                  if args.image_shape else (3, 224, 224))
    # canonical resolutions where they differ from 224 (reference
    # benchmark_score.py special-cased inception-v3 the same way); an
    # explicit --image-shape wins for every net
    canonical = {"inception-v3": (3, 299, 299)}
    for net in args.networks.split(","):
        image_shape = (base_shape if args.image_shape
                       else canonical.get(net, base_shape))
        logging.info("network: %s (input %s)", net, image_shape)
        for b in (int(x) for x in args.batch_sizes.split(",")):
            speed = score(net, b, image_shape)
            logging.info("batch size %2d, image/sec: %f", b, speed)
