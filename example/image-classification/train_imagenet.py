"""Train on ImageNet (config 2 in BASELINE.json).

Counterpart of the reference's example/image-classification/train_imagenet.py:
same CLI (fit + data + aug args, `--benchmark 1` synthetic mode), feeding an
ImageRecordIter over .rec packs produced by tools/im2rec.py. On TPU the whole
fwd+bwd+update step runs as one fused XLA computation per batch; use
``--kv-store dist_tpu_sync`` for multi-host pods.

Usage:
    python train_imagenet.py --network resnet --num-layers 50 --benchmark 1
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(__file__))
from common import find_mxnet  # noqa: F401
import mxnet_tpu as mx  # noqa: F401
from common import data, fit

logging.basicConfig(level=logging.DEBUG)

if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="train imagenet-1k",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    fit.add_fit_args(parser)
    data.add_data_args(parser)
    aug = data.add_data_aug_args(parser)
    data.set_data_aug_level(aug, 2)
    parser.set_defaults(
        network="resnet",
        num_layers=50,
        num_classes=1000,
        num_examples=1281167,
        image_shape="3,224,224",
        min_random_scale=1,
        num_epochs=80,
        lr_step_epochs="30,60",
        dtype="bfloat16",
    )
    args = parser.parse_args()

    from mxnet_tpu import models

    sym = models.get_symbol(
        args.network,
        num_classes=args.num_classes,
        num_layers=args.num_layers,
        image_shape=args.image_shape,
        dtype=args.dtype,
    )

    fit.fit(args, sym, data.get_rec_iter)
