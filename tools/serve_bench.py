#!/usr/bin/env python
"""Serving benchmark: synthetic open-loop load against the inference engine.

The headline perf artifact for the serving subsystem (docs/SERVING.md): a
load generator submits requests on a fixed open-loop schedule (arrivals do
NOT wait for completions — the honest serving-latency regime) against an
``InferenceEngine`` over a warmed ``PersistentExecutableCache``, then
reports

  - sustained QPS (completed requests / wall time),
  - p50 / p99 request latency (submit -> delivery),
  - batch occupancy (dispatched rows / dispatched bucket capacity),
  - post-warmup retrace/compile counts (MUST be zero — the engine's whole
    point; the sealed cache raises on the miss that would retrace, and the
    executor's compile/cache-hit telemetry proves the replay),
  - with ``--compare-batch1``: closed-loop saturation throughput of the
    bucket ladder vs a batch-size-1 engine — continuous batching's
    amortization of per-dispatch overhead, the PR's >=2x acceptance
    number.

``--model transformer-decode`` measures the KV-cache autoregressive path
instead: per-token decode-step latency and tokens/s over batched streams
(prefill bucket + single-token decode executable, zero retraces across
positions). ``--megastep-k K`` (default 8) adds the decode-megastep
comparison leg: the same streams decoded K tokens per dispatch through
the ``lax.scan`` megastep program (docs/SERVING.md §Megasteps), gated
under ``--check`` on token-identical parity with single-step greedy AND
``host_gap_per_token`` at K ≤ 0.5× the K=1 baseline.

``--workload zipf-prefix`` is the shared-prefix serving smoke
(docs/SERVING.md §Prefix cache & speculative decoding): requests draw
their prompt head from a small Zipf-distributed set of shared prefixes,
measured once against a prefix-cache-off PagedKVDecoder baseline and
once with the copy-on-write prefix cache on — reporting the chunk hit
rate, prefill tokens/FLOPs saved, a bitwise cached-vs-cold admit
subcheck, and the speculative-decoding leg (draft-verify megasteps,
``--spec-gamma`` / ``--spec-draft-layers``): accepted-draft rate plus
per-token p50/p99 against plain greedy, gated under ``--check`` on
token-identical parity, hit rate > 0.5, accepted rate > 0, spec p50 <=
baseline, and zero post-warmup retraces/compiles.

``--chaos`` is the serving resilience smoke (docs/RESILIENCE.md): the same
open-loop load, but with deterministic fault injection live on the
dispatch path (``serving.dispatch`` raise + delay plans,
mxnet_tpu/faultinject.py) and one hitless ``reload()`` fired mid-run. The
gate (with ``--check``) asserts ZERO hung futures (every request resolves
with a terminal state: completed | shed | deadline-failed |
injected-fault-after-retry), zero post-warmup retraces/compiles, the
reload applied, p99 of *completed* requests within ``--p99-bound-ms``, and
the engine back to ``healthy`` once injection stops.

    python tools/serve_bench.py --model mlp --qps 200 --duration 3 --json
    python tools/serve_bench.py --model lenet --compare-batch1 --check
    python tools/serve_bench.py --model mlp --chaos --qps 150 --duration 2 --check
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

ITEM_SHAPES = {
    "mlp": (784,),
    "lenet": (1, 28, 28),
    "resnet-18": (3, 32, 32),
}


def _model_kwargs(name):
    """get_symbol kwargs for an ITEM_SHAPES model — shared between the
    in-process builders and the fleet replica spec, so replicas build
    EXACTLY the model the baseline measures."""
    kwargs = {"num_classes": 10}
    if name.startswith("resnet"):
        kwargs["image_shape"] = ",".join(str(d)
                                         for d in ITEM_SHAPES[name])
    return kwargs


def _build_model(name):
    from mxnet_tpu import models
    from mxnet_tpu import context as _ctx

    item = ITEM_SHAPES[name]
    net = models.get_symbol(name, **_model_kwargs(name))
    probe = net.simple_bind(_ctx.current_context(), grad_req="null",
                            data=(1,) + item)
    rs = np.random.RandomState(0)
    arg_params = {k: (rs.randn(*a.shape) * 0.1).astype("float32")
                  for k, a in probe.arg_dict.items()
                  if k not in ("data", "softmax_label")}
    aux_params = {k: np.abs(rs.randn(*a.shape)).astype("float32") + 0.5
                  for k, a in probe.aux_dict.items()}
    return net, arg_params, aux_params, item


def _percentiles(lat_ms):
    if not lat_ms:
        return None, None
    return (float(np.percentile(lat_ms, 50)),
            float(np.percentile(lat_ms, 99)))


def _hist_delta_quantiles(name, warm_buckets):
    """Engine-side histogram quantiles for timer ``name`` over the
    measured window only: sparse-bucket delta against the pre-window
    snapshot, read back as {"p50": ms, "p95": ms, "p99": ms}."""
    from mxnet_tpu import telemetry
    from mxnet_tpu.telemetry import histogram as _hg

    end = telemetry.hist_buckets().get(name, {})
    warm = warm_buckets.get(name, {})
    db = {k: v - warm.get(k, 0) for k, v in end.items()
          if v - warm.get(k, 0) > 0}
    q = _hg.quantiles_from_buckets(db)
    q["count"] = sum(db.values())
    return q


def _quantile_agreement(hist_q, client_p50, client_p99,
                        abs_ms=(15.0, 50.0), rel=(0.5, 0.75)):
    """Cross-check histogram p50/p99 against client-side request-list
    percentiles: each must agree within max(abs floor, rel fraction) —
    loose enough for the ~10% bucket error + scheduling noise, tight
    enough to catch unit errors and a histogram measuring the wrong
    thing. Returns (ok, detail)."""
    detail = {"hist_p50_ms": round(hist_q.get("p50", 0.0), 3),
              "hist_p99_ms": round(hist_q.get("p99", 0.0), 3),
              "client_p50_ms": None if client_p50 is None
              else round(client_p50, 3),
              "client_p99_ms": None if client_p99 is None
              else round(client_p99, 3),
              "samples": hist_q.get("count", 0)}
    if not hist_q.get("count") or client_p50 is None:
        return False, detail
    ok = True
    for key, cli, a, r in (("p50", client_p50, abs_ms[0], rel[0]),
                           ("p99", client_p99, abs_ms[1], rel[1])):
        h = hist_q.get(key, 0.0)
        tol = max(a, r * max(cli, h))
        if abs(h - cli) > tol:
            ok = False
    detail["agree"] = ok
    return ok, detail


def _mk_engine(net, arg_params, aux_params, item, buckets, max_delay_ms,
               cache_dir, tag):
    from mxnet_tpu.serving import InferenceEngine, PersistentExecutableCache

    cache = PersistentExecutableCache(net, arg_params, aux_params,
                                      cache_dir=cache_dir,
                                      model_key=tag)
    return InferenceEngine(cache, {"data": item}, buckets=buckets,
                           max_delay_ms=max_delay_ms, name=tag)


def _counters():
    from mxnet_tpu import telemetry

    return dict(telemetry.counters())


def _open_loop(eng, item, qps, duration, rows):
    """Submit at the target rate for ``duration`` seconds; returns
    (latencies_ms, completed, elapsed, offered)."""
    rs = np.random.RandomState(1)
    payloads = [rs.rand(rows, *item).astype("float32") for _ in range(8)]
    futs = []
    start = time.perf_counter()
    n = 0
    interval = 1.0 / qps
    while True:
        now = time.perf_counter()
        if now - start >= duration:
            break
        target = start + n * interval
        if target > now:
            time.sleep(target - now)
        t0 = time.perf_counter()
        try:
            futs.append((t0, eng.submit({"data": payloads[n % 8]})))
        except Exception:
            futs.append((t0, None))  # backpressure drop counts as offered
        n += 1
    lat = []
    dropped = 0
    for t0, f in futs:
        if f is None:
            dropped += 1
            continue
        f.result(timeout=60.0)
        lat.append((f.done_at - t0) * 1000.0)
    elapsed = time.perf_counter() - start
    return lat, len(lat), elapsed, n, dropped


def _closed_loop(eng, item, n_requests, rows):
    """Saturation: all requests in flight at once; returns QPS."""
    rs = np.random.RandomState(2)
    x = rs.rand(rows, *item).astype("float32")
    t0 = time.perf_counter()
    futs = [eng.submit({"data": x}) for _ in range(n_requests)]
    for f in futs:
        f.result(timeout=120.0)
    return n_requests / (time.perf_counter() - t0)


def bench_engine(args):
    from mxnet_tpu import telemetry

    net, arg_params, aux_params, item = _build_model(args.model)
    buckets = tuple(int(b) for b in args.buckets.split(","))
    eng = _mk_engine(net, arg_params, aux_params, item, buckets,
                     args.max_delay_ms, args.cache_dir, args.model)
    eng.start()  # warmup compiles + seals here
    # burn-in: first post-warmup dispatch pays one-time jax dispatch-path
    # setup; keep it out of the measured window
    eng.infer({"data": np.zeros((args.rows,) + item, "float32")})
    c_warm = _counters()
    hb_warm = telemetry.hist_buckets()
    lat, completed, elapsed, offered, dropped = _open_loop(
        eng, item, args.qps, args.duration, args.rows)
    c_end = _counters()
    p50, p99 = _percentiles(lat)
    # engine-vs-client agreement: the serving.request timer histogram
    # (submit -> delivery, measured in _dispatch) must tell the same
    # latency story the client request list does
    _, hist_detail = _quantile_agreement(
        _hist_delta_quantiles("serving.request", hb_warm), p50, p99,
        abs_ms=(10.0, 25.0), rel=(0.4, 0.6))
    items = c_end.get("serving.batch_items", 0) - \
        c_warm.get("serving.batch_items", 0)
    capacity = c_end.get("serving.batch_capacity", 0) - \
        c_warm.get("serving.batch_capacity", 0)
    res = {
        "mode": "engine",
        "model": args.model,
        "buckets": list(buckets),
        "max_delay_ms": args.max_delay_ms,
        "offered_qps": args.qps,
        "qps": round(completed / elapsed, 2) if elapsed else 0.0,
        "requests": offered,
        "completed": completed,
        "dropped": dropped,
        "p50_ms": None if p50 is None else round(p50, 3),
        "p99_ms": None if p99 is None else round(p99, 3),
        "engine_hist": hist_detail,
        "batches": c_end.get("serving.batches", 0)
        - c_warm.get("serving.batches", 0),
        "batch_occupancy": round(items / capacity, 4) if capacity else None,
        "retraces_post_warmup": c_end.get("executor.retrace", 0)
        - c_warm.get("executor.retrace", 0),
        "compiles_post_warmup": c_end.get("executor.compile", 0)
        - c_warm.get("executor.compile", 0),
    }
    if args.compare_batch1:
        n_req = max(64, int(args.qps * min(args.duration, 2)))
        qps_b = _closed_loop(eng, item, n_req, args.rows)
        eng.close()
        eng1 = _mk_engine(net, arg_params, aux_params, item, (args.rows,),
                          0.0, None, args.model + "-b1")
        eng1.start()
        qps_1 = _closed_loop(eng1, item, n_req, args.rows)
        eng1.close()
        res["qps_batched_saturated"] = round(qps_b, 2)
        res["qps_batch1_saturated"] = round(qps_1, 2)
        res["batching_speedup"] = round(qps_b / qps_1, 2) if qps_1 else None
    else:
        eng.close()
    return res


def bench_decode(args):
    from mxnet_tpu import telemetry
    from mxnet_tpu.serving import KVCacheDecoder

    cfg = dict(vocab_size=256, num_layers=2, num_heads=2, model_dim=64,
               ffn_dim=128)
    S = 64
    # random weights straight from the decode graph's own shapes
    from mxnet_tpu.models import transformer as _tf
    from mxnet_tpu import context as _ctx

    probe_sym = _tf.get_symbol(seq_len=S, **cfg)
    probe = probe_sym.simple_bind(_ctx.current_context(), grad_req="null",
                                  data=(1, S), softmax_label=(1, S))
    rs = np.random.RandomState(0)
    params = {k: (rs.randn(*a.shape) * 0.1).astype("float32")
              for k, a in probe.arg_dict.items()
              if k not in ("data", "softmax_label")}
    B = args.rows
    dec = KVCacheDecoder(params, max_len=S, prefill_len=16, pos_len=S,
                         batch=B, cache_dir=args.cache_dir, **cfg)
    dec.warmup()
    prompt = rs.randint(1, 256, (B, 8)).astype("float32")
    K = max(0, int(args.megastep_k))
    if K > 1:
        # compile + seal the K-step megastep program BEFORE the counter
        # snapshot, exactly like warmup() does for the per-step executables
        # — the measured window must replay it with zero compiles
        wl = dec.prefill(prompt)
        wtok = np.argmax(wl, axis=-1)  # graphlint: waive GL703 -- warm leg, pre-snapshot
        dec.decode_megastep(wtok, k=K)
        dec.reset()
    c_warm = _counters()
    logits = dec.prefill(prompt)
    # first token from the prompt head: prefill already pulled the logits
    tok = np.argmax(logits, axis=-1)  # graphlint: waive GL703 -- once per sequence
    # one burn-in step: the first post-warmup dispatch pays one-time jax
    # dispatch-path setup that would otherwise read as a fake p99 outlier
    tok = dec.greedy_step(tok)
    steps = min(int(args.qps * args.duration), S - 8 - 2) or 1
    gap_t = telemetry.timer("dispatch.host_gap")
    lat = []
    gap0_ms = gap_t.total_ms
    t0 = time.perf_counter()
    for _ in range(steps):
        t1 = time.perf_counter()
        # graphlint: waive GL702 -- measuring the per-token loop IS the bench
        tok = dec.greedy_step(tok)
        lat.append((time.perf_counter() - t1) * 1000.0)
    elapsed = time.perf_counter() - t0
    gap_ms = gap_t.total_ms - gap0_ms
    p50, p99 = _percentiles(lat)
    # comparison leg: a short window in the pre-token-head shape (full
    # logits pull + host argmax) so the report carries the measured
    # host-gap delta the on-device greedy head buys
    dec.reset()
    logits = dec.prefill(prompt)
    cmp_steps = max(4, min(steps, 16))
    cgap0_ms = gap_t.total_ms
    t0c = time.perf_counter()
    for _ in range(cmp_steps):
        tok = np.argmax(logits, axis=-1)   # graphlint: waive GL703 -- comparison leg
        logits = dec.decode_step(tok)      # graphlint: waive GL702 -- comparison leg
    cmp_elapsed = time.perf_counter() - t0c
    cmp_gap_ms = gap_t.total_ms - cgap0_ms
    res = {
        "mode": "kv_decode",
        "model": "transformer-decode",
        "streams": B,
        "decode_steps": steps,
        "decode_path": "greedy_step" if dec._token_out else "decode_step",
        "qps": round(B * steps / elapsed, 2),  # tokens/s across streams
        "p50_ms": round(p50, 3),
        "p99_ms": round(p99, 3),
        "batch_occupancy": 1.0,
        # host time between one executable's return and the next enqueue
        # (the dispatch.host_gap timer), amortized per generated token
        "host_gap_ms": round(gap_ms, 3),
        "host_gap_per_token": round(gap_ms / (B * steps), 6),
        "host_argmax": {
            "steps": cmp_steps,
            "tokens_per_s": round(B * cmp_steps / cmp_elapsed, 2),
            "host_gap_per_token": round(cmp_gap_ms / (B * cmp_steps), 6),
        },
    }
    if K > 1:
        # megastep leg: parity first (K-chunked greedy must be
        # token-identical to single-step greedy), then a timed window of
        # K-token dispatches for the ≥2x host-gap-per-token gate
        n_par = 2 * K + 1
        dec.reset()
        seq = dec.greedy(prompt, n_par, k=1)
        dec.reset()
        mega = dec.greedy(prompt, n_par, k=K)
        parity = bool(np.array_equal(seq, mega))
        dec.reset()
        logits = dec.prefill(prompt)
        tok = np.argmax(logits, axis=-1)  # graphlint: waive GL703 -- once per sequence
        # burn-in megastep, then as many full-K chunks as positions allow
        chunk = dec.decode_megastep(tok, k=K)
        tok = chunk[:, -1]
        m_chunks = max(1, (S - prompt.shape[1] - K) // K)
        mgap0_ms = gap_t.total_ms
        t0m = time.perf_counter()
        for _ in range(m_chunks):
            # graphlint: waive GL702 -- measuring the megastep loop IS the bench
            chunk = dec.decode_megastep(tok, k=K)
            tok = chunk[:, -1]
        m_elapsed = time.perf_counter() - t0m
        m_gap_ms = gap_t.total_ms - mgap0_ms
        m_tokens = B * m_chunks * K
        m_gap_per_tok = round(m_gap_ms / m_tokens, 6)
        res["megastep"] = {
            "k": K,
            "chunks": m_chunks,
            "tokens_per_s": round(m_tokens / m_elapsed, 2),
            "host_gap_per_token": m_gap_per_tok,
            "parity_token_identical": parity,
            "k_sweep": [
                {"k": 1, "tokens_per_s": res["qps"],
                 "host_gap_per_token": res["host_gap_per_token"]},
                {"k": K, "tokens_per_s": round(m_tokens / m_elapsed, 2),
                 "host_gap_per_token": m_gap_per_tok},
            ],
        }
    c_end = _counters()
    res["retraces_post_warmup"] = c_end.get("executor.retrace", 0) \
        - c_warm.get("executor.retrace", 0)
    res["compiles_post_warmup"] = c_end.get("executor.compile", 0) \
        - c_warm.get("executor.compile", 0)
    return res


def _decode_params(cfg, S, seed=0):
    """Random transformer weights straight from the training graph's own
    shapes (the decode/prefill/chunk programs bind the same names)."""
    from mxnet_tpu.models import transformer as _tf
    from mxnet_tpu import context as _ctx

    probe = _tf.get_symbol(seq_len=S, **cfg).simple_bind(
        _ctx.current_context(), grad_req="null", data=(1, S),
        softmax_label=(1, S))
    rs = np.random.RandomState(seed)
    return {k: (rs.randn(*a.shape) * 0.1).astype("float32")
            for k, a in probe.arg_dict.items()
            if k not in ("data", "softmax_label")}


def _zipf_prompts(rs, n_requests, vocab, prefixes, suffix_len, alpha):
    """Shared-prefix workload: each request draws its prompt head from
    ``prefixes`` with Zipf(alpha) popularity and appends a unique random
    suffix — the distribution real multi-tenant serving sees (few hot
    system prompts, long unique tails)."""
    ranks = np.arange(1, len(prefixes) + 1, dtype=np.float64)
    pz = ranks ** -float(alpha)
    pz /= pz.sum()
    picks = rs.choice(len(prefixes), size=n_requests, p=pz)
    out = []
    for i in picks:
        sfx = rs.randint(1, vocab, (suffix_len,))
        out.append(np.concatenate([prefixes[int(i)],
                                   sfx]).astype("float32"))
    return out


def bench_prefix_spec(args):
    """--workload zipf-prefix: the shared-prefix cache + speculative
    decoding leg. One decoder with the prefix cache OFF is the latency
    baseline; the same workload then replays against the COW prefix
    cache, and a draft-verify SpeculativeDecoder races plain greedy."""
    from mxnet_tpu import telemetry
    from mxnet_tpu.serving import PagedKVDecoder, SpeculativeDecoder

    cfg = dict(vocab_size=256, num_layers=2, num_heads=2, model_dim=64,
               ffn_dim=128)
    S = 64
    params = _decode_params(cfg, S)
    n_params = int(sum(int(np.prod(v.shape)) for v in params.values()))

    C = 8                       # prefix chunk == page size
    plen = 3 * C                # shared head: 3 cacheable chunks
    suffix_len = C              # unique tail: 1 chunk per request
    n_decode = 4                # decode tail per request
    n_req = max(24, min(200, int(args.qps * args.duration)))
    rs = np.random.RandomState(0)
    prefixes = [rs.randint(1, cfg["vocab_size"], (plen,))
                for _ in range(4)]
    prompts = _zipf_prompts(rs, n_req, cfg["vocab_size"], prefixes,
                            suffix_len, alpha=1.1)
    serve = dict(max_len=S, page_size=C, lanes=4,
                 prefill_len=plen + suffix_len, pos_len=S,
                 cache_dir=args.cache_dir)

    def _run_requests(dec, plist):
        lat = []
        for p in plist:
            t0 = time.perf_counter()
            sid, logits = dec.admit(p)
            # graphlint: waive GL703 -- one argmax per admitted request
            tok = int(np.argmax(logits))
            for _ in range(n_decode):
                # graphlint: waive GL702 -- the per-request decode tail IS the workload
                out = dec.step({sid: tok})
                # graphlint: waive GL703 -- bench workload loop, one id per step
                tok = int(np.argmax(out[sid]))
            dec.retire(sid)
            lat.append((time.perf_counter() - t0) * 1000.0)
        return lat

    base = PagedKVDecoder(params, prefix_cache=False, **cfg,
                          **serve).warmup()
    cached = PagedKVDecoder(params, prefix_cache=True, prefix_chunk=C,
                            **cfg, **serve).warmup()
    # burn-in (one-time jax dispatch-path setup) with prompts ALIEN to
    # the workload's prefixes, so the measured hit rate is untouched
    alien = [np.concatenate([rs.randint(1, 256, (plen,)),
                             rs.randint(1, 256, (suffix_len,))
                             ]).astype("float32") for _ in range(2)]
    _run_requests(base, alien[:1])
    _run_requests(cached, alien[:1])
    # bitwise cached-vs-cold: the SAME prompt admitted cold, retired,
    # then admitted again off the cache must produce identical logits
    sid, cold = cached.admit(alien[1])
    cached.retire(sid)
    sid, warm2 = cached.admit(alien[1])
    cached.retire(sid)
    bitwise = bool(np.array_equal(cold, warm2))

    # build + warm the speculative pair BEFORE the compile snapshot:
    # the zero-post-warmup gate below covers BOTH measured legs
    g = max(1, int(args.spec_gamma))
    dl = int(args.spec_draft_layers) or cfg["num_layers"]
    sserve = dict(max_len=S, page_size=C, lanes=1, prefill_len=16,
                  pos_len=S, prefix_cache=False,
                  cache_dir=args.cache_dir)
    spec = SpeculativeDecoder.build(params, draft_layers=dl, gamma=g,
                                    **cfg, **sserve).warmup()
    sbase = PagedKVDecoder(params, **cfg, **sserve).warmup()
    n_tok = 24
    sprompts = [rs.randint(1, cfg["vocab_size"], (8,)).astype("float32")
                for _ in range(6)]
    # parity subcheck doubles as the burn-in for both timed paths
    parity = bool(np.array_equal(
        spec.greedy(sprompts[0], n_tok),
        sbase.greedy([sprompts[0]], n_tok, k=1)[0]))

    c_warm = _counters()
    t0 = time.perf_counter()
    lat_base = _run_requests(base, prompts)
    lat_cache = _run_requests(cached, prompts)
    elapsed = time.perf_counter() - t0
    c_mid = _counters()

    hits = c_mid.get("serving.prefix_hits", 0) \
        - c_warm.get("serving.prefix_hits", 0)
    misses = c_mid.get("serving.prefix_misses", 0) \
        - c_warm.get("serving.prefix_misses", 0)
    saved = c_mid.get("serving.prefill_tokens_saved", 0) \
        - c_warm.get("serving.prefill_tokens_saved", 0)
    p50b, p99b = _percentiles(lat_base)
    p50c, p99c = _percentiles(lat_cache)
    prefix = {
        "chunk_hits": hits,
        "chunk_misses": misses,
        "hit_rate": round(hits / (hits + misses), 4)
        if hits + misses else 0.0,
        "tokens_saved": saved,
        # ~2 FLOPs per weight per token (matmul-dominated forward): the
        # standard estimate, reported as such
        "param_count": n_params,
        "prefill_flops_saved": int(saved * 2 * n_params),
        "pages_shared": c_mid.get("serving.pages_shared", 0)
        - c_warm.get("serving.pages_shared", 0),
        "cow_copies": c_mid.get("serving.cow_copies", 0)
        - c_warm.get("serving.cow_copies", 0),
        "evictions": c_mid.get("serving.prefix_evictions", 0)
        - c_warm.get("serving.prefix_evictions", 0),
        "p50_ms": round(p50c, 3), "p99_ms": round(p99c, 3),
        "baseline_p50_ms": round(p50b, 3),
        "baseline_p99_ms": round(p99b, 3),
        "bitwise_cached_vs_cold": bitwise,
        "cache": cached.stats().get("prefix_cache"),
    }

    # ---- speculative leg: draft proposes gamma tokens per round, the
    # target scores all gamma+1 in one rectangular verify dispatch
    c_sp0 = c_mid
    sl_base, sl_spec = [], []
    for p in sprompts:
        t1 = time.perf_counter()
        sbase.greedy([p], n_tok, k=1)
        sl_base.append((time.perf_counter() - t1) * 1000.0 / n_tok)
    for p in sprompts:
        t1 = time.perf_counter()
        spec.greedy(p, n_tok)
        sl_spec.append((time.perf_counter() - t1) * 1000.0 / n_tok)
    c_end = _counters()
    proposed = c_end.get("spec.proposed_tokens", 0) \
        - c_sp0.get("spec.proposed_tokens", 0)
    accepted = c_end.get("spec.accepted_tokens", 0) \
        - c_sp0.get("spec.accepted_tokens", 0)
    sp50b, sp99b = _percentiles(sl_base)
    sp50s, sp99s = _percentiles(sl_spec)
    spec_res = {
        "gamma": g, "draft_layers": dl,
        "proposed_tokens": proposed, "accepted_tokens": accepted,
        "accepted_rate": round(accepted / proposed, 4)
        if proposed else 0.0,
        "rollbacks": c_end.get("spec.rollbacks", 0)
        - c_sp0.get("spec.rollbacks", 0),
        "p50_ms_per_token": round(sp50s, 4),
        "p99_ms_per_token": round(sp99s, 4),
        "baseline_p50_ms_per_token": round(sp50b, 4),
        "baseline_p99_ms_per_token": round(sp99b, 4),
        "parity_token_identical": parity,
    }
    return {
        "mode": "prefix_spec",
        "model": "transformer-decode",
        "workload": "zipf-prefix",
        "requests": n_req,
        "prefixes": len(prefixes),
        "zipf_alpha": 1.1,
        "prompt_len": plen + suffix_len,
        "prefix_chunk": C,
        "qps": round(n_req / elapsed, 2) if elapsed else 0.0,
        "p50_ms": prefix["p50_ms"], "p99_ms": prefix["p99_ms"],
        "prefix": prefix,
        "spec": spec_res,
        "retraces_post_warmup": c_end.get("executor.retrace", 0)
        - c_warm.get("executor.retrace", 0),
        "compiles_post_warmup": c_end.get("executor.compile", 0)
        - c_warm.get("executor.compile", 0),
    }


def bench_chaos(args):
    """Open-loop load under injected dispatch faults + one mid-run hitless
    reload; classifies every request's terminal state."""
    import mxnet_tpu  # noqa: F401  (package import before submodules)
    from mxnet_tpu import faultinject as fi
    from mxnet_tpu.serving import (InferenceEngine,
                                   PersistentExecutableCache,
                                   ServeDeadlineError, ServeOverloadError)

    net, arg_params, aux_params, item = _build_model(args.model)
    buckets = tuple(int(b) for b in args.buckets.split(","))
    cache = PersistentExecutableCache(net, arg_params, aux_params,
                                      cache_dir=args.cache_dir,
                                      model_key=args.model + "-chaos")
    eng = InferenceEngine(cache, {"data": item}, buckets=buckets,
                          max_delay_ms=args.max_delay_ms,
                          name=args.model + "-chaos",
                          deadline_ms=args.chaos_deadline_ms,
                          health_window_s=1.0)
    eng.start()
    eng.infer({"data": np.zeros((args.rows,) + item, "float32")})  # burn-in
    c_warm = _counters()
    fi.reset_stats()
    # the weights the mid-run reload swaps in (same shapes — zero retraces)
    new_params = {k: (v * 1.05 + 0.01).astype("float32")
                  for k, v in arg_params.items()}

    rs = np.random.RandomState(1)
    payloads = [rs.rand(args.rows, *item).astype("float32")
                for _ in range(8)]
    futs = []          # (t_submit, future or terminal-class string)
    reload_fut = None
    start = time.perf_counter()
    interval = 1.0 / args.qps
    n = 0
    half = args.duration / 2.0
    with fi.inject("serving.dispatch", "raise", prob=args.chaos_fail_prob,
                   seed=7), \
         fi.inject("serving.dispatch", "delay_ms",
                   prob=args.chaos_delay_prob, seed=11,
                   arg=args.chaos_delay_ms):
        while True:
            now = time.perf_counter()
            if now - start >= args.duration:
                break
            if reload_fut is None and now - start >= half:
                reload_fut = eng.reload(new_params)
            target = start + n * interval
            if target > now:
                time.sleep(target - now)
            t0 = time.perf_counter()
            try:
                futs.append((t0, eng.submit({"data": payloads[n % 8]})))
            except ServeOverloadError:
                futs.append((t0, "shed"))
            except Exception:
                futs.append((t0, "rejected"))  # backpressure etc.
            n += 1
    elapsed = time.perf_counter() - start

    counts = {"completed": 0, "shed": 0, "deadline": 0, "fault": 0,
              "rejected": 0, "hung": 0}
    lat = []
    for t0, f in futs:
        if isinstance(f, str):
            counts[f] += 1
            continue
        try:
            f.result(timeout=60.0)
            counts["completed"] += 1
            lat.append((f.done_at - t0) * 1000.0)
        except ServeDeadlineError:
            counts["deadline"] += 1
        except ServeOverloadError:
            counts["shed"] += 1
        except Exception:
            # terminal only if the future actually resolved; an unresolved
            # future after 60s is a HUNG request — the one chaos outcome
            # that must never happen
            counts["fault" if f.done() else "hung"] += 1
    reload_ok = False
    if reload_fut is not None:
        try:
            reload_ok = bool(reload_fut.result(timeout=30.0))
        except Exception:
            reload_ok = False

    # injection is over (context exited): a short clean run, then let the
    # recent-fault window drain — the engine must report healthy again
    for _ in range(10):
        eng.infer({"data": payloads[0]}, timeout=30.0)
    time.sleep(eng.health_window_s + 0.2)
    health = eng.health()
    c_end = _counters()
    fired = fi.stats()
    p50, p99 = _percentiles(lat)
    eng.close()
    return {
        "mode": "chaos",
        "model": args.model,
        "buckets": list(buckets),
        "offered_qps": args.qps,
        "duration_s": args.duration,
        "requests": n,
        "elapsed_s": round(elapsed, 3),
        "resolved": counts,
        "qps": round(counts["completed"] / elapsed, 2) if elapsed else 0.0,
        "p50_ms": None if p50 is None else round(p50, 3),
        "p99_ms": None if p99 is None else round(p99, 3),
        "reload_applied": reload_ok,
        "health_after": health,
        "injected": fired,
        "dispatch_retries": c_end.get("serving.dispatch_retries", 0)
        - c_warm.get("serving.dispatch_retries", 0),
        "deadline_expired": c_end.get("serving.deadline_expired", 0)
        - c_warm.get("serving.deadline_expired", 0),
        "retraces_post_warmup": c_end.get("executor.retrace", 0)
        - c_warm.get("executor.retrace", 0),
        "compiles_post_warmup": c_end.get("executor.compile", 0)
        - c_warm.get("executor.compile", 0),
        "p99_bound_ms": args.p99_bound_ms,
    }


def bench_fleet(args):
    """The fleet smoke (docs/SERVING.md §Fleet): N replica PROCESSES
    behind the router under open-loop load with a seeded chaos plan —
    injected router-dispatch faults, one replica SIGKILLed mid-run (the
    supervisor restarts it), and one fleet-wide hitless rollout — plus
    the paged-KV multiplexed-decode parity check. Reports aggregate
    QPS/p99, redispatches, restarts, and the single-replica closed-loop
    baseline the aggregate must beat."""
    import shutil
    import tempfile
    import threading

    import mxnet_tpu  # noqa: F401
    from mxnet_tpu import faultinject as fi
    from mxnet_tpu import telemetry
    from mxnet_tpu.serving import ServeOverloadError, ServeDeadlineError
    from mxnet_tpu.serving.fleet import (Fleet, RpcClient, save_params_npz,
                                         FleetRolloutError)

    net, arg_params, aux_params, item = _build_model(args.model)
    buckets = [int(b) for b in args.buckets.split(",")]
    workdir = tempfile.mkdtemp(prefix="mxtpu_fleet_bench_")
    params_path = os.path.join(workdir, "params.npz")
    save_params_npz(params_path, arg_params, aux_params)
    spec = {"model": args.model,
            "model_kwargs": _model_kwargs(args.model),
            "item_shapes": {"data": list(item)},
            "buckets": buckets,
            "params": params_path,
            "engine": {"max_delay_ms": args.max_delay_ms},
            # replica subprocesses don't inherit the bench's in-process
            # set_mode(): ship the mode so their spans/timers exist for
            # the merged trace and the health() telemetry snapshots
            "telemetry": telemetry.mode(),
            "heartbeat_ms": 300}
    n = args.fleet_replicas
    rs = np.random.RandomState(1)
    payloads = [rs.rand(args.rows, *item).astype("float32")
                for _ in range(8)]
    new_params = {k: (v * 1.02 + 0.01).astype("float32")
                  for k, v in arg_params.items()}
    res = {"mode": "fleet", "model": args.model, "replicas": n,
           "buckets": buckets}
    fi.reset_stats()
    # latency discipline under oversubscription: per-request deadlines
    # purge stuck work, the router's absolute shed cap bounds the queueing
    # a completed request can have suffered — both scale off the p99 bound
    deadline_ms = args.p99_bound_ms / 2.0
    # SLO gate, windows scaled to bench length (env wins if already set):
    # err_pct is what the seeded 100% fault burst below must trip, and
    # what the recovery traffic must clear once the window rolls past
    os.environ.setdefault("MXNET_SLO_WINDOW_S", "4")
    os.environ.setdefault("MXNET_SLO_SHORT_WINDOW_S", "1")
    slo_spec = ("p99_ms:%g,err_pct:2,avail_pct:50"
                % args.p99_bound_ms)
    fleet = Fleet(spec, n_replicas=n, workdir=workdir,
                  router_kwargs=dict(
                      workers=max(8, 2 * n), health_interval_ms=100,
                      stale_ms=1500, shed_ms=args.p99_bound_ms / 4.0,
                      dispatch_wait_ms=30000, slo=slo_spec))
    try:
        t_up = time.perf_counter()
        fleet.start()
        res["startup_s"] = round(time.perf_counter() - t_up, 1)
        router = fleet.router

        # ---- single-replica closed-loop baseline through the SAME RPC
        # path. The GATE baseline is the textbook closed loop — ONE
        # client, next arrival waits for the completion — which is what a
        # single replica gives a synchronous upstream; the fleet's win
        # over it comes from replication hiding the per-request
        # batching/dispatch latency (on a multi-core host, from real
        # parallelism too). The 4-way saturated number is reported
        # alongside for the multi-core reading.
        addr = fleet.supervisor.addresses()[0]
        n_base = max(64, int(args.qps))

        def _closed(worker_idx, counts):
            cli = RpcClient(addr, timeout_s=60.0)
            for i in range(max(1, n_base // max(1, len(counts)))):
                cli.call("infer",
                         inputs={"data": payloads[(worker_idx + i) % 8]})
                counts[worker_idx] += 1
            cli.close()

        def _run_closed(conc):
            counts = [0] * conc
            t0 = time.perf_counter()
            ts = [threading.Thread(target=_closed, args=(i, counts))
                  for i in range(conc)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            return sum(counts) / (time.perf_counter() - t0)

        base_qps = _run_closed(1)
        sat_qps = _run_closed(4)
        res["qps_single_replica_closed"] = round(base_qps, 2)
        res["qps_single_replica_saturated"] = round(sat_qps, 2)
        res["host_cores"] = os.cpu_count()

        # ---- open-loop fleet load with the chaos plan: offered rate
        # oversubscribes a single replica's saturated capacity, so the
        # completed aggregate reflects what the REPLICATION carried
        offered_qps = max(args.qps, 1.6 * sat_qps)
        duration = args.duration
        interval = 1.0 / offered_qps
        futs = []
        rollout_result = {}
        victim_pid = None
        rollout_thread = None

        def _do_rollout():
            try:
                rollout_result["res"] = fleet.rollout(
                    new_params, drain_timeout_s=60.0)
            except FleetRolloutError as exc:
                rollout_result["error"] = str(exc)

        with fi.inject("fleet.dispatch", "raise",
                       prob=args.chaos_fail_prob, seed=7):
            start = time.perf_counter()
            k = 0
            while True:
                now = time.perf_counter()
                if now - start >= duration:
                    break
                if victim_pid is None and now - start >= duration / 3.0:
                    victim_pid = fleet.supervisor.kill_replica(0)
                if rollout_thread is None and \
                        now - start >= duration / 2.0:
                    rollout_thread = threading.Thread(target=_do_rollout)
                    rollout_thread.start()
                target = start + k * interval
                if target > now:
                    time.sleep(target - now)
                t0 = time.perf_counter()
                try:
                    futs.append((t0, router.submit(
                        {"data": payloads[k % 8]},
                        deadline_ms=deadline_ms)))
                except ServeOverloadError:
                    futs.append((t0, "shed"))
                except Exception:
                    futs.append((t0, "rejected"))
                k += 1
            elapsed = time.perf_counter() - start
            counts = {"completed": 0, "shed": 0, "deadline": 0,
                      "fault": 0, "rejected": 0, "hung": 0}
            lat = []
            last_done = start
            for t0, f in futs:
                if isinstance(f, str):
                    counts[f] += 1
                    continue
                try:
                    f.result(timeout=60.0)
                    counts["completed"] += 1
                    lat.append((f.done_at - t0) * 1000.0)
                    last_done = max(last_done, f.done_at)
                except ServeDeadlineError:
                    counts["deadline"] += 1
                except ServeOverloadError:
                    counts["shed"] += 1
                except Exception:
                    counts["fault" if f.done() else "hung"] += 1
            # honest aggregate-QPS denominator: completions draining
            # AFTER the submission window count only if the window is
            # stretched to cover them — the closed-loop baseline divides
            # by time-to-last-completion, so this must too
            span = max(elapsed, last_done - start)
        if rollout_thread is not None:
            rollout_thread.join(timeout=120.0)

        # chaos over: the fleet must return to full strength
        fleet.supervisor.wait_ready(n, timeout_s=120.0)
        deadline = time.perf_counter() + 30.0
        while time.perf_counter() < deadline and \
                router.health()["state"] != "healthy":
            time.sleep(0.2)
        p50, p99 = _percentiles(lat)
        states = fleet.supervisor.states()
        res.update({
            "offered_qps": round(offered_qps, 1),
            "duration_s": duration,
            "requests": k,
            "elapsed_s": round(elapsed, 3),
            "drain_tail_s": round(span - elapsed, 3),
            "resolved": counts,
            "qps": round(counts["completed"] / span, 2)
            if span else 0.0,
            "p50_ms": None if p50 is None else round(p50, 3),
            "p99_ms": None if p99 is None else round(p99, 3),
            "victim_killed": victim_pid is not None,
            "replica_restarts": sum(d["restarts"]
                                    for d in states.values()),
            "rollout": rollout_result.get(
                "res", {"error": rollout_result.get("error",
                                                    "never ran")}),
            "router_counts": router.health()["counts"],
            "redispatches": router.health()["counts"]["redispatched"],
            "injected": fi.stats(),
            "fleet_health_after": router.health()["state"],
            "p99_bound_ms": args.p99_bound_ms,
        })

        # ---- observability plane (docs/OBSERVABILITY.md §Fleet) ----
        # fleet rollup + fleet-vs-client latency agreement: the router's
        # fleet.request histogram brackets exactly what clients timed
        # over the load window (metrics read BEFORE the SLO burst below
        # adds traffic), so its p50/p99 must tell the same story
        m = router.metrics()
        fq = (m.get("latency_ms") or {}).get("fleet.request", {})
        _, agree = _quantile_agreement(
            {"p50": fq.get("p50", 0.0), "p99": fq.get("p99", 0.0),
             "count": fq.get("count", 0)}, p50, p99,
            abs_ms=(25.0, 75.0), rel=(0.6, 0.8))
        res["fleet_metrics"] = m
        res["fleet_hist_vs_client"] = agree

        # merged fleet trace: one clock-aligned timeline whose request
        # chains must join >=2 processes (router + replica) on a single
        # router-minted trace_id
        if telemetry.tracing():
            merged = fleet.collect_fleet_trace()
            res["fleet_trace"] = _fleet_trace_stats(merged)
            if args.trace_out:
                with open(args.trace_out, "w") as f:
                    json.dump(merged, f)
                res["fleet_trace"]["written"] = args.trace_out

        # seeded fault burst: 100% fleet.dispatch raises exhaust the
        # redispatch budget -> router errors -> slo.burn_rate trips; then
        # clean recovery traffic must CLEAR it once the window rolls
        slo_burst = {"fired": False, "cleared": False, "peak_burn": 0.0}
        with fi.inject("fleet.dispatch", "raise", prob=1.0, seed=13):
            t_burst = time.perf_counter()
            while time.perf_counter() - t_burst < 12.0:
                try:
                    router.infer({"data": payloads[0]}, timeout=20.0)
                except Exception:
                    pass
                s = router.metrics().get("slo")
                if s:
                    slo_burst["peak_burn"] = max(slo_burst["peak_burn"],
                                                 s.get("burn_rate", 0.0))
                    if not s.get("ok", True):
                        slo_burst["fired"] = True
                        break
                time.sleep(0.05)
        t_rec = time.perf_counter()
        while time.perf_counter() - t_rec < 20.0:
            try:
                router.infer({"data": payloads[0]}, timeout=20.0)
            except Exception:
                pass
            s = router.metrics().get("slo")
            if slo_burst["fired"] and s and s.get("ok"):
                slo_burst["cleared"] = True
                break
            time.sleep(0.1)
        res["slo_burst"] = slo_burst
        res["slo_violations"] = router.slo_violations()
    finally:
        fleet.close()
        shutil.rmtree(workdir, ignore_errors=True)

    # ---- paged-KV multiplexed decode parity (the decode-side half of
    # the fleet story: one decode batch, many concurrent sequences)
    res["paged_kv"] = _paged_kv_parity()
    return res


def _fleet_trace_stats(merged):
    """Summary of a merged fleet trace: how many request chains cross
    process boundaries (>=2 pids joined by one trace_id) — the number the
    --check gate asserts is at least 1."""
    by_tid = {}
    span_pids = set()
    events = merged.get("traceEvents", [])
    for ev in events:
        if ev.get("ph") == "X":
            span_pids.add(ev.get("pid"))
        a = ev.get("args") or {}
        tids = []
        if a.get("trace_id"):
            tids.append(a["trace_id"])
        tids.extend(a.get("trace_ids") or [])
        for tid in tids:
            by_tid.setdefault(tid, set()).add(ev.get("pid"))
    cross = sum(1 for pids in by_tid.values() if len(pids) >= 2)
    other = merged.get("otherData") or {}
    return {"events": len(events), "span_pids": len(span_pids),
            "traced_requests": len(by_tid),
            "cross_process_traces": cross,
            "dropped": other.get("dropped", 0)}


def _paged_kv_parity(n_streams=3, n_tokens=6):
    """>=2 concurrent sequences multiplexed through ONE decode batch must
    be token-identical to sequential per-request decode."""
    from mxnet_tpu.models import transformer as _tf
    from mxnet_tpu import context as _ctx
    from mxnet_tpu.serving import KVCacheDecoder, PagedKVDecoder

    cfg = dict(vocab_size=64, num_layers=2, num_heads=2, model_dim=32,
               ffn_dim=64)
    S = 16
    probe = _tf.get_symbol(seq_len=S, **cfg).simple_bind(
        _ctx.current_context(), grad_req="null", data=(1, S),
        softmax_label=(1, S))
    rs = np.random.RandomState(0)
    params = {k: (rs.randn(*a.shape) * 0.1).astype("float32")
              for k, a in probe.arg_dict.items()
              if k not in ("data", "softmax_label")}
    prompts = [rs.randint(1, 64, (2 + i,)).astype("float32")
               for i in range(n_streams)]
    seq_out = []
    for p in prompts:
        dec = KVCacheDecoder(params, max_len=S, prefill_len=8, pos_len=S,
                             batch=1, **cfg)
        seq_out.append(dec.greedy(p[None], n_tokens)[0])
    paged = PagedKVDecoder(params, max_len=S, page_size=4,
                           lanes=n_streams, prefill_len=8, pos_len=S,
                           **cfg)
    pg_out = paged.greedy(prompts, n_tokens)
    identical = all(np.array_equal(a, b)
                    for a, b in zip(seq_out, pg_out))
    return {"streams": n_streams, "tokens_per_stream": n_tokens,
            "token_identical": bool(identical)}


def _check_fleet(res):
    ok = True

    def _fail(msg):
        nonlocal ok
        ok = False
        sys.stderr.write("serve_bench --fleet --check FAILED: %s\n" % msg)

    counts = res["resolved"]
    # zero-lost has two teeth: (a) every issued future resolved within the
    # 60s wait — an unresolved one lands in "hung", the catch-all bucket,
    # so hung==0 IS the lost-request gate; (b) the router's own books must
    # agree with what the clients observed delivered — a router that
    # dropped (or double-delivered) a request can't balance both sides
    if counts["hung"]:
        _fail("%d request(s) HUNG past the 60s resolution wait — lost "
              "to the fleet" % counts["hung"])
    rc = res["router_counts"]
    if rc["completed"] != counts["completed"]:
        _fail("router books claim %d completed but clients observed %d "
              "deliveries — requests lost or double-counted"
              % (rc["completed"], counts["completed"]))
    if not counts["completed"]:
        _fail("no request completed under fleet chaos")
    if not res["victim_killed"]:
        _fail("the chaos plan never killed a replica")
    if res["replica_restarts"] < 1:
        _fail("the supervisor never restarted the killed replica")
    if res["rollout"].get("error") or not res["rollout"].get("applied"):
        _fail("mid-run fleet rollout did not apply: %s" % res["rollout"])
    if not any(k.startswith("fleet.dispatch:")
               for k in res["injected"]):
        _fail("no fleet.dispatch faults were injected: %s"
              % res["injected"])
    if res["fleet_health_after"] != "healthy":
        _fail("fleet did not return to healthy: %r"
              % res["fleet_health_after"])
    base = res["qps_single_replica_closed"]
    if not res["qps"] or res["qps"] <= base:
        _fail("aggregate fleet QPS %.1f did not beat the single-replica "
              "closed-loop baseline %.1f" % (res["qps"] or 0.0, base))
    p99 = res.get("p99_ms")
    if p99 is None or not math.isfinite(p99) or p99 > res["p99_bound_ms"]:
        _fail("p99 of completed requests %r ms outside bound %r ms"
              % (p99, res["p99_bound_ms"]))
    if not res["paged_kv"]["token_identical"]:
        _fail("paged-KV multiplexed decode diverged from sequential "
              "per-request decode: %s" % res["paged_kv"])
    # ---- observability-plane gates (docs/OBSERVABILITY.md §Fleet)
    agree = res.get("fleet_hist_vs_client") or {}
    if not agree.get("agree"):
        _fail("fleet.request histogram p50/p99 disagree with client-side "
              "request percentiles: %s" % agree)
    ft = res.get("fleet_trace")
    if ft is None:
        _fail("no merged fleet trace was collected")
    elif not ft.get("cross_process_traces"):
        _fail("merged fleet trace has no request chain spanning >=2 "
              "processes on one trace_id: %s" % ft)
    burst = res.get("slo_burst") or {}
    if not burst.get("fired"):
        _fail("the seeded fault burst never tripped the SLO burn-rate "
              "gate: %s" % burst)
    if not burst.get("cleared"):
        _fail("the SLO violation did not clear after recovery: %s"
              % burst)
    viol = res.get("slo_violations") or []
    if not any(v.get("kind") == "slo.violation" for v in viol):
        _fail("no structured slo.violation event was recorded: %s" % viol)
    if not any(v.get("kind") == "slo.clear" for v in viol):
        _fail("no structured slo.clear event was recorded: %s" % viol)
    return ok


def _check_chaos(res):
    ok = True

    def _fail(msg):
        nonlocal ok
        ok = False
        sys.stderr.write("serve_bench --chaos --check FAILED: %s\n" % msg)

    counts = res["resolved"]
    if counts["hung"]:
        _fail("%d request(s) HUNG (future unresolved after 60s)"
              % counts["hung"])
    terminal = sum(counts.values())
    if terminal != res["requests"]:
        _fail("resolved %d of %d offered requests" % (terminal,
                                                      res["requests"]))
    if not counts["completed"]:
        _fail("no request completed under chaos")
    if res["retraces_post_warmup"]:
        _fail("post-warmup retraces: %d" % res["retraces_post_warmup"])
    if res["compiles_post_warmup"]:
        _fail("post-warmup compiles: %d" % res["compiles_post_warmup"])
    if not res["reload_applied"]:
        _fail("mid-run reload() did not apply")
    if res["health_after"].get("state") != "healthy":
        _fail("engine did not return to healthy after injection stopped: "
              "%s" % res["health_after"])
    if not any(k.startswith("serving.dispatch:") for k in res["injected"]):
        _fail("no faults were actually injected: %s" % res["injected"])
    if not res["dispatch_retries"]:
        _fail("the dispatch retry path never fired under injected faults")
    p99 = res.get("p99_ms")
    if p99 is None or not math.isfinite(p99) or p99 > res["p99_bound_ms"]:
        _fail("p99 of completed requests %r ms outside bound %r ms"
              % (p99, res["p99_bound_ms"]))
    return ok


def _check_prefix_spec(res):
    ok = True

    def _fail(msg):
        nonlocal ok
        ok = False
        sys.stderr.write("serve_bench --workload zipf-prefix --check "
                         "FAILED: %s\n" % msg)

    pre = res["prefix"]
    if pre["hit_rate"] <= 0.5:
        _fail("prefix chunk hit rate %.3f not > 0.5 under the zipf "
              "workload (%d hits / %d misses)"
              % (pre["hit_rate"], pre["chunk_hits"],
                 pre["chunk_misses"]))
    if pre["tokens_saved"] <= 0 or not pre["prefill_flops_saved"]:
        _fail("no prefill work saved: tokens_saved=%r flops_saved=%r"
              % (pre["tokens_saved"], pre["prefill_flops_saved"]))
    if not pre["bitwise_cached_vs_cold"]:
        _fail("cached admit logits are NOT bitwise identical to the "
              "cold admit of the same prompt")
    sp = res["spec"]
    if not sp["parity_token_identical"]:
        _fail("speculative greedy diverged from non-speculative greedy "
              "(gamma=%d draft_layers=%d)" % (sp["gamma"],
                                              sp["draft_layers"]))
    if sp["accepted_rate"] <= 0.0:
        _fail("accepted-draft rate %.3f not > 0 (%d proposed)"
              % (sp["accepted_rate"], sp["proposed_tokens"]))
    if sp["p50_ms_per_token"] > sp["baseline_p50_ms_per_token"]:
        _fail("speculative p50 %.4f ms/token not <= plain-greedy "
              "baseline %.4f ms/token"
              % (sp["p50_ms_per_token"],
                 sp["baseline_p50_ms_per_token"]))
    if res["retraces_post_warmup"]:
        _fail("post-warmup retraces: %d" % res["retraces_post_warmup"])
    if res["compiles_post_warmup"]:
        _fail("post-warmup compiles: %d" % res["compiles_post_warmup"])
    return ok


def _check(res, trace_families):
    ok = True

    def _fail(msg):
        nonlocal ok
        ok = False
        sys.stderr.write("serve_bench --check FAILED: %s\n" % msg)

    if not res.get("qps"):
        _fail("qps not > 0: %r" % res.get("qps"))
    p99 = res.get("p99_ms")
    if p99 is None or not math.isfinite(p99):
        _fail("p99 not finite: %r" % p99)
    if res.get("retraces_post_warmup"):
        _fail("post-warmup retraces: %d" % res["retraces_post_warmup"])
    if res.get("compiles_post_warmup"):
        _fail("post-warmup compiles: %d" % res["compiles_post_warmup"])
    need = {"serving.dispatch"} if res["mode"] == "engine" \
        else {"serving.decode_step", "serving.prefill"}
    ms = res.get("megastep")
    if ms is not None:
        need.add("serving.decode_megastep")
    missing = need - trace_families
    if missing:
        _fail("missing serving.* trace families: %s" % sorted(missing))
    if res["mode"] == "kv_decode" and not res.get("host_gap_per_token"):
        _fail("host_gap_per_token missing or zero — the dispatch.host_gap "
              "timer never ticked on the decode path")
    if ms is not None:
        if not ms.get("parity_token_identical"):
            _fail("megastep K=%d greedy diverged from single-step decode"
                  % ms["k"])
        base = res.get("host_gap_per_token") or 0.0
        if not base or ms["host_gap_per_token"] > 0.5 * base:
            _fail("megastep host_gap_per_token %.6f ms not <= 0.5x the "
                  "K=1 baseline %.6f ms"
                  % (ms["host_gap_per_token"], base))
    if res.get("batching_speedup") is not None \
            and res["batching_speedup"] < 2.0:
        _fail("continuous batching speedup %.2fx < 2x over batch-size-1"
              % res["batching_speedup"])
    if res["mode"] == "engine":
        eh = res.get("engine_hist") or {}
        if not eh.get("agree"):
            _fail("engine-side serving.request histogram p50/p99 disagree "
                  "with client-side request percentiles: %s" % eh)
    return ok


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--model", default="mlp",
                    choices=sorted(ITEM_SHAPES) + ["transformer-decode"])
    ap.add_argument("--qps", type=float, default=200.0,
                    help="offered open-loop rate (decode: steps*duration)")
    ap.add_argument("--duration", type=float, default=3.0)
    ap.add_argument("--rows", type=int, default=1,
                    help="rows per request (decode: streams)")
    ap.add_argument("--buckets", default="1,2,4,8")
    ap.add_argument("--max-delay-ms", type=float, default=5.0)
    ap.add_argument("--cache-dir", default=None,
                    help="persist executables/manifests here "
                         "(default: MXNET_SERVE_CACHE_DIR)")
    ap.add_argument("--compare-batch1", action="store_true",
                    help="also measure saturation QPS vs a batch-1 engine")
    ap.add_argument("--megastep-k", type=int, default=8,
                    help="transformer-decode: K tokens per dispatch for "
                         "the megastep comparison leg (MXNET_DECODE_"
                         "MEGASTEP_K); 0 or 1 disables the leg")
    ap.add_argument("--quant", default=None, choices=[None, "off", "bf16",
                                                      "int8"],
                    help="sets MXNET_SERVE_QUANT for the run")
    ap.add_argument("--workload", default="uniform",
                    choices=["uniform", "zipf-prefix"],
                    help="zipf-prefix: shared-prefix KV-cache + "
                         "speculative-decoding leg (transformer decode; "
                         "docs/SERVING.md §Prefix cache & speculative "
                         "decoding)")
    ap.add_argument("--spec-gamma", type=int, default=4,
                    help="zipf-prefix: draft tokens per speculative "
                         "round (MXNET_SPEC_GAMMA)")
    ap.add_argument("--spec-draft-layers", type=int, default=0,
                    help="zipf-prefix: layers truncated from the target "
                         "checkpoint for the draft model; 0 = self-draft "
                         "(draft == target, acceptance 1.0 — the "
                         "amortization smoke)")
    ap.add_argument("--fleet", action="store_true",
                    help="fleet smoke (docs/SERVING.md §Fleet): N replica "
                         "processes behind the router under open-loop "
                         "load + chaos (kill-one-replica, injected "
                         "dispatch faults, one mid-run fleet rollout) "
                         "plus the paged-KV parity check")
    ap.add_argument("--fleet-replicas", type=int, default=4)
    ap.add_argument("--chaos", action="store_true",
                    help="serving resilience smoke: open-loop load with "
                         "injected dispatch raises/delays + one mid-run "
                         "hitless reload (docs/RESILIENCE.md)")
    ap.add_argument("--chaos-fail-prob", type=float, default=0.1,
                    help="per-dispatch injected-raise probability")
    ap.add_argument("--chaos-delay-prob", type=float, default=0.2,
                    help="per-dispatch injected-delay probability")
    ap.add_argument("--chaos-delay-ms", type=float, default=15.0)
    ap.add_argument("--chaos-deadline-ms", type=float, default=300.0,
                    help="per-request deadline under chaos")
    ap.add_argument("--p99-bound-ms", type=float, default=None,
                    help="chaos/fleet gate: p99 of COMPLETED requests "
                         "must stay under this (default 1500; fleet mode "
                         "4000 — its deadline/shed knobs derive from it)")
    ap.add_argument("--trace-out", default=None,
                    help="--fleet: write the merged, clock-aligned fleet "
                         "chrome trace here (forces trace mode; view "
                         "with mxtrace or chrome://tracing)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--check", action="store_true",
                    help="CI gate: assert qps>0, finite p99, zero "
                         "post-warmup retraces/compiles, serving.* spans "
                         "(with --chaos: the resilience gate)")
    args = ap.parse_args(argv)

    if args.quant:
        os.environ["MXNET_SERVE_QUANT"] = args.quant
    from mxnet_tpu import telemetry

    telemetry.set_mode("trace" if (args.check or args.trace_out)
                       else "counters")
    if args.p99_bound_ms is None:
        args.p99_bound_ms = 4000.0 if args.fleet else 1500.0
    if args.fleet:
        if args.model == "transformer-decode":
            ap.error("--fleet drives the bucketed engine; pick an "
                     "ITEM_SHAPES model")
        res = bench_fleet(args)
    elif args.chaos:
        if args.model == "transformer-decode":
            ap.error("--chaos drives the bucketed engine; pick an "
                     "ITEM_SHAPES model")
        res = bench_chaos(args)
    elif args.workload == "zipf-prefix":
        res = bench_prefix_spec(args)
    elif args.model == "transformer-decode":
        res = bench_decode(args)
    else:
        res = bench_engine(args)
    res["quant"] = args.quant or os.environ.get("MXNET_SERVE_QUANT", "off")

    ok = True
    if args.check:
        if args.fleet:
            ok = _check_fleet(res)
        elif args.chaos:
            ok = _check_chaos(res)
        elif args.workload == "zipf-prefix":
            ok = _check_prefix_spec(res)
        else:
            families = {e[0] for e in telemetry.drain_events()}
            ok = _check(res, families)
        res["check"] = "ok" if ok else "FAILED"
    if telemetry.witnessing():
        # MXNET_CONCLINT=witness run: the bench doubles as the GL805 race
        # gate — any witnessed lock-order inversion or dispatch-seam hold
        # fails the run (tools/ci_check.sh chaos smoke)
        from mxnet_tpu.analysis.concurrency_lint import lint_lock_witness

        witness_diags = lint_lock_witness(telemetry.witness_report())
        res["gl805"] = [d.message for d in witness_diags]
        if witness_diags:
            ok = False
            for d in witness_diags:
                sys.stderr.write("serve_bench witness GL805: %s\n"
                                 % d.message)
    if args.json or args.check:
        print(json.dumps(res))
    else:
        for k, v in res.items():
            print("%-26s %s" % (k, v))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
