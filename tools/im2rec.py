#!/usr/bin/env python
"""Pack an image directory into a RecordIO file.

Counterpart of the reference's tools/im2rec.py (and the C++ tools/im2rec.cc):
two modes, matching the reference CLI —

  * ``--list``: walk an image root, write a ``.lst`` index
    (``idx \\t label \\t relpath`` per line, labels from subdirectory order);
  * pack: read a ``.lst``, encode/resize each image, write ``prefix.rec`` +
    ``prefix.idx`` via MXIndexedRecordIO so ImageRecordIter can seek.

Examples:
    python tools/im2rec.py --list data/train data/imgs
    python tools/im2rec.py --resize 256 --quality 90 data/train data/imgs
"""
import argparse
import os
import random
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))
from mxnet_tpu import recordio  # noqa: E402

EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def make_list(args):
    """Walk image root → .lst lines (reference: im2rec.py make_list)."""
    entries = []
    label_names = sorted(
        d for d in os.listdir(args.root) if os.path.isdir(os.path.join(args.root, d))
    )
    if label_names:
        label_of = {name: i for i, name in enumerate(label_names)}
        for name in label_names:
            subdir = os.path.join(args.root, name)
            for fn in sorted(os.listdir(subdir)):
                if fn.lower().endswith(EXTS):
                    entries.append((label_of[name], os.path.join(name, fn)))
    else:  # flat directory: label 0
        for fn in sorted(os.listdir(args.root)):
            if fn.lower().endswith(EXTS):
                entries.append((0, fn))
    if args.shuffle:
        random.Random(args.seed).shuffle(entries)
    lst_path = args.prefix + ".lst"
    with open(lst_path, "w") as f:
        for idx, (label, rel) in enumerate(entries):
            f.write("%d\t%f\t%s\n" % (idx, float(label), rel))
    print("wrote %d entries to %s" % (len(entries), lst_path))


def read_list(lst_path):
    with open(lst_path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            idx = int(parts[0])
            labels = [float(x) for x in parts[1:-1]]
            yield idx, labels, parts[-1]


def _load_resized(path, args):
    from PIL import Image

    from mxnet_tpu import image as mximg

    img = np.asarray(Image.open(path).convert("RGB"))
    if args.resize:
        img = mximg.resize_short(img, args.resize)
    if args.center_crop:
        s = min(img.shape[:2])
        img = mximg.center_crop(img, (s, s))[0]
    return img[:, :, ::-1]  # HWC BGR, the rec disk convention


def pack_records(args):
    rec = recordio.MXIndexedRecordIO(args.prefix + ".idx", args.prefix + ".rec", "w")
    count = 0
    for idx, labels, rel in read_list(args.prefix + ".lst"):
        path = os.path.join(args.root, rel)
        try:
            img = _load_resized(path, args)
        except Exception as e:  # unreadable image: skip, like the reference
            print("skipping %s: %s" % (path, e), file=sys.stderr)
            continue
        label = labels[0] if len(labels) == 1 else np.array(labels, np.float32)
        header = recordio.IRHeader(0, label, idx, 0)
        rec.write_idx(idx, recordio.pack_img(header, img, quality=args.quality,
                                             img_fmt=args.encoding))
        count += 1
        if count % 1000 == 0:
            print("packed %d images" % count)
    rec.close()
    print("wrote %d records to %s.rec (+.idx)" % (count, args.prefix))


def main():
    parser = argparse.ArgumentParser(
        description="Create an image list / RecordIO pack",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("prefix", help="prefix of the .lst/.rec/.idx files")
    parser.add_argument("root", help="image root directory")
    parser.add_argument("--list", action="store_true",
                        help="create a .lst file instead of packing records")
    parser.add_argument("--shuffle", type=int, default=1)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--resize", type=int, default=0,
                        help="resize the shorter edge to this size")
    parser.add_argument("--center-crop", action="store_true")
    parser.add_argument("--quality", type=int, default=95)
    parser.add_argument("--encoding", type=str, default=".jpg",
                        choices=[".jpg", ".png"])
    args = parser.parse_args()

    if args.list:
        make_list(args)
    else:
        if not os.path.exists(args.prefix + ".lst"):
            make_list(args)
        pack_records(args)


if __name__ == "__main__":
    main()
