#!/usr/bin/env bash
# CI entrypoint: static analysis first, then the fused conv+BN machinery
# smoke, then the telemetry trace smoke, then the 8-process kvstore
# bucket/overlap smoke, then the serving smoke, then the elastic
# fault-tolerance chaos smoke, then the tier-1 test suite.
#
# Step 1 dogfoods the graphlint subsystem on every bundled model (the
# acceptance gate: every model must lint with zero error-severity
# diagnostics), then runs the graph-rewrite gate: the zoo sweep under
# MXNET_GRAPHREWRITE=verify (zero GL601/602/604, transformer node-count
# reduction + strictly more norm_residual fusion sites), the 3-model
# raw-vs-rewritten bit-parity subcheck (tests/nightly/rewrite_parity.py),
# and the GL7xx dispatch-discipline gates: the zoo mesh sweep must carry
# zero GL7xx findings while the `graphlint --dispatch` source scan must
# keep flagging the known kv_decode host-sync sites — present AND waived
# since the lax.scan decode megastep became the default K-amortized
# shape, leaving only acknowledged K=1 tails — with everything outside
# kv_decode waived. Step 2 lints the sources with
# ruff when installed (pinned rule set: ruff.toml) and otherwise with the
# dependency-free tools/src_lint.py fallback — always-on either way; the
# every-source-compiles floor is additionally enforced by
# tests/test_graphlint.py::test_package_sources_compile.
# Step 3 exercises the fused conv+BN autotune harness end-to-end in Pallas
# interpret mode (timing scaffolding, fwd+bwd parity, WINS-table emission +
# loadability — docs/PERF.md §6b) plus the backward gradient-parity sweep's
# non-slow subset. Step 4 runs a tiny fit loop under MXNET_TELEMETRY=trace,
# dumps the chrome trace, and gates it with tools/mxtrace --check
# (docs/OBSERVABILITY.md — the telemetry dump is a machine contract, so CI
# smokes it end to end). Step 5 runs the 8-process CPU kvstore smoke
# (tests/nightly/dist_kvstore_overlap.py): bucket-plan overlap counters
# during a Module.fit, sharded-vs-replicated weight parity, and the
# bucketed allreduce bandwidth floor (docs/PERF.md §11).
# Step 6 runs the 2-process recommender sparse-kvstore smoke
# (tests/nightly/dist_sparse_kvstore.py, docs/SPARSE.md): a sparse-push fit
# must be weight-parity (atol 1e-6) with a dense-push control while moving
# strictly fewer wire bytes (kvstore.bytes.sparse < the control's
# allreduce bytes), plus the budget-armed autoplan gate: the 8-device plan
# for the recommender must shard an embedding table over the model axis.
# Step 7 runs the serving engine smoke (tools/serve_bench.py --check):
# QPS/p99 under a tiny open-loop load with zero post-warmup retraces, for
# both the bucketed engine and the transformer KV-cache decode path
# including the K=8 decode-megastep leg (token-identical parity +
# host-gap-per-token >=2x drop, docs/SERVING.md §Megasteps), the
# shared-prefix cache + speculative-decoding smoke (--workload
# zipf-prefix: hit rate, bitwise cached-vs-cold admits, spec-vs-greedy
# token parity and p50), plus the serving CHAOS smoke (--chaos): deterministic
# fault injection on the dispatch path + a mid-run hitless weight reload,
# gated on zero hung futures, zero retraces, and recovery to `healthy`
# (docs/RESILIENCE.md).
# Step 8 runs the serving FLEET chaos smoke (serve_bench --fleet,
# docs/SERVING.md §Fleet): open-loop load through the replica router over
# 4 replica processes with injected dispatch faults, a mid-run replica
# SIGKILL (supervised restart), and a mid-run fleet-wide hitless rollout —
# gated on zero hung/lost requests, aggregate QPS above the single-replica
# closed-loop baseline, recovery to healthy, and paged-KV multiplexed
# decode parity.
# Step 9 runs the elastic fault-tolerance chaos smoke
# (tests/nightly/dist_elastic_chaos.py --orchestrate): an 8-process
# Module.fit in sharded-update mode with periodic async checkpoints, one
# worker killed mid-run — the survivors must re-form to 7, reseed from the
# sharded checkpoint, resume, and reach weight parity with an uninterrupted
# 7-process control run; it also asserts checkpoint.inflight was observed
# > 0 mid-fit, i.e. the async write really overlapped the step
# (docs/FAULT_TOLERANCE.md).
# Step 10 is the repo's tier-1 pytest command (ROADMAP.md).
set -uo pipefail
cd "$(dirname "$0")/.."

echo "== [1/10] graphlint: all bundled models (plain + sharding-plan sweep) =="
JAX_PLATFORMS=cpu python tools/graphlint --all-models --min-severity warning \
    || { echo "graphlint FAILED"; exit 1; }
# the same zoo under an abstract dp=8,model=2 mesh: the GL4xx sharding-plan
# lint and GL5xx memory planner must run the whole sweep clean of errors AND
# produce a finite peak-HBM estimate for every model (docs/static_analysis.md)
MESH_SWEEP="$(mktemp /tmp/graphlint_mesh_ci.XXXXXX.json)"
JAX_PLATFORMS=cpu python tools/graphlint --all-models --mesh dp=8,model=2 \
    --format json > "$MESH_SWEEP" \
    || { echo "graphlint mesh sweep FAILED"; rm -f "$MESH_SWEEP"; exit 1; }
python - "$MESH_SWEEP" <<'PYEOF' || { echo "mesh sweep peak-HBM gate FAILED"; rm -f "$MESH_SWEEP"; exit 1; }
import json, math, sys
payload = json.load(open(sys.argv[1]))
assert payload, "empty mesh sweep"
bad = []
for entry in payload:
    plan = entry.get("memory_plan")
    peak = plan and plan["per_device"]["peak"]
    if not peak or not math.isfinite(peak) or peak <= 0:
        bad.append(entry["target"])
assert not bad, "models without a finite peak-HBM estimate: %s" % bad
# GL7xx dispatch-discipline zoo gate (docs/static_analysis.md §GL7xx):
# every bundled model's graph must lint clean of dispatch findings — the
# known host-sync sites live in serving/kv_decode.py, not in any model
gl7 = sorted({(e["target"], d["code"]) for e in payload
              for d in e["diagnostics"] if d["code"].startswith("GL7")})
assert not gl7, "zoo models with GL7xx dispatch findings: %s" % gl7
peaks = [e["memory_plan"]["per_device"]["peak"] / 2**30 for e in payload]
print("mesh sweep OK: %d models, peak-HBM %.3f..%.3f GiB/device, "
      "zero GL7xx" % (len(payload), min(peaks), max(peaks)))
PYEOF
rm -f "$MESH_SWEEP"
# auto-parallel planner sweep (docs/PARALLEL_PLANNER.md): every zoo model at
# 8 abstract devices must receive a budget-feasible ParallelPlan (or an
# explicit structured infeasibility reason — a planner CRASH is the failure
# mode this gates); the transformer's planner-chosen plan must additionally
# predict no more comm bytes than the naive all-dp plan
AUTOPLAN_SWEEP="$(mktemp /tmp/graphlint_autoplan_ci.XXXXXX.json)"
JAX_PLATFORMS=cpu python tools/graphlint --autoplan --all-models \
    --mesh-devices 8 --format json > "$AUTOPLAN_SWEEP" \
    || { echo "graphlint autoplan sweep FAILED"; rm -f "$AUTOPLAN_SWEEP"; exit 1; }
python - "$AUTOPLAN_SWEEP" <<'PYEOF' || { echo "autoplan sweep gate FAILED"; rm -f "$AUTOPLAN_SWEEP"; exit 1; }
import json, sys
payload = json.load(open(sys.argv[1]))
assert payload, "empty autoplan sweep"
bad, n_pipe = [], 0
for entry in payload:
    plan = entry.get("autoplan")
    if plan is None:
        bad.append("%s: planner error: %s"
                   % (entry["target"], entry.get("plan_error")))
    elif not plan["feasible"] and not plan.get("reason"):
        bad.append("%s: infeasible with NO structured reason"
                   % entry["target"])
    elif plan["pipeline_stages"] > 1:
        n_pipe += 1
assert not bad, "autoplan gate: %s" % "; ".join(bad)
tf = next(e["autoplan"] for e in payload if e["target"] == "transformer")
chosen, naive = tf["predicted"]["comm_bytes"], tf["naive"]["comm_bytes"]
assert chosen <= naive, \
    "transformer: planner comm %d B > naive all-dp %d B" % (chosen, naive)
print("autoplan sweep OK: %d models planned (%d pipelined); transformer "
      "comm %.2f MiB vs naive %.2f MiB"
      % (len(payload), n_pipe, chosen / 2**20, naive / 2**20))
PYEOF
rm -f "$AUTOPLAN_SWEEP"
# graph-rewrite gate (docs/static_analysis.md §GL6xx): the whole zoo must
# rewrite + verify under MXNET_GRAPHREWRITE=verify with ZERO GL601/602/604,
# and the transformer must show real gains — nodes merged/removed > 0 AND
# strictly more norm_residual fusion sites after canonicalization (the
# sloppy-frontend LN contract, models/transformer.py). The JSON dump is
# the committed CI record of the per-model rewrite plans.
REWRITE_SWEEP="$(mktemp /tmp/graphlint_rewrite_ci.XXXXXX.json)"
JAX_PLATFORMS=cpu MXNET_GRAPHREWRITE=verify \
python tools/graphlint --all-models --rewrite --format json \
    > "$REWRITE_SWEEP" \
    || { echo "graphlint rewrite sweep FAILED"; rm -f "$REWRITE_SWEEP"; exit 1; }
python - "$REWRITE_SWEEP" <<'PYEOF' || { echo "rewrite sweep gate FAILED"; rm -f "$REWRITE_SWEEP"; exit 1; }
import json, sys
payload = json.load(open(sys.argv[1]))
assert payload, "empty rewrite sweep"
bad = []
for entry in payload:
    if "rewrite" not in entry:
        bad.append("%s: %s" % (entry["target"],
                               entry.get("rewrite_error")
                               or entry.get("load_error")))
        continue
    codes = [d["code"] for d in entry["verify"]["diagnostics"]
             if d["code"] in ("GL601", "GL602", "GL604")]
    if codes:
        bad.append("%s: %s" % (entry["target"], codes))
assert not bad, "rewrite verify errors: %s" % "; ".join(bad)
tf = next(e for e in payload if e["target"] == "transformer")
c = tf["rewrite"]["counts"]
assert c["merged"] + c["removed"] + c["folded"] > 0, c
before = tf["fusion_sites_before"].get("norm_residual", 0)
after = tf["fusion_sites_after"].get("norm_residual", 0)
assert after > before, "norm_residual sites %d -> %d" % (before, after)
print("rewrite sweep OK: %d models verified; transformer %d->%d nodes, "
      "norm_residual sites %d->%d"
      % (len(payload), tf["rewrite"]["nodes_before"],
         tf["rewrite"]["nodes_after"], before, after))
PYEOF
rm -f "$REWRITE_SWEEP"
# bit-parity subcheck on 3 representative models: forward must be BITWISE
# identical raw-vs-rewritten, backward bitwise (atol 1e-6 where CSE's
# cotangent reassociation applies) — docs/static_analysis.md §GL6xx
JAX_PLATFORMS=cpu python tests/nightly/rewrite_parity.py \
    || { echo "rewrite bit-parity gate FAILED"; exit 1; }
# GL7xx dispatch-discipline source gate (docs/static_analysis.md §GL7xx):
# the scan over the serving surface must keep FINDING the known kv_decode
# host-sync sites (GL701 in both greedy decode loops — these are now the
# acknowledged K=1 TAILS of the megastep path and carry waivers naming
# the lax.scan megastep as the K-amortized shape, so every kv_decode
# GL701 must be BOTH present and waived: a refactor that silently stops
# detecting them fails here, and so does a new unwaived host sync),
# while every serve_bench/bench finding stays waived.  Exit 1 (live
# findings) is expected — only exit 2 (unreadable target) hard-fails the
# scan itself.
DISPATCH_SCAN="$(mktemp /tmp/graphlint_dispatch_ci.XXXXXX.json)"
JAX_PLATFORMS=cpu python tools/graphlint --dispatch --format json \
    > "$DISPATCH_SCAN"
DISPATCH_RC=$?
if [ "$DISPATCH_RC" -ge 2 ]; then
    echo "graphlint --dispatch FAILED (exit $DISPATCH_RC)"
    rm -f "$DISPATCH_SCAN"; exit 1
fi
python - "$DISPATCH_SCAN" <<'PYEOF' || { echo "dispatch source gate FAILED"; rm -f "$DISPATCH_SCAN"; exit 1; }
import json, sys
payload = json.load(open(sys.argv[1]))
sites = payload["sites"]
kv = [s for s in sites if s["file"].endswith("serving/kv_decode.py")]
gl701 = {s["function"] for s in kv if s["code"] == "GL701"}
need = {"KVCacheDecoder.greedy", "PagedKVDecoder.greedy"}
assert need <= gl701, \
    "kv_decode GL701 anchors missing: %s (got %s)" % (need - gl701, gl701)
# re-anchored for the megastep era: the megastep lax.scan is the default
# scan-clean decode shape, so every REMAINING kv_decode host sync must be
# a deliberately waived K=1 tail — an unwaived GL701 here is a regression
unwaived = [(s["function"], s["line"]) for s in kv
            if s["code"] == "GL701" and not s["waived"]]
assert not unwaived, \
    "unwaived kv_decode GL701 host syncs (megastep tails must carry " \
    "waivers): %s" % unwaived
bad = [s for s in kv
       if s["line"] <= 0 or (s["code"] == "GL701" and not s["provenance"])]
assert not bad, "kv_decode sites without file:line provenance: %s" % bad
stray = [(s["code"], "%s:%d" % (s["file"], s["line"])) for s in sites
         if s not in kv and not s["waived"]]
assert not stray, "unwaived dispatch findings outside kv_decode: %s" % stray
n_waived = sum(1 for s in sites if s["waived"])
print("dispatch source gate OK: %d sites (%d waived); kv_decode anchors %s"
      % (len(sites), n_waived, sorted(gl701)))
PYEOF
rm -f "$DISPATCH_SCAN"

# GL8xx concurrency repo gate (docs/static_analysis.md §GL8xx): the static
# lint over the threaded/distributed surface must be clean — every finding
# fixed or carrying a '# graphlint: waive GL80x -- reason'. Exit 1 means an
# unwaived finding (a new rank-divergent collective, unguarded shared
# attribute, lock-order cycle, or blocking-while-locked site) slipped in.
JAX_PLATFORMS=cpu python tools/graphlint --concurrency --format json \
    > /dev/null \
    || { echo "graphlint --concurrency FAILED (unwaived GL8xx)"; exit 1; }
echo "concurrency source gate OK (zero unwaived GL8xx)"

echo "== [2/10] source lint (pinned ruff, src_lint.py fallback — always on) =="
# the rule set is pinned in ruff.toml; when ruff is absent (the CI image
# ships no third-party linters and must not pip install) the
# dependency-free tools/src_lint.py enforces the same codes, so this step
# GATES unconditionally — there is no skip branch any more
if command -v ruff >/dev/null 2>&1; then
    ruff check mxnet_tpu/ tools/ bench.py || { echo "ruff FAILED"; exit 1; }
else
    python tools/src_lint.py mxnet_tpu tools tools/graphlint tools/mxtrace \
        bench.py || { echo "src_lint fallback FAILED"; exit 1; }
fi

echo "== [3/10] fused conv+BN: interpret-mode autotune smoke + bwd parity subset =="
FUSED_TABLE="$(mktemp /tmp/fused_conv_bn_table_ci.XXXXXX.py)"
JAX_PLATFORMS=cpu python tools/fused_stats_bench.py --interpret --emit-table \
    --table-out "$FUSED_TABLE" \
    || { echo "fused_stats_bench smoke FAILED"; rm -f "$FUSED_TABLE"; exit 1; }
python - "$FUSED_TABLE" <<'PYEOF' || { echo "emitted WINS table invalid"; rm -f "$FUSED_TABLE"; exit 1; }
import importlib.util, sys
spec = importlib.util.spec_from_file_location("t", sys.argv[1])
m = importlib.util.module_from_spec(spec); spec.loader.exec_module(m)
assert m.DEVICE, "DEVICE not stamped"
assert m.WINS, "WINS table empty on the interpret backend"
assert any(k[-1].endswith(":bwd") for k in m.WINS), "no backward entries"
print("emitted table OK: DEVICE=%r, %d entries" % (m.DEVICE, len(m.WINS)))
PYEOF
rm -f "$FUSED_TABLE"
# the subset also runs inside step 4's full sweep (~18 s overlap) — kept
# here deliberately as a fail-fast signal before the 6-minute tier-1
JAX_PLATFORMS=cpu python -m pytest tests/test_pallas_conv_bn_bwd.py -q \
    -m 'not slow' -p no:cacheprovider \
    || { echo "bwd parity subset FAILED"; exit 1; }
# pattern-engine schedule-cache smoke (docs/PERF.md §13/§15): tune ONE
# matmul+bias+act site — large enough that the (bm, bn) schedule fan-out
# has >1 distinct effective tiling — into a temp dir, then re-run the SAME
# fit against the warmed cache. Gate: the cold run tunes exactly once AND
# searches ≥1 schedule variant (the persisted record carries
# schedules_searched ≥ 1); the warm run is all cache hits with ZERO
# re-tunes and ZERO post-warmup retraces. This is the measure-and-cache
# contract: tune once per device kind, ever — now per SCHEDULE.
TUNE_DIR="$(mktemp -d /tmp/fusion_tune_ci.XXXXXX)"
for run in 1 2; do
JAX_PLATFORMS=cpu MXNET_DEFAULT_CONTEXT=cpu MXNET_TELEMETRY=counters \
MXNET_FUSION_TUNE_DIR="$TUNE_DIR" MXNET_FUSED_PATTERNS=matmul_bias_act \
MXNET_FUSION_TUNE_ITERS=2 \
python - "$run" <<'PYEOF' || { echo "schedule-cache smoke FAILED (run $run)"; rm -rf "$TUNE_DIR"; exit 1; }
import json, sys
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import fusion_tune, telemetry

run = int(sys.argv[1])
x = mx.sym.Variable("data")
h = mx.sym.FullyConnected(x, num_hidden=256, name="fc1")
h = mx.sym.Activation(h, act_type="relu", name="act1")
net = mx.sym.SoftmaxOutput(
    mx.sym.FullyConnected(h, num_hidden=4, name="fc2"), name="softmax")
rs = np.random.RandomState(0)
ex = net.simple_bind(mx.cpu(), data=(256, 32), softmax_label=(256,),
                     grad_req="write")
for name, arr in zip(net.list_arguments(), ex.arg_arrays):
    arr[:] = (rs.randint(0, 4, arr.shape) if "label" in name
              else rs.uniform(-0.5, 0.5, arr.shape)).astype("f")
ex.forward(is_train=True)
ex.backward()
# a second execution through the same executor: any retrace here would
# break the warm-run zero-retrace contract
ex.forward(is_train=True)
ex.backward()
tunes = telemetry.counter("fusion.tune").value
hits = telemetry.counter("fusion.tune_cache_hit").value
retraces = telemetry.counter("executor.retrace").value
sched = 0
payload = json.load(open(fusion_tune.cache_path()))
assert payload["version"] == 2, payload.get("version")
for rec in payload["entries"].values():
    sched = max(sched, rec.get("schedules_searched", 0))
if run == 1:
    assert tunes == 1, "cold run must tune exactly once, got %d" % tunes
    assert sched >= 1, "cold run must search >=1 schedule variant"
else:
    assert tunes == 0, "warm run must NOT re-tune, got %d" % tunes
    assert hits >= 1, "warm run must serve the verdict from the cache"
assert retraces == 0, "post-warmup retraces: %d" % retraces
print("schedule-cache smoke run %d OK: tunes=%d cache_hits=%d "
      "schedules_searched=%d retraces=%d" % (run, tunes, hits, sched,
                                             retraces))
PYEOF
done
rm -rf "$TUNE_DIR"

echo "== [4/10] telemetry: trace-on fit smoke + mxtrace schema gate =="
TRACE_DIR="$(mktemp -d /tmp/mxtrace_ci.XXXXXX)"
JAX_PLATFORMS=cpu MXNET_DEFAULT_CONTEXT=cpu MXNET_TELEMETRY=trace \
python - "$TRACE_DIR" <<'PYEOF' || { echo "telemetry fit smoke FAILED"; rm -rf "$TRACE_DIR"; exit 1; }
import json, sys, os
import numpy as np
import mxnet_tpu as mx

tmp = sys.argv[1]
sym = mx.sym.Variable("data")
sym = mx.sym.Convolution(sym, kernel=(3, 3), pad=(1, 1), num_filter=8,
                         no_bias=True, name="conv1")
sym = mx.sym.BatchNorm(sym, name="bn1")
sym = mx.sym.Activation(sym, act_type="relu")
sym = mx.sym.Flatten(sym)
sym = mx.sym.FullyConnected(sym, num_hidden=4, name="fc")
sym = mx.sym.SoftmaxOutput(sym, name="softmax")
rs = np.random.RandomState(0)
it = mx.io.NDArrayIter(rs.rand(12, 3, 8, 8).astype("float32"),
                       rs.randint(0, 4, (12,)).astype("float32"),
                       batch_size=4)
mx.profiler.profiler_set_config(filename=os.path.join(tmp, "profile.json"))
mx.profiler.profiler_set_state("run")
mod = mx.mod.Module(sym, context=mx.cpu())
mod.fit(it, num_epoch=1, kvstore=mx.kv.create("local"),
        epoch_end_callback=mx.callback.do_checkpoint(os.path.join(tmp, "ck")))
mx.nd.waitall()
path = mx.profiler.dump_profile()
trace = json.load(open(path))
cats = {e.get("cat") for e in trace["traceEvents"] if e.get("ph") == "X"}
need = {"engine", "executor", "fusion", "kvstore", "io"}
assert need <= cats, "missing span families: %s" % (need - cats)
c = trace["otherData"]["counters"]
assert c.get("executor.compile", 0) >= 1 and c.get("executor.cache_hit", 0) >= 1, c
assert len(trace["otherData"]["steps"]) == 3
print("telemetry fit smoke OK: %s (%d events)" % (path, len(trace["traceEvents"])))
PYEOF
python tools/mxtrace "$TRACE_DIR/profile.json" --check \
    || { echo "mxtrace --check FAILED"; rm -rf "$TRACE_DIR"; exit 1; }
rm -rf "$TRACE_DIR"

echo "== [5/10] kvstore: 8-process bucket/overlap smoke (docs/PERF.md §11) =="
# functional leg: overlap counters fire during Module.fit on the per-key
# priority path, and sharded-update weights bit-match replicated (atol 1e-6)
JAX_PLATFORMS=cpu MXNET_DEFAULT_CONTEXT=cpu \
python tools/launch.py -n 8 --launcher local \
    python tests/nightly/dist_kvstore_overlap.py --skip-bandwidth \
    || { echo "kvstore overlap/parity smoke FAILED"; exit 1; }
# bandwidth leg (fresh processes, nothing else resident): the bucketed
# push+pull round-trip must stay >= the r05 scoreboard number (0.056 GB/s).
# One retry absorbs transient host load — the floor is a regression gate,
# not a record attempt.
BW_CMD=(python tools/launch.py -n 8 --launcher local
        python tests/nightly/dist_kvstore_overlap.py --only-bandwidth
        --size-mb 64 --iters 4 --min-gbps 0.056)
JAX_PLATFORMS=cpu MXNET_DEFAULT_CONTEXT=cpu MXNET_KVSTORE_BUCKET_MB=16 \
"${BW_CMD[@]}" || {
    echo "kvstore bandwidth smoke below floor; retrying once...";
    JAX_PLATFORMS=cpu MXNET_DEFAULT_CONTEXT=cpu MXNET_KVSTORE_BUCKET_MB=16 \
    "${BW_CMD[@]}" || { echo "kvstore bandwidth smoke FAILED"; exit 1; }
}

echo "== [6/10] sparse kvstore: 2-proc recommender smoke (docs/SPARSE.md) =="
# sparse-push fit weight-parity with the dense-push control (atol 1e-6) AND
# kvstore.bytes.sparse strictly below the control's table allreduce bytes;
# both gates assert inside the script on every rank
JAX_PLATFORMS=cpu MXNET_DEFAULT_CONTEXT=cpu \
python tools/launch.py -n 2 --launcher local --cpu-devices 1 \
    python tests/nightly/dist_sparse_kvstore.py \
    || { echo "sparse kvstore smoke FAILED"; exit 1; }
# budget-armed autoplan gate: with replicated tables over the HBM budget,
# the 8-device per-param search must shard an embedding table over the
# model axis and beat naive all-dp on predicted comm
SPARSE_PLAN="$(mktemp /tmp/graphlint_recsys_ci.XXXXXX.json)"
JAX_PLATFORMS=cpu python tools/graphlint --autoplan recommender \
    --mesh-devices 8 --budget-gb 0.0625 --format json > "$SPARSE_PLAN" \
    || { echo "recommender autoplan FAILED"; rm -f "$SPARSE_PLAN"; exit 1; }
python - "$SPARSE_PLAN" <<'PYEOF' || { echo "recommender autoplan gate FAILED"; rm -f "$SPARSE_PLAN"; exit 1; }
import json, sys
plan = json.load(open(sys.argv[1]))[0]["autoplan"]
assert plan["feasible"], plan.get("reason")
assert plan["mesh"].get("model", 1) > 1, plan["mesh"]
tables = [n for n in ("user_embed_weight", "item_embed_weight")
          if any(plan["param_specs"].get(n, []))]
assert tables, "no embedding table sharded: %s" % plan["param_specs"]
chosen, naive = plan["predicted"]["comm_bytes"], plan["naive"]["comm_bytes"]
assert chosen < naive, "recommender: %d B >= naive %d B" % (chosen, naive)
print("recommender autoplan OK: mesh %s, sharded tables %s, comm %.2f KiB "
      "vs naive %.2f MiB" % (plan["mesh"], tables, chosen / 2**10,
                             naive / 2**20))
PYEOF
rm -f "$SPARSE_PLAN"

echo "== [7/10] serving: serve_bench smoke (docs/SERVING.md) =="
# tiny-model CPU serving smoke: sustained QPS > 0, finite p99, ZERO
# post-warmup retraces/compiles (the sealed executable-cache contract,
# gated via the GL201-203 guard + executor compile/cache-hit telemetry),
# and the serving.* span families present in the trace buffer
JAX_PLATFORMS=cpu MXNET_DEFAULT_CONTEXT=cpu \
python tools/serve_bench.py --model mlp --qps 100 --duration 1 --check \
    || { echo "serve_bench engine smoke FAILED"; exit 1; }
# the kv-decode smoke includes the megastep leg (--megastep-k 8): K
# tokens per dispatch through the sealed lax.scan program, gated on
# token-identical parity with single-step greedy, zero post-warmup
# retraces, and host_gap_per_token at K=8 <= 0.5x the K=1 baseline
JAX_PLATFORMS=cpu MXNET_DEFAULT_CONTEXT=cpu \
python tools/serve_bench.py --model transformer-decode --qps 16 \
    --duration 1 --rows 2 --megastep-k 8 --check \
    || { echo "serve_bench kv-decode smoke FAILED"; exit 1; }
# shared-prefix cache + speculative decoding smoke (docs/SERVING.md
# §Prefix cache & speculative decoding): zipf shared-prefix workload
# against the COW paged pool, gated on chunk hit rate > 0.5, prefill
# FLOPs saved > 0, BITWISE-identical cached-vs-cold admit logits,
# speculative greedy token-identical to plain greedy with accepted-draft
# rate > 0 and per-token p50 <= the non-speculative baseline, and zero
# post-warmup retraces/compiles across both legs
JAX_PLATFORMS=cpu MXNET_DEFAULT_CONTEXT=cpu \
python tools/serve_bench.py --workload zipf-prefix --qps 20 \
    --duration 2 --check \
    || { echo "serve_bench prefix/speculative smoke FAILED"; exit 1; }
# serving chaos smoke (docs/RESILIENCE.md): open-loop load with seeded
# dispatch raises + delays injected (mxnet_tpu/faultinject.py) and one
# mid-run hitless reload(); the gate asserts zero hung futures (every
# request reaches a terminal state), zero post-warmup retraces/compiles,
# the reload applied, p99 of completed requests in bound, and the engine
# back to `healthy` once injection stops. MXNET_CONCLINT=witness arms the
# lock witness for the run: serve_bench additionally fails on any GL805
# (witnessed lock-order inversion / >threshold hold across a dispatch seam)
JAX_PLATFORMS=cpu MXNET_DEFAULT_CONTEXT=cpu MXNET_CONCLINT=witness \
python tools/serve_bench.py --model mlp --chaos --qps 150 --duration 2 \
    --check \
    || { echo "serve_bench chaos smoke FAILED"; exit 1; }

echo "== [8/10] serving fleet: 4-replica router chaos smoke (docs/SERVING.md §Fleet) =="
# open-loop load through the Router over 4 replica PROCESSES with the
# seeded chaos plan: injected fleet.dispatch faults (re-dispatch path),
# one replica SIGKILLed mid-run (supervisor restart with capped backoff),
# and one mid-run fleet-wide hitless rollout. The gate asserts zero
# hung/lost requests (every request reaches a terminal state),
# completed>0, the rollout applied, the fleet back to healthy, aggregate
# QPS above the single-replica closed-loop baseline, p99 in bound, and
# paged-KV multiplexed decode token-identical to sequential decode.
# The same run also drives the fleet OBSERVABILITY plane
# (docs/OBSERVABILITY.md §Fleet): --check additionally gates the
# fleet.request histogram p50/p99 against client-side percentiles, the
# seeded 100%-fault burst tripping the SLO burn-rate gate (and clearing
# after recovery, with structured slo.violation/slo.clear events), and
# --trace-out writes the merged clock-aligned fleet chrome trace.
FLEET_TRACE="$(mktemp /tmp/fleet_trace_ci.XXXXXX.json)"
JAX_PLATFORMS=cpu MXNET_DEFAULT_CONTEXT=cpu \
python tools/serve_bench.py --model mlp --fleet --fleet-replicas 4 \
    --qps 100 --duration 4 --check --trace-out "$FLEET_TRACE" \
    || { echo "serve_bench fleet smoke FAILED"; rm -f "$FLEET_TRACE"; exit 1; }
# the merged dump is a machine contract like the single-process one:
# mxtrace must schema-gate it, and at least one request chain must span
# >=2 processes (router pid + replica pid) joined by ONE trace_id
python tools/mxtrace "$FLEET_TRACE" --check \
    || { echo "mxtrace --check on merged fleet trace FAILED"; rm -f "$FLEET_TRACE"; exit 1; }
python tools/mxtrace "$FLEET_TRACE" --fleet >/dev/null \
    || { echo "mxtrace --fleet on merged fleet trace FAILED"; rm -f "$FLEET_TRACE"; exit 1; }
python - "$FLEET_TRACE" <<'PYEOF' || { echo "fleet trace cross-process gate FAILED"; rm -f "$FLEET_TRACE"; exit 1; }
import json, sys
trace = json.load(open(sys.argv[1]))
events = trace["traceEvents"]
by_tid = {}
for ev in events:
    a = ev.get("args") or {}
    for tid in ([a["trace_id"]] if a.get("trace_id") else []) \
            + list(a.get("trace_ids") or []):
        by_tid.setdefault(tid, set()).add(ev.get("pid"))
cross = {t: sorted(p) for t, p in by_tid.items() if len(p) >= 2}
assert cross, "no trace_id joins spans from >=2 processes (%d traced)" \
    % len(by_tid)
pids = {ev.get("pid") for ev in events if ev.get("ph") == "X"}
assert len(pids) >= 2, "merged trace has spans from only %s" % pids
other = trace["otherData"]
assert other.get("merged") and other.get("fleet"), "otherData not merged"
print("fleet trace gate OK: %d events across %d pids, %d cross-process "
      "request chains" % (len(events), len(pids), len(cross)))
PYEOF
rm -f "$FLEET_TRACE"

echo "== [9/10] elastic: 8-proc chaos smoke (docs/FAULT_TOLERANCE.md) =="
# kill 1 of 8 workers mid-fit: survivors pause, re-form to 7, reseed from
# the sharded async checkpoint, resume — and must reach weight parity with
# an uninterrupted 7-proc control run; checkpoint.inflight must have been
# observed > 0 mid-fit (the async write overlaps the step)
CHAOS_DIR="$(mktemp -d /tmp/dist_elastic_chaos.XXXXXX)"
JAX_PLATFORMS=cpu MXNET_DEFAULT_CONTEXT=cpu \
python tests/nightly/dist_elastic_chaos.py --orchestrate "$CHAOS_DIR" \
    --world 8 \
    || { echo "elastic chaos smoke FAILED"; rm -rf "$CHAOS_DIR"; exit 1; }
rm -rf "$CHAOS_DIR"

echo "== [10/10] tier-1 tests =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_t1.log
exit "${PIPESTATUS[0]}"
