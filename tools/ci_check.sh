#!/usr/bin/env bash
# CI entrypoint: static analysis first, then the tier-1 test suite.
#
# Step 1 dogfoods the graphlint subsystem on every bundled model (the
# acceptance gate: every model must lint with zero error-severity
# diagnostics). Step 2 lints the package sources with ruff or pyflakes when
# one is installed (the container image may ship neither; the dependency-free
# floor — every source compiles — is enforced by
# tests/test_graphlint.py::test_package_sources_compile either way).
# Step 3 is the repo's tier-1 pytest command (ROADMAP.md).
set -uo pipefail
cd "$(dirname "$0")/.."

echo "== [1/3] graphlint: all bundled models =="
JAX_PLATFORMS=cpu python tools/graphlint --all-models --min-severity warning \
    || { echo "graphlint FAILED"; exit 1; }

echo "== [2/3] source lint (ruff/pyflakes if available) =="
if command -v ruff >/dev/null 2>&1; then
    ruff check mxnet_tpu/ || { echo "ruff FAILED"; exit 1; }
elif python -c 'import pyflakes' >/dev/null 2>&1; then
    python -m pyflakes mxnet_tpu/ || { echo "pyflakes FAILED"; exit 1; }
else
    echo "(neither ruff nor pyflakes installed; compile-check runs in pytest)"
fi

echo "== [3/3] tier-1 tests =="
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_t1.log
exit "${PIPESTATUS[0]}"
