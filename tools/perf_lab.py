"""Perf lab: measure ResNet-50 step time on the chip under different knobs.

Usage: python tools/perf_lab.py [--batch N] [--net NAME] [--profile DIR]

Not part of the public API — the experimental harness behind docs/PERF.md.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--image", type=int, default=224)
    ap.add_argument("--net", default="resnet-50")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--profile", default=None, help="capture jax trace to DIR")
    ap.add_argument("--compute-dtype", default="bfloat16")
    ap.add_argument("--remat", default="false",
                    choices=["false", "true", "dots", "nothing"])
    ap.add_argument("--cost", action="store_true",
                    help="also print XLA cost analysis (flops, bytes)")
    ap.add_argument("--input-dtype", default="float32",
                    help="dtype the input batch is placed on device in")
    args = ap.parse_args()

    import jax
    import numpy as np

    from mxnet_tpu import models, parallel

    dev = jax.devices()[0]
    mesh = parallel.make_mesh((1,), axis_names=("data",), devices=[dev])
    net = models.get_symbol(args.net, num_classes=1000,
                            image_shape="3,%d,%d" % (args.image, args.image))
    remat = {"false": False, "true": True}.get(args.remat, args.remat)
    trainer = parallel.SPMDTrainer(
        net, mesh, optimizer="sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
        remat=remat,
        compute_dtype=args.compute_dtype or None)
    b = args.batch
    trainer.init_params({"data": (b, 3, args.image, args.image)},
                        {"softmax_label": (b,)}, seed=0)
    rs = np.random.RandomState(0)
    import jax.numpy as _jnp

    x_host = rs.rand(b, 3, args.image, args.image).astype("float32")
    if args.input_dtype != "float32":
        x_host = x_host.astype(_jnp.dtype(args.input_dtype))
    x = jax.device_put(x_host,
                       trainer.rules.named(trainer.rules.batch_spec((b, 3, args.image, args.image))))
    y = jax.device_put(rs.randint(0, 1000, (b,)).astype("float32"),
                       trainer.rules.named(trainer.rules.batch_spec((b,))))
    import jax.numpy as jnp

    def sync(o):
        # block_until_ready is a no-op on some remote platforms (axon): the
        # only reliable barrier is fetching device data to host
        return np.asarray(jnp.sum(o[0].astype(jnp.float32)))

    for _ in range(3):
        outs = trainer.step({"data": x}, {"softmax_label": y})
    sync(outs)

    if args.profile:
        jax.profiler.start_trace(args.profile)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        outs = trainer.step({"data": x}, {"softmax_label": y})
    sync(outs)
    dt = time.perf_counter() - t0
    if args.profile:
        jax.profiler.stop_trace()

    img_s = b * args.steps / dt
    # FLOPs model is ResNet-50-specific — MFU only claims meaning there
    flops = 3 * 4.09e9 * (args.image / 224.0) ** 2
    peak = 197e12 if "v5 lite" in dev.device_kind else None
    mfu_ok = peak and args.net == "resnet-50"
    out = {"batch": b, "step_ms": round(1000 * dt / args.steps, 2),
           "img_s": round(img_s, 1), "device": dev.device_kind,
           "net": args.net, "remat": args.remat,
           "input_dtype": args.input_dtype,
           "mfu": round(img_s * flops / peak, 4) if mfu_ok else None}
    if args.cost:
        cost = trainer.cost_analysis({"data": x}, {"softmax_label": y})
        gb = cost.get("bytes accessed", 0.0) / 1e9
        out["xla_gb_accessed"] = round(gb, 2)
        out["xla_tflops"] = round(cost.get("flops", 0.0) / 1e12, 3)
        out["hbm_gbps_achieved"] = round(gb / (dt / args.steps), 1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
