"""A/B the Pallas flash-attention kernel against XLA's fused attention at
transformer-base shapes (VERDICT r4 #7: "measure or flip the Pallas
attention default").

Times the MultiHeadAttention op's two lowerings — fwd-only and fwd+bwd —
at (B, H, T, D) transformer-base shapes, seq 512/1024, bf16, amortized
inside one jitted scan with host-fetch sync (docs/PERF.md §0). The table
lands in PERF.md §7 and grounds the MXNET_USE_PALLAS_ATTENTION default.

    python tools/attention_bench.py
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# transformer-base: model_dim 512, 8 heads x 64
SHAPES = [
    # (B, H, T, D, causal)
    (16, 8, 512, 64, False),
    (16, 8, 512, 64, True),
    (8, 8, 1024, 64, False),
    (8, 8, 1024, 64, True),
    (4, 8, 2048, 64, True),
]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--quick", action="store_true",
                    help="one tiny shape (CPU plumbing smoke)")
    args = ap.parse_args()
    shapes = [(2, 2, 128, 64, True)] if args.quick else SHAPES

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu.ops import pallas_attention as pa
    from mxnet_tpu.ops.attention import _multi_head_attention

    dt = jnp.dtype(args.dtype)
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    def sync(x):
        return np.asarray(jnp.sum(x.astype(jnp.float32)))

    def timeit(fn, *arrs):
        @jax.jit
        def many(*arrs):
            def body(c, _):
                o = fn(*arrs)
                return c + o.reshape(-1)[:1].astype(jnp.float32), None

            out, _ = jax.lax.scan(body, jnp.zeros((1,), jnp.float32),
                                  None, length=args.iters)
            return out

        sync(many(*arrs))
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            out = many(*arrs)
            sync(out)
            best = min(best, (time.perf_counter() - t0) / args.iters)
        return best

    rs = np.random.RandomState(0)
    rows = []
    for B, H, T, D, causal in shapes:
        q, k, v = (jnp.asarray(rs.randn(B, H, T, D) * 0.3, dt)
                   for _ in range(3))
        attrs = {"causal": causal, "scale": -1.0}
        rec = {"B": B, "H": H, "T": T, "D": D, "causal": causal}
        if not pa.supported(q.shape, k.shape, causal=causal):
            rec["skipped"] = "pallas unsupported"
            rows.append(rec)
            print(json.dumps(rec))
            continue

        os.environ["MXNET_USE_PALLAS_ATTENTION"] = "0"  # op -> dense path

        def xla_fwd(q, k, v):
            return _multi_head_attention(attrs, q, k, v)

        def pal_fwd(q, k, v):
            return pa.flash_attention(q, k, v, causal=causal, scale=0.0,
                                      interpret=not on_tpu)

        cot = jnp.asarray(rs.randn(B, H, T, D) * 0.1, dt)

        def grad_of(fn):
            def f(q, k, v):
                out = fn(q, k, v)
                return jnp.sum((out * cot).astype(jnp.float32))

            return jax.grad(f, argnums=(0, 1, 2))

        try:
            t_x = timeit(xla_fwd, q, k, v)
            t_p = timeit(pal_fwd, q, k, v)
            gx = grad_of(xla_fwd)
            gp = grad_of(pal_fwd)

            def run_gx(q, k, v):
                a, b, c = gx(q, k, v)
                return a + b + c

            def run_gp(q, k, v):
                a, b, c = gp(q, k, v)
                return a + b + c

            t_xb = timeit(run_gx, q, k, v)
            t_pb = timeit(run_gp, q, k, v)
            o0 = jax.jit(xla_fwd)(q, k, v)
            o1 = jax.jit(pal_fwd)(q, k, v)
            rel = float(jnp.max(jnp.abs(o0.astype(jnp.float32)
                                        - o1.astype(jnp.float32))))
            rec.update({
                "xla_fwd_ms": round(t_x * 1e3, 3),
                "pallas_fwd_ms": round(t_p * 1e3, 3),
                "fwd_speedup": round(t_x / t_p, 3),
                "xla_bwd_ms": round(t_xb * 1e3, 3),
                "pallas_bwd_ms": round(t_pb * 1e3, 3),
                "bwd_speedup": round(t_xb / t_pb, 3),
                "max_abs_err": round(rel, 5),
            })
        except Exception as exc:
            rec["error"] = "%s: %s" % (type(exc).__name__, exc)
        rows.append(rec)
        print(json.dumps(rec))

    measured = [r for r in rows if "fwd_speedup" in r]
    if measured:
        wins = sum(1 for r in measured
                   if r["fwd_speedup"] >= 1.0 and r["bwd_speedup"] >= 1.0)
        print(json.dumps({"summary": {
            "device": dev.device_kind, "dtype": str(dt),
            "shapes_measured": len(measured),
            "pallas_wins_both_directions": wins,
            "recommend_default": "1" if wins == len(measured) else "0",
        }}))


if __name__ == "__main__":
    main()
