"""Measure the Pallas matmul+BN-stats kernel against XLA's unfused lowering
(matmul, then a separate statistics read-back pass) at ResNet-50 1x1-conv
shapes, batch 256. The quantity under test is the one docs/PERF.md §4 says
is the last MFU lever on the v5e: removing the statistics pass's re-read of
the activation.

Each timing amortizes ``--iters`` kernel executions inside one jitted scan
(the axon tunnel adds ~2 ms per dispatch) and syncs by fetching a scalar.

    python tools/fused_stats_bench.py
"""
import argparse
import functools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# (M, K, N) = (B*H*W, Cin, Cout) for b256 ResNet-50 bottleneck 1x1s
SHAPES = [
    (802816, 64, 256),    # stage1 expand, 56x56
    (802816, 256, 64),    # stage1 reduce
    (200704, 512, 128),   # stage2, 28x28
    (50176, 1024, 256),   # stage3, 14x14
    (12544, 2048, 512),   # stage4, 7x7
]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--block-m", type=int, default=512)
    ap.add_argument("--block-n", type=int, default=256)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.pallas_matmul_stats import matmul_with_stats, supported

    def sync(x):
        return np.asarray(jnp.sum(x.astype(jnp.float32)))

    def timeit(fn, a, b):
        @jax.jit
        def many(a, b):
            def body(carry, _):
                c, s, q = fn(a, b)
                # fold outputs into the carry so no iteration is dead code
                return carry + s[:1] + q[:1] + c[:1, :1].astype(jnp.float32).reshape(1), None

            out, _ = jax.lax.scan(body, jnp.zeros((1,), jnp.float32),
                                  None, length=args.iters)
            return out

        sync(many(a, b))  # compile + warmup
        t0 = time.perf_counter()
        out = many(a, b)
        sync(out)
        return (time.perf_counter() - t0) / args.iters

    def xla_path(a, b):
        c = jnp.dot(a, b)                       # bf16 out, MXU
        c32 = c.astype(jnp.float32)
        return c, jnp.sum(c32, axis=0), jnp.sum(c32 * c32, axis=0)

    rs = np.random.RandomState(0)
    for M, K, N in SHAPES:
        # fall back through smaller M-blocks so every shape that CAN tile
        # gets measured rather than silently skipped
        bm = next((c for c in (args.block_m, 256, 128, 64)
                   if supported(M, K, N, c, args.block_n, itemsize=2)), None)
        if bm is None:
            print(json.dumps({"shape": [M, K, N], "skipped": "tiling"}))
            continue
        a = jnp.asarray(rs.randn(M, K), jnp.bfloat16)
        b = jnp.asarray(rs.randn(K, N), jnp.bfloat16)

        def pallas_path_bm(a, b, bm=bm):
            return matmul_with_stats(a, b, block_m=bm, block_n=args.block_n)

        t_xla = timeit(xla_path, a, b)
        t_pal = timeit(pallas_path_bm, a, b)
        # correctness spot check: all three outputs (bf16 tolerances)
        c0, s0, q0 = jax.jit(xla_path)(a, b)
        c1, s1, q1 = jax.jit(pallas_path_bm)(a, b)
        rel = lambda x, y: float(jnp.max(jnp.abs(x.astype(jnp.float32)
                                                 - y.astype(jnp.float32)))
                                 / (jnp.max(jnp.abs(x.astype(jnp.float32)))
                                    + 1e-9))
        print(json.dumps({
            "shape": [M, K, N], "block_m": bm,
            "xla_ms": round(t_xla * 1e3, 3),
            "pallas_ms": round(t_pal * 1e3, 3),
            "speedup": round(t_xla / t_pal, 3),
            "stats_rel_err": round(rel(s0, s1), 5),
            "sumsq_rel_err": round(rel(q0, q1), 5),
            "c_rel_err": round(rel(c0, c1), 5),
        }))


if __name__ == "__main__":
    main()
