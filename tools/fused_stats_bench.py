"""Autotune harness for the fused conv+BN Pallas stack: measure fused vs
XLA per (shape, variant, direction) and emit the WINS table that gates graph
integration (mxnet_tpu/ops/fused_conv_bn_table.py).

Contracts under test (fusion.py):

  forward   unfused:  xn = relu(x*scale + shift)  [materialized]
                      c  = conv(xn);  s = sum(c32);  q = sum(c32^2)
            fused:    conv_block(...) — prologue in VMEM, stats from the f32
                      MXU accumulator, one HBM write for c.
  backward  unfused:  jax.vjp of the composition above (cotangent fold,
                      dgrad, wgrad, prologue backward each cross HBM).
            fused:    the Pallas dgrad/wgrad kernel, per residual policy —
                      'recompute' (xn re-derived in VMEM) and 'stash' (xn
                      written by the forward, streamed back).

Variants: 'p' = prologue-only, 'pr' = prologue+residual. Each direction is
timed separately; backward wins are recorded per winning POLICY — the WINS
value for a ``variant + ":bwd"`` key is the policy string, which
``fusion.bwd_mode`` rides into ``conv_block(bwd=...)`` under
``MXNET_FUSED_CONV_BN=auto``.

Each timing amortizes ``--iters`` executions inside one jitted scan (the
axon tunnel adds ~2 ms per dispatch) and syncs by fetching a scalar
(docs/PERF.md §0). A contract "wins" when fused time <= unfused time AND
gradient/output parity holds; wins are written with ``--emit-table``.

``--interpret`` forces Pallas interpret mode so the whole harness — timing
scaffolding, parity checks, table emission, loadability — runs on CPU
without a chip (the CI smoke in tools/ci_check.sh). Interpret timings are
NOT predictive (the emulator is orders of magnitude slower than compiled
XLA), so --interpret defaults ``--min-speedup`` to 0: the emitted table
records every parity-validated contract, proving the machinery end to end.

    python tools/fused_stats_bench.py --batch 256 --emit-table      # on-chip
    python tools/fused_stats_bench.py --interpret --emit-table \\
        --table-out /tmp/table.py                                   # CPU CI
"""
import argparse
import functools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_TABLE = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                      "mxnet_tpu", "ops", "fused_conv_bn_table.py")

_BWD_POLICIES = ("recompute", "stash")


def resnet50_sites():
    """Canonical @224 site list (kept as the historical entry point; the
    shared implementation lives in mxnet_tpu.ops.conv_bn_bytes)."""
    from mxnet_tpu.ops.conv_bn_bytes import resnet50_sites as _sites

    return _sites()


def tiny_sites():
    """Small shapes covering every kernel family / stride / ceil-div path —
    the interpret-mode (CPU) site list, where @224 shapes would take hours
    in the Pallas emulator."""
    return [
        ((1, 1), (1, 1), 8, 16, 8, 1, 0),
        ((1, 1), (2, 2), 8, 16, 9, 1, 0),   # odd H: ceil-div strided dims
        ((3, 3), (1, 1), 8, 8, 8, 1, 1),
        ((1, 1), (1, 1), 16, 8, 8, 1, 1),
    ]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=None,
                    help="default 256 (2 with --interpret)")
    ap.add_argument("--iters", type=int, default=None,
                    help="default 10 (2 with --interpret)")
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--emit-table", action="store_true")
    ap.add_argument("--table-out", default=_TABLE,
                    help="where --emit-table writes (default: the committed "
                         "mxnet_tpu/ops/fused_conv_bn_table.py)")
    ap.add_argument("--sites", choices=["resnet50", "tiny"], default=None,
                    help="default resnet50 (tiny with --interpret)")
    ap.add_argument("--directions", default="fwd,bwd",
                    help="comma list of fwd,bwd")
    ap.add_argument("--interpret", action="store_true",
                    help="run the Pallas kernels in interpret mode (CPU CI "
                         "smoke; timings not predictive)")
    ap.add_argument("--min-speedup", type=float, default=None,
                    help="fused engages where t_xla/t_fused >= this "
                         "(default 1.0; 0.0 with --interpret)")
    args = ap.parse_args()
    if args.interpret:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        os.environ.setdefault("MXNET_DEFAULT_CONTEXT", "cpu")
        if (args.emit_table
                and os.path.abspath(args.table_out) == os.path.abspath(_TABLE)):
            # the committed table is an ON-CHIP measurement; an interpret
            # run would clobber it with a cpu-stamped table whose min_speedup=0
            # wins are artifacts of the emulator — and auto mode would then
            # engage the interpret-slow Pallas path in every CPU test run
            ap.error("--interpret --emit-table refuses to overwrite the "
                     "committed table; pass --table-out <path>")
    batch = args.batch if args.batch is not None else (2 if args.interpret
                                                       else 256)
    iters = args.iters if args.iters is not None else (2 if args.interpret
                                                       else 10)
    min_speedup = args.min_speedup if args.min_speedup is not None else (
        0.0 if args.interpret else 1.0)
    directions = tuple(d for d in args.directions.split(",") if d)

    import jax
    import jax.numpy as jnp

    if args.interpret:
        jax.config.update("jax_platforms", "cpu")

    from mxnet_tpu.ops.pallas_conv_bn import (_stats_of, _xla_conv,
                                              conv_block, plan_bwd_blocks,
                                              strided_dims, supported)

    dt = jnp.dtype(args.dtype)
    dev = jax.devices()[0]
    site_list = (tiny_sites()
                 if (args.sites or ("tiny" if args.interpret else "resnet50"))
                 == "tiny" else resnet50_sites())

    def sync(x):
        return np.asarray(jnp.sum(x.astype(jnp.float32)))

    def timeit_many(many):
        sync(many())  # compile + warmup
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            out = many()
            sync(out)
            best = min(best, (time.perf_counter() - t0) / iters)
        return best

    def timeit_fwd(fn, *arrs):
        # operands are jit ARGUMENTS (not closure constants) so XLA cannot
        # constant-fold the measured computation out of the scan
        @jax.jit
        def many(*arrs):
            def body(carry, _):
                c, s, q = fn(*arrs)
                return (carry + s[:1] + q[:1]
                        + c.reshape(-1)[:1].astype(jnp.float32)), None

            out, _ = jax.lax.scan(body, jnp.zeros((1,), jnp.float32),
                                  None, length=iters)
            return out

        return timeit_many(lambda: many(*arrs))

    def timeit_bwd(fn, cts, *arrs):
        """Time ONLY the backward: the vjp closure (residuals resident, like
        a training step's) applied ``iters`` times in one jitted scan.
        vjp_fn is a Partial pytree, so passing it as a jit argument keeps
        the residuals traced arguments rather than baked-in constants."""
        _, vjp_fn = jax.vjp(fn, *arrs)

        @jax.jit
        def many(vjp_fn, cts):
            def body(carry, _):
                grads = vjp_fn(cts)
                leaf = grads[0].reshape(-1)[:1].astype(jnp.float32)
                return carry + leaf, None

            out, _ = jax.lax.scan(body, jnp.zeros((1,), jnp.float32),
                                  None, length=iters)
            return out

        return timeit_many(lambda: many(vjp_fn, cts))

    def grads_of(fn, cts, *arrs):
        _, vjp_fn = jax.vjp(fn, *arrs)
        return jax.jit(lambda: vjp_fn(cts))()

    rel = lambda a, b: float(
        jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)))
        / (float(jnp.max(jnp.abs(b.astype(jnp.float32)))) + 1e-9))

    rs = np.random.RandomState(0)
    wins, rows = {}, []
    for kernel, stride, K, N, H, count, _res_count in site_list:
        B = batch
        x_shape = (B, K, H, H)
        w_shape = (N, K) + kernel
        rec = {"kernel": kernel[0], "stride": stride[0], "K": K, "N": N,
               "H": H, "count": count}
        if not supported(x_shape, w_shape, stride, itemsize=dt.itemsize,
                         prologue=True):
            rec["skipped"] = "unsupported"
            rows.append(rec)
            print(json.dumps(rec))
            continue
        x = jnp.asarray(rs.randn(*x_shape), dt)
        w = jnp.asarray(rs.randn(*w_shape) * 0.1, dt)
        scale = jnp.asarray(rs.uniform(0.5, 1.5, (K,)), jnp.float32)
        shift = jnp.asarray(rs.uniform(-0.2, 0.2, (K,)), jnp.float32)
        Ho, Wo = strided_dims(H, H, stride)
        r = jnp.asarray(rs.randn(B, N, Ho, Wo) * 0.1, dt)
        cts = (jnp.asarray(rs.randn(B, N, Ho, Wo), dt),
               jnp.asarray(rs.randn(N), jnp.float32),
               jnp.asarray(rs.randn(N) * 0.1, jnp.float32))

        # two measured contracts: 'p' = prologue-only (every in-graph conv
        # with a folded BN), 'pr' = prologue + residual epilogue (convs
        # deferred into the block's skip add). gate()/bwd_mode() engage
        # exactly the (variant, direction) that was measured.
        for variant, res in (("p", None), ("pr", r)):
            if res is not None and not supported(
                    x_shape, w_shape, stride, itemsize=dt.itemsize,
                    prologue=True, res=True):
                continue
            key = (kernel[0], K, N, Ho * Wo, stride[0], variant)

            def unfused(x, w, scale, shift, res=res):
                c = _xla_conv(x, w, scale, shift, res, kernel, stride, True)
                s, q = _stats_of(c)
                return c, s, q

            def fused(x, w, scale, shift, res=res, bwd="xla"):
                return conv_block(x, w, scale, shift, res, kernel, stride,
                                  True, True, bwd)

            if "fwd" in directions:
                try:
                    t_x = timeit_fwd(unfused, x, w, scale, shift)
                    t_p = timeit_fwd(fused, x, w, scale, shift)
                    c0, s0, q0 = jax.jit(unfused)(x, w, scale, shift)
                    c1, s1, q1 = jax.jit(fused)(x, w, scale, shift)
                    rec.update({
                        "xla_ms_%s" % variant: round(t_x * 1e3, 3),
                        "pallas_ms_%s" % variant: round(t_p * 1e3, 3),
                        "speedup_%s" % variant: round(t_x / t_p, 3),
                        "c_rel_err_%s" % variant: round(rel(c1, c0), 5),
                        "stats_rel_err_%s" % variant:
                            round(max(rel(s1, s0), rel(q1, q0)), 5),
                    })
                    if (t_x / t_p >= min_speedup
                            and rec["c_rel_err_%s" % variant] < 2e-2):
                        wins[key] = True
                except Exception as exc:
                    rec["error_%s" % variant] = \
                        "%s: %s" % (type(exc).__name__, exc)

            if "bwd" in directions:
                n_args = (x, w, scale, shift)
                try:
                    t_bx = timeit_bwd(unfused, cts, *n_args)
                    g_ref = grads_of(unfused, cts, *n_args)
                    rec["bwd_xla_ms_%s" % variant] = round(t_bx * 1e3, 3)
                    best = None
                    for policy in _BWD_POLICIES:
                        if plan_bwd_blocks(
                                x_shape, w_shape, stride,
                                itemsize=dt.itemsize, prologue=True,
                                res=res is not None,
                                stash=(policy == "stash")) is None:
                            continue
                        fn = functools.partial(fused, bwd=policy)
                        t_bp = timeit_bwd(fn, cts, *n_args)
                        g_pol = grads_of(fn, cts, *n_args)
                        err = max(rel(a, b) for a, b in zip(g_pol, g_ref))
                        rec["bwd_%s_ms_%s" % (policy, variant)] = \
                            round(t_bp * 1e3, 3)
                        rec["bwd_%s_grad_rel_err_%s" % (policy, variant)] = \
                            round(err, 5)
                        if (t_bx / t_bp >= min_speedup and err < 2e-2
                                and (best is None or t_bp < best[1])):
                            best = (policy, t_bp)
                    if best is not None:
                        rec["bwd_policy_%s" % variant] = best[0]
                        rec["bwd_speedup_%s" % variant] = \
                            round(t_bx / best[1], 3)
                        wins[key[:5] + (variant + ":bwd",)] = best[0]
                except Exception as exc:
                    rec["bwd_error_%s" % variant] = \
                        "%s: %s" % (type(exc).__name__, exc)
        rows.append(rec)
        print(json.dumps(rec))

    def _key(r, variant):
        hw = ((r["H"] + r["stride"] - 1) // r["stride"]) ** 2
        return (r["kernel"], r["K"], r["N"], hw, r["stride"], variant)

    measured = [r for r in rows
                if any(k.startswith(("speedup_", "bwd_")) and "error" not in k
                       for k in r)]
    won_p = [r for r in measured if _key(r, "p") in wins]
    won_pr = [r for r in measured if _key(r, "pr") in wins]
    won_bwd = [r for r in measured
               if _key(r, "p:bwd") in wins or _key(r, "pr:bwd") in wins]
    summary = {
        "device": dev.device_kind, "batch": batch, "dtype": str(dt),
        "interpret": bool(args.interpret),
        "directions": list(directions),
        "sites_total": sum(r["count"] for r in rows),
        "sites_measured": sum(r["count"] for r in measured),
        "sites_won_p": sum(r["count"] for r in won_p),
        "sites_won_pr": sum(r["count"] for r in won_pr),
        "sites_won_bwd": sum(r["count"] for r in won_bwd),
        "unique_measured": len(measured),
        "unique_won_p": len(won_p), "unique_won_pr": len(won_pr),
        "unique_won_bwd": len(won_bwd),
    }
    print(json.dumps({"summary": summary}))

    if args.emit_table:
        with open(args.table_out, "w") as f:
            f.write('"""Per-shape engage table for the fused conv+BN Pallas '
                    'path - GENERATED by\n``tools/fused_stats_bench.py '
                    '--emit-table`` from on-chip measurements; do not\n'
                    'hand-edit. Key: ``(kernel_size, C_in, C_out, '
                    'H_out*W_out, stride, variant)`` with\nvariant "p" = '
                    'prologue-only, "pr" = prologue+residual, and '
                    '"p:bwd"/"pr:bwd"\nthe backward direction. A forward '
                    'value of True means the Pallas kernel beat\nthe '
                    'unfused XLA lowering for that measured contract on the '
                    'measured device\n(fusion.gate engages it under '
                    'MXNET_FUSED_CONV_BN=auto); a backward value is\nthe '
                    'winning residual policy string ("recompute" or '
                    '"stash") that\nfusion.bwd_mode rides into '
                    'conv_block(bwd=...).\n\nMeasurement: %s\n"""\n\n'
                    % json.dumps(summary))
            f.write("DEVICE = %r\n\nWINS = {\n" % dev.device_kind)
            for key in sorted(wins):
                f.write("    %r: %r,\n" % (key, wins[key]))
            f.write("}\n")
        print(json.dumps({"table_written": args.table_out,
                          "entries": len(wins)}))


if __name__ == "__main__":
    main()
