"""Measure the fused conv+BN Pallas kernel against XLA's unfused lowering at
every eligible ResNet-50 @224 conv+BN site, and emit the per-shape WINS table
that gates graph integration (mxnet_tpu/ops/fused_conv_bn_table.py).

The contract under test is the in-graph one (fusion.py):

  unfused:  xn = relu(x*scale + shift)  [materialized]
            c  = conv(xn);  s = sum(c32);  q = sum(c32^2)   [stats re-read c]
  fused:    conv_block(x, w, scale, shift, ...) — prologue in VMEM, stats
            from the f32 MXU accumulator, one HBM write for c.

Each timing amortizes ``--iters`` executions inside one jitted scan (the
axon tunnel adds ~2 ms per dispatch) and syncs by fetching a scalar
(docs/PERF.md §0). A shape "wins" when fused time <= unfused time; wins are
written with ``--emit-table`` and engage under MXNET_FUSED_CONV_BN=auto.

    python tools/fused_stats_bench.py --batch 256 --emit-table
"""
import argparse
import functools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_TABLE = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                      "mxnet_tpu", "ops", "fused_conv_bn_table.py")


def resnet50_sites(batch):
    """Every conv+BN site of models/resnet.py resnet-50 @224 as
    (kernel, stride, K, N, H, count). 53 convs total; the 7x7 stem and the
    three stride-2 3x3s are structurally out (supported() false)."""
    units = [3, 4, 6, 3]
    filters = [64, 256, 512, 1024, 2048]
    sites = {}

    def add(kernel, stride, K, N, H):
        key = (kernel, stride, K, N, H)
        sites[key] = sites.get(key, 0) + 1

    add((7, 7), (2, 2), 3, 64, 224)  # stem (reported, never supported)
    H = 56
    for stage, n_unit in enumerate(units):
        stride = 1 if stage == 0 else 2
        nf = filters[stage + 1]
        K_in = filters[stage]
        # unit 1 (dim_match=False)
        add((1, 1), (1, 1), K_in, nf // 4, H)            # conv1
        add((3, 3), (stride, stride), nf // 4, nf // 4, H)  # conv2 (strided)
        Ho = H // stride
        add((1, 1), (1, 1), nf // 4, nf, Ho)             # conv3
        add((1, 1), (stride, stride), K_in, nf, H)       # shortcut
        H = Ho
        for _ in range(n_unit - 1):
            add((1, 1), (1, 1), nf, nf // 4, H)
            add((3, 3), (1, 1), nf // 4, nf // 4, H)
            add((1, 1), (1, 1), nf // 4, nf, H)
    total = sum(sites.values())
    assert total == 53, total
    return [(k, s, K, N, H, c) for (k, s, K, N, H), c in sorted(sites.items())]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--emit-table", action="store_true")
    ap.add_argument("--min-speedup", type=float, default=1.0,
                    help="fused engages where t_xla/t_fused >= this")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.pallas_conv_bn import (conv_block, supported,
                                              _xla_conv, _stats_of)

    dt = jnp.dtype(args.dtype)
    dev = jax.devices()[0]

    def sync(x):
        return np.asarray(jnp.sum(x.astype(jnp.float32)))

    def timeit(fn, *arrs):
        @jax.jit
        def many(*arrs):
            def body(carry, _):
                c, s, q = fn(*arrs)
                return (carry + s[:1] + q[:1]
                        + c.reshape(-1)[:1].astype(jnp.float32)), None

            out, _ = jax.lax.scan(body, jnp.zeros((1,), jnp.float32),
                                  None, length=args.iters)
            return out

        sync(many(*arrs))  # compile + warmup
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            out = many(*arrs)
            sync(out)
            best = min(best, (time.perf_counter() - t0) / args.iters)
        return best

    rs = np.random.RandomState(0)
    wins, rows = {}, []
    for kernel, stride, K, N, H, count in resnet50_sites(args.batch):
        B = args.batch
        x_shape = (B, K, H, H)
        w_shape = (N, K) + kernel
        rec = {"kernel": kernel[0], "stride": stride[0], "K": K, "N": N,
               "H": H, "count": count}
        if not supported(x_shape, w_shape, stride, itemsize=dt.itemsize,
                         prologue=True):
            rec["skipped"] = "unsupported"
            rows.append(rec)
            print(json.dumps(rec))
            continue
        x = jnp.asarray(rs.randn(*x_shape), dt)
        w = jnp.asarray(rs.randn(*w_shape) * 0.1, dt)
        scale = jnp.asarray(rs.uniform(0.5, 1.5, (K,)), jnp.float32)
        shift = jnp.asarray(rs.uniform(-0.2, 0.2, (K,)), jnp.float32)
        Ho, Wo = H // stride[0], H // stride[1]
        r = jnp.asarray(rs.randn(B, N, Ho, Wo) * 0.1, dt)
        rel = lambda a, b: float(
            jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)))
            / (float(jnp.max(jnp.abs(b.astype(jnp.float32)))) + 1e-9))

        # two measured contracts: 'p' = prologue-only (every in-graph conv
        # with a folded BN), 'pr' = prologue + residual epilogue (convs
        # deferred into the block's skip add). gate() engages exactly the
        # variant that was measured.
        for variant, res in (("p", None), ("pr", r)):
            if res is not None and not supported(
                    x_shape, w_shape, stride, itemsize=dt.itemsize,
                    prologue=True, res=True):
                continue

            def unfused(x, w, scale, shift, res=res):
                c = _xla_conv(x, w, scale, shift, res, kernel, stride, True)
                s, q = _stats_of(c)
                return c, s, q

            def fused(x, w, scale, shift, res=res):
                return conv_block(x, w, scale, shift, res, kernel, stride,
                                  True)

            try:
                t_x = timeit(unfused, x, w, scale, shift)
                t_p = timeit(fused, x, w, scale, shift)
                c0, s0, q0 = jax.jit(unfused)(x, w, scale, shift)
                c1, s1, q1 = jax.jit(fused)(x, w, scale, shift)
                rec.update({
                    "xla_ms_%s" % variant: round(t_x * 1e3, 3),
                    "pallas_ms_%s" % variant: round(t_p * 1e3, 3),
                    "speedup_%s" % variant: round(t_x / t_p, 3),
                    "c_rel_err_%s" % variant: round(rel(c1, c0), 5),
                    "stats_rel_err_%s" % variant:
                        round(max(rel(s1, s0), rel(q1, q0)), 5),
                })
                if (t_x / t_p >= args.min_speedup
                        and rec["c_rel_err_%s" % variant] < 2e-2):
                    wins[(kernel[0], K, N, Ho * Ho, stride[0], variant)] = True
            except Exception as exc:
                rec["error_%s" % variant] = "%s: %s" % (type(exc).__name__, exc)
        rows.append(rec)
        print(json.dumps(rec))

    def _key(r, variant):
        return (r["kernel"], r["K"], r["N"], (r["H"] // r["stride"]) ** 2,
                r["stride"], variant)

    measured = [r for r in rows
                if "speedup_p" in r or "speedup_pr" in r]
    won_p = [r for r in measured if _key(r, "p") in wins]
    won_pr = [r for r in measured if _key(r, "pr") in wins]
    summary = {
        "device": dev.device_kind, "batch": args.batch, "dtype": str(dt),
        "sites_total": sum(r["count"] for r in rows),
        "sites_measured": sum(r["count"] for r in measured),
        "sites_won_p": sum(r["count"] for r in won_p),
        "sites_won_pr": sum(r["count"] for r in won_pr),
        "unique_measured": len(measured),
        "unique_won_p": len(won_p), "unique_won_pr": len(won_pr),
    }
    print(json.dumps({"summary": summary}))

    if args.emit_table:
        with open(_TABLE, "w") as f:
            f.write('"""Per-shape engage table for the fused conv+BN Pallas '
                    'path - GENERATED by\n``tools/fused_stats_bench.py '
                    '--emit-table`` from on-chip measurements; do not\n'
                    'hand-edit. Key: ``(kernel_size, C_in, C_out, '
                    'H_out*W_out, stride, variant)`` with\nvariant "p" = '
                    'prologue-only, "pr" = prologue+residual; value True '
                    'means the\nPallas kernel beat the unfused XLA lowering '
                    'for that measured contract on\nthe measured device '
                    '(fusion.gate engages it under '
                    'MXNET_FUSED_CONV_BN=auto).\n\nMeasurement: %s\n"""\n\n'
                    % json.dumps(summary))
            f.write("DEVICE = %r\n\nWINS = {\n" % dev.device_kind)
            for key in sorted(wins):
                f.write("    %r: True,\n" % (key,))
            f.write("}\n")
        print(json.dumps({"table_written": _TABLE, "entries": len(wins)}))


if __name__ == "__main__":
    main()
