#!/usr/bin/env python
"""All-reduce bandwidth microbenchmark.

Counterpart of the reference's tools/bandwidth/measure.py (KVStore push/pull
bandwidth over ps-lite). Here the reduction IS an XLA psum over the device
mesh (ICI on a pod, host shared-memory on the virtual CPU mesh), so the
measured quantity is collective bandwidth per chip:

    algo_bw   = 2 * (n-1)/n * bytes / time   (ring all-reduce wire traffic)

Run on N virtual CPU devices:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 MXNET_DEFAULT_CONTEXT=cpu \
        python tools/bandwidth/measure.py --sizes 1,16,64
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
import mxnet_tpu  # noqa: E402,F401  (honors MXNET_DEFAULT_CONTEXT=cpu platform forcing)


def measure(size_mb, n_iter=10):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    devs = jax.local_devices()
    n = len(devs)
    mesh = Mesh(np.array(devs), ("x",))
    elems = int(size_mb * 1e6 / 4)
    elems -= elems % max(n, 1)
    x = jnp.ones((elems,), jnp.float32)
    x = jax.device_put(x, NamedSharding(mesh, P("x")))

    @jax.jit
    def allreduce(v):
        return shard_map(lambda s: jax.lax.psum(s, "x"), mesh=mesh,
                         in_specs=P("x"), out_specs=P(None))(v)

    out = allreduce(x)  # compile + warmup
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n_iter):
        out = allreduce(x)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n_iter
    nbytes = elems * 4
    algo_bw = 2 * (n - 1) / max(n, 1) * nbytes / dt / 1e9
    return dt, algo_bw, n


def measure_kvstore(size_mb, n_iter=10, legacy=False, n_keys=1,
                    bucket_mb=None):
    """Measure the *KVStore* dist allreduce path (push+pull round-trip), the
    quantity BASELINE.md tracks. Run under tools/launch.py so multiple
    processes join the collective:

        python tools/launch.py -n 8 --launcher local --cpu-devices 1 \\
            python tools/bandwidth/measure.py --kvstore --sizes 16

    ``n_keys`` splits the payload into that many keys pushed per-key with
    reverse-topo priorities — the bucketed overlap path ``Module.fit``
    drives (docs/PERF.md §11); ``bucket_mb`` pins MXNET_KVSTORE_BUCKET_MB
    for this store (the bench's bucket-size sweep). ``legacy=True``
    measures the round-2 per-key host allgather+sum instead of the compiled
    collective, for comparison. Returns (dt, busbw, n, overlap_ratio)."""
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.ndarray import NDArray

    if bucket_mb is not None:
        os.environ["MXNET_KVSTORE_BUCKET_MB"] = str(bucket_mb)
    kv = mx.kv.create("dist_tpu_sync")
    n = kv.num_workers
    elems = int(size_mb * 1e6 / 4 / n_keys)
    keys = ["bw%d" % i for i in range(n_keys)]
    vals = [mx.nd.ones((elems,)) for _ in keys]
    outs = [mx.nd.zeros((elems,)) for _ in keys]
    for k in keys:
        kv.init(k, mx.nd.zeros((elems,)))

    if legacy:
        def allgather_sum(arr):
            import jax.numpy as jnp
            from jax.experimental.multihost_utils import process_allgather

            gathered = process_allgather(arr._jax())
            return NDArray(jnp.sum(gathered, axis=0), ctx=arr.context)

        def round_trip():
            for k, v in zip(keys, vals):
                kv._store[k] = allgather_sum(v)
            for k, o in zip(keys, outs):
                kv.pull(k, out=o)
    else:
        def round_trip():
            # reverse-topo push order + priorities: deepest first, the
            # schedule update_params_on_kvstore emits
            for j in range(n_keys - 1, -1, -1):
                kv.push(keys[j], vals[j], priority=-j)
            for j in range(n_keys):
                kv.pull(keys[j], out=outs[j], priority=-j)

    # warm past compile AND the engine's first-N-rounds key-hash verify
    # (MXNET_KVSTORE_CHECK_STEPS), so the timed loop is steady state
    for _ in range(4):
        round_trip()
    outs[0].wait_to_read()
    kv._barrier()
    t0 = time.perf_counter()
    for _ in range(n_iter):
        round_trip()
    for o in outs:
        o.wait_to_read()
    dt = (time.perf_counter() - t0) / n_iter
    nbytes = elems * 4 * n_keys
    algo_bw = 2 * (n - 1) / max(n, 1) * nbytes / dt / 1e9
    overlap = telemetry.gauge("kvstore.overlap_ratio").value \
        if telemetry.enabled() else None
    return dt, algo_bw, n, overlap


def main():
    parser = argparse.ArgumentParser(description="all-reduce bandwidth")
    parser.add_argument("--sizes", type=str, default="1,4,16,64",
                        help="comma-separated MB sizes")
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--kvstore", action="store_true",
                        help="measure the dist KVStore push/pull path "
                             "(run under tools/launch.py)")
    parser.add_argument("--legacy-allgather", action="store_true",
                        help="with --kvstore: measure the host allgather "
                             "path instead of the compiled collective")
    parser.add_argument("--keys", type=int, default=1,
                        help="with --kvstore: split the payload into N keys "
                             "pushed per-key with priorities (exercises the "
                             "bucket plan + overlap)")
    parser.add_argument("--bucket-mb-sweep", type=str, default="",
                        help="with --kvstore: comma-separated "
                             "MXNET_KVSTORE_BUCKET_MB values; one "
                             "measurement per value")
    parser.add_argument("--json", action="store_true",
                        help="emit one JSON line per size (for bench.py)")
    args = parser.parse_args()

    import json

    if not args.json:
        print("%8s %12s %12s" % ("size_MB", "time_ms", "busbw_GB/s"))
    sweep = ([float(b) for b in args.bucket_mb_sweep.split(",")]
             if args.bucket_mb_sweep else [None])
    for size in (float(s) for s in args.sizes.split(",")):
        for bucket_mb in sweep:
            overlap = None
            if args.kvstore:
                dt, bw, n, overlap = measure_kvstore(
                    size, args.iters, legacy=args.legacy_allgather,
                    n_keys=args.keys, bucket_mb=bucket_mb)
                # under launch.py every worker shares one stdout —
                # interleaved prints corrupt the JSON stream, so only rank 0
                # reports
                if args.json and int(os.environ.get("MXNET_TPU_WORKER_ID",
                                                    "0")):
                    continue
            else:
                dt, bw, n = measure(size, args.iters)
            if args.json:
                rec = {"size_mb": size, "time_ms": round(dt * 1e3, 3),
                       "busbw_gbps": round(bw, 3), "devices": n}
                if bucket_mb is not None:
                    rec["bucket_mb"] = bucket_mb
                if args.keys > 1:
                    rec["keys"] = args.keys
                if overlap is not None:
                    rec["overlap_ratio"] = overlap
                print(json.dumps(rec))
            else:
                extra = "" if bucket_mb is None else \
                    "  bucket=%gMB" % bucket_mb
                print("%8g %12.3f %12.2f   (%d devices)%s"
                      % (size, dt * 1e3, bw, n, extra))


if __name__ == "__main__":
    main()
