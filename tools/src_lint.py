#!/usr/bin/env python
"""Dependency-free source lint: the pure-python fallback for the pinned
ruff gate in tools/ci_check.sh.

The container image may not ship ruff (and CI must not pip install), so
step 2 can no longer be skip-when-absent: this script implements the
subset of the pinned rule set (ruff.toml) that can be checked with the
stdlib alone, and CI runs it whenever `ruff` is not on PATH.  The codes
mirror ruff/pyflakes so a waiver written for one tool works for the
other:

  E9    syntax / indentation errors (compile())
  F401  module-level import bound but never used (__init__.py exempt —
        re-export surface; names listed in __all__ count as used)
  F811  function/class redefinition shadowing an earlier def in the same
        body (@overload / @prop.setter-style decorators exempt)
  E711  comparison to None with == or !=
  E712  comparison to True / False with == or !=

A trailing ``# noqa`` (bare, or with the matching code:
``# noqa: F401``) on the flagged line suppresses the finding, exactly as
ruff treats it.

Usage:  python tools/src_lint.py PATH [PATH ...]
Exit codes: 0 clean, 1 findings, 2 unreadable target.
"""
from __future__ import annotations

import ast
import re
import sys
from pathlib import Path

_NOQA_RE = re.compile(r"#\s*noqa(?::\s*(?P<codes>[A-Z0-9 ,]+))?", re.I)

# decorator names that legitimately redefine a binding (pyflakes' list)
_REDEF_OK = {"overload", "setter", "getter", "deleter", "register"}


def _noqa_map(text):
    """line -> set of suppressed codes (empty set = suppress everything)."""
    out = {}
    for i, line in enumerate(text.splitlines(), 1):
        m = _NOQA_RE.search(line)
        if m:
            codes = m.group("codes")
            out[i] = ({c.strip().upper() for c in codes.split(",") if c.strip()}
                      if codes else set())
    return out


def _suppressed(noqa, line, code):
    codes = noqa.get(line)
    return codes is not None and (not codes or code in codes)


def _module_import_bindings(tree):
    """Module-level imports: bound name -> (line, code-visible label)."""
    bound = {}
    for node in tree.body:
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name.split(".")[0]
                bound[name] = node.lineno
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for alias in node.names:
                if alias.name == "*":
                    continue
                bound[alias.asname or alias.name] = node.lineno
    return bound


def _used_names(tree):
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, (ast.Load,
                                                                ast.Del)):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # `a.b.c` loads the base name `a`
            base = node
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name):
                used.add(base.id)
        elif (isinstance(node, ast.Assign)
              and any(isinstance(t, ast.Name) and t.id == "__all__"
                      for t in node.targets)):
            for elt in ast.walk(node.value):
                if isinstance(elt, ast.Constant) and isinstance(elt.value,
                                                                str):
                    used.add(elt.value)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            # string annotations / TYPE_CHECKING forward refs: any dotted
            # identifier inside a string counts as a (conservative) use
            for tok in re.findall(r"[A-Za-z_][A-Za-z0-9_]*", node.value):
                used.add(tok)
    return used


def _check_f401(path, tree, noqa, findings):
    if path.name == "__init__.py":
        return
    bound = _module_import_bindings(tree)
    if not bound:
        return
    used = _used_names(tree)
    for name, line in sorted(bound.items(), key=lambda kv: kv[1]):
        if name in used or _suppressed(noqa, line, "F401"):
            continue
        findings.append((path, line, "F401",
                         "%r imported but unused" % name))


def _decorator_names(node):
    out = set()
    for dec in getattr(node, "decorator_list", []):
        base = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(base, ast.Attribute):
            out.add(base.attr)
        elif isinstance(base, ast.Name):
            out.add(base.id)
    return out


def _check_f811(path, tree, noqa, findings):
    for scope in ast.walk(tree):
        if not isinstance(scope, (ast.Module, ast.ClassDef)):
            continue
        seen = {}
        for node in scope.body:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                continue
            if _decorator_names(node) & _REDEF_OK:
                seen[node.name] = node.lineno
                continue
            if node.name in seen and not _suppressed(noqa, node.lineno,
                                                     "F811"):
                findings.append((path, node.lineno, "F811",
                                 "redefinition of %r from line %d"
                                 % (node.name, seen[node.name])))
            seen[node.name] = node.lineno


def _check_e711_e712(path, tree, noqa, findings):
    for node in ast.walk(tree):
        if not isinstance(node, ast.Compare):
            continue
        for op, comp in zip(node.ops, node.comparators):
            if not isinstance(op, (ast.Eq, ast.NotEq)):
                continue
            if isinstance(comp, ast.Constant) and comp.value is None:
                code, what = "E711", "None"
            elif isinstance(comp, ast.Constant) and isinstance(comp.value,
                                                               bool):
                code, what = "E712", repr(comp.value)
            else:
                continue
            if _suppressed(noqa, node.lineno, code):
                continue
            fix = "is" if isinstance(op, ast.Eq) else "is not"
            findings.append((path, node.lineno, code,
                             "comparison to %s should be `%s %s`"
                             % (what, fix, what)))


def _iter_py_files(targets):
    for raw in targets:
        p = Path(raw)
        if not p.exists():
            raise OSError("no such file or directory: %s" % raw)
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            yield p
        else:
            # extensionless launcher scripts (tools/graphlint, tools/mxtrace)
            with open(p, "rb") as f:
                if b"python" in f.readline():
                    yield p


def lint_paths(targets):
    findings = []
    for path in _iter_py_files(targets):
        try:
            text = path.read_text()
        except (OSError, UnicodeDecodeError) as exc:
            findings.append((path, 0, "E902", str(exc)))
            continue
        try:
            tree = ast.parse(text, filename=str(path))
        except SyntaxError as exc:
            findings.append((path, exc.lineno or 0, "E999",
                             "syntax error: %s" % exc.msg))
            continue
        noqa = _noqa_map(text)
        _check_f401(path, tree, noqa, findings)
        _check_f811(path, tree, noqa, findings)
        _check_e711_e712(path, tree, noqa, findings)
    return findings


def main(argv=None):
    args = list(sys.argv[1:] if argv is None else argv)
    if not args or "-h" in args or "--help" in args:
        print(__doc__)
        return 0 if args else 2
    try:
        findings = lint_paths(args)
    except OSError as exc:
        print("src_lint: %s" % exc, file=sys.stderr)
        return 2
    for path, line, code, msg in findings:
        print("%s:%d: %s %s" % (path, line, code, msg))
    if findings:
        print("src_lint: %d finding(s)" % len(findings), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
