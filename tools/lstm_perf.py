"""LSTM perf lab: the PTB-config bucketed-LSTM step under the roofline
(docs/PERF.md §6; VERDICT r4 weak #5 — "LSTM 329k tokens/s is reported
without a roofline").

Measures the BASELINE config-3 step (2x200 LSTM, embed 200, vocab 10k,
batch 32, seq 60) and prints the measured tokens/s against the analytic
ceiling decomposition:

- projection GEMM: (B*T, H) x (H, V) fwd + 2x bwd — large, MXU-efficient;
- hoisted input-gate GEMM: (T*B, I) x (I, 4H) per layer (out-of-scan after
  the round-5 hoist);
- sequential recurrence: T steps of (B, H) x (H, 4H) per layer — small
  matmuls, latency-bound, the irreducible serial chain;
- scan/loop overhead: T iterations of XLA while-loop bookkeeping.

    python tools/lstm_perf.py [--profile DIR] [--cost] [--seq 60] ...
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--seq", type=int, default=60)
    ap.add_argument("--hidden", type=int, default=200)
    ap.add_argument("--embed", type=int, default=200)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=10000)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--compute-dtype", default="bfloat16")
    ap.add_argument("--profile", default=None, help="capture jax trace to DIR")
    ap.add_argument("--cost", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mxnet_tpu import models, parallel
    from mxnet_tpu.device_info import bf16_peak_flops

    dev = jax.devices()[0]
    mesh = parallel.make_mesh((1,), axis_names=("data",), devices=[dev])
    B, T, H, E, L, V = (args.batch, args.seq, args.hidden, args.embed,
                        args.layers, args.vocab)
    net = models.get_symbol("lstm", num_classes=V, num_embed=E, num_hidden=H,
                            num_layers=L, seq_len=T, batch_size=B)
    trainer = parallel.SPMDTrainer(
        net, mesh, optimizer="sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
        data_names=("data", "lstm_init_h", "lstm_init_c"),
        label_names=("softmax_label",),
        compute_dtype=args.compute_dtype or None)
    shapes = {"data": (B, T), "lstm_init_h": (L, B, H),
              "lstm_init_c": (L, B, H)}
    trainer.init_params(shapes, {"softmax_label": (B, T)}, seed=0)
    rs = np.random.RandomState(0)
    place = lambda name, arr: jax.device_put(
        arr, trainer.rules.named(trainer.rules.batch_spec(arr.shape)))
    data = {"data": place("data", rs.randint(1, V, (B, T)).astype("float32")),
            "lstm_init_h": place("h", np.zeros((L, B, H), "float32")),
            "lstm_init_c": place("c", np.zeros((L, B, H), "float32"))}
    y = place("y", rs.randint(1, V, (B, T)).astype("float32"))

    def sync(o):
        return np.asarray(jnp.sum(o[0].astype(jnp.float32)))

    for _ in range(3):
        outs = trainer.step(data, {"softmax_label": y})
    sync(outs)
    if args.profile:
        jax.profiler.start_trace(args.profile)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        outs = trainer.step(data, {"softmax_label": y})
    sync(outs)
    dt = time.perf_counter() - t0
    if args.profile:
        jax.profiler.stop_trace()
    step_s = dt / args.steps
    tokens_s = B * T / step_s

    # ---- analytic decomposition (FLOPs; fwd x3 for training) -------------
    gate_w = 4 * H
    proj_flops = 3 * 2.0 * B * T * H * V               # lm head
    in_gemm_flops = 3 * 2.0 * T * B * E * gate_w * L   # hoisted, batched
    rec_flops = 3 * 2.0 * T * B * H * gate_w * L       # sequential chain
    embed_bytes = B * T * E * 2                         # gather, bf16
    peak = bf16_peak_flops(dev.device_kind) or 197e12
    # efficiency assumptions: the projection runs near matmul peak (74%
    # measured for big GEMMs, docs/PERF.md §0); the recurrence's (32,200)
    # matmuls fill 32/128 MXU rows -> <=25% ceiling; while-loop overhead
    # ~2us/iteration measured on v5e (fused step dispatch)
    t_proj = proj_flops / (0.74 * peak)
    t_in = in_gemm_flops / (0.5 * peak)
    t_rec = rec_flops / (0.25 * peak * (B / 128 if B < 128 else 1.0))
    t_loop = T * (2 * L + 2) * 2e-6
    ceiling_s = t_proj + t_in + t_rec + t_loop
    out = {
        "config": "b%d_seq%d_%dx%d_v%d" % (B, T, L, H, V),
        "device": dev.device_kind,
        "step_ms": round(step_s * 1e3, 3),
        "tokens_per_s": round(tokens_s, 1),
        "ceiling_tokens_per_s": round(B * T / ceiling_s, 1),
        "pct_of_ceiling": round(100 * (B * T / ceiling_s and
                                       tokens_s / (B * T / ceiling_s)), 1),
        "ceiling_ms_breakdown": {
            "projection_gemm": round(t_proj * 1e3, 3),
            "input_gate_gemm": round(t_in * 1e3, 3),
            "sequential_recurrence": round(t_rec * 1e3, 3),
            "loop_overhead": round(t_loop * 1e3, 3),
        },
    }
    if args.cost:
        cost = trainer.cost_analysis(data, {"softmax_label": y})
        gb = cost.get("bytes accessed", 0.0) / 1e9
        out["xla_gb_accessed"] = round(gb, 3)
        out["xla_tflops"] = round(cost.get("flops", 0.0) / 1e12, 4)
        out["hbm_gbps_achieved"] = round(gb / step_s, 1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
