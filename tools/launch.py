#!/usr/bin/env python
"""Launch a multi-worker training job.

Counterpart of the reference's tools/launch.py (dmlc-core tracker submitting
scheduler+server+worker processes over ssh/mpi/sge/yarn). The TPU-native job
has no scheduler or server roles — every worker runs the same SPMD program —
so launching means: start N copies of the command with the ``MXNET_TPU_*``
coordination env (see mxnet_tpu/dist.py), worker 0 hosting the coordination
service.

Launchers:
  * ``local`` — N processes on this host (the reference's ``--launcher local``
    used by tests/nightly/dist_sync_kvstore.py). With ``--cpu-devices K`` each
    worker gets K virtual CPU devices (testing without TPU hardware).
  * ``ssh``  — one worker per host from --hostfile via ssh (reference's ssh
    tracker); workers see the coordinator via this host's address.

On real TPU pods the platform's own job scheduler (GKE/ICI runtime) starts
one process per host and this launcher is unnecessary — pass the coordinator
env directly.

Example:
  python tools/launch.py -n 4 --launcher local --cpu-devices 2 \
      python tests/nightly/dist_sync_kvstore.py
"""
import argparse
import os
import signal
import socket
import subprocess
import sys


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _worker_env(base, args, coordinator, rank):
    env = dict(base)
    env["MXNET_TPU_COORDINATOR"] = coordinator
    env["MXNET_TPU_NUM_WORKERS"] = str(args.num_workers)
    env["MXNET_TPU_WORKER_ID"] = str(rank)
    if args.cpu_devices:
        flags = env.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=%d" % args.cpu_devices
        ).strip()
        env["MXNET_DEFAULT_CONTEXT"] = "cpu"
    return env


def _wait_all(procs):
    """Wait for every worker; if one fails, terminate the rest instead of
    blocking forever on survivors stuck in collective init."""
    import time

    code = 0
    live = list(procs)
    while live:
        for p in list(live):
            rc = p.poll()
            if rc is None:
                continue
            live.remove(p)
            if rc != 0:
                code = code or rc
                for q in live:
                    if q.poll() is None:
                        q.send_signal(signal.SIGTERM)
        time.sleep(0.2)
    return code


def launch_local(args, command):
    coordinator = "127.0.0.1:%d" % _free_port()
    procs = []
    try:
        for rank in range(args.num_workers):
            env = _worker_env(os.environ, args, coordinator, rank)
            procs.append(subprocess.Popen(command, env=env))
        return _wait_all(procs)
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)


def launch_ssh(args, command):
    with open(args.hostfile) as f:
        hosts = [h.strip() for h in f if h.strip()]
    assert len(hosts) >= args.num_workers, "hostfile has fewer hosts than -n"
    # worker 0 hosts the coordination service, so advertise ITS address; the
    # port cannot be probed remotely — use a fixed high port (reference's
    # tracker likewise picks a port for the root role)
    coordinator = "%s:%d" % (hosts[0], args.port)
    procs = []
    try:
        for rank in range(args.num_workers):
            import shlex

            env = _worker_env({}, args, coordinator, rank)
            envstr = " ".join("%s=%s" % (k, shlex.quote(v)) for k, v in env.items())
            remote = "cd %s && env %s %s" % (
                shlex.quote(os.getcwd()), envstr,
                " ".join(shlex.quote(w) for w in command))
            procs.append(subprocess.Popen(["ssh", "-o",
                                           "StrictHostKeyChecking=no",
                                           hosts[rank], remote]))
        return _wait_all(procs)
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)


def main():
    parser = argparse.ArgumentParser(
        description="Launch a multi-worker mxnet_tpu job",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-n", "--num-workers", type=int, required=True,
                        help="number of worker processes")
    parser.add_argument("--launcher", type=str, default="local",
                        choices=["local", "ssh"])
    parser.add_argument("--hostfile", type=str, default=None,
                        help="(ssh) file with one host per line")
    parser.add_argument("--port", type=int, default=29400,
                        help="(ssh) coordination-service port on the first host")
    parser.add_argument("--cpu-devices", type=int, default=0,
                        help="give each worker this many virtual CPU devices "
                             "(multi-host testing without TPU hardware)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="the training command to run on every worker")
    args = parser.parse_args()
    if not args.command:
        parser.error("no command given")

    if args.launcher == "local":
        sys.exit(launch_local(args, args.command))
    sys.exit(launch_ssh(args, args.command))


if __name__ == "__main__":
    main()
