#!/usr/bin/env python
"""Launch a multi-worker training job.

Counterpart of the reference's tools/launch.py (dmlc-core tracker submitting
scheduler+server+worker processes over ssh/mpi/sge/yarn). The TPU-native job
has no scheduler or server roles — every worker runs the same SPMD program —
so launching means: start N copies of the command with the ``MXNET_TPU_*``
coordination env (see mxnet_tpu/dist.py), worker 0 hosting the coordination
service.

Launchers:
  * ``local`` — N processes on this host (the reference's ``--launcher local``
    used by tests/nightly/dist_sync_kvstore.py). With ``--cpu-devices K`` each
    worker gets K virtual CPU devices (testing without TPU hardware).
  * ``ssh``  — one worker per host from --hostfile via ssh (reference's ssh
    tracker); workers see the coordinator via this host's address.

On real TPU pods the platform's own job scheduler (GKE/ICI runtime) starts
one process per host and this launcher is unnecessary — pass the coordinator
env directly.

Example:
  python tools/launch.py -n 4 --launcher local --cpu-devices 2 \
      python tests/nightly/dist_sync_kvstore.py
"""
import argparse
import os
import signal
import socket
import subprocess
import sys


def _free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _worker_env(base, args, coordinator, rank, hb_dir=None):
    env = dict(base)
    env["MXNET_TPU_COORDINATOR"] = coordinator
    env["MXNET_TPU_NUM_WORKERS"] = str(args.num_workers)
    env["MXNET_TPU_WORKER_ID"] = str(rank)
    if getattr(args, "elastic", False):
        env["MXNET_ELASTIC"] = "1"
    if hb_dir:
        env["MXNET_TPU_HEARTBEAT_DIR"] = hb_dir
        if args.heartbeat_interval is not None:
            env["MXNET_TPU_HEARTBEAT_INTERVAL"] = str(args.heartbeat_interval)
        else:
            env.setdefault("MXNET_TPU_HEARTBEAT_INTERVAL", "5")
    if args.cpu_devices:
        flags = env.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=%d" % args.cpu_devices
        ).strip()
        env["MXNET_DEFAULT_CONTEXT"] = "cpu"
    return env


def _stale_worker(hb_dir, ranks, timeout):
    """Rank (among the still-LIVE ranks) whose heartbeat went stale, else
    None. Exited workers are excluded — a finished worker's frozen file is
    not a failure."""
    import time

    now = time.time()
    for r in ranks:
        path = os.path.join(hb_dir, "worker-%d" % r)
        try:
            if now - os.path.getmtime(path) > timeout:
                return r
        except OSError:
            pass  # not written yet: startup, covered by process polling
    return None


def _terminate(procs, grace=10):
    """SIGTERM, wait up to ``grace`` seconds, then SIGKILL — a worker
    blocked in a dead collective cannot run a SIGTERM handler."""
    import time

    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    deadline = time.time() + grace
    while any(p.poll() is None for p in procs) and time.time() < deadline:
        time.sleep(0.2)
    for p in procs:
        if p.poll() is None:
            p.kill()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass


def _wait_all(procs, hb_dir=None, hb_timeout=0, elastic=False):
    """Wait for every worker. Failure detection (reference: ps-lite
    heartbeats behind KVStore::get_num_dead_node, kvstore.h:234-244 /
    kvstore_dist.h:158-167): a nonzero exit, OR a stale heartbeat from a
    live worker process (catches frozen/SIGSTOPped/OOM-thrashed workers
    whose runtime stopped beating — NOT a live-but-deadlocked collective,
    whose heartbeat thread keeps running; that case needs job-level
    timeouts), terminates the whole job with SIGTERM-then-SIGKILL — the
    caller decides whether to restart from the last checkpoint.

    ``elastic=True`` (docs/FAULT_TOLERANCE.md) inverts the policy for
    non-coordinator workers: their death or stale heartbeat is the
    SURVIVORS' business (pause → re-form → resume), so the launcher keeps
    waiting instead of tearing the job down. Only worker 0's failure —
    its process hosts the coordination service, nothing survives it — or
    every worker failing still kills the job."""
    import time

    code = 0
    live = dict(enumerate(procs))  # rank -> proc (Popen order is rank order)
    failed = False
    any_ok = False
    while live:
        for r, p in list(live.items()):
            rc = p.poll()
            if rc is None:
                continue
            del live[r]
            if rc == 0:
                any_ok = True
            else:
                if elastic and r != 0:
                    sys.stderr.write(
                        "launch: worker %d exited rc=%d — elastic job, "
                        "survivors re-form without it\n" % (r, rc))
                    # forgiven below iff anyone succeeds AND the job never
                    # hit a terminal failure (coordinator death)
                    code = code or rc
                    continue
                code = code or rc
                failed = True
        if not failed and hb_dir and hb_timeout > 0 and live:
            stale = _stale_worker(hb_dir, sorted(live), hb_timeout)
            if stale is not None and (not elastic or stale == 0):
                sys.stderr.write(
                    "launch: worker %d heartbeat stale > %gs — declaring the "
                    "job dead\n" % (stale, hb_timeout))
                code = 124
                failed = True
            elif stale is not None:
                # elastic: the survivors already class this worker dead
                # (same staleness signal) and re-form without it — but its
                # frozen PROCESS must still be reaped or `live` never
                # empties and the launcher hangs after the job finishes
                sys.stderr.write(
                    "launch: worker %d heartbeat stale > %gs — elastic "
                    "job, reaping the frozen process; survivors re-form "
                    "without it\n" % (stale, hb_timeout))
                _terminate([live[stale]])
        if failed and live:
            _terminate(list(live.values()))
        time.sleep(0.2)
    if elastic and any_ok and not failed:
        # the job succeeded if the final generation finished, even though
        # evicted workers exited nonzero along the way — but a TERMINAL
        # failure (coordinator death, stale-coordinator watchdog) stays a
        # failure no matter how many workers exited clean before it
        return 0
    return code


def launch_local(args, command):
    """Run the job; on worker death/freeze, tear down and relaunch up to
    ``--auto-restart`` times. Training scripts resume from their last
    checkpoint (model.find_last_checkpoint / fit(begin_epoch=...))."""
    import shutil
    import tempfile

    attempts = 0
    while True:
        coordinator = "127.0.0.1:%d" % _free_port()
        # elastic jobs need the heartbeat dir unconditionally: it is the
        # workers' OWN failure detector, not just the launcher's
        hb_dir = tempfile.mkdtemp(prefix="mxtpu-hb-") \
            if (args.heartbeat_timeout > 0 or args.elastic) else None
        procs = []
        try:
            for rank in range(args.num_workers):
                env = _worker_env(os.environ, args, coordinator, rank, hb_dir)
                procs.append(subprocess.Popen(command, env=env))
            code = _wait_all(procs, hb_dir, args.heartbeat_timeout,
                             elastic=args.elastic)
        finally:
            # every old worker must be DEAD before cleanup/relaunch: a
            # straggler could race the next attempt's checkpoint resume (and
            # its beat thread would recreate hb_dir after rmtree)
            _terminate(procs)
            if hb_dir:
                shutil.rmtree(hb_dir, ignore_errors=True)
        if code == 0 or attempts >= args.auto_restart:
            return code
        attempts += 1
        sys.stderr.write(
            "launch: job failed (rc=%d) — restart %d/%d from last "
            "checkpoint\n" % (code, attempts, args.auto_restart))


def launch_ssh(args, command):
    with open(args.hostfile) as f:
        hosts = [h.strip() for h in f if h.strip()]
    assert len(hosts) >= args.num_workers, "hostfile has fewer hosts than -n"
    # worker 0 hosts the coordination service, so advertise ITS address; the
    # port cannot be probed remotely — use a fixed high port (reference's
    # tracker likewise picks a port for the root role)
    coordinator = "%s:%d" % (hosts[0], args.port)
    procs = []
    try:
        for rank in range(args.num_workers):
            import shlex

            env = _worker_env({}, args, coordinator, rank)
            envstr = " ".join("%s=%s" % (k, shlex.quote(v)) for k, v in env.items())
            remote = "cd %s && env %s %s" % (
                shlex.quote(os.getcwd()), envstr,
                " ".join(shlex.quote(w) for w in command))
            procs.append(subprocess.Popen(["ssh", "-o",
                                           "StrictHostKeyChecking=no",
                                           hosts[rank], remote]))
        return _wait_all(procs)
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)


def main():
    parser = argparse.ArgumentParser(
        description="Launch a multi-worker mxnet_tpu job",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-n", "--num-workers", type=int, required=True,
                        help="number of worker processes")
    parser.add_argument("--launcher", type=str, default="local",
                        choices=["local", "ssh"])
    parser.add_argument("--hostfile", type=str, default=None,
                        help="(ssh) file with one host per line")
    parser.add_argument("--port", type=int, default=29400,
                        help="(ssh) coordination-service port on the first host")
    parser.add_argument("--cpu-devices", type=int, default=0,
                        help="give each worker this many virtual CPU devices "
                             "(multi-host testing without TPU hardware)")
    parser.add_argument("--elastic", action="store_true",
                        help="(local) run the job elastically "
                             "(MXNET_ELASTIC=1): a non-coordinator worker's "
                             "death pauses and re-forms the job over the "
                             "survivors instead of killing it "
                             "(docs/FAULT_TOLERANCE.md)")
    parser.add_argument("--auto-restart", type=int, default=0,
                        help="(local) relaunch the whole job up to this many "
                             "times after a worker dies or hangs; workers "
                             "resume from their last checkpoint")
    parser.add_argument("--heartbeat-timeout", type=float, default=60.0,
                        help="(local) declare the job dead when a LIVE "
                             "worker's heartbeat file is older than this "
                             "many seconds — catches frozen/stopped worker "
                             "processes (0 disables)")
    parser.add_argument("--heartbeat-interval", type=float, default=None,
                        help="how often workers touch their heartbeat file "
                             "(default: inherit MXNET_TPU_HEARTBEAT_INTERVAL "
                             "from the environment, else 5)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="the training command to run on every worker")
    args = parser.parse_args()
    if not args.command:
        parser.error("no command given")

    if args.launcher == "local":
        sys.exit(launch_local(args, args.command))
    sys.exit(launch_ssh(args, args.command))


if __name__ == "__main__":
    main()
