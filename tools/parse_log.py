#!/usr/bin/env python
"""Parse a training log into a per-epoch table.

Counterpart of the reference's tools/parse_log.py: reads the logging format
emitted by fit.py/Speedometer and prints markdown with train/val accuracy and
mean speed per epoch.

    python tools/parse_log.py train.log [--format markdown|csv]
"""
import argparse
import re
import sys
from collections import defaultdict


EPOCH_METRIC = re.compile(
    r"Epoch\[(\d+)\] (Train|Validation)-([\w-]+)=([\d.eE+-]+)")
SPEED = re.compile(r"Epoch\[(\d+)\] Batch \[\d+\]\s+Speed: ([\d.]+) samples/sec")
TIME = re.compile(r"Epoch\[(\d+)\] Time cost=([\d.]+)")


def parse(fname):
    rows = defaultdict(dict)
    speeds = defaultdict(list)
    with open(fname) as f:
        for line in f:
            m = EPOCH_METRIC.search(line)
            if m:
                ep, phase, metric, val = m.groups()
                rows[int(ep)]["%s-%s" % (phase.lower(), metric)] = float(val)
            m = SPEED.search(line)
            if m:
                speeds[int(m.group(1))].append(float(m.group(2)))
            m = TIME.search(line)
            if m:
                rows[int(m.group(1))]["time"] = float(m.group(2))
    for ep, sp in speeds.items():
        rows[ep]["speed"] = sum(sp) / len(sp)
    return rows


def main():
    parser = argparse.ArgumentParser(description="parse a training log")
    parser.add_argument("logfile")
    parser.add_argument("--format", choices=["markdown", "csv"], default="markdown")
    args = parser.parse_args()

    rows = parse(args.logfile)
    if not rows:
        print("no epochs found in %s" % args.logfile, file=sys.stderr)
        sys.exit(1)
    cols = sorted({k for r in rows.values() for k in r})
    if args.format == "markdown":
        print("| epoch | " + " | ".join(cols) + " |")
        print("| --- | " + " | ".join("---" for _ in cols) + " |")
        for ep in sorted(rows):
            cells = ["%.6g" % rows[ep][c] if c in rows[ep] else "" for c in cols]
            print("| %d | " % ep + " | ".join(cells) + " |")
    else:
        print("epoch," + ",".join(cols))
        for ep in sorted(rows):
            print("%d," % ep + ",".join(
                "%.6g" % rows[ep][c] if c in rows[ep] else "" for c in cols))


if __name__ == "__main__":
    main()
