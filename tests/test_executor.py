"""Executor tests: bind/simple_bind, fwd/bwd numerics, grad_req, aux states.

Modeled on the reference's tests/python/unittest/test_executor.py
(bind correctness against numpy, grad accumulation, reshape)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _rand(*shape):
    return np.random.RandomState(0).uniform(-1, 1, shape).astype(np.float32)


def test_bind_add_mul_backward():
    rng = np.random.RandomState(3)
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    c = a * b + a
    an, bn = rng.uniform(-1, 1, (4, 5)).astype("f"), rng.uniform(-1, 1, (4, 5)).astype("f")
    ga = mx.nd.zeros((4, 5))
    gb = mx.nd.zeros((4, 5))
    ex = c.bind(
        mx.cpu(),
        {"a": mx.nd.array(an), "b": mx.nd.array(bn)},
        args_grad={"a": ga, "b": gb},
    )
    out = ex.forward(is_train=True)
    assert np.allclose(out[0].asnumpy(), an * bn + an, atol=1e-6)
    head = np.ones((4, 5), dtype="f") * 2.0
    ex.backward(mx.nd.array(head))
    assert np.allclose(ga.asnumpy(), head * (bn + 1), atol=1e-6)
    assert np.allclose(gb.asnumpy(), head * an, atol=1e-6)


def test_grad_req_add_accumulates():
    a = mx.sym.Variable("a")
    out = a * 3.0
    ga = mx.nd.zeros((2, 2))
    ex = out.bind(mx.cpu(), {"a": mx.nd.ones((2, 2))}, args_grad={"a": ga}, grad_req="add")
    ex.forward(is_train=True)
    ex.backward(mx.nd.ones((2, 2)))
    ex.backward(mx.nd.ones((2, 2)))
    assert np.allclose(ga.asnumpy(), 6.0 * np.ones((2, 2)))


def test_grad_req_null_skips():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    out = a * b
    ga = mx.nd.zeros((2,))
    gb = mx.nd.zeros((2,))
    ex = out.bind(
        mx.cpu(),
        {"a": mx.nd.ones((2,)), "b": mx.nd.ones((2,))},
        args_grad={"a": ga, "b": gb},
        grad_req={"a": "write", "b": "null"},
    )
    ex.forward(is_train=True)
    ex.backward(mx.nd.ones((2,)))
    assert np.allclose(ga.asnumpy(), 1.0)
    assert np.allclose(gb.asnumpy(), 0.0)


def test_simple_bind_allocates_and_infers():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc")
    sm = mx.sym.SoftmaxOutput(data=fc, name="softmax")
    ex = sm.simple_bind(ctx=mx.cpu(), data=(16, 30))
    assert ex.arg_dict["fc_weight"].shape == (8, 30)
    assert ex.arg_dict["softmax_label"].shape == (16,)
    assert ex.grad_dict["fc_weight"].shape == (8, 30)
    out = ex.forward(is_train=False)
    assert out[0].shape == (16, 8)
    assert np.allclose(out[0].asnumpy().sum(axis=1), 1.0, atol=1e-5)


def test_softmax_output_backward_semantics():
    """SoftmaxOutput backward = (p - onehot) regardless of head gradient."""
    data = mx.sym.Variable("data")
    sm = mx.sym.SoftmaxOutput(data=data, name="softmax")
    x = _rand(3, 4)
    label = np.array([0, 1, 3], dtype="f")
    gd = mx.nd.zeros((3, 4))
    ex = sm.bind(
        mx.cpu(),
        {"data": mx.nd.array(x), "softmax_label": mx.nd.array(label)},
        args_grad={"data": gd},
    )
    out = ex.forward(is_train=True)
    ex.backward()
    p = np.exp(x) / np.exp(x).sum(axis=1, keepdims=True)
    onehot = np.eye(4, dtype="f")[label.astype(int)]
    assert np.allclose(out[0].asnumpy(), p, atol=1e-5)
    assert np.allclose(gd.asnumpy(), p - onehot, atol=1e-5)


def test_batchnorm_aux_updated_only_in_forward_train():
    data = mx.sym.Variable("data")
    bn = mx.sym.BatchNorm(data=data, momentum=0.5, name="bn")
    ex = bn.simple_bind(ctx=mx.cpu(), data=(8, 3))
    ex.arg_dict["data"][:] = _rand(8, 3) + 2.0
    ex.arg_dict["bn_gamma"][:] = 1.0
    mm0 = ex.aux_dict["bn_moving_mean"].asnumpy().copy()
    ex.forward(is_train=False)
    assert np.allclose(ex.aux_dict["bn_moving_mean"].asnumpy(), mm0)
    ex.forward(is_train=True)
    mm1 = ex.aux_dict["bn_moving_mean"].asnumpy().copy()
    assert not np.allclose(mm1, mm0)
    batch_mean = ex.arg_dict["data"].asnumpy().mean(axis=0)
    assert np.allclose(mm1, 0.5 * mm0 + 0.5 * batch_mean, atol=1e-5)
    # backward must not touch aux again
    ex.backward()
    assert np.allclose(ex.aux_dict["bn_moving_mean"].asnumpy(), mm1)


def test_forward_backward_fused_matches_separate():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=4, name="fc")
    sm = mx.sym.SoftmaxOutput(data=fc, name="softmax")
    x = _rand(6, 5)
    lab = np.array([0, 1, 2, 3, 0, 1], dtype="f")

    def build():
        ex = sm.simple_bind(ctx=mx.cpu(), data=(6, 5))
        ex.arg_dict["data"][:] = x
        ex.arg_dict["fc_weight"][:] = _rand(4, 5)
        ex.arg_dict["softmax_label"][:] = lab
        return ex

    e1, e2 = build(), build()
    e1.forward(is_train=True)
    e1.backward()
    e2.forward_backward()
    assert np.allclose(e1.outputs[0].asnumpy(), e2.outputs[0].asnumpy(), atol=1e-6)
    assert np.allclose(
        e1.grad_dict["fc_weight"].asnumpy(), e2.grad_dict["fc_weight"].asnumpy(), atol=1e-6
    )


def test_executor_forward_kwargs_update():
    a = mx.sym.Variable("a")
    out = a * 2.0
    ex = out.bind(mx.cpu(), {"a": mx.nd.zeros((2, 2))})
    res = ex.forward(a=np.full((2, 2), 3.0, dtype="f"))
    assert np.allclose(res[0].asnumpy(), 6.0)


def test_executor_reshape():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=4, name="fc")
    ex = fc.simple_bind(ctx=mx.cpu(), data=(8, 10))
    w = ex.arg_dict["fc_weight"]
    w[:] = _rand(4, 10)
    ex2 = ex.reshape(data=(2, 10))
    assert ex2.arg_dict["data"].shape == (2, 10)
    # weight shape unchanged → same array shared
    assert ex2.arg_dict["fc_weight"].shape == (4, 10)
    x = _rand(2, 10)
    ex2.arg_dict["data"][:] = x
    out = ex2.forward()
    assert np.allclose(out[0].asnumpy(), x @ w.asnumpy().T, atol=1e-5)


def test_executor_copy_params_from():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=3, no_bias=True, name="fc")
    ex = fc.simple_bind(ctx=mx.cpu(), data=(2, 4))
    w = _rand(3, 4)
    ex.copy_params_from({"fc_weight": mx.nd.array(w)})
    assert np.allclose(ex.arg_dict["fc_weight"].asnumpy(), w)
    with pytest.raises(mx.MXNetError):
        ex.copy_params_from({"bogus": mx.nd.zeros((1,))})


def test_dropout_rng_consistent_between_fwd_bwd():
    data = mx.sym.Variable("data")
    d = mx.sym.Dropout(data=data, p=0.5, name="drop")
    x = np.ones((100,), dtype="f")
    gd = mx.nd.zeros((100,))
    ex = d.bind(mx.cpu(), {"data": mx.nd.array(x)}, args_grad={"data": gd})
    out = ex.forward(is_train=True)
    mask_fwd = out[0].asnumpy() != 0
    ex.backward(mx.nd.ones((100,)))
    mask_bwd = gd.asnumpy() != 0
    assert (mask_fwd == mask_bwd).all()


def test_multi_output_executor():
    data = mx.sym.Variable("data")
    parts = mx.sym.SliceChannel(data=data, num_outputs=2, axis=1, name="sl")
    g = mx.sym.Group([parts[0] * 2.0, parts[1] + 1.0])
    x = _rand(3, 4)
    ex = g.bind(mx.cpu(), {"data": mx.nd.array(x)})
    outs = ex.forward()
    assert len(outs) == 2
    assert np.allclose(outs[0].asnumpy(), x[:, :2] * 2.0, atol=1e-6)
    assert np.allclose(outs[1].asnumpy(), x[:, 2:] + 1.0, atol=1e-6)


def test_rnn_symbol_bind():
    data = mx.sym.Variable("data")
    rnn = mx.sym.RNN(
        data=data, state_size=6, num_layers=1, mode="lstm", name="lstm", state_outputs=True
    )
    arg_shapes, out_shapes, _ = rnn.infer_shape(data=(7, 2, 5))
    d = dict(zip(rnn.list_arguments(), arg_shapes))
    assert d["lstm_state"] == (1, 2, 6)
    assert out_shapes[0] == (7, 2, 6)
    ex = rnn.simple_bind(ctx=mx.cpu(), data=(7, 2, 5))
    ex.arg_dict["data"][:] = _rand(7, 2, 5)
    ex.arg_dict["lstm_parameters"][:] = _rand(*d["lstm_parameters"]) * 0.1
    outs = ex.forward(is_train=True)
    assert outs[0].shape == (7, 2, 6)
    assert outs[1].shape == (1, 2, 6)


def test_backward_does_not_recompute_forward():
    """forward(is_train=True) + backward() must run the forward host-visible
    computation exactly once (the cached-vjp path; previously backward
    re-ran the fused fwd+bwd, silently doubling forward cost). Observed via
    a CustomOp whose forward increments a host counter."""
    from mxnet_tpu import operator as op

    counters = {"fwd": 0}

    @op.register("count_fwd_sigmoid")
    class CountProp(op.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=True)

        def create_operator(self, ctx, in_shapes, in_dtypes):
            class CountOp(op.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    counters["fwd"] += 1
                    x = in_data[0].asnumpy()
                    self.assign(out_data[0], req[0], 1.0 / (1.0 + np.exp(-x)))

                def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
                    y = out_data[0].asnumpy()
                    self.assign(in_grad[0], req[0],
                                out_grad[0].asnumpy() * y * (1.0 - y))

            return CountOp()

    data = mx.sym.Variable("data")
    net = mx.sym.Custom(data=mx.sym.FullyConnected(data, num_hidden=4,
                                                   name="fc"),
                        op_type="count_fwd_sigmoid", name="sig")
    exe = net.simple_bind(ctx=mx.cpu(), data=(2, 3))
    rs = np.random.RandomState(0)
    for name, arr in exe.arg_dict.items():
        arr[:] = rs.rand(*arr.shape).astype("float32")
    counters["fwd"] = 0
    exe.forward(is_train=True)
    assert counters["fwd"] == 1
    exe.backward(out_grads=[mx.nd.ones((2, 4))])
    assert counters["fwd"] == 1, (
        "backward re-ran the forward %d extra time(s)" % (counters["fwd"] - 1))
    g = exe.grad_dict["fc_weight"].asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0
