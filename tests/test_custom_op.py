"""Custom op API (port of the reference's test_operator.py custom-op tests:
a python Sigmoid with hand-written backward, used imperatively, symbolically,
and under autograd)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import operator as op
from mxnet_tpu import symbol as sym


@op.register("test_sigmoid")
class SigmoidProp(op.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return SigmoidOp()


class SigmoidOp(op.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        self.assign(out_data[0], req[0], 1.0 / (1.0 + np.exp(-x)))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0].asnumpy()
        g = out_grad[0].asnumpy()
        self.assign(in_grad[0], req[0], g * y * (1.0 - y))


@op.register("test_scale2")
class Scale2Prop(op.CustomOpProp):
    def __init__(self, factor="2.0"):
        super().__init__(need_top_grad=True)
        self.factor = float(factor)

    def create_operator(self, ctx, in_shapes, in_dtypes):
        prop = self

        class ScaleOp(op.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                self.assign(out_data[0], req[0], in_data[0].asnumpy() * prop.factor)

            def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
                self.assign(in_grad[0], req[0], out_grad[0].asnumpy() * prop.factor)

        return ScaleOp()


def test_custom_imperative():
    x = np.random.uniform(-1, 1, (3, 4)).astype("float32")
    out = mx.nd.Custom(mx.nd.array(x), op_type="test_sigmoid").asnumpy()
    np.testing.assert_allclose(out, 1 / (1 + np.exp(-x)), rtol=1e-6)


def test_custom_attr_passthrough():
    x = np.random.uniform(-1, 1, (2, 2)).astype("float32")
    out = mx.nd.Custom(mx.nd.array(x), op_type="test_scale2", factor="3.0").asnumpy()
    np.testing.assert_allclose(out, 3.0 * x, rtol=1e-6)


def test_custom_symbolic_forward_backward():
    from mxnet_tpu import test_utils as tu

    x = np.random.uniform(-1, 1, (3, 3)).astype("float32")
    out = sym.Custom(sym.Variable("data"), op_type="test_sigmoid")
    s = 1 / (1 + np.exp(-x))
    tu.check_symbolic_forward(out, {"data": x}, [s], check_eps=1e-5)
    g = np.full((3, 3), 2.0, "float32")
    tu.check_symbolic_backward(out, {"data": x}, [g],
                               {"data": g * s * (1 - s)}, check_eps=1e-4)


def test_custom_composes_in_graph():
    x = np.random.uniform(-1, 1, (4, 2)).astype("float32")
    d = sym.Variable("data")
    out = sym.sum(sym.Custom(d * 2.0, op_type="test_sigmoid"))
    from mxnet_tpu import test_utils as tu

    tu.check_numeric_gradient(out, {"data": x}, numeric_eps=1e-3, check_eps=2e-2)


def test_custom_under_autograd():
    from mxnet_tpu import autograd as ag

    x = mx.nd.array(np.random.uniform(-1, 1, (2, 3)).astype("float32"))
    grads = ag.grad(lambda a: mx.nd.Custom(a, op_type="test_sigmoid"))(x)
    s = 1 / (1 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(grads[0].asnumpy(), s * (1 - s), rtol=1e-5)
