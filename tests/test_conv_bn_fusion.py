"""Graph-level tests of the conv+BN fusion pass (fusion.py +
ops/pallas_conv_bn.py): a pre-activation bottleneck trained through the
executor must produce identical outputs/gradients/aux updates with the
fusion force-engaged (MXNET_FUSED_CONV_BN=1, Pallas interpret mode on CPU)
as with it disabled (=0). This is the fwd+bwd parity contract the WINS-table
gating relies on."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import fusion


def _bottleneck(nf=16):
    """Pre-activation bottleneck + shortcut conv (models/resnet.py shape):
    exercises prologue folds, 1x1 + 3x3 kernels, residual defer, stats
    reuse across the whole chain."""
    sym = mx.sym
    data = sym.Variable("data")
    bn1 = sym.BatchNorm(data=data, fix_gamma=False, name="bn1")
    act1 = sym.Activation(data=bn1, act_type="relu", name="relu1")
    conv1 = sym.Convolution(data=act1, num_filter=nf // 2, kernel=(1, 1),
                            stride=(1, 1), pad=(0, 0), no_bias=True,
                            name="conv1")
    bn2 = sym.BatchNorm(data=conv1, fix_gamma=False, name="bn2")
    act2 = sym.Activation(data=bn2, act_type="relu", name="relu2")
    conv2 = sym.Convolution(data=act2, num_filter=nf // 2, kernel=(3, 3),
                            stride=(1, 1), pad=(1, 1), no_bias=True,
                            name="conv2")
    bn3 = sym.BatchNorm(data=conv2, fix_gamma=False, name="bn3")
    act3 = sym.Activation(data=bn3, act_type="relu", name="relu3")
    conv3 = sym.Convolution(data=act3, num_filter=nf, kernel=(1, 1),
                            stride=(1, 1), pad=(0, 0), no_bias=True,
                            name="conv3")
    sc = sym.Convolution(data=act1, num_filter=nf, kernel=(1, 1),
                         stride=(1, 1), pad=(0, 0), no_bias=True, name="sc")
    out = conv3 + sc
    pool = sym.Pooling(data=out, kernel=(1, 1), global_pool=True,
                       pool_type="avg", name="pool")
    fc = sym.FullyConnected(data=sym.Flatten(pool), num_hidden=10, name="fc")
    return sym.SoftmaxOutput(data=fc, name="softmax")


def _run(env_value, monkeypatch, seed=7):
    monkeypatch.setenv("MXNET_FUSED_CONV_BN", env_value)
    net = _bottleneck()
    rs = np.random.RandomState(seed)
    B, C, H, W = 4, 8, 8, 8
    ex = net.simple_bind(mx.cpu(), data=(B, C, H, W),
                         softmax_label=(B,), grad_req="write")
    for name, arr in zip(net.list_arguments(), ex.arg_arrays):
        if name == "data":
            arr[:] = rs.uniform(-1, 1, arr.shape).astype("f")
        elif name == "softmax_label":
            arr[:] = rs.randint(0, 10, arr.shape).astype("f")
        elif name.endswith(("_gamma",)):
            arr[:] = rs.uniform(0.5, 1.5, arr.shape).astype("f")
        elif name.endswith(("_beta",)):
            arr[:] = rs.uniform(-0.2, 0.2, arr.shape).astype("f")
        else:
            arr[:] = (rs.uniform(-1, 1, arr.shape) * 0.2).astype("f")
    out = ex.forward(is_train=True)[0].asnumpy()
    ex.backward()
    grads = {n: g.asnumpy() for n, g in zip(net.list_arguments(),
                                            ex.grad_arrays) if g is not None}
    aux = {n: a.asnumpy() for n, a in zip(net.list_auxiliary_states(),
                                          ex.aux_arrays)}
    return out, grads, aux


def test_plan_structure():
    """The planner must find every fold/defer in the bottleneck."""
    net = _bottleneck()
    topo = net._topo()
    plan = fusion.plan(topo)
    by_name = {n.name: plan.get(id(n)) for n in topo if not n.is_variable}
    # bn2/bn3 feed exactly one relu feeding exactly one conv: folded.
    assert by_name["bn2"] == {"kind": "bn", "fold": True}
    assert by_name["bn3"] == {"kind": "bn", "fold": True}
    # bn1 -> relu1 feeds BOTH conv1 and sc — fold still legal (each consumer
    # re-applies the prologue in VMEM)
    assert by_name["bn1"]["fold"] is True
    assert by_name["relu1"] == {"kind": "relu_fold"}
    # exactly one add operand (both are single-consumer eligible convs) is
    # deferred into the add's epilogue; the other runs standalone
    assert (by_name["conv3"]["defer"], by_name["sc"]["defer"]).count(True) == 1
    add_name = [n.name for n in topo if n.op == "elemwise_add"][0]
    assert by_name[add_name]["kind"] == "resadd"


def test_group_output_conv_not_deferred(monkeypatch):
    """Regression (fusion.py residual-defer leak): a fusable conv feeding a
    residual add is a program output too (Group symbol). The planner used
    to defer it — consumers never see graph outputs — so interpret()
    returned the PendingConv marker as a jit output and trace failed under
    MXNET_FUSED_CONV_BN=1. The conv must run standalone instead, and the
    Group must produce the same numbers as the unfused lowering."""
    sym = mx.sym

    def _net():
        data = sym.Variable("data")
        bn = sym.BatchNorm(data=data, fix_gamma=False, name="bn")
        act = sym.Activation(data=bn, act_type="relu", name="relu")
        conv = sym.Convolution(data=act, num_filter=8, kernel=(1, 1),
                               stride=(1, 1), pad=(0, 0), no_bias=True,
                               name="conv")
        sc = sym.Convolution(data=act, num_filter=8, kernel=(1, 1),
                             stride=(1, 1), pad=(0, 0), no_bias=True,
                             name="sc")
        add = conv + sc
        return sym.Group([add, conv]), conv, add

    net, conv, add = _net()
    topo = net._topo()
    out_ids = {id(n) for n, _ in net._outputs}
    plan = fusion.plan(topo, output_ids=out_ids)
    by_name = {n.name: plan.get(id(n)) for n in topo if not n.is_variable}
    # the graph-output conv must NOT be deferred; the other operand (sc,
    # not an output) is still eligible
    assert by_name["conv"]["defer"] is False
    assert by_name["sc"]["defer"] is True

    outs = {}
    for env in ("0", "1"):
        monkeypatch.setenv("MXNET_FUSED_CONV_BN", env)
        net = _net()[0]
        ex = net.simple_bind(mx.cpu(), data=(2, 8, 8, 8), grad_req="null")
        rs = np.random.RandomState(5)
        for arr in ex.arg_arrays:
            arr[:] = rs.uniform(-0.5, 0.5, arr.shape).astype("f")
        outs[env] = [o.asnumpy() for o in ex.forward(is_train=True)]
    for a, b in zip(outs["1"], outs["0"]):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_group_output_bn_not_folded():
    """A BN whose output is also a program output materializes regardless —
    the planner must not fold it (the fold would save nothing and
    double-compute the prologue in every consumer)."""
    sym = mx.sym
    data = sym.Variable("data")
    bn = sym.BatchNorm(data=data, fix_gamma=False, name="bn")
    conv = sym.Convolution(data=bn, num_filter=8, kernel=(1, 1),
                           stride=(1, 1), pad=(0, 0), no_bias=True,
                           name="conv")
    net = sym.Group([conv, bn])
    topo = net._topo()
    plan = fusion.plan(topo, output_ids={id(n) for n, _ in net._outputs})
    by_name = {n.name: plan.get(id(n)) for n in topo if not n.is_variable}
    assert by_name["bn"]["fold"] is False
    # without the output edge the same BN folds
    plan2 = fusion.plan(topo, output_ids={id(conv._outputs[0][0])})
    assert plan2[id([n for n in topo if n.name == "bn"][0])]["fold"] is True


def test_fused_backward_policies_match_unfused(monkeypatch):
    """End-to-end Pallas backward through the executor: forcing the fused
    dgrad/wgrad kernels (both policies) must reproduce the unfused
    gradients and aux updates — the §6b graph-integration contract."""
    out0, g0, aux0 = _run("0", monkeypatch)
    for policy in ("recompute", "stash"):
        monkeypatch.setenv("MXNET_FUSED_CONV_BN_BWD", policy)
        out1, g1, aux1 = _run("1", monkeypatch)
        monkeypatch.delenv("MXNET_FUSED_CONV_BN_BWD")
        np.testing.assert_allclose(out1, out0, rtol=1e-4, atol=1e-5,
                                   err_msg=policy)
        assert set(g1) == set(g0)
        for name in g0:
            np.testing.assert_allclose(g1[name], g0[name], rtol=2e-3,
                                       atol=2e-4,
                                       err_msg="%s/%s" % (policy, name))
        for name in aux0:
            np.testing.assert_allclose(aux1[name], aux0[name], rtol=1e-4,
                                       atol=1e-5, err_msg=name)


def test_bwd_mode_env_and_table(monkeypatch):
    """bwd_mode: env forcing, the auto path against a (monkeypatched)
    device-matched WINS table with :bwd policy entries, and the ceil-div
    WINS key for odd strided dims."""
    import jax

    from mxnet_tpu.ops import fused_conv_bn_table as tbl

    shape, wshape = (4, 8, 9, 9), (16, 8, 1, 1)
    kern, stride = (1, 1), (2, 2)
    # env forcing wins over everything
    monkeypatch.setenv("MXNET_FUSED_CONV_BN_BWD", "recompute")
    assert fusion.bwd_mode(kern, stride, shape, wshape, "float32",
                           True) == "recompute"
    monkeypatch.setenv("MXNET_FUSED_CONV_BN_BWD", "0")
    assert fusion.bwd_mode(kern, stride, shape, wshape, "float32",
                           True) == "xla"
    # auto consults the table; the key's spatial term is ceil(9/2)**2 = 25
    monkeypatch.setenv("MXNET_FUSED_CONV_BN_BWD", "auto")
    monkeypatch.setattr(tbl, "DEVICE", jax.devices()[0].device_kind)
    monkeypatch.setattr(tbl, "WINS", {(1, 8, 16, 25, 2, "p:bwd"): "stash"})
    assert fusion.bwd_mode(kern, stride, shape, wshape, "float32",
                           True) == "stash"
    # the matching forward gate key engages too (same ceil-div arithmetic)
    monkeypatch.setattr(tbl, "WINS", {(1, 8, 16, 25, 2, "p"): True})
    assert fusion.gate(kern, stride, shape, wshape, "float32", True)
    # unmeasured shape -> xla
    assert fusion.bwd_mode(kern, stride, shape, wshape, "float32",
                           False) == "xla"


def test_fused_matches_unfused(monkeypatch):
    out0, g0, aux0 = _run("0", monkeypatch)
    out1, g1, aux1 = _run("1", monkeypatch)
    np.testing.assert_allclose(out1, out0, rtol=1e-4, atol=1e-5)
    assert set(g1) == set(g0)
    for name in g0:
        np.testing.assert_allclose(g1[name], g0[name], rtol=2e-3, atol=2e-4,
                                   err_msg=name)
    for name in aux0:
        np.testing.assert_allclose(aux1[name], aux0[name], rtol=1e-4,
                                   atol=1e-5, err_msg=name)


def test_auto_mode_empty_table_falls_back(monkeypatch):
    """auto + empty WINS table must produce the plain XLA numbers (the plan
    exists, every gate declines)."""
    out0, g0, _ = _run("0", monkeypatch)
    outa, ga, _ = _run("auto", monkeypatch)
    np.testing.assert_allclose(outa, out0, rtol=1e-4, atol=1e-5)
    for name in g0:
        np.testing.assert_allclose(ga[name], g0[name], rtol=2e-3, atol=2e-4,
                                   err_msg=name)


def test_eval_mode_unaffected(monkeypatch):
    """is_train=False must bypass fusion (BN uses moving stats)."""
    monkeypatch.setenv("MXNET_FUSED_CONV_BN", "1")
    net = _bottleneck()
    ex = net.simple_bind(mx.cpu(), data=(2, 8, 8, 8), softmax_label=(2,),
                         grad_req="null")
    rs = np.random.RandomState(1)
    for arr in ex.arg_arrays:
        arr[:] = rs.uniform(-0.5, 0.5, arr.shape).astype("f")
    out_eval = ex.forward(is_train=False)[0].asnumpy()
    assert np.isfinite(out_eval).all()


def test_spmd_trainer_single_device_fused(monkeypatch):
    """The SPMDTrainer path (bench.py's) engages fusion on a 1-device mesh
    and trains: loss must drop over a few steps with fusion forced on."""
    monkeypatch.setenv("MXNET_FUSED_CONV_BN", "1")
    import jax

    from mxnet_tpu import parallel

    net = _bottleneck()
    mesh = parallel.make_mesh((1,), axis_names=("data",),
                              devices=[jax.devices()[0]])
    tr = parallel.SPMDTrainer(net, mesh, optimizer="sgd",
                              optimizer_params={"learning_rate": 0.05})
    tr.init_params({"data": (4, 8, 8, 8)}, {"softmax_label": (4,)}, seed=0)
    rs = np.random.RandomState(2)
    x = jax.numpy.asarray(rs.uniform(-1, 1, (4, 8, 8, 8)).astype("f"))
    y = jax.numpy.asarray(rs.randint(0, 10, (4,)).astype("f"))
    losses = []
    for _ in range(8):
        outs = tr.step({"data": x}, {"softmax_label": y})
        prob = np.asarray(outs[0])
        losses.append(-np.log(prob[np.arange(4), y.astype(int)] + 1e-9).mean())
    assert losses[-1] < losses[0] * 0.9, losses


def test_spmd_trainer_dp_mesh_fused_matches_unfused(monkeypatch):
    """Pure-dp multi-device mesh: the fused path runs the kernel per shard
    under shard_map with psum'd (global-batch) statistics — outputs must
    match the unfused GSPMD lowering on the same mesh."""
    import jax

    from mxnet_tpu import parallel

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")

    outs = {}
    for env in ("0", "1"):
        monkeypatch.setenv("MXNET_FUSED_CONV_BN", env)
        net = _bottleneck()
        mesh = parallel.make_mesh({"data": 4}, devices=jax.devices()[:4])
        tr = parallel.SPMDTrainer(net, mesh, optimizer="sgd",
                                  optimizer_params={"learning_rate": 0.05})
        tr.init_params({"data": (8, 8, 8, 8)}, {"softmax_label": (8,)},
                       seed=0)
        rs = np.random.RandomState(2)
        x = jax.numpy.asarray(rs.uniform(-1, 1, (8, 8, 8, 8)).astype("f"))
        y = jax.numpy.asarray(rs.randint(0, 10, (8,)).astype("f"))
        res = []
        for _ in range(3):
            o = tr.step({"data": x}, {"softmax_label": y})
            res.append(np.asarray(o[0]))
        params, _ = tr.get_params()
        outs[env] = (res, {k: np.asarray(v) for k, v in params.items()})
    for a, b in zip(outs["0"][0], outs["1"][0]):
        np.testing.assert_allclose(b, a, rtol=2e-4, atol=2e-5)
    for k in outs["0"][1]:
        np.testing.assert_allclose(outs["1"][1][k], outs["0"][1][k],
                                   rtol=2e-3, atol=2e-4, err_msg=k)


def test_tensor_sharded_mesh_takes_xla_fallback(monkeypatch):
    """A dp x tp mesh must NOT engage the raw Pallas kernel (no GSPMD
    partitioning rule — it would gather operands); the fused force-flag is
    ignored and the step still runs via the XLA lowering."""
    import jax

    from mxnet_tpu import parallel

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    monkeypatch.setenv("MXNET_FUSED_CONV_BN", "1")
    net = _bottleneck()
    mesh = parallel.make_mesh({"data": 2, "model": 2},
                              devices=jax.devices()[:4])
    tr = parallel.SPMDTrainer(net, mesh, optimizer="sgd",
                              optimizer_params={"learning_rate": 0.05})
    tr.init_params({"data": (4, 8, 8, 8)}, {"softmax_label": (4,)}, seed=0)
    rs = np.random.RandomState(3)
    x = jax.numpy.asarray(rs.uniform(-1, 1, (4, 8, 8, 8)).astype("f"))
    y = jax.numpy.asarray(rs.randint(0, 10, (4,)).astype("f"))
    outs = tr.step({"data": x}, {"softmax_label": y})
    prob = np.asarray(outs[0])
    assert np.isfinite(prob).all()
    np.testing.assert_allclose(prob.sum(axis=1), 1.0, atol=1e-3)
