"""Graph-level tests of the conv+BN fusion pass (fusion.py +
ops/pallas_conv_bn.py): a pre-activation bottleneck trained through the
executor must produce identical outputs/gradients/aux updates with the
fusion force-engaged (MXNET_FUSED_CONV_BN=1, Pallas interpret mode on CPU)
as with it disabled (=0). This is the fwd+bwd parity contract the WINS-table
gating relies on."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import fusion


def _bottleneck(nf=16):
    """Pre-activation bottleneck + shortcut conv (models/resnet.py shape):
    exercises prologue folds, 1x1 + 3x3 kernels, residual defer, stats
    reuse across the whole chain."""
    sym = mx.sym
    data = sym.Variable("data")
    bn1 = sym.BatchNorm(data=data, fix_gamma=False, name="bn1")
    act1 = sym.Activation(data=bn1, act_type="relu", name="relu1")
    conv1 = sym.Convolution(data=act1, num_filter=nf // 2, kernel=(1, 1),
                            stride=(1, 1), pad=(0, 0), no_bias=True,
                            name="conv1")
    bn2 = sym.BatchNorm(data=conv1, fix_gamma=False, name="bn2")
    act2 = sym.Activation(data=bn2, act_type="relu", name="relu2")
    conv2 = sym.Convolution(data=act2, num_filter=nf // 2, kernel=(3, 3),
                            stride=(1, 1), pad=(1, 1), no_bias=True,
                            name="conv2")
    bn3 = sym.BatchNorm(data=conv2, fix_gamma=False, name="bn3")
    act3 = sym.Activation(data=bn3, act_type="relu", name="relu3")
    conv3 = sym.Convolution(data=act3, num_filter=nf, kernel=(1, 1),
                            stride=(1, 1), pad=(0, 0), no_bias=True,
                            name="conv3")
    sc = sym.Convolution(data=act1, num_filter=nf, kernel=(1, 1),
                         stride=(1, 1), pad=(0, 0), no_bias=True, name="sc")
    out = conv3 + sc
    pool = sym.Pooling(data=out, kernel=(1, 1), global_pool=True,
                       pool_type="avg", name="pool")
    fc = sym.FullyConnected(data=sym.Flatten(pool), num_hidden=10, name="fc")
    return sym.SoftmaxOutput(data=fc, name="softmax")


def _run(env_value, monkeypatch, seed=7):
    monkeypatch.setenv("MXNET_FUSED_CONV_BN", env_value)
    net = _bottleneck()
    rs = np.random.RandomState(seed)
    B, C, H, W = 4, 8, 8, 8
    ex = net.simple_bind(mx.cpu(), data=(B, C, H, W),
                         softmax_label=(B,), grad_req="write")
    for name, arr in zip(net.list_arguments(), ex.arg_arrays):
        if name == "data":
            arr[:] = rs.uniform(-1, 1, arr.shape).astype("f")
        elif name == "softmax_label":
            arr[:] = rs.randint(0, 10, arr.shape).astype("f")
        elif name.endswith(("_gamma",)):
            arr[:] = rs.uniform(0.5, 1.5, arr.shape).astype("f")
        elif name.endswith(("_beta",)):
            arr[:] = rs.uniform(-0.2, 0.2, arr.shape).astype("f")
        else:
            arr[:] = (rs.uniform(-1, 1, arr.shape) * 0.2).astype("f")
    out = ex.forward(is_train=True)[0].asnumpy()
    ex.backward()
    grads = {n: g.asnumpy() for n, g in zip(net.list_arguments(),
                                            ex.grad_arrays) if g is not None}
    aux = {n: a.asnumpy() for n, a in zip(net.list_auxiliary_states(),
                                          ex.aux_arrays)}
    return out, grads, aux


def test_plan_structure():
    """The planner must find every fold/defer in the bottleneck."""
    net = _bottleneck()
    topo = net._topo()
    plan = fusion.plan(topo)
    by_name = {n.name: plan.get(id(n)) for n in topo if not n.is_variable}
    # bn2/bn3 feed exactly one relu feeding exactly one conv: folded.
    assert by_name["bn2"] == {"kind": "bn", "fold": True}
    assert by_name["bn3"] == {"kind": "bn", "fold": True}
    # bn1 -> relu1 feeds BOTH conv1 and sc — fold still legal (each consumer
    # re-applies the prologue in VMEM)
    assert by_name["bn1"]["fold"] is True
    assert by_name["relu1"] == {"kind": "relu_fold"}
    # exactly one add operand (both are single-consumer eligible convs) is
    # deferred into the add's epilogue; the other runs standalone
    assert (by_name["conv3"]["defer"], by_name["sc"]["defer"]).count(True) == 1
    add_name = [n.name for n in topo if n.op == "elemwise_add"][0]
    assert by_name[add_name]["kind"] == "resadd"


def test_fused_matches_unfused(monkeypatch):
    out0, g0, aux0 = _run("0", monkeypatch)
    out1, g1, aux1 = _run("1", monkeypatch)
    np.testing.assert_allclose(out1, out0, rtol=1e-4, atol=1e-5)
    assert set(g1) == set(g0)
    for name in g0:
        np.testing.assert_allclose(g1[name], g0[name], rtol=2e-3, atol=2e-4,
                                   err_msg=name)
    for name in aux0:
        np.testing.assert_allclose(aux1[name], aux0[name], rtol=1e-4,
                                   atol=1e-5, err_msg=name)


def test_auto_mode_empty_table_falls_back(monkeypatch):
    """auto + empty WINS table must produce the plain XLA numbers (the plan
    exists, every gate declines)."""
    out0, g0, _ = _run("0", monkeypatch)
    outa, ga, _ = _run("auto", monkeypatch)
    np.testing.assert_allclose(outa, out0, rtol=1e-4, atol=1e-5)
    for name in g0:
        np.testing.assert_allclose(ga[name], g0[name], rtol=2e-3, atol=2e-4,
                                   err_msg=name)


def test_eval_mode_unaffected(monkeypatch):
    """is_train=False must bypass fusion (BN uses moving stats)."""
    monkeypatch.setenv("MXNET_FUSED_CONV_BN", "1")
    net = _bottleneck()
    ex = net.simple_bind(mx.cpu(), data=(2, 8, 8, 8), softmax_label=(2,),
                         grad_req="null")
    rs = np.random.RandomState(1)
    for arr in ex.arg_arrays:
        arr[:] = rs.uniform(-0.5, 0.5, arr.shape).astype("f")
    out_eval = ex.forward(is_train=False)[0].asnumpy()
    assert np.isfinite(out_eval).all()


def test_spmd_trainer_single_device_fused(monkeypatch):
    """The SPMDTrainer path (bench.py's) engages fusion on a 1-device mesh
    and trains: loss must drop over a few steps with fusion forced on."""
    monkeypatch.setenv("MXNET_FUSED_CONV_BN", "1")
    import jax

    from mxnet_tpu import parallel

    net = _bottleneck()
    mesh = parallel.make_mesh((1,), axis_names=("data",),
                              devices=[jax.devices()[0]])
    tr = parallel.SPMDTrainer(net, mesh, optimizer="sgd",
                              optimizer_params={"learning_rate": 0.05})
    tr.init_params({"data": (4, 8, 8, 8)}, {"softmax_label": (4,)}, seed=0)
    rs = np.random.RandomState(2)
    x = jax.numpy.asarray(rs.uniform(-1, 1, (4, 8, 8, 8)).astype("f"))
    y = jax.numpy.asarray(rs.randint(0, 10, (4,)).astype("f"))
    losses = []
    for _ in range(8):
        outs = tr.step({"data": x}, {"softmax_label": y})
        prob = np.asarray(outs[0])
        losses.append(-np.log(prob[np.arange(4), y.astype(int)] + 1e-9).mean())
    assert losses[-1] < losses[0] * 0.9, losses


def test_spmd_trainer_dp_mesh_fused_matches_unfused(monkeypatch):
    """Pure-dp multi-device mesh: the fused path runs the kernel per shard
    under shard_map with psum'd (global-batch) statistics — outputs must
    match the unfused GSPMD lowering on the same mesh."""
    import jax

    from mxnet_tpu import parallel

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")

    outs = {}
    for env in ("0", "1"):
        monkeypatch.setenv("MXNET_FUSED_CONV_BN", env)
        net = _bottleneck()
        mesh = parallel.make_mesh({"data": 4}, devices=jax.devices()[:4])
        tr = parallel.SPMDTrainer(net, mesh, optimizer="sgd",
                                  optimizer_params={"learning_rate": 0.05})
        tr.init_params({"data": (8, 8, 8, 8)}, {"softmax_label": (8,)},
                       seed=0)
        rs = np.random.RandomState(2)
        x = jax.numpy.asarray(rs.uniform(-1, 1, (8, 8, 8, 8)).astype("f"))
        y = jax.numpy.asarray(rs.randint(0, 10, (8,)).astype("f"))
        res = []
        for _ in range(3):
            o = tr.step({"data": x}, {"softmax_label": y})
            res.append(np.asarray(o[0]))
        params, _ = tr.get_params()
        outs[env] = (res, {k: np.asarray(v) for k, v in params.items()})
    for a, b in zip(outs["0"][0], outs["1"][0]):
        np.testing.assert_allclose(b, a, rtol=2e-4, atol=2e-5)
    for k in outs["0"][1]:
        np.testing.assert_allclose(outs["1"][1][k], outs["0"][1][k],
                                   rtol=2e-3, atol=2e-4, err_msg=k)


def test_tensor_sharded_mesh_takes_xla_fallback(monkeypatch):
    """A dp x tp mesh must NOT engage the raw Pallas kernel (no GSPMD
    partitioning rule — it would gather operands); the fused force-flag is
    ignored and the step still runs via the XLA lowering."""
    import jax

    from mxnet_tpu import parallel

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    monkeypatch.setenv("MXNET_FUSED_CONV_BN", "1")
    net = _bottleneck()
    mesh = parallel.make_mesh({"data": 2, "model": 2},
                              devices=jax.devices()[:4])
    tr = parallel.SPMDTrainer(net, mesh, optimizer="sgd",
                              optimizer_params={"learning_rate": 0.05})
    tr.init_params({"data": (4, 8, 8, 8)}, {"softmax_label": (4,)}, seed=0)
    rs = np.random.RandomState(3)
    x = jax.numpy.asarray(rs.uniform(-1, 1, (4, 8, 8, 8)).astype("f"))
    y = jax.numpy.asarray(rs.randint(0, 10, (4,)).astype("f"))
    outs = tr.step({"data": x}, {"softmax_label": y})
    prob = np.asarray(outs[0])
    assert np.isfinite(prob).all()
    np.testing.assert_allclose(prob.sum(axis=1), 1.0, atol=1e-3)
