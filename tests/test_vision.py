"""Vision/detection ops vs numpy oracles (reference coverage:
test_operator.py ROIPooling/BilinearSampler/SpatialTransformer sections and
the SSD MultiBox pipeline)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


def _run(out_sym, args, aux=None):
    exe = mx.executor.bind(out_sym, mx.cpu(),
                           {k: mx.nd.array(v) for k, v in args.items()},
                           args_grad=None, grad_req="null", aux_states=aux or {})
    return [o.asnumpy() for o in exe.forward(is_train=False)]


def test_roi_pooling_identity_roi():
    # ROI covering the whole 4x4 image, pooled to 2x2 → max of each quadrant
    data = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
    rois = np.array([[0, 0, 0, 3, 3]], dtype="float32")
    out = mx.nd.ROIPooling(mx.nd.array(data), mx.nd.array(rois),
                           pooled_size=(2, 2), spatial_scale=1.0).asnumpy()
    expected = np.array([[[[5, 7], [13, 15]]]], dtype="float32")
    np.testing.assert_array_equal(out, expected)


def test_roi_pooling_spatial_scale():
    data = np.random.rand(1, 2, 8, 8).astype("float32")
    rois = np.array([[0, 0, 0, 15, 15]], dtype="float32")  # scale .5 → full map
    out = mx.nd.ROIPooling(mx.nd.array(data), mx.nd.array(rois),
                           pooled_size=(1, 1), spatial_scale=0.5).asnumpy()
    np.testing.assert_allclose(out[0, :, 0, 0], data[0].max(axis=(1, 2)), rtol=1e-6)


def test_bilinear_sampler_identity_grid():
    data = np.random.rand(2, 3, 5, 6).astype("float32")
    H, W = 5, 6
    ys, xs = np.meshgrid(np.linspace(-1, 1, H), np.linspace(-1, 1, W), indexing="ij")
    grid = np.stack([xs, ys], 0)[None].repeat(2, axis=0).astype("float32")
    out = mx.nd.BilinearSampler(mx.nd.array(data), mx.nd.array(grid)).asnumpy()
    np.testing.assert_allclose(out, data, rtol=1e-5, atol=1e-6)


def test_spatial_transformer_identity_theta():
    data = np.random.rand(1, 2, 4, 4).astype("float32")
    theta = np.array([[1, 0, 0, 0, 1, 0]], dtype="float32")
    out = mx.nd.SpatialTransformer(mx.nd.array(data), mx.nd.array(theta),
                                   target_shape=(4, 4)).asnumpy()
    np.testing.assert_allclose(out, data, rtol=1e-5, atol=1e-6)


def test_grid_generator_affine_identity():
    theta = np.array([[1, 0, 0, 0, 1, 0]], dtype="float32")
    grid = mx.nd.GridGenerator(mx.nd.array(theta), transform_type="affine",
                               target_shape=(3, 3)).asnumpy()
    assert grid.shape == (1, 2, 3, 3)
    np.testing.assert_allclose(grid[0, 0, 0], [-1, 0, 1], atol=1e-6)  # x row
    np.testing.assert_allclose(grid[0, 1, :, 0], [-1, 0, 1], atol=1e-6)  # y col


def test_crop():
    data = np.arange(36, dtype="float32").reshape(1, 1, 6, 6)
    out = mx.nd.Crop(mx.nd.array(data), offset=(1, 2), h_w=(3, 3)).asnumpy()
    np.testing.assert_array_equal(out[0, 0], data[0, 0, 1:4, 2:5])
    out_c = mx.nd.Crop(mx.nd.array(data), h_w=(2, 2), center_crop=True).asnumpy()
    np.testing.assert_array_equal(out_c[0, 0], data[0, 0, 2:4, 2:4])


def test_multibox_prior():
    data = np.zeros((1, 3, 2, 2), dtype="float32")
    anchors = mx.nd.MultiBoxPrior(mx.nd.array(data), sizes=(0.5,),
                                  ratios=(1.0, 2.0)).asnumpy()
    assert anchors.shape == (1, 2 * 2 * 2, 4)
    # first anchor: center (0.25, 0.25), size 0.5 ratio 1 → square
    np.testing.assert_allclose(anchors[0, 0], [0.0, 0.0, 0.5, 0.5], atol=1e-6)
    # ratio-2 anchor is wider than tall
    a1 = anchors[0, 1]
    assert (a1[2] - a1[0]) > (a1[3] - a1[1])


def test_multibox_target_matches_gt():
    anchors = np.array([[[0.0, 0.0, 0.5, 0.5], [0.5, 0.5, 1.0, 1.0]]], dtype="float32")
    # gt overlapping the first anchor exactly, class 0
    label = np.array([[[0, 0.0, 0.0, 0.5, 0.5]]], dtype="float32")
    cls_pred = np.zeros((1, 2, 2), dtype="float32")
    loc_t, loc_m, cls_t = mx.nd.MultiBoxTarget(
        mx.nd.array(anchors), mx.nd.array(label), mx.nd.array(cls_pred))
    cls_t = cls_t.asnumpy()
    loc_m = loc_m.asnumpy()
    assert cls_t[0, 0] == 1.0 and cls_t[0, 1] == 0.0  # class0 → target 1, bg 0
    assert loc_m[0, :4].sum() == 4 and loc_m[0, 4:].sum() == 0
    # exact match → zero offsets
    np.testing.assert_allclose(loc_t.asnumpy()[0, :4], 0.0, atol=1e-5)


def test_multibox_detection_decodes_and_nms():
    anchors = np.array([[[0.1, 0.1, 0.4, 0.4],
                         [0.12, 0.1, 0.42, 0.4],
                         [0.6, 0.6, 0.9, 0.9]]], dtype="float32")
    # class probs: [background; class0] — anchors 0,1 confident class0
    cls_prob = np.array([[[0.1, 0.2, 0.9], [0.9, 0.8, 0.1]]], dtype="float32")
    loc_pred = np.zeros((1, 12), dtype="float32")
    out = mx.nd.MultiBoxDetection(mx.nd.array(cls_prob), mx.nd.array(loc_pred),
                                  mx.nd.array(anchors), nms_threshold=0.5,
                                  threshold=0.5).asnumpy()
    assert out.shape == (1, 3, 6)
    ids = out[0, :, 0]
    # one of the two overlapping anchors suppressed; far anchor under threshold
    assert (ids >= 0).sum() == 1
    assert ids[0] == 0.0 and out[0, 0, 1] == pytest.approx(0.9)


def test_proposal_shapes():
    B, A, H, W = 1, 12, 4, 4  # 4 scales x 3 ratios
    cls_prob = np.random.rand(B, 2 * A, H, W).astype("float32")
    bbox_pred = (np.random.rand(B, 4 * A, H, W).astype("float32") - 0.5) * 0.1
    im_info = np.array([[64, 64, 1.0]], dtype="float32")
    rois = mx.nd.Proposal(mx.nd.array(cls_prob), mx.nd.array(bbox_pred),
                          mx.nd.array(im_info), feature_stride=16,
                          rpn_post_nms_top_n=8).asnumpy()
    assert rois.shape == (8, 5)
    assert (rois[:, 0] == 0).all()
    assert (rois[:, 1:] >= 0).all() and (rois[:, 1:] <= 64).all()


def test_fft_ifft_roundtrip():
    x = np.random.rand(2, 8).astype("float32")
    f = mx.nd.fft(mx.nd.array(x))
    assert f.shape == (2, 16)
    # oracle: numpy fft interleaved
    ref = np.fft.fft(x, axis=-1)
    inter = np.stack([ref.real, ref.imag], -1).reshape(2, 16).astype("float32")
    np.testing.assert_allclose(f.asnumpy(), inter, rtol=1e-4, atol=1e-4)
    back = mx.nd.ifft(f).asnumpy() / 8  # reference ifft is unnormalized (×K)
    np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-4)


def test_count_sketch():
    data = np.array([[1.0, 2.0, 3.0]], dtype="float32")
    h = np.array([0, 1, 0], dtype="float32")
    s = np.array([1, -1, 1], dtype="float32")
    out = mx.nd.count_sketch(mx.nd.array(data), mx.nd.array(h), mx.nd.array(s),
                             out_dim=2).asnumpy()
    np.testing.assert_allclose(out, [[4.0, -2.0]], atol=1e-6)


def test_correlation_self_is_mean_square():
    x = np.random.rand(1, 4, 5, 5).astype("float32")
    out = mx.nd.Correlation(mx.nd.array(x), mx.nd.array(x),
                            max_displacement=1).asnumpy()
    assert out.shape == (1, 9, 5, 5)
    center = out[0, 4]  # zero displacement channel
    np.testing.assert_allclose(center, (x[0] ** 2).mean(axis=0), rtol=1e-5)


def test_roi_pooling_gradient_flows():
    from mxnet_tpu import test_utils as tu

    rs = np.random.RandomState(3)
    data = rs.rand(1, 2, 6, 6).astype("float32")
    rois = np.array([[0, 0, 0, 5, 5]], dtype="float32")
    out = sym.ROIPooling(data=sym.Variable("data"), rois=sym.Variable("rois"),
                         pooled_size=(2, 2), spatial_scale=1.0)
    g = tu.check_symbolic_backward(out, {"data": data, "rois": rois},
                                   [np.ones((1, 2, 2, 2), "float32")], {})
    # max pooling routes each bin's gradient to exactly one input element
    assert g["data"].sum() == pytest.approx(8.0)


def test_bilinear_sampler_gradient():
    from mxnet_tpu import test_utils as tu

    rs = np.random.RandomState(4)
    data = rs.rand(1, 1, 4, 4).astype("float32")
    ys, xs = np.meshgrid(np.linspace(-0.9, 0.9, 4), np.linspace(-0.9, 0.9, 4),
                         indexing="ij")
    grid = np.stack([xs, ys], 0)[None].astype("float32")
    out = sym.BilinearSampler(data=sym.Variable("data"), grid=sym.Variable("grid"))
    tu.check_numeric_gradient(out, {"data": data, "grid": grid},
                              numeric_eps=1e-3, check_eps=3e-2)


def test_multibox_target_hard_negative_mining():
    """With mining (ratio 3): unmined negatives carry ignore_label, mined
    negatives are the lowest-background-probability anchors, positives keep
    their class (reference: multibox_target.cc:162-229)."""
    import numpy as np
    import mxnet_tpu as mx

    anchors = np.array([[[0.0, 0.0, 0.5, 0.5],
                         [0.5, 0.5, 1.0, 1.0],
                         [0.0, 0.5, 0.5, 1.0],
                         [0.45, 0.0, 0.95, 0.5],
                         [0.1, 0.1, 0.2, 0.2],
                         [0.8, 0.8, 0.9, 0.9]]], "float32")
    label = -np.ones((1, 2, 5), "float32")
    label[0, 0] = [2, 0.0, 0.0, 0.5, 0.5]  # matches anchor 0 exactly
    N = anchors.shape[1]
    # background logits: anchor 4 is the most confident background, anchor 5
    # the least (hardest negative)
    cls_pred = np.zeros((1, 3, N), "float32")
    cls_pred[0, 0] = [0.0, -1.0, 0.0, 1.0, 5.0, -5.0]

    a = mx.nd.array(anchors); l = mx.nd.array(label); p = mx.nd.array(cls_pred)
    _, loc_mask, cls_t = mx.nd.MultiBoxTarget(
        a, l, p, overlap_threshold=0.5, ignore_label=-1,
        negative_mining_ratio=2, negative_mining_thresh=0.5)
    ct = cls_t.asnumpy()[0]
    assert ct[0] == 3.0  # class 2 → target 3 (0 is background)
    # 1 positive × ratio 2 = 2 mined negatives; hardest = lowest bg prob
    assert (ct == 0).sum() == 2
    assert ct[5] == 0 and ct[1] == 0  # lowest background logits
    assert ct[4] == -1 and ct[3] == -1  # confident backgrounds ignored, not mined

    # without mining every unmatched anchor is background
    _, _, cls_all = mx.nd.MultiBoxTarget(a, l, p, overlap_threshold=0.5)
    assert (cls_all.asnumpy()[0] == 0).sum() == N - 1
