"""Regression tests for the round-2 fixes (ADVICE.md + VERDICT.md weak items)."""
import io
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import ndarray as nd
from mxnet_tpu.base import MXNetError


def test_gamma_is_unary_gamma_function():
    # ADVICE high: `gamma` must be Γ(x), not the sampler (reference keeps them distinct)
    x = mx.nd.array(np.array([1.0, 2.0, 3.0, 4.0], dtype=np.float32))
    out = mx.nd.gamma(x).asnumpy()
    np.testing.assert_allclose(out, [1.0, 1.0, 2.0, 6.0], rtol=1e-5)


def test_register_rejects_duplicates():
    from mxnet_tpu.ops.registry import register

    with pytest.raises(MXNetError):
        register("gamma")(lambda attrs, x: x)
    with pytest.raises(MXNetError):
        register("_totally_new_op_xyz", aliases=("gamma",))(lambda attrs, x: x)


def test_params_reference_binary_layout():
    """The .params byte stream must match the reference NDArray::Save layout
    (src/ndarray/ndarray.cc:623-645): shape, ctx, type_flag, raw data — no
    per-array length prefix."""
    arr = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    buf = io.BytesIO()
    nd._write_ndarray(buf, arr)
    raw = buf.getvalue()
    expect = (
        struct.pack("<I", 2)
        + struct.pack("<II", 2, 3)
        + struct.pack("<ii", arr.context.device_typeid, arr.context.device_id)
        + struct.pack("<i", 0)  # float32 type_flag
        + np.arange(6, dtype=np.float32).tobytes()
    )
    assert raw == expect
    back = nd._read_ndarray(io.BytesIO(raw))
    np.testing.assert_array_equal(back.asnumpy(), arr.asnumpy())


def test_params_file_written_by_reference_layout_loads(tmp_path):
    """Hand-craft a file in the exact reference format and load it."""
    fname = str(tmp_path / "ref.params")
    a = np.random.rand(3, 4).astype(np.float32)
    b = np.random.randint(0, 10, size=(5,)).astype(np.int32)
    with open(fname, "wb") as f:
        f.write(struct.pack("<QQ", 0x112, 0))
        f.write(struct.pack("<Q", 2))
        # array 0: float32 on cpu(0)
        f.write(struct.pack("<I", 2) + struct.pack("<II", 3, 4))
        f.write(struct.pack("<ii", 1, 0) + struct.pack("<i", 0) + a.tobytes())
        # array 1: int32
        f.write(struct.pack("<I", 1) + struct.pack("<I", 5))
        f.write(struct.pack("<ii", 1, 0) + struct.pack("<i", 4) + b.tobytes())
        # names
        f.write(struct.pack("<Q", 2))
        for name in (b"arg:w", b"arg:b"):
            f.write(struct.pack("<Q", len(name)) + name)
    loaded = nd.load(fname)
    np.testing.assert_allclose(loaded["arg:w"].asnumpy(), a)
    np.testing.assert_array_equal(loaded["arg:b"].asnumpy(), b)


def test_save_load_roundtrip(tmp_path):
    fname = str(tmp_path / "rt.params")
    d = {"x": mx.nd.array(np.random.rand(2, 2).astype(np.float32)),
         "y": mx.nd.array(np.arange(3, dtype=np.float32))}
    nd.save(fname, d)
    back = nd.load(fname)
    for k in d:
        np.testing.assert_allclose(back[k].asnumpy(), d[k].asnumpy())


def test_fullyconnected_flatten_false():
    data = np.random.rand(2, 3, 4).astype(np.float32)
    w = np.random.rand(5, 4).astype(np.float32)
    b = np.random.rand(5).astype(np.float32)
    out = mx.nd.FullyConnected(
        data=mx.nd.array(data), weight=mx.nd.array(w), bias=mx.nd.array(b),
        num_hidden=5, flatten=False,
    ).asnumpy()
    np.testing.assert_allclose(out, np.einsum("nti,oi->nto", data, w) + b, rtol=1e-5)


def test_topk_mask():
    x = np.array([[3.0, 1.0, 4.0, 1.5], [0.0, 2.0, -1.0, 5.0]], dtype=np.float32)
    m = mx.nd.topk(mx.nd.array(x), k=2, ret_typ="mask").asnumpy()
    np.testing.assert_array_equal(m, [[1, 0, 1, 0], [0, 1, 0, 1]])
    with pytest.raises(MXNetError):
        mx.nd.topk(mx.nd.array(x), k=2, ret_typ="bogus")


def test_tuple_setitem():
    a = mx.nd.zeros((3, 4))
    a[1, 2] = 7.0
    a[0, 1:3] = 2.0
    got = a.asnumpy()
    assert got[1, 2] == 7.0
    np.testing.assert_array_equal(got[0, 1:3], [2.0, 2.0])
    assert got.sum() == 11.0


def test_regression_output_backward_through_jax():
    """ADVICE medium: differentiating the custom-vjp output ops must not raise
    pytree errors, and must produce the reference gradients."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.registry import get_op

    data = jnp.array([[0.2, -0.5], [1.0, 0.3]], dtype=jnp.float32)
    label = jnp.array([[0.0, 0.0], [1.0, 1.0]], dtype=jnp.float32)

    for name, ref_grad in [
        ("LinearRegressionOutput", (data - label) / 2.0),
        ("MAERegressionOutput", jnp.sign(data - label) / 2.0),
    ]:
        op = get_op(name)
        loss = lambda d: jnp.sum(op.fn({"grad_scale": 1.0}, d, label))
        g = jax.grad(loss)(data)
        np.testing.assert_allclose(np.asarray(g), np.asarray(ref_grad), rtol=1e-5)

    ml = get_op("MakeLoss")
    g = jax.grad(lambda d: jnp.sum(ml.fn(
        {"grad_scale": 3.0, "normalization": "null", "valid_thresh": 0.0}, d)))(data)
    np.testing.assert_allclose(np.asarray(g), np.full(data.shape, 3.0))


def test_waitall_blocks():
    a = mx.nd.ones((64, 64))
    b = mx.nd.dot(a, a)
    nd.waitall()  # must not raise, and must block on b's buffer
    assert b.asnumpy()[0, 0] == 64.0
