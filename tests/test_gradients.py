"""Gradient oracles: finite differences + closed-form loss backwards.

The reference's test_operator.py validates nearly every op with
check_numeric_gradient; this suite does the same for the TPU build, with
explicit closed-form checks for the custom-vjp loss ops (whose one job is
their backward — SoftmaxOutput's p−y, regression deltas, MakeLoss's
grad-scale), plus bf16 forward tolerance.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu import test_utils as tu


def _rs():
    return np.random.RandomState(7)


# ------------------------------------------------------------ loss backwards
def test_softmax_output_backward_is_p_minus_y():
    rs = _rs()
    x = rs.uniform(-1, 1, (4, 5)).astype("float32")
    label = np.array([0, 2, 1, 4], dtype="float32")
    data = sym.Variable("data")
    lab = sym.Variable("label")
    out = sym.SoftmaxOutput(data=data, label=lab, name="sm")
    e = np.exp(x - x.max(axis=1, keepdims=True))
    p = e / e.sum(axis=1, keepdims=True)
    y = np.eye(5, dtype="float32")[label.astype(int)]
    expected = (p - y) / 4.0 * 4.0  # grad_scale=1, no normalization → p-y
    tu.check_symbolic_backward(
        out, {"data": x, "label": label}, [np.ones((4, 5), "float32")],
        {"data": p - y}, check_eps=1e-4)


def test_softmax_output_ignores_head_gradient():
    rs = _rs()
    x = rs.uniform(-1, 1, (3, 4)).astype("float32")
    label = np.array([1, 0, 3], dtype="float32")
    out = sym.SoftmaxOutput(data=sym.Variable("data"), label=sym.Variable("label"))
    g1 = tu.check_symbolic_backward(out, {"data": x, "label": label},
                                    [np.ones((3, 4), "float32")], {})
    g2 = tu.check_symbolic_backward(out, {"data": x, "label": label},
                                    [np.full((3, 4), 123.0, "float32")], {})
    np.testing.assert_allclose(g1["data"], g2["data"], rtol=1e-6)


def test_linear_regression_backward():
    rs = _rs()
    x = rs.uniform(-1, 1, (6, 3)).astype("float32")
    y = rs.uniform(-1, 1, (6, 3)).astype("float32")
    out = sym.LinearRegressionOutput(data=sym.Variable("data"), label=sym.Variable("label"))
    # reference regression_output-inl.h divides by per-sample output count
    tu.check_symbolic_backward(
        out, {"data": x, "label": y}, [np.ones((6, 3), "float32")],
        {"data": (x - y) / 3.0}, check_eps=1e-4)


def test_logistic_regression_backward():
    rs = _rs()
    x = rs.uniform(-1, 1, (5, 2)).astype("float32")
    y = rs.randint(0, 2, (5, 2)).astype("float32")
    out = sym.LogisticRegressionOutput(data=sym.Variable("data"), label=sym.Variable("label"))
    p = 1 / (1 + np.exp(-x))
    tu.check_symbolic_backward(
        out, {"data": x, "label": y}, [np.ones((5, 2), "float32")],
        {"data": (p - y) / 2.0}, check_eps=1e-4)


def test_mae_regression_backward():
    rs = _rs()
    x = rs.uniform(-1, 1, (4, 3)).astype("float32")
    y = rs.uniform(-1, 1, (4, 3)).astype("float32")
    out = sym.MAERegressionOutput(data=sym.Variable("data"), label=sym.Variable("label"))
    tu.check_symbolic_backward(
        out, {"data": x, "label": y}, [np.ones((4, 3), "float32")],
        {"data": np.sign(x - y) / 3.0}, check_eps=1e-4)


def test_make_loss_grad_scale():
    rs = _rs()
    x = rs.uniform(0.1, 1, (3, 3)).astype("float32")
    out = sym.MakeLoss(data=sym.Variable("data"), grad_scale=2.5)
    tu.check_symbolic_backward(
        out, {"data": x}, [np.ones((3, 3), "float32")],
        {"data": np.full((3, 3), 2.5, "float32")}, check_eps=1e-5)


def test_block_grad_stops_gradient():
    rs = _rs()
    x = rs.uniform(-1, 1, (3, 3)).astype("float32")
    d = sym.Variable("data")
    out = sym.BlockGrad(d * 2.0)
    g = tu.check_symbolic_backward(out, {"data": x},
                                   [np.ones((3, 3), "float32")], {})
    np.testing.assert_allclose(g["data"], np.zeros((3, 3)), atol=1e-7)


def test_svm_output_backward_finite():
    rs = _rs()
    x = rs.uniform(-1, 1, (4, 3)).astype("float32")
    label = np.array([0, 1, 2, 1], dtype="float32")
    out = sym.SVMOutput(data=sym.Variable("data"), label=sym.Variable("label"))
    g = tu.check_symbolic_backward(out, {"data": x, "label": label},
                                   [np.ones((4, 3), "float32")], {})
    assert np.isfinite(g["data"]).all() and np.abs(g["data"]).sum() > 0


# ------------------------------------------------------- numeric grad checks
_UNARY_CASES = [
    ("exp", lambda d: sym.exp(d), 0.5),
    ("log", lambda d: sym.log(d + 3.0), 0.5),
    ("sqrt", lambda d: sym.sqrt(d + 3.0), 0.5),
    ("tanh", lambda d: sym.tanh(d), 0.5),
    ("sigmoid", lambda d: sym.sigmoid(d), 0.5),
    ("square", lambda d: sym.square(d), 0.5),
    ("relu_act", lambda d: sym.Activation(d, act_type="relu"), 0.6),
    ("softrelu", lambda d: sym.Activation(d, act_type="softrelu"), 0.5),
    ("negative", lambda d: -d, 0.5),
    ("sin", lambda d: sym.sin(d), 0.8),
    ("cos", lambda d: sym.cos(d), 0.8),
    ("abs", lambda d: sym.abs(d + 1.7), 0.5),
]


@pytest.mark.parametrize("name,builder,scale", _UNARY_CASES)
def test_unary_numeric_gradient(name, builder, scale):
    rs = _rs()
    x = rs.uniform(-scale, scale, (3, 4)).astype("float32")
    # keep finite differences away from kinks (relu/abs at 0)
    x = np.where(np.abs(x) < 0.05, 0.1, x).astype("float32")
    tu.check_numeric_gradient(builder(sym.Variable("data")), {"data": x})


_BINARY_CASES = [
    ("add", lambda a, b: a + b),
    ("sub", lambda a, b: a - b),
    ("mul", lambda a, b: a * b),
    ("div", lambda a, b: a / (b + 2.0)),
    ("broadcast_add", lambda a, b: sym.broadcast_add(a, b)),
    ("broadcast_mul", lambda a, b: sym.broadcast_mul(a, b)),
    ("maximum", lambda a, b: sym.maximum(a, b)),
]


@pytest.mark.parametrize("name,builder", _BINARY_CASES)
def test_binary_numeric_gradient(name, builder):
    rs = _rs()
    a = rs.uniform(-1, 1, (3, 4)).astype("float32")
    b = rs.uniform(-1, 1, (3, 4)).astype("float32") + 0.1
    out = builder(sym.Variable("a"), sym.Variable("b"))
    tu.check_numeric_gradient(out, {"a": a, "b": b})


def test_fully_connected_numeric_gradient():
    rs = _rs()
    out = sym.FullyConnected(data=sym.Variable("data"), num_hidden=3, name="fc")
    loc = {
        "data": rs.uniform(-1, 1, (2, 4)).astype("float32"),
        "fc_weight": rs.uniform(-1, 1, (3, 4)).astype("float32"),
        "fc_bias": rs.uniform(-1, 1, (3,)).astype("float32"),
    }
    tu.check_numeric_gradient(out, loc)


def test_conv_numeric_gradient():
    rs = _rs()
    out = sym.Convolution(data=sym.Variable("data"), kernel=(3, 3), num_filter=2,
                          pad=(1, 1), name="c")
    loc = {
        "data": rs.uniform(-1, 1, (1, 2, 5, 5)).astype("float32"),
        "c_weight": rs.uniform(-0.5, 0.5, (2, 2, 3, 3)).astype("float32"),
        "c_bias": rs.uniform(-0.5, 0.5, (2,)).astype("float32"),
    }
    tu.check_numeric_gradient(out, loc, numeric_eps=1e-3, check_eps=2e-2)


def test_pooling_numeric_gradient():
    rs = _rs()
    out = sym.Pooling(data=sym.Variable("data"), kernel=(2, 2), stride=(2, 2),
                      pool_type="avg")
    x = rs.uniform(-1, 1, (1, 2, 4, 4)).astype("float32")
    tu.check_numeric_gradient(out, {"data": x})


def test_dot_numeric_gradient():
    rs = _rs()
    out = sym.dot(sym.Variable("a"), sym.Variable("b"))
    loc = {"a": rs.uniform(-1, 1, (3, 4)).astype("float32"),
           "b": rs.uniform(-1, 1, (4, 2)).astype("float32")}
    tu.check_numeric_gradient(out, loc)


def test_reductions_numeric_gradient():
    rs = _rs()
    x = rs.uniform(-1, 1, (3, 4)).astype("float32")
    for builder in (lambda d: sym.sum(d, axis=1), lambda d: sym.mean(d),
                    lambda d: sym.sum(d, axis=(0, 1), keepdims=True)):
        tu.check_numeric_gradient(builder(sym.Variable("data")), {"data": x})


def test_reshape_transpose_numeric_gradient():
    rs = _rs()
    x = rs.uniform(-1, 1, (2, 6)).astype("float32")
    tu.check_numeric_gradient(sym.Reshape(sym.Variable("data"), shape=(3, 4)), {"data": x})
    tu.check_numeric_gradient(sym.transpose(sym.Variable("data")), {"data": x})


def test_concat_slice_numeric_gradient():
    rs = _rs()
    a = rs.uniform(-1, 1, (2, 3)).astype("float32")
    b = rs.uniform(-1, 1, (2, 3)).astype("float32")
    out = sym.Concat(sym.Variable("a"), sym.Variable("b"), dim=1, num_args=2)
    tu.check_numeric_gradient(out, {"a": a, "b": b})
    parts = sym.SliceChannel(sym.Variable("a"), num_outputs=3, axis=1)
    tu.check_numeric_gradient(sym.Group(list(parts)), {"a": a})


def test_batchnorm_numeric_gradient():
    rs = _rs()
    # square the output: the sum of BN outputs is ~constant in the inputs
    # (normalization), which would make the check vacuous
    out = sym.square(sym.BatchNorm(data=sym.Variable("data"), fix_gamma=False, name="bn"))
    loc = {"data": rs.uniform(-1, 1, (4, 3)).astype("float32"),
           "bn_gamma": rs.uniform(0.5, 1.5, (3,)).astype("float32"),
           "bn_beta": rs.uniform(-0.5, 0.5, (3,)).astype("float32")}
    aux = {"bn_moving_mean": np.zeros((3,), "float32"),
           "bn_moving_var": np.ones((3,), "float32")}
    tu.check_numeric_gradient(out, loc, aux_states=aux, numeric_eps=1e-3, check_eps=3e-2)


def test_embedding_take_gradient():
    rs = _rs()
    emb = sym.Embedding(data=sym.Variable("idx"), input_dim=7, output_dim=3, name="e")
    idx = np.array([[0, 2], [5, 1]], dtype="int32")
    w = rs.uniform(-1, 1, (7, 3)).astype("float32")
    g = tu.check_symbolic_backward(
        emb, {"idx": idx, "e_weight": w}, [np.ones((2, 2, 3), "float32")], {})
    expected = np.zeros((7, 3), "float32")
    for i in idx.ravel():
        expected[i] += 1
    np.testing.assert_allclose(g["e_weight"], expected, rtol=1e-5)


# ---------------------------------------------------------------- bf16 paths
def test_bf16_forward_consistency_fc():
    rs = _rs()
    out = sym.FullyConnected(data=sym.Variable("data"), num_hidden=8, name="fc")
    loc = {"data": rs.uniform(-1, 1, (4, 16)).astype("float32"),
           "fc_weight": rs.uniform(-1, 1, (8, 16)).astype("float32"),
           "fc_bias": rs.uniform(-1, 1, (8,)).astype("float32")}
    tu.check_consistency(out, loc, dtypes=("float32", "bfloat16"))


def test_bf16_forward_consistency_conv():
    rs = _rs()
    out = sym.Convolution(data=sym.Variable("data"), kernel=(3, 3), num_filter=4,
                          no_bias=True, name="c")
    loc = {"data": rs.uniform(-1, 1, (2, 3, 8, 8)).astype("float32"),
           "c_weight": rs.uniform(-0.3, 0.3, (4, 3, 3, 3)).astype("float32")}
    tu.check_consistency(out, loc, dtypes=("float32", "bfloat16"))


def test_bf16_softmax_consistency():
    rs = _rs()
    out = sym.SoftmaxActivation(data=sym.Variable("data"))
    loc = {"data": rs.uniform(-2, 2, (4, 10)).astype("float32")}
    tu.check_consistency(out, loc, dtypes=("float32", "bfloat16"))
