"""Serving subsystem (mxnet_tpu/serving/, docs/SERVING.md): executable
cache warmup/seal/persistence, continuous batching over shape buckets
(pad-to-bucket correctness, deadline partials, oversize rejection,
cross-thread ordering), the predictor's zero-recompile contract, and the
fusion gate's inference mode with the bf16/int8 quantized variants."""
import json
import os
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serving import (InferenceEngine, PersistentExecutableCache)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def tm():
    telemetry.reset()
    telemetry.clear_events()
    saved = telemetry.current_override()
    yield telemetry
    telemetry.set_mode(saved)
    telemetry.reset()
    telemetry.clear_events()


def _mlp_net():
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=5,
                                name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _mlp_params(seed=0):
    rs = np.random.RandomState(seed)
    return {"fc_weight": rs.randn(5, 8).astype("float32"),
            "fc_bias": rs.randn(5).astype("float32")}


def _direct_forward(net, params, x_padded):
    exe = net.simple_bind(mx.cpu(), grad_req="null",
                          data=x_padded.shape)
    for k, v in params.items():
        exe.arg_dict[k][:] = v
    exe.arg_dict["data"][:] = x_padded
    exe.forward(is_train=False)
    return exe.outputs[0].asnumpy()


# ---------------------------------------------------------------- cache
def test_cache_warmup_seal_and_miss_raises():
    cache = PersistentExecutableCache(_mlp_net(), _mlp_params(), {},
                                      cache_dir=None)
    n = cache.warmup([{"data": (1, 8)}, {"data": (4, 8)}])
    assert n == 2 and cache.sealed
    # warmed bucket: runs
    out = cache.run({"data": np.zeros((4, 8), "float32")})
    assert out[0].shape == (4, 5)
    # unwarmed bucket: the call that would retrace raises with diagnosis
    with pytest.raises(MXNetError, match="post-warmup executable-cache "
                                         "miss"):
        cache.run({"data": np.zeros((3, 8), "float32")})


def test_cache_hit_vs_compile_counters(tm):
    tm.set_mode("counters")
    cache = PersistentExecutableCache(_mlp_net(), _mlp_params(), {},
                                      cache_dir=None)
    cache.warmup([{"data": (2, 8)}])
    c0 = tm.counters()
    for _ in range(5):
        cache.run({"data": np.zeros((2, 8), "float32")})
    c1 = tm.counters()
    assert c1["serving.executable_hit"] - c0.get("serving.executable_hit",
                                                 0) == 5
    assert c1.get("serving.executable_compile", 0) == \
        c0.get("serving.executable_compile", 0)
    # the executor underneath replays its jit entry: no new compiles
    assert c1.get("executor.compile", 0) == c0.get("executor.compile", 0)
    assert c1.get("executor.retrace", 0) == c0.get("executor.retrace", 0)


def test_cache_manifest_persistence(tmp_path):
    params = _mlp_params()
    c1 = PersistentExecutableCache(_mlp_net(), params, {},
                                   cache_dir=str(tmp_path), model_key="m")
    c1.warmup([{"data": (1, 8)}, {"data": (2, 8)}])
    manifest = c1._manifest_path()
    assert os.path.exists(manifest)
    rec = json.load(open(manifest))
    assert len(rec["buckets"]) == 2 and rec["dtype"] == "float32"
    # a fresh process-equivalent: warmup(None) replays the manifest
    c2 = PersistentExecutableCache(_mlp_net(), params, {},
                                   cache_dir=str(tmp_path), model_key="m")
    assert c2.warmup(None) == 2
    assert sorted(c2.keys()) == sorted(c1.keys())
    # a DIFFERENT model under the same key must not inherit the buckets
    other = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=7,
                              name="fc"), name="softmax")
    rs = np.random.RandomState(1)
    c3 = PersistentExecutableCache(
        other, {"fc_weight": rs.randn(7, 8).astype("float32"),
                "fc_bias": np.zeros(7, "float32")}, {},
        cache_dir=str(tmp_path), model_key="m")
    assert c3.warmup(None) == 0
    # zero warmed buckets must NOT seal (an empty sealed cache would
    # reject every request with no way back) nor clobber the manifest
    assert not c3.sealed
    c3.executable({"data": (1, 8)})  # still bindable
    assert json.load(open(manifest))["digest"] == rec["digest"]


def test_cache_shares_params_across_buckets():
    """Bucket executors share ONE set of param/aux device arrays — a
    4-rung ladder must not hold 4 full weight copies, and a param write
    through one executor is visible to every bucket."""
    cache = PersistentExecutableCache(_mlp_net(), _mlp_params(), {},
                                      cache_dir=None)
    cache.warmup([{"data": (1, 8)}, {"data": (4, 8)}])
    e1, e4 = (cache._exes[k] for k in sorted(cache._exes))
    for p in ("fc_weight", "fc_bias"):
        assert e1.arg_dict[p] is e4.arg_dict[p], \
            "param %r duplicated across bucket executors" % p
    # inputs stay per-bucket (their shape IS the cache key)
    assert e1.arg_dict["data"] is not e4.arg_dict["data"]
    before = cache.run({"data": np.ones((1, 8), "float32")})[0]
    e4.arg_dict["fc_weight"][:] = 0.0
    e4.arg_dict["fc_bias"][:] = 0.0
    after = cache.run({"data": np.ones((1, 8), "float32")})[0]
    assert not np.array_equal(before, after), \
        "bucket-1 executor did not see the shared param update"


# --------------------------------------------------------------- engine
def test_pad_to_bucket_bitwise():
    """A request padded into a bucket returns exactly the rows the padded
    direct forward produces — bitwise for fp32 (same executable, same
    batch layout, slicing only)."""
    net, params = _mlp_net(), _mlp_params()
    cache = PersistentExecutableCache(net, params, {}, cache_dir=None)
    rs = np.random.RandomState(3)
    x = rs.rand(3, 8).astype("float32")
    with InferenceEngine(cache, {"data": (8,)}, buckets=(4, 8),
                         max_delay_ms=1) as eng:
        got = eng.infer({"data": x})[0]
    pad = np.zeros((4, 8), "float32")
    pad[:3] = x
    want = _direct_forward(net, params, pad)[:3]
    np.testing.assert_array_equal(got, want)


def test_bucket_selection_smallest_covering(tm):
    tm.set_mode("counters")
    cache = PersistentExecutableCache(_mlp_net(), _mlp_params(), {},
                                      cache_dir=None)
    eng = InferenceEngine(cache, {"data": (8,)}, buckets=(1, 2, 4, 8),
                          max_delay_ms=0)
    eng.start()
    try:
        for rows, want_bucket in ((1, 1), (2, 2), (3, 4), (5, 8)):
            c0 = tm.counters()
            out = eng.infer({"data": np.zeros((rows, 8), "float32")})
            assert out[0].shape == (rows, 5)
            c1 = tm.counters()
            got = c1["serving.batch_capacity"] - \
                c0.get("serving.batch_capacity", 0)
            assert got == want_bucket, (rows, got, want_bucket)
    finally:
        eng.close()


def test_deadline_triggered_partial_batch(tm):
    """Requests smaller than the largest bucket dispatch when the batching
    deadline expires, not when the bucket fills."""
    tm.set_mode("counters")
    cache = PersistentExecutableCache(_mlp_net(), _mlp_params(), {},
                                      cache_dir=None)
    eng = InferenceEngine(cache, {"data": (8,)}, buckets=(8,),
                          max_delay_ms=50)
    eng.start()
    try:
        t0 = time.perf_counter()
        f1 = eng.submit({"data": np.zeros((1, 8), "float32")})
        f2 = eng.submit({"data": np.zeros((2, 8), "float32")})
        r = f1.result(timeout=10.0)
        waited = time.perf_counter() - t0
        f2.result(timeout=10.0)
        assert r[0].shape == (1, 5)
        # dispatched as ONE partial batch of 3/8 after the deadline
        snap = tm.counters()
        assert snap["serving.batches"] == 1
        assert snap["serving.batch_items"] == 3
        assert snap["serving.batch_capacity"] == 8
        assert waited >= 0.045, "dispatched before the deadline"
        assert telemetry.gauge("serving.batch_occupancy").value == \
            pytest.approx(3 / 8)
    finally:
        eng.close()


def test_full_bucket_dispatches_before_deadline():
    cache = PersistentExecutableCache(_mlp_net(), _mlp_params(), {},
                                      cache_dir=None)
    eng = InferenceEngine(cache, {"data": (8,)}, buckets=(2,),
                          max_delay_ms=10_000)
    eng.start()
    try:
        t0 = time.perf_counter()
        f1 = eng.submit({"data": np.zeros((1, 8), "float32")})
        f2 = eng.submit({"data": np.zeros((1, 8), "float32")})
        f1.result(timeout=10.0)
        f2.result(timeout=10.0)
        assert time.perf_counter() - t0 < 5.0, \
            "a full bucket waited for the deadline"
    finally:
        eng.close()


def test_oversize_request_rejected():
    cache = PersistentExecutableCache(_mlp_net(), _mlp_params(), {},
                                      cache_dir=None)
    with InferenceEngine(cache, {"data": (8,)}, buckets=(1, 4),
                         max_delay_ms=1) as eng:
        with pytest.raises(MXNetError, match="exceed the largest bucket"):
            eng.submit({"data": np.zeros((5, 8), "float32")})
        # wrong item shape is rejected too (it would silently mis-pad)
        with pytest.raises(MXNetError, match="item shape"):
            eng.submit({"data": np.zeros((2, 9), "float32")})


def test_rejected_counter_counts_oversize(tm):
    """serving.rejected is the load-shedding row: oversize/malformed
    submits count, not just queue-full backpressure."""
    tm.set_mode("counters")
    cache = PersistentExecutableCache(_mlp_net(), _mlp_params(), {},
                                      cache_dir=None)
    with InferenceEngine(cache, {"data": (8,)}, buckets=(1, 4),
                         max_delay_ms=1) as eng:
        c0 = tm.counters().get("serving.rejected", 0)
        with pytest.raises(MXNetError):
            eng.submit({"data": np.zeros((5, 8), "float32")})  # oversize
        with pytest.raises(MXNetError):
            eng.submit({"data": np.zeros((2, 9), "float32")})  # bad shape
        assert tm.counters().get("serving.rejected", 0) == c0 + 2


def test_non_batch_major_output_replicated_whole():
    """An output whose leading dim does NOT scale with the bucket (here a
    per-unit weight reduction of constant shape (8,)) is delivered whole
    to every request — even when that dim coincidentally divides the
    dispatched bucket, which a runtime divisibility test would mis-slice."""
    rs = np.random.RandomState(2)
    params = {"fc_weight": rs.randn(8, 8).astype("float32"),
              "fc_bias": rs.randn(8).astype("float32")}
    net = mx.sym.Group([
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=8,
                              name="fc"),
        mx.sym.sum(mx.sym.Variable("fc_weight"), axis=1, name="wsum")])
    # the classification is pure shape inference, so it must hold even on
    # a SINGLE-bucket ladder where no cross-bucket comparison exists and
    # wsum's dim 8 coincidentally divides the lone bucket
    for buckets in ((1, 8), (8,)):
        cache = PersistentExecutableCache(net, params, {}, cache_dir=None)
        with InferenceEngine(cache, {"data": (8,)}, buckets=buckets,
                             max_delay_ms=1) as eng:
            out = eng.infer({"data": rs.rand(5, 8).astype("float32")})
        assert out[0].shape == (5, 8), buckets  # batch-major: sliced
        assert out[1].shape == (8,), buckets  # constant: replicated whole
        np.testing.assert_allclose(out[1], params["fc_weight"].sum(axis=1),
                                   rtol=1e-6)


def test_cross_thread_queue_ordering_and_correctness():
    """Concurrent submitters each get THEIR outputs back, and a request is
    never overtaken by one submitted after it (per-thread submit order is
    preserved in completion timestamps)."""
    net, params = _mlp_net(), _mlp_params()
    cache = PersistentExecutableCache(net, params, {}, cache_dir=None)
    results = {}
    errs = []

    def worker(tid):
        try:
            futs = []
            for j in range(6):
                x = np.full((1, 8), tid * 10 + j, "float32")
                futs.append((j, x, eng.submit({"data": x})))
            for j, x, f in futs:
                results[(tid, j)] = (x, f.result(timeout=30.0)[0], f.done_at)
        except Exception as exc:  # pragma: no cover - surfaced by assert
            errs.append(exc)

    with InferenceEngine(cache, {"data": (8,)}, buckets=(1, 2, 4),
                         max_delay_ms=2) as eng:
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not errs, errs
    assert len(results) == 30
    for (tid, j), (x, got, _) in results.items():
        want = _direct_forward(net, params, np.tile(x, (1, 1)))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    for tid in range(5):
        stamps = [results[(tid, j)][2] for j in range(6)]
        assert stamps == sorted(stamps), \
            "completions overtook submit order within a thread"


def test_engine_unknown_input_name_rejected():
    cache = PersistentExecutableCache(_mlp_net(), _mlp_params(), {},
                                      cache_dir=None)
    with pytest.raises(MXNetError, match="not model inputs"):
        InferenceEngine(cache, {"nope": (8,)}, buckets=(1,))


# ------------------------------------------------------------ predictor
def test_predictor_zero_recompiles_across_100_calls(tm, tmp_path):
    """The satellite regression: repeated forward() at an identical shape
    is a guaranteed executable-cache hit — 0 recompiles across 100 calls."""
    from mxnet_tpu.predictor import Predictor

    tm.set_mode("counters")
    rs = np.random.RandomState(0)
    net = _mlp_net()
    p = str(tmp_path / "m.params")
    mx.nd.save(p, {"arg:fc_weight": mx.nd.array(rs.randn(5, 8)
                                                .astype("float32")),
                   "arg:fc_bias": mx.nd.array(rs.randn(5)
                                              .astype("float32"))})
    pred = Predictor(net.tojson(), open(p, "rb").read(), {"data": (4, 8)})
    x = rs.rand(4, 8).astype("float32")
    pred.forward(data=x)
    first = pred.get_output(0).copy()
    base = tm.counters().get("executor.compile", 0)
    for _ in range(100):
        pred.forward(data=x)
    snap = tm.counters()
    assert snap.get("executor.compile", 0) == base, \
        "forward() recompiled on a repeated identical shape"
    assert snap.get("executor.retrace", 0) == 0
    np.testing.assert_array_equal(pred.get_output(0), first)


def test_predictor_reshape_roundtrip_reuses_executable(tm, tmp_path):
    from mxnet_tpu.predictor import Predictor

    tm.set_mode("counters")
    rs = np.random.RandomState(0)
    p = str(tmp_path / "m.params")
    mx.nd.save(p, {"arg:fc_weight": mx.nd.array(rs.randn(5, 8)
                                                .astype("float32")),
                   "arg:fc_bias": mx.nd.zeros((5,))})
    pred = Predictor(_mlp_net().tojson(), open(p, "rb").read(),
                     {"data": (4, 8)})
    x = rs.rand(4, 8).astype("float32")
    pred.forward(data=x)
    want = pred.get_output(0).copy()
    pred.reshape({"data": (2, 8)})
    pred.forward(data=x[:2])
    compiles = tm.counters().get("executor.compile", 0)
    pred.reshape({"data": (4, 8)})  # back to a seen shape: cache hit
    pred.forward(data=x)
    assert tm.counters().get("executor.compile", 0) == compiles, \
        "reshape back to a known shape recompiled"
    np.testing.assert_array_equal(pred.get_output(0), want)


def test_predictor_reshape_lru_bounded(tm, tmp_path, monkeypatch):
    """An unsealed (predict-API) cache is LRU-bounded: reshaping through
    more distinct shapes than MXNET_SERVE_MAX_EXECUTABLES retains at most
    the cap, recent shapes stay zero-recompile, and an evicted shape
    recompiles once instead of growing memory forever."""
    from mxnet_tpu.predictor import Predictor

    tm.set_mode("counters")
    monkeypatch.setenv("MXNET_SERVE_MAX_EXECUTABLES", "3")
    rs = np.random.RandomState(0)
    p = str(tmp_path / "m.params")
    mx.nd.save(p, {"arg:fc_weight": mx.nd.array(rs.randn(5, 8)
                                                .astype("float32")),
                   "arg:fc_bias": mx.nd.zeros((5,))})
    pred = Predictor(_mlp_net().tojson(), open(p, "rb").read(),
                     {"data": (1, 8)})
    for b in (2, 3, 4, 5, 6):
        pred.reshape({"data": (b, 8)})
        pred.forward(data=rs.rand(b, 8).astype("float32"))
    assert len(pred._cache.keys()) == 3
    assert tm.counters().get("serving.executable_evict", 0) == 3
    c = tm.counters().get("executor.compile", 0)
    pred.reshape({"data": (6, 8)})  # most recent: still cached
    pred.forward(data=rs.rand(6, 8).astype("float32"))
    assert tm.counters().get("executor.compile", 0) == c
    pred.reshape({"data": (1, 8)})  # evicted long ago: recompiles once
    pred.forward(data=rs.rand(1, 8).astype("float32"))
    assert tm.counters().get("executor.compile", 0) > c


# ------------------------------------------- fusion inference mode + quant
def _conv_bn_net():
    s = mx.sym.Variable("data")
    s = mx.sym.BatchNorm(s, name="bn0", fix_gamma=False)
    s = mx.sym.Activation(s, act_type="relu")
    s = mx.sym.Convolution(s, kernel=(3, 3), pad=(1, 1), num_filter=8,
                           no_bias=True, name="conv1")
    s = mx.sym.BatchNorm(s, name="bn1", fix_gamma=False)
    s = mx.sym.Activation(s, act_type="relu")
    s = mx.sym.Flatten(s)
    s = mx.sym.FullyConnected(s, num_hidden=4, name="fc")
    return mx.sym.SoftmaxOutput(s, name="softmax")


def _infer_forward(seed=7):
    net = _conv_bn_net()
    exe = net.simple_bind(mx.cpu(), grad_req="null", data=(2, 8, 8, 8))
    # deterministic per-param seeds (no hash(): PYTHONHASHSEED varies);
    # moving stats near (0, 1) keep the post-BN relus from clamping the
    # whole activation to zero, which would mask the quantized conv
    for i, k in enumerate(sorted(exe.arg_dict)):
        if k == "data":
            continue
        arr = exe.arg_dict[k]
        rs = np.random.RandomState(100 + i)
        arr[:] = (rs.randn(*arr.shape) * 0.3
                  + (1.0 if "gamma" in k else 0.0)).astype("float32")
    for i, k in enumerate(sorted(exe.aux_dict)):
        arr = exe.aux_dict[k]
        arr[:] = (np.full(arr.shape, 0.1, "float32") if "mean" in k
                  else np.ones(arr.shape, "float32"))
    x = np.random.RandomState(seed).rand(2, 8, 8, 8).astype("float32")
    exe.arg_dict["data"][:] = x
    exe.forward(is_train=False)
    return exe.outputs[0].asnumpy()


def test_fusion_inference_gate_trigger(tm, monkeypatch):
    """Forced fusion engages the Pallas path on a grad-less bind
    (fusion.infer_engaged fires) and matches the unfused inference
    output."""
    tm.set_mode("counters")
    monkeypatch.setenv("MXNET_FUSED_CONV_BN", "0")
    base = _infer_forward()
    monkeypatch.setenv("MXNET_FUSED_CONV_BN", "1")
    c0 = tm.counters()
    fused = _infer_forward()
    c1 = tm.counters()
    assert c1.get("fusion.infer_engaged", 0) > \
        c0.get("fusion.infer_engaged", 0)
    np.testing.assert_allclose(fused, base, rtol=1e-5, atol=1e-6)


def test_fusion_inference_gate_clean(tm, monkeypatch):
    """Auto mode on CPU (no device-matched WINS table, no quant): the
    inference plan stays INACTIVE — no engage/fallback counters, output
    byte-identical to fusion-off."""
    tm.set_mode("counters")
    monkeypatch.setenv("MXNET_FUSED_CONV_BN", "0")
    base = _infer_forward()
    monkeypatch.delenv("MXNET_FUSED_CONV_BN", raising=False)
    monkeypatch.delenv("MXNET_SERVE_QUANT", raising=False)
    c0 = tm.counters()
    auto = _infer_forward()
    c1 = tm.counters()
    assert c1.get("fusion.infer_engaged", 0) == \
        c0.get("fusion.infer_engaged", 0)
    assert c1.get("fusion.infer_fallback", 0) == \
        c0.get("fusion.infer_fallback", 0)
    np.testing.assert_array_equal(auto, base)


@pytest.mark.parametrize("quant,tol", [("bf16", 0.05), ("int8", 0.02)])
def test_quantized_inference_variants(tm, monkeypatch, quant, tol):
    """MXNET_SERVE_QUANT activates the inference plan even in auto mode
    (the quantized weights ride the fused execute path) and stays within
    the quantization error budget of the fp32 output."""
    tm.set_mode("counters")
    monkeypatch.setenv("MXNET_FUSED_CONV_BN", "0")
    base = _infer_forward()
    monkeypatch.delenv("MXNET_FUSED_CONV_BN", raising=False)
    monkeypatch.setenv("MXNET_SERVE_QUANT", quant)
    from mxnet_tpu import fusion

    assert fusion.quant_mode() == quant
    assert fusion.infer_default()
    q = _infer_forward()
    assert np.abs(q - base).max() < tol
    assert np.abs(q - base).max() > 0  # it actually quantized something


def test_quant_mode_unrecognized_stays_off(monkeypatch):
    from mxnet_tpu import fusion

    monkeypatch.setenv("MXNET_SERVE_QUANT", "fp4")
    assert fusion.quant_mode() == "off"


def test_fusion_training_unchanged_by_inference_mode(monkeypatch):
    """The inference predicate must not leak into training binds: a train
    forward/backward under forced fusion still runs (regression guard for
    the executor's fusion_on change)."""
    monkeypatch.setenv("MXNET_FUSED_CONV_BN", "1")
    net = _conv_bn_net()
    exe = net.simple_bind(mx.cpu(), data=(2, 8, 8, 8), softmax_label=(2,))
    exe.arg_dict["data"][:] = np.random.RandomState(0).rand(
        2, 8, 8, 8).astype("float32")
    exe.forward(is_train=True)
    exe.backward()
    assert np.isfinite(exe.outputs[0].asnumpy()).all()


# ------------------------------------------------------------ serve_bench
@pytest.mark.slow
def test_serve_bench_check_smoke():
    import subprocess
    import sys

    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "MXNET_DEFAULT_CONTEXT": "cpu"})
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "serve_bench.py"),
         "--model", "mlp", "--qps", "60", "--duration", "1", "--check"],
        capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)
    assert r.returncode == 0, (r.stdout + r.stderr)[-800:]
    rec = json.loads([l for l in r.stdout.splitlines()
                      if l.startswith("{")][-1])
    assert rec["qps"] > 0 and rec["retraces_post_warmup"] == 0


# ------------------------------------------------- batcher failure latch
def test_batcher_death_latches_and_fails_fast(tm):
    """A dead batcher thread must not strand its callers: the in-flight
    batch's futures fail, the engine latches, and later ``submit()`` /
    ``start()`` raise promptly instead of hanging forever (the
    PrefetchingIter._shutdown latch pattern)."""
    tm.set_mode("counters")
    cache = PersistentExecutableCache(_mlp_net(), _mlp_params(), {},
                                      cache_dir=None)
    eng = InferenceEngine(cache, {"data": (8,)}, buckets=(2,),
                          max_delay_ms=1)
    eng.start()
    try:
        def boom(batch):
            # non-Exception: escapes the per-batch handler and kills the
            # thread — exactly the case that used to hang every future
            raise KeyboardInterrupt("simulated batcher death")

        eng._dispatch = boom
        f1 = eng.submit({"data": np.zeros((2, 8), "float32")})
        with pytest.raises((MXNetError, KeyboardInterrupt)):
            f1.result(timeout=10)
        eng._thread.join(timeout=10)
        t0 = time.time()
        with pytest.raises(MXNetError, match="latched|died"):
            eng.submit({"data": np.zeros((1, 8), "float32")})
        assert time.time() - t0 < 5, "submit after batcher death must " \
            "fail promptly, not hang"
        with pytest.raises(MXNetError, match="latched|died"):
            eng.start()
        assert telemetry.counter("serving.batcher_deaths").value == 1
    finally:
        eng._started = False  # thread already dead; skip close()'s join


def test_latch_fails_pending_queued_futures():
    """Requests still sitting in the queue when the batcher dies get their
    futures failed immediately — no waiter left behind."""
    cache = PersistentExecutableCache(_mlp_net(), _mlp_params(), {},
                                      cache_dir=None)
    eng = InferenceEngine(cache, {"data": (8,)}, buckets=(8,),
                          max_delay_ms=5000)
    eng.start()
    try:
        # 1 row into an 8-bucket: the batcher holds it in the queue while
        # waiting out the 5s admission deadline
        fut = eng.submit({"data": np.zeros((1, 8), "float32")})
        deadline = time.time() + 5
        while not eng._queue and time.time() < deadline:
            time.sleep(0.005)
        eng._latch_failure(RuntimeError("simulated death"))
        with pytest.raises(MXNetError, match="died"):
            fut.result(timeout=5)
        with pytest.raises(MXNetError, match="died"):
            eng.submit({"data": np.zeros((1, 8), "float32")})
    finally:
        eng._started = False
