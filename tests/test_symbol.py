"""Symbol composition / inference / JSON tests.

Modeled on the reference's tests/python/unittest/test_symbol.py and
test_infer_shape.py (composition, list_arguments, infer_shape chains,
attr handling, internals, save/load)."""
import json

import numpy as np
import pytest

import mxnet_tpu as mx


def mlp_two_layers():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data=data, name="fc1", num_hidden=10)
    net = mx.sym.Activation(data=net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(data=net, name="fc2", num_hidden=100)
    return net


def test_symbol_basic_compose():
    net = mlp_two_layers()
    assert net.list_arguments() == [
        "data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias",
    ]
    assert net.list_outputs() == ["fc2_output"]
    assert net.name == "fc2"


def test_symbol_infer_shape_mlp():
    net = mlp_two_layers()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(32, 200))
    assert arg_shapes == [(32, 200), (10, 200), (10,), (100, 10), (100,)]
    assert out_shapes == [(32, 100)]
    assert aux_shapes == []


def test_symbol_infer_shape_underdetermined():
    net = mlp_two_layers()
    arg, out, aux = net.infer_shape()
    assert arg is None and out is None and aux is None


def test_symbol_infer_shape_partial():
    data = mx.sym.Variable("data")
    prev = mx.sym.Variable("prev")
    net = mx.sym.FullyConnected(data=data, name="fc1", num_hidden=128)
    net2 = mx.sym.FullyConnected(data=prev, name="fc2", num_hidden=128)
    out = net + net2
    arg_shapes, _, _ = out.infer_shape_partial(data=(10, 64))
    args = out.list_arguments()
    d = dict(zip(args, arg_shapes))
    assert d["fc1_weight"] == (128, 64)
    assert d["prev"] is None
    assert d["fc2_weight"] is None


def test_symbol_infer_conv_chain():
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data=data, kernel=(3, 3), num_filter=16, pad=(1, 1), name="c")
    b = mx.sym.BatchNorm(data=c, name="b")
    p = mx.sym.Pooling(data=b, kernel=(2, 2), stride=(2, 2), pool_type="max")
    arg_shapes, out_shapes, aux_shapes = p.infer_shape(data=(2, 3, 32, 32))
    d = dict(zip(p.list_arguments(), arg_shapes))
    assert d["c_weight"] == (16, 3, 3, 3)
    assert d["c_bias"] == (16,)
    assert d["b_gamma"] == (16,)
    assert out_shapes == [(2, 16, 16, 16)]
    assert aux_shapes == [(16,), (16,)]
    assert p.list_auxiliary_states() == ["b_moving_mean", "b_moving_var"]


def test_symbol_infer_type():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=4, name="fc")
    arg_types, out_types, _ = fc.infer_type(data=np.float32)
    assert all(t == np.dtype(np.float32) for t in arg_types)
    assert out_types == [np.dtype(np.float32)]


def test_symbol_group_and_getitem():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data=data, num_hidden=4, name="fc1")
    fc2 = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc2")
    g = mx.sym.Group([fc1, fc2])
    assert g.list_outputs() == ["fc1_output", "fc2_output"]
    assert len(g) == 2
    sub = g["fc2_output"]
    assert sub.list_outputs() == ["fc2_output"]
    with pytest.raises(mx.MXNetError):
        g["nope"]


def test_symbol_internals():
    net = mlp_two_layers()
    internals = net.get_internals()
    names = internals.list_outputs()
    assert "fc1_output" in names and "relu1_output" in names
    fc1 = internals["fc1_output"]
    assert fc1.list_arguments() == ["data", "fc1_weight", "fc1_bias"]


def test_symbol_arithmetic_and_scalar():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    c = (a + b) * 2.0 - a / b + (a ** 2)
    ex = c.bind(mx.cpu(), {"a": mx.nd.array([4.0]), "b": mx.nd.array([2.0])})
    out = ex.forward()
    # (4+2)*2 - 4/2 + 16 = 26
    assert np.allclose(out[0].asnumpy(), [26.0])


def test_symbol_attr_and_scope():
    data = mx.sym.Variable("data", shape=(3, 4), lr_mult=2.0)
    assert data.attr("__shape__") == "(3, 4)"
    assert data.attr("__lr_mult__") == "2.0"
    with mx.AttrScope(ctx_group="dev1"):
        fc = mx.sym.FullyConnected(data=data, num_hidden=3, name="fc")
    assert fc.attr("__ctx_group__") == "dev1"
    # shape attr participates in inference
    arg_shapes, out_shapes, _ = data.infer_shape()
    assert arg_shapes == [(3, 4)]


def test_symbol_variable_shape_used_in_bind():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=3, name="fc")
    arg_shapes, out_shapes, _ = fc.infer_shape(data=(5, 7))
    assert out_shapes == [(5, 3)]


def test_symbol_json_roundtrip():
    net = mlp_two_layers()
    js = net.tojson()
    graph = json.loads(js)
    assert "nodes" in graph and "arg_nodes" in graph and "heads" in graph
    assert "node_row_ptr" in graph
    net2 = mx.sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    assert net2.list_outputs() == net.list_outputs()
    a1, o1, _ = net.infer_shape(data=(4, 6))
    a2, o2, _ = net2.infer_shape(data=(4, 6))
    assert a1 == a2 and o1 == o2


def test_symbol_json_file_roundtrip(tmp_path):
    net = mlp_two_layers()
    fname = str(tmp_path / "net-symbol.json")
    net.save(fname)
    net2 = mx.sym.load(fname)
    assert net2.list_outputs() == ["fc2_output"]


def test_symbol_json_attr_stringified():
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data=data, kernel=(3, 3), num_filter=4, stride=(2, 2), name="c")
    graph = json.loads(c.tojson())
    node = [n for n in graph["nodes"] if n["name"] == "c"][0]
    assert node["attr"]["kernel"] == "(3, 3)"
    # attrs parse back identically
    c2 = mx.sym.load_json(c.tojson())
    a1, o1, _ = c.infer_shape(data=(1, 2, 8, 8))
    a2, o2, _ = c2.infer_shape(data=(1, 2, 8, 8))
    assert o1 == o2 == [(1, 4, 3, 3)]


def test_symbol_multi_output_indexing():
    data = mx.sym.Variable("data")
    sliced = mx.sym.SliceChannel(data=data, num_outputs=3, name="slice")
    assert len(sliced) == 3
    assert sliced.list_outputs() == ["slice_output0", "slice_output1", "slice_output2"]
    one = sliced[1]
    ex = one.bind(mx.cpu(), {"data": mx.nd.array(np.arange(6).reshape(2, 3).astype("f"))})
    out = ex.forward()
    assert np.allclose(out[0].asnumpy(), [[1.0], [4.0]])


def test_symbol_variadic_concat():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    c = mx.sym.Concat(a, b, dim=1, name="cat")
    arg_shapes, out_shapes, _ = c.infer_shape(a=(2, 3), b=(2, 5))
    assert out_shapes == [(2, 8)]


def test_symbol_name_manager_unique():
    with mx.name.NameManager():
        f1 = mx.sym.FullyConnected(data=mx.sym.Variable("x"), num_hidden=2)
        f2 = mx.sym.FullyConnected(data=mx.sym.Variable("y"), num_hidden=2)
        assert f1.name != f2.name
    with mx.name.Prefix("net_"):
        f3 = mx.sym.FullyConnected(data=mx.sym.Variable("z"), num_hidden=2)
        assert f3.name.startswith("net_")


def test_symbol_deep_graph_no_recursion():
    x = mx.sym.Variable("x")
    net = x
    for _ in range(2000):
        net = net + 1.0
    assert len(net.list_arguments()) == 1
    assert net.tojson()
