"""Graph-rewrite pass framework tests (analysis/rewrite.py, ISSUE 14).

Covers each builtin pass (const fold, CSE, canonicalize, bf16 legalize,
DCE) with its bit-parity contract, pipeline idempotence (running twice is a
no-op with zero provenance records on pass 2), the bind-time
MXNET_GRAPHREWRITE integration on both executor paths, the fusion-site
acceptance (canonicalization strictly increases matched norm_residual
sites on the transformer zoo model), and the cached per-program fusion
site inventory.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import analysis


@pytest.fixture(autouse=True)
def _pin_rewrite_env(monkeypatch):
    # the bitwise-parity assertions assume the default pass set: an
    # ambient MXNET_GRAPHREWRITE[_BF16] would change what rewrite() does
    monkeypatch.delenv("MXNET_GRAPHREWRITE", raising=False)
    monkeypatch.delenv("MXNET_GRAPHREWRITE_BF16", raising=False)


def _tiny_transformer():
    return mx.models.get_symbol("transformer", vocab_size=50, model_dim=32,
                                num_heads=2, num_layers=1, ffn_dim=64,
                                seq_len=8)


_TF_SHAPES = {"data": (2, 8), "softmax_label": (2, 8)}
_TF_TYPES = {"data": "int32"}


def _fill(ex, seed=1):
    rs = np.random.RandomState(seed)
    for n, a in zip(ex._prog.arg_names, ex.arg_arrays):
        if np.issubdtype(np.dtype(a.dtype), np.integer):
            a[:] = rs.randint(0, 50, a.shape).astype(a.dtype)
        elif "label" in n:
            a[:] = rs.randint(0, 10, a.shape).astype(a.dtype)
        else:
            a[:] = rs.uniform(-0.1, 0.1, a.shape).astype(a.dtype)


def _fwd_bwd(sym, shapes, types=None, seed=1, grad_req="write"):
    mx.random.seed(7)
    ex = sym.simple_bind(mx.cpu(), type_dict=types, grad_req=grad_req,
                         **shapes)
    _fill(ex, seed)
    ex.forward(is_train=True)
    ex.backward()
    grads = {n: (g.asnumpy() if g is not None else None)
             for n, g in zip(ex._prog.arg_names, ex.grad_arrays)}
    return [o.asnumpy() for o in ex.outputs], grads


# --------------------------------------------------------------- const fold
def test_const_fold_evaluates_init_subgraph_once():
    x = mx.sym.Variable("x")
    scale = mx.sym._ones(shape=(4,)) * 3.0  # init-op subgraph: foldable
    net = mx.sym.broadcast_mul(x, scale, name="out")
    res = analysis.rewrite(net, shapes={"x": (2, 4)})
    assert res.counts["folded"] == 1
    ops = [n.op for n in res.symbol._topo() if n.op]
    assert "_graph_const" in ops and "_ones" not in ops
    # the fold is bitwise: same forward as the unfolded graph
    a, _ = _fwd_bwd(net, {"x": (2, 4)})
    b, _ = _fwd_bwd(res.symbol, {"x": (2, 4)})
    assert np.array_equal(a[0], b[0])


def test_const_fold_never_touches_variables_or_aux():
    # a parameter-fed subgraph must NOT fold (weights are runtime values)
    x = mx.sym.Variable("x")
    w = mx.sym.Variable("w", shape=(4,))
    net = mx.sym.broadcast_mul(x, w * 2.0)
    res = analysis.rewrite(net, shapes={"x": (2, 4)})
    assert res.counts["folded"] == 0
    assert res.symbol.list_arguments() == net.list_arguments()


# -------------------------------------------------------------------- cse
def test_cse_merges_duplicate_subexpressions_bitwise():
    a, b = mx.sym.Variable("a"), mx.sym.Variable("b")
    net = (a + b) * (a + b)
    res = analysis.rewrite(net, shapes={"a": (3,), "b": (3,)})
    assert res.counts["merged"] == 1
    o1, g1 = _fwd_bwd(net, {"a": (3,), "b": (3,)})
    o2, g2 = _fwd_bwd(res.symbol, {"a": (3,), "b": (3,)})
    assert np.array_equal(o1[0], o2[0])
    for k in g1:
        assert np.array_equal(g1[k], g2[k]), k


def test_cse_never_merges_stateful_ops():
    # two Dropouts over the same input are two masks; two BatchNorms are
    # two moving-stat updates — neither may merge
    x = mx.sym.Variable("x")
    net = mx.sym.Dropout(x, p=0.5, name="d1") + mx.sym.Dropout(
        x, p=0.5, name="d2")
    res = analysis.rewrite(net, shapes={"x": (4, 4)})
    assert res.counts["merged"] == 0
    x2 = mx.sym.Variable("y")
    bn = mx.sym.BatchNorm(x2, name="bn1") + mx.sym.BatchNorm(x2, name="bn2")
    res2 = analysis.rewrite(bn, shapes={"y": (4, 4)})
    merged = [r for r in res2.records if r["action"] == "merge"
              and "bn" in (r["node"] or "")]
    assert not merged


# ------------------------------------------------------------ canonicalize
@pytest.mark.parametrize("build,rule", [
    (lambda x: x * x, "mul_self_to_square"),
    (lambda x: mx.sym.relu(x), "relu_to_activation"),
    (lambda x: 1.0 / mx.sym.sqrt(x + 2.0), "rsqrt_compose"),
    (lambda x: mx.sym.reciprocal(mx.sym.sqrt(x + 2.0)), "rsqrt_compose"),
    (lambda x: (x * 1.0) + 0.5, "identity_elide"),
], ids=["square", "relu", "rdiv_sqrt", "recip_sqrt", "mul_one"])
def test_canonicalize_rules_fire_and_stay_bitwise(build, rule):
    x = mx.sym.Variable("x")
    net = build(x)
    res = analysis.rewrite(net, shapes={"x": (16,)})
    assert "canonicalize." + rule in res.rule_table(), res.rule_table()
    o1, g1 = _fwd_bwd(net, {"x": (16,)})
    o2, g2 = _fwd_bwd(res.symbol, {"x": (16,)})
    assert np.array_equal(o1[0], o2[0])  # forward: bitwise, every rule
    if rule == "rsqrt_compose":
        # rsqrt's vjp is a different (mathematically equal) expression
        # than the composed div∘sqrt chain rule — single-ulp drift,
        # same documented backward tolerance as CSE
        np.testing.assert_allclose(g1["x"], g2["x"], atol=1e-6, rtol=0)
    else:
        assert np.array_equal(g1["x"], g2["x"])


def test_canonicalize_negative_axis_normalization():
    x = mx.sym.Variable("x")
    net = mx.sym.broadcast_sub(x, mx.sym.mean(x, axis=2, keepdims=True))
    res = analysis.rewrite(net, shapes={"x": (2, 3, 8)})
    assert "canonicalize.negative_axis" in res.rule_table()
    mean_node = [n for n in res.symbol._topo() if n.op == "mean"][0]
    assert tuple(mean_node.parsed_attrs()["axis"]) == (-1,)
    o1, _ = _fwd_bwd(net, {"x": (2, 3, 8)})
    o2, _ = _fwd_bwd(res.symbol, {"x": (2, 3, 8)})
    assert np.array_equal(o1[0], o2[0])


def test_canonicalize_keeps_output_identity_nodes():
    # an identity op that IS a program output must not be elided (its name
    # is the output name)
    x = mx.sym.Variable("x")
    net = x * 1.0
    res = analysis.rewrite(net, shapes={"x": (4,)})
    assert res.symbol.list_outputs() == net.list_outputs()


# ----------------------------------------------- transformer parity + sites
def test_transformer_rewrite_parity_and_node_reduction():
    """The zoo transformer's sloppy-frontend LN: CSE+canonicalize+DCE must
    shrink the graph, keep the forward BITWISE, and keep the backward
    within documented single-ulp cotangent-reassociation drift."""
    net = _tiny_transformer()
    res = analysis.rewrite(net, shapes=_TF_SHAPES, types=_TF_TYPES)
    assert res.counts["merged"] > 0 and res.counts["removed"] > 0
    assert res.nodes_after < res.nodes_before
    o1, g1 = _fwd_bwd(net, _TF_SHAPES, _TF_TYPES)
    o2, g2 = _fwd_bwd(res.symbol, _TF_SHAPES, _TF_TYPES)
    assert np.array_equal(o1[0], o2[0])  # forward: bitwise
    for k in g1:
        if g1[k] is None:
            continue
        # backward: the merged graph sums cotangents in a different order
        # than the duplicated one — ≤1e-6 absolute (measured ~3e-8)
        np.testing.assert_allclose(g1[k], g2[k], atol=1e-6, rtol=0,
                                   err_msg=k)


def test_canonicalization_strictly_increases_norm_residual_sites(
        monkeypatch):
    """Acceptance (ISSUE 14): the transformer zoo model matches strictly
    MORE norm_residual fusion sites after the rewrite pipeline."""
    monkeypatch.setenv("MXNET_FUSED_PATTERNS", "auto")
    net = _tiny_transformer()
    before = analysis.pattern_site_counts(net)
    after = analysis.pattern_site_counts(analysis.rewrite(net).symbol)
    assert after.get("norm_residual", 0) > before.get("norm_residual", 0)
    assert after.get("norm_residual") == 3
    # the other patterns are untouched
    assert after.get("attention") == before.get("attention")
    assert after.get("matmul_bias_act") == before.get("matmul_bias_act")


def test_rewrite_idempotent_second_run_is_noop():
    """Running the pipeline twice is a no-op: pass 2 fires zero rules and
    emits zero provenance records (the satellite contract)."""
    net = _tiny_transformer()
    r1 = analysis.rewrite(net, shapes=_TF_SHAPES, types=_TF_TYPES)
    assert r1.changed
    r2 = analysis.rewrite(r1.symbol, shapes=_TF_SHAPES, types=_TF_TYPES)
    assert r2.records == []
    assert not r2.changed
    assert r2.nodes_before == r2.nodes_after == r1.nodes_after
    assert r2.rounds == 1 and r2.fixpoint


# ------------------------------------------------------------------- bf16
def test_bf16_legalization_cast_sandwich():
    net = mx.models.get_symbol("mlp", num_classes=10)
    shapes = {"data": (4, 784), "softmax_label": (4,)}
    res = analysis.rewrite(net, shapes=shapes, bf16=True)
    assert res.counts["casts"] > 0
    rep = analysis.verify_rewrite(res, grad_req="write")
    assert not rep.errors, rep.format()  # GL601-clean: dtypes sandwiched
    casts = [n for n in res.symbol._topo() if n.op == "Cast"]
    assert any(str(n.parsed_attrs()["dtype"]) == "bfloat16" for n in casts)
    # bf16 compute, f32 interface: documented-tolerance parity, not bitwise
    o1, _ = _fwd_bwd(net, shapes)
    o2, _ = _fwd_bwd(res.symbol, shapes)
    assert o1[0].dtype == o2[0].dtype == np.float32
    np.testing.assert_allclose(o1[0], o2[0], atol=5e-2, rtol=0)
    # idempotent: a second run inserts nothing
    r2 = analysis.rewrite(res.symbol, shapes=shapes, bf16=True)
    assert r2.counts["casts"] == 0


def test_bf16_off_by_default():
    net = mx.models.get_symbol("mlp", num_classes=10)
    res = analysis.rewrite(net, shapes={"data": (4, 784)})
    assert res.counts["casts"] == 0


# -------------------------------------------------------- bind integration
def test_bind_rewrites_under_env_and_stays_bitwise(monkeypatch):
    net = _tiny_transformer()
    o1, _ = _fwd_bwd(net, _TF_SHAPES, _TF_TYPES)
    n_raw = len(net._topo())
    monkeypatch.setenv("MXNET_GRAPHREWRITE", "on")
    mx.random.seed(7)
    ex = net.simple_bind(mx.cpu(), type_dict=_TF_TYPES, grad_req="write",
                         **_TF_SHAPES)
    assert len(ex._prog.topo) < n_raw  # bound program IS the rewritten one
    assert ex._orig_symbol is net
    _fill(ex)
    ex.forward(is_train=True)
    assert np.array_equal(o1[0], ex.outputs[0].asnumpy())


def test_bind_verify_mode_clean_zoo(monkeypatch):
    monkeypatch.setenv("MXNET_GRAPHREWRITE", "verify")
    net = mx.models.get_symbol("mlp", num_classes=10)
    ex = net.simple_bind(mx.cpu(), data=(4, 784), softmax_label=(4,))
    assert ex.forward(is_train=False)[0].shape == (4, 10)


def test_bind_rewrite_off_by_default(monkeypatch):
    monkeypatch.delenv("MXNET_GRAPHREWRITE", raising=False)
    assert analysis.graphrewrite_mode() is None
    net = _tiny_transformer()
    ex = net.simple_bind(mx.cpu(), type_dict=_TF_TYPES, grad_req="write",
                         **_TF_SHAPES)
    assert len(ex._prog.topo) == len(net._topo())


def test_graphrewrite_mode_aliases_and_unknown(monkeypatch, caplog):
    monkeypatch.setenv("MXNET_GRAPHREWRITE", "1")
    assert analysis.graphrewrite_mode() == "on"
    monkeypatch.setenv("MXNET_GRAPHREWRITE", "verify")
    assert analysis.graphrewrite_mode() == "verify"
    monkeypatch.setenv("MXNET_GRAPHREWRITE", "bogus")
    with caplog.at_level("WARNING", logger="mxnet_tpu.graphrewrite"):
        assert analysis.graphrewrite_mode() is None


def test_spmd_adapter_binds_rewritten_symbol(monkeypatch):
    """The fused-SPMD path compiles the rewritten graph too."""
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    monkeypatch.setenv("MXNET_GRAPHREWRITE", "verify")
    net = _tiny_transformer()
    rs = np.random.RandomState(0)
    it = mx.io.NDArrayIter(rs.randint(0, 50, (8, 8)).astype("int32"),
                           rs.randint(0, 50, (8, 8)).astype("float32"),
                           batch_size=4)
    mod = mx.mod.Module(net, context=mx.cpu(),
                        label_names=("softmax_label",))
    mod.fit(it, num_epoch=1, optimizer="sgd", eval_metric=mx.metric.Loss())
    assert mod._spmd is not None, "fused SPMD step did not engage"
    assert len(mod._spmd.trainer._prog.topo) < len(net._topo())


# ------------------------------------------------------------ observability
def test_rewrite_telemetry_counters(monkeypatch):
    from mxnet_tpu import telemetry

    monkeypatch.setenv("MXNET_TELEMETRY", "counters")
    telemetry.reset()
    analysis.rewrite(_tiny_transformer(), shapes=_TF_SHAPES,
                     types=_TF_TYPES)
    assert telemetry.counter("rewrite.runs").value == 1
    assert telemetry.counter("rewrite.nodes_merged").value > 0
    assert telemetry.counter("rewrite.nodes_removed").value > 0


def test_program_caches_pattern_site_inventory(monkeypatch):
    """Satellite: the bound program carries the plan's per-pattern site
    inventory, computed once — the serving cache reads it verbatim."""
    monkeypatch.setenv("MXNET_FUSED_PATTERNS", "auto")
    from mxnet_tpu.executor import _GraphProgram
    from mxnet_tpu import fusion

    net = analysis.rewrite(_tiny_transformer()).symbol
    prog = _GraphProgram(net)
    sites, conv_bn = fusion.plan_sites(prog._fusion_plan)
    assert prog.pattern_sites == sites
    assert prog.pattern_sites.get("norm_residual") == 3
    assert prog.conv_bn_directives == conv_bn


def test_cli_rewrite_dump_and_json(capsys, monkeypatch):
    monkeypatch.setenv("MXNET_FUSED_PATTERNS", "auto")
    from mxnet_tpu.analysis.cli import main

    rc = main(["transformer", "--rewrite"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "graphrewrite: transformer" in out
    assert "cse.merge" in out and "mul_self_to_square" in out
    assert "norm_residual 0 -> 13" in out
    rc = main(["transformer", "--rewrite", "--rewrite-json"])
    import json as _json

    payload = _json.loads(capsys.readouterr().out)
    assert rc == 0
    entry = payload[0]
    assert entry["rewrite"]["nodes_after"] < entry["rewrite"]["nodes_before"]
    assert entry["fusion_sites_after"]["norm_residual"] == 13
    assert entry["records"], "provenance records missing from the dump"
    assert not [d for d in entry["verify"]["diagnostics"]
                if d["code"] in ("GL601", "GL602", "GL604")]
