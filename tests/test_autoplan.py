"""Auto-parallel planner tests (parallel/autoplan.py, ISSUE 10).

Covers: search determinism, the budget boundary (a plan exactly at budget is
accepted, one byte under rejects it), the indivisible-param replication
fallback matching GL401, the uncapped GL402 totals on Report, the GL501 fix
hint naming the planner, pipeline cuts/splitting, the GPipe microbatch
schedule's gradient parity against a single-stage baseline, the
over-budget-everywhere → pipeline-plan → trains-successfully scenario, the
SPMDStepAdapter MXNET_AUTOPLAN=1 consumption, the graphlint --autoplan CLI,
and the 2-process predicted-vs-measured comm-bytes acceptance (2x band).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import analysis
from mxnet_tpu.module import PipelineExecutorGroup
from mxnet_tpu.parallel import autoplan

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _mlp(hidden=512, layers=2, name_prefix="fc"):
    s = mx.sym.Variable("data")
    for i in range(layers):
        s = mx.sym.FullyConnected(s, num_hidden=hidden,
                                  name="%s%d" % (name_prefix, i))
        s = mx.sym.Activation(s, act_type="relu", name="act%d" % i)
    s = mx.sym.FullyConnected(s, num_hidden=4, name="head")
    return mx.sym.SoftmaxOutput(s, name="softmax")


MLP_SHAPES = {"data": (32, 512)}


# ------------------------------------------------------------------ search
def test_plan_deterministic():
    """Same model + devices (+ budget) => the same plan, bit for bit."""
    a = autoplan.plan_parallel(_mlp(), MLP_SHAPES, devices=8)
    b = autoplan.plan_parallel(_mlp(), MLP_SHAPES, devices=8)
    assert a.to_dict() == b.to_dict()
    # and through the JSON round trip
    c = autoplan.ParallelPlan.from_dict(json.loads(a.to_json()))
    assert c.to_dict() == a.to_dict()


def test_plan_beats_or_matches_naive():
    plan = autoplan.plan_parallel(_mlp(), MLP_SHAPES, devices=8)
    assert plan.feasible
    assert plan.naive is not None
    assert plan.predicted["comm_bytes"] <= plan.naive["comm_bytes"]


def test_budget_boundary():
    """A candidate whose predicted peak is EXACTLY the budget is accepted;
    one byte less rejects it (the winner must then change or pipeline)."""
    free = autoplan.plan_parallel(_mlp(), MLP_SHAPES, devices=8)
    peak = free.predicted["peak_bytes"]

    at = autoplan.plan_parallel(_mlp(), MLP_SHAPES, devices=8,
                                budget_bytes=peak)
    assert at.feasible
    assert at.mesh == free.mesh
    assert at.predicted["peak_bytes"] == peak

    under = autoplan.plan_parallel(_mlp(), MLP_SHAPES, devices=8,
                                   budget_bytes=peak - 1)
    if under.feasible and under.pipeline_stages == 1:
        # another dp x tp candidate fit — but never the at-budget winner
        assert under.predicted["peak_bytes"] <= peak - 1
    assert under.to_dict() != at.to_dict()


def test_indivisible_param_falls_back_to_replication_matching_gl401():
    """hidden=1001 divides no tp in {2,4,8}: the planner must replicate
    every weight (the GL401 fallback), and the GL4xx lint agrees."""
    sym = _mlp(hidden=1001)
    shapes = {"data": (8, 1001)}
    plan = autoplan.plan_parallel(sym, shapes, devices=8)
    for name, axes in plan.param_specs.items():
        assert not any(axes), "planner sharded indivisible param %r" % name

    report = analysis.lint(sym, shapes=shapes, mesh="data=4,model=2")
    gl401 = [d for d in report.by_code("GL401")]
    assert any("fc0_weight" in (d.node or "") for d in gl401), \
        report.format()


def test_spec_options_respect_min_shard_elems():
    """A tiny rank-2 param (< MIN_SHARD_ELEMS) is never offered for
    sharding even when its dims divide."""
    sym = _mlp(hidden=64)  # 64*64 = 4096 < 2**16
    plan = autoplan.plan_parallel(sym, {"data": (32, 64)}, devices=8)
    for name, axes in plan.param_specs.items():
        if name.startswith("fc") and name.endswith("_weight"):
            assert not any(axes), name


# --------------------------------------------------- analysis satellites
def test_reshard_total_bytes_uncapped():
    """12 identical reshard edges: the human GL402 list stays capped at 8,
    but Report.reshard_total_bytes carries the FULL sum."""
    from jax.sharding import PartitionSpec as P

    from mxnet_tpu.parallel import MeshSpec, ShardingRules

    H, N = 64, 12
    s = mx.sym.Variable("data")
    for i in range(N):
        s = mx.sym.FullyConnected(s, num_hidden=H, no_bias=True,
                                  name="fc%d" % i)
    sym = mx.sym.SoftmaxOutput(s, name="softmax")

    def rule(name, shape):
        # shard every FC weight on its CONTRACTION dim: each layer then
        # forces a gather of the weight (one GL402 edge per layer)
        if name.endswith("_weight") and len(shape) == 2:
            return P(None, "model")
        return P()

    mesh = MeshSpec({"data": 2, "model": 2})
    rules = ShardingRules(mesh, param_rule=rule)
    report = analysis.lint(sym, shapes={"data": (8, H)}, mesh=mesh,
                           rules=rules)
    per_edge = H * H * 4 // 2  # (f-1)/f of the fp32 weight at f=2
    assert report.reshard_total_bytes == N * per_edge
    # the human list is still capped: 8 per-edge diags + 1 summary
    gl402 = report.by_code("GL402")
    assert len(gl402) == 9
    assert "reshard_total_bytes" in report.to_json()


def test_gl501_hint_names_the_planner():
    report = analysis.lint(_mlp(), shapes=MLP_SHAPES, mesh="data=2,model=1",
                           budget_gb=1e-6)
    gl501 = report.by_code("GL501")
    assert gl501, report.format()
    hint = gl501[0].fix_hint or ""
    assert "MXNET_AUTOPLAN=1" in hint and "graphlint --autoplan" in hint


# ----------------------------------------------------------- pipeline split
def test_find_cuts_and_split_symbol():
    sym = _mlp(hidden=128, layers=3)
    shapes = {"data": (8, 128)}
    cuts = autoplan.find_pipeline_cuts(sym, shapes)
    assert cuts, "a sequential MLP must offer cuts"
    assert all(c["bytes"] > 0 for c in cuts)
    labels = [cuts[0]["entry"]]
    stages, bnames = autoplan.split_symbol(sym, labels)
    assert len(stages) == 2 and bnames == ["__pipe0__"]
    # stage params partition the original params (no spanning weights)
    orig = set(sym.list_arguments()) - {"data", "softmax_label"}
    s0 = set(stages[0].list_arguments()) - {"data"}
    s1 = set(stages[1].list_arguments()) - {"__pipe0__", "softmax_label"}
    assert s0 | s1 == orig and not (s0 & s1)
    # the original symbol is untouched (fresh nodes in the stages)
    assert set(sym.list_arguments()) >= orig


def test_pipeline_schedule_grad_parity():
    """GPipe microbatch schedule == single-executor full batch, atol 1e-5."""
    rs = np.random.RandomState(0)
    B, D, C = 8, 16, 4
    s = mx.sym.Variable("data")
    s = mx.sym.FullyConnected(s, num_hidden=32, name="fc1")
    s = mx.sym.Activation(s, act_type="relu", name="a1")
    s = mx.sym.FullyConnected(s, num_hidden=32, name="fc2")
    s = mx.sym.Activation(s, act_type="tanh", name="a2")
    s = mx.sym.FullyConnected(s, num_hidden=C, name="fc3")
    sym = mx.sym.SoftmaxOutput(s, name="softmax")

    x = rs.uniform(-1, 1, (B, D)).astype("f")
    y = rs.randint(0, C, (B,)).astype("f")
    ex = sym.simple_bind(mx.cpu(), data=(B, D), softmax_label=(B,),
                         grad_req="write")
    init = {}
    for name, arr in ex.arg_dict.items():
        if name in ("data", "softmax_label"):
            continue
        init[name] = mx.nd.array(
            rs.uniform(-0.5, 0.5, arr.shape).astype("f"))
        arr[:] = init[name]
    ex.arg_dict["data"][:] = x
    ex.arg_dict["softmax_label"][:] = y
    ex.forward(is_train=True)
    ex.backward()
    base_grads = {n: ex.grad_dict[n].asnumpy() for n in init}
    base_out = ex.outputs[0].asnumpy()

    class Batch:
        pass

    b = Batch()
    b.data = [mx.nd.array(x)]
    b.label = [mx.nd.array(y)]
    pg = PipelineExecutorGroup(sym, mx.cpu(), [("data", (B, D))],
                               [("softmax_label", (B,))], num_stages=2,
                               microbatches=4)
    assert pg.num_stages == 2 and pg.microbatches == 4
    pg.set_params(init, {})
    pg.forward_backward(b)
    np.testing.assert_allclose(pg.get_outputs()[0].asnumpy(), base_out,
                               atol=1e-5)
    for n in init:
        g = pg._owner(n).grad_dict[n].asnumpy()
        np.testing.assert_allclose(g, base_grads[n], atol=1e-5, err_msg=n)


def test_over_budget_model_trains_under_pipeline_plan():
    """The ISSUE 10 scenario: a model that GL501-fails EVERY dp x tp
    assignment gets a pipeline plan instead of an error, and training under
    that plan's schedule reaches weight parity with the single-stage
    baseline (atol 1e-5) on a size that fits."""
    rs = np.random.RandomState(1)
    B, D, C = 8, 1001, 4
    s = mx.sym.Variable("data")
    for i in range(4):
        s = mx.sym.FullyConnected(s, num_hidden=1001, name="fc%d" % i)
        s = mx.sym.Activation(s, act_type="relu", name="act%d" % i)
    s = mx.sym.FullyConnected(s, num_hidden=C, name="head")
    sym = mx.sym.SoftmaxOutput(s, name="softmax")
    shapes = {"data": (B, D)}

    free = autoplan.plan_parallel(sym, shapes, devices=4)
    budget = int(free.predicted["peak_bytes"] * 0.55)
    # every dp x tp assignment GL501-fails this budget...
    report = analysis.lint(sym, shapes=shapes, mesh="data=4,model=1",
                           budget_gb=budget / 2 ** 30)
    assert report.by_code("GL501"), report.format()
    # ...so the planner pipelines
    plan = autoplan.plan_parallel(sym, shapes, devices=4,
                                  budget_bytes=budget, microbatches=4)
    assert plan.feasible and plan.pipeline_stages > 1, plan.summary()
    assert plan.stage_cuts and plan.predicted["peak_bytes"] <= budget

    # train 3 SGD steps under the plan's schedule vs the single-stage
    # baseline — identical updates
    x = rs.uniform(-1, 1, (B, D)).astype("f")
    y = rs.randint(0, C, (B,)).astype("f")
    ex = sym.simple_bind(mx.cpu(), data=(B, D), softmax_label=(B,),
                         grad_req="write")
    init = {}
    for name, arr in ex.arg_dict.items():
        if name in ("data", "softmax_label"):
            continue
        # keep activations O(1) through the 1001-wide layers: a hot init
        # diverges in a step or two and fp noise then swamps the atol
        init[name] = mx.nd.array(
            rs.uniform(-0.02, 0.02, arr.shape).astype("f"))
        arr[:] = init[name]
    ex.arg_dict["data"][:] = x
    ex.arg_dict["softmax_label"][:] = y
    lr = 0.01
    for _ in range(3):
        ex.forward(is_train=True)
        ex.backward()
        for name in init:
            ex.arg_dict[name][:] = (ex.arg_dict[name].asnumpy()
                                    - lr * ex.grad_dict[name].asnumpy())

    class Batch:
        pass

    b = Batch()
    b.data = [mx.nd.array(x)]
    b.label = [mx.nd.array(y)]
    pg = PipelineExecutorGroup(sym, mx.cpu(), [("data", (B, D))],
                               [("softmax_label", (B,))],
                               cut_entries=plan.stage_cuts,
                               microbatches=plan.microbatches)
    pg.set_params(init, {})
    for _ in range(3):
        pg.forward_backward(b)
        for k, ex_k in enumerate(pg.execs):
            for name in pg._stage_params[k]:
                ex_k.arg_dict[name][:] = (
                    ex_k.arg_dict[name].asnumpy()
                    - lr * ex_k.grad_dict[name].asnumpy())
    for name in init:
        np.testing.assert_allclose(
            pg._owner(name).arg_dict[name].asnumpy(),
            ex.arg_dict[name].asnumpy(), atol=1e-5, err_msg=name)


# ------------------------------------------------------------ integration
def test_spmd_adapter_consumes_plan(monkeypatch):
    """MXNET_AUTOPLAN=1: the fused-step Module lays params out per the
    planner's specs (and explicit fused_step still trains)."""
    monkeypatch.setenv("MXNET_AUTOPLAN", "1")
    rs = np.random.RandomState(0)
    sym = _mlp()
    it = mx.io.NDArrayIter(rs.rand(32, 512).astype("f"),
                           rs.randint(0, 4, (32,)).astype("f"),
                           batch_size=16)
    mod = mx.mod.Module(sym, context=[mx.cpu(i) for i in range(4)])
    mod.fit(it, num_epoch=1, optimizer="sgd")
    assert mod._spmd is not None
    tr = mod._spmd.trainer
    plan = autoplan.plan_parallel(sym, {"data": (16, 512),
                                        "softmax_label": (16,)}, devices=4)
    assert dict(tr.mesh.shape) == plan.mesh
    # a param the plan shards is actually laid out sharded
    sharded = [n for n, axes in plan.param_specs.items() if any(axes)]
    assert sharded
    for name in sharded:
        spec = tr.params[name].sharding.spec
        assert "model" in tuple(spec), (name, spec)


def test_graphlint_autoplan_cli(capsys):
    from mxnet_tpu.analysis.cli import main

    rc = main(["mlp", "--autoplan", "--mesh-devices", "8",
               "--format", "json"])
    out = capsys.readouterr().out
    assert rc == 0
    payload = json.loads(out)
    plan = payload[0]["autoplan"]
    assert plan["devices"] == 8 and plan["feasible"]
    assert plan["naive"]["comm_bytes"] >= plan["predicted"]["comm_bytes"]


def test_graphlint_autoplan_needs_devices(capsys):
    from mxnet_tpu.analysis.cli import main

    assert main(["mlp", "--autoplan"]) == 2


def test_predicted_within_2x_of_measured_2proc(tmp_path):
    """Acceptance: the cost model's grad-sync prediction lands within 2x of
    the measured kvstore.bytes.* counters on a real 2-process CPU fit."""
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu"})
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", "--cpu-devices", "1",
         sys.executable,
         os.path.join(ROOT, "tests", "nightly", "autoplan_measure.py")],
        capture_output=True, text=True, timeout=420, env=env, cwd=ROOT)
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    assert "AUTOPLAN_MEASURE_OK" in r.stdout
    row = next(json.loads(l[len("AUTOPLAN_MEASURE "):])
               for l in r.stdout.splitlines()
               if l.startswith("AUTOPLAN_MEASURE {"))
    assert 0.5 <= row["ratio"] <= 2.0, row
