"""Parity tests for the fused conv+BN Pallas kernel stack (interpret mode on
CPU; the on-TPU timing lives in tools/fused_stats_bench.py). The oracle is
the pure-XLA reference implementation of the same fused contract."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.ops import pallas_conv_bn as pcb


def _mk(shape, seed, dtype=np.float32):
    rs = np.random.RandomState(seed)
    return jnp.asarray(rs.randn(*shape).astype(np.float32), dtype)


def _ref(x, w, scale, shift, res, kernel_hw, stride, relu):
    c = pcb._xla_conv(x, w, scale, shift, res, kernel_hw, stride, relu)
    s, q = pcb._stats_of(c)
    return c, s, q


CASES = [
    # (kernel, stride, prologue, relu, res)
    ((1, 1), (1, 1), False, False, False),
    ((1, 1), (1, 1), True, True, False),
    ((1, 1), (1, 1), True, False, True),
    ((1, 1), (2, 2), True, True, False),
    ((3, 3), (1, 1), False, False, False),
    ((3, 3), (1, 1), True, True, True),
]


@pytest.mark.parametrize("kernel,stride,prologue,relu,res", CASES)
def test_forward_parity(kernel, stride, prologue, relu, res):
    B, K, H, W, N = 4, 16, 8, 8, 32
    x = _mk((B, K, H, W), 0)
    w = _mk((N, K) + kernel, 1) * 0.1
    scale = _mk((K,), 2) if prologue else None
    shift = _mk((K,), 3) if prologue else None
    Ho, Wo = H // stride[0], W // stride[1]
    r = _mk((B, N, Ho, Wo), 4) if res else None
    assert pcb.supported(x.shape, w.shape, stride)

    c0, s0, q0 = _ref(x, w, scale, shift, r, kernel, stride, relu)
    c1, s1, q1 = pcb.conv_block(x, w, scale, shift, r, kernel, stride, relu)
    np.testing.assert_allclose(np.asarray(c1), np.asarray(c0),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s0),
                               rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(np.asarray(q1), np.asarray(q0),
                               rtol=1e-4, atol=1e-3)


@pytest.mark.parametrize("kernel,stride,prologue,relu,res", CASES)
def test_gradient_parity(kernel, stride, prologue, relu, res):
    """grad through conv_block == grad through the XLA reference, for a
    loss that exercises all three outputs (c, ssum, ssq)."""
    B, K, H, W, N = 2, 8, 8, 8, 16
    x = _mk((B, K, H, W), 10)
    w = _mk((N, K) + kernel, 11) * 0.1
    scale = _mk((K,), 12) if prologue else None
    shift = _mk((K,), 13) if prologue else None
    Ho, Wo = H // stride[0], W // stride[1]
    r = _mk((B, N, Ho, Wo), 14) if res else None

    cos = _mk((B, N, Ho, Wo), 15)

    def loss(fn, x, w, scale, shift, r):
        c, s, q = fn(x, w, scale, shift, r)
        return (jnp.sum(c.astype(jnp.float32) * cos.astype(jnp.float32))
                + jnp.sum(jnp.sin(s)) + 1e-3 * jnp.sum(jnp.sqrt(q + 1.0)))

    argnums = tuple(i for i, a in enumerate((x, w, scale, shift, r))
                    if a is not None)
    g_ref = jax.grad(
        lambda *a: loss(lambda x, w, sc, sh, r: _ref(
            x, w, sc, sh, r, kernel, stride, relu), *a),
        argnums=argnums)(x, w, scale, shift, r)
    g_pal = jax.grad(
        lambda *a: loss(lambda x, w, sc, sh, r: pcb.conv_block(
            x, w, sc, sh, r, kernel, stride, relu), *a),
        argnums=argnums)(x, w, scale, shift, r)
    # atol 2e-3 on the densest config ONLY (3x3 + prologue + relu +
    # residual, 72 f32 products per output element): the fused backward
    # accumulates dgrad/wgrad from VMEM-resident tiles in a different
    # order than XLA's per-term reduction, and the worst observed
    # reassociation drift there is ~1.8e-3 on ONE element in 1152 of
    # O(0.1) magnitude — summation-order noise, not a kernel bug (same
    # argument as the PR 3 test_parallel atol notes). Every other config
    # keeps the original 1e-3 sensitivity.
    dense = kernel == (3, 3) and prologue and relu and res
    atol = 2e-3 if dense else 1e-3
    for ga, gb in zip(g_pal, g_ref):
        np.testing.assert_allclose(np.asarray(ga), np.asarray(gb),
                                   rtol=1e-3, atol=atol)


def test_fallback_unsupported_shape():
    """Shapes the kernel cannot tile must silently take the XLA path."""
    x = _mk((2, 6, 5, 5), 20)   # K=6 not a multiple of 8
    w = _mk((7, 6, 1, 1), 21)
    assert not pcb.supported(x.shape, w.shape)
    c, s, q = pcb.conv_block(x, w, None, None, None, (1, 1), (1, 1), False)
    c0, s0, q0 = _ref(x, w, None, None, None, (1, 1), (1, 1), False)
    np.testing.assert_allclose(np.asarray(c), np.asarray(c0), rtol=1e-5)


def test_resnet_shapes_supported():
    """Every ResNet-50 @224 bottleneck conv except the stride-2 3x3s and the
    7x7 stem must tile (batch 256 working-set check is analytic —
    choose_blocks — so a small B here proves the same tiling)."""
    sites = [
        # (K, N, H, kernel, stride)
        (64, 64, 56, (1, 1), (1, 1)),
        (64, 64, 56, (3, 3), (1, 1)),
        (64, 256, 56, (1, 1), (1, 1)),
        (256, 64, 56, (1, 1), (1, 1)),
        (256, 128, 56, (1, 1), (1, 1)),
        (128, 128, 28, (3, 3), (1, 1)),
        (128, 512, 28, (1, 1), (1, 1)),
        (512, 128, 28, (1, 1), (1, 1)),
        (256, 512, 56, (1, 1), (2, 2)),   # stage2 shortcut
        (512, 256, 28, (1, 1), (1, 1)),
        (256, 256, 14, (3, 3), (1, 1)),
        (256, 1024, 14, (1, 1), (1, 1)),
        (1024, 256, 14, (1, 1), (1, 1)),
        (512, 1024, 28, (1, 1), (2, 2)),  # stage3 shortcut
        (1024, 512, 14, (1, 1), (1, 1)),
        (512, 512, 7, (3, 3), (1, 1)),
        (512, 2048, 7, (1, 1), (1, 1)),
        (2048, 512, 7, (1, 1), (1, 1)),
        (1024, 2048, 14, (1, 1), (2, 2)),  # stage4 shortcut
    ]
    for K, N, H, kernel, stride in sites:
        assert pcb.supported((256, K, H, H), (N, K) + kernel, stride), (
            K, N, H, kernel, stride)


def test_bf16_stats_precision():
    """bf16 inputs: the kernel's f32-accumulator stats must be closer to the
    f64 truth than naive bf16 accumulation would be (sanity of the epilogue
    numerics)."""
    B, K, H, W, N = 8, 16, 8, 8, 32
    x = _mk((B, K, H, W), 30, jnp.bfloat16)
    w = _mk((N, K, 1, 1), 31, jnp.bfloat16) * 0.1
    c, s, q = pcb.conv_block(x, w, None, None, None, (1, 1), (1, 1), False)
    c64 = np.asarray(c, np.float64)
    np.testing.assert_allclose(np.asarray(s), c64.sum((0, 2, 3)),
                               rtol=3e-2, atol=1e-2)
    np.testing.assert_allclose(np.asarray(q), (c64 * c64).sum((0, 2, 3)),
                               rtol=3e-2, atol=1e-2)


def test_odd_strided_dims_forward_parity():
    """Odd spatial dims at stride 2: the forward slices x[:, :, ::2, ::2]
    (ceil) — parity must hold and supported() must agree."""
    B, K, H, W, N = 4, 16, 9, 9, 32
    x = _mk((B, K, H, W), 50)
    w = _mk((N, K, 1, 1), 51) * 0.1
    scale, shift = _mk((K,), 52), _mk((K,), 53)
    assert pcb.supported(x.shape, w.shape, (2, 2))
    c0, s0, q0 = _ref(x, w, scale, shift, None, (1, 1), (2, 2), True)
    c1, s1, q1 = pcb.conv_block(x, w, scale, shift, None, (1, 1), (2, 2),
                                True)
    assert c1.shape == (B, N, 5, 5)  # ceil(9/2)
    np.testing.assert_allclose(np.asarray(c1), np.asarray(c0),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s0),
                               rtol=1e-4, atol=1e-3)


def test_plan_blocks_ceil_div_strided(monkeypatch):
    """Regression: plan_blocks floored H//stride while the forward slices
    ceil — near the VMEM budget an odd-dim strided conv passed the gate but
    tripped the kernel's internal assert. The planner must now size the
    working set with the SAME ceil dims the forward uses, so the tight
    shape takes the XLA fallback instead."""
    B, K, N = 4, 16, 32
    # budget between est(HW=ceil(7/2)^2=16) and est(HW=floor=9): the floor
    # arithmetic would claim a tile fits that the forward cannot allocate
    est = lambda hw: (2 * K * hw * 4 + 2 * 8 * hw * 4 + 8 * hw * 4
                      + 8 * K * 4 + K * hw * 4)
    assert est(9) < est(16)
    monkeypatch.setattr(pcb, "_VMEM_BUDGET", (est(9) + est(16)) // 2)
    assert pcb.plan_blocks((B, K, 7, 7), (N, K, 1, 1), (2, 2),
                           itemsize=4) is None
    assert not pcb.supported((B, K, 7, 7), (N, K, 1, 1), (2, 2), itemsize=4)
    # and the fallback actually runs (no in-jit assert)
    x = _mk((B, K, 7, 7), 60)
    w = _mk((N, K, 1, 1), 61) * 0.1
    scale, shift = _mk((K,), 62), _mk((K,), 63)
    c, s, q = pcb.conv_block(x, w, scale, shift, None, (1, 1), (2, 2), True)
    c0, s0, q0 = _ref(x, w, scale, shift, None, (1, 1), (2, 2), True)
    np.testing.assert_allclose(np.asarray(c), np.asarray(c0), rtol=1e-5,
                               atol=1e-5)


def test_strided_dims_helper():
    assert pcb.strided_dims(7, 7, (2, 2)) == (4, 4)
    assert pcb.strided_dims(8, 8, (2, 2)) == (4, 4)
    assert pcb.strided_dims(9, 7, (1, 1)) == (9, 7)


def test_tight_vmem_falls_back_not_asserts():
    """A shape whose f32+prologue working set exceeds the VMEM budget (but
    would fit at bf16 without prologue) must take the XLA fallback, never an
    internal assert (code-review regression: supported() and the kernel used
    different tiling parameters)."""
    x = _mk((1, 64, 112, 112), 40)  # float32
    w = _mk((64, 64, 3, 3), 41) * 0.1
    scale = _mk((64,), 42)
    shift = _mk((64,), 43)
    c, s, q = pcb.conv_block(x, w, scale, shift, None, (3, 3), (1, 1), True)
    c0, s0, q0 = _ref(x, w, scale, shift, None, (3, 3), (1, 1), True)
    np.testing.assert_allclose(np.asarray(c), np.asarray(c0),
                               rtol=1e-4, atol=1e-4)
