"""Sharded async checkpointing (mxnet_tpu/checkpoint.py,
docs/FAULT_TOLERANCE.md): atomic write helpers, torn-file armor, the
manifest/shard completeness contract, re-flattening (the different-W
resume seed), retention, the async writer's supersede/latch behavior, and
the classic save_checkpoint/optimizer-state atomicity satellites."""
import json
import os
import pickle
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import checkpoint as ckpt
from mxnet_tpu import telemetry
from mxnet_tpu.base import MXNetError


@pytest.fixture
def tm():
    telemetry.reset()
    telemetry.clear_events()
    saved = telemetry.current_override()
    telemetry.set_mode("counters")
    yield telemetry
    telemetry.set_mode(saved)
    telemetry.reset()
    telemetry.clear_events()


# ------------------------------------------------------------ atomic writes
def test_atomic_write_bytes_replaces_whole_file(tmp_path):
    p = str(tmp_path / "f.bin")
    ckpt.atomic_write_bytes(p, b"one")
    ckpt.atomic_write_bytes(p, b"two")
    assert open(p, "rb").read() == b"two"
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]


def test_atomic_replace_keeps_old_file_on_error(tmp_path):
    p = str(tmp_path / "f.bin")
    ckpt.atomic_write_bytes(p, b"good")
    with pytest.raises(RuntimeError):
        with ckpt.atomic_replace(p) as tmp:
            with open(tmp, "wb") as f:
                f.write(b"half-writ")
            raise RuntimeError("crash mid-save")
    assert open(p, "rb").read() == b"good"
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]


def test_load_ndarrays_checked_torn_file_names_path(tmp_path):
    p = str(tmp_path / "torn.params")
    good = str(tmp_path / "good.params")
    mx.nd.save(good, {"w": mx.nd.ones((3,))})
    blob = open(good, "rb").read()
    with open(p, "wb") as f:
        f.write(blob[: len(blob) // 2])  # torn mid-write
    with pytest.raises(MXNetError, match="torn.params"):
        ckpt.load_ndarrays_checked(p)


def test_model_load_checkpoint_torn_params_structured(tmp_path):
    """model.load_checkpoint of a torn params file raises a structured
    error naming the path, not a raw deserialization error."""
    from mxnet_tpu import model

    prefix = str(tmp_path / "ck")
    sym = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Variable("data"), num_hidden=2, name="fc"), name="softmax")
    model.save_checkpoint(prefix, 1, sym,
                          {"fc_weight": mx.nd.ones((2, 4))}, {})
    mx.nd.waitall()
    path = prefix + "-0001.params"
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[: len(blob) // 2])
    with pytest.raises(MXNetError, match="0001.params"):
        model.load_checkpoint(prefix, 1)


def test_module_optimizer_states_atomic_and_checked(tmp_path):
    """Module.save_optimizer_states writes atomically; loading a torn
    state file raises a structured error naming the path."""
    sym = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Variable("data"), num_hidden=2, name="fc"), name="softmax")
    mod = mx.mod.Module(sym, context=mx.cpu(), fused_step=False)
    it = mx.io.NDArrayIter(np.random.RandomState(0).rand(8, 4).astype("f"),
                           np.zeros((8,), "f"), batch_size=4)
    mod.fit(it, num_epoch=1, optimizer="sgd", kvstore="local",
            optimizer_params=(("learning_rate", 0.1), ("momentum", 0.9)))
    p = str(tmp_path / "opt.states")
    mod.save_optimizer_states(p)
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]
    with open(p, "wb") as f:
        f.write(b"\x80\x04 torn!")
    with pytest.raises(MXNetError, match="opt.states"):
        mod.load_optimizer_states(p)


# --------------------------------------------------- manifest / completeness
def _write_fake_sharded_step(root, step, world=2, n_states=1, seed=0,
                             extra_files=(), break_shard=None,
                             skip_manifest=False):
    """Handcraft a minimal sharded checkpoint step: one bucket, two keys
    (key 1 split across nothing — single part), flat total divisible by
    world. Returns the per-key host arrays the shards encode."""
    rs = np.random.RandomState(seed)
    k0, k1 = rs.rand(4).astype("f"), rs.rand(2, 3).astype("f")
    flat_w = np.concatenate([k0, k1.reshape(-1)])  # total 10 % 2 == 0
    states = [np.arange(10, dtype="f") * (i + 1) for i in range(n_states)]
    d = ckpt.step_dir(root, step)
    os.makedirs(d, exist_ok=True)
    shard = 10 // world
    for r in range(world):
        arrays = {"b0.w": flat_w[r * shard:(r + 1) * shard]}
        for i, s in enumerate(states):
            arrays["b0.s%d" % i] = s[r * shard:(r + 1) * shard]
        buf = ckpt._npz_bytes(arrays)
        base = os.path.join(d, "shard-%05d-of-%05d" % (r, world))
        data = buf if break_shard != r else buf[: len(buf) // 2]
        ckpt.atomic_write_bytes(base + ".npz", data)
        ckpt.atomic_write_bytes(base + ".json", json.dumps(
            {"digest": ckpt._sha256(buf), "rank": r, "world": world,
             "step": step, "plan_hash": "fakehash", "nbytes": len(buf)}
        ).encode())
    manifest = {
        "format": ckpt.FORMAT_VERSION, "kind": "sharded", "step": step,
        "world": world, "plan_hash": "fakehash",
        "plan": {"buckets": [{
            "index": 0, "dtype": "float32",
            "slots": [[0, 0, 4, [4], "float32", 0, 0, 1],
                      [1, 4, 6, [2, 3], "float32", 0, 0, 1]]}]},
        "optimizer": {"kind": "sgd", "n_states": n_states,
                      "hyper": {}, "class": "SGD"},
        "update_counts": [[0, 7], [1, 7]], "num_update": 7,
        "files": sorted(extra_files), "meta": {}, "written_at": time.time(),
    }
    if not skip_manifest:
        ckpt.atomic_write_bytes(os.path.join(d, ckpt.MANIFEST_NAME),
                                json.dumps(manifest).encode())
    return {0: k0, 1: k1}, states


def test_latest_complete_skips_incomplete_steps(tmp_path):
    root = str(tmp_path)
    _write_fake_sharded_step(root, 10)
    _write_fake_sharded_step(root, 20, skip_manifest=True)   # no commit mark
    got = ckpt.latest_complete(root)
    assert got is not None and got[0] == 10


def test_latest_complete_rejects_missing_shard(tmp_path):
    root = str(tmp_path)
    _write_fake_sharded_step(root, 10)
    _write_fake_sharded_step(root, 20)
    os.unlink(os.path.join(ckpt.step_dir(root, 20),
                           "shard-00001-of-00002.npz"))
    assert ckpt.latest_complete(root)[0] == 10


def test_read_flat_buckets_and_per_key_states_roundtrip(tmp_path):
    root = str(tmp_path)
    keys, states = _write_fake_sharded_step(root, 5, n_states=2)
    step, manifest = ckpt.latest_complete(root)
    flats = ckpt.read_flat_buckets(root, step, manifest)
    np.testing.assert_array_equal(flats[0]["states"][0], states[0])
    per_key = ckpt.per_key_states(manifest, flats)
    assert set(per_key) == {0, 1}
    assert per_key[1][1].shape == (2, 3)  # state slot 1 of key 1
    weights = ckpt.per_key_states(manifest, flats, weights=True)
    np.testing.assert_array_equal(weights[0], keys[0])
    np.testing.assert_array_equal(weights[1], keys[1])


def test_torn_shard_fails_digest_with_structured_error(tmp_path):
    root = str(tmp_path)
    _write_fake_sharded_step(root, 5, break_shard=1)
    manifest = ckpt.load_manifest(root, 5)
    with pytest.raises(MXNetError, match="digest|corrupt"):
        ckpt.read_local_shard(root, 5, manifest, 1)
    # a reader asking for the newest COMPLETE step never sees the torn one
    assert ckpt.latest_complete(root) is None


def test_read_sharded_pointer(tmp_path):
    p = str(tmp_path / "opt.states")
    ckpt.atomic_write_bytes(p, json.dumps(
        {"format": "mxtpu-sharded-states", "dir": "/x", "step": 3}).encode())
    got = ckpt.read_sharded_pointer(p)
    assert got["step"] == 3 and got["dir"] == "/x"
    ckpt.atomic_write_bytes(p, pickle.dumps({"classic": "blob"}))
    assert ckpt.read_sharded_pointer(p) is None
    assert ckpt.read_sharded_pointer(str(tmp_path / "absent")) is None


# ------------------------------------------------------------ async writer
def test_checkpointer_async_write_and_wait(tmp_path, tm):
    w = ckpt.Checkpointer(str(tmp_path), async_=True)
    job = w.save_replicated(3, {"w": np.ones((4,), "f")},
                            meta={"epoch": 0}, block=False)
    w.wait()
    assert job.error is None
    step, manifest = ckpt.latest_complete(str(tmp_path))
    assert step == 3 and manifest["kind"] == "replicated"
    blob = ckpt._load_npz_checked(
        os.path.join(ckpt.step_dir(str(tmp_path), 3), "weights.npz"))
    np.testing.assert_array_equal(blob["w"], np.ones((4,), "f"))


def test_checkpointer_supersede_drops_queued_job(tmp_path, tm):
    """A newer save supersedes a QUEUED (not-yet-started) one: only the
    newest matters under failure recovery, so the stale write is dropped
    (checkpoint.drops) instead of wasting the I/O budget."""
    w = ckpt.Checkpointer(str(tmp_path), async_=True)
    gate = threading.Event()
    w._submit(gate.wait, step=1, block=False)   # writer busy until released
    w.save_replicated(2, {"w": np.zeros((2,), "f")}, block=False)
    w.save_replicated(3, {"w": np.ones((2,), "f")}, block=False)  # drops 2
    gate.set()
    w.wait()
    assert telemetry.counter("checkpoint.drops").value == 1
    steps = ckpt.list_steps(str(tmp_path))
    assert 3 in steps and 2 not in steps


def test_checkpointer_failure_latches_to_next_save(tmp_path):
    w = ckpt.Checkpointer(str(tmp_path), async_=True)

    def boom():
        raise RuntimeError("disk on fire")

    w._submit(boom, step=1, block=False)
    deadline = time.time() + 10
    while w._error is None and time.time() < deadline:
        time.sleep(0.01)
    with pytest.raises(MXNetError, match="disk on fire"):
        w.save_replicated(2, {"w": np.zeros((2,), "f")}, block=False)
    # the latch clears once raised; the next save goes through
    w.save_replicated(3, {"w": np.zeros((2,), "f")}, block=True)
    assert ckpt.latest_complete(str(tmp_path))[0] == 3


def test_checkpointer_close_stops_thread_even_on_latched_failure(tmp_path):
    """close() must stop the writer thread when the final drain re-raises
    a latched write failure (it used to leak one daemon thread per failed
    fit)."""
    w = ckpt.Checkpointer(str(tmp_path), async_=True)

    def boom():
        raise RuntimeError("disk on fire")

    w._submit(boom, step=1, block=False)
    deadline = time.time() + 10
    while w._error is None and time.time() < deadline:
        time.sleep(0.01)
    t = w._thread
    assert t is not None and t.is_alive()
    with pytest.raises(MXNetError, match="disk on fire"):
        w.close()
    t.join(timeout=10)
    assert not t.is_alive() and w._thread is None
    # close() is restartable: a later save spins a fresh thread and lands
    w.save_replicated(2, {"w": np.zeros((2,), "f")}, block=True)
    assert ckpt.latest_complete(str(tmp_path))[0] == 2


def test_checkpoint_inflight_gauge_set_while_queued(tmp_path, tm):
    w = ckpt.Checkpointer(str(tmp_path), async_=True)
    gate = threading.Event()
    w._submit(gate.wait, step=1, block=False)
    deadline = time.time() + 5
    while telemetry.gauge("checkpoint.inflight").value in (None, 0) \
            and time.time() < deadline:
        time.sleep(0.005)
    assert telemetry.gauge("checkpoint.inflight").value >= 1
    gate.set()
    w.wait()
    assert telemetry.gauge("checkpoint.inflight").value == 0


# --------------------------------------------------------------- retention
def test_apply_retention_keeps_newest_complete_and_protected(tmp_path):
    root = str(tmp_path)
    for s in (1, 2, 3, 4):
        _write_fake_sharded_step(root, s)
    victims = ckpt.apply_retention(root, keep=2, protect_step=1)
    assert sorted(victims) == [2]
    assert sorted(ckpt.list_steps(root)) == [1, 3, 4]


def test_prefix_retention_spares_newest_complete_manifest(tmp_path):
    """keep-last-K for classic epoch checkpoints: the newest COMPLETE
    epoch survives even outside the keep window, and a sharded .states
    pointer's backing shard set is (a) checked for completeness and (b)
    removed together with its epoch."""
    prefix = str(tmp_path / "run")
    shard_root = str(tmp_path / "run-0002.states.sharded")
    _write_fake_sharded_step(shard_root, 7)
    for ep in (1, 2, 3, 4):
        ckpt.atomic_write_bytes("%s-%04d.params" % (prefix, ep), b"P")
    ckpt.atomic_write_bytes("%s-0002.states" % prefix, json.dumps(
        {"format": "mxtpu-sharded-states", "dir": shard_root,
         "step": 7}).encode())
    # epochs 3 and 4 have BROKEN sharded pointers -> incomplete
    for ep in (3, 4):
        ckpt.atomic_write_bytes("%s-%04d.states" % (prefix, ep), json.dumps(
            {"format": "mxtpu-sharded-states",
             "dir": str(tmp_path / "nope"), "step": 1}).encode())
    victims = ckpt.prefix_retention(prefix, keep=1)
    # epoch 2 is the newest COMPLETE (pointer target complete) -> spared;
    # epochs 1 and 3 fall out of the window, 4 stays (last K)
    assert sorted(victims) == [1, 3]
    assert os.path.exists("%s-0002.params" % prefix)
    assert os.path.exists(ckpt.step_dir(shard_root, 7))
    victims = ckpt.prefix_retention(prefix, keep=0 or None)
    assert victims == []  # keep=None -> unlimited, no deletions


def test_prefix_retention_removes_sharded_backing_dir(tmp_path):
    prefix = str(tmp_path / "run")
    shard_root = str(tmp_path / "run-0001.states.sharded")
    _write_fake_sharded_step(shard_root, 3)
    for ep in (1, 2, 3):
        ckpt.atomic_write_bytes("%s-%04d.params" % (prefix, ep), b"P")
    ckpt.atomic_write_bytes("%s-0001.states" % prefix, json.dumps(
        {"format": "mxtpu-sharded-states", "dir": shard_root,
         "step": 3}).encode())
    victims = ckpt.prefix_retention(prefix, keep=1)
    assert 1 in victims
    assert not os.path.exists(shard_root)


def test_module_checkpoint_callback_retention(tmp_path):
    """callback.module_checkpoint(keep=K) prunes old epochs as it saves."""
    sym = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Variable("data"), num_hidden=2, name="fc"), name="softmax")
    mod = mx.mod.Module(sym, context=mx.cpu(), fused_step=False)
    it = mx.io.NDArrayIter(np.random.RandomState(0).rand(8, 4).astype("f"),
                           np.zeros((8,), "f"), batch_size=4)
    cb = mx.callback.module_checkpoint(mod, str(tmp_path / "m"), keep=2)
    mod.fit(it, num_epoch=5, optimizer="sgd", kvstore="local",
            epoch_end_callback=cb)
    import glob

    left = sorted(glob.glob(str(tmp_path / "m-*.params")))
    assert len(left) == 2 and left[-1].endswith("m-0005.params")


def test_callback_negative_keep_disables_retention(tmp_path):
    """An explicit non-positive keep= warns and disables retention (same
    contract as MXNET_CHECKPOINT_KEEP) instead of mis-slicing epochs."""
    from mxnet_tpu.callback import _apply_keep

    prefix = str(tmp_path / "m")
    for ep in (1, 2, 3):
        with open("%s-%04d.params" % (prefix, ep), "wb") as f:
            f.write(b"x")
    _apply_keep(prefix, -1)
    import glob

    assert len(glob.glob(prefix + "-*.params")) == 3  # nothing deleted


def test_checkpoint_keep_env_parsing(monkeypatch):
    monkeypatch.delenv("MXNET_CHECKPOINT_KEEP", raising=False)
    assert ckpt.checkpoint_keep() is None
    monkeypatch.setenv("MXNET_CHECKPOINT_KEEP", "4")
    assert ckpt.checkpoint_keep() == 4
    monkeypatch.setenv("MXNET_CHECKPOINT_KEEP", "-1")
    assert ckpt.checkpoint_keep() is None
    monkeypatch.setenv("MXNET_CHECKPOINT_KEEP", "lots")
    assert ckpt.checkpoint_keep() is None


# ------------------------------------------------------- single-proc elastic
def test_elastic_fit_single_process_checkpoints_and_resumes(tmp_path):
    """fit(elastic=...) on a single process: periodic replicated
    checkpoints land asynchronously with step metadata, and a second fit
    resumes from the newest complete one (weights bit-equal at the
    resume point, iterator fast-forwarded)."""
    sym = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Variable("data"), num_hidden=3, name="fc"), name="softmax")
    rs = np.random.RandomState(0)
    x = rs.rand(24, 4).astype("f")
    y = rs.randint(0, 3, (24,)).astype("f")
    root = str(tmp_path / "ck")

    mod = mx.mod.Module(sym, context=mx.cpu(), fused_step=False)
    ctl = mod.fit(mx.io.NDArrayIter(x, y, batch_size=4), num_epoch=2,
                  optimizer="sgd", kvstore="local",
                  optimizer_params=(("learning_rate", 0.05),
                                    ("momentum", 0.9)),
                  elastic={"checkpoint_dir": root, "checkpoint_period": 4})
    assert not ctl.evicted and ctl._round == 12
    step, manifest = ckpt.latest_complete(root)
    assert step == 12 and manifest["meta"]["epoch"] == 1
    w_full = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}

    # resumed run picks up at the recorded (epoch, nbatch) and matches
    mod2 = mx.mod.Module(sym, context=mx.cpu(), fused_step=False)
    ctl2 = mod2.fit(mx.io.NDArrayIter(x, y, batch_size=4), num_epoch=2,
                    optimizer="sgd", kvstore="local",
                    optimizer_params=(("learning_rate", 0.05),
                                      ("momentum", 0.9)),
                    elastic={"checkpoint_dir": root,
                             "checkpoint_period": 0, "resume": True})
    assert ctl2._round == 12
    w_res = {k: v.asnumpy() for k, v in mod2.get_params()[0].items()}
    for k in w_full:
        np.testing.assert_array_equal(w_res[k], w_full[k])


def test_elastic_fit_fused_spmd_saves_and_restores_optimizer_state(
        tmp_path, monkeypatch):
    """The fused SPMD step owns the optimizer state (no kv._updater):
    elastic checkpointing must capture it via mod._spmd.get_states() and a
    resume must restore momentum — a resumed run matches an uninterrupted
    one instead of silently restarting momentum at zero."""
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    sym = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Variable("data"), num_hidden=3, name="fc"), name="softmax")
    rs = np.random.RandomState(0)
    x = rs.rand(24, 4).astype("f")
    y = rs.randint(0, 3, (24,)).astype("f")
    BATCHES = 6

    def fit(root, num_epoch, resume):
        mx.random.seed(7)
        mod = mx.mod.Module(sym, context=mx.cpu())  # fused_step default
        mod.fit(mx.io.NDArrayIter(x, y, batch_size=4), num_epoch=num_epoch,
                optimizer="sgd", kvstore="local",
                optimizer_params=(("learning_rate", 0.1),
                                  ("momentum", 0.9)),
                elastic={"checkpoint_dir": root,
                         "checkpoint_period": BATCHES, "resume": resume})
        assert mod._spmd is not None, "fused path did not engage"
        return {k: v.asnumpy() for k, v in mod.get_params()[0].items()}

    root = str(tmp_path / "ck")
    fit(root, 2, False)
    step, _ = ckpt.latest_complete(root)
    assert os.path.exists(os.path.join(ckpt.step_dir(root, step),
                                       "states.bin")), \
        "fused SPMD optimizer state missing from the checkpoint"
    resumed = fit(root, 4, True)
    reference = fit(str(tmp_path / "ck-ref"), 4, False)
    for k in reference:
        np.testing.assert_allclose(
            resumed[k], reference[k], atol=1e-6, rtol=0,
            err_msg="momentum lost across fused-SPMD resume on %s" % k)
