"""Monitor / visualization / profiler / recordio (coverage parity with the
reference's test_recordio.py, test_viz.py, test_profiler.py, monitor use in
test_monitor-style flows)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio
from mxnet_tpu import symbol as sym


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "test.rec")
    w = recordio.MXRecordIO(path, "w")
    for i in range(5):
        w.write(("record%d" % i).encode())
    w.close()
    r = recordio.MXRecordIO(path, "r")
    for i in range(5):
        assert r.read() == ("record%d" % i).encode()
    assert r.read() is None
    r.close()


def test_indexed_recordio(tmp_path):
    rec = str(tmp_path / "t.rec")
    idx = str(tmp_path / "t.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(10):
        w.write_idx(i, ("rec%d" % i).encode())
    w.close()
    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    assert r.read_idx(7) == b"rec7"
    assert r.read_idx(2) == b"rec2"
    assert sorted(r.keys) == list(range(10))
    r.close()


def test_irheader_pack_unpack_scalar_label():
    h = recordio.IRHeader(0, 3.0, 42, 0)
    blob = recordio.pack(h, b"payload")
    h2, payload = recordio.unpack(blob)
    assert h2.label == 3.0 and h2.id == 42
    assert payload == b"payload"


def test_irheader_pack_unpack_array_label():
    h = recordio.IRHeader(0, np.array([1.0, 2.0, 3.0], "float32"), 7, 0)
    blob = recordio.pack(h, b"xyz")
    h2, payload = recordio.unpack(blob)
    np.testing.assert_array_equal(h2.label, [1.0, 2.0, 3.0])
    assert payload == b"xyz"


def test_monitor_collects_stats():
    from mxnet_tpu.monitor import Monitor

    net = sym.FullyConnected(data=sym.Variable("data"), num_hidden=4, name="fc")
    exe = net.simple_bind(ctx=mx.cpu(), data=(2, 3))
    mon = Monitor(interval=1, pattern=".*")
    mon.install(exe)
    exe.arg_dict["data"][:] = np.ones((2, 3), "float32")
    exe.arg_dict["fc_weight"][:] = np.ones((4, 3), "float32")
    mon.tic()
    exe.forward(is_train=False)
    res = mon.toc()
    assert len(res) >= 1
    names = [k for _, k, _ in res]
    assert any("fc" in n for n in names)


def test_print_summary(capsys):
    from mxnet_tpu import visualization

    net = sym.FullyConnected(data=sym.Variable("data"), num_hidden=4, name="fc")
    total = visualization.print_summary(net, shape={"data": (2, 3)})
    out = capsys.readouterr().out
    assert "fc" in out
    assert total == 4 * 3 + 4  # weight + bias


def test_profiler_api(tmp_path):
    from mxnet_tpu import profiler

    profiler.profiler_set_config(mode="all", filename=str(tmp_path / "p.json"))
    with pytest.raises(mx.MXNetError):
        profiler.profiler_set_config(mode="bogus")
    # start/stop a real capture round-trip — and assert artifacts LANDED
    # (VERDICT r3 weak #4: a profiler that can't prove a dump is no profiler)
    profiler.profiler_set_state("run")
    x = mx.nd.ones((64, 64))
    (mx.nd.dot(x, x) + 1).wait_to_read()
    profiler.profiler_set_state("stop")
    files = profiler.trace_files()
    assert files, "profiler capture produced no trace artifacts"
    assert any(f.endswith((".trace.json.gz", ".xplane.pb")) for f in files), files
    # per-op summary parses the trace (host events on the CPU backend)
    rows = profiler.summarize(device_only=False, top=10)
    assert rows and all({"name", "ms", "count", "process"} <= set(r) for r in rows)


def test_libinfo_and_log():
    """libinfo.find_lib_path lists the built native .so files; log.getLogger
    yields a usable configured logger (reference: libinfo.py, log.py)."""
    from mxnet_tpu import libinfo, log

    libs = libinfo.find_lib_path()
    assert libs, "no native libraries found — build/ missing or names drifted"
    assert all(p.endswith(".so") for p in libs)
    assert libinfo.__version__
    lg = log.getLogger("mxtpu_test_logger", level=log.DEBUG)
    try:
        assert lg.isEnabledFor(log.DEBUG)
        assert lg is log.getLogger("mxtpu_test_logger")  # idempotent
        assert len(lg.handlers) == 1
    finally:
        lg.handlers.clear()  # don't leak handlers into other tests


def test_log_validation_metrics_callback(caplog):
    import collections
    import logging

    import numpy as np

    import mxnet_tpu as mx

    m = mx.metric.Accuracy()
    m.update([mx.nd.array(np.array([1.0, 0.0]))],
             [mx.nd.array(np.array([[0.1, 0.9], [0.8, 0.2]]))])
    P = collections.namedtuple("P", ["epoch", "nbatch", "eval_metric", "locals"])
    with caplog.at_level(logging.INFO):
        mx.callback.LogValidationMetricsCallback()(P(3, 0, m, None))
    assert any("Validation-accuracy" in r.message for r in caplog.records)
