"""Attention op + ring attention (sequence parallel) + Transformer model."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import parallel
from mxnet_tpu import symbol as sym
from mxnet_tpu.parallel.ring_attention import ring_attention


def _ref_attention(q, k, v, causal):
    # numpy oracle over (B,H,T,D); causal mask bottom-right aligned for S>=T
    d = q.shape[-1]
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    if causal:
        T, S = s.shape[-2], s.shape[-1]
        mask = np.tril(np.ones((T, S), bool), k=S - T)
        s = np.where(mask, s, -np.inf)
    s = s - s.max(-1, keepdims=True)
    p = np.exp(s)
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_mha_op_matches_numpy(causal):
    rs = np.random.RandomState(0)
    q, k, v = (rs.randn(2, 3, 8, 4).astype("float32") for _ in range(3))
    out = mx.nd.MultiHeadAttention(mx.nd.array(q), mx.nd.array(k), mx.nd.array(v),
                                   causal=causal).asnumpy()
    np.testing.assert_allclose(out, _ref_attention(q, k, v, causal),
                               rtol=1e-4, atol=1e-5)


def test_mha_causal_rectangular_decode():
    # single-token decode: 1 query over 16 cached keys must see ALL of them
    rs = np.random.RandomState(1)
    q = rs.randn(1, 2, 1, 4).astype("float32")
    k, v = (rs.randn(1, 2, 16, 4).astype("float32") for _ in range(2))
    out = mx.nd.MultiHeadAttention(mx.nd.array(q), mx.nd.array(k), mx.nd.array(v),
                                   causal=True).asnumpy()
    np.testing.assert_allclose(out, _ref_attention(q, k, v, True),
                               rtol=1e-4, atol=1e-5)
    assert np.isfinite(out).all()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_exact(causal):
    import jax

    rs = np.random.RandomState(1)
    B, T, H, D = 2, 16, 2, 4
    q, k, v = (rs.randn(B, T, H, D).astype("float32") for _ in range(3))
    mesh = parallel.make_mesh({"seq": 4}, devices=jax.devices()[:4])
    out = np.asarray(ring_attention(q, k, v, mesh, seq_axis="seq", causal=causal))
    # oracle in (B,H,T,D) layout
    ref = _ref_attention(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                         v.transpose(0, 2, 1, 3), causal).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_ring_attention_grad_flows():
    import jax
    import jax.numpy as jnp

    rs = np.random.RandomState(2)
    B, T, H, D = 1, 8, 1, 4
    q, k, v = (jnp.asarray(rs.randn(B, T, H, D).astype("float32")) for _ in range(3))
    mesh = parallel.make_mesh({"seq": 4}, devices=jax.devices()[:4])

    def loss(q, k, v):
        return ring_attention(q, k, v, mesh, causal=True).sum()

    g = jax.grad(loss)(q, k, v)
    assert np.isfinite(np.asarray(g)).all()


def test_transformer_builds_and_steps():
    from mxnet_tpu.models import transformer

    net = transformer.get_symbol(vocab_size=100, num_layers=2, num_heads=2,
                                 model_dim=16, ffn_dim=32, seq_len=8)
    exe = net.simple_bind(ctx=mx.cpu(), data=(2, 8), softmax_label=(2, 8),
                          type_dict={"data": "int32"})
    rs = np.random.RandomState(3)
    exe.arg_dict["data"][:] = rs.randint(0, 100, (2, 8)).astype("int32")
    exe.arg_dict["softmax_label"][:] = rs.randint(0, 100, (2, 8)).astype("float32")
    for name, arr in exe.arg_dict.items():
        if name in ("data", "softmax_label"):
            continue
        arr[:] = rs.uniform(-0.05, 0.05, arr.shape).astype("float32")
    out = exe.forward_backward()
    assert out[0].shape == (16, 100)
    g = exe.grad_dict["lm_head_weight"].asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_transformer_spmd_trains():
    import jax

    from mxnet_tpu.models import transformer

    mesh = parallel.make_mesh({"data": 2, "model": 2},
                              devices=jax.devices()[:4])
    net = transformer.get_symbol(vocab_size=64, num_layers=1, num_heads=2,
                                 model_dim=16, ffn_dim=32, seq_len=8)
    tr = parallel.SPMDTrainer(net, mesh, optimizer="adam",
                              optimizer_params={"learning_rate": 1e-3})
    tr.init_params({"data": (4, 8)}, {"softmax_label": (4, 8)})
    rs = np.random.RandomState(4)
    x = rs.randint(0, 64, (4, 8)).astype("int32")
    y = rs.randint(0, 64, (4, 8)).astype("float32")
    import jax.numpy as jnp

    for _ in range(2):
        outs = tr.step({"data": jnp.asarray(x)}, {"softmax_label": y})
    assert np.isfinite(np.asarray(outs[0])).all()


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_flash_attention_matches_oracle(causal):
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_attention as pa

    rs = np.random.RandomState(2)
    q = rs.randn(2, 2, 16, 8).astype("float32")
    k, v = (rs.randn(2, 2, 32, 8).astype("float32") for _ in range(2))
    out = np.asarray(pa.flash_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal,
        block_q=8, block_k=8, interpret=True))
    np.testing.assert_allclose(out, _ref_attention(q, k, v, causal),
                               rtol=1e-4, atol=1e-5)


def test_pallas_flash_attention_env_gate(monkeypatch):
    monkeypatch.setenv("MXNET_USE_PALLAS_ATTENTION", "1")
    rs = np.random.RandomState(3)
    q, k, v = (rs.randn(1, 2, 16, 8).astype("float32") for _ in range(3))
    out = mx.nd.MultiHeadAttention(mx.nd.array(q), mx.nd.array(k),
                                   mx.nd.array(v), causal=True).asnumpy()
    np.testing.assert_allclose(out, _ref_attention(q, k, v, True),
                               rtol=1e-4, atol=1e-5)
