"""Attention op + ring attention (sequence parallel) + Transformer model."""
import jax
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import parallel
from mxnet_tpu import symbol as sym
from mxnet_tpu.parallel.ring_attention import ring_attention


def _ref_attention(q, k, v, causal):
    # numpy oracle over (B,H,T,D); causal mask bottom-right aligned for S>=T
    d = q.shape[-1]
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    if causal:
        T, S = s.shape[-2], s.shape[-1]
        mask = np.tril(np.ones((T, S), bool), k=S - T)
        s = np.where(mask, s, -np.inf)
    s = s - s.max(-1, keepdims=True)
    p = np.exp(s)
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_mha_op_matches_numpy(causal):
    rs = np.random.RandomState(0)
    q, k, v = (rs.randn(2, 3, 8, 4).astype("float32") for _ in range(3))
    out = mx.nd.MultiHeadAttention(mx.nd.array(q), mx.nd.array(k), mx.nd.array(v),
                                   causal=causal).asnumpy()
    np.testing.assert_allclose(out, _ref_attention(q, k, v, causal),
                               rtol=1e-4, atol=1e-5)


def test_mha_causal_rectangular_decode():
    # single-token decode: 1 query over 16 cached keys must see ALL of them
    rs = np.random.RandomState(1)
    q = rs.randn(1, 2, 1, 4).astype("float32")
    k, v = (rs.randn(1, 2, 16, 4).astype("float32") for _ in range(2))
    out = mx.nd.MultiHeadAttention(mx.nd.array(q), mx.nd.array(k), mx.nd.array(v),
                                   causal=True).asnumpy()
    np.testing.assert_allclose(out, _ref_attention(q, k, v, True),
                               rtol=1e-4, atol=1e-5)
    assert np.isfinite(out).all()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_exact(causal):
    import jax

    rs = np.random.RandomState(1)
    B, T, H, D = 2, 16, 2, 4
    q, k, v = (rs.randn(B, T, H, D).astype("float32") for _ in range(3))
    mesh = parallel.make_mesh({"seq": 4}, devices=jax.devices()[:4])
    out = np.asarray(ring_attention(q, k, v, mesh, seq_axis="seq", causal=causal))
    # oracle in (B,H,T,D) layout
    ref = _ref_attention(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                         v.transpose(0, 2, 1, 3), causal).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_ring_attention_grad_flows():
    import jax
    import jax.numpy as jnp

    rs = np.random.RandomState(2)
    B, T, H, D = 1, 8, 1, 4
    q, k, v = (jnp.asarray(rs.randn(B, T, H, D).astype("float32")) for _ in range(3))
    mesh = parallel.make_mesh({"seq": 4}, devices=jax.devices()[:4])

    def loss(q, k, v):
        return ring_attention(q, k, v, mesh, causal=True).sum()

    g = jax.grad(loss)(q, k, v)
    assert np.isfinite(np.asarray(g)).all()


def test_transformer_builds_and_steps():
    from mxnet_tpu.models import transformer

    net = transformer.get_symbol(vocab_size=100, num_layers=2, num_heads=2,
                                 model_dim=16, ffn_dim=32, seq_len=8)
    exe = net.simple_bind(ctx=mx.cpu(), data=(2, 8), softmax_label=(2, 8),
                          type_dict={"data": "int32"})
    rs = np.random.RandomState(3)
    exe.arg_dict["data"][:] = rs.randint(0, 100, (2, 8)).astype("int32")
    exe.arg_dict["softmax_label"][:] = rs.randint(0, 100, (2, 8)).astype("float32")
    for name, arr in exe.arg_dict.items():
        if name in ("data", "softmax_label"):
            continue
        arr[:] = rs.uniform(-0.05, 0.05, arr.shape).astype("float32")
    out = exe.forward_backward()
    assert out[0].shape == (16, 100)
    g = exe.grad_dict["lm_head_weight"].asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_transformer_spmd_trains():
    import jax

    from mxnet_tpu.models import transformer

    mesh = parallel.make_mesh({"data": 2, "model": 2},
                              devices=jax.devices()[:4])
    net = transformer.get_symbol(vocab_size=64, num_layers=1, num_heads=2,
                                 model_dim=16, ffn_dim=32, seq_len=8)
    tr = parallel.SPMDTrainer(net, mesh, optimizer="adam",
                              optimizer_params={"learning_rate": 1e-3})
    tr.init_params({"data": (4, 8)}, {"softmax_label": (4, 8)})
    rs = np.random.RandomState(4)
    x = rs.randint(0, 64, (4, 8)).astype("int32")
    y = rs.randint(0, 64, (4, 8)).astype("float32")
    import jax.numpy as jnp

    for _ in range(2):
        outs = tr.step({"data": jnp.asarray(x)}, {"softmax_label": y})
    assert np.isfinite(np.asarray(outs[0])).all()


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_flash_attention_matches_oracle(causal):
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_attention as pa

    rs = np.random.RandomState(2)
    q = rs.randn(2, 2, 16, 8).astype("float32")
    k, v = (rs.randn(2, 2, 32, 8).astype("float32") for _ in range(2))
    out = np.asarray(pa.flash_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal,
        block_q=8, block_k=8, interpret=True))
    np.testing.assert_allclose(out, _ref_attention(q, k, v, causal),
                               rtol=1e-4, atol=1e-5)


def test_pallas_flash_attention_env_gate(monkeypatch):
    monkeypatch.setenv("MXNET_USE_PALLAS_ATTENTION", "1")
    rs = np.random.RandomState(3)
    q, k, v = (rs.randn(1, 2, 16, 8).astype("float32") for _ in range(3))
    out = mx.nd.MultiHeadAttention(mx.nd.array(q), mx.nd.array(k),
                                   mx.nd.array(v), causal=True).asnumpy()
    np.testing.assert_allclose(out, _ref_attention(q, k, v, True),
                               rtol=1e-4, atol=1e-5)


# ------------------------------------------------------- flash attention grads
def _xla_attention_jax(q, k, v, causal, scale=None):
    import jax
    import jax.numpy as jnp

    d = q.shape[-1]
    scale = scale or 1.0 / np.sqrt(d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        T, S = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((T, S), bool), k=S - T)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [
    # (B, H, T, S, D): square, rectangular decode (S > T), multi-block
    (2, 2, 16, 16, 8),
    (1, 2, 8, 32, 8),
    (1, 1, 32, 32, 16),
])
def test_pallas_flash_attention_grad_matches_xla(causal, shape):
    """VERDICT r2 item 5: jax.grad through flash_attention must match the
    XLA path (it used to fail with a bare AssertionError)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_attention as pa

    B, H, T, S, D = shape
    rs = np.random.RandomState(5)
    q = jnp.asarray(rs.randn(B, H, T, D).astype("float32"))
    k = jnp.asarray(rs.randn(B, H, S, D).astype("float32"))
    v = jnp.asarray(rs.randn(B, H, S, D).astype("float32"))
    w = jnp.asarray(rs.randn(B, H, T, D).astype("float32"))  # cotangent mix

    def loss_flash(q, k, v):
        out = pa.flash_attention(q, k, v, causal=causal, block_q=8,
                                 block_k=8, interpret=True)
        return (out * w).sum()

    def loss_xla(q, k, v):
        return (_xla_attention_jax(q, k, v, causal) * w).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gx):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
            err_msg="d%s mismatch (causal=%s shape=%s)" % (name, causal, shape))


def test_pallas_flash_attention_grad_bf16_long_seq():
    """bf16 grads over a longer sequence (S=512, streamed in 128-blocks)
    track the XLA path within bf16 tolerance."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_attention as pa

    rs = np.random.RandomState(6)
    B, H, T, S, D = 1, 1, 256, 512, 8
    q = jnp.asarray(rs.randn(B, H, T, D).astype("float32"), dtype=jnp.bfloat16)
    k = jnp.asarray(rs.randn(B, H, S, D).astype("float32"), dtype=jnp.bfloat16)
    v = jnp.asarray(rs.randn(B, H, S, D).astype("float32"), dtype=jnp.bfloat16)

    def loss_flash(q, k, v):
        return pa.flash_attention(q, k, v, causal=True,
                                  interpret=True).astype(jnp.float32).sum()

    def loss_xla(q, k, v):
        return _xla_attention_jax(q.astype(jnp.float32), k.astype(jnp.float32),
                                  v.astype(jnp.float32), True).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gx):
        np.testing.assert_allclose(
            np.asarray(a, dtype=np.float32), np.asarray(b), rtol=5e-2,
            atol=2e-2, err_msg="d%s bf16 mismatch" % name)


def test_pallas_training_through_module_op():
    """Training through the op (MXNET_USE_PALLAS_ATTENTION=1) must not
    crash and must produce finite grads — the round-2 failure mode."""
    import jax
    import jax.numpy as jnp
    import os

    from mxnet_tpu.ops import pallas_attention as pa

    old = os.environ.get("MXNET_USE_PALLAS_ATTENTION")
    os.environ["MXNET_USE_PALLAS_ATTENTION"] = "1"
    try:
        rs = np.random.RandomState(7)
        q, k, v = (jnp.asarray(rs.randn(1, 2, 16, 8).astype("float32"))
                   for _ in range(3))
        from mxnet_tpu.ops.registry import get_op
        op = get_op("_contrib_MultiHeadAttention")

        def loss(q, k, v):
            return op.fn({"causal": True, "scale": -1.0}, q, k, v).sum()

        grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        assert all(np.isfinite(np.asarray(g)).all() for g in grads)
    finally:
        if old is None:
            os.environ.pop("MXNET_USE_PALLAS_ATTENTION", None)
        else:
            os.environ["MXNET_USE_PALLAS_ATTENTION"] = old


def test_pallas_supported_rejects_causal_decode_underflow():
    """ADVICE r2: causal with S < T has fully-masked rows — must be
    rejected so the XLA path handles it."""
    from mxnet_tpu.ops import pallas_attention as pa

    assert not pa.supported((1, 1, 32, 8), (1, 1, 16, 8), causal=True)
    assert pa.supported((1, 1, 32, 8), (1, 1, 16, 8), causal=False)
    assert pa.supported((1, 1, 16, 8), (1, 1, 32, 8), causal=True)


@pytest.mark.skipif("jax.default_backend() != 'tpu'")
def test_pallas_flash_attention_grad_8k_tpu():
    """Long-context check on real hardware: S=T=8192 streams through VMEM in
    128-blocks (fwd + bwd), grads finite and close to XLA (bf16)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas_attention as pa

    rs = np.random.RandomState(8)
    B, H, T, D = 1, 1, 8192, 64
    q, k, v = (jnp.asarray(rs.randn(B, H, T, D).astype("float32") * 0.1,
                           dtype=jnp.bfloat16) for _ in range(3))

    def loss(q, k, v):
        return pa.flash_attention(q, k, v, causal=True).astype(jnp.float32).sum()

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert all(np.isfinite(np.asarray(g, dtype=np.float32)).all() for g in grads)

    def loss_xla(q, k, v):
        return _xla_attention_jax(q.astype(jnp.float32), k.astype(jnp.float32),
                                  v.astype(jnp.float32), True).sum()

    gx = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", grads, gx):
        np.testing.assert_allclose(np.asarray(a, dtype=np.float32),
                                   np.asarray(b), rtol=5e-2, atol=5e-2,
                                   err_msg="d%s 8k mismatch" % name)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_dispatch_in_op(causal):
    """Under a trace mesh with a 'seq' axis, the MultiHeadAttention op must
    dispatch to ring attention (dp x sp) and match the dense path exactly."""
    import jax

    from mxnet_tpu.ops import attention as attn_op
    from mxnet_tpu.ops.registry import get_op
    from mxnet_tpu.parallel.mesh import trace_mesh

    rs = np.random.RandomState(3)
    B, H, T, D = 4, 2, 16, 4
    q, k, v = (rs.randn(B, H, T, D).astype("float32") for _ in range(3))
    opdef = get_op("_contrib_MultiHeadAttention")
    attrs = {"causal": causal, "scale": -1.0}
    (dense,), _ = opdef.apply(attrs, [q, k, v])

    mesh = parallel.make_mesh({"data": 2, "seq": 4}, devices=jax.devices()[:8])
    before = attn_op.DISPATCH_COUNTS["ring"]
    with trace_mesh(mesh):
        (ring,), _ = opdef.apply(attrs, [q, k, v])
    assert attn_op.DISPATCH_COUNTS["ring"] == before + 1
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=1e-4, atol=1e-5)


def test_ring_dispatch_respects_kill_switch(monkeypatch):
    import jax

    from mxnet_tpu.ops import attention as attn_op
    from mxnet_tpu.ops.registry import get_op
    from mxnet_tpu.parallel.mesh import trace_mesh

    monkeypatch.setenv("MXNET_RING_ATTENTION", "0")
    rs = np.random.RandomState(4)
    q, k, v = (rs.randn(2, 2, 16, 4).astype("float32") for _ in range(3))
    mesh = parallel.make_mesh({"data": 2, "seq": 4}, devices=jax.devices()[:8])
    before = attn_op.DISPATCH_COUNTS["ring"]
    with trace_mesh(mesh):
        get_op("_contrib_MultiHeadAttention").apply(
            {"causal": True, "scale": -1.0}, [q, k, v])
    assert attn_op.DISPATCH_COUNTS["ring"] == before
