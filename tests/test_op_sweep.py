"""Registry-driven operator sweep (round-5, VERDICT #3).

One declarative spec per registered op: forward against a numpy/scipy
oracle, a finite-difference gradient check where the op is smooth, moment
tests for the samplers. The meta-test at the bottom walks
``registry.list_ops()`` and FAILS if any registered op has neither a spec
here nor an explicit EXEMPT pointer to the dedicated suite that covers it —
silent breakage of an op can no longer pass CI. Depth model:
/root/reference/tests/python/unittest/test_operator.py + test_random.py.
"""
import math

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import test_utils
from mxnet_tpu.ops import registry

RS = lambda seed=0: np.random.RandomState(seed)


def _u(lo, hi, shape=(3, 4), seed=0):
    return RS(seed).uniform(lo, hi, shape).astype("float32")


class Spec:
    """One op's sweep entry. ``build(rs)`` returns (symbol, location,
    expected outputs); ``grad`` enables the finite-difference check."""

    def __init__(self, build, grad=False, rtol=1e-4, atol=1e-5,
                 grad_eps=1e-2):
        self.build, self.grad = build, grad
        self.rtol, self.atol, self.grad_eps = rtol, atol, grad_eps


def UNARY(fn, lo=-1.0, hi=1.0, grad=True, name=None, **kw):
    def build(op):
        x = _u(lo, hi)
        s = getattr(mx.sym, op)(mx.sym.Variable("x"))
        return s, {"x": x}, [fn(x)]

    return Spec(build, grad=grad, **kw)


def BINARY(fn, lo=-1.0, hi=1.0, bcast=False, grad=True, **kw):
    def build(op):
        a = _u(lo, hi, (3, 4), 1)
        b = _u(lo, hi, (3, 1) if bcast else (3, 4), 2)
        s = getattr(mx.sym, op)(mx.sym.Variable("a"), mx.sym.Variable("b"))
        return s, {"a": a, "b": b}, [fn(a, b)]

    return Spec(build, grad=grad, **kw)


def SCALAR(fn, scalar=1.7, lo=-1.0, hi=1.0, grad=True, **kw):
    def build(op):
        x = _u(lo, hi, seed=3)
        s = getattr(mx.sym, op)(mx.sym.Variable("x"), scalar=scalar)
        return s, {"x": x}, [fn(x, np.float32(scalar))]

    return Spec(build, grad=grad, **kw)


def REDUCE(fn, lo=0.5, hi=1.5, grad=True, attrs=None, **kw):
    attrs = attrs if attrs is not None else {"axis": (1,), "keepdims": True}

    def build(op):
        x = _u(lo, hi, (2, 3, 4), 4)
        s = getattr(mx.sym, op)(mx.sym.Variable("x"), **attrs)
        ax = attrs.get("axis")
        np_kw = {}
        if ax is not None and ax != ():
            np_kw["axis"] = ax if not isinstance(ax, tuple) or len(ax) > 1 else ax[0]
        if attrs.get("keepdims"):
            np_kw["keepdims"] = True
        return s, {"x": x}, [fn(x, **np_kw)]

    return Spec(build, grad=grad, **kw)


def CUSTOM(build, **kw):
    return Spec(build, **kw)


def _sp():
    return pytest.importorskip("scipy.special")


# ---------------------------------------------------------------------- specs
SPECS = {
    # ---- unary elementwise (elemwise_unary_op.cc families)
    "abs": UNARY(np.abs),
    "negative": UNARY(np.negative),
    "reciprocal": UNARY(np.reciprocal, 0.5, 2.0),
    "sign": UNARY(np.sign, grad=False),
    "square": UNARY(np.square),
    "sqrt": UNARY(np.sqrt, 0.5, 2.0),
    "rsqrt": UNARY(lambda x: 1.0 / np.sqrt(x), 0.5, 2.0),
    "cbrt": UNARY(np.cbrt, 0.5, 2.0),
    "rcbrt": UNARY(lambda x: 1.0 / np.cbrt(x), 0.5, 2.0),
    "exp": UNARY(np.exp),
    "expm1": UNARY(np.expm1),
    "log": UNARY(np.log, 0.5, 3.0),
    "log10": UNARY(np.log10, 0.5, 3.0),
    "log2": UNARY(np.log2, 0.5, 3.0),
    "log1p": UNARY(np.log1p, -0.4, 2.0),
    "sin": UNARY(np.sin),
    "cos": UNARY(np.cos),
    "tan": UNARY(np.tan, -1.0, 1.0),
    "arcsin": UNARY(np.arcsin, -0.8, 0.8),
    "arccos": UNARY(np.arccos, -0.8, 0.8),
    "arctan": UNARY(np.arctan, -2.0, 2.0),
    "sinh": UNARY(np.sinh),
    "cosh": UNARY(np.cosh),
    "tanh": UNARY(np.tanh),
    "arcsinh": UNARY(np.arcsinh),
    "arccosh": UNARY(np.arccosh, 1.2, 3.0),
    "arctanh": UNARY(np.arctanh, -0.8, 0.8),
    "degrees": UNARY(np.degrees),
    "radians": UNARY(np.radians),
    # rounding family: no ties in (lo,hi) randoms; zero/undefined gradient
    "ceil": UNARY(np.ceil, -2.3, 2.3, grad=False),
    "floor": UNARY(np.floor, -2.3, 2.3, grad=False),
    "trunc": UNARY(np.trunc, -2.3, 2.3, grad=False),
    "fix": UNARY(np.fix, -2.3, 2.3, grad=False),
    "rint": UNARY(np.rint, -2.3, 2.3, grad=False),
    "round": UNARY(lambda x: np.sign(x) * np.floor(np.abs(x) + 0.5),
                   -2.3, 2.3, grad=False),
    "erf": CUSTOM(lambda op: (mx.sym.erf(mx.sym.Variable("x")),
                              {"x": _u(-2, 2)},
                              [_sp().erf(_u(-2, 2)).astype("float32")]),
                  grad=True),
    "gamma": CUSTOM(lambda op: (mx.sym.gamma(mx.sym.Variable("x")),
                                {"x": _u(1.2, 3.0)},
                                [_sp().gamma(_u(1.2, 3.0)).astype("float32")]),
                    grad=True),
    "gammaln": CUSTOM(lambda op: (mx.sym.gammaln(mx.sym.Variable("x")),
                                  {"x": _u(1.2, 3.0)},
                                  [_sp().gammaln(_u(1.2, 3.0)).astype("float32")]),
                      grad=True),
    "relu": UNARY(lambda x: np.maximum(x, 0), grad=False),  # kink at 0
    "sigmoid": UNARY(lambda x: 1 / (1 + np.exp(-x))),
    "softsign": UNARY(lambda x: x / (1 + np.abs(x))),
    "logical_not": UNARY(lambda x: (x == 0).astype("float32"), -1, 1,
                         grad=False),
    "_copy": UNARY(lambda x: x),
    "ones_like": UNARY(np.ones_like, grad=False),
    "zeros_like": UNARY(np.zeros_like, grad=False),
    "BlockGrad": UNARY(lambda x: x, grad=False),
    "smooth_l1": SCALAR(
        lambda x, s: np.where(np.abs(x) < 1 / s ** 2,
                              0.5 * (s * x) ** 2, np.abs(x) - 0.5 / s ** 2),
        scalar=1.0, lo=-2, hi=2, grad=False),
    "clip": CUSTOM(lambda op: (
        mx.sym.clip(mx.sym.Variable("x"), a_min=-0.5, a_max=0.5),
        {"x": _u(-1, 1)}, [np.clip(_u(-1, 1), -0.5, 0.5)]), grad=False),
    "Cast": CUSTOM(lambda op: (
        mx.sym.Cast(mx.sym.Variable("x"), dtype="float64"),
        {"x": _u(-1, 1)}, [_u(-1, 1).astype("float64")])),
    # ---- binary elementwise
    "elemwise_add": BINARY(np.add),
    "elemwise_sub": BINARY(np.subtract),
    "elemwise_mul": BINARY(np.multiply),
    "elemwise_div": BINARY(np.divide, 0.5, 2.0),
    "_grad_add": BINARY(np.add),
    "_maximum": BINARY(np.maximum, grad=False),
    "_minimum": BINARY(np.minimum, grad=False),
    "_hypot": BINARY(np.hypot, 0.5, 2.0),
    "_mod": BINARY(np.mod, 1.0, 3.0, grad=False),
    "_power": BINARY(np.power, 0.5, 2.0),
    "_equal": BINARY(lambda a, b: (a == b).astype("f"), grad=False),
    "_not_equal": BINARY(lambda a, b: (a != b).astype("f"), grad=False),
    "_greater": BINARY(lambda a, b: (a > b).astype("f"), grad=False),
    "_greater_equal": BINARY(lambda a, b: (a >= b).astype("f"), grad=False),
    "_lesser": BINARY(lambda a, b: (a < b).astype("f"), grad=False),
    "_lesser_equal": BINARY(lambda a, b: (a <= b).astype("f"), grad=False),
    # ---- broadcast binary
    "broadcast_add": BINARY(np.add, bcast=True),
    "broadcast_sub": BINARY(np.subtract, bcast=True),
    "broadcast_mul": BINARY(np.multiply, bcast=True),
    "broadcast_div": BINARY(np.divide, 0.5, 2.0, bcast=True),
    "broadcast_mod": BINARY(np.mod, 1.0, 3.0, bcast=True, grad=False),
    "broadcast_power": BINARY(np.power, 0.5, 2.0, bcast=True),
    "broadcast_maximum": BINARY(np.maximum, bcast=True, grad=False),
    "broadcast_minimum": BINARY(np.minimum, bcast=True, grad=False),
    "broadcast_hypot": BINARY(np.hypot, 0.5, 2.0, bcast=True),
    "broadcast_equal": BINARY(lambda a, b: (a == b).astype("f"),
                              bcast=True, grad=False),
    "broadcast_not_equal": BINARY(lambda a, b: (a != b).astype("f"),
                                  bcast=True, grad=False),
    "broadcast_greater": BINARY(lambda a, b: (a > b).astype("f"),
                                bcast=True, grad=False),
    "broadcast_greater_equal": BINARY(lambda a, b: (a >= b).astype("f"),
                                      bcast=True, grad=False),
    "broadcast_lesser": BINARY(lambda a, b: (a < b).astype("f"),
                               bcast=True, grad=False),
    "broadcast_lesser_equal": BINARY(lambda a, b: (a <= b).astype("f"),
                                     bcast=True, grad=False),
    # ---- scalar ops
    "_plus_scalar": SCALAR(lambda x, s: x + s),
    "_minus_scalar": SCALAR(lambda x, s: x - s),
    "_rminus_scalar": SCALAR(lambda x, s: s - x),
    "_mul_scalar": SCALAR(lambda x, s: x * s),
    "_div_scalar": SCALAR(lambda x, s: x / s),
    "_rdiv_scalar": SCALAR(lambda x, s: s / x, lo=0.5, hi=2.0),
    "_mod_scalar": SCALAR(lambda x, s: np.mod(x, s), lo=1, hi=3, grad=False),
    "_rmod_scalar": SCALAR(lambda x, s: np.mod(s, x), lo=1, hi=3, grad=False),
    "_power_scalar": SCALAR(lambda x, s: np.power(x, s), lo=0.5, hi=2.0),
    "_rpower_scalar": SCALAR(lambda x, s: np.power(s, x)),
    "_maximum_scalar": SCALAR(np.maximum, scalar=0.1, grad=False),
    "_minimum_scalar": SCALAR(np.minimum, scalar=0.1, grad=False),
    "_hypot_scalar": SCALAR(np.hypot, lo=0.5, hi=2.0),
    "_equal_scalar": SCALAR(lambda x, s: (x == s).astype("f"), grad=False),
    "_not_equal_scalar": SCALAR(lambda x, s: (x != s).astype("f"), grad=False),
    "_greater_scalar": SCALAR(lambda x, s: (x > s).astype("f"), scalar=0.0,
                              grad=False),
    "_greater_equal_scalar": SCALAR(lambda x, s: (x >= s).astype("f"),
                                    scalar=0.0, grad=False),
    "_lesser_scalar": SCALAR(lambda x, s: (x < s).astype("f"), scalar=0.0,
                             grad=False),
    "_lesser_equal_scalar": SCALAR(lambda x, s: (x <= s).astype("f"),
                                   scalar=0.0, grad=False),
    # ---- reductions
    "sum": REDUCE(np.sum),
    "mean": REDUCE(np.mean),
    "prod": REDUCE(np.prod),
    "max": REDUCE(np.max, grad=False),
    "min": REDUCE(np.min, grad=False),
    "nansum": CUSTOM(lambda op: _nan_reduce(mx.sym.nansum, np.nansum),
                     grad=False),
    "nanprod": CUSTOM(lambda op: _nan_reduce(mx.sym.nanprod, np.nanprod),
                      grad=False),
    "norm": CUSTOM(lambda op: (
        mx.sym.norm(mx.sym.Variable("x")), {"x": _u(-1, 1, (3, 4), 6)},
        [np.sqrt(np.sum(np.square(_u(-1, 1, (3, 4), 6))))]), grad=False),
    "argmax": REDUCE(lambda x, axis, keepdims: np.argmax(x, axis=axis)
                     .astype("f")[:, None],
                     attrs={"axis": 1, "keepdims": True}, grad=False),
    "argmin": REDUCE(lambda x, axis, keepdims: np.argmin(x, axis=axis)
                     .astype("f")[:, None],
                     attrs={"axis": 1, "keepdims": True}, grad=False),
    "argmax_channel": CUSTOM(lambda op: (
        mx.sym.argmax_channel(mx.sym.Variable("x")),
        {"x": _u(-1, 1, (3, 4), 7)},
        [np.argmax(_u(-1, 1, (3, 4), 7), axis=1).astype("f")]), grad=False),
    # ---- shape / layout
    "Reshape": CUSTOM(lambda op: (
        mx.sym.Reshape(mx.sym.Variable("x"), shape=(4, 3)),
        {"x": _u(-1, 1)}, [_u(-1, 1).reshape(4, 3)]), grad=True),
    "Flatten": CUSTOM(lambda op: (
        mx.sym.Flatten(mx.sym.Variable("x")),
        {"x": _u(-1, 1, (2, 3, 4))}, [_u(-1, 1, (2, 3, 4)).reshape(2, 12)]),
        grad=True),
    "expand_dims": CUSTOM(lambda op: (
        mx.sym.expand_dims(mx.sym.Variable("x"), axis=1),
        {"x": _u(-1, 1)}, [_u(-1, 1)[:, None, :]]), grad=True),
    "transpose": CUSTOM(lambda op: (
        mx.sym.transpose(mx.sym.Variable("x"), axes=(1, 0)),
        {"x": _u(-1, 1)}, [_u(-1, 1).T]), grad=True),
    "SwapAxis": CUSTOM(lambda op: (
        mx.sym.SwapAxis(mx.sym.Variable("x"), dim1=0, dim2=2),
        {"x": _u(-1, 1, (2, 3, 4))}, [_u(-1, 1, (2, 3, 4)).swapaxes(0, 2)]),
        grad=True),
    "tile": CUSTOM(lambda op: (
        mx.sym.tile(mx.sym.Variable("x"), reps=(2, 3)),
        {"x": _u(-1, 1)}, [np.tile(_u(-1, 1), (2, 3))]), grad=True),
    "repeat": CUSTOM(lambda op: (
        mx.sym.repeat(mx.sym.Variable("x"), repeats=2, axis=1),
        {"x": _u(-1, 1)}, [np.repeat(_u(-1, 1), 2, axis=1)]), grad=True),
    "reverse": CUSTOM(lambda op: (
        mx.sym.reverse(mx.sym.Variable("x"), axis=(1,)),
        {"x": _u(-1, 1)}, [_u(-1, 1)[:, ::-1]]), grad=True),
    "broadcast_to": CUSTOM(lambda op: (
        mx.sym.broadcast_to(mx.sym.Variable("x"), shape=(3, 4)),
        {"x": _u(-1, 1, (3, 1), 8)},
        [np.broadcast_to(_u(-1, 1, (3, 1), 8), (3, 4))]), grad=True),
    "broadcast_axis": CUSTOM(lambda op: (
        mx.sym.broadcast_axis(mx.sym.Variable("x"), axis=(1,), size=(4,)),
        {"x": _u(-1, 1, (3, 1), 8)},
        [np.broadcast_to(_u(-1, 1, (3, 1), 8), (3, 4))]), grad=True),
    "slice": CUSTOM(lambda op: (
        mx.sym.slice(mx.sym.Variable("x"), begin=(1, 0), end=(3, 2)),
        {"x": _u(-1, 1, (4, 4), 9)}, [_u(-1, 1, (4, 4), 9)[1:3, 0:2]]),
        grad=True),
    "slice_axis": CUSTOM(lambda op: (
        mx.sym.slice_axis(mx.sym.Variable("x"), axis=1, begin=1, end=3),
        {"x": _u(-1, 1, (4, 4), 9)}, [_u(-1, 1, (4, 4), 9)[:, 1:3]]),
        grad=True),
    "Concat": CUSTOM(lambda op: (
        mx.sym.Concat(mx.sym.Variable("a"), mx.sym.Variable("b"), dim=1),
        {"a": _u(-1, 1, (3, 2), 1), "b": _u(-1, 1, (3, 3), 2)},
        [np.concatenate([_u(-1, 1, (3, 2), 1), _u(-1, 1, (3, 3), 2)], 1)]),
        grad=True),
    "SliceChannel": CUSTOM(lambda op: (
        mx.sym.SliceChannel(mx.sym.Variable("x"), num_outputs=2, axis=1),
        {"x": _u(-1, 1, (3, 4), 10)},
        [_u(-1, 1, (3, 4), 10)[:, :2], _u(-1, 1, (3, 4), 10)[:, 2:]]),
        grad=True),
    "add_n": CUSTOM(lambda op: (
        mx.sym.add_n(mx.sym.Variable("a"), mx.sym.Variable("b"),
                     mx.sym.Variable("c")),
        {"a": _u(-1, 1, (3, 4), 1), "b": _u(-1, 1, (3, 4), 2),
         "c": _u(-1, 1, (3, 4), 3)},
        [_u(-1, 1, (3, 4), 1) + _u(-1, 1, (3, 4), 2) + _u(-1, 1, (3, 4), 3)]),
        grad=True),
    "where": CUSTOM(lambda op: (
        mx.sym.where(mx.sym.Variable("c"), mx.sym.Variable("a"),
                     mx.sym.Variable("b")),
        {"c": (RS(11).rand(3, 4) > 0.5).astype("f"),
         "a": _u(-1, 1, (3, 4), 1), "b": _u(-1, 1, (3, 4), 2)},
        [np.where(RS(11).rand(3, 4) > 0.5, _u(-1, 1, (3, 4), 1),
                  _u(-1, 1, (3, 4), 2))]), grad=False),
    "Pad": CUSTOM(lambda op: (
        mx.sym.Pad(mx.sym.Variable("x"), mode="constant",
                   pad_width=(0, 0, 0, 0, 1, 1, 2, 2), constant_value=0.5),
        {"x": _u(-1, 1, (2, 3, 4, 4), 12)},
        [np.pad(_u(-1, 1, (2, 3, 4, 4), 12),
                ((0, 0), (0, 0), (1, 1), (2, 2)), mode="constant",
                constant_values=0.5)]), grad=True),
    # ---- indexing / gather
    "take": CUSTOM(lambda op: (
        mx.sym.take(mx.sym.Variable("a"), mx.sym.Variable("i")),
        {"a": _u(-1, 1, (5, 3), 13), "i": np.array([0., 2., 4.], "f")},
        [_u(-1, 1, (5, 3), 13)[[0, 2, 4]]]), grad=False),
    "batch_take": CUSTOM(lambda op: (
        mx.sym.batch_take(mx.sym.Variable("a"), mx.sym.Variable("i")),
        {"a": _u(-1, 1, (3, 4), 13), "i": np.array([0., 3., 1.], "f")},
        [_u(-1, 1, (3, 4), 13)[np.arange(3), [0, 3, 1]]]), grad=False),
    "pick": CUSTOM(lambda op: (
        mx.sym.pick(mx.sym.Variable("a"), mx.sym.Variable("i"), axis=1),
        {"a": _u(-1, 1, (3, 4), 14), "i": np.array([1., 0., 3.], "f")},
        [_u(-1, 1, (3, 4), 14)[np.arange(3), [1, 0, 3]]]), grad=False),
    "one_hot": CUSTOM(lambda op: (
        mx.sym.one_hot(mx.sym.Variable("i"), depth=4),
        {"i": np.array([0., 2., 3.], "f")},
        [np.eye(4, dtype="f")[[0, 2, 3]]]), grad=False),
    "Embedding": CUSTOM(lambda op: (
        mx.sym.Embedding(mx.sym.Variable("i"), mx.sym.Variable("w"),
                         input_dim=5, output_dim=3),
        {"i": np.array([1., 4., 0.], "f"), "w": _u(-1, 1, (5, 3), 15)},
        [_u(-1, 1, (5, 3), 15)[[1, 4, 0]]]), grad=False),
    # same lookup; the row-sparse-gradient contract lives in the sparse
    # subsystem (tests/test_sparse.py), the op itself is the plain gather
    "SparseEmbedding": CUSTOM(lambda op: (
        mx.sym.SparseEmbedding(mx.sym.Variable("i"), mx.sym.Variable("w"),
                               input_dim=5, output_dim=3),
        {"i": np.array([1., 4., 0.], "f"), "w": _u(-1, 1, (5, 3), 15)},
        [_u(-1, 1, (5, 3), 15)[[1, 4, 0]]]), grad=False),
    # ---- linalg
    "dot": CUSTOM(lambda op: (
        mx.sym.dot(mx.sym.Variable("a"), mx.sym.Variable("b")),
        {"a": _u(-1, 1, (3, 4), 16), "b": _u(-1, 1, (4, 2), 17)},
        [_u(-1, 1, (3, 4), 16) @ _u(-1, 1, (4, 2), 17)]), grad=True,
        rtol=1e-3, atol=1e-4),
    "batch_dot": CUSTOM(lambda op: (
        mx.sym.batch_dot(mx.sym.Variable("a"), mx.sym.Variable("b")),
        {"a": _u(-1, 1, (2, 3, 4), 16), "b": _u(-1, 1, (2, 4, 2), 17)},
        [np.einsum("bij,bjk->bik", _u(-1, 1, (2, 3, 4), 16),
                   _u(-1, 1, (2, 4, 2), 17))]), grad=True,
        rtol=1e-3, atol=1e-4),
    # ---- softmax family. The gradient check weights the output by a second
    # input: with the checker's all-ones head gradient, d(sum softmax)/dx
    # is identically zero (softmax rows sum to 1) and the check degenerates.
    "softmax": CUSTOM(lambda op: _weighted(
        mx.sym.softmax(mx.sym.Variable("x"), axis=-1),
        _np_softmax(_u(-2, 2, (3, 4), 18))), grad=True),
    "log_softmax": CUSTOM(lambda op: _weighted(
        mx.sym.log_softmax(mx.sym.Variable("x"), axis=-1),
        np.log(_np_softmax(_u(-2, 2, (3, 4), 18)))), grad=True),
    "SoftmaxActivation": CUSTOM(lambda op: _weighted(
        mx.sym.SoftmaxActivation(mx.sym.Variable("x")),
        _np_softmax(_u(-2, 2, (3, 4), 18))), grad=True),
    # ---- sorting
    "sort": CUSTOM(lambda op: (
        mx.sym.sort(mx.sym.Variable("x"), axis=1),
        {"x": _u(-1, 1, (3, 4), 19)}, [np.sort(_u(-1, 1, (3, 4), 19), 1)]),
        grad=False),
    "argsort": CUSTOM(lambda op: (
        mx.sym.argsort(mx.sym.Variable("x"), axis=1),
        {"x": _u(-1, 1, (3, 4), 19)},
        [np.argsort(_u(-1, 1, (3, 4), 19), 1).astype("f")]), grad=False),
    "topk": CUSTOM(lambda op: (
        mx.sym.topk(mx.sym.Variable("x"), axis=1, k=2),
        {"x": _u(-1, 1, (3, 4), 19)},
        [np.argsort(-_u(-1, 1, (3, 4), 19), 1)[:, :2].astype("f")]),
        grad=False),
    # ---- creation (no-input; imperative path)
    "_zeros": CUSTOM(lambda op: (None, {"shape": (2, 3)},
                                 [np.zeros((2, 3), "f")])),
    "_ones": CUSTOM(lambda op: (None, {"shape": (2, 3)},
                                [np.ones((2, 3), "f")])),
    "_full": CUSTOM(lambda op: (None, {"shape": (2, 3), "value": 2.5},
                                [np.full((2, 3), 2.5, "f")])),
    "_arange": CUSTOM(lambda op: (None, {"start": 2.0, "stop": 8.0,
                                         "step": 1.5},
                                  [np.arange(2.0, 8.0, 1.5, "f")])),
    # ---- layers with no dedicated suite (VERDICT r4 weak #3 names these)
    "InstanceNorm": CUSTOM(lambda op: _instance_norm_spec(), grad=True,
                           rtol=1e-3, atol=1e-4),
    "UpSampling": CUSTOM(lambda op: (
        mx.sym.UpSampling(mx.sym.Variable("x"), scale=2,
                          sample_type="nearest"),
        {"x": _u(-1, 1, (2, 3, 4, 4), 29)},
        [_u(-1, 1, (2, 3, 4, 4), 29).repeat(2, 2).repeat(2, 3)]), grad=True),
    "IdentityAttachKLSparseReg": CUSTOM(lambda op: (
        mx.sym.IdentityAttachKLSparseReg(mx.sym.Variable("x")),
        {"x": _u(0.05, 0.95, (3, 4), 30)}, [_u(0.05, 0.95, (3, 4), 30)],
        {"identityattachklsparsereg0_moving_avg": np.full((4,), 0.2, "f")})),
    "_CrossDeviceCopy": CUSTOM(lambda op: (
        getattr(mx.sym, "_CrossDeviceCopy")(mx.sym.Variable("x")),
        {"x": _u(-1, 1, (3, 4), 31)}, [_u(-1, 1, (3, 4), 31)]), grad=True),
    # ---- optimizer updates (closed-form oracles; reference
    # src/operator/optimizer_op.cc:18-85)
    "sgd_update": CUSTOM(lambda op: _opt_sgd()),
    "sgd_mom_update": CUSTOM(lambda op: _opt_sgd_mom()),
    "adam_update": CUSTOM(lambda op: _opt_adam()),
    "rmsprop_update": CUSTOM(lambda op: _opt_rmsprop()),
    "rmspropalex_update": CUSTOM(lambda op: _opt_rmspropalex()),
}


def _nan_reduce(symf, npf):
    x = _u(0.5, 1.5, (3, 4), 5)
    x[0, 1] = np.nan
    x[2, 2] = np.nan
    return symf(mx.sym.Variable("x"), axis=(1,)), {"x": x}, [npf(x, axis=1)]


def _np_softmax(x):
    e = np.exp(x - x.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


def _weighted(sym_out, expect_raw):
    """Multiply an op's output by a second variable so the sum objective of
    the gradient checker is non-degenerate, keeping the forward checkable."""
    w = _u(0.5, 1.5, (3, 4), 99)
    s = mx.sym.elemwise_mul(sym_out, mx.sym.Variable("wgt"))
    return s, {"x": _u(-2, 2, (3, 4), 18), "wgt": w}, [expect_raw * w]


def _instance_norm_spec(eps=1e-3):
    x = _u(-1, 1, (2, 3, 4, 4), 32)
    g = _u(0.5, 1.5, (3,), 33)
    b = _u(-0.2, 0.2, (3,), 34)
    m = x.mean(axis=(2, 3), keepdims=True)
    v = x.var(axis=(2, 3), keepdims=True)
    want = ((x - m) / np.sqrt(v + eps) * g[None, :, None, None]
            + b[None, :, None, None])
    # weight the output (as in _weighted): the plain sum objective is
    # degenerate for a normalizer (sum of out == sum of beta, grad wrt x ~ 0)
    w = _u(0.5, 1.5, (2, 3, 4, 4), 35)
    s = mx.sym.InstanceNorm(mx.sym.Variable("x"), mx.sym.Variable("g"),
                            mx.sym.Variable("b"), eps=eps)
    s = mx.sym.elemwise_mul(s, mx.sym.Variable("wgt"))
    return s, {"x": x, "g": g, "b": b, "wgt": w}, [want * w]


# -------------------------------------------------- optimizer-update oracles
def _opt_arrays():
    w = _u(-1, 1, (3, 4), 20)
    g = _u(-1, 1, (3, 4), 21)
    return w, g


def _clip(g, c):
    return np.clip(g, -c, c) if c > 0 else g


def _opt_sgd(lr=0.1, wd=0.01, rescale=2.0, clip=0.5):
    w, g = _opt_arrays()
    gp = _clip(g * rescale, clip)
    want = w - lr * (gp + wd * w)
    s = mx.sym.sgd_update(mx.sym.Variable("w"), mx.sym.Variable("g"),
                          lr=lr, wd=wd, rescale_grad=rescale,
                          clip_gradient=clip)
    return s, {"w": w, "g": g}, [want]


def _opt_sgd_mom(lr=0.1, wd=0.01, mom=0.9):
    w, g = _opt_arrays()
    m = _u(-0.1, 0.1, (3, 4), 22)
    new_m = mom * m - lr * (g + wd * w)
    s = mx.sym.sgd_mom_update(mx.sym.Variable("w"), mx.sym.Variable("g"),
                              mx.sym.Variable("m"), lr=lr, wd=wd,
                              momentum=mom)
    return s, {"w": w, "g": g, "m": m}, [w + new_m, new_m]


def _opt_adam(lr=0.01, wd=0.01, b1=0.9, b2=0.999, eps=1e-8):
    w, g = _opt_arrays()
    m = _u(-0.1, 0.1, (3, 4), 23)
    v = _u(0.0, 0.1, (3, 4), 24)
    gp = g + wd * w
    nm = b1 * m + (1 - b1) * gp
    nv = b2 * v + (1 - b2) * gp ** 2
    want_w = w - lr * nm / (np.sqrt(nv) + eps)
    s = mx.sym.adam_update(mx.sym.Variable("w"), mx.sym.Variable("g"),
                           mx.sym.Variable("m"), mx.sym.Variable("v"),
                           lr=lr, wd=wd, beta1=b1, beta2=b2, epsilon=eps)
    return s, {"w": w, "g": g, "m": m, "v": v}, [want_w, nm, nv]


def _opt_rmsprop(lr=0.01, wd=0.0, g1=0.95, eps=1e-8):
    w, g = _opt_arrays()
    n = _u(0.0, 0.1, (3, 4), 25)
    gp = g + wd * w
    nn = g1 * n + (1 - g1) * gp ** 2
    want_w = w - lr * gp / np.sqrt(nn + eps)
    s = mx.sym.rmsprop_update(mx.sym.Variable("w"), mx.sym.Variable("g"),
                              mx.sym.Variable("n"), lr=lr, wd=wd, gamma1=g1,
                              epsilon=eps)
    return s, {"w": w, "g": g, "n": n}, [want_w, nn]


def _opt_rmspropalex(lr=0.01, g1=0.95, g2=0.9, eps=1e-8):
    w, g = _opt_arrays()
    n = _u(0.5, 1.0, (3, 4), 26)
    gs = _u(-0.1, 0.1, (3, 4), 27)
    d = _u(-0.1, 0.1, (3, 4), 28)
    nn = g1 * n + (1 - g1) * g ** 2
    ng = g1 * gs + (1 - g1) * g
    nd = g2 * d - lr * g / np.sqrt(nn - ng ** 2 + eps)
    s = mx.sym.rmspropalex_update(
        mx.sym.Variable("w"), mx.sym.Variable("g"), mx.sym.Variable("n"),
        mx.sym.Variable("gs"), mx.sym.Variable("d"), lr=lr, gamma1=g1,
        gamma2=g2, epsilon=eps)
    return s, {"w": w, "g": g, "n": n, "gs": gs, "d": d}, [w + nd, nn, ng, nd]


# ------------------------------------------------------------ forward + grad
def _built(spec, opname):
    out = spec.build(opname)
    sym, loc, expect = out[:3]
    aux = out[3] if len(out) > 3 else None
    return sym, loc, expect, aux


@pytest.mark.parametrize("opname", sorted(SPECS))
def test_forward(opname):
    spec = SPECS[opname]
    sym, loc, expect, aux = _built(spec, opname)
    if sym is None:  # creation op: imperative call with attrs
        out = getattr(mx.nd, opname)(**loc)
        np.testing.assert_allclose(out.asnumpy(), expect[0],
                                   rtol=spec.rtol, atol=spec.atol)
        return
    test_utils.check_symbolic_forward(sym, loc, expect, aux_states=aux,
                                      check_eps=max(spec.rtol, 1e-4))


@pytest.mark.parametrize(
    "opname", sorted(n for n, s in SPECS.items() if s.grad))
def test_gradient(opname):
    spec = SPECS[opname]
    sym, loc, _, aux = _built(spec, opname)
    test_utils.check_numeric_gradient(sym, loc, aux_states=aux,
                                      check_eps=spec.grad_eps)


# ------------------------------------------------------------ sampler moments
_MOMENTS = {
    # op -> (attrs, mean, var)
    "random_uniform": ({"low": -1.0, "high": 3.0}, 1.0, 16.0 / 12),
    "random_normal": ({"loc": 2.0, "scale": 1.5}, 2.0, 2.25),
    "random_exponential": ({"lam": 2.0}, 0.5, 0.25),
    "random_gamma": ({"alpha": 3.0, "beta": 2.0}, 6.0, 12.0),
    "random_poisson": ({"lam": 4.0}, 4.0, 4.0),
    "random_negative_binomial": ({"k": 3, "p": 0.4}, 4.5, 11.25),
    # GNB(mu, alpha): mean mu, var mu + alpha mu^2
    "random_generalized_negative_binomial":
        ({"mu": 2.0, "alpha": 0.5}, 2.0, 4.0),
}


@pytest.mark.parametrize("opname", sorted(_MOMENTS))
def test_sampler_moments(opname):
    attrs, want_mean, want_var = _MOMENTS[opname]
    mx.random.seed(42)
    x = getattr(mx.nd, opname)(shape=(200000,), **attrs).asnumpy()
    assert abs(x.mean() - want_mean) < 0.05 * max(1.0, abs(want_mean)), (
        x.mean(), want_mean)
    assert abs(x.var() - want_var) < 0.08 * max(1.0, want_var), (
        x.var(), want_var)


_MULTI = {
    # sample_* take per-row parameter ARRAYS -> (n, shape) draws per row
    "sample_uniform": ({"low": [0.0, 2.0], "high": [1.0, 6.0]},
                       [0.5, 4.0], [1.0 / 12, 16.0 / 12]),
    "sample_normal": ({"mu": [0.0, 3.0], "sigma": [1.0, 2.0]},
                      [0.0, 3.0], [1.0, 4.0]),
    "sample_exponential": ({"lam": [1.0, 4.0]}, [1.0, 0.25], [1.0, 1.0 / 16]),
    "sample_gamma": ({"alpha": [2.0, 5.0], "beta": [1.0, 0.5]},
                     [2.0, 2.5], [2.0, 1.25]),
    "sample_poisson": ({"lam": [2.0, 6.0]}, [2.0, 6.0], [2.0, 6.0]),
    "sample_negative_binomial": ({"k": [2.0, 5.0], "p": [0.5, 0.4]},
                                 [2.0, 7.5], [4.0, 18.75]),
    "sample_generalized_negative_binomial":
        ({"mu": [2.0, 3.0], "alpha": [0.25, 0.5]},
         [2.0, 3.0], [3.0, 7.5]),
}


@pytest.mark.parametrize("opname", sorted(_MULTI))
def test_multisample_moments(opname):
    attrs, want_mean, want_var = _MULTI[opname]
    mx.random.seed(7)
    ins = {k: mx.nd.array(np.asarray(v, "f")) for k, v in attrs.items()}
    x = getattr(mx.nd, opname)(shape=(100000,), **ins).asnumpy()
    assert x.shape == (2, 100000)
    for row in range(2):
        m, v = x[row].mean(), x[row].var()
        assert abs(m - want_mean[row]) < 0.08 * max(1.0, abs(want_mean[row])), (
            opname, row, m, want_mean[row])
        assert abs(v - want_var[row]) < 0.12 * max(1.0, want_var[row]), (
            opname, row, v, want_var[row])


# ------------------------------------------------------------- coverage meta
# Every registered op must be swept above OR carry an explicit pointer to
# the dedicated suite that exercises it. Pointers are validated: the file
# must exist and mention the op.
EXEMPT = {
    "_graph_const": "tests/test_graph_rewrite.py",
    "Activation": "tests/test_operator.py",
    "BatchNorm": "tests/test_operator.py",
    "BilinearSampler": "tests/test_vision.py",
    "Convolution": "tests/test_operator.py",
    "Correlation": "tests/test_vision.py",
    "Crop": "tests/test_vision.py",
    "Custom": "tests/test_custom_op.py",
    "Deconvolution": "tests/test_operator.py",
    "Dropout": "tests/test_operator.py",
    "FullyConnected": "tests/test_operator.py",
    "GridGenerator": "tests/test_vision.py",
    "L2Normalization": "tests/test_operator.py",
    "LRN": "tests/test_operator.py",
    "LeakyReLU": "tests/test_operator.py",
    "LinearRegressionOutput": "tests/test_gradients.py",
    "LogisticRegressionOutput": "tests/test_gradients.py",
    "MAERegressionOutput": "tests/test_gradients.py",
    "MakeLoss": "tests/test_gradients.py",
    "Pooling": "tests/test_operator.py",
    "RNN": "tests/test_rnn.py",
    "ROIPooling": "tests/test_vision.py",
    "SVMOutput": "tests/test_gradients.py",
    "SequenceLast": "tests/test_operator.py",
    "SequenceMask": "tests/test_operator.py",
    "SequenceReverse": "tests/test_operator.py",
    "SoftmaxOutput": "tests/test_operator.py",
    "SpatialTransformer": "tests/test_vision.py",
    "WarpCTC": "tests/test_ctc.py",
    "_contrib_MultiBoxDetection": "tests/test_vision.py",
    "_contrib_MultiBoxPrior": "tests/test_vision.py",
    "_contrib_MultiBoxTarget": "tests/test_vision.py",
    "_contrib_MultiHeadAttention": "tests/test_attention.py",
    "_contrib_Proposal": "tests/test_vision.py",
    "_contrib_count_sketch": "tests/test_vision.py",
    "_contrib_fft": "tests/test_vision.py",
    "_contrib_ifft": "tests/test_vision.py",
}

_ROOT = __file__.rsplit("/", 2)[0]


def test_every_registered_op_is_covered():
    import os

    missing, stale = [], []
    for op in registry.list_ops():
        if op in SPECS or op in _MOMENTS or op in _MULTI:
            continue
        ref = EXEMPT.get(op)
        if ref is None:
            missing.append(op)
            continue
        path = os.path.join(_ROOT, ref)
        with open(path) as f:
            src = f.read()
        variants = {op, op.lstrip("_"), op.replace("_contrib_", "")}
        if not any(v in src for v in variants):
            stale.append((op, ref))
    assert not missing, (
        "registered ops with no sweep spec and no EXEMPT pointer: %s"
        % missing)
    assert not stale, "EXEMPT pointers that do not mention the op: %s" % stale


# gather-family gradients: differentiable w.r.t. the DATA argument only
# (indices have no tangent space) — check_numeric_gradient restricted via
# grad_nodes so finite differences never perturb the integer inputs.
_GATHER_GRADS = {
    "take": (lambda: (mx.sym.take(mx.sym.Variable("a"), mx.sym.Variable("i")),
                      {"a": _u(-1, 1, (5, 3), 13),
                       "i": np.array([0., 2., 4.], "f")}), ["a"]),
    "batch_take": (lambda: (mx.sym.batch_take(mx.sym.Variable("a"),
                                              mx.sym.Variable("i")),
                            {"a": _u(-1, 1, (3, 4), 13),
                             "i": np.array([0., 3., 1.], "f")}), ["a"]),
    "pick": (lambda: (mx.sym.pick(mx.sym.Variable("a"),
                                  mx.sym.Variable("i"), axis=1),
                      {"a": _u(-1, 1, (3, 4), 14),
                       "i": np.array([1., 0., 3.], "f")}), ["a"]),
    "Embedding": (lambda: (mx.sym.Embedding(mx.sym.Variable("i"),
                                            mx.sym.Variable("w"),
                                            input_dim=5, output_dim=3),
                           {"i": np.array([1., 4., 0.], "f"),
                            "w": _u(-1, 1, (5, 3), 15)}), ["w"]),
}


@pytest.mark.parametrize("opname", sorted(_GATHER_GRADS))
def test_gather_gradients(opname):
    build, grad_nodes = _GATHER_GRADS[opname]
    sym, loc = build()
    test_utils.check_numeric_gradient(sym, loc, grad_nodes=grad_nodes)
