"""The Pallas norm+residual kernel (ops/pallas_norm_residual.py): fwd+bwd
parity against the unfused LayerNorm composition, schedule-override
invariance, tiling gates, and the pattern-level engagement through the
``norm_residual`` fusion pattern (forced ``=pallas``, interpret mode)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.ops import pallas_norm_residual as pn

_EPS = 1e-5


def _ref(x, g, b):
    m = jnp.mean(x, axis=-1, keepdims=True)
    c = x - m
    v = jnp.mean(c * c, axis=-1, keepdims=True)
    return c * jax.lax.rsqrt(v + _EPS) * g + b


def _data(shape, dtype="float32", seed=0):
    rs = np.random.RandomState(seed)
    D = shape[-1]
    return (jnp.asarray(rs.randn(*shape), dtype),
            jnp.asarray(rs.uniform(0.5, 1.5, (D,)), dtype),
            jnp.asarray(rs.uniform(-0.2, 0.2, (D,)), dtype))


@pytest.mark.parametrize("dtype,tol", [("float32", 2e-6),
                                       ("bfloat16", 4e-2)])
def test_kernel_fwd_bwd_parity(dtype, tol):
    x, g, b = _data((4, 16, 128), dtype)
    y = pn.layer_norm_affine(x, g, b, _EPS)
    ref = _ref(x.astype(jnp.float32), g.astype(jnp.float32),
               b.astype(jnp.float32))
    assert np.max(np.abs(np.asarray(y, "float32") - np.asarray(ref))) <= tol

    out, vjp = jax.vjp(lambda x, g, b: pn.layer_norm_affine(x, g, b, _EPS),
                       x, g, b)
    _, rvjp = jax.vjp(_ref, x, g, b)
    do = jnp.ones_like(out)
    for name, a, r in zip(("dx", "dgamma", "dbeta"), vjp(do), rvjp(do)):
        err = np.max(np.abs(np.asarray(a, "float32")
                            - np.asarray(r, "float32")))
        denom = np.max(np.abs(np.asarray(r, "float32"))) + 1e-9
        assert err / denom <= max(tol, 1e-5), (name, err)


def test_schedule_override_is_bitwise_invariant():
    """A different row-block height changes the grid, never the numbers:
    rows are independent, so every valid schedule is bit-identical."""
    x, g, b = _data((4, 16, 128))
    cands = pn.block_candidates(x.shape, 4)
    assert len(cands) >= 2
    ref = np.asarray(pn.layer_norm_affine(x, g, b, _EPS,
                                          block_rows=cands[0]))
    for br in cands[1:]:
        got = np.asarray(pn.layer_norm_affine(x, g, b, _EPS,
                                              block_rows=br))
        assert np.array_equal(ref, got), br


def test_tiling_gates():
    assert pn.supported((4, 16, 128))
    assert not pn.supported((4, 16, 100))    # D not lane-aligned
    assert not pn.supported((7, 128))        # rows < 8
    assert pn.choose_block_rows((4, 16, 128)) == 64
    with pytest.raises(ValueError):
        pn.layer_norm_affine(*_data((4, 16, 100)), eps=_EPS)
    with pytest.raises(ValueError):
        # an override that does not divide the rows is refused, not demoted
        # (the caller asked for a specific measured schedule)
        pn.layer_norm_affine(*_data((4, 16, 128)), eps=_EPS, block_rows=48)


# ------------------------------------------------------------ pattern level
def _ln_net(dim):
    sym = mx.sym
    x = sym.Variable("data")
    mean = sym.mean(x, axis=-1, keepdims=True)
    cent = sym.broadcast_sub(x, mean, name="cent")
    var = sym.mean(sym.square(cent), axis=-1, keepdims=True)
    inv = sym.rsqrt(var + _EPS)
    normed = sym.broadcast_mul(cent, inv)
    gamma = sym.Variable("ln_gamma", shape=(dim,))
    beta = sym.Variable("ln_beta", shape=(dim,))
    out = sym.broadcast_add(sym.broadcast_mul(normed, gamma), beta,
                            name="ln")
    fc = sym.FullyConnected(out, num_hidden=4, flatten=True, name="head")
    return sym.SoftmaxOutput(fc, name="softmax")


def _run(net, shapes, env, monkeypatch):
    monkeypatch.setenv("MXNET_FUSED_PATTERNS", env)
    monkeypatch.delenv("MXNET_FUSION_TUNE_DIR", raising=False)
    rs = np.random.RandomState(3)
    ex = net.simple_bind(mx.cpu(), grad_req="write", **shapes)
    for name, arr in zip(net.list_arguments(), ex.arg_arrays):
        arr[:] = (rs.randint(0, 4, arr.shape) if "label" in name
                  else rs.uniform(-0.5, 0.5, arr.shape)).astype("f")
    outs = ex.forward(is_train=True)
    host = [o.asnumpy() for o in outs]
    ex.backward()
    grads = {n: (g.asnumpy() if g is not None else None)
             for n, g in ex.grad_dict.items()}
    return host, grads


def test_pattern_forced_pallas_parity(monkeypatch):
    """MXNET_FUSED_PATTERNS=norm_residual=pallas engages the kernel at the
    zoo LayerNorm composition (interpret mode on CPU) with fwd+bwd parity
    vs the unfused graph."""
    net = _ln_net(128)
    shapes = {"data": (4, 8, 128), "softmax_label": (4,)}
    ref = _run(net, shapes, "0", monkeypatch)
    got = _run(net, shapes, "norm_residual=pallas", monkeypatch)
    for a, b in zip(ref[0], got[0]):
        assert np.max(np.abs(a - b)) / (np.max(np.abs(a)) + 1e-9) <= 1e-5
    for k in ref[1]:
        if ref[1][k] is None:
            continue
        denom = np.max(np.abs(ref[1][k])) + 1e-9
        assert np.max(np.abs(ref[1][k] - got[1][k])) / denom <= 1e-5, k


def test_pattern_untileable_dim_falls_back_clean(monkeypatch):
    """A force-named pallas lowering at a shape the kernel cannot tile
    (D=32) falls back to the unfused graph — never a crash."""
    net = _ln_net(32)
    shapes = {"data": (4, 8, 32), "softmax_label": (4,)}
    ref = _run(net, shapes, "0", monkeypatch)
    got = _run(net, shapes, "norm_residual=pallas", monkeypatch)
    for a, b in zip(ref[0], got[0]):
        assert np.array_equal(a, b)
