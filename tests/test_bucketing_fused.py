"""BucketingModule over the fused SPMD step (VERDICT r3 item 4).

One compiled sharded step per bucket shape, all buckets training ONE set of
live weights (shared `_TrainState` cell). Oracle: closed-form parity — the
fused multi-device run must produce the same params as the legacy
single-device run over an identical mixed-bucket batch schedule (reference
analogue: executor-per-bucket sharing one memory pool,
src/executor/graph_executor.cc:348-351).
"""
import numpy as np
import pytest

import mxnet_tpu as mx

VOCAB = 40
EMBED = 8
HIDDEN = 16
BATCH = 16
BUCKETS = [4, 6]


def _sym_gen(seq_len):
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    embed = mx.sym.Embedding(data=data, input_dim=VOCAB, output_dim=EMBED,
                             name="embed")
    cell = mx.rnn.LSTMCell(num_hidden=HIDDEN, prefix="lstm_")
    cell.reset()
    outputs, _ = cell.unroll(seq_len, inputs=embed, merge_outputs=True,
                             begin_state=cell.begin_state(batch_size=BATCH))
    pred = mx.sym.Reshape(outputs, shape=(-1, HIDDEN))
    pred = mx.sym.FullyConnected(data=pred, num_hidden=VOCAB, name="pred")
    label = mx.sym.Reshape(label, shape=(-1,))
    pred = mx.sym.SoftmaxOutput(data=pred, label=label, name="softmax")
    return pred, ("data",), ("softmax_label",)


def _batches(n, seed=0):
    """Alternating-bucket token batches."""
    rs = np.random.RandomState(seed)
    out = []
    for i in range(n):
        L = BUCKETS[i % len(BUCKETS)]
        x = rs.randint(1, VOCAB, (BATCH, L)).astype("float32")
        y = np.concatenate([x[:, 1:], np.zeros((BATCH, 1), "float32")], axis=1)
        out.append(mx.io.DataBatch(
            data=[mx.nd.array(x)], label=[mx.nd.array(y)],
            bucket_key=L,
            provide_data=[mx.io.DataDesc("data", (BATCH, L))],
            provide_label=[mx.io.DataDesc("softmax_label", (BATCH, L))]))
    return out


def _train(ctxs, batches, fused=True, epochs=1):
    mx.random.seed(11)
    mod = mx.mod.BucketingModule(
        sym_gen=_sym_gen, default_bucket_key=max(BUCKETS), context=ctxs,
        fused_step=fused)
    b0 = [b for b in batches if b.bucket_key == max(BUCKETS)][0]
    mod.bind(data_shapes=b0.provide_data, label_shapes=b0.provide_label)
    mod.init_params(initializer=mx.init.Xavier(magnitude=2.0))
    mod.init_optimizer(kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.1),
                                         ("momentum", 0.9)))
    for _ in range(epochs):
        for b in batches:
            mod.forward_backward(b)
            mod.update()
    args, _ = mod.get_params()
    return mod, {k: v.asnumpy() for k, v in args.items()}


class TestBucketingFused:
    def test_fused_adapter_active_per_bucket(self):
        mod, _ = _train([mx.cpu(i) for i in range(4)], _batches(4))
        assert mod._curr_module._spmd is not None
        # every bound bucket has its own adapter, all sharing ONE state cell
        adapters = [m._spmd for m in mod._buckets.values()]
        assert all(a is not None for a in adapters)
        cells = {id(a.trainer._state) for a in adapters}
        assert len(cells) == 1, "buckets must share one training-state cell"

    def test_params_match_legacy_path(self):
        batches = _batches(6)
        _, fused = _train([mx.cpu(i) for i in range(8)], batches, fused=True)
        _, legacy = _train([mx.cpu(0)], batches, fused=False)
        assert set(fused) == set(legacy)
        for k in fused:
            np.testing.assert_allclose(
                fused[k], legacy[k], rtol=3e-4, atol=3e-5,
                err_msg="param %s diverged (fused bucketing vs legacy)" % k)

    def test_checkpoint_after_bucketed_steps(self, tmp_path):
        """get_params must see weights updated through a non-default bucket."""
        batches = _batches(3)
        mod, params = _train([mx.cpu(i) for i in range(4)], batches)
        before = {k: v.copy() for k, v in params.items()}
        # run one more step through the small bucket only, then re-read
        small = [b for b in batches if b.bucket_key == min(BUCKETS)][0]
        mod.forward_backward(small)
        mod.update()
        args, _ = mod.get_params()
        changed = any(
            np.abs(args[k].asnumpy() - before[k]).max() > 1e-7 for k in before)
        assert changed, "a step through a non-default bucket must move params"
