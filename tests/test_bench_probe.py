"""The bench.py backend probe must survive a flapping tunnel.

Round 4's scoreboard was forfeited because the probe returned False on the
first attempt timeout (old bench.py:53-55). The round-5 policy retries in
fresh subprocesses with backoff across a window; these tests simulate
fail -> fail -> succeed (a tunnel that heals) and a window that exhausts.
"""
import json
import os
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import bench  # noqa: E402


def _flaky_probe_code(counter_path, fail_times):
    """Probe snippet that fails its first ``fail_times`` invocations (each in
    a fresh subprocess, so state lives in a file) then succeeds."""
    return (
        "import os, sys\n"
        "p = %r\n"
        "n = int(open(p).read()) if os.path.exists(p) else 0\n"
        "open(p, 'w').write(str(n + 1))\n"
        "if n < %d:\n"
        "    sys.stderr.write('simulated tunnel flap %%d' %% n)\n"
        "    sys.exit(1)\n"
        "print('tpu')\n" % (counter_path, fail_times)
    )


def test_probe_recovers_from_flapping_tunnel(tmp_path, monkeypatch):
    counter = str(tmp_path / "attempts")
    monkeypatch.setenv("MXTPU_BENCH_PROBE_CODE",
                       _flaky_probe_code(counter, fail_times=2))
    monkeypatch.setenv("MXTPU_BENCH_PROBE_WINDOW", "600")
    monkeypatch.setenv("MXTPU_BENCH_PROBE_TIMEOUT", "30")
    # fail -> fail -> succeed: the probe must keep retrying and return True
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)  # skip backoff
    assert bench._probe_backend() is True
    assert int(open(counter).read()) == 3


def test_probe_gives_up_when_window_exhausted(tmp_path, monkeypatch):
    counter = str(tmp_path / "attempts")
    monkeypatch.setenv("MXTPU_BENCH_PROBE_CODE",
                       _flaky_probe_code(counter, fail_times=10 ** 6))
    monkeypatch.setenv("MXTPU_BENCH_PROBE_WINDOW", "0.1")
    monkeypatch.setenv("MXTPU_BENCH_PROBE_TIMEOUT", "30")
    assert bench._probe_backend() is False
    # window ~0 still grants at least the first attempt
    assert int(open(counter).read()) >= 1


def test_probe_retries_after_timeout(tmp_path, monkeypatch):
    """A timed-out attempt must NOT end the probe (the round-4 bug): the next
    attempt runs in a fresh subprocess and can succeed."""
    counter = str(tmp_path / "attempts")
    code = (
        "import os, time\n"
        "p = %r\n"
        "n = int(open(p).read()) if os.path.exists(p) else 0\n"
        "open(p, 'w').write(str(n + 1))\n"
        "if n < 1:\n"
        "    time.sleep(60)\n"  # simulated hang; killed by per-attempt timeout
        "print('tpu')\n" % counter
    )
    monkeypatch.setenv("MXTPU_BENCH_PROBE_CODE", code)
    monkeypatch.setenv("MXTPU_BENCH_PROBE_WINDOW", "600")
    # per-attempt timeout must cover interpreter startup (sitecustomize
    # imports jax) but be well under the simulated 60s hang
    monkeypatch.setenv("MXTPU_BENCH_PROBE_TIMEOUT", "20")
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench._probe_backend() is True
    assert int(open(counter).read()) >= 2


def test_onchip_artifact_pointer():
    """Degraded output must point at the committed on-chip measurement."""
    art = bench._onchip_artifact()
    assert art is not None
    assert art["file"].startswith("PERF_MEASURED_r")
    assert art["img_s"] and art["img_s"] > 1000  # a real TPU number, not CPU
    path = os.path.join(ROOT, art["file"])
    with open(path) as f:
        rec = json.load(f)
    assert any(abs(r["img_s"] - art["img_s"]) < 1e-6
               for r in rec["resnet50_train"])


def test_probe_attempt_cap(tmp_path, monkeypatch):
    """MXNET_BENCH_PROBE_ATTEMPTS caps the retries even with window left —
    the r05 degraded runs burned 4x180s; the cap is the budget now."""
    counter = str(tmp_path / "attempts")
    monkeypatch.setenv("MXTPU_BENCH_PROBE_CODE",
                       _flaky_probe_code(counter, fail_times=10 ** 6))
    monkeypatch.setenv("MXTPU_BENCH_PROBE_WINDOW", "600")
    monkeypatch.setenv("MXNET_BENCH_PROBE_ATTEMPTS", "2")
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench._probe_backend() is False
    assert int(open(counter).read()) == 2


def test_probe_conclusive_failure_stops_immediately(tmp_path, monkeypatch):
    """A clean backend-absence error (jax raised, no tunnel hang) must end
    the probe on attempt 1 — retrying cannot conjure a TPU."""
    counter = str(tmp_path / "attempts")
    code = (
        "import os, sys\n"
        "p = %r\n"
        "n = int(open(p).read()) if os.path.exists(p) else 0\n"
        "open(p, 'w').write(str(n + 1))\n"
        "sys.stderr.write('RuntimeError: Unable to initialize backend "
        "tpu')\n"
        "sys.exit(1)\n" % counter
    )
    monkeypatch.setenv("MXTPU_BENCH_PROBE_CODE", code)
    monkeypatch.setenv("MXTPU_BENCH_PROBE_WINDOW", "600")
    monkeypatch.setenv("MXNET_BENCH_PROBE_ATTEMPTS", "5")
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench._probe_backend() is False
    assert int(open(counter).read()) == 1


def test_probe_timeout_env_alias(tmp_path, monkeypatch):
    """MXNET_BENCH_PROBE_TIMEOUT_S takes precedence over the legacy
    MXTPU_BENCH_PROBE_TIMEOUT name."""
    counter = str(tmp_path / "attempts")
    code = (
        "import os, time\n"
        "p = %r\n"
        "n = int(open(p).read()) if os.path.exists(p) else 0\n"
        "open(p, 'w').write(str(n + 1))\n"
        "if n < 1:\n"
        "    time.sleep(60)\n"
        "print('tpu')\n" % counter
    )
    monkeypatch.setenv("MXTPU_BENCH_PROBE_CODE", code)
    monkeypatch.setenv("MXTPU_BENCH_PROBE_WINDOW", "600")
    monkeypatch.setenv("MXTPU_BENCH_PROBE_TIMEOUT", "500")  # legacy: slow
    # the new name wins; 6 s covers interpreter startup while keeping the
    # deliberate first-attempt hang cheap for the suite
    monkeypatch.setenv("MXNET_BENCH_PROBE_TIMEOUT_S", "6")
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench._probe_backend() is True
    assert int(open(counter).read()) >= 2
