"""Tools coverage: im2rec packing, parse_log, multi-process launcher + dist
kvstore closed-form sync (fast version of tests/nightly/dist_sync_kvstore.py,
which the reference runs via tools/launch.py --launcher local)."""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def test_im2rec_pack_and_read(tmp_path):
    from PIL import Image

    from mxnet_tpu import recordio

    root = tmp_path / "imgs"
    for cls in ("a", "b"):
        (root / cls).mkdir(parents=True)
        rs = np.random.RandomState(hash(cls) % 2**31)
        for i in range(3):
            Image.fromarray(rs.randint(0, 255, (24, 30, 3), dtype=np.uint8)).save(
                str(root / cls / ("%d.jpg" % i)))

    prefix = str(tmp_path / "pack")
    subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "im2rec.py"), prefix, str(root),
         "--resize", "16", "--center-crop", "--shuffle", "0"],
        check=True, cwd=ROOT)

    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    header, img = recordio.unpack_img(rec.read_idx(0))
    assert img.shape == (16, 16, 3)
    assert header.label == 0.0
    header5, _ = recordio.unpack_img(rec.read_idx(5))
    assert header5.label == 1.0


def test_parse_log(tmp_path):
    log = tmp_path / "train.log"
    log.write_text(textwrap.dedent("""\
        INFO:root:Epoch[0] Batch [4]\tSpeed: 1000.00 samples/sec\tTrain-accuracy=0.5
        INFO:root:Epoch[0] Train-accuracy=0.600000
        INFO:root:Epoch[0] Time cost=1.500
        INFO:root:Epoch[0] Validation-accuracy=0.700000
        INFO:root:Epoch[1] Batch [4]\tSpeed: 2000.00 samples/sec\tTrain-accuracy=0.8
        INFO:root:Epoch[1] Train-accuracy=0.900000
    """))
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "parse_log.py"), str(log),
         "--format", "csv"],
        check=True, capture_output=True, text=True, cwd=ROOT).stdout
    lines = out.strip().splitlines()
    assert lines[0].startswith("epoch,")
    assert "0.6" in lines[1] and "0.7" in lines[1]
    assert "0.9" in lines[2]


@pytest.mark.slow
def test_launcher_dist_sync():
    """2-worker closed-form kvstore sync through tools/launch.py."""
    script = textwrap.dedent("""
        import numpy as np
        import mxnet_tpu as mx
        kv = mx.kv.create("dist_tpu_sync")
        kv.init("k", mx.nd.zeros((3, 2)))
        kv.push("k", mx.nd.ones((3, 2)) * (kv.rank + 1))
        out = mx.nd.zeros((3, 2))
        kv.pull("k", out=out)
        expected = kv.num_workers * (kv.num_workers + 1) / 2
        np.testing.assert_allclose(out.asnumpy(), expected)
        print("worker", kv.rank, "ok")
    """)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "MXNET_TPU_COORDINATOR")}
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"), "-n", "2",
         "--launcher", "local", "--cpu-devices", "1", sys.executable, "-c", script],
        capture_output=True, text=True, timeout=300, cwd=ROOT, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_rcnn_example_end_to_end():
    """The compact Faster-RCNN example (RPN -> Proposal -> ProposalTarget
    CustomOp -> ROIPooling -> heads) trains one epoch with finite loss."""
    import subprocess
    import sys

    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "MXNET_DEFAULT_CONTEXT": "cpu"})
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "example", "rcnn", "train_rcnn.py"),
         "--num-epochs", "1"],
        capture_output=True, text=True, timeout=500, env=env)
    assert r.returncode == 0, (r.stderr or r.stdout)[-800:]
    assert "RCNN end-to-end training finished" in r.stdout


@pytest.mark.slow
def test_launcher_restarts_after_worker_death(tmp_path):
    """VERDICT r4 #6 done-criterion: worker 1 of 2 dies mid-run; the
    launcher detects it, tears down, relaunches with --auto-restart, and
    the job resumes from rank 0's checkpoint to the closed-form answer."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "MXNET_TPU_COORDINATOR")}
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"), "-n", "2",
         "--launcher", "local", "--cpu-devices", "1", "--auto-restart", "1",
         "--heartbeat-timeout", "120",
         sys.executable, os.path.join(ROOT, "tests", "nightly",
                                      "dist_crash_resume.py"), str(tmp_path)],
        capture_output=True, text=True, timeout=600, cwd=ROOT, env=env)
    assert proc.returncode == 0, (proc.stdout[-1000:], proc.stderr[-2000:])
    assert "simulating death" in proc.stdout
    assert "restart 1/1" in proc.stderr
    assert "resumed from epoch" in proc.stdout
    # both workers reached the closed-form final value
    assert proc.stdout.count("OK") == 2
    # the crash marker proves the death happened on attempt 1
    assert (tmp_path / "crashed-once").exists()


@pytest.mark.slow
def test_launcher_detects_hung_worker(tmp_path):
    """A worker that wedges (no exit, no heartbeat progress is NOT the
    trigger here — the heartbeat thread keeps beating; the trigger is a
    worker whose PROCESS stops beating, simulated with SIGSTOP-like sleep
    via a worker that never starts heartbeating) is detected by the
    heartbeat watchdog and the job is torn down instead of hanging."""
    script = textwrap.dedent("""
        import os, sys, time
        rank = int(os.environ["MXNET_TPU_WORKER_ID"])
        hb = os.environ["MXNET_TPU_HEARTBEAT_DIR"]
        if rank == 0:
            # beat by hand, then wait (worker 1 never beats: wedged pre-init)
            for _ in range(200):
                open(os.path.join(hb, "worker-0"), "a").close()
                os.utime(os.path.join(hb, "worker-0"))
                time.sleep(0.1)
        else:
            open(os.path.join(hb, "worker-1"), "a").close()
            os.utime(os.path.join(hb, "worker-1"),
                     (time.time() - 3600, time.time() - 3600))
            time.sleep(600)  # wedged: heartbeat never advances
    """)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "MXNET_TPU_COORDINATOR")}
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"), "-n", "2",
         "--launcher", "local", "--heartbeat-timeout", "3",
         "--heartbeat-interval", "0.5",
         sys.executable, "-c", script],
        capture_output=True, text=True, timeout=120, cwd=ROOT, env=env)
    assert proc.returncode == 124, (proc.returncode, proc.stderr[-800:])
    assert "heartbeat stale" in proc.stderr


def test_num_dead_nodes_counts_stale_heartbeats(tmp_path, monkeypatch):
    """kv.num_dead_nodes analog (reference kvstore.h:234-244): stale files
    count as dead; a MISSING file counts as alive during the startup grace
    (workers come up staggered — matching the launcher's _stale_worker
    treatment of not-yet-written files) and as dead after it."""
    import time

    from mxnet_tpu import dist

    hb = tmp_path / "hb"
    hb.mkdir()
    monkeypatch.setenv("MXNET_TPU_HEARTBEAT_DIR", str(hb))
    monkeypatch.setenv("MXNET_TPU_NUM_WORKERS", "3")
    now = time.time()
    (hb / "worker-0").touch()
    (hb / "worker-1").touch()
    os.utime(hb / "worker-1", (now - 400, now - 400))  # stale
    # worker-2 never heartbeated

    # job just started (anchor pinned now): the missing worker is in its
    # startup grace — only the stale one is dead
    monkeypatch.setattr(dist, "_start_time", now)
    assert dist.num_dead_nodes(timeout=60) == 1
    assert dist.num_dead_nodes(timeout=1000) == 0

    # grace expired: a still-missing heartbeat means the worker never came
    # up — dead (the pre-fix behavior, now only after the grace)
    monkeypatch.setattr(dist, "_start_time", now - 400)
    assert dist.num_dead_nodes(timeout=60) == 2
    # only the missing one (grace defaults to timeout, so pin it short)
    assert dist.num_dead_nodes(timeout=1000, startup_grace=60) == 1
    # a custom grace longer than the elapsed time keeps it alive
    assert dist.num_dead_nodes(timeout=60, startup_grace=1000) == 1


@pytest.mark.slow
def test_launcher_ignores_finished_workers_heartbeat(tmp_path):
    """A worker that exits 0 early must NOT be declared stale while the
    rest keep running past the heartbeat timeout (review regression)."""
    script = textwrap.dedent("""
        import os, sys, time
        rank = int(os.environ["MXNET_TPU_WORKER_ID"])
        hb = os.environ["MXNET_TPU_HEARTBEAT_DIR"]
        p = os.path.join(hb, "worker-%d" % rank)
        open(p, "a").close()
        if rank == 1:
            sys.exit(0)  # done early; its heartbeat file freezes
        for _ in range(80):  # keep running ~8s >> the 2s timeout
            open(p, "a").close(); os.utime(p)
            time.sleep(0.1)
    """)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "MXNET_TPU_COORDINATOR")}
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"), "-n", "2",
         "--launcher", "local", "--heartbeat-timeout", "2",
         "--heartbeat-interval", "0.5",
         sys.executable, "-c", script],
        capture_output=True, text=True, timeout=120, cwd=ROOT, env=env)
    assert proc.returncode == 0, (proc.returncode, proc.stderr[-800:])
    assert "heartbeat stale" not in proc.stderr
