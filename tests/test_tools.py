"""Tools coverage: im2rec packing, parse_log, multi-process launcher + dist
kvstore closed-form sync (fast version of tests/nightly/dist_sync_kvstore.py,
which the reference runs via tools/launch.py --launcher local)."""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def test_im2rec_pack_and_read(tmp_path):
    from PIL import Image

    from mxnet_tpu import recordio

    root = tmp_path / "imgs"
    for cls in ("a", "b"):
        (root / cls).mkdir(parents=True)
        rs = np.random.RandomState(hash(cls) % 2**31)
        for i in range(3):
            Image.fromarray(rs.randint(0, 255, (24, 30, 3), dtype=np.uint8)).save(
                str(root / cls / ("%d.jpg" % i)))

    prefix = str(tmp_path / "pack")
    subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "im2rec.py"), prefix, str(root),
         "--resize", "16", "--center-crop", "--shuffle", "0"],
        check=True, cwd=ROOT)

    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    header, img = recordio.unpack_img(rec.read_idx(0))
    assert img.shape == (16, 16, 3)
    assert header.label == 0.0
    header5, _ = recordio.unpack_img(rec.read_idx(5))
    assert header5.label == 1.0


def test_parse_log(tmp_path):
    log = tmp_path / "train.log"
    log.write_text(textwrap.dedent("""\
        INFO:root:Epoch[0] Batch [4]\tSpeed: 1000.00 samples/sec\tTrain-accuracy=0.5
        INFO:root:Epoch[0] Train-accuracy=0.600000
        INFO:root:Epoch[0] Time cost=1.500
        INFO:root:Epoch[0] Validation-accuracy=0.700000
        INFO:root:Epoch[1] Batch [4]\tSpeed: 2000.00 samples/sec\tTrain-accuracy=0.8
        INFO:root:Epoch[1] Train-accuracy=0.900000
    """))
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "parse_log.py"), str(log),
         "--format", "csv"],
        check=True, capture_output=True, text=True, cwd=ROOT).stdout
    lines = out.strip().splitlines()
    assert lines[0].startswith("epoch,")
    assert "0.6" in lines[1] and "0.7" in lines[1]
    assert "0.9" in lines[2]


@pytest.mark.slow
def test_launcher_dist_sync():
    """2-worker closed-form kvstore sync through tools/launch.py."""
    script = textwrap.dedent("""
        import numpy as np
        import mxnet_tpu as mx
        kv = mx.kv.create("dist_tpu_sync")
        kv.init("k", mx.nd.zeros((3, 2)))
        kv.push("k", mx.nd.ones((3, 2)) * (kv.rank + 1))
        out = mx.nd.zeros((3, 2))
        kv.pull("k", out=out)
        expected = kv.num_workers * (kv.num_workers + 1) / 2
        np.testing.assert_allclose(out.asnumpy(), expected)
        print("worker", kv.rank, "ok")
    """)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "MXNET_TPU_COORDINATOR")}
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"), "-n", "2",
         "--launcher", "local", "--cpu-devices", "1", sys.executable, "-c", script],
        capture_output=True, text=True, timeout=300, cwd=ROOT, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_rcnn_example_end_to_end():
    """The compact Faster-RCNN example (RPN -> Proposal -> ProposalTarget
    CustomOp -> ROIPooling -> heads) trains one epoch with finite loss."""
    import subprocess
    import sys

    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "MXNET_DEFAULT_CONTEXT": "cpu"})
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "example", "rcnn", "train_rcnn.py"),
         "--num-epochs", "1"],
        capture_output=True, text=True, timeout=500, env=env)
    assert r.returncode == 0, (r.stderr or r.stdout)[-800:]
    assert "RCNN end-to-end training finished" in r.stdout
