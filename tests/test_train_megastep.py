"""Training megasteps (SPMDTrainer.step_many + the SPMD adapter's
MXNET_TRAIN_MEGASTEP_N buffering, docs/PERF.md §Megasteps): N fused
steps per dispatch through one lax.scan. Gates: bitwise weight parity
with N separate step() calls (NaN-guard skipped step included),
dispatches-per-batch reduced N×, and Module.fit metric parity through
the buffered update_metric/flush seams."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import parallel, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.module.spmd_adapter import train_megastep_n


@pytest.fixture
def tm():
    telemetry.reset()
    telemetry.clear_events()
    saved = telemetry.current_override()
    yield telemetry
    telemetry.set_mode(saved)
    telemetry.reset()
    telemetry.clear_events()


def _mlp(hidden=32, classes=4):
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, name="fc1", num_hidden=hidden)
    h = mx.sym.Activation(h, name="relu1", act_type="relu")
    h = mx.sym.FullyConnected(h, name="fc2", num_hidden=classes)
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _host_batches(n, batch=16, feat=8, classes=4, seed=0, nan_step=None):
    rs = np.random.RandomState(seed)
    out = []
    for i in range(n):
        x = rs.rand(batch, feat).astype("float32")
        if i == nan_step:
            x[0, 0] = np.nan
        y = rs.randint(0, classes, (batch,)).astype("float32")
        out.append(({"data": x}, {"softmax_label": y}))
    return out


def _trainer(seed=5):
    import jax

    mesh = parallel.make_mesh((2,), ("data",), jax.devices()[:2])
    tr = parallel.SPMDTrainer(
        _mlp(), mesh, optimizer="sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    tr.init_params({"data": (16, 8)}, {"softmax_label": (16,)}, seed=seed)
    return tr


LRS = [0.1, 0.09, 0.08, 0.07]


# ------------------------------------------------------------------ knobs
def test_train_megastep_n_env(monkeypatch):
    monkeypatch.delenv("MXNET_TRAIN_MEGASTEP_N", raising=False)
    assert train_megastep_n() == 1
    monkeypatch.setenv("MXNET_TRAIN_MEGASTEP_N", "4")
    assert train_megastep_n() == 4
    monkeypatch.setenv("MXNET_TRAIN_MEGASTEP_N", "junk")
    assert train_megastep_n() == 1
    monkeypatch.setenv("MXNET_TRAIN_MEGASTEP_N", "0")
    assert train_megastep_n() == 1


# ----------------------------------------------------------------- parity
def test_step_many_bitwise_parity():
    """The acceptance gate: one N=4 megastep must produce bitwise the
    weights of 4 individual fused steps with the same per-step lrs."""
    batches = _host_batches(4)
    tr1 = _trainer()
    for (d, l), lr in zip(batches, LRS):
        tr1.step(d, l, lr=lr)
    tr2 = _trainer()
    tr2.step_many([d for d, _ in batches], [l for _, l in batches],
                  lrs=LRS)
    p1, _ = tr1.get_params()
    p2, _ = tr2.get_params()
    assert set(p1) == set(p2)
    for k in p1:
        assert np.array_equal(p1[k], p2[k]), \
            "param %s not bitwise identical" % k


def test_step_many_nan_guard_skip_parity(monkeypatch):
    """A NaN-poisoned batch inside the scan must where-select the old
    state exactly like the unfused skip: same skip count, bitwise
    weights."""
    monkeypatch.setenv("MXNET_ANOMALY_GUARD", "skip")
    batches = _host_batches(4, nan_step=2)
    tr1 = _trainer()
    for (d, l), lr in zip(batches, LRS):
        tr1.step(d, l, lr=lr)
    tr2 = _trainer()
    tr2.step_many([d for d, _ in batches], [l for _, l in batches],
                  lrs=LRS)
    assert tr1.skipped_steps == 1
    assert tr2.skipped_steps == 1
    p1, _ = tr1.get_params()
    p2, _ = tr2.get_params()
    for k in p1:
        assert np.array_equal(p1[k], p2[k]), \
            "param %s diverged across the skipped step" % k


def test_step_many_outputs_match_per_step():
    batches = _host_batches(2)
    tr1 = _trainer()
    want = [tr1.step(d, l, lr=0.1) for d, l in batches]
    tr2 = _trainer()
    got = tr2.step_many([d for d, _ in batches], [l for _, l in batches],
                        lrs=[0.1, 0.1])
    for w, g in zip(want, got):
        for a, b in zip(w, g):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_step_many_dispatch_counters(tm):
    """8 batches at N=4: trainer.step counts 8 both ways, but dispatches
    drop 8 -> 2 (the 4x dispatches-per-batch reduction)."""
    tm.set_mode("counters")
    batches = _host_batches(8)
    tr1 = _trainer()
    c0 = tm.counters()
    for d, l in batches:
        tr1.step(d, l, lr=0.1)
    c1 = tm.counters()
    assert c1.get("trainer.step", 0) - c0.get("trainer.step", 0) == 8
    assert c1.get("trainer.dispatches", 0) - c0.get("trainer.dispatches", 0) == 8

    tr2 = _trainer()
    c2 = tm.counters()
    for i in range(0, 8, 4):
        tr2.step_many([d for d, _ in batches[i:i + 4]],
                      [l for _, l in batches[i:i + 4]],
                      lrs=[0.1] * 4)
    c3 = tm.counters()
    assert c3.get("trainer.step", 0) - c2.get("trainer.step", 0) == 8
    assert c3.get("trainer.dispatches", 0) - c2.get("trainer.dispatches", 0) == 2
    assert c3.get("trainer.megastep", 0) - c2.get("trainer.megastep", 0) == 2
    assert tm.gauge("train.steps_per_dispatch").value == 4


def test_step_many_single_degenerates_to_step():
    tr = _trainer()
    (d, l), = _host_batches(1)
    outs = tr.step_many([d], [l], lrs=[0.1])
    assert len(outs) == 1
    assert not tr._megastep_fns  # no scan program built for N=1


def test_step_many_empty_and_unbuilt():
    import jax

    tr = _trainer()
    assert tr.step_many([]) == []
    mesh = parallel.make_mesh((2,), ("data",), jax.devices()[:2])
    tr2 = parallel.SPMDTrainer(_mlp(), mesh)
    with pytest.raises(MXNetError):
        tr2.step_many([b[0] for b in _host_batches(2)],
                      [b[1] for b in _host_batches(2)])


# ------------------------------------------------------------ module seam
def _fit_mod(batches, megastep_n, monkeypatch, nb_metric=True):
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    if megastep_n is None:
        monkeypatch.delenv("MXNET_TRAIN_MEGASTEP_N", raising=False)
    else:
        monkeypatch.setenv("MXNET_TRAIN_MEGASTEP_N", str(megastep_n))
    mx.random.seed(7)
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(4)])
    b0 = batches[0]
    mod.bind(data_shapes=[("data", b0.data[0].shape)],
             label_shapes=[("softmax_label", b0.label[0].shape)])
    mod.init_params(initializer=mx.init.Xavier(magnitude=2.0))
    mod.init_optimizer(kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.1),
                                         ("momentum", 0.9)))
    metric = mx.metric.Accuracy()
    for b in batches:
        mod.forward_backward(b)
        mod.update()
        mod.update_metric(metric, b.label)
    mod.flush_pending_steps(metric)
    args, _ = mod.get_params()
    return ({k: v.asnumpy().copy() for k, v in args.items()},
            metric.get(), mod)


def _nd_batches(n, seed=0):
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        x = rs.rand(16, 8).astype("float32")
        y = rs.randint(0, 4, (16,)).astype("float32")
        out.append(mx.io.DataBatch(data=[mx.nd.array(x)],
                                   label=[mx.nd.array(y)]))
    return out


def test_module_megastep_bitwise_and_metric_parity(monkeypatch):
    """Module-level N=4 buffering (6 batches: one full flush + a partial
    tail flush) must match N=1 bitwise in weights AND in the metric —
    the buffered (labels, outputs) pairs drain through update_metric."""
    batches = _nd_batches(6)
    p1, m1, _ = _fit_mod(batches, None, monkeypatch)
    p4, m4, mod = _fit_mod(batches, 4, monkeypatch)
    assert mod._spmd is not None and mod._spmd._megastep_n == 4
    for k in p1:
        assert np.array_equal(p1[k], p4[k]), "param %s diverged" % k
    assert m1 == m4


def test_module_megastep_fit_converges(monkeypatch):
    """End-to-end fit() with the megastep on: epoch-tail flush +
    score() both work, and the model still converges."""
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    monkeypatch.setenv("MXNET_TRAIN_MEGASTEP_N", "4")
    rs = np.random.RandomState(0)
    n, feat = 256, 16
    w = rs.randn(feat, 2).astype("float32")
    x = rs.randn(n, feat).astype("float32")
    y = np.argmax(x @ w, axis=1).astype("float32")
    it = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=False,
                           label_name="softmax_label")
    mod = mx.mod.Module(_mlp(hidden=32, classes=2),
                        context=[mx.cpu(i) for i in range(8)])
    mod.fit(it, num_epoch=12, optimizer="sgd",
            optimizer_params=(("learning_rate", 0.5), ("momentum", 0.9)),
            initializer=mx.init.Xavier(magnitude=2.0),
            eval_metric="acc", kvstore="local")
    assert mod._spmd is not None and mod._spmd._megastep_n == 4
    it.reset()
    score = mod.score(it, mx.metric.Accuracy())
    assert dict(score)["accuracy"] > 0.95


def test_module_megastep_checkpoint_flushes(monkeypatch, tmp_path):
    """get_params/export after a partial buffer must flush first — the
    checkpointed weights include the buffered batches."""
    batches = _nd_batches(2)
    p1, _, _ = _fit_mod(batches, None, monkeypatch)
    # N=4 with only 2 batches: nothing flushed until get_params
    p4, _, mod = _fit_mod(batches, 4, monkeypatch)
    assert mod._spmd._buf == []  # export drained the buffer
    for k in p1:
        assert np.array_equal(p1[k], p4[k]), "param %s diverged" % k
