"""graphlint test suite (analysis/ subsystem).

Every diagnostic code ships with BOTH a trigger (a deliberately-broken
graph or schedule that fires it) and a clean case (a healthy graph or
schedule that does not) — parametrized from one table so the completeness
meta-test can prove no code is untested. Plus: bind-time integration
(MXNET_GRAPHLINT=warn|error), the engine wait_for_var satellite fix, the
infer_meta registry, the CLI, and the models/resnet.py lint-clean
regression.
"""
import json
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import analysis
from mxnet_tpu import engine as eng
from mxnet_tpu.analysis import CODES, RecordingEngine, analyze_trace


def _codes(sym, **kw):
    return set(analysis.lint(sym, **kw).codes())


# --------------------------------------------------------------------------
# graph-code table: code -> (broken_builder, clean_builder), each returning
# (symbol, lint_kwargs)
# --------------------------------------------------------------------------
def _gl001_broken():
    a = mx.sym.Variable("a", shape=(2, 3))
    b = mx.sym.Variable("b", shape=(4, 5))
    return mx.sym.dot(a, b, name="baddot"), {}


def _gl001_clean():
    a = mx.sym.Variable("a", shape=(2, 3))
    b = mx.sym.Variable("b", shape=(3, 5))
    return mx.sym.dot(a, b, name="okdot"), {}


def _gl002_broken():
    d = mx.sym.Variable("data")
    e = mx.sym.Variable("extra")
    s = mx.sym.FullyConnected(data=d, num_hidden=4, name="fcA") \
        + mx.sym.FullyConnected(data=e, num_hidden=4, name="fcB")
    return s, {"shapes": {"data": (2, 8)}}  # 'extra' stays unknown


def _gl002_clean():
    d = mx.sym.Variable("data")
    s = mx.sym.FullyConnected(data=d, num_hidden=4, name="fcC")
    return s, {"shapes": {"data": (2, 8)}}


def _gl003_broken():
    d = mx.sym.Variable("data")
    w = mx.sym.Variable("fc_weight", shape=(7, 99))
    return (mx.sym.FullyConnected(data=d, weight=w, num_hidden=7, name="fc"),
            {"shapes": {"data": (2, 10)}})


def _gl003_clean():
    d = mx.sym.Variable("data")
    w = mx.sym.Variable("fc_weight", shape=(7, 10))
    return (mx.sym.FullyConnected(data=d, weight=w, num_hidden=7, name="fc"),
            {"shapes": {"data": (2, 10)}})


def _gl004_broken():
    x = mx.sym.Variable("x", dtype="float16")
    y = mx.sym.Variable("y", dtype="float32")
    return x + y, {"shapes": {"x": (2,), "y": (2,)}}


def _gl004_clean():
    x = mx.sym.Variable("x", dtype="float16")
    y = mx.sym.Variable("y", dtype="float16")
    return x + y, {"shapes": {"x": (2,), "y": (2,)}}


def _gl005_broken():
    return mx.sym.Variable("dup") + mx.sym.Variable("dup"), \
        {"shapes": {"dup": (2,)}}


def _gl005_clean():
    return mx.sym.Variable("p") + mx.sym.Variable("q"), \
        {"shapes": {"p": (2,), "q": (2,)}}


def _gl006_broken():
    d = mx.sym.Variable("data")
    flat = mx.sym.Flatten(data=d)
    return (mx.sym.Convolution(data=flat, num_filter=8, kernel=(3, 3),
                               name="badconv"),
            {"shapes": {"data": (2, 3, 8, 8)}})


def _gl006_clean():
    d = mx.sym.Variable("data")
    return (mx.sym.Convolution(data=d, num_filter=8, kernel=(3, 3),
                               pad=(1, 1), name="okconv"),
            {"shapes": {"data": (2, 3, 8, 8)}})


def _gl201_broken():
    return mx.sym.Variable("x") * 0.125, {}


def _gl201_clean():
    return mx.sym.Variable("x") + mx.sym.Variable("y"), {}


def _gl202_broken():
    h = mx.sym.Variable("h", dtype="float16")
    x = mx.sym.Variable("x")  # weak: defaults to f32 at trace time
    return x + h, {}


def _gl202_clean():
    h = mx.sym.Variable("h", dtype="float16")
    x = mx.sym.Variable("x", dtype="float16")
    return x + h, {}


def _gl203_broken():
    # no shape hints at all: data inputs are shape-polymorphic
    return mx.sym.FullyConnected(data=mx.sym.Variable("data"),
                                 num_hidden=4, name="fcP"), {}


def _gl203_clean():
    return mx.sym.FullyConnected(data=mx.sym.Variable("data"),
                                 num_hidden=4, name="fcP"), \
        {"shapes": {"data": (2, 8)}}


def _fusable_chain(kernel=(3, 3), pad=(1, 1), no_bias=True, name="c"):
    d = mx.sym.Variable("data")
    bn = mx.sym.BatchNorm(data=d, fix_gamma=False, name=name + "_bn")
    act = mx.sym.Activation(data=bn, act_type="relu", name=name + "_relu")
    return mx.sym.Convolution(data=act, num_filter=8, kernel=kernel, pad=pad,
                              no_bias=no_bias, name=name + "_conv")


def _gl301_broken():
    # bias present -> the planner's first predicate fails
    return _fusable_chain(no_bias=False, name="biased"), {}


def _gl301_clean():
    return _fusable_chain(name="fusable"), {}


def _gl302_broken():
    # BN feeding a pooling layer: eligible BN, but nothing to fold into
    d = mx.sym.Variable("data")
    bn = mx.sym.BatchNorm(data=d, fix_gamma=False, name="pool_bn")
    return mx.sym.Pooling(data=bn, kernel=(2, 2), pool_type="max",
                          name="pool"), {}


def _gl302_clean():
    return _fusable_chain(name="folded"), {}


# --- GL4xx: sharding-plan lint (mesh/rules kwargs ride through lint()) -----
def _gl401_broken():
    # weight (999, 783): both dims odd, prod >= min_shard_elems -> the rule
    # silently falls back to full replication
    d = mx.sym.Variable("data")
    return (mx.sym.FullyConnected(data=d, num_hidden=999, name="oddfc"),
            {"shapes": {"data": (4, 783)}, "mesh": "dp=2,model=2"})


def _gl401_clean():
    d = mx.sym.Variable("data")
    return (mx.sym.FullyConnected(data=d, num_hidden=1000, name="evenfc"),
            {"shapes": {"data": (4, 784)}, "mesh": "dp=2,model=2"})


def _gl402_broken():
    # fc1's weight is sharded (out dim model-split), so its activation is
    # model-sharded on dim 1; fc2's weight is too small to shard, so the
    # contraction is sharded on the data side only -> implicit all-gather
    d = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data=d, num_hidden=256, name="fcbig")
    return (mx.sym.FullyConnected(data=h, num_hidden=8, name="fcsmall"),
            {"shapes": {"data": (8, 512)}, "mesh": "dp=2,model=2"})


def _gl402_clean():
    d = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data=d, num_hidden=16, name="fc_a")
    return (mx.sym.FullyConnected(data=h, num_hidden=8, name="fc_b"),
            {"shapes": {"data": (8, 64)}, "mesh": "dp=2,model=2"})


def _gl403_broken():
    # sum collapses the data-sharded batch dim MID-graph (the scalar then
    # feeds another op) -> everything downstream runs un-sharded
    d = mx.sym.Variable("data")
    s = mx.sym.sum(d, name="collapse")
    return s * 2.0, {"shapes": {"data": (8, 16)}, "mesh": "dp=2"}


def _gl403_clean():
    # the same reduction as the graph HEAD is a loss-style scalar: fine
    d = mx.sym.Variable("data")
    return (mx.sym.sum(d, name="lossval"),
            {"shapes": {"data": (8, 16)}, "mesh": "dp=2"})


def _gl404_broken():
    d = mx.sym.Variable("data")
    return (mx.sym.FullyConnected(data=d, num_hidden=8, name="fc"),
            {"shapes": {"data": (3, 16)}, "mesh": "dp=2"})


def _gl404_clean():
    d = mx.sym.Variable("data")
    return (mx.sym.FullyConnected(data=d, num_hidden=8, name="fc"),
            {"shapes": {"data": (4, 16)}, "mesh": "dp=2"})


def _gl405_rules(param_rule):
    from mxnet_tpu.parallel import ShardingRules, parse_mesh_spec

    mesh = parse_mesh_spec("dp=2,model=2")
    return mesh, ShardingRules.infer_axes(mesh, param_rule=param_rule)


def _gl405_broken():
    from jax.sharding import PartitionSpec as P

    mesh, rules = _gl405_rules(lambda name, shape: P())  # replicate all
    d = mx.sym.Variable("data")
    return (mx.sym.FullyConnected(data=d, num_hidden=256, name="fc"),
            {"shapes": {"data": (8, 512)}, "mesh": mesh, "rules": rules})


def _gl405_clean():
    mesh, rules = _gl405_rules(None)  # the default rule shards it
    d = mx.sym.Variable("data")
    return (mx.sym.FullyConnected(data=d, num_hidden=256, name="fc"),
            {"shapes": {"data": (8, 512)}, "mesh": mesh, "rules": rules})


def _gl303_broken():
    # NEAR miss: the FullyConnected has a fusable relu consumer but also a
    # second consumer, so the matmul_bias_act pattern cannot root
    d = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=d, num_hidden=8, name="fc_shared")
    relu = mx.sym.Activation(data=fc, act_type="relu", name="relu")
    return relu + fc, {"shapes": {"data": (4, 16)}}


def _gl303_clean():
    # sole fusable consumer: the pattern roots, nothing to report
    d = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=d, num_hidden=8, name="fc")
    return (mx.sym.Activation(data=fc, act_type="relu", name="relu"),
            {"shapes": {"data": (4, 16)}})


# --- GL5xx: memory planner (no mesh needed: plans replicated) --------------
def _gl501_broken():
    d = mx.sym.Variable("data")
    return (mx.sym.FullyConnected(data=d, num_hidden=8, name="fc"),
            {"shapes": {"data": (8, 16)}, "budget_gb": 1e-6})


def _gl501_clean():
    d = mx.sym.Variable("data")
    return (mx.sym.FullyConnected(data=d, num_hidden=8, name="fc"),
            {"shapes": {"data": (8, 16)}, "budget_gb": 1000.0})


def _gl502_broken():
    # one 1-GiB activation (4096 x 65536 f32) IS the stash: it dominates
    # the fwd->bwd watermark and the fix is a recompute policy
    d = mx.sym.Variable("data")
    return (mx.sym.FullyConnected(data=d, num_hidden=65536, name="bigfc"),
            {"shapes": {"data": (4096, 64)}})


def _gl502_clean():
    d = mx.sym.Variable("data")
    return (mx.sym.FullyConnected(data=d, num_hidden=1024, name="smallfc"),
            {"shapes": {"data": (64, 64)}})


GRAPH_CODE_CASES = {
    "GL001": (_gl001_broken, _gl001_clean),
    "GL002": (_gl002_broken, _gl002_clean),
    "GL003": (_gl003_broken, _gl003_clean),
    "GL004": (_gl004_broken, _gl004_clean),
    "GL005": (_gl005_broken, _gl005_clean),
    "GL006": (_gl006_broken, _gl006_clean),
    "GL201": (_gl201_broken, _gl201_clean),
    "GL202": (_gl202_broken, _gl202_clean),
    "GL203": (_gl203_broken, _gl203_clean),
    "GL301": (_gl301_broken, _gl301_clean),
    "GL302": (_gl302_broken, _gl302_clean),
    "GL303": (_gl303_broken, _gl303_clean),
    "GL401": (_gl401_broken, _gl401_clean),
    "GL402": (_gl402_broken, _gl402_clean),
    "GL403": (_gl403_broken, _gl403_clean),
    "GL404": (_gl404_broken, _gl404_clean),
    "GL405": (_gl405_broken, _gl405_clean),
    "GL501": (_gl501_broken, _gl501_clean),
    "GL502": (_gl502_broken, _gl502_clean),
}


@pytest.mark.parametrize("code", sorted(GRAPH_CODE_CASES))
def test_graph_code_triggers_on_broken_graph(code):
    sym, kw = GRAPH_CODE_CASES[code][0]()
    assert code in _codes(sym, **kw)


@pytest.mark.parametrize("code", sorted(GRAPH_CODE_CASES))
def test_graph_code_silent_on_clean_graph(code):
    sym, kw = GRAPH_CODE_CASES[code][1]()
    assert code not in _codes(sym, **kw)


# --------------------------------------------------------------------------
# engine-schedule codes: trace builders over a RecordingEngine
# --------------------------------------------------------------------------
def _trace_gl101_broken(e):
    v = e.new_variable()
    e.push(lambda: None, const_vars=[v], mutable_vars=[v])


def _trace_gl102_broken(e):
    v = e.new_variable()
    e.push(lambda: None, const_vars=[v])
    e.wait_for_var(v)


def _trace_gl103_broken(e):
    v = e.new_variable()
    e.push(lambda: None, mutable_vars=[v, v])


def _trace_gl104_broken(e):
    v = e.new_variable()
    e.push(lambda: None, const_vars=[v])   # read before any write
    e.push(lambda: None, mutable_vars=[v])


def _trace_clean(e):
    v, w = e.new_variable(), e.new_variable()
    e.push(lambda: None, mutable_vars=[v])
    e.push(lambda: None, const_vars=[v], mutable_vars=[w])
    e.push(lambda: None, const_vars=[v, w])
    e.wait_for_var(w)


ENGINE_CODE_CASES = {
    "GL101": _trace_gl101_broken,
    "GL102": _trace_gl102_broken,
    "GL103": _trace_gl103_broken,
    "GL104": _trace_gl104_broken,
}


@pytest.mark.parametrize("code", sorted(ENGINE_CODE_CASES))
def test_engine_code_triggers_on_broken_schedule(code):
    e = RecordingEngine(eng.NaiveEngine())
    ENGINE_CODE_CASES[code](e)
    assert code in analyze_trace(e.trace).codes()


@pytest.mark.parametrize("code", sorted(ENGINE_CODE_CASES) + ["GL105"])
def test_engine_code_silent_on_clean_schedule(code):
    e = RecordingEngine(eng._PythonThreadedEngine(2), assert_discipline=True)
    _trace_clean(e)
    e.wait_for_all()
    assert code not in analyze_trace(e.trace).codes()


class _NoDisciplineEngine(eng.Engine):
    """Deliberately broken: runs every op on its own thread, ignoring the
    declared var sets entirely — what the shim exists to catch."""

    def __init__(self):
        self._n = 0
        self._threads = []

    def new_variable(self):
        self._n += 1
        return self._n

    def push(self, fn, const_vars=(), mutable_vars=()):
        def quiet():
            try:
                fn()
            except Exception:
                pass  # the shim raises; the trace records it

        t = threading.Thread(target=quiet)
        t.start()
        self._threads.append(t)

    def wait_for_var(self, var):
        self.wait_for_all()

    def wait_for_all(self):
        for t in self._threads:
            t.join()


def test_gl105_runtime_shim_catches_broken_engine():
    e = RecordingEngine(_NoDisciplineEngine(), assert_discipline=True)
    v = e.new_variable()
    gate = threading.Event()
    started = threading.Event()

    def first():
        started.set()
        gate.wait(5)

    e.push(first, mutable_vars=[v])
    assert started.wait(5)
    e.push(lambda: None, mutable_vars=[v])  # overlapping writer
    time.sleep(0.05)
    gate.set()
    e.wait_for_all()
    report = analyze_trace(e.trace)
    assert "GL105" in report.codes()
    assert any("write-write" in d.message for d in report.by_code("GL105"))


def test_shipped_python_engine_passes_discipline_shim():
    """The pure-Python fallback engine, under a real concurrent workload,
    never violates the var discipline the shim asserts."""
    e = RecordingEngine(eng._PythonThreadedEngine(4), assert_discipline=True)
    vars_ = [e.new_variable() for _ in range(4)]
    for i in range(80):
        e.push(lambda: time.sleep(0.0005), mutable_vars=[vars_[i % 4]])
        e.push(lambda: None, const_vars=[vars_[i % 4]],
               mutable_vars=[vars_[(i + 1) % 4]])
    e.wait_for_all()
    assert not e.trace.violations
    assert "GL105" not in analyze_trace(e.trace).codes()


# --------------------------------------------------------------------------
# GL6xx: graph-rewrite verifier codes (analysis/rewrite.py). These come from
# verify_rewrite over a RewriteResult, not from lint() — each case returns
# the code set the verifier produced. Deliberately-buggy custom passes
# exercise the contract a correct pass must uphold.
# --------------------------------------------------------------------------
def _rw_codes(sym, passes=None, grad_req=None, max_rounds=None, shapes=None,
              types=None):
    res = analysis.rewrite(sym, shapes=shapes, types=types, passes=passes,
                           max_rounds=max_rounds)
    return set(analysis.verify_rewrite(res, grad_req=grad_req).codes())


class _OncePass(analysis.RewritePass):
    """Base for the buggy test passes: fires exactly once."""

    def __init__(self):
        self._done = False

    def run(self, g):
        if self._done:
            return 0
        self._done = True
        return self._fire(g)


class _ShapeBreakingPass(_OncePass):
    """Replaces the output with its whole-array sum — shape drift."""

    name = "badshape"

    def _fire(self, g):
        node, oi = g.outputs[0]
        new = g.new_node("sum", node.name + "_collapsed", {}, [(node, oi)])
        g.outputs[0] = (new, 0)
        g.note(self.name, "collapse", "replace", node=new.name,
               origins=[node.name])
        g.invalidate()
        return 1


class _NoProvenancePass(_OncePass):
    """Inserts an identity node but never notes it — a provenance gap."""

    name = "noprov"

    def _fire(self, g):
        node, oi = g.outputs[0]
        new = g.new_node("_copy", node.name + "_id", {}, [(node, oi)])
        g.outputs[0] = (new, 0)
        g.invalidate()
        return 1


class _NeverConvergesPass(analysis.RewritePass):
    """Claims a firing every round without changing the graph."""

    name = "pingpong"

    def run(self, g):
        return 1


class _ArgDroppingPass(_OncePass):
    """Replaces the output with a literal of the same shape/dtype — every
    argument becomes unreachable while shapes/dtypes stay intact."""

    name = "argdrop"

    def _fire(self, g):
        import numpy as _np

        arr = _np.zeros((2,), "float32")
        lit = g.new_node("_graph_const", "lit",
                         {"data": arr.tobytes(), "shape": (2,),
                          "dtype": "float32"}, [])
        g.outputs[0] = (lit, 0)
        g.note(self.name, "drop", "replace", node=lit.name,
               origins=[g.topo()[0].name])
        g.invalidate()
        return 1


def _scalar_chain():
    return mx.sym.Variable("x") * 2.0, {"shapes": {"x": (2,)}}


def _gl601_broken_rw():
    sym, kw = _scalar_chain()
    return _rw_codes(sym, passes=[_ShapeBreakingPass()], **kw)


def _gl601_clean_rw():
    sym, kw = _scalar_chain()
    return _rw_codes(sym, **kw)


def _gl602_broken_rw():
    sym, kw = _scalar_chain()
    return _rw_codes(sym, passes=[_NoProvenancePass()], **kw)


def _gl602_clean_rw():
    # the builtin pipeline notes every node it creates
    d = mx.sym.Variable("data")
    net = mx.sym.Activation(d * d, act_type="relu")  # fires canonicalize
    return _rw_codes(net, shapes={"data": (2, 3)})


def _gl603_broken_rw():
    sym, kw = _scalar_chain()
    return _rw_codes(sym, passes=[_NeverConvergesPass()], max_rounds=2,
                     **kw)


def _gl603_clean_rw():
    net = mx.models.get_symbol("transformer", vocab_size=20, model_dim=16,
                               num_heads=2, num_layers=1, ffn_dim=16,
                               seq_len=4)
    return _rw_codes(net)  # real multi-pass run converges in budget


def _gl604_broken_rw():
    sym, kw = _scalar_chain()
    return _rw_codes(sym, passes=[_ArgDroppingPass()], grad_req="write",
                     **kw)


def _gl604_clean_rw():
    sym, kw = _scalar_chain()
    return _rw_codes(sym, passes=[_ArgDroppingPass()], grad_req="null",
                     **kw)


def _gl605_broken_rw():
    # "broken" here = the summary fires whenever the pipeline changed
    # anything: a graph with a common subexpression
    a, b = mx.sym.Variable("a"), mx.sym.Variable("b")
    net = (a + b) * (a + b)
    return _rw_codes(net, shapes={"a": (2,), "b": (2,)})


def _gl605_clean_rw():
    # an already-canonical graph: zero records, no summary
    return _rw_codes(mx.models.get_symbol("mlp", num_classes=10),
                     shapes={"data": (2, 784)})


REWRITE_CODE_CASES = {
    "GL601": (_gl601_broken_rw, _gl601_clean_rw),
    "GL602": (_gl602_broken_rw, _gl602_clean_rw),
    "GL603": (_gl603_broken_rw, _gl603_clean_rw),
    "GL604": (_gl604_broken_rw, _gl604_clean_rw),
    "GL605": (_gl605_broken_rw, _gl605_clean_rw),
}


@pytest.mark.parametrize("code", sorted(REWRITE_CODE_CASES))
def test_rewrite_code_triggers_on_broken_rewrite(code):
    assert code in REWRITE_CODE_CASES[code][0]()


@pytest.mark.parametrize("code", sorted(REWRITE_CODE_CASES))
def test_rewrite_code_silent_on_clean_rewrite(code):
    assert code not in REWRITE_CODE_CASES[code][1]()


# --------------------------------------------------------------------------
# dispatch-discipline codes (GL7xx): source snippets through the AST lint
# (GL701-GL704), synthetic gap rows through the measured lint (GL705)
# --------------------------------------------------------------------------
from mxnet_tpu.analysis import dispatch_lint  # noqa: E402

_GL701_BROKEN = """
def greedy(dec, tok, n):
    for _ in range(n):
        logits = dec.decode_step(tok)
        tok = logits.asnumpy()
    return tok
"""

_GL701_CLEAN = """
def drain(dec, toks):
    outs = []
    for t in toks:
        outs.append(dec.decode_step(t))
    return outs[-1].asnumpy()
"""

_GL702_BROKEN = """
def decode(dec, tok, n):
    for _ in range(n):
        tok = dec.decode_step(tok)
    return tok
"""

# the lax.scan rewrite of _GL702_BROKEN: the loop state rides as the scan
# carry and the host dispatches ONE megastep — exactly the fix GL702 asks for
_GL702_CLEAN = """
def decode(dec, tok, n):
    def megastep(carry, _):
        return dec.scan_body(carry), None
    final, _ = lax.scan(megastep, tok, None, length=n)
    return final
"""

_GL703_BROKEN = """
def pick(dec, x):
    logits = dec.decode_step(x)
    return np.argmax(logits, axis=-1)
"""

_GL703_CLEAN = """
def pick(dec, x):
    ids = dec.greedy_step(x)
    return ids
"""

_GL703_WAIVED = """
def pick(dec, x):
    logits = dec.decode_step(x)
    return np.argmax(logits, axis=-1)  # graphlint: waive GL703 -- acknowledged
"""

_GL704_BROKEN = """
def run2(a, b, x):
    ya = a.forward(x)
    out = ya.asnumpy()
    yb = b.forward(x)
    return out, yb.asnumpy()
"""

_GL704_CLEAN = """
def run2(a, b, x):
    ya = a.forward(x)
    yb = b.forward(x)
    return ya.asnumpy(), yb.asnumpy()
"""


def _dl_codes(src):
    return {f.code
            for f in dispatch_lint.lint_dispatch_source("<case>", text=src)}


def _gl705_rows(gap_ms):
    return [{"name": "serving.decode_step", "count": 10, "intervals": 9,
             "busy_ms": 10.0, "gap_ms": gap_ms, "max_gap_ms": gap_ms / 2.0,
             "clamped": 0}]


def _gl705_codes(gap_ms):
    return {d.code
            for d in dispatch_lint.lint_dispatch_gaps(_gl705_rows(gap_ms),
                                                      pct=0.25)}


DISPATCH_CODE_CASES = {
    "GL701": (lambda: _dl_codes(_GL701_BROKEN),
              lambda: _dl_codes(_GL701_CLEAN)),
    "GL702": (lambda: _dl_codes(_GL702_BROKEN),
              lambda: _dl_codes(_GL702_CLEAN)),
    "GL703": (lambda: _dl_codes(_GL703_BROKEN),
              lambda: _dl_codes(_GL703_CLEAN)),
    "GL704": (lambda: _dl_codes(_GL704_BROKEN),
              lambda: _dl_codes(_GL704_CLEAN)),
    # 8 ms host gap against 10 ms busy = 80% >> the 25% threshold; the
    # clean side's 1 ms = 10% stays under it
    "GL705": (lambda: _gl705_codes(8.0), lambda: _gl705_codes(1.0)),
}


@pytest.mark.parametrize("code", sorted(DISPATCH_CODE_CASES))
def test_dispatch_code_triggers_on_broken_source(code):
    assert code in DISPATCH_CODE_CASES[code][0]()


@pytest.mark.parametrize("code", sorted(DISPATCH_CODE_CASES))
def test_dispatch_code_silent_on_clean_source(code):
    assert code not in DISPATCH_CODE_CASES[code][1]()


def test_dispatch_waived_site_reported_but_not_failing():
    """A '# graphlint: waive GL703 -- reason' comment keeps the finding in
    the site table (waived=True, severity info, '[waived]' marker) instead
    of failing the run."""
    findings = dispatch_lint.lint_dispatch_source("<case>",
                                                  text=_GL703_WAIVED)
    f = next(f for f in findings if f.code == "GL703")
    assert f.waived
    d = f.to_diagnostic()
    assert d.severity == "info"
    assert d.message.endswith("[waived]")
    # the same site without the waiver is a warning
    g = next(f for f in dispatch_lint.lint_dispatch_source(
        "<case>", text=_GL703_BROKEN) if f.code == "GL703")
    assert not g.waived
    assert g.to_diagnostic().severity == "warning"


def test_dispatch_family_waiver_covers_every_gl7xx_code():
    src = _GL701_BROKEN.replace(
        "tok = logits.asnumpy()",
        "tok = logits.asnumpy()  # graphlint: waive GL7xx -- family waiver")
    findings = dispatch_lint.lint_dispatch_source("<case>", text=src)
    waived_lines = {f.line for f in findings if f.waived}
    assert waived_lines, [f.to_dict() for f in findings]


def test_gl705_needs_two_intervals():
    rows = _gl705_rows(8.0)
    rows[0]["intervals"] = 1
    assert not dispatch_lint.lint_dispatch_gaps(rows, pct=0.25)


def test_repo_dispatch_scan_flags_kv_decode_host_sync_sites():
    """Acceptance: the default-surface scan flags the known kv_decode
    host-sync sites (GL701 in both decoders' greedy loops) with file:line
    provenance."""
    report, sites = dispatch_lint.lint_dispatch_paths()
    kv = [s for s in sites if s["file"].endswith("serving/kv_decode.py")
          and s["code"] == "GL701"]
    assert len(kv) >= 2, sites
    assert {s["function"] for s in kv} >= {"KVCacheDecoder.greedy",
                                           "PagedKVDecoder.greedy"}
    assert all(s["line"] > 0 and s["provenance"] for s in kv)


def test_graph_gl703_fires_on_tokenless_decode_symbol_only():
    """Graph-side GL703: the decode-signature symbol WITHOUT the on-device
    greedy head triggers; token_out=True (the default) is clean."""
    from mxnet_tpu.models import transformer as tf

    cfg = dict(vocab_size=64, num_layers=2, num_heads=2, model_dim=32,
               ffn_dim=64)
    B, S, H, dh = 2, 8, 2, 16
    sh = {"data": (B, 1), "pos_idx": (B, 1), "slot_onehot": (S,),
          "kv_mask": (S,)}
    for i in range(cfg["num_layers"]):
        sh["kv_k_%d" % i] = (B, H, S, dh)
        sh["kv_v_%d" % i] = (B, H, S, dh)
    bare = tf.get_decode_symbol(max_len=S, pos_len=S, token_out=False, **cfg)
    assert "GL703" in _codes(bare, shapes=sh)
    headed = tf.get_decode_symbol(max_len=S, pos_len=S, **cfg)
    assert "GL703" not in _codes(headed, shapes=sh)


# --------------------------------------------------------------------------
# concurrency codes (GL8xx): source snippets through the AST lint
# (GL801-GL804), witness dumps through the measured lint (GL805)
# --------------------------------------------------------------------------
from mxnet_tpu.analysis import concurrency_lint  # noqa: E402

_GL801_BROKEN = """
import jax

def step(kv):
    if jax.process_index() == 0:
        kv.allreduce([1])
"""

# guarding on world SIZE is rank-uniform — the correct idiom, not divergence
_GL801_CLEAN = """
import jax

def step(kv):
    if jax.process_count() > 1:
        kv.allreduce([1])
"""

_GL802_BROKEN = """
import threading

class Srv:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._t = threading.Thread(target=self._loop)

    def _loop(self):
        self.count += 1

    def bump(self):
        with self._lock:
            self.count += 1
"""

_GL802_CLEAN = """
import threading

class Srv:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._t = threading.Thread(target=self._loop)

    def _loop(self):
        with self._lock:
            self.count += 1

    def bump(self):
        with self._lock:
            self.count += 1
"""

_GL803_BROKEN = """
import threading

class Srv:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:
                pass

    def two(self):
        with self._b:
            with self._a:
                pass
"""

_GL803_CLEAN = """
import threading

class Srv:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:
                pass

    def two(self):
        with self._a:
            with self._b:
                pass
"""

_GL804_BROKEN = """
import threading

class Srv:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = None

    def drain(self):
        with self._lock:
            return self._q.get()
"""

# cond.wait() on a condition backed by the held lock RELEASES it — exempt
_GL804_CLEAN = """
import threading

class Srv:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._q = None

    def drain(self):
        with self._lock:
            self._cv.wait()
        return self._q.get()
"""

_GL804_WAIVED = """
import threading

class Srv:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = None

    def drain(self):
        with self._lock:
            return self._q.get()  # graphlint: waive GL804 -- bounded producer
"""


def _cl_codes(src):
    return {f.code for f in
            concurrency_lint.lint_concurrency_source("<case>", text=src)}


def _gl805_witness(seam):
    return {"enabled": True, "threshold_ms": 50.0,
            "events": [{"kind": "long_hold", "lock": "serving.engine",
                        "hold_ms": 80.0, "threshold_ms": 50.0,
                        "thread": "T", "dispatch_seam": seam}]}


def _gl805_codes(seam):
    return {d.code for d in concurrency_lint.lint_lock_witness(
        _gl805_witness(seam))}


CONCURRENCY_CODE_CASES = {
    "GL801": (lambda: _cl_codes(_GL801_BROKEN),
              lambda: _cl_codes(_GL801_CLEAN)),
    "GL802": (lambda: _cl_codes(_GL802_BROKEN),
              lambda: _cl_codes(_GL802_CLEAN)),
    "GL803": (lambda: _cl_codes(_GL803_BROKEN),
              lambda: _cl_codes(_GL803_CLEAN)),
    "GL804": (lambda: _cl_codes(_GL804_BROKEN),
              lambda: _cl_codes(_GL804_CLEAN)),
    # measured: a >threshold hold ACROSS a dispatch seam fires; the same
    # hold with no seam stays in the contention table only
    "GL805": (lambda: _gl805_codes(True), lambda: _gl805_codes(False)),
}


@pytest.mark.parametrize("code", sorted(CONCURRENCY_CODE_CASES))
def test_concurrency_code_triggers_on_broken_source(code):
    assert code in CONCURRENCY_CODE_CASES[code][0]()


@pytest.mark.parametrize("code", sorted(CONCURRENCY_CODE_CASES))
def test_concurrency_code_silent_on_clean_source(code):
    assert code not in CONCURRENCY_CODE_CASES[code][1]()


def test_concurrency_waived_site_reported_but_not_failing():
    findings = concurrency_lint.lint_concurrency_source(
        "<case>", text=_GL804_WAIVED)
    f = next(f for f in findings if f.code == "GL804")
    assert f.waived
    d = f.to_diagnostic()
    assert d.severity == "info"
    assert d.message.endswith("[waived]")
    g = next(f for f in concurrency_lint.lint_concurrency_source(
        "<case>", text=_GL804_BROKEN) if f.code == "GL804")
    assert not g.waived
    assert g.to_diagnostic().severity == "warning"


def test_concurrency_family_waiver_covers_every_gl8xx_code():
    src = _GL801_BROKEN.replace(
        "kv.allreduce([1])",
        "kv.allreduce([1])  # graphlint: waive GL8xx -- family waiver")
    findings = concurrency_lint.lint_concurrency_source("<case>", text=src)
    waived_lines = {f.line for f in findings if f.waived}
    assert waived_lines, [f.to_dict() for f in findings]


def test_gl801_except_handler_is_rank_varying():
    """A collective inside a caught-exception branch diverges: which rank
    raises (and what) is runtime-local."""
    src = """
def step(kv):
    try:
        risky()
    except Exception:
        kv._barrier()
"""
    assert "GL801" in _cl_codes(src)


def test_gl801_provenance_names_the_divergent_read():
    findings = concurrency_lint.lint_concurrency_source(
        "<case>", text=_GL801_BROKEN)
    f = next(f for f in findings if f.code == "GL801")
    assert any("process_index" in p for p in f.provenance), f.provenance


def test_repo_concurrency_scan_is_clean_or_waived():
    """Acceptance: the default-surface scan exits clean — every finding on
    the real tree fixed or carrying a waive reason (the CI repo gate)."""
    report, sites = concurrency_lint.lint_concurrency_paths()
    unwaived = [s for s in sites if not s["waived"]]
    assert not unwaived, unwaived
    # the known protocol-level GL801 in the elastic pause path stays
    # visible as a waived site (the docs worked example)
    assert any(s["code"] == "GL801"
               and s["file"].endswith("module/elastic.py")
               for s in sites), sites


def test_every_diagnostic_code_is_tested():
    covered = (set(GRAPH_CODE_CASES) | set(ENGINE_CODE_CASES) | {"GL105"}
               | set(REWRITE_CODE_CASES) | set(DISPATCH_CODE_CASES)
               | set(CONCURRENCY_CODE_CASES))
    assert covered == set(CODES), (
        "codes missing a trigger/clean test pair: %s; stale test entries: %s"
        % (sorted(set(CODES) - covered), sorted(covered - set(CODES))))


# --------------------------------------------------------------------------
# sharding-plan lint + memory planner (GL4xx/GL5xx) acceptance
# --------------------------------------------------------------------------
def test_missharded_symbol_fires_three_distinct_gl4xx_codes():
    """Acceptance: a deliberately mis-sharded symbol triggers >= 3 distinct
    GL4xx codes — uneven batch (GL404), indivisible weight (GL401), and a
    sharded-contraction all-gather (GL402)."""
    d = mx.sym.Variable("data")        # batch 3 over dp=2 -> GL404
    h = mx.sym.FullyConnected(data=d, num_hidden=256, name="fc1")
    h = mx.sym.Activation(data=h, act_type="relu", name="relu1")
    h = mx.sym.FullyConnected(data=h, num_hidden=8, name="fc2")  # GL402
    d2 = mx.sym.Variable("aux_data")
    odd = mx.sym.FullyConnected(data=d2, num_hidden=999, name="oddfc")
    sym = mx.sym.Group([h, odd])       # oddfc weight (999, 783) -> GL401
    report = analysis.lint(
        sym, shapes={"data": (3, 512), "aux_data": (4, 783)},
        mesh="dp=2,model=2", target="missharded")
    fired = {c for c in report.codes() if c.startswith("GL4")}
    assert len(fired) >= 3, report.format()
    assert {"GL401", "GL402", "GL404"} <= fired, report.format()


def test_clean_model_lints_clean_under_mesh_and_budget():
    """Acceptance: an under-budget, well-sharded model has zero findings."""
    net = mx.models.get_symbol("mlp", num_classes=10)
    report = analysis.lint(net, shapes={"data": (8, 784)},
                           mesh="dp=8", budget_gb=16.0, target="mlp")
    assert report.codes() == [], report.format()
    assert report.memory_plan is not None
    assert report.memory_plan["per_device"]["peak"] > 0


def test_memory_plan_structure_and_policies():
    """The plan's accounting identities: peak = params+grads+opt+inputs+act;
    recompute never stashes more than stash; inference drops grads/opt."""
    net = mx.models.get_symbol("mlp", num_classes=10)
    sh = {"data": (32, 784)}
    stash = analysis.lint(net, shapes=sh).memory_plan
    rec = analysis.lint(net, shapes=sh, bwd="recompute").memory_plan
    inf = analysis.lint(net, shapes=sh, train=False).memory_plan
    pd = stash["per_device"]
    assert pd["peak"] == (pd["params"] + pd["grads"] + pd["opt_state"]
                          + pd["inputs"] + pd["act_peak"])
    assert pd["grads"] == pd["opt_state"] > 0
    assert rec["per_device"]["act_peak"] <= pd["act_peak"]
    assert inf["per_device"]["grads"] == inf["per_device"]["opt_state"] == 0
    assert inf["per_device"]["peak"] < pd["peak"]
    assert stash["peak_node"] and stash["peak_live"]
    # sharding divides per-device bytes: dp=8 cuts the batch-sharded
    # activation watermark vs the single-device plan
    dp = analysis.lint(net, shapes=sh, mesh="dp=8").memory_plan
    assert dp["per_device"]["act_peak"] < pd["act_peak"]
    assert dp["per_device"]["params"] == pd["params"]  # replicated


def test_predicted_peak_within_2x_of_measured_live_buffers():
    """Acceptance: the GL5xx prediction for a zoo model is within 2x of the
    bytes actually held live by a bound executor on the CPU backend (args +
    grads + aux + outputs — the buffers that survive a fwd/bwd step)."""
    net = mx.models.get_symbol("mlp", num_classes=10)
    shapes = {"data": (32, 784), "softmax_label": (32,)}
    report = analysis.lint(net, shapes=shapes, target="mlp")
    pred = report.memory_plan["per_device"]["peak"]
    exe = net.simple_bind(ctx=mx.cpu(), **shapes)
    exe.forward(is_train=True)
    exe.backward()

    def nbytes(a):
        return int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize

    measured = sum(nbytes(a) for a in exe.arg_arrays)
    measured += sum(nbytes(g) for g in exe.grad_arrays if g is not None)
    measured += sum(nbytes(a) for a in exe.aux_arrays)
    measured += sum(nbytes(o) for o in exe.outputs)
    assert measured / 2 <= pred <= measured * 2, (pred, measured)


def test_batch_one_keeps_batch_sharding_no_false_gl403():
    """Regression: an extent-1 batch dim that STAYS extent 1 through an
    elementwise op must keep its data-axis sharding — batch=1 shapes (the
    CLI's zoo defaults) used to lose the axis at the first Activation and
    emit a false GL403 'collapses the batch dim'."""
    net = mx.models.get_symbol("mlp", num_classes=10)
    report = analysis.lint(net, shapes={"data": (1, 784)},
                           mesh="dp=8,model=2", target="mlp-b1")
    assert "GL403" not in report.codes(), report.format()


def test_null_grad_req_bind_plans_inference(monkeypatch):
    """Regression: bind with grad arrays but grad_req='null' never runs a
    backward — the GL5xx planner must account it as inference (no grads,
    no optimizer state), not as a training bind."""
    from mxnet_tpu import telemetry

    monkeypatch.setenv("MXNET_GRAPHLINT", "warn")
    monkeypatch.setenv("MXNET_TELEMETRY", "counters")
    net = mx.models.get_symbol("mlp", num_classes=10)
    arg_shapes, _, _ = net.infer_shape(data=(8, 784))
    args = {n: mx.nd.zeros(s) for n, s in zip(net.list_arguments(),
                                              arg_shapes)}
    grads = {n: mx.nd.zeros(s) for n, s in zip(net.list_arguments(),
                                               arg_shapes)}
    telemetry.reset()
    net.bind(ctx=mx.cpu(), args=args, args_grad=grads, grad_req="write")
    train_peak = telemetry.gauge("memlint.predicted_peak_bytes").value
    telemetry.reset()
    net.bind(ctx=mx.cpu(), args=args, args_grad=grads, grad_req="null")
    inf_peak = telemetry.gauge("memlint.predicted_peak_bytes").value
    assert inf_peak < train_peak, (inf_peak, train_peak)


def test_memory_plan_exports_telemetry_gauge(monkeypatch):
    from mxnet_tpu import telemetry

    monkeypatch.setenv("MXNET_TELEMETRY", "counters")
    telemetry.reset()
    net = mx.models.get_symbol("mlp", num_classes=10)
    report = analysis.lint(net, shapes={"data": (8, 784)})
    g = telemetry.gauge("memlint.predicted_peak_bytes")
    assert g.value == report.memory_plan["per_device"]["peak"]


def test_memlint_budget_env_var(monkeypatch):
    """MXNET_MEMLINT_BUDGET_GB arms GL501 without any caller kwarg."""
    monkeypatch.setenv("MXNET_MEMLINT_BUDGET_GB", "0.000001")
    sym, kw = _gl501_clean()  # generous-kwarg variant; env drives it now
    report = analysis.lint(sym, shapes=kw["shapes"])
    assert "GL501" in report.codes()
    monkeypatch.setenv("MXNET_MEMLINT_BUDGET_GB", "1000")
    assert "GL501" not in _codes(sym, shapes=kw["shapes"])


def test_cli_mesh_resnet50_reshard_and_peak_table(capsys):
    """Acceptance: graphlint resnet-50 --mesh dp=8,model=2 prints per-edge
    reshard-bytes diagnostics and the per-device peak-HBM table."""
    from mxnet_tpu.analysis.cli import main

    rc = main(["resnet-50", "--shape", "data=32,3,224,224",
               "--mesh", "dp=8,model=2"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "implicit reshard" in out and "moved per device" in out
    assert "predicted peak HBM per device" in out
    assert "params" in out and "activations" in out


def test_cli_mesh_summary_table_and_json_plan(tmp_path, capsys):
    from mxnet_tpu.analysis.cli import main

    rc = main(["mlp", "lenet", "--mesh", "dp=2"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "peak-HBM summary" in out  # multi-target text mode summarizes
    rc = main(["mlp", "--mesh", "dp=2", "--format", "json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    plan = payload[0]["memory_plan"]
    assert plan["mesh"] == {"dp": 2}
    assert plan["per_device"]["peak"] > 0


def test_cli_bad_mesh_is_usage_error(capsys):
    from mxnet_tpu.analysis.cli import main

    assert main(["mlp", "--mesh", "dp8"]) == 2


def test_spmd_adapter_feeds_mesh_to_lint(monkeypatch):
    """SPMDStepAdapter's bind path lints with the REAL mesh + rules: the
    predicted peak lands on the telemetry gauge and reflects dp sharding."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    from mxnet_tpu import telemetry

    monkeypatch.setenv("MXNET_GRAPHLINT", "warn")
    monkeypatch.setenv("MXNET_TELEMETRY", "counters")
    telemetry.reset()
    net = mx.models.get_symbol("mlp", num_classes=10)
    it = mx.io.NDArrayIter(np.zeros((16, 784), "float32"),
                           np.zeros((16,), "float32"), batch_size=16)
    mod = mx.mod.Module(net, context=[mx.cpu(i) for i in range(8)])
    mod.fit(it, num_epoch=1)
    assert mod._spmd is not None, "fused SPMD step did not engage"
    spmd_peak = telemetry.gauge("memlint.predicted_peak_bytes").value
    assert spmd_peak and spmd_peak > 0
    # the same symbol planned single-device predicts MORE per device than
    # the dp=8 plan (batch-sharded activations divide by 8)
    single = analysis.lint(net, shapes={"data": (16, 784),
                                        "softmax_label": (16,)}).memory_plan
    assert single["per_device"]["act_peak"] > 0
    assert spmd_peak < single["per_device"]["peak"]


# --------------------------------------------------------------------------
# satellite: engine wait_for_var on an unknown var raises (all engine types)
# --------------------------------------------------------------------------
@pytest.mark.parametrize("maker", [
    eng.NaiveEngine,
    lambda: eng.ThreadedEngine(num_workers=2),
    lambda: eng._PythonThreadedEngine(2),
], ids=["naive", "threaded", "python"])
def test_wait_for_unknown_var_raises(maker):
    e = maker()
    with pytest.raises(mx.MXNetError, match="unknown engine variable"):
        e.wait_for_var(987654321)
    # known vars still work
    v = e.new_variable()
    done = []
    e.push(lambda: done.append(1), mutable_vars=[v])
    e.wait_for_var(v)
    assert done == [1]


# --------------------------------------------------------------------------
# satellite: infer_meta registry is the shared source of truth
# --------------------------------------------------------------------------
def test_infer_meta_registry():
    from mxnet_tpu.ops import infer_meta, shape_rules

    conv = infer_meta.get_meta("Convolution")
    assert conv.input_ranks["data"] == (4, 4)
    assert "weight" in conv.param_slots
    # backward rules are re-exported, not duplicated
    assert infer_meta.backward_shape_rule("FullyConnected") \
        is shape_rules.RULES["FullyConnected"]
    # unregistered ops get the permissive default
    default = infer_meta.get_meta("no_such_op")
    assert default.input_ranks == {} and default.param_slots == ()


# --------------------------------------------------------------------------
# bind integration: MXNET_GRAPHLINT=0|warn|error
# --------------------------------------------------------------------------
def test_bind_lint_error_mode_raises(monkeypatch):
    monkeypatch.setenv("MXNET_GRAPHLINT", "error")
    sym, kw = _gl006_broken()
    with pytest.raises(mx.MXNetError, match="GL006"):
        sym.simple_bind(ctx=mx.cpu(), **{k: v for k, v in kw["shapes"].items()})


def test_bind_lint_error_mode_passes_clean_graph(monkeypatch):
    monkeypatch.setenv("MXNET_GRAPHLINT", "error")
    net = mx.models.get_symbol("mlp", num_classes=10)
    exe = net.simple_bind(ctx=mx.cpu(), data=(4, 784), softmax_label=(4,))
    assert exe.forward(is_train=False)[0].shape == (4, 10)


def test_bind_lint_warn_mode_logs_but_binds(monkeypatch, caplog):
    monkeypatch.setenv("MXNET_GRAPHLINT", "warn")
    x = mx.sym.Variable("x", dtype="float16")
    y = mx.sym.Variable("y", dtype="float32")
    s = x + y
    with caplog.at_level("WARNING", logger="mxnet_tpu.graphlint"):
        exe = s.simple_bind(ctx=mx.cpu(), x=(2,), y=(2,),
                            type_dict={"x": "float16", "y": "float32"})
    assert exe is not None
    assert any("GL004" in r.message for r in caplog.records)


def test_bind_lint_off_by_default(monkeypatch):
    monkeypatch.delenv("MXNET_GRAPHLINT", raising=False)
    assert analysis.graphlint_mode() is None


def test_graphlint_mode_aliases_and_unknown(monkeypatch, caplog):
    monkeypatch.setenv("MXNET_GRAPHLINT", "1")
    assert analysis.graphlint_mode() == "warn"  # boolean idiom honored
    monkeypatch.setenv("MXNET_GRAPHLINT", "bogus")
    with caplog.at_level("WARNING", logger="mxnet_tpu.graphlint"):
        assert analysis.graphlint_mode() is None
    assert any("not a recognized mode" in r.message for r in caplog.records)


# --------------------------------------------------------------------------
# regression: models/resnet.py lints clean under MXNET_GRAPHLINT=error
# --------------------------------------------------------------------------
def test_resnet_lints_clean_under_error_mode(monkeypatch):
    monkeypatch.setenv("MXNET_GRAPHLINT", "error")
    net = mx.models.get_symbol("resnet-18", num_classes=10,
                               image_shape="3,32,32")
    exe = net.simple_bind(ctx=mx.cpu(), data=(2, 3, 32, 32),
                          softmax_label=(2,))
    assert exe is not None
    report = analysis.lint(net, shapes={"data": (2, 3, 32, 32)},
                           target="resnet-18")
    assert report.errors == [] and report.warnings == []


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------
def test_cli_single_model_clean():
    from mxnet_tpu.analysis.cli import main

    assert main(["mlp"]) == 0


def test_cli_list_codes(capsys):
    from mxnet_tpu.analysis.cli import main

    assert main(["--list-codes"]) == 0
    out = capsys.readouterr().out
    for code in CODES:
        assert code in out


def test_cli_json_format_and_broken_symbol_file(tmp_path, capsys):
    from mxnet_tpu.analysis.cli import main

    sym, kw = _gl006_broken()
    path = str(tmp_path / "broken-symbol.json")
    sym.save(path)
    rc = main([path, "--shape", "data=2,3,8,8", "--format", "json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any(d["code"] == "GL006"
               for entry in payload for d in entry["diagnostics"])


def test_cli_unknown_target_is_usage_error(capsys):
    from mxnet_tpu.analysis.cli import main

    assert main(["no-such-model"]) == 2


def test_cli_default_shapes_are_case_insensitive(capsys):
    """'MLP' must get the same default shape hints as 'mlp' (get_symbol
    lowercases the zoo key, so the shape table must too)."""
    from mxnet_tpu.analysis.cli import main

    assert main(["MLP"]) == 0
    out = capsys.readouterr().out
    # with default shapes applied the graph is fully determined: no GL203,
    # zero findings — a structural-only lint would report 1 finding
    assert "0 total finding(s)" in out


def test_unknown_pass_subset_raises():
    """A typo'd --passes selection must not lint nothing and exit 'clean'."""
    sym, _ = _gl001_clean()
    with pytest.raises(ValueError, match="unknown analysis pass"):
        analysis.lint(sym, passes=["shapelint"])  # typo of shape_lint


def test_cli_strict_fails_on_warnings():
    from mxnet_tpu.analysis.cli import main

    sym, _ = _gl202_broken()
    import tempfile, os as _os

    with tempfile.TemporaryDirectory() as td:
        path = _os.path.join(td, "warn-symbol.json")
        sym.save(path)
        assert main([path]) == 0            # warnings alone pass
        assert main([path, "--strict"]) == 1  # ... unless strict


@pytest.mark.slow
def test_cli_all_models_sweep_exits_zero():
    """Acceptance: tools/graphlint runs on every bundled model and exits 0."""
    from mxnet_tpu.analysis.cli import main

    assert main(["--all-models"]) == 0


# --------------------------------------------------------------------------
# CI dogfood: the subsystem lints itself on every PR (tools/ci_check.sh runs
# the same steps standalone)
# --------------------------------------------------------------------------
def test_package_sources_compile():
    """Every mxnet_tpu source parses/compiles — the dependency-free floor of
    the ruff/pyflakes step (those run in ci_check.sh when installed)."""
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(mx.__file__)))
    pkg = os.path.join(root, "mxnet_tpu")
    bad = []
    for dirpath, _, files in os.walk(pkg):
        for f in files:
            if not f.endswith(".py"):
                continue
            path = os.path.join(dirpath, f)
            with open(path) as fh:
                try:
                    compile(fh.read(), path, "exec")
                except SyntaxError as exc:
                    bad.append("%s: %s" % (path, exc))
    assert not bad, "\n".join(bad)


def test_pyflakes_clean_when_available():
    pytest.importorskip("pyflakes")
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(mx.__file__)))
    proc = subprocess.run(
        [sys.executable, "-m", "pyflakes", os.path.join(root, "mxnet_tpu")],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
