"""graphlint test suite (analysis/ subsystem).

Every diagnostic code ships with BOTH a trigger (a deliberately-broken
graph or schedule that fires it) and a clean case (a healthy graph or
schedule that does not) — parametrized from one table so the completeness
meta-test can prove no code is untested. Plus: bind-time integration
(MXNET_GRAPHLINT=warn|error), the engine wait_for_var satellite fix, the
infer_meta registry, the CLI, and the models/resnet.py lint-clean
regression.
"""
import json
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import analysis
from mxnet_tpu import engine as eng
from mxnet_tpu.analysis import CODES, RecordingEngine, analyze_trace


def _codes(sym, **kw):
    return set(analysis.lint(sym, **kw).codes())


# --------------------------------------------------------------------------
# graph-code table: code -> (broken_builder, clean_builder), each returning
# (symbol, lint_kwargs)
# --------------------------------------------------------------------------
def _gl001_broken():
    a = mx.sym.Variable("a", shape=(2, 3))
    b = mx.sym.Variable("b", shape=(4, 5))
    return mx.sym.dot(a, b, name="baddot"), {}


def _gl001_clean():
    a = mx.sym.Variable("a", shape=(2, 3))
    b = mx.sym.Variable("b", shape=(3, 5))
    return mx.sym.dot(a, b, name="okdot"), {}


def _gl002_broken():
    d = mx.sym.Variable("data")
    e = mx.sym.Variable("extra")
    s = mx.sym.FullyConnected(data=d, num_hidden=4, name="fcA") \
        + mx.sym.FullyConnected(data=e, num_hidden=4, name="fcB")
    return s, {"shapes": {"data": (2, 8)}}  # 'extra' stays unknown


def _gl002_clean():
    d = mx.sym.Variable("data")
    s = mx.sym.FullyConnected(data=d, num_hidden=4, name="fcC")
    return s, {"shapes": {"data": (2, 8)}}


def _gl003_broken():
    d = mx.sym.Variable("data")
    w = mx.sym.Variable("fc_weight", shape=(7, 99))
    return (mx.sym.FullyConnected(data=d, weight=w, num_hidden=7, name="fc"),
            {"shapes": {"data": (2, 10)}})


def _gl003_clean():
    d = mx.sym.Variable("data")
    w = mx.sym.Variable("fc_weight", shape=(7, 10))
    return (mx.sym.FullyConnected(data=d, weight=w, num_hidden=7, name="fc"),
            {"shapes": {"data": (2, 10)}})


def _gl004_broken():
    x = mx.sym.Variable("x", dtype="float16")
    y = mx.sym.Variable("y", dtype="float32")
    return x + y, {"shapes": {"x": (2,), "y": (2,)}}


def _gl004_clean():
    x = mx.sym.Variable("x", dtype="float16")
    y = mx.sym.Variable("y", dtype="float16")
    return x + y, {"shapes": {"x": (2,), "y": (2,)}}


def _gl005_broken():
    return mx.sym.Variable("dup") + mx.sym.Variable("dup"), \
        {"shapes": {"dup": (2,)}}


def _gl005_clean():
    return mx.sym.Variable("p") + mx.sym.Variable("q"), \
        {"shapes": {"p": (2,), "q": (2,)}}


def _gl006_broken():
    d = mx.sym.Variable("data")
    flat = mx.sym.Flatten(data=d)
    return (mx.sym.Convolution(data=flat, num_filter=8, kernel=(3, 3),
                               name="badconv"),
            {"shapes": {"data": (2, 3, 8, 8)}})


def _gl006_clean():
    d = mx.sym.Variable("data")
    return (mx.sym.Convolution(data=d, num_filter=8, kernel=(3, 3),
                               pad=(1, 1), name="okconv"),
            {"shapes": {"data": (2, 3, 8, 8)}})


def _gl201_broken():
    return mx.sym.Variable("x") * 0.125, {}


def _gl201_clean():
    return mx.sym.Variable("x") + mx.sym.Variable("y"), {}


def _gl202_broken():
    h = mx.sym.Variable("h", dtype="float16")
    x = mx.sym.Variable("x")  # weak: defaults to f32 at trace time
    return x + h, {}


def _gl202_clean():
    h = mx.sym.Variable("h", dtype="float16")
    x = mx.sym.Variable("x", dtype="float16")
    return x + h, {}


def _gl203_broken():
    # no shape hints at all: data inputs are shape-polymorphic
    return mx.sym.FullyConnected(data=mx.sym.Variable("data"),
                                 num_hidden=4, name="fcP"), {}


def _gl203_clean():
    return mx.sym.FullyConnected(data=mx.sym.Variable("data"),
                                 num_hidden=4, name="fcP"), \
        {"shapes": {"data": (2, 8)}}


def _fusable_chain(kernel=(3, 3), pad=(1, 1), no_bias=True, name="c"):
    d = mx.sym.Variable("data")
    bn = mx.sym.BatchNorm(data=d, fix_gamma=False, name=name + "_bn")
    act = mx.sym.Activation(data=bn, act_type="relu", name=name + "_relu")
    return mx.sym.Convolution(data=act, num_filter=8, kernel=kernel, pad=pad,
                              no_bias=no_bias, name=name + "_conv")


def _gl301_broken():
    # bias present -> the planner's first predicate fails
    return _fusable_chain(no_bias=False, name="biased"), {}


def _gl301_clean():
    return _fusable_chain(name="fusable"), {}


def _gl302_broken():
    # BN feeding a pooling layer: eligible BN, but nothing to fold into
    d = mx.sym.Variable("data")
    bn = mx.sym.BatchNorm(data=d, fix_gamma=False, name="pool_bn")
    return mx.sym.Pooling(data=bn, kernel=(2, 2), pool_type="max",
                          name="pool"), {}


def _gl302_clean():
    return _fusable_chain(name="folded"), {}


GRAPH_CODE_CASES = {
    "GL001": (_gl001_broken, _gl001_clean),
    "GL002": (_gl002_broken, _gl002_clean),
    "GL003": (_gl003_broken, _gl003_clean),
    "GL004": (_gl004_broken, _gl004_clean),
    "GL005": (_gl005_broken, _gl005_clean),
    "GL006": (_gl006_broken, _gl006_clean),
    "GL201": (_gl201_broken, _gl201_clean),
    "GL202": (_gl202_broken, _gl202_clean),
    "GL203": (_gl203_broken, _gl203_clean),
    "GL301": (_gl301_broken, _gl301_clean),
    "GL302": (_gl302_broken, _gl302_clean),
}


@pytest.mark.parametrize("code", sorted(GRAPH_CODE_CASES))
def test_graph_code_triggers_on_broken_graph(code):
    sym, kw = GRAPH_CODE_CASES[code][0]()
    assert code in _codes(sym, **kw)


@pytest.mark.parametrize("code", sorted(GRAPH_CODE_CASES))
def test_graph_code_silent_on_clean_graph(code):
    sym, kw = GRAPH_CODE_CASES[code][1]()
    assert code not in _codes(sym, **kw)


# --------------------------------------------------------------------------
# engine-schedule codes: trace builders over a RecordingEngine
# --------------------------------------------------------------------------
def _trace_gl101_broken(e):
    v = e.new_variable()
    e.push(lambda: None, const_vars=[v], mutable_vars=[v])


def _trace_gl102_broken(e):
    v = e.new_variable()
    e.push(lambda: None, const_vars=[v])
    e.wait_for_var(v)


def _trace_gl103_broken(e):
    v = e.new_variable()
    e.push(lambda: None, mutable_vars=[v, v])


def _trace_gl104_broken(e):
    v = e.new_variable()
    e.push(lambda: None, const_vars=[v])   # read before any write
    e.push(lambda: None, mutable_vars=[v])


def _trace_clean(e):
    v, w = e.new_variable(), e.new_variable()
    e.push(lambda: None, mutable_vars=[v])
    e.push(lambda: None, const_vars=[v], mutable_vars=[w])
    e.push(lambda: None, const_vars=[v, w])
    e.wait_for_var(w)


ENGINE_CODE_CASES = {
    "GL101": _trace_gl101_broken,
    "GL102": _trace_gl102_broken,
    "GL103": _trace_gl103_broken,
    "GL104": _trace_gl104_broken,
}


@pytest.mark.parametrize("code", sorted(ENGINE_CODE_CASES))
def test_engine_code_triggers_on_broken_schedule(code):
    e = RecordingEngine(eng.NaiveEngine())
    ENGINE_CODE_CASES[code](e)
    assert code in analyze_trace(e.trace).codes()


@pytest.mark.parametrize("code", sorted(ENGINE_CODE_CASES) + ["GL105"])
def test_engine_code_silent_on_clean_schedule(code):
    e = RecordingEngine(eng._PythonThreadedEngine(2), assert_discipline=True)
    _trace_clean(e)
    e.wait_for_all()
    assert code not in analyze_trace(e.trace).codes()


class _NoDisciplineEngine(eng.Engine):
    """Deliberately broken: runs every op on its own thread, ignoring the
    declared var sets entirely — what the shim exists to catch."""

    def __init__(self):
        self._n = 0
        self._threads = []

    def new_variable(self):
        self._n += 1
        return self._n

    def push(self, fn, const_vars=(), mutable_vars=()):
        def quiet():
            try:
                fn()
            except Exception:
                pass  # the shim raises; the trace records it

        t = threading.Thread(target=quiet)
        t.start()
        self._threads.append(t)

    def wait_for_var(self, var):
        self.wait_for_all()

    def wait_for_all(self):
        for t in self._threads:
            t.join()


def test_gl105_runtime_shim_catches_broken_engine():
    e = RecordingEngine(_NoDisciplineEngine(), assert_discipline=True)
    v = e.new_variable()
    gate = threading.Event()
    started = threading.Event()

    def first():
        started.set()
        gate.wait(5)

    e.push(first, mutable_vars=[v])
    assert started.wait(5)
    e.push(lambda: None, mutable_vars=[v])  # overlapping writer
    time.sleep(0.05)
    gate.set()
    e.wait_for_all()
    report = analyze_trace(e.trace)
    assert "GL105" in report.codes()
    assert any("write-write" in d.message for d in report.by_code("GL105"))


def test_shipped_python_engine_passes_discipline_shim():
    """The pure-Python fallback engine, under a real concurrent workload,
    never violates the var discipline the shim asserts."""
    e = RecordingEngine(eng._PythonThreadedEngine(4), assert_discipline=True)
    vars_ = [e.new_variable() for _ in range(4)]
    for i in range(80):
        e.push(lambda: time.sleep(0.0005), mutable_vars=[vars_[i % 4]])
        e.push(lambda: None, const_vars=[vars_[i % 4]],
               mutable_vars=[vars_[(i + 1) % 4]])
    e.wait_for_all()
    assert not e.trace.violations
    assert "GL105" not in analyze_trace(e.trace).codes()


def test_every_diagnostic_code_is_tested():
    covered = set(GRAPH_CODE_CASES) | set(ENGINE_CODE_CASES) | {"GL105"}
    assert covered == set(CODES), (
        "codes missing a trigger/clean test pair: %s; stale test entries: %s"
        % (sorted(set(CODES) - covered), sorted(covered - set(CODES))))


# --------------------------------------------------------------------------
# satellite: engine wait_for_var on an unknown var raises (all engine types)
# --------------------------------------------------------------------------
@pytest.mark.parametrize("maker", [
    eng.NaiveEngine,
    lambda: eng.ThreadedEngine(num_workers=2),
    lambda: eng._PythonThreadedEngine(2),
], ids=["naive", "threaded", "python"])
def test_wait_for_unknown_var_raises(maker):
    e = maker()
    with pytest.raises(mx.MXNetError, match="unknown engine variable"):
        e.wait_for_var(987654321)
    # known vars still work
    v = e.new_variable()
    done = []
    e.push(lambda: done.append(1), mutable_vars=[v])
    e.wait_for_var(v)
    assert done == [1]


# --------------------------------------------------------------------------
# satellite: infer_meta registry is the shared source of truth
# --------------------------------------------------------------------------
def test_infer_meta_registry():
    from mxnet_tpu.ops import infer_meta, shape_rules

    conv = infer_meta.get_meta("Convolution")
    assert conv.input_ranks["data"] == (4, 4)
    assert "weight" in conv.param_slots
    # backward rules are re-exported, not duplicated
    assert infer_meta.backward_shape_rule("FullyConnected") \
        is shape_rules.RULES["FullyConnected"]
    # unregistered ops get the permissive default
    default = infer_meta.get_meta("no_such_op")
    assert default.input_ranks == {} and default.param_slots == ()


# --------------------------------------------------------------------------
# bind integration: MXNET_GRAPHLINT=0|warn|error
# --------------------------------------------------------------------------
def test_bind_lint_error_mode_raises(monkeypatch):
    monkeypatch.setenv("MXNET_GRAPHLINT", "error")
    sym, kw = _gl006_broken()
    with pytest.raises(mx.MXNetError, match="GL006"):
        sym.simple_bind(ctx=mx.cpu(), **{k: v for k, v in kw["shapes"].items()})


def test_bind_lint_error_mode_passes_clean_graph(monkeypatch):
    monkeypatch.setenv("MXNET_GRAPHLINT", "error")
    net = mx.models.get_symbol("mlp", num_classes=10)
    exe = net.simple_bind(ctx=mx.cpu(), data=(4, 784), softmax_label=(4,))
    assert exe.forward(is_train=False)[0].shape == (4, 10)


def test_bind_lint_warn_mode_logs_but_binds(monkeypatch, caplog):
    monkeypatch.setenv("MXNET_GRAPHLINT", "warn")
    x = mx.sym.Variable("x", dtype="float16")
    y = mx.sym.Variable("y", dtype="float32")
    s = x + y
    with caplog.at_level("WARNING", logger="mxnet_tpu.graphlint"):
        exe = s.simple_bind(ctx=mx.cpu(), x=(2,), y=(2,),
                            type_dict={"x": "float16", "y": "float32"})
    assert exe is not None
    assert any("GL004" in r.message for r in caplog.records)


def test_bind_lint_off_by_default(monkeypatch):
    monkeypatch.delenv("MXNET_GRAPHLINT", raising=False)
    assert analysis.graphlint_mode() is None


def test_graphlint_mode_aliases_and_unknown(monkeypatch, caplog):
    monkeypatch.setenv("MXNET_GRAPHLINT", "1")
    assert analysis.graphlint_mode() == "warn"  # boolean idiom honored
    monkeypatch.setenv("MXNET_GRAPHLINT", "bogus")
    with caplog.at_level("WARNING", logger="mxnet_tpu.graphlint"):
        assert analysis.graphlint_mode() is None
    assert any("not a recognized mode" in r.message for r in caplog.records)


# --------------------------------------------------------------------------
# regression: models/resnet.py lints clean under MXNET_GRAPHLINT=error
# --------------------------------------------------------------------------
def test_resnet_lints_clean_under_error_mode(monkeypatch):
    monkeypatch.setenv("MXNET_GRAPHLINT", "error")
    net = mx.models.get_symbol("resnet-18", num_classes=10,
                               image_shape="3,32,32")
    exe = net.simple_bind(ctx=mx.cpu(), data=(2, 3, 32, 32),
                          softmax_label=(2,))
    assert exe is not None
    report = analysis.lint(net, shapes={"data": (2, 3, 32, 32)},
                           target="resnet-18")
    assert report.errors == [] and report.warnings == []


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------
def test_cli_single_model_clean():
    from mxnet_tpu.analysis.cli import main

    assert main(["mlp"]) == 0


def test_cli_list_codes(capsys):
    from mxnet_tpu.analysis.cli import main

    assert main(["--list-codes"]) == 0
    out = capsys.readouterr().out
    for code in CODES:
        assert code in out


def test_cli_json_format_and_broken_symbol_file(tmp_path, capsys):
    from mxnet_tpu.analysis.cli import main

    sym, kw = _gl006_broken()
    path = str(tmp_path / "broken-symbol.json")
    sym.save(path)
    rc = main([path, "--shape", "data=2,3,8,8", "--format", "json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any(d["code"] == "GL006"
               for entry in payload for d in entry["diagnostics"])


def test_cli_unknown_target_is_usage_error(capsys):
    from mxnet_tpu.analysis.cli import main

    assert main(["no-such-model"]) == 2


def test_cli_default_shapes_are_case_insensitive(capsys):
    """'MLP' must get the same default shape hints as 'mlp' (get_symbol
    lowercases the zoo key, so the shape table must too)."""
    from mxnet_tpu.analysis.cli import main

    assert main(["MLP"]) == 0
    out = capsys.readouterr().out
    # with default shapes applied the graph is fully determined: no GL203,
    # zero findings — a structural-only lint would report 1 finding
    assert "0 total finding(s)" in out


def test_unknown_pass_subset_raises():
    """A typo'd --passes selection must not lint nothing and exit 'clean'."""
    sym, _ = _gl001_clean()
    with pytest.raises(ValueError, match="unknown analysis pass"):
        analysis.lint(sym, passes=["shapelint"])  # typo of shape_lint


def test_cli_strict_fails_on_warnings():
    from mxnet_tpu.analysis.cli import main

    sym, _ = _gl202_broken()
    import tempfile, os as _os

    with tempfile.TemporaryDirectory() as td:
        path = _os.path.join(td, "warn-symbol.json")
        sym.save(path)
        assert main([path]) == 0            # warnings alone pass
        assert main([path, "--strict"]) == 1  # ... unless strict


@pytest.mark.slow
def test_cli_all_models_sweep_exits_zero():
    """Acceptance: tools/graphlint runs on every bundled model and exits 0."""
    from mxnet_tpu.analysis.cli import main

    assert main(["--all-models"]) == 0


# --------------------------------------------------------------------------
# CI dogfood: the subsystem lints itself on every PR (tools/ci_check.sh runs
# the same steps standalone)
# --------------------------------------------------------------------------
def test_package_sources_compile():
    """Every mxnet_tpu source parses/compiles — the dependency-free floor of
    the ruff/pyflakes step (those run in ci_check.sh when installed)."""
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(mx.__file__)))
    pkg = os.path.join(root, "mxnet_tpu")
    bad = []
    for dirpath, _, files in os.walk(pkg):
        for f in files:
            if not f.endswith(".py"):
                continue
            path = os.path.join(dirpath, f)
            with open(path) as fh:
                try:
                    compile(fh.read(), path, "exec")
                except SyntaxError as exc:
                    bad.append("%s: %s" % (path, exc))
    assert not bad, "\n".join(bad)


def test_pyflakes_clean_when_available():
    pytest.importorskip("pyflakes")
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(mx.__file__)))
    proc = subprocess.run(
        [sys.executable, "-m", "pyflakes", os.path.join(root, "mxnet_tpu")],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
