"""Streaming latency histogram (mxnet_tpu/telemetry/histogram.py,
docs/OBSERVABILITY.md §Fleet): log-bucket quantile error bound vs numpy
percentiles, merge associativity/commutativity, thread-safety of the
one-increment record path, empty/single-sample edges, and the sparse
delta encoding round-tripped through the fleet's framed-pickle RPC."""
import threading

import numpy as np
import pytest

from mxnet_tpu.telemetry import histogram as hg
from mxnet_tpu.telemetry.histogram import Histogram


# ------------------------------------------------------------- buckets
def test_bucket_index_edges():
    assert hg.bucket_index(0.0) == hg.UNDER
    assert hg.bucket_index(hg.LO / 10.0) == hg.UNDER
    assert hg.bucket_index(hg.HI) == hg.OVER
    assert hg.bucket_index(hg.HI * 10.0) == hg.OVER
    assert hg.bucket_index(hg.LO) == 0
    # every finite bucket's own midpoint maps back to itself
    for i in range(hg.NUM_BUCKETS):
        lo, hi = hg.bucket_bounds(i)
        mid = (lo * hi) ** 0.5
        assert hg.bucket_index(mid) == i, i


def test_bucket_bounds_tile_the_range():
    prev_hi = None
    for i in range(hg.NUM_BUCKETS):
        lo, hi = hg.bucket_bounds(i)
        assert lo < hi
        if prev_hi is not None:
            assert lo == pytest.approx(prev_hi, rel=1e-12)
        prev_hi = hi
    assert hg.bucket_bounds(0)[0] == pytest.approx(hg.LO)
    assert prev_hi == pytest.approx(hg.HI, rel=1e-9)


# ------------------------------------------------------------ quantiles
def test_empty_and_single_sample():
    h = Histogram()
    assert h.count == 0
    assert h.quantile(0.5) is None
    assert h.quantiles_ms() == {}
    h.record(0.0105)
    assert h.count == 1
    # every quantile of a single sample is that sample (within bound)
    for p in (0.0, 0.5, 0.99, 1.0):
        assert h.quantile(p) == pytest.approx(0.0105, rel=hg.REL_ERROR)


def test_quantile_bad_p_raises():
    h = Histogram()
    h.record(0.01)
    with pytest.raises(ValueError):
        h.quantile(-0.1)
    with pytest.raises(ValueError):
        h.quantile(1.5)


def test_sentinel_buckets_answer_their_edge():
    h = Histogram()
    for _ in range(10):
        h.record(1e-9)        # below LO
    for _ in range(10):
        h.record(1000.0)      # above HI
    assert h.quantile(0.1) == pytest.approx(hg.LO)
    assert h.quantile(0.9) == pytest.approx(hg.HI)


@pytest.mark.parametrize("dist", ["loguniform", "bimodal"])
def test_quantile_error_bound_vs_numpy(dist):
    rs = np.random.RandomState(7)
    if dist == "loguniform":
        # latencies spread over 1µs..10s uniformly in log space
        samples = 10.0 ** rs.uniform(-6, 1, 20000)
    else:
        # fast path ~2ms + slow tail ~800ms — the shape SLO p99s care
        # about; a mean-only timer reads ~80ms and misses both modes
        fast = 10.0 ** rs.normal(np.log10(2e-3), 0.1, 18000)
        slow = 10.0 ** rs.normal(np.log10(0.8), 0.1, 2000)
        samples = np.concatenate([fast, slow])
    h = Histogram()
    for s in samples:
        h.record(float(s))
    assert h.count == len(samples)
    for p in (0.5, 0.9, 0.95, 0.99):
        # nearest-rank percentile (method="lower") matches the bucketed
        # ceil-rank scan, so the bound is the pure bucket-midpoint error
        # — linear interpolation would smear across the bimodal gap
        true = float(np.percentile(samples, 100.0 * p, method="lower"))
        got = h.quantile(p)
        assert got == pytest.approx(true, rel=hg.REL_ERROR + 0.01), \
            (dist, p, true, got)


def test_quantiles_ms_keys():
    h = Histogram()
    for ms in (1, 2, 5, 10, 100):
        for _ in range(10):
            h.record(ms / 1000.0)
    q = h.quantiles_ms()
    assert set(q) == {"p50", "p95", "p99"}
    assert q["p50"] <= q["p95"] <= q["p99"]
    assert q["p50"] == pytest.approx(5.0, rel=hg.REL_ERROR + 0.01)


# --------------------------------------------------------------- merge
def _random_hist(seed, n=500):
    rs = np.random.RandomState(seed)
    h = Histogram()
    for s in 10.0 ** rs.uniform(-6, 1, n):
        h.record(float(s))
    return h


def test_merge_commutative_and_associative():
    a, b, c = _random_hist(1), _random_hist(2), _random_hist(3)
    ab = Histogram().merge(a).merge(b)
    ba = Histogram().merge(b).merge(a)
    assert ab.to_dict() == ba.to_dict()
    ab_c = Histogram().merge(ab).merge(c)
    a_bc = Histogram().merge(a).merge(
        Histogram().merge(b).merge(c))
    assert ab_c.to_dict() == a_bc.to_dict()
    assert ab_c.count == a.count + b.count + c.count


def test_merge_accepts_wire_dict_and_preserves_quantiles():
    a, b = _random_hist(4), _random_hist(5)
    merged = Histogram().merge(a.to_dict()).merge(b.to_dict())
    # merged quantiles == quantiles of the pooled samples' histogram
    pooled = Histogram().merge(a).merge(b)
    for p in (0.5, 0.95, 0.99):
        assert merged.quantile(p) == pooled.quantile(p)


def test_merge_bucket_maps_matches_histogram_merge():
    a, b = _random_hist(6), _random_hist(8)
    da, db = a.to_dict()["buckets"], b.to_dict()["buckets"]
    m = hg.merge_bucket_maps(da, db, None, {})
    assert m == Histogram().merge(a).merge(b).to_dict()["buckets"]
    q = hg.quantiles_from_buckets(m)
    assert set(q) == {"p50", "p95", "p99"}
    assert hg.quantiles_from_buckets({}) == {}


def test_merge_drops_out_of_range_buckets():
    # a corrupt wire snapshot must not index outside the fixed array
    h = Histogram.from_dict(
        {"v": 1, "buckets": {"0": 3, "97": 2, "500": 9, "-4": 1}})
    assert h.count == 5


# -------------------------------------------------------- thread-safety
def test_concurrent_record_loses_nothing():
    h = Histogram()
    N, T = 5000, 8
    vals = [1e-4, 1e-3, 1e-2, 1e-1]

    def work(k):
        v = vals[k % len(vals)]
        for _ in range(N):
            h.record(v)

    threads = [threading.Thread(target=work, args=(k,)) for k in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.count == N * T
    buckets = h.to_dict()["buckets"]
    # each value hit exactly one bucket, T/len(vals) workers each
    assert sorted(buckets.values()) == [2 * N] * 4


# ------------------------------------------------- delta encoding + RPC
def test_delta_since_is_sparse_and_exact():
    h = Histogram()
    h.record(0.001)
    h.record(0.001)
    snap = h.to_dict()["buckets"]
    assert h.delta_since(snap) == {}
    h.record(0.001)
    h.record(0.5)
    d = h.delta_since(snap)
    assert sum(d.values()) == 2
    assert hg.merge_bucket_maps(snap, d) == h.to_dict()["buckets"]


def test_delta_round_trip_through_framed_pickle_rpc():
    """The fleet wire path end to end: a 'replica' records latencies,
    ships sparse bucket DELTAS over the real framed-pickle RPC, and the
    'router' folds them — the folded rollup must equal the replica's
    full histogram no matter how the increments were windowed."""
    from mxnet_tpu.serving.fleet.rpc import RpcServer, RpcClient

    replica_hist = Histogram()
    shipped = {"last": {}}
    lock = threading.Lock()

    def snapshot():
        with lock:
            d = replica_hist.delta_since(shipped["last"])
            shipped["last"] = replica_hist.to_dict()["buckets"]
        return {"hist": {"t.req": d}}

    server = RpcServer({"health": snapshot}).start()
    cli = RpcClient(server.addr, timeout_s=10.0)
    try:
        rs = np.random.RandomState(11)
        folded = {}
        for _window in range(5):
            for s in 10.0 ** rs.uniform(-4, 0, 200):
                replica_hist.record(float(s))
            tel = cli.call("health")
            folded = hg.merge_bucket_maps(folded,
                                          tel["hist"].get("t.req"))
        # the clock handshake measured an offset on connect, too
        assert cli.clock_offset_s is not None
        assert abs(cli.clock_offset_s) < 5.0  # same host, same clock
        assert cli.remote_pid is not None
    finally:
        cli.close()
        server.stop()
    assert folded == replica_hist.to_dict()["buckets"]
    assert sum(folded.values()) == 1000
    for p in (0.5, 0.99):
        assert hg.quantiles_from_buckets(folded)["p%g" % (p * 100)] \
            == pytest.approx(replica_hist.quantile(p) * 1000.0)
