"""Training-side C ABI (src/c_api.cc; reference include/mxnet/c_api.h's
imperative slice). The done-criterion test: a real C program binds LeNet
from symbol JSON through MXTrainExecutorCreate, runs forward/backward, and
applies sgd_update in place via MXImperativeInvokeByName — the loss it
computes in C must drop. KVStore init/push/pull round-trips through the
same ABI."""
import os
import subprocess

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import c_api
from mxnet_tpu.models import lenet

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

C_TRAIN = r"""
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include "mxtpu/c_api.h"

static unsigned long rng_state = 12345;
static float frand(void) {  /* xorshift in [-0.5, 0.5) */
  rng_state ^= rng_state << 13; rng_state ^= rng_state >> 7;
  rng_state ^= rng_state << 17;
  return (float)((double)(rng_state % 100000) / 100000.0 - 0.5);
}

static int fill(NDArrayHandle h, float scale) {
  mx_uint ndim; const mx_uint* shp;
  if (MXNDArrayGetShape(h, &ndim, &shp)) return -1;
  size_t n = 1; for (mx_uint i = 0; i < ndim; ++i) n *= shp[i];
  float* buf = (float*)malloc(n * sizeof(float));
  for (size_t i = 0; i < n; ++i) buf[i] = frand() * scale;
  int rc = MXNDArraySyncCopyFromCPU(h, buf, n);
  free(buf);
  return rc;
}

#define CHECK(x) do { if (x) { \
  fprintf(stderr, "%s failed: %s\n", #x, MXGetLastError()); return 1; } \
} while (0)

int main(int argc, char** argv) {
  /* argv: lenet-symbol.json */
  FILE* f = fopen(argv[1], "rb");
  fseek(f, 0, SEEK_END); long js = ftell(f); fseek(f, 0, SEEK_SET);
  char* json = (char*)malloc(js + 1);
  if (fread(json, 1, js, f) != (size_t)js) return 10;
  json[js] = 0; fclose(f);

  enum { B = 8, NCLS = 10 };
  const char* keys[] = {"data", "softmax_label"};
  mx_uint indptr[] = {0, 4, 5};
  mx_uint shapes[] = {B, 1, 28, 28, B};
  ExecutorHandle ex = NULL;
  CHECK(MXTrainExecutorCreate(json, 2, keys, indptr, shapes, &ex));

  /* deterministic init of every argument */
  mx_uint n_args; const char** arg_names;
  CHECK(MXExecutorListArguments(ex, &n_args, &arg_names));
  float label[B];
  for (int i = 0; i < B; ++i) label[i] = (float)(i % NCLS);
  for (mx_uint i = 0; i < n_args; ++i) {
    NDArrayHandle a;
    CHECK(MXExecutorGetArg(ex, arg_names[i], &a));
    if (!strcmp(arg_names[i], "softmax_label")) {
      CHECK(MXNDArraySyncCopyFromCPU(a, label, B));
    } else if (!strcmp(arg_names[i], "data")) {
      CHECK(fill(a, 1.0f));
    } else {
      CHECK(fill(a, 0.2f));
    }
    MXNDArrayFree(a);
  }

  float first = 0.0f, last = 0.0f;
  const char* okeys[] = {"lr"};
  const char* ovals[] = {"0.01"};
  for (int step = 0; step < 10; ++step) {
    CHECK(MXExecutorForward(ex, 1));
    NDArrayHandle out;
    CHECK(MXExecutorGetOutput(ex, 0, &out));
    float prob[B * NCLS];
    CHECK(MXNDArraySyncCopyToCPU(out, prob, B * NCLS));
    MXNDArrayFree(out);
    float loss = 0.0f;
    for (int i = 0; i < B; ++i)
      loss += -logf(prob[i * NCLS + (int)label[i]] + 1e-9f);
    loss /= B;
    if (step == 0) first = loss;
    last = loss;
    printf("step %d loss %.6f\n", step, loss);
    CHECK(MXExecutorBackward(ex, 0, NULL));
    for (mx_uint i = 0; i < n_args; ++i) {
      /* the header's idiom: grad is NULL for data/label inputs, so the
         update loop needs no name knowledge */
      NDArrayHandle w, g;
      CHECK(MXExecutorGetArg(ex, arg_names[i], &w));
      CHECK(MXExecutorGetGrad(ex, arg_names[i], &g));
      if (g) {  /* in-place sgd_update through the imperative ABI */
        NDArrayHandle ins[2] = {w, g};
        NDArrayHandle* outs_p = &w;
        int n_out = 1;
        CHECK(MXImperativeInvokeByName("sgd_update", 2, ins, &n_out,
                                       &outs_p, 1, okeys, ovals));
        MXNDArrayFree(g);
      }
      MXNDArrayFree(w);
    }
  }
  CHECK(MXNDArrayWaitAll());

  /* KVStore round-trip: init a key, push a delta, pull the reduced value */
  KVStoreHandle kv;
  CHECK(MXKVStoreCreate("local", &kv));
  mx_uint vshape[] = {4};
  NDArrayHandle v0, delta, got;
  CHECK(MXNDArrayCreate(vshape, 1, 1, 0, 0, &v0));
  CHECK(MXNDArrayCreate(vshape, 1, 1, 0, 0, &delta));
  CHECK(MXNDArrayCreate(vshape, 1, 1, 0, 0, &got));
  float dbuf[4] = {1.0f, 2.0f, 3.0f, 4.0f};
  CHECK(MXNDArraySyncCopyFromCPU(delta, dbuf, 4));
  int kv_keys[] = {3};
  CHECK(MXKVStoreInit(kv, 1, kv_keys, &v0));
  CHECK(MXKVStorePush(kv, 1, kv_keys, &delta, 0));
  CHECK(MXKVStorePull(kv, 1, kv_keys, &got, 0));
  float gbuf[4];
  CHECK(MXNDArraySyncCopyToCPU(got, gbuf, 4));
  for (int i = 0; i < 4; ++i) {
    if (fabsf(gbuf[i] - dbuf[i]) > 1e-5f) {
      fprintf(stderr, "kvstore pull mismatch at %d: %f vs %f\n",
              i, gbuf[i], dbuf[i]);
      return 6;
    }
  }
  MXNDArrayFree(v0); MXNDArrayFree(delta); MXNDArrayFree(got);
  MXKVStoreFree(kv);
  MXExecutorFree(ex);

  printf("first %.6f last %.6f\n", first, last);
  return last < first * 0.9f ? 0 : 7;
}
"""


@pytest.fixture(scope="module")
def libc_api():
    path = c_api.build()
    if path is None:
        pytest.skip("no toolchain for libmxtpu_c.so")
    return path


@pytest.mark.slow
def test_c_program_trains_lenet(tmp_path, libc_api):
    net = lenet.get_symbol(num_classes=10)
    json_path = tmp_path / "lenet-symbol.json"
    json_path.write_text(net.tojson())

    csrc = tmp_path / "train.c"
    csrc.write_text(C_TRAIN)
    exe = tmp_path / "train"
    subprocess.run(
        ["gcc", str(csrc), "-I", os.path.join(ROOT, "include"),
         "-o", str(exe), str(libc_api),
         "-Wl,-rpath," + os.path.dirname(str(libc_api)), "-lm"],
        check=True, capture_output=True)

    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("MXNET_DEFAULT_CONTEXT", "cpu")
    r = subprocess.run([str(exe), str(json_path)], capture_output=True,
                       text=True, timeout=600, env=env)
    assert r.returncode == 0, (r.returncode, r.stdout[-500:], r.stderr[-800:])
    losses = [float(l.split()[-1]) for l in r.stdout.splitlines()
              if l.startswith("step")]
    assert len(losses) == 10
    assert losses[-1] < losses[0] * 0.9, losses


def test_imperative_invoke_allocating_mode(libc_api):
    """The Python-side glue for *num_outputs == 0 (library-allocated
    outputs): invoke through the glue layer directly."""
    from mxnet_tpu.c_api import invoke

    a = mx.nd.array(np.array([[1.0, 2.0], [3.0, 4.0]], "f"))
    b = mx.nd.array(np.array([[10.0, 20.0], [30.0, 40.0]], "f"))
    (out,) = invoke("elemwise_add", [a, b], [], [], None)
    np.testing.assert_allclose(out.asnumpy(),
                               [[11.0, 22.0], [33.0, 44.0]])
    (out2,) = invoke("sgd_update", [a, b], ["lr"], ["0.1"], [a])
    assert out2 is a
    np.testing.assert_allclose(a.asnumpy(),
                               [[0.0, 0.0], [0.0, 0.0]], atol=1e-6)


CPP_TRAIN = r"""
#include <cmath>
#include <cstdio>
#include <fstream>
#include <random>
#include <sstream>
#include "mxtpu/cpp/trainer.hpp"

int main(int argc, char** argv) {
  std::ifstream jf(argv[1]);
  std::stringstream ss; ss << jf.rdbuf();
  const int B = 8, NCLS = 10;
  mxtpu::Trainer tr(ss.str(), {{"data", {B, 1, 28, 28}},
                               {"softmax_label", {B}}});
  std::mt19937 rng(7);
  std::uniform_real_distribution<float> u(-0.1f, 0.1f);
  std::vector<float> label(B);
  for (int i = 0; i < B; ++i) label[i] = float(i % NCLS);
  for (const auto& name : tr.ArgNames()) {
    if (name == "softmax_label") { tr.SetArg(name, label); continue; }
    std::vector<float> v(tr.ArgSize(name));
    float s = (name == "data") ? 5.0f : 1.0f;
    for (auto& x : v) x = u(rng) * s;
    tr.SetArg(name, v);
  }
  float first = 0, last = 0;
  for (int step = 0; step < 10; ++step) {
    tr.Forward(true);
    std::vector<float> prob = tr.GetOutput(0);
    float loss = 0;
    for (int i = 0; i < B; ++i)
      loss += -std::log(prob[i * NCLS + int(label[i])] + 1e-9f);
    loss /= B;
    if (step == 0) first = loss;
    last = loss;
    tr.Backward();
    tr.SGDUpdate(0.01f);
  }
  std::printf("first %f last %f\n", first, last);
  if (!(last < first * 0.9f)) return 7;
  // the input must have no gradient (bind contract)
  if (tr.HasGrad("data") || tr.HasGrad("softmax_label")) return 8;
  return 0;
}
"""


@pytest.mark.slow
def test_cpp_trainer_wrapper(tmp_path, libc_api):
    """The header-only C++ RAII trainer (cpp-package training analogue)
    trains LeNet through the same ABI."""
    net = lenet.get_symbol(num_classes=10)
    json_path = tmp_path / "lenet-symbol.json"
    json_path.write_text(net.tojson())
    cpp = tmp_path / "train.cc"
    cpp.write_text(CPP_TRAIN)
    exe = tmp_path / "train_cpp"
    subprocess.run(
        ["g++", "-std=c++17", str(cpp), "-I", os.path.join(ROOT, "include"),
         "-o", str(exe), str(libc_api),
         "-Wl,-rpath," + os.path.dirname(str(libc_api))],
        check=True, capture_output=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("MXNET_DEFAULT_CONTEXT", "cpu")
    r = subprocess.run([str(exe), str(json_path)], capture_output=True,
                       text=True, timeout=600, env=env)
    assert r.returncode == 0, (r.returncode, r.stdout[-300:], r.stderr[-800:])
