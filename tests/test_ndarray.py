"""Imperative NDArray tests, modeled on the reference's
tests/python/unittest/test_ndarray.py (numpy as the oracle)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def test_creation():
    a = mx.nd.zeros((3, 4))
    assert a.shape == (3, 4)
    assert a.dtype == np.float32
    assert np.allclose(a.asnumpy(), 0)
    b = mx.nd.ones((2, 2), dtype=np.float16)
    assert b.dtype == np.float16
    c = mx.nd.full((2, 3), 7.5)
    assert np.allclose(c.asnumpy(), 7.5)
    d = mx.nd.array([[1, 2], [3, 4]])
    assert d.shape == (2, 2)
    assert d.dtype == np.float32
    e = mx.nd.array(np.array([1, 2], dtype=np.int32))
    assert e.dtype == np.int32
    f = mx.nd.arange(0, 10, 2)
    assert np.allclose(f.asnumpy(), [0, 2, 4, 6, 8])


def test_elementwise_binary():
    rng = np.random.RandomState(0)
    x = rng.rand(4, 5).astype(np.float32)
    y = rng.rand(4, 5).astype(np.float32)
    a, b = mx.nd.array(x), mx.nd.array(y)
    assert np.allclose((a + b).asnumpy(), x + y, rtol=1e-5)
    assert np.allclose((a - b).asnumpy(), x - y, rtol=1e-5)
    assert np.allclose((a * b).asnumpy(), x * y, rtol=1e-5)
    assert np.allclose((a / b).asnumpy(), x / y, rtol=1e-5)
    assert np.allclose((a + 2.0).asnumpy(), x + 2, rtol=1e-5)
    assert np.allclose((2.0 - a).asnumpy(), 2 - x, rtol=1e-5)
    assert np.allclose((a**2).asnumpy(), x**2, rtol=1e-5)
    assert np.allclose((-a).asnumpy(), -x, rtol=1e-5)


def test_comparisons():
    x = np.array([[1, 2], [3, 4]], dtype=np.float32)
    a = mx.nd.array(x)
    assert np.allclose((a > 2).asnumpy(), (x > 2).astype(np.float32))
    assert np.allclose((a == 3).asnumpy(), (x == 3).astype(np.float32))


def test_inplace_ops():
    a = mx.nd.ones((2, 3))
    a += 1
    assert np.allclose(a.asnumpy(), 2)
    a *= 3
    assert np.allclose(a.asnumpy(), 6)
    a /= 2
    assert np.allclose(a.asnumpy(), 3)


def test_unary_ops():
    x = np.random.RandomState(1).rand(3, 3).astype(np.float32) + 0.1
    a = mx.nd.array(x)
    assert np.allclose(mx.nd.sqrt(a).asnumpy(), np.sqrt(x), rtol=1e-5)
    assert np.allclose(mx.nd.exp(a).asnumpy(), np.exp(x), rtol=1e-5)
    assert np.allclose(mx.nd.log(a).asnumpy(), np.log(x), rtol=1e-5)
    assert np.allclose(mx.nd.square(a).asnumpy(), x * x, rtol=1e-5)


def test_reductions():
    x = np.random.RandomState(2).rand(3, 4, 5).astype(np.float32)
    a = mx.nd.array(x)
    assert np.allclose(mx.nd.sum(a).asnumpy(), x.sum(), rtol=1e-4)
    assert np.allclose(mx.nd.sum(a, axis=1).asnumpy(), x.sum(axis=1), rtol=1e-4)
    assert np.allclose(a.sum(axis=(0, 2)).asnumpy(), x.sum(axis=(0, 2)), rtol=1e-4)
    assert np.allclose(mx.nd.max(a, axis=0).asnumpy(), x.max(axis=0))
    assert np.allclose(mx.nd.argmax(a, axis=1).asnumpy(), x.argmax(axis=1))


def test_dot():
    rng = np.random.RandomState(3)
    x = rng.rand(4, 5).astype(np.float32)
    y = rng.rand(5, 6).astype(np.float32)
    out = mx.nd.dot(mx.nd.array(x), mx.nd.array(y))
    assert np.allclose(out.asnumpy(), x.dot(y), rtol=1e-4)
    xt = rng.rand(5, 4).astype(np.float32)
    out = mx.nd.dot(mx.nd.array(xt), mx.nd.array(y), transpose_a=True)
    assert np.allclose(out.asnumpy(), xt.T.dot(y), rtol=1e-4)


def test_reshape_and_views():
    a = mx.nd.arange(0, 12).reshape((3, 4))
    assert a.shape == (3, 4)
    b = a.reshape((4, 3))
    assert b.shape == (4, 3)
    # reshape is a view: writes through
    b[:] = 0
    assert np.allclose(a.asnumpy(), 0)


def test_slice_view_write_through():
    a = mx.nd.zeros((4, 3))
    s = a[1:3]
    assert s.shape == (2, 3)
    s[:] = 5
    expect = np.zeros((4, 3), np.float32)
    expect[1:3] = 5
    assert np.allclose(a.asnumpy(), expect)
    a[0] = 9
    expect[0] = 9
    assert np.allclose(a.asnumpy(), expect)
    row = a[2]
    assert row.shape == (3,)
    assert np.allclose(row.asnumpy(), 5)


def test_setitem_array():
    a = mx.nd.zeros((3, 2))
    a[1] = np.array([1.0, 2.0])
    assert np.allclose(a.asnumpy()[1], [1, 2])
    a[:] = np.ones((3, 2))
    assert np.allclose(a.asnumpy(), 1)


def test_copyto_astype():
    a = mx.nd.ones((2, 2))
    b = mx.nd.zeros((2, 2))
    a.copyto(b)
    assert np.allclose(b.asnumpy(), 1)
    c = a.astype(np.float16)
    assert c.dtype == np.float16
    d = a.as_in_context(mx.cpu(1))
    assert d.context == mx.cpu(1)


def test_broadcast_ops():
    x = np.random.rand(3, 1).astype(np.float32)
    y = np.random.rand(1, 4).astype(np.float32)
    out = mx.nd.broadcast_add(mx.nd.array(x), mx.nd.array(y))
    assert np.allclose(out.asnumpy(), x + y, rtol=1e-5)
    out = mx.nd.broadcast_to(mx.nd.array(x), shape=(3, 4))
    assert out.shape == (3, 4)


def test_concat_split():
    x = np.random.rand(2, 3).astype(np.float32)
    y = np.random.rand(2, 3).astype(np.float32)
    out = mx.nd.concatenate([mx.nd.array(x), mx.nd.array(y)], axis=0)
    assert np.allclose(out.asnumpy(), np.concatenate([x, y], 0))
    parts = mx.nd.SliceChannel(mx.nd.array(x), num_outputs=3, axis=1)
    assert len(parts) == 3
    assert parts[0].shape == (2, 1)


def test_save_load(tmp_path):
    fname = str(tmp_path / "arrs.params")
    d = {"w": mx.nd.ones((2, 3)), "b": mx.nd.arange(0, 4)}
    mx.nd.save(fname, d)
    loaded = mx.nd.load(fname)
    assert set(loaded.keys()) == {"w", "b"}
    assert np.allclose(loaded["w"].asnumpy(), 1)
    assert np.allclose(loaded["b"].asnumpy(), [0, 1, 2, 3])
    lst = [mx.nd.zeros((2,))]
    mx.nd.save(fname, lst)
    loaded = mx.nd.load(fname)
    assert isinstance(loaded, list) and len(loaded) == 1


def test_wait_and_scalar():
    a = mx.nd.ones((1,))
    a.wait_to_read()
    assert a.asscalar() == 1.0
    mx.nd.waitall()


def test_take_onehot():
    w = np.random.rand(10, 4).astype(np.float32)
    idx = np.array([1, 3, 5], dtype=np.float32)
    out = mx.nd.Embedding(mx.nd.array(idx), mx.nd.array(w), input_dim=10, output_dim=4)
    assert np.allclose(out.asnumpy(), w[[1, 3, 5]])
    oh = mx.nd.one_hot(mx.nd.array(idx), depth=10)
    assert oh.shape == (3, 10)
    assert np.allclose(oh.asnumpy().argmax(1), [1, 3, 5])


def test_random_ops():
    mx.random.seed(42)
    a = mx.random.uniform(0, 1, shape=(1000,))
    m = a.asnumpy().mean()
    assert 0.4 < m < 0.6
    b = mx.random.normal(0, 1, shape=(1000,))
    assert abs(b.asnumpy().mean()) < 0.2
    mx.random.seed(42)
    a2 = mx.random.uniform(0, 1, shape=(1000,))
    assert np.allclose(a.asnumpy(), a2.asnumpy())
