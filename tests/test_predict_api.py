"""C predict ABI (src/predict_api.cc; reference: c_predict_api.h).

Oracle: a real C program compiles against include/mxtpu/c_predict_api.h,
links libmxtpu_predict.so, runs MXPredCreate/SetInput/Forward/GetOutput on
a checkpoint saved by the Python API, and its printed probabilities match
the Python predictor's bit-for-bit (same XLA executable underneath)."""
import os
import subprocess
import sys
import sysconfig

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import predict_api

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

C_SMOKE = r"""
#include <stdio.h>
#include <stdlib.h>
#include "mxtpu/c_predict_api.h"

int main(int argc, char** argv) {
  /* argv: symbol.json params.bin input.bin n_in out.bin */
  FILE* f = fopen(argv[1], "rb");
  fseek(f, 0, SEEK_END); long js = ftell(f); fseek(f, 0, SEEK_SET);
  char* json = (char*)malloc(js + 1);
  if (fread(json, 1, js, f) != (size_t)js) return 10;
  json[js] = 0; fclose(f);

  f = fopen(argv[2], "rb");
  fseek(f, 0, SEEK_END); long ps = ftell(f); fseek(f, 0, SEEK_SET);
  void* params = malloc(ps);
  if (fread(params, 1, ps, f) != (size_t)ps) return 11;
  fclose(f);

  mx_uint n_in = (mx_uint)atoi(argv[4]);
  f = fopen(argv[3], "rb");
  float* input = (float*)malloc(n_in * sizeof(float));
  if (fread(input, sizeof(float), n_in, f) != n_in) return 12;
  fclose(f);

  const char* keys[] = {"data"};
  mx_uint indptr[] = {0, 2};
  mx_uint shape[] = {4, 8};  /* batch 4, feat 8 */
  PredictorHandle h = NULL;
  if (MXPredCreate(json, params, (int)ps, 1, 0, 1, keys, indptr, shape, &h)) {
    fprintf(stderr, "create: %s\n", MXGetLastError()); return 1;
  }
  if (MXPredSetInput(h, "data", input, n_in)) {
    fprintf(stderr, "set: %s\n", MXGetLastError()); return 2;
  }
  if (MXPredForward(h)) {
    fprintf(stderr, "fwd: %s\n", MXGetLastError()); return 3;
  }
  mx_uint* oshape; mx_uint ondim;
  if (MXPredGetOutputShape(h, 0, &oshape, &ondim)) return 4;
  mx_uint total = 1;
  for (mx_uint i = 0; i < ondim; ++i) total *= oshape[i];
  float* out = (float*)malloc(total * sizeof(float));
  if (MXPredGetOutput(h, 0, out, total)) {
    fprintf(stderr, "get: %s\n", MXGetLastError()); return 5;
  }
  f = fopen(argv[5], "wb");
  fwrite(&ondim, sizeof(mx_uint), 1, f);
  fwrite(oshape, sizeof(mx_uint), ondim, f);
  fwrite(out, sizeof(float), total, f);
  fclose(f);
  MXPredFree(h);
  return 0;
}
"""


@pytest.fixture(scope="module")
def libpredict():
    path = predict_api.build()
    if path is None:
        pytest.skip("no toolchain for libmxtpu_predict.so")
    return path


def test_c_program_matches_python_predictor(tmp_path, libpredict):
    # 1) save a small net + params through the Python API
    rs = np.random.RandomState(0)
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=5, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    w = rs.randn(5, 8).astype("float32") * 0.3
    b = rs.randn(5).astype("float32") * 0.1
    json_path = tmp_path / "m-symbol.json"
    json_path.write_text(net.tojson())
    params_path = tmp_path / "m.params"
    mx.nd.save(str(params_path), {"arg:fc_weight": mx.nd.array(w),
                                  "arg:fc_bias": mx.nd.array(b)})
    x = rs.rand(4, 8).astype("float32")
    (tmp_path / "input.bin").write_bytes(x.tobytes())

    # 2) compile the C smoke program against the public header
    csrc = tmp_path / "smoke.c"
    csrc.write_text(C_SMOKE)
    exe = tmp_path / "smoke"
    subprocess.run(
        ["gcc", str(csrc), "-I", os.path.join(ROOT, "include"),
         "-o", str(exe), str(libpredict),
         "-Wl,-rpath," + os.path.dirname(str(libpredict))],
        check=True, capture_output=True)

    # 3) run it (PYTHONPATH so the embedded interpreter finds the package)
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("MXNET_DEFAULT_CONTEXT", "cpu")
    out_bin = tmp_path / "out.bin"
    r = subprocess.run(
        [str(exe), str(json_path), str(params_path),
         str(tmp_path / "input.bin"), str(x.size), str(out_bin)],
        capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 0, r.stderr[-800:]

    blob = out_bin.read_bytes()
    ndim = np.frombuffer(blob[:4], np.uint32)[0]
    shape = tuple(np.frombuffer(blob[4:4 + 4 * ndim], np.uint32))
    got = np.frombuffer(blob[4 + 4 * ndim:], np.float32).reshape(shape)

    # 4) the Python predictor is the oracle
    from mxnet_tpu.predictor import Predictor

    pred = Predictor(json_path.read_text(), params_path.read_bytes(),
                     {"data": (4, 8)})
    pred.forward(data=x)
    want = pred.get_output(0)
    assert shape == want.shape
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


CPP_SMOKE = r"""
#include <cstdio>
#include <fstream>
#include <sstream>
#include "mxtpu/cpp/predictor.hpp"

static std::string slurp(const char* p, bool binary) {
  std::ifstream f(p, binary ? std::ios::binary : std::ios::in);
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

int main(int argc, char** argv) {
  try {
    mxtpu::Predictor pred(slurp(argv[1], false), slurp(argv[2], true),
                          {{"data", {4, 8}}});
    std::string in = slurp(argv[3], true);
    std::vector<float> x(reinterpret_cast<const float*>(in.data()),
                         reinterpret_cast<const float*>(in.data()) + 32);
    pred.SetInput("data", x);
    pred.Forward();
    auto out = pred.GetOutput(0);
    // move + reshape to batch 1
    mxtpu::Predictor small = pred.Reshape({{"data", {1, 8}}});
    small.SetInput("data", std::vector<float>(x.begin(), x.begin() + 8));
    small.Forward();
    auto out1 = small.GetOutput(0);
    std::ofstream f(argv[4], std::ios::binary);
    f.write(reinterpret_cast<const char*>(out.data()),
            out.size() * sizeof(float));
    f.write(reinterpret_cast<const char*>(out1.data()),
            out1.size() * sizeof(float));
    return 0;
  } catch (const std::exception& e) {
    fprintf(stderr, "cpp error: %s\n", e.what());
    return 1;
  }
}
"""


def test_cpp_wrapper_matches_python(tmp_path, libpredict):
    """The header-only C++ RAII wrapper (cpp-package analogue) drives the
    same checkpoint, with Reshape returning an independent predictor."""
    rs = np.random.RandomState(1)
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=5, name="fc"),
        name="softmax")
    w = rs.randn(5, 8).astype("float32") * 0.3
    (tmp_path / "m-symbol.json").write_text(net.tojson())
    mx.nd.save(str(tmp_path / "m.params"),
               {"arg:fc_weight": mx.nd.array(w),
                "arg:fc_bias": mx.nd.zeros((5,))})
    x = rs.rand(4, 8).astype("float32")
    (tmp_path / "in.bin").write_bytes(x.tobytes())

    cpp = tmp_path / "smoke.cc"
    cpp.write_text(CPP_SMOKE)
    exe = tmp_path / "smokecc"
    subprocess.run(
        ["g++", "-std=c++17", str(cpp), "-I", os.path.join(ROOT, "include"),
         "-o", str(exe), str(libpredict),
         "-Wl,-rpath," + os.path.dirname(str(libpredict))],
        check=True, capture_output=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("MXNET_DEFAULT_CONTEXT", "cpu")
    out_bin = tmp_path / "o.bin"
    r = subprocess.run(
        [str(exe), str(tmp_path / "m-symbol.json"), str(tmp_path / "m.params"),
         str(tmp_path / "in.bin"), str(out_bin)],
        capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 0, r.stderr[-800:]
    blob = np.frombuffer(out_bin.read_bytes(), np.float32)
    got4, got1 = blob[:20].reshape(4, 5), blob[20:].reshape(1, 5)

    from mxnet_tpu.predictor import Predictor

    pr = Predictor((tmp_path / "m-symbol.json").read_text(),
                   (tmp_path / "m.params").read_bytes(), {"data": (4, 8)})
    pr.forward(data=x)
    np.testing.assert_allclose(got4, pr.get_output(0), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got1, pr.get_output(0)[:1], rtol=1e-4, atol=1e-5)
