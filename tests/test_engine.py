"""Engine tests (reference strategy: tests/cpp/threaded_engine_test.cc —
random read/write workloads through every engine type, checking the var
discipline: writers serialize in push order, readers run between writes)."""
import random
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import engine as eng


@pytest.fixture(params=["native", "python", "naive"])
def make_engine(request):
    def factory():
        if request.param == "naive":
            return eng.NaiveEngine()
        e = eng.ThreadedEngine(num_workers=4)
        if request.param == "native":
            if not e.native:
                pytest.skip("native engine lib unavailable")
            return e
        # force the python fallback path
        py = eng._PythonThreadedEngine(4)
        return py

    return factory


def test_native_lib_builds():
    e = eng.ThreadedEngine(num_workers=2)
    assert e.native, "src/engine_native.cc failed to build"


def test_writers_serialize_in_push_order(make_engine):
    e = make_engine()
    v = e.new_variable()
    log = []
    for i in range(50):
        e.push((lambda i=i: log.append(i)), const_vars=[], mutable_vars=[v])
    e.wait_for_var(v)
    assert log == list(range(50))


def test_reader_sees_preceding_writes(make_engine):
    e = make_engine()
    v = e.new_variable()
    state = {"n": 0}
    observed = []

    def writer():
        time.sleep(0.001)
        state["n"] += 1

    for i in range(10):
        e.push(writer, const_vars=[], mutable_vars=[v])
        # reader pushed after the (i+1)-th writer, before the next one:
        # must observe exactly i+1 completed writes
        e.push((lambda i=i: observed.append((i, state["n"]))),
               const_vars=[v], mutable_vars=[])
    e.wait_for_all()
    assert observed == [(i, i + 1) for i in range(10)]


def test_readers_run_concurrently(make_engine):
    e = make_engine()
    if isinstance(e, eng.NaiveEngine):
        pytest.skip("naive engine is serial by design")
    v = e.new_variable()
    barrier = threading.Barrier(3, timeout=10)

    def reader():
        barrier.wait()  # deadlocks unless ≥3 readers overlap

    for _ in range(3):
        e.push(reader, const_vars=[v], mutable_vars=[])
    e.wait_for_all()


def test_disjoint_vars_run_independently(make_engine):
    e = make_engine()
    va, vb = e.new_variable(), e.new_variable()
    log_a, log_b = [], []
    for i in range(20):
        e.push((lambda i=i: log_a.append(i)), mutable_vars=[va])
        e.push((lambda i=i: log_b.append(i)), mutable_vars=[vb])
    e.wait_for_all()
    assert log_a == list(range(20)) and log_b == list(range(20))


def test_random_workload_dependency_consistency(make_engine):
    """Random DAG of ops over 6 vars; each writer appends (its id) to every
    var it mutates, each op snapshots its const vars. The var discipline
    implies per-var logs are exactly the writers in push order, and every
    reader sees a prefix-consistent snapshot."""
    e = make_engine()
    rng = random.Random(0)
    n_vars, n_ops = 6, 120
    vars_ = [e.new_variable() for _ in range(n_vars)]
    logs = {v: [] for v in vars_}
    expected = {v: [] for v in vars_}
    snapshots = []

    for op_id in range(n_ops):
        n_mut = rng.randint(0, 2)
        muts = rng.sample(vars_, n_mut)
        consts = [v for v in rng.sample(vars_, rng.randint(0, 3)) if v not in muts]
        expected_counts = {v: len(expected[v]) for v in consts}
        for v in muts:
            expected[v].append(op_id)

        def fn(op_id=op_id, muts=tuple(muts), consts=tuple(consts),
               expected_counts=dict(expected_counts)):
            snap = {v: len(logs[v]) for v in consts}
            for v in muts:
                logs[v].append(op_id)
            snapshots.append((op_id, snap, expected_counts))

        e.push(fn, const_vars=consts, mutable_vars=muts)
    e.wait_for_all()

    for v in vars_:
        assert logs[v] == expected[v]
    for op_id, snap, want in snapshots:
        assert snap == want, "op %d read stale/future state" % op_id


def test_wait_for_var_blocks_until_drained(make_engine):
    e = make_engine()
    v = e.new_variable()
    done = []

    def slow():
        time.sleep(0.05)
        done.append(1)

    e.push(slow, mutable_vars=[v])
    e.wait_for_var(v)
    assert done == [1]


def test_engine_error_surfaces():
    e = eng.ThreadedEngine(num_workers=2)
    v = e.new_variable()
    e.push(lambda: 1 / 0, mutable_vars=[v])
    from mxnet_tpu.base import MXNetError

    with pytest.raises(MXNetError):
        e.wait_for_all()


def test_engine_type_selection(monkeypatch):
    monkeypatch.setenv("MXNET_ENGINE_TYPE", "NaiveEngine")
    monkeypatch.setattr(eng, "_engine", None)
    assert isinstance(eng.get(), eng.NaiveEngine)
    e = eng.set_engine_type("ThreadedEnginePerDevice")
    assert isinstance(e, eng.ThreadedEngine)
    monkeypatch.setattr(eng, "_engine", None)


def test_checkpoint_writes_ride_the_engine(tmp_path):
    """save_checkpoint pushes the disk write through the engine (the
    facade's claimed IO role is load-bearing): find/load on the same prefix
    waits for the pending write and round-trips the exact values."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import model

    prefix = str(tmp_path / "ck")
    net = mx.sym.Variable("w")
    for epoch in (1, 2, 3):
        model.save_checkpoint(
            prefix, epoch, net,
            {"w": mx.nd.array(np.full((4,), float(epoch), "f"))}, {})
    assert model.find_last_checkpoint(prefix) == 3  # waits for the writes
    _, args, _ = model.load_checkpoint(prefix, 3)
    np.testing.assert_allclose(args["w"].asnumpy(), 3.0)
    _, args1, _ = model.load_checkpoint(prefix, 1)
    np.testing.assert_allclose(args1["w"].asnumpy(), 1.0)
