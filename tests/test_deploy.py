"""Predictor (c_predict_api parity), rtc Pallas kernels, multisample ops,
PythonModule, checkpoint auto-resume, failure-detection probe."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models


def test_predictor_matches_module(tmp_path):
    """Save a trained-ish lenet checkpoint, reload through Predictor, and
    match Module.predict outputs (reference: c_predict_api flow)."""
    net = models.get_symbol("lenet", num_classes=3)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 1, 28, 28))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params(initializer=mx.init.Xavier())
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 1)

    rs = np.random.RandomState(0)
    x = rs.rand(2, 1, 28, 28).astype("float32")

    from mxnet_tpu.predictor import Predictor

    with open(prefix + "-symbol.json") as f:
        sym_json = f.read()
    with open(prefix + "-0001.params", "rb") as f:
        params = f.read()
    pred = Predictor(sym_json, params, {"data": (2, 1, 28, 28)}, ctx=mx.cpu())
    pred.forward(data=x)
    out = pred.get_output(0)

    batch = mx.io.DataBatch(data=[mx.nd.array(x)], label=None, pad=0)
    mod_infer = mx.mod.Module(net, context=mx.cpu(), label_names=None)
    mod_infer.bind(data_shapes=[("data", (2, 1, 28, 28))], for_training=False)
    arg_p, aux_p = mod.get_params()
    mod_infer.set_params(arg_p, aux_p, allow_missing=True)
    mod_infer.forward(batch, is_train=False)
    want = mod_infer.get_outputs()[0].asnumpy()
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)
    assert pred.num_outputs == 1


def test_predictor_reshape(tmp_path):
    net = models.get_symbol("mlp", num_classes=4)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 16))], label_shapes=[("softmax_label", (2,))])
    mod.init_params(initializer=mx.init.Xavier())
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 0)

    from mxnet_tpu.predictor import Predictor

    pred = Predictor(open(prefix + "-symbol.json").read(),
                     open(prefix + "-0000.params", "rb").read(),
                     {"data": (2, 16)}, ctx=mx.cpu())
    pred.forward(data=np.zeros((2, 16), "float32"))
    first = pred.get_output(0)
    pred.reshape({"data": (5, 16)})
    pred.forward(data=np.zeros((5, 16), "float32"))
    second = pred.get_output(0)
    assert second.shape == (5, 4)
    np.testing.assert_allclose(second[0], first[0], rtol=1e-5)


def test_rtc_pallas_kernel():
    src = """
def kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:] * 2.0 + 1.0
"""
    k = mx.rtc.Rtc("axpb", src)
    x = mx.nd.array(np.arange(8, dtype="float32").reshape(2, 4))
    (y,) = k.push([x], out_shapes=[(2, 4)])
    np.testing.assert_allclose(y.asnumpy(), x.asnumpy() * 2 + 1)


def test_rtc_two_inputs():
    src = """
def kernel(a_ref, b_ref, o_ref):
    o_ref[:] = a_ref[:] + b_ref[:] * 3.0
"""
    k = mx.rtc.Rtc("fma", src)
    a = mx.nd.ones((4, 4))
    b = mx.nd.ones((4, 4))
    (y,) = k.push([a, b], out_shapes=[(4, 4)])
    np.testing.assert_allclose(y.asnumpy(), 4.0)


def test_rtc_bad_source_raises():
    with pytest.raises(mx.MXNetError):
        mx.rtc.Rtc("broken", "def kernel(x_ref, o_ref:\n  pass")


def test_multisample_moments():
    rs = np.random.RandomState(0)
    mu = mx.nd.array(np.array([0.0, 5.0], "float32"))
    sigma = mx.nd.array(np.array([1.0, 0.1], "float32"))
    s = mx.nd.sample_normal(mu, sigma, shape=(4000,)).asnumpy()
    assert s.shape == (2, 4000)
    assert abs(s[0].mean()) < 0.1 and abs(s[1].mean() - 5.0) < 0.05
    assert abs(s[0].std() - 1.0) < 0.1 and abs(s[1].std() - 0.1) < 0.02

    lam = mx.nd.array(np.array([1.0, 8.0], "float32"))
    p = mx.nd.sample_poisson(lam, shape=(4000,)).asnumpy()
    assert abs(p[0].mean() - 1.0) < 0.2 and abs(p[1].mean() - 8.0) < 0.5


def test_multisample_empty_shape_matches_params():
    # reference semantics (multisample_op.h): empty shape → output == params
    low = mx.nd.array(np.zeros(3, "float32"))
    high = mx.nd.array(np.ones(3, "float32"))
    s = mx.nd.sample_uniform(low, high)
    assert s.shape == (3,)


def test_rtc_more_outputs_than_inputs():
    src = """
def kernel(x_ref, o1_ref, o2_ref):
    o1_ref[:] = x_ref[:] + 1.0
    o2_ref[:] = x_ref[:] - 1.0
"""
    k = mx.rtc.Rtc("split", src)
    x = mx.nd.ones((2, 2))
    y1, y2 = k.push([x], out_shapes=[(2, 2), (2, 2)])
    np.testing.assert_allclose(y1.asnumpy(), 2.0)
    np.testing.assert_allclose(y2.asnumpy(), 0.0)


def test_python_loss_module():
    from mxnet_tpu.module import PythonLossModule

    mod = PythonLossModule(grad_func=lambda scores, labels:
                           scores.asnumpy() - labels.asnumpy())
    mod.bind(data_shapes=[("data", (4, 3))], label_shapes=[("softmax_label", (4, 3))])
    mod.init_params()
    rs = np.random.RandomState(0)
    scores = rs.rand(4, 3).astype("float32")
    labels = rs.rand(4, 3).astype("float32")
    batch = mx.io.DataBatch(data=[mx.nd.array(scores)],
                            label=[mx.nd.array(labels)], pad=0)
    mod.forward(batch, is_train=True)
    np.testing.assert_allclose(mod.get_outputs()[0].asnumpy(), scores)
    mod.backward()
    np.testing.assert_allclose(mod.get_input_grads()[0].asnumpy(),
                               scores - labels, rtol=1e-6)


def test_resume_or_init(tmp_path):
    prefix = str(tmp_path / "ck")
    begin, args, auxs = mx.model.resume_or_init(prefix)
    assert (begin, args, auxs) == (0, None, None)

    net = models.get_symbol("mlp", num_classes=2)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 8))], label_shapes=[("softmax_label", (2,))])
    mod.init_params(initializer=mx.init.Xavier())
    mod.save_checkpoint(prefix, 3)
    mod.save_checkpoint(prefix, 7)

    begin, args, auxs = mx.model.resume_or_init(prefix)
    assert begin == 7 and args
    ref, _ = mod.get_params()
    np.testing.assert_allclose(args[sorted(args)[0]].asnumpy(),
                               ref[sorted(ref)[0]].asnumpy())


def test_get_num_dead_node_single_process():
    kv = mx.kv.create("local")
    assert kv.get_num_dead_node() == 0
    kvd = mx.kv.create("dist_tpu_sync")
    assert kvd.get_num_dead_node(timeout=1) == 0
