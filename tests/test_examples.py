"""Example-script smoke tests (the reference ran its examples as the
tests/python/train tier). Each runs a real example end-to-end in a
subprocess at a deliberately tiny configuration — these catch API drift in
the scripts (iterator contracts, metric names, symbol builders), not model
quality; the quality numbers live in each example's default config."""
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (script, args) — configs sized to finish in tens of seconds on one core
CASES = [
    ("example/numpy-ops/custom_softmax.py",
     ["--num-epochs", "2", "--batch-size", "64"]),
    ("example/multi-task/multi_task.py",
     ["--num-epochs", "1", "--train-size", "512"]),
    ("example/autoencoder/manifold_ae.py",
     ["--num-epochs", "2", "--train-size", "512"]),
    ("example/recommenders/matrix_fact.py",
     ["--num-epochs", "1", "--num-obs", "4000"]),
    ("example/cnn_text_classification/text_cnn.py",
     ["--num-epochs", "1", "--train-size", "512", "--val-size", "128"]),
    ("example/nce-loss/nce_word2vec.py",
     ["--num-epochs", "4", "--train-size", "2048"]),
    ("example/long-context/ring_attention_lm.py",
     ["--dp", "2", "--sp", "4", "--seq-len", "32", "--steps", "120"]),
]


@pytest.mark.slow
@pytest.mark.parametrize("script,args", CASES,
                         ids=[c[0].split("/")[1] for c in CASES])
def test_example_runs(script, args):
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "MXNET_DEFAULT_CONTEXT": "cpu"})
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, script)] + args,
        capture_output=True, text=True, timeout=600, env=env, cwd=ROOT)
    assert out.returncode == 0, (
        "%s failed:\n%s" % (script, (out.stderr or out.stdout)[-1500:]))
