"""Operator forward checks against numpy/torch oracles, modeled on the
reference's tests/python/unittest/test_operator.py (numpy oracle strategy,
SURVEY.md §4). Gradient checks live in test_executor.py / test_autograd.py."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _nd(x):
    return mx.nd.array(x)


def test_fully_connected():
    rng = np.random.RandomState(0)
    x = rng.rand(4, 10).astype(np.float32)
    w = rng.rand(5, 10).astype(np.float32)
    b = rng.rand(5).astype(np.float32)
    out = mx.nd.FullyConnected(_nd(x), _nd(w), _nd(b), num_hidden=5)
    assert np.allclose(out.asnumpy(), x.dot(w.T) + b, rtol=1e-4)
    out = mx.nd.FullyConnected(_nd(x), _nd(w), num_hidden=5, no_bias=True)
    assert np.allclose(out.asnumpy(), x.dot(w.T), rtol=1e-4)
    # 4D input flattens
    x4 = rng.rand(2, 3, 2, 2).astype(np.float32)
    w4 = rng.rand(7, 12).astype(np.float32)
    out = mx.nd.FullyConnected(_nd(x4), _nd(w4), num_hidden=7, no_bias=True)
    assert np.allclose(out.asnumpy(), x4.reshape(2, -1).dot(w4.T), rtol=1e-4)


def test_convolution_vs_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(1)
    x = rng.rand(2, 3, 8, 8).astype(np.float32)
    w = rng.rand(4, 3, 3, 3).astype(np.float32)
    b = rng.rand(4).astype(np.float32)
    out = mx.nd.Convolution(
        _nd(x), _nd(w), _nd(b), kernel=(3, 3), num_filter=4, stride=(2, 2), pad=(1, 1)
    )
    ref = torch.nn.functional.conv2d(
        torch.from_numpy(x), torch.from_numpy(w), torch.from_numpy(b), stride=2, padding=1
    ).numpy()
    assert np.allclose(out.asnumpy(), ref, rtol=1e-3, atol=1e-4)


def test_convolution_grouped_dilated():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(2)
    x = rng.rand(1, 4, 9, 9).astype(np.float32)
    w = rng.rand(6, 2, 3, 3).astype(np.float32)
    out = mx.nd.Convolution(
        _nd(x), _nd(w), kernel=(3, 3), num_filter=6, num_group=2, dilate=(2, 2), no_bias=True
    )
    ref = torch.nn.functional.conv2d(
        torch.from_numpy(x), torch.from_numpy(w), groups=2, dilation=2
    ).numpy()
    assert np.allclose(out.asnumpy(), ref, rtol=1e-3, atol=1e-4)


def test_deconvolution_vs_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(3)
    x = rng.rand(2, 3, 5, 5).astype(np.float32)
    w = rng.rand(3, 4, 3, 3).astype(np.float32)  # (C_in, num_filter, kh, kw)
    out = mx.nd.Deconvolution(_nd(x), _nd(w), kernel=(3, 3), num_filter=4, stride=(2, 2), pad=(1, 1), no_bias=True)
    ref = torch.nn.functional.conv_transpose2d(
        torch.from_numpy(x), torch.from_numpy(w), stride=2, padding=1
    ).numpy()
    assert np.allclose(out.asnumpy(), ref, rtol=1e-3, atol=1e-4)


def test_pooling_vs_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(4)
    x = rng.rand(2, 3, 8, 8).astype(np.float32)
    out = mx.nd.Pooling(_nd(x), kernel=(2, 2), stride=(2, 2), pool_type="max")
    ref = torch.nn.functional.max_pool2d(torch.from_numpy(x), 2, 2).numpy()
    assert np.allclose(out.asnumpy(), ref)
    out = mx.nd.Pooling(_nd(x), kernel=(2, 2), stride=(2, 2), pool_type="avg")
    ref = torch.nn.functional.avg_pool2d(torch.from_numpy(x), 2, 2).numpy()
    assert np.allclose(out.asnumpy(), ref, rtol=1e-5)
    out = mx.nd.Pooling(_nd(x), kernel=(2, 2), global_pool=True, pool_type="avg")
    assert np.allclose(out.asnumpy(), x.mean(axis=(2, 3), keepdims=True), rtol=1e-5)


def test_activation():
    x = np.array([[-1.0, 0.0, 2.0]], dtype=np.float32)
    assert np.allclose(mx.nd.Activation(_nd(x), act_type="relu").asnumpy(), [[0, 0, 2]])
    assert np.allclose(
        mx.nd.Activation(_nd(x), act_type="sigmoid").asnumpy(), 1 / (1 + np.exp(-x)), rtol=1e-5
    )
    assert np.allclose(mx.nd.Activation(_nd(x), act_type="tanh").asnumpy(), np.tanh(x), rtol=1e-5)
    assert np.allclose(
        mx.nd.Activation(_nd(x), act_type="softrelu").asnumpy(), np.log1p(np.exp(x)), rtol=1e-4
    )
    assert np.allclose(
        mx.nd.LeakyReLU(_nd(x), act_type="leaky", slope=0.1).asnumpy(),
        np.where(x >= 0, x, 0.1 * x),
        rtol=1e-5,
    )


def test_batchnorm_train_and_aux():
    rng = np.random.RandomState(5)
    x = rng.rand(4, 3, 2, 2).astype(np.float32)
    gamma = np.ones(3, np.float32)
    beta = np.zeros(3, np.float32)
    mmean = mx.nd.zeros((3,))
    mvar = mx.nd.ones((3,))
    out = mx.nd.BatchNorm(_nd(x), _nd(gamma), _nd(beta), mmean, mvar, fix_gamma=False, momentum=0.9)
    mean = x.mean(axis=(0, 2, 3))
    var = (x**2).mean(axis=(0, 2, 3)) - mean**2
    ref = (x - mean[None, :, None, None]) / np.sqrt(var[None, :, None, None] + 1e-3)
    assert np.allclose(out.asnumpy(), ref, rtol=1e-3, atol=1e-4)
    # aux moving stats updated in place (FMutateInputs semantics)
    assert np.allclose(mmean.asnumpy(), 0.1 * mean, rtol=1e-3)
    assert np.allclose(mvar.asnumpy(), 0.9 * 1.0 + 0.1 * var, rtol=1e-3)


def test_softmax_output_forward():
    rng = np.random.RandomState(6)
    x = rng.rand(4, 5).astype(np.float32)
    label = np.array([0, 1, 2, 3], dtype=np.float32)
    out = mx.nd.SoftmaxOutput(_nd(x), _nd(label))
    e = np.exp(x - x.max(axis=1, keepdims=True))
    assert np.allclose(out.asnumpy(), e / e.sum(axis=1, keepdims=True), rtol=1e-4)


def test_dropout():
    x = np.ones((100, 100), dtype=np.float32)
    out = mx.nd.Dropout(_nd(x), p=0.5)
    arr = out.asnumpy()
    frac = (arr == 0).mean()
    assert 0.4 < frac < 0.6
    kept = arr[arr != 0]
    assert np.allclose(kept, 2.0, rtol=1e-5)


def test_reshape_codes():
    x = np.zeros((2, 3, 4), np.float32)
    assert mx.nd.Reshape(_nd(x), shape=(-1,)).shape == (24,)
    assert mx.nd.Reshape(_nd(x), shape=(0, -1)).shape == (2, 12)
    assert mx.nd.Reshape(_nd(x), shape=(-2,)).shape == (2, 3, 4)
    assert mx.nd.Reshape(_nd(x), shape=(-3, 0)).shape == (6, 4)
    assert mx.nd.Reshape(_nd(x), shape=(-4, 1, 2, 0, 0)).shape == (1, 2, 3, 4)
    assert mx.nd.Flatten(_nd(x)).shape == (2, 12)


def test_transpose_swap_expand():
    x = np.random.rand(2, 3, 4).astype(np.float32)
    assert mx.nd.transpose(_nd(x)).shape == (4, 3, 2)
    assert np.allclose(mx.nd.transpose(_nd(x), axes=(1, 0, 2)).asnumpy(), x.transpose(1, 0, 2))
    assert np.allclose(mx.nd.SwapAxis(_nd(x), dim1=0, dim2=2).asnumpy(), x.swapaxes(0, 2))
    assert mx.nd.expand_dims(_nd(x), axis=1).shape == (2, 1, 3, 4)


def test_slice_ops():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    out = mx.nd.slice(_nd(x), begin=(0, 1, 0), end=(2, 3, 2))
    assert np.allclose(out.asnumpy(), x[0:2, 1:3, 0:2])
    out = mx.nd.slice_axis(_nd(x), axis=1, begin=1, end=3)
    assert np.allclose(out.asnumpy(), x[:, 1:3])
    out = mx.nd.slice_axis(_nd(x), axis=-1, begin=0, end=2)
    assert np.allclose(out.asnumpy(), x[..., 0:2])


def test_ordering():
    x = np.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]], dtype=np.float32)
    out = mx.nd.topk(_nd(x), k=2, ret_typ="value")
    assert np.allclose(out.asnumpy(), [[3, 2], [5, 4]])
    out = mx.nd.argsort(_nd(x))
    assert np.allclose(out.asnumpy(), [[1, 2, 0], [0, 2, 1]])
    out = mx.nd.sort(_nd(x), is_ascend=False)
    assert np.allclose(out.asnumpy(), [[3, 2, 1], [5, 4, 0]])


def test_sequence_ops():
    x = np.random.rand(4, 2, 3).astype(np.float32)
    slen = np.array([2, 4], dtype=np.float32)
    out = mx.nd.SequenceLast(_nd(x), _nd(slen), use_sequence_length=True)
    assert np.allclose(out.asnumpy(), np.stack([x[1, 0], x[3, 1]]))
    out = mx.nd.SequenceMask(_nd(x), _nd(slen), use_sequence_length=True, value=-1.0)
    assert np.allclose(out.asnumpy()[2:, 0], -1.0)
    assert np.allclose(out.asnumpy()[:, 1], x[:, 1])
    out = mx.nd.SequenceReverse(_nd(x), _nd(slen), use_sequence_length=True)
    assert np.allclose(out.asnumpy()[0, 0], x[1, 0])
    assert np.allclose(out.asnumpy()[1, 0], x[0, 0])
    assert np.allclose(out.asnumpy()[2:, 0], x[2:, 0])
    assert np.allclose(out.asnumpy()[:, 1], x[::-1, 1])


def test_elemwise_sum_and_where():
    xs = [np.random.rand(2, 2).astype(np.float32) for _ in range(3)]
    out = mx.nd.add_n(*[_nd(x) for x in xs], num_args=3)
    assert np.allclose(out.asnumpy(), sum(xs), rtol=1e-5)
    cond = np.array([[1, 0], [0, 1]], np.float32)
    out = mx.nd.where(_nd(cond), _nd(xs[0]), _nd(xs[1]))
    assert np.allclose(out.asnumpy(), np.where(cond != 0, xs[0], xs[1]))


def test_pick_take():
    x = np.random.rand(3, 4).astype(np.float32)
    idx = np.array([0, 2, 3], np.float32)
    out = mx.nd.pick(_nd(x), _nd(idx))
    assert np.allclose(out.asnumpy(), x[np.arange(3), idx.astype(int)])
    out = mx.nd.batch_take(_nd(x), _nd(idx))
    assert np.allclose(out.asnumpy(), x[np.arange(3), idx.astype(int)])
    out = mx.nd.take(_nd(x), _nd(np.array([0, 2], np.float32)))
    assert np.allclose(out.asnumpy(), x[[0, 2]])


def test_lrn_l2norm():
    x = np.random.rand(2, 4, 3, 3).astype(np.float32)
    out = mx.nd.LRN(_nd(x), nsize=3, alpha=1e-4, beta=0.75, knorm=2.0)
    # naive reference
    sq = x**2
    ref = np.zeros_like(x)
    for c in range(4):
        lo, hi = max(0, c - 1), min(4, c + 2)
        s = sq[:, lo:hi].sum(axis=1)
        ref[:, c] = x[:, c] * (2.0 + (1e-4 / 3) * s) ** -0.75
    assert np.allclose(out.asnumpy(), ref, rtol=1e-4)
    out = mx.nd.L2Normalization(_nd(x), mode="instance")
    n = np.sqrt((x.reshape(2, -1) ** 2).sum(axis=1) + 1e-10)
    assert np.allclose(out.asnumpy(), x / n[:, None, None, None], rtol=1e-4)


def test_optimizer_update_ops():
    w = np.random.rand(5).astype(np.float32)
    g = np.random.rand(5).astype(np.float32)
    wn, gn = _nd(w), _nd(g)
    out = mx.nd.sgd_update(wn, gn, lr=0.1, wd=0.01)
    ref = w - 0.1 * (g + 0.01 * w)
    assert np.allclose(out.asnumpy(), ref, rtol=1e-5)
    # in-place via out=
    mx.nd.sgd_update(wn, gn, lr=0.1, wd=0.01, out=wn)
    assert np.allclose(wn.asnumpy(), ref, rtol=1e-5)
    # momentum
    w2, m2 = _nd(w), mx.nd.zeros((5,))
    new_w, new_m = mx.nd.sgd_mom_update(w2, gn, m2, lr=0.1, momentum=0.9)
    assert np.allclose(new_m.asnumpy(), -0.1 * g, rtol=1e-5)
    assert np.allclose(new_w.asnumpy(), w - 0.1 * g, rtol=1e-5)


def test_cast_clip_onehot():
    x = np.array([[0.5, 1.7]], np.float32)
    assert mx.nd.Cast(_nd(x), dtype=np.int32).dtype == np.int32
    assert np.allclose(mx.nd.clip(_nd(x), a_min=0.6, a_max=1.0).asnumpy(), [[0.6, 1.0]])


def test_rnn_op_lstm_shapes():
    from mxnet_tpu.ops.rnn import rnn_param_size

    T, N, I, H, L = 5, 2, 3, 4, 2
    psize = rnn_param_size(L, I, H, False, "lstm")
    params = np.random.RandomState(7).rand(psize).astype(np.float32) * 0.1
    x = np.random.rand(T, N, I).astype(np.float32)
    h0 = np.zeros((L, N, H), np.float32)
    c0 = np.zeros((L, N, H), np.float32)
    outs = mx.nd.RNN(
        _nd(x), _nd(params), _nd(h0), _nd(c0),
        state_size=H, num_layers=L, mode="lstm", state_outputs=True,
    )
    out, hT, cT = outs
    assert out.shape == (T, N, H)
    assert hT.shape == (L, N, H)
    assert cT.shape == (L, N, H)
    # bidirectional
    psize = rnn_param_size(1, I, H, True, "gru")
    params = np.random.rand(psize).astype(np.float32) * 0.1
    h0 = np.zeros((2, N, H), np.float32)
    out = mx.nd.RNN(_nd(x), _nd(params), _nd(h0), state_size=H, num_layers=1, mode="gru", bidirectional=True)
    assert out.shape == (T, N, 2 * H)


def test_samplers_moments():
    mx.random.seed(0)
    u = mx.nd.uniform(low=2.0, high=4.0, shape=(5000,))
    assert abs(u.asnumpy().mean() - 3.0) < 0.1
    n = mx.nd.normal(loc=1.0, scale=2.0, shape=(5000,))
    assert abs(n.asnumpy().mean() - 1.0) < 0.15
    assert abs(n.asnumpy().std() - 2.0) < 0.15
    # bare `gamma` is the unary Γ(x) op (as in the reference); the sampler is random_gamma
    g = mx.nd.random_gamma(alpha=3.0, beta=2.0, shape=(5000,))
    assert abs(g.asnumpy().mean() - 6.0) < 0.4
    e = mx.nd.exponential(lam=2.0, shape=(5000,))
    assert abs(e.asnumpy().mean() - 0.5) < 0.1
    p = mx.nd.poisson(lam=4.0, shape=(5000,))
    assert abs(p.asnumpy().mean() - 4.0) < 0.3
