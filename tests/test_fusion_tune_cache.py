"""The persistent measure-and-cache autotuner (fusion_tune.py) end to end:
cold tune → persist → warm hits with zero re-tunes; corrupt or
digest-mismatched cache files are ignored with a warning, never a crash;
tuned-and-rejected verdicts surface their measured timings through the
gate reasons (the GL302/GL303 explain contract)."""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import fusion, fusion_tune, telemetry


@pytest.fixture(autouse=True)
def _fresh(monkeypatch, tmp_path):
    saved = telemetry.current_override()
    monkeypatch.setenv("MXNET_FUSION_TUNE_DIR", str(tmp_path))
    monkeypatch.setenv("MXNET_FUSION_TUNE_ITERS", "2")
    monkeypatch.setenv("MXNET_TELEMETRY", "counters")
    telemetry.set_mode("counters")
    fusion_tune.reset()
    telemetry.reset()
    yield
    fusion_tune.reset()
    telemetry.reset()
    telemetry.set_mode(saved)


def _mba_net():
    sym = mx.sym
    x = sym.Variable("data")
    h = sym.FullyConnected(x, num_hidden=128, name="fc1")
    h = sym.Activation(h, act_type="relu", name="act1")
    fc = sym.FullyConnected(h, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(fc, name="softmax")


def _fit_once(monkeypatch, seed=0):
    monkeypatch.setenv("MXNET_FUSED_PATTERNS", "matmul_bias_act")
    rs = np.random.RandomState(seed)
    net = _mba_net()
    ex = net.simple_bind(mx.cpu(), data=(8, 32), softmax_label=(8,),
                        grad_req="write")
    for name, arr in zip(net.list_arguments(), ex.arg_arrays):
        if "label" in name:
            arr[:] = rs.randint(0, 4, arr.shape).astype("f")
        else:
            arr[:] = rs.uniform(-0.5, 0.5, arr.shape).astype("f")
    ex.forward(is_train=True)
    ex.backward()
    return ex


def test_cold_tune_persists_and_warm_process_never_retunes(monkeypatch,
                                                           tmp_path):
    _fit_once(monkeypatch)
    tunes = telemetry.counter("fusion.tune").value
    assert tunes == 1  # one site, one measurement
    path = fusion_tune.cache_path()
    assert path is not None and os.path.exists(path)
    payload = json.load(open(path))
    assert payload["device_kind"] == fusion_tune.device_kind()
    assert payload["digest"] == fusion_tune.entries_digest(
        payload["entries"])
    [key] = list(payload["entries"])
    assert key.startswith("matmul_bias_act|relu|")

    # "fresh process": drop the in-memory memo, rebind, re-run — the
    # verdict must come from disk with ZERO re-tunes
    fusion_tune.reset()
    telemetry.reset()
    _fit_once(monkeypatch)
    assert telemetry.counter("fusion.tune").value == 0
    assert telemetry.counter("fusion.tune_cache_hit").value >= 1


def test_corrupt_cache_file_is_ignored_not_fatal(monkeypatch, tmp_path,
                                                 caplog):
    path = fusion_tune.cache_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write("{ this is not json")
    with caplog.at_level("WARNING", logger="mxnet_tpu"):
        _fit_once(monkeypatch)  # must tune fresh, not crash
    assert telemetry.counter("fusion.tune").value == 1
    assert any("ignoring cache file" in r.message for r in caplog.records)
    # and the re-tune REWROTE the file valid
    payload = json.load(open(path))
    assert payload["digest"] == fusion_tune.entries_digest(
        payload["entries"])


def test_digest_mismatch_is_ignored_with_warning(monkeypatch, tmp_path,
                                                 caplog):
    _fit_once(monkeypatch)
    path = fusion_tune.cache_path()
    payload = json.load(open(path))
    # hand-edit an entry without updating the digest (a value no real
    # measurement can produce, so the edit is never a no-op)
    for k in payload["entries"]:
        payload["entries"][k]["engage"] = True
        payload["entries"][k]["lowering"] = "hand-edited"
    with open(path, "w") as f:
        json.dump(payload, f)
    fusion_tune.reset()
    with caplog.at_level("WARNING", logger="mxnet_tpu"):
        assert fusion_tune.peek(list(payload["entries"])[0]) is None
    assert any("digest mismatch" in r.message for r in caplog.records)


def test_device_kind_mismatch_is_ignored(monkeypatch, tmp_path, caplog):
    path = fusion_tune.cache_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    entries = {"k": {"engage": True}}
    with open(path, "w") as f:
        json.dump({"version": 1, "device_kind": "TPU v99",
                   "digest": fusion_tune.entries_digest(entries),
                   "entries": entries}, f)
    with caplog.at_level("WARNING", logger="mxnet_tpu"):
        assert fusion_tune.peek("k") is None
    assert any("device_kind" in r.message for r in caplog.records)


def test_tuned_and_rejected_reason_reports_measured_timings(monkeypatch):
    """satellite contract: a tuned-and-rejected site's gate reason carries
    the measured fused-vs-baseline µs from the cache, not a bare 'no
    verdict'."""
    # seed a rejection record directly through the verdict path
    key = "conv_bn|k1s1p|float32(2, 8, 8, 8);(16, 8, 1, 1)"
    rec = {"engage": False, "engage_fwd": False, "lowering": None,
           "base_fwd_us": 100.0, "base_bwd_us": 200.0,
           "measured": {"pallas:xla": {"fwd_us": 400.0, "bwd_us": 500.0,
                                       "rel_err": 0.0}}}
    got = fusion_tune.verdict(key, lambda: rec)
    assert got["engage"] is False
    note = fusion.tuned_reject_note(got)
    assert "tuned and rejected" in note
    assert "900" in note and "300" in note  # fused vs baseline fwd+bwd µs


def test_conv_bn_gate_explain_quotes_tuned_timings(monkeypatch):
    """fusion.gate_explain for a conv+BN shape with a cached rejection
    must quote the measured timings (the GL302 feed)."""
    kernel, stride = (1, 1), (1, 1)
    x_shape, w_shape = (2, 8, 8, 8), (16, 8, 1, 1)
    key = fusion._conv_bn_key(kernel, stride, x_shape, w_shape,
                              np.float32, False)
    rec = {"engage": False, "engage_fwd": False, "lowering": None,
           "base_fwd_us": 50.0, "base_bwd_us": 70.0,
           "measured": {"pallas:xla": {"fwd_us": 300.0, "bwd_us": 400.0,
                                       "rel_err": 0.0}}}
    assert fusion_tune.verdict(key, lambda: rec) is rec
    monkeypatch.setenv("MXNET_FUSED_CONV_BN", "auto")
    engaged, reason = fusion.gate_explain(kernel, stride, x_shape, w_shape,
                                          np.float32, prologue=True)
    assert engaged is False
    assert "tuned and rejected" in reason and "µs" in reason


def test_measure_candidates_rejects_parity_violations():
    import jax.numpy as jnp

    def baseline(x):
        return x * 2.0

    def wrong(x):
        return x * 2.5  # fast but WRONG: must never engage

    rec = fusion_tune.measure_candidates(
        baseline, [("wrong", wrong)],
        (np.random.RandomState(0).randn(64).astype("f"),), train=True,
        iters=2)
    assert rec["engage"] is False
    assert "rejected" in rec["measured"]["wrong"]
