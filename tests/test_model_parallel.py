"""Model parallelism via ctx groups (reference:
tests/python/unittest/test_model_parallel.py:12-31,
test_multi_device_exec.py:4-33 — ctx groups mapped to cpu(i) so placement and
cross-device-copy logic run without special hardware; here cpu(i) are the
virtual XLA host devices from conftest)."""
import numpy as np

import mxnet_tpu as mx


def _build_net():
    with mx.AttrScope(ctx_group="dev1"):
        data = mx.sym.Variable("data")
        fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
        act1 = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    with mx.AttrScope(ctx_group="dev2"):
        fc2 = mx.sym.FullyConnected(act1, num_hidden=4, name="fc2")
        net = mx.sym.LinearRegressionOutput(fc2, mx.sym.Variable("label"),
                                            name="loss")
    return net


def test_ctxgroup_attr_propagates():
    net = _build_net()
    topo_attrs = {}
    for node in net._topo():
        if node.op:
            topo_attrs[node.name] = node.attrs.get("__ctx_group__")
    assert topo_attrs["fc1"] == "dev1" and topo_attrs["relu1"] == "dev1"
    assert topo_attrs["fc2"] == "dev2"


def test_model_parallel_forward_backward_matches_single_device():
    net = _build_net()
    shapes = {"data": (8, 10), "label": (8, 4)}
    rs = np.random.RandomState(0)
    arrays = {n: rs.rand(*s).astype("float32")
              for n, s in zip(net.list_arguments(),
                              net.infer_shape(**shapes)[0])}

    def run(group2ctx):
        exe = net.simple_bind(mx.cpu(0), grad_req="write",
                              group2ctx=group2ctx, **shapes)
        for k, v in arrays.items():
            exe.arg_dict[k][:] = v
        exe.forward(is_train=True)
        out = exe.outputs[0].asnumpy()
        exe.backward()
        grads = {k: g.asnumpy() for k, g in exe.grad_dict.items()
                 if g is not None}
        return out, grads

    out_single, grads_single = run(None)
    out_mp, grads_mp = run({"dev1": mx.cpu(0), "dev2": mx.cpu(1)})
    np.testing.assert_allclose(out_mp, out_single, rtol=1e-5, atol=1e-6)
    for k in grads_single:
        np.testing.assert_allclose(grads_mp[k], grads_single[k],
                                   rtol=1e-5, atol=1e-6)


def test_model_parallel_trains():
    """2-group net trains end-to-end through Module (placement is invisible
    to the training API, as in the reference)."""
    net = _build_net()
    rs = np.random.RandomState(1)
    x = rs.rand(32, 10).astype("float32")
    w = rs.rand(10, 4).astype("float32")
    y = x @ w
    exe = net.simple_bind(mx.cpu(0), grad_req="write",
                          group2ctx={"dev1": mx.cpu(0), "dev2": mx.cpu(1)},
                          data=(32, 10), label=(32, 4))
    for name in exe.arg_dict:
        if name not in ("data", "label"):
            exe.arg_dict[name][:] = rs.uniform(-0.3, 0.3,
                                               exe.arg_dict[name].shape)
    exe.arg_dict["data"][:] = x
    exe.arg_dict["label"][:] = y
    losses = []
    for _ in range(60):
        exe.forward(is_train=True)
        losses.append(float(np.square(exe.outputs[0].asnumpy() - y).mean()))
        exe.backward()
        for name, grad in exe.grad_dict.items():
            if grad is not None and name not in ("data", "label"):
                exe.arg_dict[name][:] = exe.arg_dict[name].asnumpy() \
                    - 0.05 * grad.asnumpy()
    assert losses[-1] < losses[0] * 0.5, losses[::20]
