"""CTC loss (reference: plugin/warpctc). Oracles: brute-force alignment
enumeration on tiny shapes, finite-difference gradients, and a toy OCR
convergence run."""
import itertools

import numpy as np
import pytest

import mxnet_tpu as mx


def _brute_force_nll(log_probs, label, blank=0):
    """-log P(label) by enumerating every length-T path and collapsing it
    (remove repeats, then blanks)."""
    T, C = log_probs.shape
    total = -np.inf
    for path in itertools.product(range(C), repeat=T):
        collapsed = []
        prev = None
        for s in path:
            if s != prev and s != blank:
                collapsed.append(s)
            prev = s
        if collapsed == list(label):
            lp = sum(log_probs[t, s] for t, s in enumerate(path))
            total = np.logaddexp(total, lp)
    return -total


class TestCTCNll:
    @pytest.mark.parametrize("label", [[1, 2], [1, 1], [2], []])
    def test_matches_brute_force(self, label):
        import jax.numpy as jnp

        from mxnet_tpu.ops.ctc import ctc_nll

        rs = np.random.RandomState(0)
        T, C = 4, 3
        logits = rs.randn(T, 1, C).astype("float32")
        lp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
        L = max(len(label), 1)
        lab = np.zeros((1, L), "int32")
        lab[0, : len(label)] = label
        got = float(ctc_nll(jnp.asarray(lp), jnp.asarray(lab),
                            jnp.asarray([len(label)]))[0])
        want = _brute_force_nll(lp[:, 0], label)
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_batch_and_padding(self):
        """Padded rows must match their unpadded singletons."""
        import jax.numpy as jnp

        from mxnet_tpu.ops.ctc import ctc_nll

        rs = np.random.RandomState(1)
        T, C = 5, 4
        logits = rs.randn(T, 2, C).astype("float32")
        lp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
        lab = np.array([[1, 2, 3], [2, 0, 0]], "int32")
        lens = np.array([3, 1])
        got = np.asarray(ctc_nll(jnp.asarray(lp), jnp.asarray(lab),
                                 jnp.asarray(lens)))
        for b in (0, 1):
            want = _brute_force_nll(lp[:, b], list(lab[b][: lens[b]]))
            np.testing.assert_allclose(got[b], want, rtol=1e-5)


class TestWarpCTCOp:
    def _bind(self, T, B, C, L):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("label")
        out = mx.sym.WarpCTC(data=data, label=label, input_length=T,
                             label_length=L)
        ex = out.simple_bind(ctx=mx.cpu(), data=(T * B, C), label=(B, L),
                             grad_req="write")
        return ex

    def test_forward_is_softmax(self):
        T, B, C, L = 3, 2, 4, 2
        ex = self._bind(T, B, C, L)
        rs = np.random.RandomState(0)
        x = rs.randn(T * B, C).astype("float32")
        ex.arg_dict["data"][:] = x
        ex.arg_dict["label"][:] = np.array([[1, 2], [3, 0]], "float32")
        ex.forward(is_train=False)
        p = ex.outputs[0].asnumpy()
        want = np.exp(x) / np.exp(x).sum(-1, keepdims=True)
        np.testing.assert_allclose(p, want, rtol=1e-5)

    def test_gradient_matches_finite_difference(self):
        import jax, jax.numpy as jnp

        from mxnet_tpu.ops.ctc import _warpctc_core

        T, B, C, L = 4, 2, 3, 2
        rs = np.random.RandomState(2)
        x = rs.randn(T * B, C).astype("float64").astype("float32")
        lab = np.array([[1, 2], [2, 0]], "float32")

        ex = self._bind(T, B, C, L)
        ex.arg_dict["data"][:] = x
        ex.arg_dict["label"][:] = lab
        ex.forward(is_train=True)
        ex.backward()
        g = ex.grad_dict["data"].asnumpy()

        # finite differences of the total nll
        def nll(xv):
            lp = xv.reshape(T, B, C)
            lp = lp - np.log(np.exp(lp).sum(-1, keepdims=True))
            tot = 0.0
            for b in range(B):
                labels = [int(v) for v in lab[b] if v != 0]
                tot += _brute_force_nll(lp[:, b], labels)
            return tot

        eps = 1e-3
        for idx in [(0, 0), (3, 2), (5, 1)]:
            xp = x.copy(); xp[idx] += eps
            xm = x.copy(); xm[idx] -= eps
            fd = (nll(xp) - nll(xm)) / (2 * eps)
            np.testing.assert_allclose(g[idx], fd, rtol=2e-2, atol=2e-3)

    def test_toy_ocr_converges(self):
        """A linear model on fixed per-frame features must learn a target
        transcription (the warpctc toy example's economics)."""
        T, B, C, L = 6, 4, 5, 3
        rs = np.random.RandomState(3)
        X = rs.randn(B, T, 8).astype("float32")
        Y = np.zeros((B, L), "float32")
        for b in range(B):
            Y[b] = rs.choice(np.arange(1, C), L, replace=False)

        data = mx.sym.Variable("data")          # (T*B, feat)
        label = mx.sym.Variable("label")
        net = mx.sym.FullyConnected(data, num_hidden=C, name="fc")
        net = mx.sym.WarpCTC(data=net, label=label, input_length=T,
                             label_length=L)
        ex = net.simple_bind(ctx=mx.cpu(), data=(T * B, 8), label=(B, L),
                             grad_req="write")
        rs2 = np.random.RandomState(0)
        for k, v in ex.arg_dict.items():
            if k not in ("data", "label"):
                v[:] = rs2.normal(0, 0.1, v.shape)
        x_flat = X.transpose(1, 0, 2).reshape(T * B, 8)  # time-major rows
        ex.arg_dict["data"][:] = x_flat
        ex.arg_dict["label"][:] = Y
        for step in range(300):
            ex.forward(is_train=True)
            ex.backward()
            for k, g in ex.grad_dict.items():
                if k not in ("data", "label") and g is not None:
                    ex.arg_dict[k][:] = ex.arg_dict[k].asnumpy() - 0.5 * g.asnumpy()
        ex.forward(is_train=False)
        p = ex.outputs[0].asnumpy().reshape(T, B, C)
        # greedy decode must equal the target for most rows
        hits = 0
        for b in range(B):
            path = p[:, b].argmax(-1)
            dec = []
            prev = None
            for s in path:
                if s != prev and s != 0:
                    dec.append(s)
                prev = s
            hits += dec == [int(v) for v in Y[b]]
        assert hits >= B - 1, "toy CTC training failed: %d/%d decoded" % (hits, B)

    def test_infeasible_label_gets_zero_gradient(self):
        """warp-ctc contract: a label needing more frames than input_length
        contributes zero loss and zero gradient."""
        T, B, C, L = 2, 1, 3, 2
        ex = self._bind(T, B, C, L)
        rs = np.random.RandomState(4)
        ex.arg_dict["data"][:] = rs.randn(T * B, C).astype("float32")
        ex.arg_dict["label"][:] = np.array([[1, 1]], "float32")  # needs T>=3
        ex.forward(is_train=True)
        ex.backward()
        g = ex.grad_dict["data"].asnumpy()
        np.testing.assert_allclose(g, 0.0, atol=1e-8)
