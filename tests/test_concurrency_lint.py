"""Lock-witness runtime (telemetry/lockwitness.py) + GL805 wiring.

The static GL801-GL804 trigger/clean pairs live in test_graphlint.py next
to the other code-case tables; this file covers the MEASURED side: the
seeded two-thread races the witness must catch, the mode gate, and the
witness -> trace -> mxtrace/graphlint plumbing."""
import json
import threading
import time

import pytest

from mxnet_tpu.analysis.concurrency_lint import lint_lock_witness
from mxnet_tpu.telemetry import lockwitness as lw


@pytest.fixture
def witness():
    lw.set_mode("witness")
    lw.reset_witness()
    yield lw
    lw.set_mode(None)
    lw.reset_witness()


# ------------------------------------------------------------- mode gate

def test_off_mode_returns_plain_primitives(monkeypatch):
    monkeypatch.delenv("MXNET_CONCLINT", raising=False)
    lw.set_mode(None)
    assert not lw.witnessing()
    assert isinstance(lw.named_lock("x"), type(threading.Lock()))
    assert isinstance(lw.named_rlock("x"), type(threading.RLock()))
    assert isinstance(lw.named_condition("x"), threading.Condition)


def test_env_arms_witness(monkeypatch):
    lw.set_mode(None)
    monkeypatch.setenv("MXNET_CONCLINT", "witness")
    assert lw.witnessing()
    monkeypatch.setenv("MXNET_CONCLINT", "off")
    assert not lw.witnessing()


# ------------------------------------------- seeded races (the acceptance)

def test_witness_catches_seeded_two_thread_inversion(witness):
    """The ISSUE acceptance repro: T1 takes a->b, T2 takes b->a. The
    interleaving is SEQUENCED (no actual deadlock) — the witness must
    still report the order inversion, and GL805 must fire on it."""
    a, b = lw.named_lock("repro.a"), lw.named_lock("repro.b")
    t1_done = threading.Event()

    def t1():
        with a:
            with b:
                pass
        t1_done.set()

    def t2():
        t1_done.wait(5.0)
        with b:
            with a:
                pass

    th1 = threading.Thread(target=t1)
    th2 = threading.Thread(target=t2)
    th1.start(); th2.start()
    th1.join(5.0); th2.join(5.0)
    rep = lw.witness_report()
    inv = [e for e in rep["events"] if e["kind"] == "inversion"]
    assert inv, rep["events"]
    assert {inv[0]["first"], inv[0]["then"]} == {"repro.a", "repro.b"}
    diags = lint_lock_witness(rep)
    assert [d.code for d in diags] == ["GL805"]
    assert "inversion" in diags[0].message


def test_witness_long_hold_across_dispatch_seam(witness, monkeypatch):
    monkeypatch.setenv("MXNET_CONCLINT_HOLD_MS", "5")
    lk = lw.named_lock("repro.hold")
    with lk:
        lw.note_dispatch()
        time.sleep(0.02)
    rep = lw.witness_report()
    holds = [e for e in rep["events"] if e["kind"] == "long_hold"]
    assert holds and holds[0]["dispatch_seam"]
    assert [d.code for d in lint_lock_witness(rep)] == ["GL805"]


def test_long_hold_without_seam_is_not_gl805(witness, monkeypatch):
    monkeypatch.setenv("MXNET_CONCLINT_HOLD_MS", "5")
    lk = lw.named_lock("repro.hostwork")
    with lk:
        time.sleep(0.02)
    rep = lw.witness_report()
    holds = [e for e in rep["events"] if e["kind"] == "long_hold"]
    assert holds and not holds[0]["dispatch_seam"]
    assert lint_lock_witness(rep) == []


def test_same_order_twice_is_not_an_inversion(witness):
    a, b = lw.named_lock("ok.a"), lw.named_lock("ok.b")
    for _ in range(2):
        with a:
            with b:
                pass
    rep = lw.witness_report()
    assert not [e for e in rep["events"] if e["kind"] == "inversion"]
    assert lint_lock_witness(rep) == []


# ----------------------------------------------------- stats / primitives

def test_contention_and_hold_stats(witness):
    lk = lw.named_lock("stats.l")
    started = threading.Event()
    release = threading.Event()

    def holder():
        with lk:
            started.set()
            release.wait(5.0)

    th = threading.Thread(target=holder)
    th.start()
    started.wait(5.0)
    got = lk.acquire(timeout=0.05)   # contended probe
    if got:
        lk.release()
    release.set()
    th.join(5.0)
    with lk:
        pass
    row = next(r for r in lw.witness_report()["locks"]
               if r["name"] == "stats.l")
    assert row["acquisitions"] >= 2
    assert row["contentions"] >= 1
    assert row["hold_ms"] >= 0.0
    assert len(row["threads"]) >= 2


def test_witness_rlock_reentrancy(witness):
    rl = lw.named_rlock("re.l")
    with rl:
        with rl:
            assert rl._is_owned()
    row = next(r for r in lw.witness_report()["locks"]
               if r["name"] == "re.l")
    # the reentrant inner acquire is not a second top-level acquisition
    assert row["acquisitions"] == 1


def test_witness_condition_wait_notify(witness):
    lk = lw.named_lock("cv.l")
    cv = lw.named_condition("cv.l", lk)
    fired = []

    def waiter():
        with lk:
            while not fired:
                if not cv.wait(5.0):
                    return

    th = threading.Thread(target=waiter)
    th.start()
    time.sleep(0.01)
    with lk:
        fired.append(1)
        cv.notify_all()
    th.join(5.0)
    assert not th.is_alive()


def test_reset_witness_clears_everything(witness):
    with lw.named_lock("reset.l"):
        pass
    lw.reset_witness()
    rep = lw.witness_report()
    assert rep["locks"] == [] and rep["events"] == []


# ------------------------------------------------------------- plumbing

def test_trace_embeds_lock_witness_block(witness):
    from mxnet_tpu.telemetry.trace import build_trace

    with lw.named_lock("trace.l"):
        pass
    dump = build_trace()
    block = dump["otherData"]["lock_witness"]
    assert block["enabled"]
    assert any(r["name"] == "trace.l" for r in block["locks"])


def test_mxtrace_locks_table_renders(witness):
    from mxnet_tpu.telemetry.cli import locks_table
    from mxnet_tpu.telemetry.trace import build_trace

    with lw.named_lock("tbl.l"):
        pass
    out = locks_table(build_trace())
    assert "tbl.l" in out
    assert "hold_ms" in out
    # a dump captured without the witness explains itself
    assert "MXNET_CONCLINT" in locks_table({"otherData": {}})


def test_graphlint_witness_flag_judges_a_dump(witness, tmp_path,
                                              capsys):
    from mxnet_tpu.analysis.cli import main
    from mxnet_tpu.telemetry.trace import build_trace

    a, b = lw.named_lock("cli.a"), lw.named_lock("cli.b")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    dump = tmp_path / "trace.json"
    dump.write_text(json.dumps(build_trace()))
    empty = tmp_path / "empty"
    empty.mkdir()
    rc = main(["--concurrency", "--witness", str(dump), "--format",
               "json", str(empty)])
    # the target dir has no .py files; the witness GL805 alone fails it
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert [d["code"] for d in out["witness"]] == ["GL805"]


def test_bindtime_pass_surfaces_gl805_when_witnessing(witness,
                                                      monkeypatch):
    monkeypatch.setenv("MXNET_CONCLINT_HOLD_MS", "5")
    lk = lw.named_lock("pass.l")
    with lk:
        lw.note_dispatch()
        time.sleep(0.02)
    import mxnet_tpu as mx
    from mxnet_tpu import analysis

    net = mx.models.get_symbol("mlp", num_classes=10)
    report = analysis.lint(net, shapes={"data": (8, 784)},
                           passes=["concurrency_lint"], target="witness")
    assert "GL805" in report.codes()
    # off-witness the pass is silent regardless of recorded state
    lw.set_mode(None)
    report = analysis.lint(net, shapes={"data": (8, 784)},
                           passes=["concurrency_lint"], target="off")
    assert report.codes() == []
