"""Sharded-checkpoint round-trip of row-sparse tables (docs/SPARSE.md).

The PR 7 format gains a ``sparse`` manifest section: worker r writes the
r-th contiguous piece of each dense table plus the r-th piece of its
touched-index set with the state rows (index+rows per shard). The pieces
re-assemble by concatenation, so a checkpoint saved under W workers resumes
bit-identically under W *and* W-1 — the re-flatten property the flat
buckets already had, extended to the sparse keys. One process plays every
rank here (the writer helpers are rank-parameterized); the 2-process smoke
exercises the real multi-process save path.
"""
import hashlib
import io
import json
import os

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import checkpoint as ckpt
from mxnet_tpu.sparse import RowSparseState, embedding_backward

V, D = 24, 4


def _tables(rs, nnz_rows):
    st = RowSparseState((V, D), "float32", 2)
    idx = np.asarray(sorted(nnz_rows), np.int64)
    st.scatter(idx, [rs.rand(idx.size, D).astype("float32"),
                     rs.rand(idx.size, D).astype("float32")])
    return {"emb": {"shape": (V, D), "dtype": "float32",
                    "w": rs.rand(V, D).astype("float32"),
                    "indices": st.indices,
                    "states": [r.copy() for r in st.rows]}}


def _write_step(root, step, world, tables, n_states=2):
    d = ckpt.step_dir(root, step)
    os.makedirs(d, exist_ok=True)
    for rank in range(world):
        local = ckpt.sparse_shard_arrays(tables, rank, world)
        buf = io.BytesIO()
        np.savez(buf, **local)
        data = buf.getvalue()
        base = os.path.join(d, "shard-%05d-of-%05d" % (rank, world))
        with open(base + ".npz", "wb") as f:
            f.write(data)
        with open(base + ".json", "w") as f:
            json.dump({"digest": hashlib.sha256(data).hexdigest(),
                       "rank": rank, "world": world, "step": step,
                       "plan_hash": None, "nbytes": len(data)}, f)
    manifest = {"format": 1, "kind": "sharded", "step": step, "world": world,
                "plan_hash": None, "plan": {"buckets": []},
                "sparse": ckpt.sparse_manifest_section(tables),
                "optimizer": {"kind": "adam", "n_states": n_states,
                              "hyper": {}, "class": "Adam"},
                "update_counts": [["emb", 3]], "num_update": 3, "files": []}
    with open(os.path.join(d, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    return manifest


def _assert_tables_equal(got, want):
    np.testing.assert_array_equal(got["w"], want["w"])
    np.testing.assert_array_equal(got["indices"], want["indices"])
    assert len(got["states"]) == len(want["states"])
    for a, b in zip(got["states"], want["states"]):
        np.testing.assert_array_equal(a, b)


def test_save_w2_resume_w2_and_w1_bit_parity(tmp_path):
    """The satellite's core claim: shards written under W=2 re-assemble
    bit-identically for a W=2 AND a W=1 reader (the reader never needs the
    writer's world — concatenation is world-agnostic)."""
    rs = np.random.RandomState(0)
    tables = _tables(rs, [1, 5, 9, 17, 22])
    root = str(tmp_path)
    manifest = _write_step(root, 11, world=2, tables=tables)
    got = ckpt.latest_complete(root)
    assert got is not None and got[0] == 11
    # any-world readers: the manifest names the WRITER world; readers of
    # any live world call the same re-assembly
    out = ckpt.read_sparse_tables(root, 11, manifest)
    _assert_tables_equal(out["emb"], tables["emb"])


def test_uneven_nnz_split_across_workers(tmp_path):
    """nnz not divisible by world: np.array_split slices must still
    re-assemble exactly (the W-1 resume's bread and butter)."""
    rs = np.random.RandomState(1)
    tables = _tables(rs, [2, 3, 19])  # 3 rows over 2 workers
    manifest = _write_step(str(tmp_path), 5, world=2, tables=tables)
    out = ckpt.read_sparse_tables(str(tmp_path), 5, manifest)
    _assert_tables_equal(out["emb"], tables["emb"])
    # and over 3 workers (one worker gets a zero-row piece)
    manifest = _write_step(str(tmp_path), 6, world=3, tables=tables)
    out = ckpt.read_sparse_tables(str(tmp_path), 6, manifest)
    _assert_tables_equal(out["emb"], tables["emb"])


def test_zero_nnz_table_round_trips(tmp_path):
    rs = np.random.RandomState(2)
    tables = _tables(rs, [])
    manifest = _write_step(str(tmp_path), 1, world=2, tables=tables)
    out = ckpt.read_sparse_tables(str(tmp_path), 1, manifest)
    assert out["emb"]["indices"].size == 0
    np.testing.assert_array_equal(out["emb"]["w"], tables["emb"]["w"])


def test_manifest_nnz_mismatch_raises(tmp_path):
    rs = np.random.RandomState(3)
    tables = _tables(rs, [4, 8])
    manifest = _write_step(str(tmp_path), 2, world=2, tables=tables)
    manifest["sparse"][0]["nnz"] = 99
    import pytest

    from mxnet_tpu.base import MXNetError

    with pytest.raises(MXNetError):
        ckpt.read_sparse_tables(str(tmp_path), 2, manifest)


def test_kvstore_save_resume_full_stack(tmp_path):
    """Local-store end to end: sparse fit → Checkpointer.save_sharded →
    fresh store load_sharded_checkpoint → weights, state rows, touched set
    and update counts all bit-identical."""
    rs = np.random.RandomState(4)
    kv = mx.kv.create("local")
    opt = mx.optimizer.Adam(learning_rate=0.01)
    kv.set_optimizer(opt)
    w0 = rs.rand(V, D).astype("float32")
    kv.init("emb", mx.nd.array(w0))
    for _ in range(3):
        ids = rs.randint(0, V, (6,))
        og = rs.rand(6, D).astype("float32")
        kv.push("emb", embedding_backward(ids, mx.nd.array(og), V))
    writer = ckpt.Checkpointer(str(tmp_path))
    try:
        writer.save_sharded(kv, 9, block=True)
    finally:
        writer.close()
    manifest = ckpt.load_manifest(str(tmp_path), 9)
    assert manifest["sparse"] and manifest["plan"]["buckets"] == []

    kv2 = mx.kv.create("local")
    opt2 = mx.optimizer.Adam(learning_rate=0.01)
    kv2.set_optimizer(opt2)
    kv2.init("emb", mx.nd.zeros((V, D)))
    step, _ = kv2.load_sharded_checkpoint(str(tmp_path))
    assert step == 9
    a = mx.nd.zeros((V, D))
    kv.pull("emb", out=a)
    b = mx.nd.zeros((V, D))
    kv2.pull("emb", out=b)
    np.testing.assert_array_equal(a.asnumpy(), b.asnumpy())
    st1, st2 = kv._updater.states["emb"], kv2._updater.states["emb"]
    assert isinstance(st2, RowSparseState)
    np.testing.assert_array_equal(st1.indices, st2.indices)
    for x, y in zip(st1.rows, st2.rows):
        np.testing.assert_array_equal(x, y)
    assert opt2._index_update_count == opt._index_update_count
    # and the resumed store trains on identically: one more identical round
    ids = rs.randint(0, V, (6,))
    og = rs.rand(6, D).astype("float32")
    kv.push("emb", embedding_backward(ids, mx.nd.array(og), V))
    kv2.push("emb", embedding_backward(ids, mx.nd.array(og), V))
    kv.pull("emb", out=a)
    kv2.pull("emb", out=b)
    np.testing.assert_array_equal(a.asnumpy(), b.asnumpy())


def test_save_optimizer_states_keeps_dense_keys_next_to_sparse(tmp_path):
    """Regression: a mixed store (sparse table + dense FC) must persist BOTH
    keys' optimizer state through save/load_optimizer_states — an early
    sparse-only reroute into the sharded writer silently dropped every
    dense key's momentum."""
    rs = np.random.RandomState(6)
    kv = mx.kv.create("local")
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9))
    kv.init("emb", mx.nd.array(rs.rand(V, D).astype("float32")))
    kv.init("fc_weight", mx.nd.array(rs.rand(8, 4).astype("float32")))
    ids = rs.randint(0, V, (5,))
    og = rs.rand(5, D).astype("float32")
    kv.push("emb", embedding_backward(ids, mx.nd.array(og), V))
    kv.push("fc_weight", mx.nd.array(rs.rand(8, 4).astype("float32")))
    path = str(tmp_path / "opt.states")
    kv.save_optimizer_states(path)
    kv2 = mx.kv.create("local")
    kv2.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9))
    kv2.load_optimizer_states(path)
    assert set(kv2._updater.states) == {"emb", "fc_weight"}
    assert isinstance(kv2._updater.states["emb"], RowSparseState)
    np.testing.assert_array_equal(
        kv2._updater.states["fc_weight"].asnumpy(),
        kv._updater.states["fc_weight"].asnumpy())


def test_updater_state_pickle_round_trip():
    """RowSparseState must survive the classic per-key state pickle
    (save_optimizer_states' replicated path)."""
    rs = np.random.RandomState(5)
    kv = mx.kv.create("local")
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9))
    kv.init("emb", mx.nd.array(rs.rand(V, D).astype("float32")))
    ids = rs.randint(0, V, (5,))
    og = rs.rand(5, D).astype("float32")
    kv.push("emb", embedding_backward(ids, mx.nd.array(og), V))
    blob = kv._updater.get_states()
    kv2 = mx.kv.create("local")
    kv2.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9))
    kv2._updater.set_states(blob)
    st1, st2 = kv._updater.states["emb"], kv2._updater.states["emb"]
    np.testing.assert_array_equal(st1.indices, st2.indices)
    for x, y in zip(st1.rows, st2.rows):
        np.testing.assert_array_equal(x, y)
