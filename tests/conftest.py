"""Test configuration: run everything on a virtual 8-device CPU mesh.

The reference tests multi-device logic on CPU by mapping ctx groups to
mx.cpu(0)/mx.cpu(1) (SURVEY.md §4 "multi-device-without-GPUs trick"). The JAX
equivalent is --xla_force_host_platform_device_count: 8 virtual CPU devices,
so sharding/collective paths compile and run without TPU hardware. Must be set
before jax is imported anywhere.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the session env presets the TPU platform
os.environ["MXNET_DEFAULT_CONTEXT"] = "cpu"  # default ctx → virtual CPU devices
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
