"""Test configuration: run everything on a virtual 8-device CPU mesh.

The reference tests multi-device logic on CPU by mapping ctx groups to
mx.cpu(0)/mx.cpu(1) (SURVEY.md §4 "multi-device-without-GPUs trick"). The JAX
equivalent is --xla_force_host_platform_device_count: 8 virtual CPU devices,
so sharding/collective paths compile and run without TPU hardware.

This image's sitecustomize imports jax at interpreter startup (with
JAX_PLATFORMS=axon preset), so mutating os.environ["JAX_PLATFORMS"] here is
too late — the platform must be forced through jax.config before any backend
is initialized. XLA_FLAGS is still read at CPU-client creation, so the
virtual-device count can be injected via the environment.
"""
import os

os.environ["MXNET_DEFAULT_CONTEXT"] = "cpu"  # default ctx → virtual CPU devices
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax

jax.config.update("jax_platforms", "cpu")
