"""Speculative decoding (mxnet_tpu/serving/speculative.py,
docs/SERVING.md §Prefix cache & speculative decoding): the accept/
rollback protocol is TOKEN-IDENTICAL to non-speculative greedy decode
no matter how good or bad the draft is, full-accept rounds re-sync the
draft, rejections release pages, and the steady state never compiles."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.models import transformer as tfm
from mxnet_tpu.serving import PagedKVDecoder, SpeculativeDecoder
from mxnet_tpu.serving.speculative import spec_decode_enabled, spec_gamma

CFG = dict(vocab_size=50, num_layers=2, num_heads=2, model_dim=32,
           ffn_dim=64)
SERVE = dict(max_len=32, page_size=4, lanes=1, prefill_len=8, pos_len=32,
             prefix_cache=False)


@pytest.fixture
def tm():
    telemetry.reset()
    telemetry.clear_events()
    saved = telemetry.current_override()
    yield telemetry
    telemetry.set_mode(saved)
    telemetry.reset()
    telemetry.clear_events()


def _trained_params(S, seed=0):
    net = tfm.get_symbol(seq_len=S, **CFG)
    exe = net.simple_bind(mx.cpu(), grad_req="null", data=(1, S),
                          softmax_label=(1, S))
    rs = np.random.RandomState(seed)
    params = {}
    for name, arr in exe.arg_dict.items():
        if name in ("data", "softmax_label"):
            continue
        w = (rs.randn(*arr.shape) * 0.1).astype("float32")
        arr[:] = w
        params[name] = w
    return params


def _want(params, prompt, n):
    """Oracle: plain non-speculative greedy on the target alone."""
    dec = PagedKVDecoder(params, **CFG, **SERVE)
    return dec.greedy([prompt], n, k=1)[0]


def test_env_knobs(monkeypatch):
    monkeypatch.delenv("MXNET_SPEC_DECODE", raising=False)
    monkeypatch.delenv("MXNET_SPEC_GAMMA", raising=False)
    assert not spec_decode_enabled() and spec_gamma() == 4
    monkeypatch.setenv("MXNET_SPEC_DECODE", "on")
    monkeypatch.setenv("MXNET_SPEC_GAMMA", "7")
    assert spec_decode_enabled() and spec_gamma() == 7
    monkeypatch.setenv("MXNET_SPEC_GAMMA", "junk")
    assert spec_gamma(3) == 3
    monkeypatch.setenv("MXNET_SPEC_GAMMA", "-2")
    assert spec_gamma(3) == 3


def test_spec_greedy_token_identical_truncated_draft(tm):
    """The ci parity bar: a 1-layer draft truncated from the 2-layer
    target's own checkpoint (positional weight names) speculates, and
    the emitted stream is token-identical to non-speculative greedy —
    with zero post-warmup compiles or retraces."""
    tm.set_mode("counters")
    params = _trained_params(32)
    rs = np.random.RandomState(7)
    prompt = rs.randint(1, CFG["vocab_size"], (5,)).astype(np.float32)
    want = _want(params, prompt, 18)

    spec = SpeculativeDecoder.build(params, draft_layers=1, gamma=3,
                                    **CFG, **SERVE).warmup()
    c0 = telemetry.counters()
    got = spec.greedy(prompt, 18)
    c1 = telemetry.counters()
    np.testing.assert_array_equal(got, want)
    assert c1.get("spec.proposed_tokens", 0) > 0
    assert c1.get("spec.accepted_tokens", 0) >= 0
    assert c1.get("executor.compile", 0) == c0.get("executor.compile", 0)
    assert c1.get("executor.retrace", 0) == c0.get("executor.retrace", 0)
    # every page released: both decoders fully retired their lanes
    assert spec.target.stats()["pages_in_use"] == 0
    assert spec.draft.stats()["pages_in_use"] == 0


def test_spec_full_accept_self_draft_resyncs(tm):
    """Draft == target (draft_layers == num_layers): every proposal is
    accepted, no round ever rolls back, and the catch-up step keeps the
    pair position-aligned across rounds."""
    tm.set_mode("counters")
    params = _trained_params(32)
    rs = np.random.RandomState(11)
    prompt = rs.randint(1, CFG["vocab_size"], (4,)).astype(np.float32)
    want = _want(params, prompt, 16)

    spec = SpeculativeDecoder.build(params, draft_layers=CFG["num_layers"],
                                    gamma=4, **CFG, **SERVE).warmup()
    got = spec.greedy(prompt, 16)
    c = telemetry.counters()
    np.testing.assert_array_equal(got, want)
    assert c.get("spec.accepted_tokens", 0) == c.get("spec.proposed_tokens")
    assert c.get("spec.rollbacks", 0) == 0


def test_spec_hostile_draft_still_token_identical(tm):
    """Acceptance may hit ZERO (a draft with unrelated random weights):
    rounds then emit exactly the target's own token, rollbacks release
    the rejected pages, and the output is STILL token-identical — the
    draft can only cost dispatches, never change the stream."""
    tm.set_mode("counters")
    params = _trained_params(32, seed=0)
    hostile = _trained_params(32, seed=99)
    rs = np.random.RandomState(13)
    prompt = rs.randint(1, CFG["vocab_size"], (5,)).astype(np.float32)
    want = _want(params, prompt, 14)

    target = PagedKVDecoder(params, **CFG, **SERVE)
    draft = PagedKVDecoder(hostile, model_key="spec_hostile_draft",
                           **CFG, **SERVE)
    spec = SpeculativeDecoder(target, draft, gamma=4).warmup()
    got = spec.greedy(prompt, 14)
    c = telemetry.counters()
    np.testing.assert_array_equal(got, want)
    assert c.get("spec.rollbacks", 0) >= 1
    assert c.get("spec.accepted_tokens", 0) < c.get("spec.proposed_tokens")
    assert target.stats()["pages_in_use"] == 0
    assert draft.stats()["pages_in_use"] == 0
