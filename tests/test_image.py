"""Image pipeline tests (reference strategy: test_io.py ImageRecordIter
checks + augmenter unit checks over deterministic images)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image, recordio

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


@pytest.fixture(scope="module")
def rec_pack(tmp_path_factory):
    """8 deterministic images (2 classes) packed via tools/im2rec.py."""
    from PIL import Image

    tmp = tmp_path_factory.mktemp("imgs")
    root = tmp / "imgs"
    for ci, cls in enumerate(("a", "b")):
        (root / cls).mkdir(parents=True)
        for i in range(4):
            arr = np.full((40, 48, 3), 40 * ci + 10 * i, np.uint8)
            Image.fromarray(arr).save(str(root / cls / ("%d.png" % i)))
    prefix = str(tmp / "pack")
    subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "im2rec.py"), prefix,
         str(root), "--shuffle", "0", "--encoding", ".png"],
        check=True, cwd=ROOT)
    return prefix


def test_image_record_iter_shapes_and_labels(rec_pack):
    it = image.ImageRecordIter(
        path_imgrec=rec_pack + ".rec", data_shape=(3, 32, 32), batch_size=4,
        preprocess_threads=2)
    batches = list(it)
    assert len(batches) == 2
    for b in batches:
        assert b.data[0].shape == (4, 3, 32, 32)
        assert b.label[0].shape == (4,)
    labels = np.concatenate([b.label[0].asnumpy() for b in batches])
    np.testing.assert_array_equal(np.sort(labels), [0, 0, 0, 0, 1, 1, 1, 1])
    # PNG round-trip of constant images: decoded pixels == written values
    # (records are unshuffled: class a images are 0,10,20,30)
    first = batches[0].data[0].asnumpy()
    np.testing.assert_allclose(
        sorted(first[i].mean() for i in range(4)), [0.0, 10.0, 20.0, 30.0], atol=1.0)


def test_image_record_iter_mean_sub_and_mirror(rec_pack):
    it = image.ImageRecordIter(
        path_imgrec=rec_pack + ".rec", data_shape=(3, 32, 32), batch_size=8,
        mean_r=10.0, mean_g=10.0, mean_b=10.0, rand_mirror=True,
        shuffle=True, seed=3, preprocess_threads=1)
    b = next(iter(it))
    assert b.data[0].shape == (8, 3, 32, 32)
    # mean got subtracted: constant-10 image becomes ~0 somewhere in the batch
    mins = [abs(b.data[0].asnumpy()[i].mean()) for i in range(8)]
    assert min(mins) < 1.0


def test_image_record_iter_sharding(rec_pack):
    parts = []
    for part in range(2):
        it = image.ImageRecordIter(
            path_imgrec=rec_pack + ".rec", data_shape=(3, 32, 32),
            batch_size=4, num_parts=2, part_index=part, preprocess_threads=1)
        parts.append(np.concatenate([b.label[0].asnumpy() for b in it]))
    # the two shards partition the dataset
    merged = np.sort(np.concatenate(parts))
    np.testing.assert_array_equal(merged, [0, 0, 0, 0, 1, 1, 1, 1])


def test_image_record_iter_shard_smaller_than_batch(rec_pack):
    # 2-record shard with batch_size=8: pad by cycling, no crash
    it = image.ImageRecordIter(
        path_imgrec=rec_pack + ".rec", data_shape=(3, 32, 32), batch_size=8,
        num_parts=4, part_index=0, preprocess_threads=1)
    b = next(iter(it))
    assert b.data[0].shape == (8, 3, 32, 32)
    assert b.pad == 6


def test_augmenters_deterministic():
    img = np.arange(48 * 64 * 3, dtype=np.uint8).reshape(48, 64, 3)
    out = image.resize_short(img, 32)
    assert min(out.shape[:2]) == 32
    cropped, _ = image.center_crop(img, (32, 32))
    assert cropped.shape == (32, 32, 3)
    rng = __import__("random").Random(0)
    rc, (x0, y0, w, h) = image.random_crop(img, (20, 16), rng)
    assert rc.shape == (16, 20, 3) and 0 <= x0 <= 44 and 0 <= y0 <= 32
    normed = image.color_normalize(img, np.float32(128.0), np.float32(2.0))
    np.testing.assert_allclose(normed, (img.astype(np.float32) - 128) / 2)


def test_image_det_iter(tmp_path):
    """Detection labels [cls,x0,y0,x1,y1]×k round-trip with -1 padding."""
    from PIL import Image as PILImage

    rec = recordio.MXIndexedRecordIO(str(tmp_path / "d.idx"), str(tmp_path / "d.rec"), "w")
    rs = np.random.RandomState(0)
    for i in range(4):
        img = rs.randint(0, 255, (32, 32, 3), np.uint8)
        import io as _bio

        bio = _bio.BytesIO()
        PILImage.fromarray(img).save(bio, format="PNG")
        label = np.array([[i % 2, 0.1, 0.1, 0.5, 0.5],
                          [1, 0.2, 0.2, 0.8, 0.9]], np.float32).ravel()
        header = recordio.IRHeader(0, label, i, 0)
        rec.write_idx(i, recordio.pack(header, bio.getvalue()))
    rec.close()

    it = image.ImageDetIter(
        path_imgrec=str(tmp_path / "d.rec"), data_shape=(3, 32, 32),
        batch_size=2, max_objects=4, preprocess_threads=1)
    b = next(iter(it))
    lab = b.label[0].asnumpy()
    assert lab.shape == (2, 4, 5)
    np.testing.assert_allclose(lab[0, 0], [0, 0.1, 0.1, 0.5, 0.5], atol=1e-6)
    np.testing.assert_allclose(lab[0, 1], [1, 0.2, 0.2, 0.8, 0.9], atol=1e-6)
    assert (lab[0, 2:] == -1).all()


def test_image_iter_from_list(rec_pack):
    lst = rec_pack + ".lst"
    root = os.path.join(os.path.dirname(rec_pack), "imgs")
    it = image.ImageIter(batch_size=4, data_shape=(3, 24, 24),
                         path_imglist=lst, path_root=root)
    b = next(iter(it))
    assert b.data[0].shape == (4, 3, 24, 24)


def test_rec_iter_feeds_module(rec_pack):
    """End-to-end: ImageRecordIter → Module.fit runs a full epoch."""
    from mxnet_tpu import models

    it = image.ImageRecordIter(
        path_imgrec=rec_pack + ".rec", data_shape=(3, 28, 28), batch_size=4,
        shuffle=True, preprocess_threads=2)
    net = models.get_symbol("lenet", num_classes=2)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.01}, eval_metric="acc",
            initializer=mx.init.Xavier())


def _pack_det_rec(tmp_path, n, img_fn, label_fn, size=64):
    """Pack n records whose images+labels come from callbacks."""
    from PIL import Image as PILImage
    import io as _bio

    rec = recordio.MXIndexedRecordIO(str(tmp_path / "det.idx"),
                                     str(tmp_path / "det.rec"), "w")
    for i in range(n):
        bio = _bio.BytesIO()
        PILImage.fromarray(img_fn(i)).save(bio, format="PNG")
        header = recordio.IRHeader(0, np.asarray(label_fn(i), np.float32),
                                   i, 0)
        rec.write_idx(i, recordio.pack(header, bio.getvalue()))
    rec.close()
    return str(tmp_path / "det.rec")


def _recover_box(chw):
    """Normalized bbox of the bright rectangle in a CHW float image."""
    mask = chw[0] > 128.0
    ys, xs = np.where(mask)
    h, w = chw.shape[1:]
    return (xs.min() / w, ys.min() / h, (xs.max() + 1) / w, (ys.max() + 1) / h)


def test_image_det_iter_native_bbox_transform(tmp_path):
    """VERDICT r4 #9: ImageDetIter rides the native pipeline bbox-aware.
    Oracle: a bright rectangle drawn exactly at the bbox — after native
    random crop + mirror, the rectangle recovered from the output PIXELS
    must coincide with the transformed label box, sample by sample."""
    from mxnet_tpu import image_native

    if not image_native.available():
        pytest.skip("no native image pipeline toolchain")

    size, out = 64, 48
    box = (0.25, 0.375, 0.625, 0.75)  # normalized, off-center

    def img_fn(i):
        a = np.zeros((size, size, 3), np.uint8)
        a[int(box[1] * size):int(box[3] * size),
          int(box[0] * size):int(box[2] * size)] = 255
        return a

    path = _pack_det_rec(tmp_path, 16, img_fn, lambda i: [1.0, *box])
    it = image.ImageDetIter(
        path_imgrec=path, data_shape=(3, out, out), batch_size=16,
        rand_crop=True, rand_mirror=True, max_objects=4, seed=3)
    assert it._native is not None, "det iter did not engage the native path"
    batch = it.next()
    data = batch.data[0].asnumpy()
    labels = batch.label[0].asnumpy()
    assert labels.shape == (16, 4, 5)
    for j in range(16):
        rows = labels[j][labels[j][:, 0] >= 0]
        assert len(rows) == 1, labels[j]
        assert rows[0, 0] == 1.0
        got = _recover_box(data[j])
        # box corners may be clipped by the crop; compare against the
        # clipped label with ~2px tolerance
        np.testing.assert_allclose(got, rows[0, 1:], atol=2.5 / out)


def test_image_det_iter_native_matches_python_labels(tmp_path, monkeypatch):
    """With no geometric augments (image == data_shape) the native det
    labels must equal the Python path's -1-padded rows exactly."""
    from mxnet_tpu import image_native

    if not image_native.available():
        pytest.skip("no native image pipeline toolchain")

    rs = np.random.RandomState(0)
    labels = [[i % 3, 0.1, 0.2, 0.6, 0.8, 2, 0.3, 0.3, 0.7, 0.9]
              for i in range(6)]
    path = _pack_det_rec(
        tmp_path, 6, lambda i: rs.randint(0, 255, (32, 32, 3), np.uint8),
        lambda i: labels[i], size=32)
    kw = dict(path_imgrec=path, data_shape=(3, 32, 32), batch_size=6,
              max_objects=3)
    it_nat = image.ImageDetIter(**kw)
    assert it_nat._native is not None
    nat = it_nat.next().label[0].asnumpy()

    monkeypatch.setenv("MXNET_NATIVE_IMAGE_PIPELINE", "0")
    it_py = image.ImageDetIter(**kw)
    assert it_py._native is None
    py = it_py.next().label[0].asnumpy()
    np.testing.assert_allclose(nat, py, atol=1e-6)
