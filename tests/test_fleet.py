"""Distributed serving fleet (mxnet_tpu/serving/fleet/, docs/SERVING.md
§Fleet): router dispatch policy against in-process fake replicas
(load-aware pick, degraded/latched skip, stale-snapshot discard,
fleet-saturated shed, dead-replica re-dispatch with zero lost requests,
rollout drain + abort-on-bad-swap), supervisor spawn/restart/heartbeat
machinery against a lightweight stand-in worker, the RPC framing layer,
the fleet.* fault-injection sites, and the health() seq/snapshot_ms
staleness satellite."""
import sys
import threading
import time

import numpy as np
import pytest

from mxnet_tpu.base import MXNetError
from mxnet_tpu import faultinject, telemetry
from mxnet_tpu.serving import ServeOverloadError
from mxnet_tpu.serving.fleet import (Router, ReplicaSupervisor,
                                     FleetRolloutError, RpcServer,
                                     RpcClient, RpcConnectionError)


# ---------------------------------------------------------------- fakes
class FakeReplica:
    """In-process replica implementing the RPC-handler protocol with
    scripted behavior: per-call transport faults, overloads, slow
    inference, frozen health snapshots, reload success/failure."""

    def __init__(self, rid, wait_ms=1.0, state="healthy"):
        self.rid = rid
        self.wait_ms = wait_ms
        self.state = state
        self.seq = 0
        self.pid = 40000 + rid
        self.served = 0
        self.fail_next = 0          # raise ConnectionError on next N infers
        self.overload_next = 0      # shed the next N infers
        self.infer_delay_s = 0.0
        self.frozen_health = None   # replay this dict (a corpse's numbers)
        self.health_raises = False
        self.reload_raises = False
        self.params_ver = 0
        self._prev_ver = None
        self.reload_times = []
        self.infer_done_times = []
        self._lock = threading.Lock()

    def health(self, **kw):
        if self.health_raises:
            raise ConnectionError("health: replica %d gone" % self.rid)
        if self.frozen_health is not None:
            return dict(self.frozen_health)
        self.seq += 1
        return {"state": self.state, "seq": self.seq,
                "snapshot_ms": time.time() * 1000.0,
                "ewma_queue_wait_ms": self.wait_ms, "pid": self.pid,
                "queue_depth": 0}

    def infer(self, inputs, deadline_ms=None, **kw):
        with self._lock:
            if self.fail_next > 0:
                self.fail_next -= 1
                raise ConnectionError("infer: replica %d died" % self.rid)
            if self.overload_next > 0:
                self.overload_next -= 1
                raise ServeOverloadError("replica %d saturated" % self.rid,
                                         retry_after_ms=25)
        if self.infer_delay_s:
            time.sleep(self.infer_delay_s)
        with self._lock:
            self.served += 1
            self.infer_done_times.append(time.perf_counter())
        return [np.full((2, 4), self.rid, np.float32)]

    def reload(self, arg_params, aux_params=None, **kw):
        if self.reload_raises:
            raise MXNetError("swap refused on replica %d" % self.rid)
        with self._lock:
            self._prev_ver = self.params_ver
            self.params_ver += 1
            self.reload_times.append(time.perf_counter())
        return True

    def rollback(self, **kw):
        with self._lock:
            if self._prev_ver is None:
                raise MXNetError("nothing to roll back")
            self.params_ver = self._prev_ver
            self._prev_ver = None
        return True


def make_router(fakes, **kw):
    kw.setdefault("workers", 4)
    kw.setdefault("health_interval_ms", 20)
    kw.setdefault("stale_ms", 400)
    kw.setdefault("dispatch_wait_ms", 2000)
    return Router(lambda: fakes, **kw)


@pytest.fixture
def payload():
    return {"data": np.zeros((2, 3), np.float32)}


def _wait_fresh(router, n, timeout=3.0):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        h = router.health()
        if sum(1 for d in h["replicas"].values() if d["fresh"]) >= n:
            return h
        time.sleep(0.02)
    raise AssertionError("views never became fresh: %s" % router.health())


# ------------------------------------------------------------ dispatch
def test_load_aware_pick_prefers_lowest_wait(payload):
    fakes = {0: FakeReplica(0, wait_ms=2.0), 1: FakeReplica(1, wait_ms=80.0)}
    with make_router(fakes) as r:
        _wait_fresh(r, 2)
        futs = [r.submit(payload) for _ in range(12)]
        for f in futs:
            f.result(timeout=5)
    assert fakes[0].served == 12
    assert fakes[1].served == 0


def test_prefix_affinity_pins_key_to_rendezvous_replica(payload):
    """Requests carrying a prefix_key land on the HRW-assigned replica
    even when EWMA load-awareness would pick a lighter one — that is
    where the prefix's KV pages live; plain requests are untouched."""
    fakes = {i: FakeReplica(i, wait_ms=1.0 + 40.0 * i) for i in range(3)}
    telemetry.reset()
    telemetry.set_mode("counters")
    try:
        with make_router(fakes) as r:
            _wait_fresh(r, 3)
            key = "prefix-chain-abc123"
            target = r._affinity_target(key)
            futs = [r.submit(payload, prefix_key=key) for _ in range(8)]
            for f in futs:
                f.result(timeout=5)
            assert fakes[target].served == 8
            # the same key maps to the same replica, call after call
            assert all(r._affinity_target(key) == target
                       for _ in range(4))
            c = telemetry.counters()
            assert c.get("fleet.affinity_hits", 0) == 8
            assert c.get("fleet.affinity_fallbacks", 0) == 0
            # plain traffic still follows EWMA to the lightest replica
            r.infer(payload, timeout=5)
            assert fakes[0].served >= (1 if target != 0 else 9)
    finally:
        telemetry.set_mode(None)
        telemetry.reset()


def test_prefix_affinity_falls_back_when_target_unhealthy(payload):
    """Health and freshness outrank page locality: latch the assigned
    replica and the key's traffic reroutes through the load-aware pick,
    counting fleet.affinity_fallbacks."""
    fakes = {i: FakeReplica(i, wait_ms=1.0 + 10.0 * i) for i in range(3)}
    telemetry.reset()
    telemetry.set_mode("counters")
    try:
        with make_router(fakes) as r:
            _wait_fresh(r, 3)
            key = "prefix-chain-def456"
            target = r._affinity_target(key)
            fakes[target].state = "latched"
            _wait_fresh(r, 3)
            time.sleep(0.1)
            for _ in range(5):
                r.infer(payload, timeout=5, prefix_key=key)
            assert fakes[target].served == 0
            others = [f.served for rid, f in fakes.items() if rid != target]
            assert sum(others) == 5
            c = telemetry.counters()
            assert c.get("fleet.affinity_fallbacks", 0) == 5
    finally:
        telemetry.set_mode(None)
        telemetry.reset()


def test_prefix_affinity_disabled_by_env(payload, monkeypatch):
    """MXNET_FLEET_AFFINITY=0: prefix keys are ignored and dispatch is
    pure EWMA — byte-for-byte the pre-affinity policy."""
    monkeypatch.setenv("MXNET_FLEET_AFFINITY", "0")
    fakes = {0: FakeReplica(0, wait_ms=2.0), 1: FakeReplica(1, wait_ms=80.0)}
    with make_router(fakes) as r:
        _wait_fresh(r, 2)
        futs = [r.submit(payload, prefix_key="anything") for _ in range(6)]
        for f in futs:
            f.result(timeout=5)
    assert fakes[0].served == 6 and fakes[1].served == 0


def test_degraded_and_latched_skip(payload):
    fakes = {0: FakeReplica(0, wait_ms=1.0, state="degraded"),
             1: FakeReplica(1, wait_ms=90.0),
             2: FakeReplica(2, wait_ms=1.0, state="latched")}
    with make_router(fakes) as r:
        _wait_fresh(r, 3)
        for _ in range(5):
            r.infer(payload, timeout=5)
        # the slow-but-healthy replica wins over faster degraded/latched
        assert fakes[1].served == 5
        assert fakes[0].served == 0 and fakes[2].served == 0
        # with NO healthy replica left, degraded still beats shedding
        fakes[1].state = "latched"
        _wait_fresh(r, 3)
        time.sleep(0.1)
        r.infer(payload, timeout=5)
        assert fakes[0].served == 1


def test_stale_snapshot_discarded(payload):
    """A dead replica's last-good numbers must not attract traffic: a
    frozen (seq/snapshot_ms replay) health response is discarded and the
    replica ages out of eligibility."""
    telemetry.reset()
    telemetry.set_mode("counters")
    try:
        fakes = {0: FakeReplica(0, wait_ms=1.0),
                 1: FakeReplica(1, wait_ms=50.0)}
        with make_router(fakes) as r:
            _wait_fresh(r, 2)
            # freeze replica 0's snapshot — same seq, same snapshot_ms,
            # flattering wait estimate
            fakes[0].frozen_health = fakes[0].health()
            deadline = time.perf_counter() + 3.0
            while time.perf_counter() < deadline:
                if not r.health()["replicas"][0]["fresh"]:
                    break
                time.sleep(0.02)
            assert not r.health()["replicas"][0]["fresh"]
            for _ in range(4):
                r.infer(payload, timeout=5)
            assert fakes[1].served == 4
            assert fakes[0].served == 0
        assert telemetry.counters().get("fleet.stale_health_discards", 0) > 0
    finally:
        telemetry.set_mode(None)
        telemetry.reset()


def test_fleet_saturated_shed_with_retry_after(payload):
    fakes = {0: FakeReplica(0, wait_ms=5000.0),
             1: FakeReplica(1, wait_ms=9000.0)}
    with make_router(fakes, shed_ms=1000.0) as r:
        _wait_fresh(r, 2)
        with pytest.raises(ServeOverloadError) as ei:
            r.submit(payload)
        assert ei.value.retry_after_ms >= 1000
        # deadline-aware shed too: budget below the best estimate
        with pytest.raises(ServeOverloadError):
            r.submit(payload, deadline_ms=100)
    # no replica eligible at all -> shed with retry_after, not a hang
    with make_router({}, stale_ms=100) as r:
        with pytest.raises(ServeOverloadError) as ei:
            r.submit(payload)
        assert ei.value.retry_after_ms > 0


def test_dead_replica_redispatch_zero_lost(payload):
    """Kill the preferred replica with requests in flight: every one of
    them re-dispatches to the survivor — zero lost, zero hung."""
    telemetry.reset()
    telemetry.set_mode("counters")
    try:
        fakes = {0: FakeReplica(0, wait_ms=1.0),
                 1: FakeReplica(1, wait_ms=60.0)}
        with make_router(fakes, workers=4) as r:
            _wait_fresh(r, 2)
            # replica 0 dies for the next 6 calls (in-flight + queued),
            # and its health endpoint dies with it
            fakes[0].fail_next = 6
            fakes[0].health_raises = True
            futs = [r.submit(payload) for _ in range(6)]
            outs = [f.result(timeout=10) for f in futs]
            # let the poller observe the dead health endpoint too
            deadline = time.perf_counter() + 3.0
            while time.perf_counter() < deadline and \
                    not telemetry.counters().get(
                        "fleet.health_poll_errors", 0):
                time.sleep(0.02)
        for o in outs:
            assert o[0][0, 0] == 1.0  # everyone landed on the survivor
        assert fakes[1].served == 6
        assert telemetry.counters().get("fleet.redispatches", 0) >= 1
        assert telemetry.counters().get("fleet.health_poll_errors", 0) >= 1
    finally:
        telemetry.set_mode(None)
        telemetry.reset()


def test_redispatch_budget_exhausted_fails_structured(payload):
    from mxnet_tpu.serving.fleet import FleetDispatchError

    fakes = {0: FakeReplica(0)}
    fakes[0].fail_next = 10
    with make_router(fakes, max_redispatch=2,
                     dispatch_wait_ms=500) as r:
        _wait_fresh(r, 1)
        fut = r.submit(payload)
        with pytest.raises(FleetDispatchError, match="re-dispatches"):
            fut.result(timeout=10)


def test_replica_overload_tries_next_then_sheds(payload):
    fakes = {0: FakeReplica(0, wait_ms=1.0), 1: FakeReplica(1, wait_ms=2.0)}
    with make_router(fakes) as r:
        _wait_fresh(r, 2)
        # preferred replica sheds once -> request lands on the other
        fakes[0].overload_next = 1
        out = r.infer(payload, timeout=5)
        assert fakes[0].served + fakes[1].served == 1
        # the WHOLE fleet shedding propagates the overload to the client
        fakes[0].overload_next = 5
        fakes[1].overload_next = 5
        fut = r.submit(payload)
        with pytest.raises(ServeOverloadError):
            fut.result(timeout=10)


# -------------------------------------------------------------- rollout
def test_rollout_drains_then_swaps_every_replica(payload):
    fakes = {0: FakeReplica(0, wait_ms=1.0), 1: FakeReplica(1, wait_ms=2.0)}
    fakes[0].infer_delay_s = 0.3
    with make_router(fakes) as r:
        _wait_fresh(r, 2)
        fut = r.submit(payload)  # in flight on replica 0 for ~300ms
        time.sleep(0.05)
        res = r.rollout({"w": np.zeros(3, np.float32)},
                        drain_timeout_s=5.0)
        fut.result(timeout=5)
        assert sorted(res["applied"]) == [0, 1]
        assert fakes[0].params_ver == 1 and fakes[1].params_ver == 1
        # the drain ordering: replica 0's swap happened only after its
        # in-flight request delivered
        assert fakes[0].reload_times[0] > fakes[0].infer_done_times[0]


def test_rollout_abort_rolls_back_swapped_replicas(payload):
    telemetry.reset()
    telemetry.set_mode("counters")
    try:
        fakes = {0: FakeReplica(0), 1: FakeReplica(1), 2: FakeReplica(2)}
        fakes[2].reload_raises = True  # third swap fails
        with make_router(fakes) as r:
            _wait_fresh(r, 3)
            with pytest.raises(FleetRolloutError, match="rolled back"):
                r.rollout({"w": np.zeros(3, np.float32)})
            # old weights live fleet-wide: 0 and 1 swapped then rolled back
            assert fakes[0].params_ver == 0
            assert fakes[1].params_ver == 0
            assert fakes[2].params_ver == 0
            # serving continues after the abort
            r.infer(payload, timeout=5)
        assert telemetry.counters().get("fleet.rollout_aborts", 0) == 1
    finally:
        telemetry.set_mode(None)
        telemetry.reset()


# --------------------------------------------------------- faultinject
def test_fleet_dispatch_site_drives_redispatch(payload):
    fakes = {0: FakeReplica(0)}
    with make_router(fakes) as r:
        _wait_fresh(r, 1)
        faultinject.reset_stats()
        with faultinject.inject("fleet.dispatch", "raise", prob=1.0,
                                seed=3, times=1):
            out = r.infer(payload, timeout=10)
        assert faultinject.stats().get("fleet.dispatch:raise") == 1
        assert r.health()["counts"]["redispatched"] == 1
    assert out[0][0, 0] == 0.0


def test_wedged_health_poll_does_not_stale_the_fleet(payload):
    """One replica whose health RPC wedges must cost only ITSELF
    freshness: polls run per-replica-concurrent (with an in-flight
    guard), so the survivor's view stays fresh and keeps serving."""
    fakes = {0: FakeReplica(0, wait_ms=1.0), 1: FakeReplica(1, wait_ms=5.0)}
    orig = fakes[0].health

    def slow_health(**kw):
        time.sleep(1.2)  # way past stale_ms — a wedged replica
        return orig(**kw)

    with make_router(fakes, stale_ms=300) as r:
        _wait_fresh(r, 2)
        fakes[0].health = slow_health
        time.sleep(0.6)
        h = r.health()
        assert h["replicas"][1]["fresh"], h
        assert not h["replicas"][0]["fresh"], h
        r.infer(payload, timeout=5)
        assert fakes[1].served == 1


def test_fleet_health_site_starves_the_view(payload):
    """An injected health-poll fault makes the replica's snapshot stale —
    the router must stop dispatching on it (and recover once the
    injection stops)."""
    fakes = {0: FakeReplica(0, wait_ms=1.0), 1: FakeReplica(1, wait_ms=50.0)}
    with make_router(fakes, stale_ms=150) as r:
        _wait_fresh(r, 2)
        # the injection hits polls for BOTH replicas; give replica 0's
        # plan enough fires to starve it while 1 survives on p<1 misses
        with faultinject.inject("fleet.health", "raise", prob=1.0, seed=5):
            deadline = time.perf_counter() + 2.0
            while time.perf_counter() < deadline:
                h = r.health()
                if not any(d["fresh"] for d in h["replicas"].values()):
                    break
                time.sleep(0.02)
            assert not any(d["fresh"]
                           for d in r.health()["replicas"].values())
        _wait_fresh(r, 2)  # polls succeed again once injection stops
        r.infer(payload, timeout=5)


# ------------------------------------------------------------------ rpc
def test_rpc_roundtrip_errors_and_connection_loss():
    calls = []

    def echo(x):
        calls.append(x)
        return {"got": x, "arr": np.arange(6).reshape(2, 3)}

    def boom():
        raise ServeOverloadError("busy", retry_after_ms=7)

    srv = RpcServer({"echo": echo, "boom": boom}).start()
    addr = srv.addr
    try:
        cli = RpcClient(addr, timeout_s=5.0)
        out = cli.call("echo", x=3)
        assert out["got"] == 3
        np.testing.assert_array_equal(out["arr"], np.arange(6).reshape(2, 3))
        # remote structured errors arrive as their original type
        with pytest.raises(ServeOverloadError) as ei:
            cli.call("boom")
        assert ei.value.retry_after_ms == 7
        with pytest.raises(MXNetError, match="unknown method"):
            cli.call("nope")
    finally:
        srv.stop()
    # server gone: transport failure, not a hang
    cli2 = RpcClient(addr, timeout_s=1.0, connect_timeout_s=0.5)
    with pytest.raises(RpcConnectionError):
        cli2.call("echo", x=1)
    cli.close()


# ------------------------------------------------------------ supervisor
_FAKE_WORKER = r"""
import json, os, sys, time
spec = json.load(open(sys.argv[1]))
mode = spec.get("fake_mode", "ok")
if mode != "never_ready":
    with open(spec["port_file"] + ".tmp", "w") as f:
        f.write("127.0.0.1:1\n")
    os.replace(spec["port_file"] + ".tmp", spec["port_file"])
beats = 0
while True:
    if mode != "wedge" or beats < 2:
        with open(spec["heartbeat_path"], "a"):
            os.utime(spec["heartbeat_path"], None)
        beats += 1
    time.sleep(0.05)
"""


class StubSupervisor(ReplicaSupervisor):
    """Spawns a tiny stand-in worker (port file + heartbeats, no jax) so
    spawn/monitor/restart logic is testable in milliseconds."""

    def _spawn_cmd(self, h):
        return [sys.executable, "-c", _FAKE_WORKER, h.spec_path]


def _mk_sup(tmp_path, n=2, **kw):
    spec = {"model": "stub", "fake_mode": kw.pop("fake_mode", "ok")}
    kw.setdefault("restart_backoff_ms", 50)
    kw.setdefault("restart_backoff_max_ms", 400)
    kw.setdefault("dead_after_ms", 600)
    kw.setdefault("poll_interval_s", 0.05)
    return StubSupervisor(spec, n_replicas=n, workdir=str(tmp_path), **kw)


def test_supervisor_spawns_to_ready_and_restarts_dead(tmp_path):
    sup = _mk_sup(tmp_path, n=2)
    try:
        sup.start()
        sup.wait_ready(2, timeout_s=15)
        states = sup.states()
        pid0 = states[0]["pid"]
        assert all(d["state"] == "ready" for d in states.values())
        # kill replica 0: monitor must notice the exit and respawn it
        sup.kill_replica(0)
        deadline = time.perf_counter() + 15
        while time.perf_counter() < deadline:
            s = sup.states()[0]
            if s["state"] == "ready" and s["pid"] not in (None, pid0):
                break
            time.sleep(0.05)
        s = sup.states()[0]
        assert s["state"] == "ready" and s["pid"] != pid0
        assert s["restarts"] == 1
        assert sup.states()[1]["restarts"] == 0  # the peer never blinked
    finally:
        sup.stop()


def test_supervisor_kills_wedged_replica_on_stale_heartbeat(tmp_path):
    """A process that stops heartbeating but keeps its PID is dead for
    serving purposes: the monitor SIGKILLs and restarts it."""
    sup = _mk_sup(tmp_path, n=1, fake_mode="wedge", dead_after_ms=300)
    try:
        sup.start()
        sup.wait_ready(1, timeout_s=15)
        deadline = time.perf_counter() + 15
        while time.perf_counter() < deadline:
            if sup.states()[0]["restarts"] >= 1:
                break
            time.sleep(0.05)
        assert sup.states()[0]["restarts"] >= 1
    finally:
        sup.stop()


def test_supervisor_spawn_fault_injection_backs_off_and_retries(tmp_path):
    """An injected fleet.replica_spawn raise fails the first attempt; the
    capped backoff retries and the replica still comes up."""
    faultinject.reset_stats()
    sup = _mk_sup(tmp_path, n=1)
    try:
        with faultinject.inject("fleet.replica_spawn", "raise", prob=1.0,
                                seed=9, times=1):
            sup.start()
            sup.wait_ready(1, timeout_s=15)
        assert faultinject.stats().get("fleet.replica_spawn:raise") == 1
        assert sup.states()[0]["restarts"] >= 1  # the failed attempt
    finally:
        sup.stop()


def test_supervisor_backoff_is_capped(tmp_path):
    sup = _mk_sup(tmp_path, n=1, restart_backoff_ms=100,
                  restart_backoff_max_ms=250)
    h = sup._handles[0]
    now = time.perf_counter()
    delays = []
    with sup._lock:
        for _ in range(5):
            sup._note_death_locked(h, "test", now)
            delays.append(h.next_spawn_t - now)
    assert delays[0] == pytest.approx(0.1, abs=0.02)
    assert delays[-1] == pytest.approx(0.25, abs=0.02)  # capped
    assert all(b >= a - 1e-9 for a, b in zip(delays, delays[1:]))


# ------------------------------------------- engine health() staleness
def test_engine_health_seq_and_snapshot_ms_are_monotonic():
    """The satellite contract: every health() snapshot carries a strictly
    increasing seq and a wall-clock snapshot_ms — the fields the router's
    staleness check keys on."""
    from mxnet_tpu.serving import InferenceEngine, PersistentExecutableCache
    import mxnet_tpu as mx

    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                                name="fc")
    rs = np.random.RandomState(0)
    cache = PersistentExecutableCache(
        net, {"fc_weight": rs.randn(4, 6).astype("float32"),
              "fc_bias": np.zeros(4, "float32")})
    eng = InferenceEngine(cache, {"data": (6,)}, buckets=(1, 2))
    eng.start()
    try:
        t0 = time.time() * 1000.0
        h1 = eng.health()
        h2 = eng.health()
        assert h2["seq"] == h1["seq"] + 1
        assert t0 - 5000 < h1["snapshot_ms"] <= h2["snapshot_ms"]
        assert h2["snapshot_ms"] <= time.time() * 1000.0 + 5000
    finally:
        eng.close()


@pytest.mark.slow
def test_fleet_end_to_end_real_processes(tmp_path):
    """Full stack: 2 real replica subprocesses (jax + engine + RPC),
    routed inference, a hitless rollout, a SIGKILL + supervised restart,
    and zero lost requests throughout."""
    import mxnet_tpu as mx
    from mxnet_tpu import models
    from mxnet_tpu.serving.fleet import Fleet, save_params_npz

    item = (784,)
    net = models.get_symbol("mlp", num_classes=10)
    probe = net.simple_bind(mx.cpu(), grad_req="null", data=(1,) + item)
    rs = np.random.RandomState(0)
    arg_params = {k: (rs.randn(*a.shape) * 0.1).astype("float32")
                  for k, a in probe.arg_dict.items()
                  if k not in ("data", "softmax_label")}
    pp = str(tmp_path / "params.npz")
    save_params_npz(pp, arg_params)
    spec = {"model": "mlp", "model_kwargs": {"num_classes": 10},
            "item_shapes": {"data": list(item)}, "buckets": [1, 2, 4],
            "params": pp, "heartbeat_ms": 300}
    with Fleet(spec, n_replicas=2, workdir=str(tmp_path),
               router_kwargs=dict(health_interval_ms=100)) as fl:
        out = fl.router.infer({"data": rs.rand(2, 784).astype("float32")},
                              timeout=30)
        assert out[0].shape == (2, 10)
        new = {k: (v * 1.01).astype("float32")
               for k, v in arg_params.items()}
        res = fl.router.rollout(new)
        assert sorted(res["applied"]) == [0, 1]
        assert fl.supervisor.kill_replica(0) is not None
        for _ in range(10):
            fl.router.infer({"data": rs.rand(1, 784).astype("float32")},
                            timeout=30)
        fl.supervisor.wait_ready(2, timeout_s=120)
        assert fl.supervisor.states()[0]["restarts"] >= 1
        counts = fl.router.health()["counts"]
        assert counts["completed"] == counts["submitted"]


def test_fleet_rollout_recycles_unrolled_replicas(tmp_path):
    """Fleet.rollout closes the restart/mixed-weights hole: on success
    it rewrites the spec param file with the NEW weights and recycles
    every replica the router-level rollout could not swap, so a replica
    that restarts at any later point loads the rolled-out weights."""
    from mxnet_tpu.serving import fleet as fleet_mod
    from mxnet_tpu.serving.fleet import load_params_npz, save_params_npz

    params_path = str(tmp_path / "p.npz")
    save_params_npz(params_path, {"w": np.zeros(2, np.float32)})

    class StubSup:
        n_replicas = 3
        base_spec = {"params": params_path}
        killed = []

        def kill_replica(self, rid):
            self.killed.append(rid)

    class StubRouter:
        def rollout(self, arg_params, aux_params=None, **kw):
            # replica 0 was dead/mid-restart: router could not see it
            return {"applied": [1, 2], "skipped": []}

    f = object.__new__(fleet_mod.Fleet)
    f.supervisor = StubSup()
    f.router = StubRouter()
    res = f.rollout({"w": np.ones(2, np.float32)})
    assert res == {"applied": [1, 2], "recycled": [0]}
    assert f.supervisor.killed == [0]  # recycled onto the new file
    arg, _ = load_params_npz(params_path)
    np.testing.assert_array_equal(arg["w"], np.ones(2, np.float32))


# ---------------------------------------- fleet observability (metrics)
class TelemetryFake(FakeReplica):
    """FakeReplica that ships queued delta-encoded telemetry snapshots in
    health() — the replica wire contract the router folds."""

    def __init__(self, rid, **kw):
        super().__init__(rid, **kw)
        self.pending_tel = []

    def health(self, **kw):
        h = super().health(**kw)
        if self.pending_tel:
            h["telemetry"] = self.pending_tel.pop(0)
        return h


def test_router_metrics_fold_replica_snapshots(payload):
    """Delta-encoded replica snapshots fold EXACTLY ONCE each into the
    fleet.* rollups: counters add, histogram buckets merge (quantiles
    rebuilt fleet-wide), per-replica dropped counts surface."""
    from mxnet_tpu.telemetry.histogram import Histogram

    h0, h1 = Histogram(), Histogram()
    for _ in range(20):
        h0.record(0.004)
    for _ in range(20):
        h1.record(0.016)
    fakes = {0: TelemetryFake(0, wait_ms=1.0),
             1: TelemetryFake(1, wait_ms=2.0)}
    fakes[0].pending_tel = [
        {"counters": {"serving.requests": 20},
         "hist": {"serving.request": h0.to_dict()["buckets"]},
         "dropped": 0},
        {"counters": {"serving.requests": 5}, "hist": {}, "dropped": 2},
    ]
    fakes[1].pending_tel = [
        {"counters": {"serving.requests": 20},
         "hist": {"serving.request": h1.to_dict()["buckets"]},
         "dropped": 0},
    ]
    with make_router(fakes) as r:
        _wait_fresh(r, 2)
        for _ in range(3):
            r.infer(payload, timeout=5)
        deadline = time.perf_counter() + 3.0
        m = r.metrics()
        while time.perf_counter() < deadline:
            m = r.metrics()
            if m["counters"].get("serving.requests") == 45 \
                    and m["replicas"].get("0", {}).get("dropped") == 2:
                break
            time.sleep(0.02)
    assert m["counters"]["serving.requests"] == 45
    lat = m["latency_ms"]["serving.request"]
    assert lat["count"] == 40
    # merged across replicas: 20 @4ms + 20 @16ms — p50 in the fast mode,
    # p99 in the slow (within the histogram's ~10% bucket error)
    assert abs(lat["p50"] - 4.0) / 4.0 < 0.15
    assert abs(lat["p99"] - 16.0) / 16.0 < 0.15
    # the router's own submit->delivery histogram is the fleet view
    assert m["latency_ms"]["fleet.request"]["count"] == 3
    assert m["requests"] == 3 and m["errors"] == 0
    assert m["replicas"]["0"]["dropped"] == 2
    assert m["dropped_events"] >= 2


def test_trace_id_minting_gated_by_mode(payload):
    """The router mints a per-request trace id at admission ONLY in trace
    mode, and installs it around the dispatch so the replica call
    inherits it (in-process fakes included)."""
    seen = []

    class Spy(FakeReplica):
        def infer(self, inputs, **kw):
            seen.append(telemetry.trace_context())
            return super().infer(inputs, **kw)

    telemetry.reset()
    telemetry.clear_events()
    try:
        fakes = {0: Spy(0)}
        with make_router(fakes) as r:
            _wait_fresh(r, 1)
            telemetry.set_mode("counters")
            r.infer(payload, timeout=5)
            telemetry.set_mode("trace")
            r.infer(payload, timeout=5)
        assert seen[0] is None                      # counters: no id
        assert isinstance(seen[1], str) and len(seen[1]) == 16
        int(seen[1], 16)                            # hex request id
    finally:
        telemetry.set_mode(None)
        telemetry.reset()
        telemetry.clear_events()


def test_fleet_trace_ids_propagate_across_rpc(payload):
    """End-to-end request tracing over the REAL wire: the router-minted
    trace id rides the RPC frame, the replica handler's spans inherit it,
    the health-poll connection measures a clock offset, and
    collect_fleet_trace() merges both processes' spans into one chain
    keyed by that id."""
    import os

    from mxnet_tpu.telemetry import cli

    telemetry.reset()
    telemetry.clear_events()
    telemetry.set_mode("trace")
    seen = []
    seq = [0]

    def health(**kw):
        seq[0] += 1
        return {"state": "healthy", "seq": seq[0],
                "snapshot_ms": time.time() * 1000.0,
                "ewma_queue_wait_ms": 1.0, "pid": os.getpid(),
                "queue_depth": 0}

    def infer(inputs, deadline_ms=None, **kw):
        seen.append(telemetry.trace_context())
        with telemetry.span("serving.dispatch", rows=2):
            pass
        return [np.zeros((2, 4), np.float32)]

    def dump_trace(**kw):
        d = telemetry.build_trace(extra={"label": "replica-0"})
        # a real replica is a subprocess with its own pid; this in-process
        # stand-in must self-identify as one for the merge to re-pid it
        d["otherData"]["pid"] = os.getpid() + 100000
        return d

    srv = RpcServer({"health": health, "infer": infer,
                     "dump_trace": dump_trace}).start()
    try:
        with make_router({0: srv.addr}) as r:
            _wait_fresh(r, 1)
            r.infer(payload, timeout=10)
            assert len(seen) == 1 and isinstance(seen[0], str)
            m = r.metrics()
            # the health-poll connection's midpoint handshake landed
            assert abs(m["replicas"]["0"]["clock_offset_ms"]) < 5000.0
            merged = r.collect_fleet_trace()
        assert cli.check(merged) == []
        assert merged["otherData"]["merged"] is True
        assert merged["otherData"]["fleet"]["requests"] == 1
        labels = {d["label"]
                  for d in merged["otherData"]["processes"].values()}
        assert "router" in labels and "replica-0" in labels
        chains = cli.request_chains(merged)
        assert seen[0] in chains
        # the chain spans >= 2 process lanes (router + replica)
        assert len({s["pid"] for s in chains[seen[0]]}) >= 2
        names = {s["name"] for s in chains[seen[0]]}
        assert "fleet.dispatch" in names and "serving.dispatch" in names
    finally:
        srv.stop()
        telemetry.set_mode(None)
        telemetry.reset()
        telemetry.clear_events()


def test_router_slo_violation_fires_and_clears(payload, monkeypatch):
    """A redispatch-exhausting fault burst trips the err_pct burn gate
    (structured slo.violation event); clean traffic rolls the failures
    out of both windows and the matching slo.clear is emitted."""
    monkeypatch.setenv("MXNET_SLO_WINDOW_S", "2")
    monkeypatch.setenv("MXNET_SLO_SHORT_WINDOW_S", "0.5")
    fakes = {0: FakeReplica(0, wait_ms=1.0)}
    with make_router(fakes, slo="err_pct:5", max_redispatch=1,
                     dispatch_wait_ms=500) as r:
        _wait_fresh(r, 1)
        for _ in range(5):
            r.infer(payload, timeout=5)        # healthy baseline
        s = r.metrics()["slo"]
        assert s["ok"] and "err_pct" in s["objectives"]
        fakes[0].fail_next = 12                # initial + 1 redispatch x6
        futs = [r.submit(payload) for _ in range(6)]
        for f in futs:
            with pytest.raises(Exception):
                f.result(timeout=10)
        fakes[0].fail_next = 0
        deadline = time.perf_counter() + 5.0
        fired = False
        while time.perf_counter() < deadline:
            s = r.metrics().get("slo") or {}
            if s and not s.get("ok", True):
                fired = True
                break
            time.sleep(0.05)
        assert fired, s
        assert s["objectives"]["err_pct"]["firing"]
        assert s["burn_rate"] >= s["burn_threshold"]
        # recovery: healthy traffic ages the burst out of the window
        deadline = time.perf_counter() + 10.0
        cleared = False
        while time.perf_counter() < deadline:
            try:
                r.infer(payload, timeout=5)
            except Exception:
                pass
            s = r.metrics().get("slo") or {}
            if s.get("ok"):
                cleared = True
                break
            time.sleep(0.1)
        assert cleared, s
        kinds = [v["kind"] for v in r.slo_violations()]
        assert "slo.violation" in kinds and "slo.clear" in kinds
        viol = [v for v in r.slo_violations()
                if v["kind"] == "slo.violation"][0]
        assert viol["objective"] == "err_pct"
        assert viol["burn_rate"] >= 1.0
