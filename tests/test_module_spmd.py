"""Module-API training lowered onto the fused SPMD step.

VERDICT r2 item 3: `Module(ctx=<8 devices>)` must run ONE jitted sharded
step (fwd+bwd+psum+update), not per-key host reduction — and produce the
same numbers as the legacy single-device path. Oracles: exact parameter
parity against the unfused path after N steps, plus a convergence check
through `fit()` (reference analogue: tests/python/train/test_mlp.py).
"""
import numpy as np
import pytest

import mxnet_tpu as mx


def _mlp(hidden=32, classes=4):
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, name="fc1", num_hidden=hidden)
    h = mx.sym.Activation(h, name="relu1", act_type="relu")
    h = mx.sym.FullyConnected(h, name="fc2", num_hidden=classes)
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _fit_params(symbol, ctxs, batches, optimizer="sgd", opt_params=None,
                fused=None):
    """Train the same batches through a Module on the given contexts and
    return the final params (numpy dict)."""
    import os

    mx.random.seed(7)  # identical init across the runs being compared
    mod = mx.mod.Module(symbol, context=ctxs,
                        **({} if fused is None else {"fused_step": fused}))
    b0 = batches[0]
    mod.bind(data_shapes=[("data", b0.data[0].shape)],
             label_shapes=[("softmax_label", b0.label[0].shape)])
    mod.init_params(initializer=mx.init.Xavier(magnitude=2.0))
    mod.init_optimizer(kvstore="local", optimizer=optimizer,
                       optimizer_params=opt_params
                       or (("learning_rate", 0.1), ("momentum", 0.9)))
    for batch in batches:
        mod.forward_backward(batch)
        mod.update()
    args, _ = mod.get_params()
    return mod, {k: v.asnumpy() for k, v in args.items()}


def _batches(n, batch=16, feat=8, classes=4, seed=0):
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        x = rs.rand(batch, feat).astype("float32")
        y = rs.randint(0, classes, (batch,)).astype("float32")
        out.append(mx.io.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.array(y)]))
    return out


class TestFusedStepParity:
    def test_fused_path_is_active_on_multi_device(self):
        sym = _mlp()
        mod, _ = _fit_params(sym, [mx.cpu(i) for i in range(4)], _batches(1))
        assert mod._spmd is not None, "fused SPMD step should be active"

    def test_single_device_stays_legacy(self):
        sym = _mlp()
        mod, _ = _fit_params(sym, [mx.cpu(0)], _batches(1))
        assert mod._spmd is None

    @pytest.mark.parametrize("optimizer,opt_params", [
        ("sgd", (("learning_rate", 0.1), ("momentum", 0.9))),
        ("sgd", (("learning_rate", 0.05), ("momentum", 0.0), ("wd", 1e-3))),
        ("adam", (("learning_rate", 0.01),)),
    ])
    def test_params_match_legacy_path(self, optimizer, opt_params):
        """Same data, same init → fused multi-device params == legacy
        single-device params (the psum over shards equals the full-batch
        gradient)."""
        sym = _mlp()
        batches = _batches(5)
        _, fused = _fit_params(sym, [mx.cpu(i) for i in range(8)], batches,
                               optimizer, opt_params)
        _, legacy = _fit_params(sym, [mx.cpu(0)], batches,
                                optimizer, opt_params)
        assert set(fused) == set(legacy)
        for k in fused:
            np.testing.assert_allclose(
                fused[k], legacy[k], rtol=2e-4, atol=2e-5,
                err_msg="param %s diverged between fused and legacy" % k)

    def test_outputs_match_legacy_path(self):
        sym = _mlp()
        batches = _batches(1)
        modf, _ = _fit_params(sym, [mx.cpu(i) for i in range(4)], batches)
        modl, _ = _fit_params(sym, [mx.cpu(0)], batches)
        of = modf.get_outputs()[0].asnumpy()
        ol = modl.get_outputs()[0].asnumpy()
        np.testing.assert_allclose(of, ol, rtol=1e-4, atol=1e-5)

    def test_lr_scheduler_drives_fused_step(self):
        """A FactorScheduler must change the effective lr inside the fused
        step: with factor=0 after step 1 the params freeze."""
        sym = _mlp()
        batches = _batches(4, seed=3)
        sched = mx.lr_scheduler.FactorScheduler(step=1, factor=1e-8)
        mod = mx.mod.Module(sym, context=[mx.cpu(i) for i in range(4)])
        mod.bind(data_shapes=[("data", batches[0].data[0].shape)],
                 label_shapes=[("softmax_label", batches[0].label[0].shape)])
        mod.init_params(initializer=mx.init.Xavier())
        mod.init_optimizer(optimizer="sgd", optimizer_params=(
            ("learning_rate", 0.5), ("momentum", 0.0),
            ("lr_scheduler", sched)))
        assert mod._spmd is not None
        mod.forward_backward(batches[0])
        mod.update()
        after_1, _ = mod.get_params()
        after_1 = {k: v.asnumpy().copy() for k, v in after_1.items()}
        for b in batches[1:]:
            mod.forward_backward(b)
            mod.update()
        after_n, _ = mod.get_params()
        for k, v in after_n.items():
            np.testing.assert_allclose(v.asnumpy(), after_1[k], rtol=0, atol=1e-6)

    def test_fit_converges_and_scores(self):
        """End-to-end fit() on separable data through the fused path, then
        score() (which must see the SPMD-updated params via forward)."""
        rs = np.random.RandomState(0)
        n, feat = 256, 16
        w = rs.randn(feat, 2).astype("float32")
        x = rs.randn(n, feat).astype("float32")
        y = np.argmax(x @ w, axis=1).astype("float32")
        it = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=False,
                               label_name="softmax_label")
        sym = _mlp(hidden=32, classes=2)
        mod = mx.mod.Module(sym, context=[mx.cpu(i) for i in range(8)])
        mod.fit(it, num_epoch=12, optimizer="sgd",
                optimizer_params=(("learning_rate", 0.5), ("momentum", 0.9)),
                initializer=mx.init.Xavier(magnitude=2.0),
                eval_metric="acc", kvstore="local")
        assert mod._spmd is not None
        it.reset()
        score = mod.score(it, mx.metric.Accuracy())
        acc = dict(score)["accuracy"]
        assert acc > 0.95, "fused-path fit failed to converge: acc=%.3f" % acc

    def test_checkpoint_roundtrip_with_spmd_states(self, tmp_path):
        sym = _mlp()
        batches = _batches(2)
        mod, params = _fit_params(sym, [mx.cpu(i) for i in range(4)], batches)
        prefix = str(tmp_path / "spmd")
        mod.save_checkpoint(prefix, 1, save_optimizer_states=True)
        loaded = mx.mod.Module.load(prefix, 1, load_optimizer_states=True,
                                    context=[mx.cpu(i) for i in range(4)])
        loaded.bind(data_shapes=[("data", batches[0].data[0].shape)],
                    label_shapes=[("softmax_label", batches[0].label[0].shape)])
        loaded.init_params()
        loaded.init_optimizer(optimizer="sgd", optimizer_params=(
            ("learning_rate", 0.1), ("momentum", 0.9)))
        args, _ = loaded.get_params()
        for k, v in args.items():
            np.testing.assert_allclose(v.asnumpy(), params[k], rtol=1e-6)
        # the momentum state survived the round-trip into the fused step
        assert loaded._spmd is not None
        mom = loaded._spmd.trainer.opt_state.get("mom")
        assert mom and any(np.abs(np.asarray(m)).sum() > 0 for m in mom.values())
