"""Decode megasteps (mxnet_tpu/serving/kv_decode.py decode_megastep /
step_megastep, docs/SERVING.md §Megasteps): K tokens per dispatch through
one lax.scan program. Gates: token-identical parity with single-step
greedy, seeded top-k reproducibility across K partitionings, EOS
early-exit lanes write NOTHING (KV bitwise-unchanged past eos), paged
pre-acquire backpressure, and the name-based token-head detection that
keeps a disk-cached K=1 program from masquerading as a megastep one."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.models import transformer as tfm
from mxnet_tpu.serving import KVCacheDecoder, PagedKVDecoder, PagedKVExhausted
from mxnet_tpu.serving.kv_decode import decode_megastep_k

CFG = dict(vocab_size=50, num_layers=2, num_heads=2, model_dim=32,
           ffn_dim=64)


@pytest.fixture
def tm():
    telemetry.reset()
    telemetry.clear_events()
    saved = telemetry.current_override()
    yield telemetry
    telemetry.set_mode(saved)
    telemetry.reset()
    telemetry.clear_events()


def _params(S, seed=0):
    net = tfm.get_symbol(seq_len=S, **CFG)
    exe = net.simple_bind(mx.cpu(), grad_req="null", data=(1, S),
                          softmax_label=(1, S))
    rs = np.random.RandomState(seed)
    params = {}
    for name, arr in exe.arg_dict.items():
        if name in ("data", "softmax_label"):
            continue
        w = (rs.randn(*arr.shape) * 0.1).astype("float32")
        arr[:] = w
        params[name] = w
    return params


def _decoder(params, S, B, **kw):
    return KVCacheDecoder(params, max_len=S, prefill_len=8, pos_len=S,
                          batch=B, **CFG, **kw)


def _prompt(B, seed=3, L=4):
    rs = np.random.RandomState(seed)
    return rs.randint(1, CFG["vocab_size"], (B, L)).astype(np.float32)


# ------------------------------------------------------------------ knobs
def test_megastep_k_env(monkeypatch):
    monkeypatch.delenv("MXNET_DECODE_MEGASTEP_K", raising=False)
    assert decode_megastep_k() == 1
    monkeypatch.setenv("MXNET_DECODE_MEGASTEP_K", "8")
    assert decode_megastep_k() == 8
    monkeypatch.setenv("MXNET_DECODE_MEGASTEP_K", "junk")
    assert decode_megastep_k() == 1
    monkeypatch.setenv("MXNET_DECODE_MEGASTEP_K", "0")
    assert decode_megastep_k() == 1


# ----------------------------------------------------------------- parity
def test_megastep_greedy_token_identical(tm):
    """The acceptance gate: K-chunked greedy == single-step greedy,
    token for token — the scan body IS the single-step math."""
    tm.set_mode("counters")
    S, B, n = 32, 2, 17
    params = _params(S)
    prompt = _prompt(B)
    seq = _decoder(params, S, B).greedy(prompt, n, k=1)
    mega = _decoder(params, S, B).greedy(prompt, n, k=4)
    np.testing.assert_array_equal(seq, mega)


def test_megastep_env_default_drives_greedy(tm, monkeypatch):
    tm.set_mode("counters")
    S, B, n = 32, 2, 9
    params = _params(S)
    prompt = _prompt(B)
    base = _decoder(params, S, B).greedy(prompt, n, k=1)
    monkeypatch.setenv("MXNET_DECODE_MEGASTEP_K", "4")
    got = _decoder(params, S, B).greedy(prompt, n)
    np.testing.assert_array_equal(base, got)


def test_megastep_zero_retrace_and_sealed(tm):
    """Repeated megasteps replay ONE compiled program (cache-hit path);
    a K change is a different sealed program, and a shape drift raises
    instead of retracing."""
    tm.set_mode("counters")
    S, B, K = 32, 2, 4
    params = _params(S)
    dec = _decoder(params, S, B)
    logits = dec.prefill(_prompt(B))
    tok = np.argmax(logits, axis=-1)
    chunk = dec.decode_megastep(tok, k=K)  # compiles + seals here
    c0 = tm.counters()
    for _ in range(3):
        chunk = dec.decode_megastep(chunk[:, -1], k=K)
    c1 = tm.counters()
    assert c1.get("executor.retrace", 0) == c0.get("executor.retrace", 0)
    assert c1.get("executor.compile", 0) == c0.get("executor.compile", 0)
    assert c1.get("executor.cache_hit", 0) >= c0.get("executor.cache_hit", 0) + 3


def test_megastep_counters_and_gauge(tm):
    tm.set_mode("counters")
    S, B, K = 32, 2, 4
    dec = _decoder(_params(S), S, B)
    logits = dec.prefill(_prompt(B))
    tok = np.argmax(logits, axis=-1)
    dec.decode_megastep(tok, k=K)
    c = tm.counters()
    assert c.get("serving.megasteps", 0) == 1
    assert c.get("serving.decode_tokens", 0) >= B * K
    assert tm.gauge("decode.tokens_per_dispatch").value == B * K


def test_megastep_position_budget_raises():
    S, B = 16, 1
    dec = _decoder(_params(S), S, B)
    logits = dec.prefill(_prompt(B, L=4))
    tok = np.argmax(logits, axis=-1)
    with pytest.raises(MXNetError):
        dec.decode_megastep(tok, k=S)  # pos 4 + 16 > pos_len 16


# --------------------------------------------------------------- sampling
def test_topk_sampling_reproducible_across_k(tm):
    """Seeded top-k draws key off (seed, absolute position, lane), so one
    K=4 megastep must emit the exact tokens of two K=2 megasteps."""
    tm.set_mode("counters")
    S, B = 32, 2
    params = _params(S)
    prompt = _prompt(B)
    kw = dict(sample="topk", temperature=0.8, top_k=5)

    d4 = _decoder(params, S, B, sample_seed=11)
    tok = np.argmax(d4.prefill(prompt), axis=-1)
    full = d4.decode_megastep(tok, k=4, **kw)

    d2 = _decoder(params, S, B, sample_seed=11)
    tok = np.argmax(d2.prefill(prompt), axis=-1)
    a = d2.decode_megastep(tok, k=2, **kw)
    b = d2.decode_megastep(a[:, -1], k=2, **kw)
    np.testing.assert_array_equal(full, np.concatenate([a, b], axis=1))


# ------------------------------------------------------------- early exit
def test_eos_early_exit_writes_nothing(tm):
    """Once a lane emits eos mid-megastep its later scan steps must write
    NOTHING: the KV slots past the eos step stay bitwise what they were
    before the dispatch, and the lane's remaining outputs are eos filler.
    The other lane keeps decoding normally."""
    tm.set_mode("counters")
    S, B, K = 32, 2, 6
    params = _params(S)
    prompt = _prompt(B)
    # seeded top-k: deterministic like greedy but token-diverse (random
    # weights make greedy collapse to one repeated id, which would leave
    # no usable eos candidate); the eos/done latch is sampler-independent
    kw = dict(sample="topk", temperature=1.5, top_k=10)

    probe_dec = _decoder(params, S, B, sample_seed=23)
    tok0 = np.argmax(probe_dec.prefill(prompt), axis=-1)
    probe = probe_dec.decode_megastep(tok0, k=K, **kw)  # (B, K) eos-free

    # an eos candidate lane 0 emits mid-megastep, not emitted earlier by
    # lane 0 and never emitted by lane 1 (keeps lane 1 assertions exact)
    j = eos = None
    for cand_j in range(1, K - 1):
        cand = int(probe[0, cand_j])
        if cand not in probe[0, :cand_j] and cand not in probe[1]:
            j, eos = cand_j, cand
            break
    assert eos is not None, "no usable eos candidate in %r" % probe

    dec = _decoder(params, S, B, sample_seed=23)
    tok0 = np.argmax(dec.prefill(prompt), axis=-1)
    p = dec.position
    kv_names = [n for n in dec._dec_exe.arg_dict
                if n.startswith(("kv_k_", "kv_v_"))]
    before = {n: np.asarray(dec._dec_exe.arg_dict[n]._jax()).copy()
              for n in kv_names}
    out = dec.decode_megastep(tok0, k=K, eos_id=eos, **kw)

    # lane 0: tokens up to and including eos match the eos-free run, the
    # rest is eos filler
    np.testing.assert_array_equal(out[0, :j + 1], probe[0, :j + 1])
    assert (out[0, j + 1:] == eos).all()
    # lane 1 never hit eos: identical to the eos-free run
    np.testing.assert_array_equal(out[1], probe[1])

    after = {n: np.asarray(dec._dec_exe.arg_dict[n]._jax())
             for n in kv_names}
    # step t writes slot p+t for its INPUT token; the eos EMITTED at step
    # j latches done, so steps j+1.. write nothing for lane 0
    dead = [(p + t) % S for t in range(j + 1, K)]
    live = [(p + t) % S for t in range(0, j + 1)]
    for n in kv_names:
        np.testing.assert_array_equal(
            after[n][0][:, dead, :], before[n][0][:, dead, :],
            err_msg="%s: EOS'd lane wrote past its eos step" % n)
        # sanity: the pre-eos slots DID get written
        assert not np.array_equal(after[n][0][:, live, :],
                                  before[n][0][:, live, :])
        # lane 1 wrote all K slots
        assert not np.array_equal(after[n][1][:, dead, :],
                                  before[n][1][:, dead, :])


# ------------------------------------------------------------------ paged
def test_paged_megastep_parity_with_page_crossing(tm):
    """Paged K-chunked greedy == paged single-step greedy with page_size 4
    and enough tokens that every lane crosses a page boundary mid-run."""
    tm.set_mode("counters")
    S, n_streams, n = 32, 3, 13
    params = _params(S)
    rs = np.random.RandomState(9)
    prompts = [rs.randint(1, CFG["vocab_size"], (2 + i,)).astype(np.float32)
               for i in range(n_streams)]

    def mk():
        return PagedKVDecoder(params, max_len=S, page_size=4,
                              lanes=n_streams, prefill_len=8, pos_len=S,
                              **CFG)

    seq = mk().greedy(prompts, n, k=1)
    mega = mk().greedy(prompts, n, k=4)
    for a, b in zip(seq, mega):
        np.testing.assert_array_equal(a, b)


def test_paged_megastep_backpressure_before_dispatch(tm):
    """Pool exhaustion mid-pre-acquire raises PagedKVExhausted BEFORE any
    device work: lane positions and KV are untouched, and after a retire
    frees frames the same megastep succeeds."""
    tm.set_mode("counters")
    S = 16
    params = _params(S)
    dec = PagedKVDecoder(params, max_len=S, page_size=2, lanes=2,
                         prefill_len=8, pos_len=S, page_budget=5, **CFG)
    rs = np.random.RandomState(1)
    pa = rs.randint(1, CFG["vocab_size"], (3,)).astype(np.float32)
    pb = rs.randint(1, CFG["vocab_size"], (3,)).astype(np.float32)
    sa, la = dec.admit(pa)   # positions 0..2 -> 2 frames
    sb, lb = dec.admit(pb)   # 2 more frames; 1 of 5 left
    tok_a = int(np.argmax(la))
    tok_b = int(np.argmax(lb))
    pos_before = (dec.position(sa), dec.position(sb))
    with pytest.raises(PagedKVExhausted):
        # each lane needs pages for positions 3..6 -> 2 new frames apiece,
        # only 1 in the pool
        dec.step_megastep({sa: tok_a, sb: tok_b}, k=4)
    assert (dec.position(sa), dec.position(sb)) == pos_before, \
        "failed pre-acquire moved a lane position"
    dec.retire(sb)
    out = dec.step_megastep({sa: tok_a}, k=4)
    assert out[sa].shape == (4,)
    assert dec.position(sa) == pos_before[0] + 4


def test_paged_megastep_matches_single_steps(tm):
    """Direct step_megastep parity against the per-step loop (argmax fed
    back host-side) for lanes at DIFFERENT positions."""
    tm.set_mode("counters")
    S, K = 32, 4
    params = _params(S)
    rs = np.random.RandomState(5)
    prompts = [rs.randint(1, CFG["vocab_size"], (L,)).astype(np.float32)
               for L in (2, 5)]

    def admit_all(d):
        toks = {}
        for p in prompts:
            sid, logits = d.admit(p)
            toks[sid] = int(np.argmax(logits))
        return toks

    d1 = PagedKVDecoder(params, max_len=S, page_size=4, lanes=2,
                        prefill_len=8, pos_len=S, **CFG)
    toks = admit_all(d1)
    want = {sid: [] for sid in toks}
    cur = dict(toks)
    for _ in range(K):
        lg = d1.step(cur)
        cur = {sid: int(np.argmax(lg[sid])) for sid in lg}
        for sid in cur:
            want[sid].append(cur[sid])

    d2 = PagedKVDecoder(params, max_len=S, page_size=4, lanes=2,
                        prefill_len=8, pos_len=S, **CFG)
    toks2 = admit_all(d2)
    assert toks2 == toks
    got = d2.step_megastep(toks2, k=K)
    for sid in toks:
        np.testing.assert_array_equal(got[sid], np.asarray(want[sid]))


# -------------------------------------------------- token-head detection
def test_token_out_detected_by_name_not_arity(tm):
    """warmup() must key the greedy-token head off the OUTPUT NAME, not
    the output count: a coincidental arity match (e.g. a disk-cached K=1
    program with 1 + 2*layers outputs) must not masquerade as a
    token-head program."""
    tm.set_mode("counters")
    S, B = 16, 1
    dec = _decoder(_params(S), S, B)
    dec.warmup()
    names = list(dec._dec_exe.output_dict)
    assert any(n.startswith("greedy_token") for n in names)
    assert dec._token_out is True
    # a program with the same ARITY but no greedy_token output must read
    # as token_out=False — the old count-based sniff got this wrong
    fake = {("out%d" % i): None for i in range(len(names))}
    assert not any(n.startswith("greedy_token") for n in fake)
