"""Shared-prefix KV cache (mxnet_tpu/serving/prefix_cache.py + the
refcounted COW _PagePool in kv_decode.py, docs/SERVING.md §Prefix cache
& speculative decoding): refcount/COW edge contracts on the pool, and
the serving-level guarantees — cached-prefix admits are BITWISE
identical to cold admits, hit accounting is truthful, eviction never
frees a shared page, and fork/COW isolates writers."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.models import transformer as tfm
from mxnet_tpu.serving import PagedKVDecoder, PagedKVExhausted, PrefixCache
from mxnet_tpu.serving.kv_decode import _PagePool

CFG = dict(vocab_size=50, num_layers=2, num_heads=2, model_dim=32,
           ffn_dim=64)


@pytest.fixture
def tm():
    telemetry.reset()
    telemetry.clear_events()
    saved = telemetry.current_override()
    yield telemetry
    telemetry.set_mode(saved)
    telemetry.reset()
    telemetry.clear_events()


def _trained_params(S, seed=0):
    net = tfm.get_symbol(seq_len=S, **CFG)
    exe = net.simple_bind(mx.cpu(), grad_req="null", data=(1, S),
                          softmax_label=(1, S))
    rs = np.random.RandomState(seed)
    params = {}
    for name, arr in exe.arg_dict.items():
        if name in ("data", "softmax_label"):
            continue
        w = (rs.randn(*arr.shape) * 0.1).astype("float32")
        arr[:] = w
        params[name] = w
    return params


def _decoder(params, S=16, lanes=3, **kw):
    kw.setdefault("prefix_cache", True)
    kw.setdefault("prefix_chunk", 4)
    return PagedKVDecoder(params, max_len=S, page_size=4, lanes=lanes,
                          prefill_len=12, pos_len=S, **CFG, **kw)


# --------------------------------------------------------- index contract
def test_chain_hashes_are_prefix_addressed():
    """h[i] names the ENTIRE prefix through chunk i: change any earlier
    token and every later hash moves; append-only growth keeps the
    shared stem's hashes stable."""
    pool = _PagePool(lanes=1, slots=16, page_size=4)
    pc = PrefixCache(pool, chunk=4)
    a = pc.chain_hashes(np.arange(12))
    b = pc.chain_hashes(np.arange(12))
    assert a == b and len(a) == 3
    mut = np.arange(12)
    mut[1] += 1
    c = pc.chain_hashes(mut)
    assert c[0] != a[0] and c[1] != a[1] and c[2] != a[2]
    tail = np.concatenate([np.arange(12), [99, 98, 97, 96]])
    d = pc.chain_hashes(tail)
    assert d[:3] == a and len(d) == 4
    with pytest.raises(ValueError, match="multiple"):
        PrefixCache(pool, chunk=6)  # page_size 4 does not divide 6


def test_eviction_never_frees_shared_pages_and_is_leaf_first():
    """The satellite edge: evicting a cache entry whose frames a lane
    still references must NOT return them to the free list (the lane
    holds a ref); interior chain entries outlive their children."""
    pool = _PagePool(lanes=1, slots=16, page_size=4)  # 4 frames
    pc = PrefixCache(pool, chunk=4)
    h = pc.chain_hashes(np.arange(8))
    f0, f1 = pool.acquire(), pool.acquire()
    pc.insert(h[0], [f0])
    pc.insert(h[1], [f1], parent=h[0])
    # the admitting lane retires; a second lane still shares f0
    pool.incref(f0)
    pool.release([f0, f1])
    assert pool.refcount(f0) == 2 and pool.refcount(f1) == 1
    # 4 frames can never come free while the lane pins f0: eviction
    # walks child-then-parent, drops both entries, REPORTS failure —
    # and the shared frame stays allocated under the lane's reference
    assert not pc.evict_for(4)
    assert pc.stats()["entries"] == 0 and pc.stats()["evictions"] == 2
    assert pool.refcount(f1) == 0
    assert pool.refcount(f0) == 1 and pool.in_use == 1
    assert pool.can_acquire(3)


def test_evict_for_reports_failure_when_nothing_evictable():
    pool = _PagePool(lanes=1, slots=16, page_size=4)
    pc = PrefixCache(pool, chunk=4)
    held = [pool.acquire() for _ in range(4)]  # lanes hold everything
    assert not pc.evict_for(1)
    pool.release(held)


# --------------------------------------------------- serving-level parity
def test_cached_admit_bitwise_identical_and_hit_accounting(tm):
    """The acceptance gate: admit a prompt cold, admit it again cached —
    the second admit adopts the cached pages (hit counters move, prefill
    work is saved) and returns BITWISE-identical logits; a retire +
    re-admit replays the same physical placement. Zero post-warmup
    compiles or retraces."""
    tm.set_mode("counters")
    params = _trained_params(16)
    dec = _decoder(params)
    rs = np.random.RandomState(3)
    prompt = rs.randint(1, CFG["vocab_size"], (8,)).astype(np.float32)

    s0, cold = dec.admit(prompt)  # cold: 2 chunks computed + registered
    c0 = telemetry.counters()
    assert c0.get("serving.prefix_misses", 0) == 2
    s1, hit = dec.admit(prompt)   # full match: zero-write replay
    c1 = telemetry.counters()
    np.testing.assert_array_equal(np.asarray(cold), np.asarray(hit))
    assert c1.get("serving.prefix_hits", 0) == 2
    assert c1.get("serving.prefill_tokens_saved", 0) == 8
    assert c1.get("serving.pages_shared", 0) >= 2
    # shared pages: both lanes + the cache reference the same frames
    lane0 = dec._lanes[dec._seq_lane[s0]]
    lane1 = dec._lanes[dec._seq_lane[s1]]
    assert lane0.frames == lane1.frames
    for f in lane0.frames:
        assert dec.pool.refcount(f) == 3
    # retire + re-admit: deterministic placement => still bitwise
    dec.retire(s1)
    s2, again = dec.admit(prompt)
    np.testing.assert_array_equal(np.asarray(cold), np.asarray(again))
    # the two admits after warmup replayed sealed programs only
    assert c1.get("executor.retrace", 0) == 0
    c2 = telemetry.counters()
    assert c2.get("executor.compile", 0) == c0.get("executor.compile", 0)
    # hit-rate gauge is live
    assert dec.stats()["prefix_hit_rate"] > 0.5
    dec.retire(s0)
    dec.retire(s2)


def test_partial_prefix_match_decodes_token_identical(tm):
    """Two prompts sharing a 4-token stem: the second admit reuses the
    stem chunk and computes only its tail, then decodes token-identical
    to a prefix-cache-OFF decoder over the same checkpoint."""
    tm.set_mode("counters")
    params = _trained_params(16)
    rs = np.random.RandomState(5)
    stem = rs.randint(1, CFG["vocab_size"], (4,)).astype(np.float32)
    p0 = np.concatenate([stem, [7.0, 9.0, 11.0, 13.0]])
    p1 = np.concatenate([stem, [8.0, 10.0, 12.0, 14.0]])

    base = PagedKVDecoder(params, max_len=16, page_size=4, lanes=2,
                          prefill_len=12, pos_len=16,
                          prefix_cache=False, **CFG)
    want = base.greedy([p0, p1], 5, k=1)

    dec = _decoder(params)
    dec.admit(p0)
    c0 = telemetry.counters()
    s1, _ = dec.admit(p1)
    c1 = telemetry.counters()
    assert c1.get("serving.prefix_hits", 0) - \
        c0.get("serving.prefix_hits", 0) == 1   # the stem chunk
    dec.retire(s1)
    for sid in list(dec.active):
        dec.retire(sid)
    got = dec.greedy([p0, p1], 5, k=1)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)


# ------------------------------------------------------------- COW / fork
def test_fork_shares_pages_then_cow_isolates_writers(tm):
    """The mid-megastep COW satellite: fork a sequence (every page
    shared at a refcount), megastep BOTH forks down different token
    paths — the first write into the shared boundary page triggers a
    private copy, the divergent continuations never corrupt each other,
    and cow_copies counts the copy."""
    tm.set_mode("counters")
    params = _trained_params(16)
    dec = _decoder(params, lanes=3)
    rs = np.random.RandomState(9)
    prompt = rs.randint(1, CFG["vocab_size"], (6,)).astype(np.float32)

    s0, lg = dec.admit(prompt)
    fk = dec.fork(s0)
    l0 = dec._lanes[dec._seq_lane[s0]]
    l1 = dec._lanes[dec._seq_lane[fk]]
    assert l0.frames == l1.frames and l1.pos == l0.pos
    shared = list(l0.frames)
    for f in shared:
        assert dec.pool.refcount(f) >= 2

    # oracle: each continuation decoded alone, no sharing anywhere
    solo = PagedKVDecoder(params, max_len=16, page_size=4, lanes=1,
                          prefill_len=12, pos_len=16,
                          prefix_cache=False, **CFG)
    t0 = int(np.argmax(lg))
    t1 = int(t0 == 0)  # any different token
    want = {}
    for tok in (t0, t1):
        sid, _ = solo.admit(prompt)
        want[tok] = solo.step_megastep({sid: tok}, k=4)[sid]
        solo.retire(sid)

    # both forks advance in ONE multiplexed megastep; position 6 lands
    # mid-page, so each lane's first write COWs the shared boundary page
    got = dec.step_megastep({s0: t0, fk: t1}, k=4)
    c = telemetry.counters()
    np.testing.assert_array_equal(got[s0], want[t0])
    np.testing.assert_array_equal(got[fk], want[t1])
    assert c.get("serving.cow_copies", 0) >= 1
    assert dec._lanes[dec._seq_lane[s0]].frames[1] != \
        dec._lanes[dec._seq_lane[fk]].frames[1]
    dec.retire(s0)
    dec.retire(fk)
    assert dec.stats()["pages_in_use"] == 1  # cache still holds the stem


def test_retire_while_shared_and_exhaustion_with_shared_pages(tm):
    """Two satellite edges: (1) retiring a lane whose pages are shared
    leaves the survivors' KV intact (frames stay allocated under their
    refs); (2) pool exhaustion with shared pages held raises the
    structured backpressure error instead of stealing shared frames."""
    tm.set_mode("counters")
    params = _trained_params(16)
    # 3 lanes x 4 frames = 12 frames, budget capped to 4
    dec = _decoder(params, lanes=3, page_budget=4)
    rs = np.random.RandomState(13)
    prompt = rs.randint(1, CFG["vocab_size"], (8,)).astype(np.float32)

    s0, lg0 = dec.admit(prompt)   # 2 frames (cache shares them)
    s1, lg1 = dec.admit(prompt)   # same 2 frames adopted
    assert dec.pool.in_use == 2
    np.testing.assert_array_equal(np.asarray(lg0), np.asarray(lg1))

    # (1) retire the ORIGINAL writer while its pages are shared
    dec.retire(s0)
    lane1 = dec._lanes[dec._seq_lane[s1]]
    for f in lane1.frames:
        assert dec.pool.refcount(f) == 2  # survivor + cache
    ref = PagedKVDecoder(params, max_len=16, page_size=4, lanes=1,
                         prefill_len=12, pos_len=16,
                         prefix_cache=False, **CFG)
    rsid, rlg = ref.admit(prompt)
    t = int(np.argmax(rlg))
    assert t == int(np.argmax(lg1))
    np.testing.assert_array_equal(
        dec.step_megastep({s1: t}, k=2)[s1],
        ref.step_megastep({rsid: t}, k=2)[rsid])

    # (2) exhaustion with shared pages held: the megastep grew s1 to 3
    # distinct frames (budget 4); an unrelated 12-token admit needs 3
    # fresh frames, so it must raise structured backpressure — the
    # shared frames survive under s1's references (the cache's own
    # entries are legal eviction fodder, their pages are not)
    held_before = dec.pool.in_use
    assert held_before == 3
    alien = np.arange(30, 42).astype(np.float32)
    with pytest.raises(PagedKVExhausted, match="budget exhausted"):
        dec.admit(alien)
    assert dec.pool.in_use == held_before
    for f in lane1.frames:
        assert dec.pool.refcount(f) >= 1


def test_rollback_releases_whole_pages_only(tm):
    """Rollback (the speculative reject primitive): whole pages past the
    boundary are released, the partial boundary page is kept, and the
    re-decoded continuation is token-identical to never having rolled
    back."""
    tm.set_mode("counters")
    params = _trained_params(16)
    dec = _decoder(params, lanes=2, prefix_cache=False)
    rs = np.random.RandomState(17)
    prompt = rs.randint(1, CFG["vocab_size"], (4,)).astype(np.float32)
    sid, lg = dec.admit(prompt)
    t0 = int(np.argmax(lg))
    want = dec.step_megastep({sid: t0}, k=6)[sid]  # positions 4..9
    assert len(dec._lanes[dec._seq_lane[sid]].frames) == 3
    before = telemetry.counters().get("spec.rollbacks", 0)
    dec.rollback(sid, 6)   # keep pages 0..1, drop page 2
    lane = dec._lanes[dec._seq_lane[sid]]
    assert lane.pos == 6 and len(lane.frames) == 2
    assert telemetry.counters().get("spec.rollbacks", 0) == before + 1
    # re-decode from the rollback point: identical tokens
    redo = dec.step_megastep({sid: int(want[1])}, k=4)[sid]
    np.testing.assert_array_equal(redo, want[2:6])
    with pytest.raises(MXNetError, match="rollback target"):
        dec.rollback(sid, 99)
