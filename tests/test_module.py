"""Module / training-stack tests.

Modeled on the reference's tests/python/unittest/test_module.py and
tests/python/train/test_mlp.py / test_conv.py — end-to-end convergence on a
learnable task is the oracle (SURVEY.md §4: "convergence thresholds").
"""
import numpy as np
import pytest

import mxnet_tpu as mx


def _synthetic_classification(n=600, n_features=20, n_classes=5, seed=7):
    """Linearly separable-ish clusters an MLP must fit to ~100%."""
    rs = np.random.RandomState(seed)
    centers = rs.uniform(-3, 3, (n_classes, n_features)).astype("f")
    y = rs.randint(0, n_classes, n)
    x = centers[y] + rs.normal(0, 0.3, (n, n_features)).astype("f")
    return x.astype("f"), y.astype("f")


def mlp_symbol(num_classes=5):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data=data, num_hidden=64, name="fc1")
    net = mx.sym.Activation(data=net, act_type="relu")
    net = mx.sym.FullyConnected(data=net, num_hidden=num_classes, name="fc2")
    return mx.sym.SoftmaxOutput(data=net, name="softmax")


def test_module_fit_mlp_converges():
    x, y = _synthetic_classification()
    train = mx.io.NDArrayIter(x[:500], y[:500], batch_size=50, shuffle=True)
    val = mx.io.NDArrayIter(x[500:], y[500:], batch_size=50)
    mod = mx.mod.Module(mlp_symbol(), context=mx.cpu())
    mod.fit(
        train,
        eval_data=val,
        optimizer="sgd",
        optimizer_params=(("learning_rate", 0.1), ("momentum", 0.9)),
        num_epoch=6,
    )
    score = mod.score(val, "acc")
    assert score[0][1] > 0.95, "accuracy %f too low" % score[0][1]


def test_module_fit_conv_converges():
    """Small conv net on image-shaped synthetic data (train/test_conv.py gate)."""
    rs = np.random.RandomState(0)
    n, classes = 400, 4
    y = rs.randint(0, classes, n)
    x = np.zeros((n, 1, 8, 8), dtype="f")
    # each class lights up a distinct quadrant
    for i, yi in enumerate(y):
        r, c = divmod(int(yi), 2)
        x[i, 0, r * 4 : r * 4 + 4, c * 4 : c * 4 + 4] = 1.0
    x += rs.normal(0, 0.2, x.shape).astype("f")

    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data=data, kernel=(3, 3), num_filter=8, name="c1")
    net = mx.sym.Activation(data=net, act_type="relu")
    net = mx.sym.Pooling(data=net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = mx.sym.Flatten(data=net)
    net = mx.sym.FullyConnected(data=net, num_hidden=classes, name="fc")
    net = mx.sym.SoftmaxOutput(data=net, name="softmax")

    train = mx.io.NDArrayIter(x, y.astype("f"), batch_size=40, shuffle=True)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(train, optimizer="sgd", optimizer_params=(("learning_rate", 0.2),), num_epoch=5)
    score = mod.score(mx.io.NDArrayIter(x, y.astype("f"), batch_size=40), "acc")
    assert score[0][1] > 0.95


def test_module_adam_converges():
    x, y = _synthetic_classification(n=300)
    train = mx.io.NDArrayIter(x, y, batch_size=30, shuffle=True)
    mod = mx.mod.Module(mlp_symbol(), context=mx.cpu())
    mod.fit(train, optimizer="adam", optimizer_params=(("learning_rate", 0.01),), num_epoch=5)
    score = mod.score(mx.io.NDArrayIter(x, y, batch_size=30), "acc")
    assert score[0][1] > 0.95


def test_module_get_set_params_roundtrip():
    mod = mx.mod.Module(mlp_symbol(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (10, 20))], label_shapes=[("softmax_label", (10,))])
    mod.init_params(initializer=mx.init.Xavier())
    args, auxs = mod.get_params()
    assert set(args.keys()) == {"fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"}
    mod2 = mx.mod.Module(mlp_symbol(), context=mx.cpu())
    mod2.bind(data_shapes=[("data", (10, 20))], label_shapes=[("softmax_label", (10,))])
    mod2.init_params(arg_params=args, aux_params=auxs)
    a2, _ = mod2.get_params()
    for k in args:
        assert np.allclose(args[k].asnumpy(), a2[k].asnumpy())


def test_module_predict():
    x, y = _synthetic_classification(n=100)
    mod = mx.mod.Module(mlp_symbol(), context=mx.cpu())
    it = mx.io.NDArrayIter(x, y, batch_size=25)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label, for_training=False)
    mod.init_params()
    out = mod.predict(it)
    assert out.shape == (100, 5)
    assert np.allclose(out.asnumpy().sum(axis=1), 1.0, atol=1e-5)


def test_module_predict_unlabeled_after_fit():
    """A module bound for training must predict from a LABEL-LESS iterator
    (the batch carries an empty label list) — the decode-time idiom of
    example/nmt/train_transformer_mt.py."""
    x, y = _synthetic_classification(n=100)
    mod = mx.mod.Module(mlp_symbol(), context=mx.cpu())
    mod.fit(mx.io.NDArrayIter(x, y, batch_size=25), num_epoch=1)
    out = mod.predict(mx.io.NDArrayIter(x, batch_size=25))
    assert out.shape == (100, 5)
    assert np.allclose(out.asnumpy().sum(axis=1), 1.0, atol=1e-5)


def test_module_save_load_checkpoint(tmp_path):
    x, y = _synthetic_classification(n=100)
    prefix = str(tmp_path / "mlp")
    mod = mx.mod.Module(mlp_symbol(), context=mx.cpu())
    it = mx.io.NDArrayIter(x, y, batch_size=20)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.save_checkpoint(prefix, 3)
    mod2 = mx.mod.Module.load(prefix, 3, context=mx.cpu())
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    a1, _ = mod.get_params()
    a2, _ = mod2.get_params()
    for k in a1:
        assert np.allclose(a1[k].asnumpy(), a2[k].asnumpy())


def test_module_multi_device_data_parallel():
    """Multi-context DP on the virtual 8-device CPU mesh (reference trick:
    test_multi_device_exec.py uses cpu(0)/cpu(1))."""
    x, y = _synthetic_classification(n=400)
    ctxs = [mx.cpu(i) for i in range(4)]
    train = mx.io.NDArrayIter(x, y, batch_size=40, shuffle=True)
    mod = mx.mod.Module(mlp_symbol(), context=ctxs)
    mod.fit(train, optimizer="sgd", optimizer_params=(("learning_rate", 0.1),), num_epoch=4)
    score = mod.score(mx.io.NDArrayIter(x, y, batch_size=40), "acc")
    assert score[0][1] > 0.9


def test_module_multi_device_matches_single_device():
    """One step of DP training must equal single-device training on the same
    batch (gradient-sum arithmetic, reference: dist_sync closed-form test)."""
    x, y = _synthetic_classification(n=40, seed=3)
    batch = mx.io.DataBatch(
        data=[mx.nd.array(x)], label=[mx.nd.array(y)], pad=0, index=None
    )
    results = []
    for ctxs in ([mx.cpu(0)], [mx.cpu(i) for i in range(4)]):
        mx.random.seed(11)
        mod = mx.mod.Module(mlp_symbol(), context=ctxs)
        mod.bind(data_shapes=[("data", (40, 20))], label_shapes=[("softmax_label", (40,))])
        mod.init_params(initializer=mx.init.Xavier())
        mod.init_optimizer(optimizer="sgd", optimizer_params=(("learning_rate", 0.5),))
        for _ in range(3):
            mod.forward_backward(batch)
            mod.update()
        args, _ = mod.get_params()
        results.append({k: v.asnumpy() for k, v in args.items()})
    for k in results[0]:
        assert np.allclose(results[0][k], results[1][k], rtol=1e-4, atol=1e-5), k


def test_ndarray_iter_pad_and_shuffle():
    x = np.arange(50, dtype="f").reshape(10, 5)
    y = np.arange(10, dtype="f")
    it = mx.io.NDArrayIter(x, y, batch_size=4, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[-1].pad == 2
    it.reset()
    total = sum(b.data[0].shape[0] for b in it)
    assert total == 12


def test_optimizer_lr_scheduler():
    sched = mx.lr_scheduler.FactorScheduler(step=10, factor=0.5)
    opt = mx.optimizer.SGD(learning_rate=1.0, lr_scheduler=sched)
    w = mx.nd.ones((2,))
    g = mx.nd.ones((2,))
    for _ in range(25):
        opt.update(0, w, g, None)
    assert sched.base_lr < 1.0


def test_optimizer_wd_mult_skips_bias():
    opt = mx.optimizer.SGD(learning_rate=0.1, wd=0.5, param_idx2name={0: "fc_weight", 1: "fc_bias"})
    w = mx.nd.ones((2,))
    b = mx.nd.ones((2,))
    zero_grad = mx.nd.zeros((2,))
    opt.update(0, w, zero_grad, None)
    opt.update(1, b, zero_grad, None)
    assert np.allclose(w.asnumpy(), 1.0 - 0.1 * 0.5)  # decayed
    assert np.allclose(b.asnumpy(), 1.0)  # bias: wd_mult 0


def test_kvstore_local_semantics():
    """Aggregation identities (reference: tests/python/unittest/test_kvstore.py)."""
    shape = (4, 4)
    kv = mx.kv.create("local")
    kv.init(3, mx.nd.zeros(shape))
    kv.push(3, mx.nd.ones(shape))
    out = mx.nd.zeros(shape)
    kv.pull(3, out=out)
    assert np.allclose(out.asnumpy(), 1.0)
    # aggregate over "devices"
    vals = [mx.nd.ones(shape) for _ in range(4)]
    kv.push(3, vals)
    kv.pull(3, out=out)
    assert np.allclose(out.asnumpy(), 4.0)
    # updater path
    kv2 = mx.kv.create("local")
    kv2.init(9, mx.nd.ones(shape))
    opt = mx.optimizer.SGD(learning_rate=0.1, rescale_grad=1.0)
    kv2.set_optimizer(opt)
    kv2.push(9, [mx.nd.ones(shape)] * 2)  # grad sum = 2
    kv2.pull(9, out=out)
    assert np.allclose(out.asnumpy(), 1.0 - 0.1 * 2.0)


def test_initializers():
    for init, check in [
        (mx.init.Zero(), lambda a: np.allclose(a, 0)),
        (mx.init.One(), lambda a: np.allclose(a, 1)),
        (mx.init.Constant(3.5), lambda a: np.allclose(a, 3.5)),
        (mx.init.Uniform(0.1), lambda a: np.abs(a).max() <= 0.1),
        (mx.init.Normal(0.01), lambda a: np.abs(a).mean() < 0.05),
        (mx.init.Xavier(), lambda a: np.isfinite(a).all()),
        (mx.init.MSRAPrelu(), lambda a: np.isfinite(a).all()),
    ]:
        arr = mx.nd.zeros((20, 30))
        init("test_weight", arr)
        assert check(arr.asnumpy()), type(init).__name__
    # orthogonal: W @ W.T ≈ scale^2 * I
    arr = mx.nd.zeros((10, 30))
    mx.init.Orthogonal(scale=1.0)("q_weight", arr)
    a = arr.asnumpy()
    assert np.allclose(a @ a.T, np.eye(10), atol=1e-4)
    # bias/gamma/beta dispatch
    arr = mx.nd.full((5,), 9.0)
    mx.init.Xavier()("fc1_bias", arr)
    assert np.allclose(arr.asnumpy(), 0.0)


def test_regression_metrics_rank1_pred():
    """MAE/MSE/RMSE with rank-1 preds vs rank-1 labels (the
    LinearRegressionOutput shape): must NOT broadcast (B,1)-(B,) into a
    (B,B) matrix — regression for the bug that froze every regression
    example's reported RMSE at ~sqrt(var(label)+var(pred))."""
    rs = np.random.RandomState(0)
    y = rs.randn(32).astype("float32")
    p = rs.randn(32).astype("float32")
    for cls, ref in ((mx.metric.MAE, np.abs(y - p).mean()),
                     (mx.metric.MSE, ((y - p) ** 2).mean()),
                     (mx.metric.RMSE, np.sqrt(((y - p) ** 2).mean()))):
        m = cls()
        m.update([mx.nd.array(y)], [mx.nd.array(p)])
        assert abs(m.get()[1] - ref) < 1e-5, (cls.__name__, m.get()[1], ref)
        # 2-D (B,1) preds (the reference layout) must agree exactly
        m2 = cls()
        m2.update([mx.nd.array(y)], [mx.nd.array(p.reshape(-1, 1))])
        assert abs(m2.get()[1] - m.get()[1]) < 1e-7


def test_metrics():
    acc = mx.metric.create("acc")
    acc.update([mx.nd.array([0, 1, 1])], [mx.nd.array([[0.9, 0.1], [0.2, 0.8], [0.7, 0.3]])])
    assert abs(acc.get()[1] - 2.0 / 3) < 1e-6
    mse = mx.metric.MSE()
    mse.update([mx.nd.array([1.0, 2.0])], [mx.nd.array([[1.5], [2.5]])])
    assert abs(mse.get()[1] - 0.25) < 1e-6
    topk = mx.metric.TopKAccuracy(top_k=2)
    topk.update([mx.nd.array([2, 0])], [mx.nd.array([[0.1, 0.5, 0.4], [0.35, 0.4, 0.25]])])
    assert abs(topk.get()[1] - 1.0) < 1e-6
