"""Imperative autograd (port of the reference's tests/python/unittest/
test_autograd.py semantics: grad_and_loss, argnum, unary/binary chains,
training-mode flag)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu.ndarray import zeros


def _uniform(shape):
    return mx.nd.array(np.random.uniform(-1, 1, shape).astype("float32"))


def autograd_assert(*args, func, grad_func):
    grad_and_loss = ag.grad_and_loss(func)
    grads, output = grad_and_loss(*args)
    res = func(*args)
    np.testing.assert_allclose(output.asnumpy(), res.asnumpy(), rtol=1e-5)
    expected = grad_func(*args)
    for g, e in zip(grads, expected):
        np.testing.assert_allclose(g.asnumpy(), e, rtol=1e-4, atol=1e-5)


def test_unary_func():
    x = _uniform((4, 5))
    autograd_assert(x, func=lambda x: x + 1, grad_func=lambda x: [np.ones_like(x.asnumpy())])
    autograd_assert(x, func=lambda x: x * 4, grad_func=lambda x: [4 * np.ones_like(x.asnumpy())])
    autograd_assert(x, func=lambda x: x * x, grad_func=lambda x: [2 * x.asnumpy()])


def test_binary_func():
    x = _uniform((3, 4))
    y = _uniform((3, 4))
    autograd_assert(x, y, func=lambda a, b: a * b,
                    grad_func=lambda a, b: [b.asnumpy(), a.asnumpy()])
    autograd_assert(x, y, func=lambda a, b: a + b,
                    grad_func=lambda a, b: [np.ones((3, 4), "f"), np.ones((3, 4), "f")])


def test_argnum():
    def f_with_mode(a, b, mode):
        if mode:
            return a + b
        return a * b

    x = _uniform((3, 2))
    y = _uniform((3, 2))
    fn = ag.grad_and_loss(lambda a, b, m: f_with_mode(a, b, m), argnum=[0, 1])
    grads, out = fn(x, y, True)
    np.testing.assert_allclose(grads[0].asnumpy(), np.ones((3, 2)), rtol=1e-5)


def test_chain_of_ops():
    x = _uniform((2, 3))

    def f(x):
        y = mx.nd.exp(x)
        z = y * y
        return mx.nd.sum(z)

    grads = ag.grad(f)(x)
    expected = 2 * np.exp(2 * x.asnumpy())
    np.testing.assert_allclose(grads[0].asnumpy(), expected, rtol=1e-4)


def test_backward_with_head_grad():
    x = _uniform((3, 3))
    gx = zeros((3, 3))
    ag.mark_variables([x], [gx])
    with ag.record():
        y = x * 2
    head = mx.nd.array(np.full((3, 3), 0.5, "float32"))
    ag.backward([y], out_grads=[head])
    np.testing.assert_allclose(gx.asnumpy(), np.ones((3, 3)), rtol=1e-5)
    ag._MARKED.clear()


def test_grad_req_add():
    x = _uniform((2, 2))
    gx = zeros((2, 2))
    ag.mark_variables([x], [gx], grad_reqs="add")
    for _ in range(2):
        with ag.record():
            y = x * 3
        ag.backward([y])
    np.testing.assert_allclose(gx.asnumpy(), 6 * np.ones((2, 2)), rtol=1e-5)
    ag._MARKED.clear()


def test_training_flag():
    x = mx.nd.ones((10, 10))
    with ag.record(train_mode=False):
        assert ag.is_training() is False
        assert ag.is_recording() is True
    assert ag.is_recording() is False


def test_retain_graph():
    x = _uniform((2, 2))
    gx = zeros((2, 2))
    ag.mark_variables([x], [gx])
    with ag.record():
        y = x * x
    ag.backward([y], retain_graph=True)
    g1 = gx.asnumpy().copy()
    ag.backward([y])  # tape still alive
    np.testing.assert_allclose(gx.asnumpy(), g1, rtol=1e-6)
    ag._MARKED.clear()
