"""Multi-worker data-parallel training smoke test.

Counterpart of the reference's tests/nightly/dist_lenet.py: train a small
conv net across workers with kvstore=dist_tpu_sync and assert convergence.
Each worker holds a disjoint shard of the same synthetic set (deterministic
templates), gradients sync through the all-reduce KVStore.

    python tools/launch.py -n 2 --launcher local --cpu-devices 1 \
        python tests/nightly/dist_lenet.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import models  # noqa: E402


def make_data(rank, nworker, n=512, num_classes=4):
    templates = np.random.RandomState(7).rand(num_classes, 28, 28) > 0.7
    rs = np.random.RandomState(100 + rank)
    y = rs.randint(0, num_classes, n // nworker).astype(np.float32)
    x = templates[y.astype(int)].astype(np.float32)
    x += rs.normal(0, 0.25, x.shape)
    return x[:, None], y


def main():
    kv = mx.kv.create("dist_tpu_sync")
    x, y = make_data(kv.rank, kv.num_workers)
    it = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True)

    net = models.get_symbol("lenet", num_classes=4)
    mod = mx.mod.Module(net, context=mx.current_context())
    accs = []

    class Grab:
        def __call__(self, param):
            if param.eval_metric:
                accs.append(param.eval_metric.get()[1])

    mod.fit(it, num_epoch=3, kvstore=kv,
            optimizer="sgd", optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            initializer=mx.init.Xavier(), eval_metric="acc",
            batch_end_callback=Grab())
    final = accs[-1]
    assert final > 0.9, "rank %d final accuracy %.3f" % (kv.rank, final)

    # all workers must hold identical parameters after synced training
    params, _ = mod.get_params()
    sample = params["conv1_weight" if "conv1_weight" in params else sorted(params)[0]]
    import jax
    from jax.experimental.multihost_utils import process_allgather

    gathered = np.asarray(process_allgather(sample._jax()))
    for w in range(1, kv.num_workers):
        np.testing.assert_allclose(gathered[0], gathered[w], rtol=1e-5, atol=1e-6)
    print("dist_lenet rank %d/%d: acc=%.3f, params in sync" % (kv.rank, kv.num_workers, final))


if __name__ == "__main__":
    main()
