"""2-process sparse-vs-dense KVStore smoke: the recommender round
(docs/SPARSE.md; tools/ci_check.sh runs this at -n 2).

One tiny embedding+MLP click model trains twice through a dist KVStore with
rank-DISJOINT batches (the index-union machinery must merge genuinely
different touched sets):

  * sparse arm — the embedding gradient pushes as a RowSparseNDArray: the
    engine allgathers the unique-row union and allreduces only those rows
    (``kvstore.bytes.sparse``);
  * dense arm  — the same gradient pushes as the full (vocab, dim) buffer
    through the bucketed allreduce (``kvstore.bytes.allreduce``), the
    pre-sparse control.

Gates, on every rank:
  1. weight parity: after R rounds the two arms' weights match, atol 1e-6
     (wire strategy must not change the math);
  2. wire bytes: the sparse arm's ``kvstore.bytes.sparse`` is strictly less
     than the dense control's table-attributable allreduce bytes.

Rank 0 prints one ``DIST_SPARSE {json}`` line (bench.py's recommender leg
reads it: embedding-bytes-moved + the sparse/dense wire ratio).

    python tools/launch.py -n 2 --launcher local --cpu-devices 1 \
        python tests/nightly/dist_sparse_kvstore.py
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__),
                                                "..", "..")))

os.environ.setdefault("MXNET_TELEMETRY", "counters")

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import telemetry  # noqa: E402
from mxnet_tpu.sparse import from_dense  # noqa: E402

V, D, B, ROUNDS = 2048, 32, 32, 6
TRAINABLE = ("emb_weight", "fc_weight", "fc_bias", "click_weight",
             "click_bias")


def _net():
    user = mx.sym.Variable("user")
    emb = mx.sym.SparseEmbedding(data=user, input_dim=V, output_dim=D,
                                 name="emb")
    h = mx.sym.Activation(
        mx.sym.FullyConnected(emb, num_hidden=16, name="fc"),
        act_type="relu")
    logit = mx.sym.FullyConnected(h, num_hidden=1, name="click")
    return mx.sym.LogisticRegressionOutput(
        data=logit, label=mx.sym.Variable("label"), name="out")


def _batch(rnd, rank):
    """Rank-disjoint ids: rank r draws from its own half of the vocab, so
    the union is strictly larger than any local set."""
    rs = np.random.RandomState(1000 * rnd + rank)
    lo, hi = rank * (V // 8), (rank + 1) * (V // 8)
    ids = rs.randint(lo, hi, (B,))
    labels = rs.randint(0, 2, (B,))
    return ids, labels


def run_arm(sparse_wire, nworker):
    kv = mx.kv.create("dist_tpu_sync")
    opt = mx.optimizer.SGD(learning_rate=0.05,
                           rescale_grad=1.0 / nworker)
    kv.set_optimizer(opt)
    ex = _net().simple_bind(mx.context.current_context(),
                            user=(B,), label=(B,))
    rs = np.random.RandomState(42)  # identical on every rank and arm
    for name in TRAINABLE:
        ex.arg_dict[name][:] = (rs.rand(*ex.arg_dict[name].shape)
                                .astype("float32") - 0.5) * 0.1
        kv.init(name, ex.arg_dict[name])
        kv.pull(name, out=ex.arg_dict[name])
    pre = dict(telemetry.counters())
    t0 = time.perf_counter()
    for rnd in range(ROUNDS):
        ids, labels = _batch(rnd, kv.rank)
        ex.arg_dict["user"][:] = ids.astype("float32")
        ex.arg_dict["label"][:] = labels.astype("float32")
        ex.forward(is_train=True)
        ex.backward()
        g_emb = ex.grad_dict["emb_weight"]
        if sparse_wire:
            kv.push("emb_weight", from_dense(g_emb, rows=ids))
        else:
            kv.push("emb_weight", g_emb)
        for name in TRAINABLE[1:]:
            kv.push(name, ex.grad_dict[name])
        for name in TRAINABLE:
            kv.pull(name, out=ex.arg_dict[name])
    for name in TRAINABLE:
        ex.arg_dict[name].wait_to_read()
    elapsed = time.perf_counter() - t0
    kv._barrier()
    post = dict(telemetry.counters())
    delta = {k: post.get(k, 0) - pre.get(k, 0)
             for k in ("kvstore.bytes.sparse", "kvstore.bytes.allreduce",
                       "kvstore.sparse_rows_pushed",
                       "kvstore.sparse_dense_fallbacks",
                       "embedding.rows_touched")}
    weights = {name: ex.arg_dict[name].asnumpy() for name in TRAINABLE}
    return weights, delta, elapsed


def main():
    kv_probe = mx.kv.create("dist_tpu_sync")
    rank, nworker = kv_probe.rank, kv_probe.num_workers
    assert nworker >= 2, "run under tools/launch.py -n 2"

    w_sparse, d_sparse, t_sparse = run_arm(True, nworker)
    w_dense, d_dense, t_dense = run_arm(False, nworker)

    # ---- gate 1: weight parity (wire strategy must not change the math)
    max_diff = 0.0
    for name in TRAINABLE:
        diff = float(np.abs(w_sparse[name] - w_dense[name]).max())
        max_diff = max(max_diff, diff)
        np.testing.assert_allclose(
            w_sparse[name], w_dense[name], atol=1e-6,
            err_msg="sparse/dense weight divergence in %s" % name)

    # ---- gate 2: wire bytes. The dense control's table cost is its
    # allreduce delta minus the sparse arm's (both arms push the SAME
    # dense MLP params through the bucket path — that cost cancels).
    sparse_bytes = d_sparse["kvstore.bytes.sparse"]
    table_dense_bytes = (d_dense["kvstore.bytes.allreduce"]
                         - d_sparse["kvstore.bytes.allreduce"])
    assert sparse_bytes > 0, "sparse arm moved no sparse bytes"
    assert d_sparse["kvstore.sparse_dense_fallbacks"] == 0, \
        "sparse arm fell back to dense wire (union too dense for the test?)"
    assert sparse_bytes < table_dense_bytes, \
        "sparse wire (%d B) not below the dense control's table " \
        "allreduce (%d B)" % (sparse_bytes, table_dense_bytes)

    if rank == 0:
        print("DIST_SPARSE " + json.dumps({
            "workers": nworker, "vocab": V, "dim": D, "batch": B,
            "rounds": ROUNDS,
            "parity_max_abs_diff": max_diff,
            "embedding_bytes_moved": int(sparse_bytes),
            "dense_table_bytes": int(table_dense_bytes),
            "sparse_vs_dense_wire_ratio": round(
                sparse_bytes / max(1, table_dense_bytes), 4),
            "rows_pushed": int(d_sparse["kvstore.sparse_rows_pushed"]),
            "samples_per_s_sparse": round(nworker * B * ROUNDS / t_sparse, 1),
            "samples_per_s_dense": round(nworker * B * ROUNDS / t_dense, 1),
        }), flush=True)
    print("dist_sparse_kvstore rank %d/%d: parity + wire-byte gates passed "
          "(sparse %d B < dense %d B)"
          % (rank, nworker, sparse_bytes, table_dense_bytes))


if __name__ == "__main__":
    main()
