"""2-process predicted-vs-measured comm-bytes check for the auto-parallel
planner's cost model (docs/PARALLEL_PLANNER.md).

Run under the launcher::

    python tools/launch.py -n 2 --launcher local --cpu-devices 1 \
        python tests/nightly/autoplan_measure.py

The planner predicts gradient-sync wire bytes per device per step with the
ring-allreduce formula ``2*(W-1)/W * grad_bytes`` — the same accounting
``kvstore_bucket`` counts into the ``kvstore.bytes.*`` counters at flush
time. This script fits a small MLP on the legacy (``fused_step=False``)
bucketed kvstore path for a fixed number of steps and asserts the measured
counters land within 2x of the prediction (the ISSUE 10 acceptance bar —
bucket padding and comm-dtype packing are the expected slack). Rank 0
prints one ``AUTOPLAN_MEASURE {json}`` line for the bench autoplan leg.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))

os.environ.setdefault("MXNET_TELEMETRY", "counters")

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import telemetry  # noqa: E402
from mxnet_tpu.parallel import autoplan  # noqa: E402

BATCH, BATCHES, EPOCHS, DIM = 16, 4, 2, 64


def _mlp():
    s = mx.sym.Variable("data")
    s = mx.sym.FullyConnected(s, num_hidden=256, name="fc1")
    s = mx.sym.Activation(s, act_type="relu")
    s = mx.sym.FullyConnected(s, num_hidden=256, name="fc2")
    s = mx.sym.Activation(s, act_type="relu")
    s = mx.sym.FullyConnected(s, num_hidden=4, name="fc3")
    return mx.sym.SoftmaxOutput(s, name="softmax")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-ratio", type=float, default=2.0,
                    help="measured/predicted acceptance band "
                         "[1/max, max] (default 2x)")
    args = ap.parse_args()

    kv = mx.kv.create("dist_tpu_sync")
    rank, world = kv.rank, kv.num_workers
    rs = np.random.RandomState(11 + rank)
    x = rs.rand(BATCH * BATCHES, DIM).astype("float32")
    y = rs.randint(0, 4, (BATCH * BATCHES,)).astype("float32")
    it = mx.io.NDArrayIter(x, y, batch_size=BATCH)
    mod = mx.mod.Module(_mlp(), context=mx.cpu(), fused_step=False)
    mod.fit(it, num_epoch=EPOCHS, kvstore=kv,
            optimizer="sgd", optimizer_params=(("learning_rate", 0.05),))
    mx.nd.waitall()
    measured = sum(
        telemetry.counter("kvstore.bytes.%s" % k).value
        for k in ("allreduce", "reduce_scatter", "all_gather"))
    steps = BATCHES * EPOCHS

    # the legacy kvstore path IS the naive all-dp plan: predict with the
    # planner's naive row (gradsync only — a pure-dp MLP has no reshard)
    plan = autoplan.plan_parallel(
        _mlp(), {"data": (BATCH * world, DIM)}, devices=world)
    predicted = plan.naive["comm_bytes"]
    ratio = measured / float(predicted * steps) if predicted else float("inf")
    row = {"workers": world, "steps": steps,
           "predicted_bytes_per_step": int(predicted),
           "measured_bytes": int(measured),
           "measured_bytes_per_step": int(measured // steps),
           "ratio": round(ratio, 4)}
    if rank == 0:
        print("AUTOPLAN_MEASURE " + json.dumps(row))
    assert measured > 0, "no kvstore.bytes.* counters fired"
    assert 1.0 / args.max_ratio <= ratio <= args.max_ratio, \
        "measured comm %d B is outside %gx of predicted %d B/step x %d" \
        % (measured, args.max_ratio, predicted, steps)
    kv._barrier()
    print("AUTOPLAN_MEASURE_OK rank %d" % rank)


if __name__ == "__main__":
    main()
