"""8-process smoke for the bucketed dist KVStore path (docs/PERF.md §11).

Run under the launcher (tools/ci_check.sh step 5 runs this at -n 8):

    python tools/launch.py -n 8 --launcher local \
        python tests/nightly/dist_kvstore_overlap.py

Asserts, on every rank:
  1. overlap telemetry fires during a Module.fit backward on the legacy
     (fused_step=False) kvstore path: ``kvstore.bucket_flushes`` > 1 and
     ``kvstore.overlap_ratio`` > 0 with a multi-bucket plan;
  2. sharded-update (MXNET_KVSTORE_UPDATE=sharded) weights match
     replicated-update weights after 5 SGD(momentum) steps, fp32 atol 1e-6;
  3. the bucketed push+pull round-trip sustains ``--min-gbps`` bus
     bandwidth (default: 3x the r05 scoreboard value of 0.056).
"""
import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))

os.environ.setdefault("MXNET_TELEMETRY", "counters")
# small buckets force a multi-bucket plan on the tiny test net, so the
# overlap machinery (priority flush + per-bucket finalize) actually engages
os.environ.setdefault("MXNET_KVSTORE_BUCKET_MB", "0.002")

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import telemetry  # noqa: E402


def _mlp():
    sym = mx.sym.Variable("data")
    sym = mx.sym.FullyConnected(sym, num_hidden=32, name="fc1")
    sym = mx.sym.Activation(sym, act_type="relu")
    sym = mx.sym.FullyConnected(sym, num_hidden=16, name="fc2")
    sym = mx.sym.Activation(sym, act_type="relu")
    sym = mx.sym.FullyConnected(sym, num_hidden=4, name="fc3")
    return mx.sym.SoftmaxOutput(sym, name="softmax")


def check_fit_overlap(kv):
    """Module.fit on the per-key priority kvstore path must light up the
    bucket/overlap telemetry."""
    rs = np.random.RandomState(7)
    it = mx.io.NDArrayIter(rs.rand(24, 8).astype("float32"),
                           rs.randint(0, 4, (24,)).astype("float32"),
                           batch_size=8)
    mod = mx.mod.Module(_mlp(), context=mx.cpu(), fused_step=False)
    mod.fit(it, num_epoch=2, kvstore=kv,
            optimizer="sgd", optimizer_params=(("learning_rate", 0.05),))
    flushes = telemetry.counter("kvstore.bucket_flushes").value
    overlap = telemetry.gauge("kvstore.overlap_ratio").value
    assert flushes > 1, "no bucket flushes fired (got %r)" % flushes
    assert overlap is not None and overlap > 0.0, \
        "kvstore.overlap_ratio did not register (got %r)" % overlap
    assert kv._bucket_engine is not None and kv._bucket_engine.plan is not None
    n_buckets = len(kv._bucket_engine.plan.buckets)
    assert n_buckets > 1, "expected a multi-bucket plan, got %d" % n_buckets
    return {"bucket_flushes": int(flushes), "overlap_ratio": float(overlap),
            "buckets": n_buckets}


def _run_updates(kv_type, mode, shapes, n_steps=5):
    """Push deterministic pseudo-gradients through a fresh dist store with a
    momentum-SGD updater in the given MXNET_KVSTORE_UPDATE mode; return the
    final weights."""
    os.environ["MXNET_KVSTORE_UPDATE"] = mode
    kv = mx.kv.create(kv_type)
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=1e-4,
                           rescale_grad=1.0 / (8 * kv.num_workers))
    kv.set_optimizer(opt)
    rs = np.random.RandomState(11)
    weights = {i: rs.rand(*s).astype("float32") for i, s in enumerate(shapes)}
    for i, w in weights.items():
        kv.init(i, mx.nd.array(w))
    grads = [{i: rs.rand(*s).astype("float32") - 0.5
              for i, s in enumerate(shapes)} for _ in range(n_steps)]
    rank = kv.rank
    outs = {i: mx.nd.zeros(s) for i, s in enumerate(shapes)}
    for step in range(n_steps):
        # rank-dependent scale, closed-form-summable across workers
        for i in reversed(sorted(grads[step])):
            kv.push(i, mx.nd.array(grads[step][i] * (rank + 1)),
                    priority=-i)
        for i in sorted(grads[step]):
            kv.pull(i, out=outs[i], priority=-i)
    kv._barrier()
    return {i: o.asnumpy() for i, o in outs.items()}


def check_sharded_parity(kv_type):
    shapes = [(64, 8), (64,), (32, 64), (32,), (4, 32), (4,)]
    rep = _run_updates(kv_type, "replicated", shapes)
    shd = _run_updates(kv_type, "sharded", shapes)
    os.environ["MXNET_KVSTORE_UPDATE"] = "replicated"
    for i in rep:
        np.testing.assert_allclose(
            shd[i], rep[i], atol=1e-6, rtol=0,
            err_msg="sharded/replicated weight divergence on key %d" % i)
    return {"keys": len(shapes), "atol": 1e-6}


def check_double_push():
    """Two pushes of one key in a single round must BOTH apply through the
    updater (an undispatched bucket drains — partial flush — instead of the
    second push overwriting the first's slot)."""
    os.environ["MXNET_KVSTORE_UPDATE"] = "replicated"
    kv = mx.kv.create("dist_tpu_sync")
    W = kv.num_workers
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.0, wd=0.0,
                           rescale_grad=1.0)
    kv.set_optimizer(opt)
    # two keys in one bucket so the first push leaves the bucket unfilled
    kv.init("dp_a", mx.nd.ones((16,)))
    kv.init("dp_b", mx.nd.ones((16,)))
    kv.push("dp_a", mx.nd.ones((16,)))
    kv.push("dp_b", mx.nd.ones((16,)))
    out = mx.nd.zeros((16,))
    kv.pull("dp_a", out=out)  # plan commits: [dp_a, dp_b] share a bucket
    kv.push("dp_a", mx.nd.ones((16,)) * 2)   # round 2, bucket 1/2 full
    kv.push("dp_a", mx.nd.ones((16,)) * 3)   # same key again: must drain
    kv.pull("dp_a", out=out)
    # w = 1 - .1*(W*1) - .1*(W*2) - .1*(W*3)
    expected = 1.0 - 0.1 * W * (1 + 2 + 3)
    np.testing.assert_allclose(out.asnumpy(), expected, atol=1e-6)
    kv._barrier()
    return {"expected": expected}


def check_bandwidth(size_mb, n_iter, n_keys, min_gbps):
    """Reuses tools/bandwidth/measure.py's measure_kvstore — the exact path
    bench.py times — so CI gates the same code it scores."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..",
                                    "tools", "bandwidth"))
    from measure import measure_kvstore

    # best of two passes: the floor is a regression gate, and a transient
    # host-load dip on an oversubscribed CI box must not fail it
    best = None
    for _ in range(2):
        dt, gbps, n, overlap = measure_kvstore(size_mb, n_iter,
                                               n_keys=n_keys)
        if best is None or gbps > best[0]:
            best = (gbps, overlap)
        if best[0] >= min_gbps:
            break
    gbps, overlap = best
    assert gbps >= min_gbps, (
        "bucketed allreduce bus bandwidth %.3f GB/s below the %.3f floor"
        % (gbps, min_gbps))
    return {"gbps": round(gbps, 3), "min_gbps": min_gbps,
            "size_mb": size_mb, "keys": n_keys,
            "overlap_ratio": overlap}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--min-gbps", type=float, default=3 * 0.056,
                    help="bandwidth floor (default: 3x the r05 kvstore number)")
    ap.add_argument("--size-mb", type=float, default=32.0)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--skip-bandwidth", action="store_true",
                    help="functional checks only (oversubscribed hosts)")
    ap.add_argument("--only-bandwidth", action="store_true",
                    help="bandwidth floor only, in otherwise-idle processes")
    args = ap.parse_args()

    kv = mx.kv.create("dist_tpu_sync")
    report = {"workers": kv.num_workers, "rank": kv.rank}
    if not args.only_bandwidth:
        report["fit_overlap"] = check_fit_overlap(kv)
        report["sharded_parity"] = check_sharded_parity("dist_tpu_sync")
        report["double_push"] = check_double_push()
    if not args.skip_bandwidth:
        if "MXNET_KVSTORE_BUCKET_MB" in os.environ \
                and float(os.environ["MXNET_KVSTORE_BUCKET_MB"]) < 1:
            os.environ.pop("MXNET_KVSTORE_BUCKET_MB")  # tiny-test override
        report["bandwidth"] = check_bandwidth(
            args.size_mb, args.iters, n_keys=16, min_gbps=args.min_gbps)
    kv._barrier()
    if kv.rank == 0:
        print(json.dumps({"dist_kvstore_overlap": "OK", **report}))


if __name__ == "__main__":
    main()
