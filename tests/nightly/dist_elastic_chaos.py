"""Elastic fault-tolerance chaos smoke (docs/FAULT_TOLERANCE.md).

Orchestrated end-to-end (tools/ci_check.sh):

    python tests/nightly/dist_elastic_chaos.py --orchestrate <workdir>

which runs three phases:

  1. **chaos** — ``tools/launch.py -n 8 --elastic``: 8 workers run
     ``Module.fit(elastic=...)`` in sharded-update mode with periodic async
     checkpoints; worker ORIGINAL RANK 7 SIGTERMs itself mid-run. The drain
     protocol kicks in: rank 7 proposes the pause, everyone trains through
     the agreed round, rank 7 exits cleanly (rc 0), the 7 survivors re-form,
     reseed from the newest complete sharded checkpoint (``reseed=
     "checkpoint"`` pins the deterministic-rollback path), rescale the
     gradient normalization 8→7 and finish training. Rank 0 writes the
     final weights + a report (generation, world, reseed step, telemetry).
  2. **control** — a FRESH 7-worker elastic job pointed at a pruned copy of
     the checkpoint dir containing exactly the step the survivors reseeded
     from. It takes the different-W resume path (manifest world=8, live
     world=7), fast-forwards its iterator to the recorded position and
     trains the same remaining rounds.
  3. **compare** — chaos-survivor weights must match the control run's
     within fp32 tolerance: provable only if the re-form really reseeded
     from the checkpoint and replayed identically.

Also asserts: the ``checkpoint.inflight`` gauge was observed > 0 while
training (the async write really overlaps the step), the job re-formed to
generation 1 / world 7, and the evicted worker exited rc 0.
"""
import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))

EPOCHS = 2
BATCHES = 12          # per epoch per worker
BATCH = 8
KILL_ROUND = 8        # rank 7 SIGTERMs itself after this many updates
CKPT_PERIOD = 3


def _mlp():
    import mxnet_tpu as mx

    sym = mx.sym.Variable("data")
    sym = mx.sym.FullyConnected(sym, num_hidden=32, name="fc1")
    sym = mx.sym.Activation(sym, act_type="relu")
    sym = mx.sym.FullyConnected(sym, num_hidden=16, name="fc2")
    sym = mx.sym.Activation(sym, act_type="relu")
    sym = mx.sym.FullyConnected(sym, num_hidden=4, name="fc3")
    return mx.sym.SoftmaxOutput(sym, name="softmax")


def _data(orig_rank):
    import mxnet_tpu as mx

    rs = np.random.RandomState(100 + orig_rank)
    x = rs.rand(BATCHES * BATCH, 8).astype("float32")
    y = rs.randint(0, 4, (BATCHES * BATCH,)).astype("float32")
    return mx.io.NDArrayIter(x, y, batch_size=BATCH)


def run_worker(args):
    if os.environ.get("MXNET_CHAOS_VERBOSE"):
        import logging
        logging.basicConfig(
            level=logging.INFO,
            format="[w%(process)d] %(levelname)s %(message)s")
    os.environ.setdefault("MXNET_TELEMETRY", "counters")
    os.environ.setdefault("MXNET_KVSTORE_BUCKET_MB", "0.002")
    os.environ.setdefault("MXNET_KVSTORE_UPDATE", "sharded")
    import mxnet_tpu as mx
    from mxnet_tpu import dist, telemetry

    kv_type = "dist_tpu_sync"
    mx.kv.create(kv_type)  # triggers dist.init under the launcher env
    orig = dist.orig_rank() if dist.elastic_enabled() else 0
    launch_world = int(os.environ.get("MXNET_TPU_NUM_WORKERS", "1"))
    kill_rank = launch_world - 1
    args.mode = os.environ.get("MXNET_CHAOS_MODE", "drain")

    # sample the checkpoint.inflight gauge while training: the async write
    # must OVERLAP the step (acceptance: observed > 0 mid-run)
    peak = {"inflight": 0.0}
    stop = threading.Event()

    def sample():
        g = telemetry.gauge("checkpoint.inflight")
        while not stop.is_set():
            v = g.value
            if v:
                peak["inflight"] = max(peak["inflight"], v)
            time.sleep(0.0005)

    sampler = threading.Thread(target=sample, daemon=True)
    sampler.start()

    seen = {"rounds": 0}

    def batch_cb(param):
        seen["rounds"] += 1
        if (args.phase == "chaos" and orig == kill_rank
                and seen["rounds"] == KILL_ROUND):
            if args.mode == "crash":
                # hard death: no drain, no pause proposal — the survivors
                # must detect the broken collective, wait out the
                # heartbeat staleness, and recover from the checkpoint
                print("worker %d SIGKILLing itself at round %d"
                      % (orig, seen["rounds"]), flush=True)
                os.kill(os.getpid(), signal.SIGKILL)
            print("worker %d SIGTERMing itself at round %d"
                  % (orig, seen["rounds"]), flush=True)
            os.kill(os.getpid(), signal.SIGTERM)

    mod = mx.mod.Module(_mlp(), context=mx.cpu(), fused_step=False)
    ctl = mod.fit(
        _data(orig), num_epoch=EPOCHS, kvstore=kv_type,
        optimizer="sgd",
        optimizer_params=(("learning_rate", 0.05), ("momentum", 0.9),
                          ("wd", 1e-4)),
        batch_end_callback=batch_cb,
        elastic={"checkpoint_dir": args.ckpt_dir,
                 "checkpoint_period": CKPT_PERIOD,
                 "reseed": "checkpoint",
                 "resume": args.phase == "control"})
    stop.set()
    if ctl.evicted:
        print("worker %d evicted cleanly at round %d" % (orig, ctl._round),
              flush=True)
        return 0

    rank, world = dist.rank(), dist.num_workers()
    gen = dist.generation()
    if args.phase == "chaos":
        assert world == launch_world - 1, \
            "expected %d survivors, got %d" % (launch_world - 1, world)
        assert gen == 1, "expected generation 1, got %d" % gen
    arg_params, _ = mod.get_params()
    if rank == 0:
        out = os.path.join(args.workdir, "%s_final.npz" % args.phase)
        np.savez(out, **{k: v.asnumpy() for k, v in arg_params.items()})
        report = {
            "phase": args.phase, "world": world, "generation": gen,
            "rounds": ctl._round,
            "resume_round": ctl._resume_epoch,
            "peak_inflight": peak["inflight"],
            "checkpoint_saves":
                telemetry.counter("checkpoint.saves").value,
            "recoveries": telemetry.counter("dist.recoveries").value,
        }
        with open(os.path.join(args.workdir,
                               "%s_report.json" % args.phase), "w") as f:
            json.dump(report, f)
        print(json.dumps(report), flush=True)
    return 0


# --------------------------------------------------------------- orchestrate
def _launch(n, phase, workdir, ckpt_dir, extra_env=None):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.update(extra_env or {})
    cmd = [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                        "..", "tools", "launch.py"),
           "-n", str(n), "--launcher", "local", "--cpu-devices", "1",
           "--elastic",
           sys.executable, os.path.abspath(__file__),
           "--phase", phase, "--workdir", workdir, "--ckpt-dir", ckpt_dir]
    t0 = time.time()
    rc = subprocess.call(cmd, env=env)
    print("[chaos] phase %s: rc=%d in %.1fs" % (phase, rc, time.time() - t0),
          flush=True)
    return rc


def orchestrate(workdir, world=8, mode="drain"):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
    from mxnet_tpu import checkpoint as ckpt

    os.makedirs(workdir, exist_ok=True)
    chaos_ckpt = os.path.join(workdir, "ckpt")
    extra_env = {"MXNET_CHAOS_MODE": mode}
    if mode == "crash":
        # a SIGKILLed worker is detected by heartbeat staleness, not a
        # drain proposal — tighten the staleness window so the smoke
        # doesn't sit through the 60 s production default
        extra_env.update({"MXNET_ELASTIC_DEAD_TIMEOUT": "4",
                          "MXNET_TPU_HEARTBEAT_INTERVAL": "0.5"})
    rc = _launch(world, "chaos", workdir, chaos_ckpt, extra_env=extra_env)
    assert rc == 0, "chaos phase failed rc=%d" % rc
    report_path = os.path.join(workdir, "chaos_report.json")
    assert os.path.exists(report_path), (
        "chaos phase exited rc=0 but wrote no report — every worker took "
        "the evicted path instead of re-forming (pause payload named the "
        "survivors dead?)")
    report = json.load(open(report_path))
    assert report["world"] == world - 1 and report["generation"] == 1, report
    assert report["recoveries"] >= 1, report
    assert report["peak_inflight"] > 0, (
        "checkpoint.inflight gauge never observed > 0 — the async write "
        "did not overlap the step (report: %s)" % report)

    # the survivors reseeded from the newest complete checkpoint with a
    # launch-world manifest; give the control run EXACTLY that step
    steps = [s for s in ckpt.list_steps(chaos_ckpt)
             if (ckpt.load_manifest(chaos_ckpt, s) or {}).get("world")
             == world]
    assert steps, "no world-%d checkpoint left under %s" % (world, chaos_ckpt)
    reseed_step = None
    for s in reversed(steps):
        m = ckpt.load_manifest(chaos_ckpt, s)
        if m and ckpt._step_complete(chaos_ckpt, s, m):
            reseed_step = s
            break
    assert reseed_step is not None, "no COMPLETE world-%d step" % world
    control_ckpt = os.path.join(workdir, "ckpt-control")
    shutil.rmtree(control_ckpt, ignore_errors=True)
    os.makedirs(control_ckpt)
    shutil.copytree(ckpt.step_dir(chaos_ckpt, reseed_step),
                    ckpt.step_dir(control_ckpt, reseed_step))

    rc = _launch(world - 1, "control", workdir, control_ckpt)
    assert rc == 0, "control phase failed rc=%d" % rc

    chaos = np.load(os.path.join(workdir, "chaos_final.npz"))
    control = np.load(os.path.join(workdir, "control_final.npz"))
    assert set(chaos.files) == set(control.files)
    for k in chaos.files:
        np.testing.assert_allclose(
            chaos[k], control[k], atol=1e-6, rtol=0,
            err_msg="post-recovery weight divergence on %r: the re-formed "
                    "run does not match an uninterrupted %d-proc run" %
                    (k, report["world"]))
    print(json.dumps({"dist_elastic_chaos": "OK",
                      "reseed_step": reseed_step,
                      "peak_inflight": report["peak_inflight"],
                      "survivor_world": report["world"]}), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--orchestrate", metavar="WORKDIR", default=None)
    ap.add_argument("--world", type=int, default=8,
                    help="chaos-phase worker count (control runs world-1)")
    ap.add_argument("--mode", choices=["drain", "crash"], default="drain",
                    help="drain = worker SIGTERMs itself (pause proposal); "
                         "crash = SIGKILL (survivors detect the broken "
                         "collective + stale heartbeat)")
    ap.add_argument("--phase", choices=["chaos", "control"], default=None)
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--ckpt-dir", dest="ckpt_dir", default=None)
    args = ap.parse_args()
    if args.orchestrate:
        orchestrate(args.orchestrate, world=args.world, mode=args.mode)
        return
    sys.exit(run_worker(args))


if __name__ == "__main__":
    main()
