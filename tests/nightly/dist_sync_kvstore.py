"""Closed-form multi-worker KVStore sync test.

Counterpart of the reference's tests/nightly/dist_sync_kvstore.py:30-44
(test_sync_push_pull): every worker pushes a deterministic value, and after
the synchronized reduce the pulled result must equal the closed-form
arithmetic — here sum over ranks of (rank+1)·scale per round.

Run under the launcher (this is how the reference runs it, via
tools/launch.py --launcher local):

    python tools/launch.py -n 2 --launcher local --cpu-devices 1 \
        python tests/nightly/dist_sync_kvstore.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..")))
import mxnet_tpu as mx  # noqa: E402

SHAPE = (4, 5)
BIG_SHAPE = (1200, 1100)  # > the reference's BIGARRAY_BOUND analog: exercise big arrays
KEYS = ["3", "5", "7"]
NUM_ROUNDS = 3


def main():
    kv = mx.kv.create("dist_tpu_sync")
    rank, nworker = kv.rank, kv.num_workers
    assert nworker >= 1

    for key in KEYS:
        kv.init(key, mx.nd.zeros(SHAPE))
    kv.init("99", mx.nd.zeros(BIG_SHAPE))

    # sum over all ranks of (rank+1) = nworker(nworker+1)/2
    rank_sum = nworker * (nworker + 1) // 2

    for r in range(1, NUM_ROUNDS + 1):
        for key in KEYS:
            kv.push(key, mx.nd.ones(SHAPE) * (rank + 1) * r)
            out = mx.nd.zeros(SHAPE)
            kv.pull(key, out=out)
            expected = rank_sum * r  # no updater: push replaces with reduced sum
            np.testing.assert_allclose(out.asnumpy(), expected, rtol=1e-6)
        kv.push("99", mx.nd.ones(BIG_SHAPE) * (rank + 1) * r)
        out = mx.nd.zeros(BIG_SHAPE)
        kv.pull("99", out=out)
        np.testing.assert_allclose(out.asnumpy(), rank_sum * r, rtol=1e-6)
        kv._barrier()

    # batched multi-key push: all keys of the call ride ONE compiled
    # all-reduce (flatten-concat); closed form must still hold per key
    kv.push(KEYS, [mx.nd.ones(SHAPE) * (rank + 1) * int(k) for k in KEYS])
    outs = [mx.nd.zeros(SHAPE) for _ in KEYS]
    kv.pull(KEYS, out=outs)
    for k, out in zip(KEYS, outs):
        np.testing.assert_allclose(out.asnumpy(), rank_sum * int(k), rtol=1e-6)
    kv._barrier()

    print("dist_sync_kvstore rank %d/%d: all closed-form checks passed" % (rank, nworker))


if __name__ == "__main__":
    main()
