"""Failure-detection + restart-from-checkpoint worker (SURVEY.md §5.3).

Run under the launcher's supervision (the done-criterion of VERDICT r4 #6:
kill worker 1 of 2 mid-run, the job resumes from checkpoint):

    python tools/launch.py -n 2 --launcher local --cpu-devices 1 \
        --auto-restart 1 python tests/nightly/dist_crash_resume.py <workdir>

Each epoch every worker pushes a closed-form value through the dist KVStore
and accumulates the reduced sum into a checkpointed scalar ``w``. On the
first attempt, worker 1 kills itself mid-epoch-3 (after leaving a marker);
the launcher detects the death, tears the job down, and relaunches; workers
resume from rank 0's last checkpoint via model.find_last_checkpoint. The
final w must equal the closed form sum over ALL epochs — provable only if
the resumed run really continued from the checkpoint."""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import model  # noqa: E402

EPOCHS = 4
SHAPE = (3, 2)


def main():
    workdir = sys.argv[1]
    crash_epoch = int(os.environ.get("CRASH_EPOCH", "3"))
    prefix = os.path.join(workdir, "ckpt")
    marker = os.path.join(workdir, "crashed-once")

    kv = mx.kv.create("dist_tpu_sync")
    rank, nworker = kv.rank, kv.num_workers
    rank_sum = nworker * (nworker + 1) // 2

    net = mx.sym.Variable("w")
    last = model.find_last_checkpoint(prefix)
    if last is None:
        start_epoch, w = 0, 0.0
    else:
        _, args, _ = model.load_checkpoint(prefix, last)
        start_epoch, w = last, float(args["w"].asnumpy()[0])
        print("worker %d resumed from epoch %d w=%g" % (rank, last, w),
              flush=True)

    for epoch in range(start_epoch + 1, EPOCHS + 1):
        key = "e%d" % epoch
        kv.init(key, mx.nd.zeros(SHAPE))
        kv.push(key, mx.nd.ones(SHAPE) * (rank + 1) * epoch)
        out = mx.nd.zeros(SHAPE)
        kv.pull(key, out=out)
        expected = epoch * rank_sum
        np.testing.assert_allclose(out.asnumpy(), expected)
        w += expected
        if rank == 1 and epoch == crash_epoch and not os.path.exists(marker):
            with open(marker, "w") as f:
                f.write("epoch %d\n" % epoch)
            print("worker 1 simulating death at epoch %d" % epoch, flush=True)
            os._exit(1)
        if rank == 0:
            model.save_checkpoint(prefix, epoch, net,
                                  {"w": mx.nd.array(np.array([w], "f"))}, {})

    want = sum(e * rank_sum for e in range(1, EPOCHS + 1))
    assert abs(w - want) < 1e-6, (w, want)
    # at successful completion nobody is dead
    print("worker %d final w=%g dead_nodes=%d OK"
          % (rank, w, kv.num_dead_nodes(timeout=300)), flush=True)


if __name__ == "__main__":
    main()
