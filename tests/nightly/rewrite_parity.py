#!/usr/bin/env python
"""Bit-parity gate for the graph-rewrite pipeline (ISSUE 14 / CI).

For each representative model, build the symbol, run the rewrite pipeline
(analysis/rewrite.py) + the GL6xx verifier, then run an identical
forward+backward on the RAW and the REWRITTEN graph (same params, same
seed) and compare:

* forward outputs must be BITWISE identical (the fold/CSE/DCE/canonicalize
  contract — every rule preserves the compiled computation);
* backward gradients must be bitwise identical when no CSE merge fired,
  and within atol 1e-6 when one did (the vjp of a merged graph sums
  cotangents in a different order than the duplicated one — single-ulp
  reassociation, documented in docs/static_analysis.md §GL6xx).

Exit 0 on full parity + zero GL601/GL602/GL604, 1 otherwise. Run by
tools/ci_check.sh alongside the `graphlint --all-models --rewrite` sweep.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
os.environ.setdefault("MXNET_DEFAULT_CONTEXT", "cpu")

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import analysis  # noqa: E402

# (label, builder kwargs, bind shapes, bind dtypes)
MODELS = [
    ("mlp", ("mlp", {"num_classes": 10}),
     {"data": (8, 784), "softmax_label": (8,)}, {}),
    ("resnet-18", ("resnet-18", {"num_classes": 10,
                                 "image_shape": "3,32,32"}),
     {"data": (2, 3, 32, 32), "softmax_label": (2,)}, {}),
    ("transformer", ("transformer", {"vocab_size": 50, "model_dim": 32,
                                     "num_heads": 2, "num_layers": 2,
                                     "ffn_dim": 64, "seq_len": 8}),
     {"data": (2, 8), "softmax_label": (2, 8)}, {"data": "int32"}),
]


def run_once(sym, shapes, types, seed=1):
    mx.random.seed(7)
    ex = sym.simple_bind(mx.cpu(), type_dict=dict(types), grad_req="write",
                         **shapes)
    rs = np.random.RandomState(seed)
    for n, a in zip(ex._prog.arg_names, ex.arg_arrays):
        if np.issubdtype(np.dtype(a.dtype), np.integer):
            a[:] = rs.randint(0, 50, a.shape).astype(a.dtype)
        elif "label" in n:
            a[:] = rs.randint(0, 10, a.shape).astype(a.dtype)
        else:
            a[:] = rs.uniform(-0.1, 0.1, a.shape).astype(a.dtype)
    ex.forward(is_train=True)
    ex.backward()
    outs = [o.asnumpy() for o in ex.outputs]
    grads = {n: g.asnumpy() for n, g in zip(ex._prog.arg_names,
                                            ex.grad_arrays)
             if g is not None}
    return outs, grads


def main():
    failed = False
    for label, (zoo, kw), shapes, types in MODELS:
        sym = mx.models.get_symbol(zoo, **kw)
        res = analysis.rewrite(sym, shapes=shapes, types=types, label=label)
        report = analysis.verify_rewrite(res, grad_req="write",
                                         target=label)
        hard = [d for d in report.errors
                if d.code in ("GL601", "GL602", "GL604")]
        if hard:
            print("[%s] VERIFY FAILED:\n%s" % (label, report.format()))
            failed = True
            continue
        o_raw, g_raw = run_once(sym, shapes, types)
        o_rw, g_rw = run_once(res.symbol, shapes, types)
        fwd_ok = all(np.array_equal(a, b) for a, b in zip(o_raw, o_rw))
        cse_fired = res.counts["merged"] > 0
        bwd_max = 0.0
        bwd_ok = True
        for k, ga in g_raw.items():
            gb = g_rw[k]
            if cse_fired or "rsqrt_compose" in res.rule_table():
                d = float(np.max(np.abs(ga - gb))) if ga.size else 0.0
                bwd_max = max(bwd_max, d)
                bwd_ok = bwd_ok and d <= 1e-6
            else:
                bwd_ok = bwd_ok and np.array_equal(ga, gb)
        verdict = "OK" if (fwd_ok and bwd_ok) else "FAIL"
        if verdict == "FAIL":
            failed = True
        print("[%s] nodes %d->%d (%d merged, %d removed) fwd_bitwise=%s "
              "bwd_%s=%s (max %.2e) %s"
              % (label, res.nodes_before, res.nodes_after,
                 res.counts["merged"], res.counts["removed"], fwd_ok,
                 "atol1e-6" if cse_fired else "bitwise", bwd_ok, bwd_max,
                 verdict))
    if failed:
        print("rewrite parity gate FAILED")
        return 1
    print("rewrite parity gate OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
