"""Parity + plan tests for the generic pattern fusion engine
(fusion.py plan/execute + ops/fusion_patterns.py + the gate).

The contract under test: for every pattern, the FUSED lowering
(force-engaged via MXNET_FUSED_PATTERNS=<name>=1) produces the same
outputs and gradients as the unfused graph (engine off), forward and
backward, f32 and bf16, train and inference — and with the engine in auto
mode but no tune cache, execution is bit-identical to the engine being
off (every site falls back)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import fusion


def _tol(dtype):
    # bf16 headroom: the fused epilogue rounds through bf16 at a different
    # point than the unfused chain (f32 accumulator -> one bf16 round vs
    # per-op rounds), so boundary elements (e.g. relu at ~0) can differ by
    # a few bf16 ulps
    return 4e-2 if dtype == "bfloat16" else 2e-5


def _run(net, shapes, dtype, env, monkeypatch, is_train=True, seed=3):
    """Bind, seed params deterministically, forward(+backward); returns
    (outputs, grads dict)."""
    monkeypatch.setenv("MXNET_FUSED_PATTERNS", env)
    monkeypatch.delenv("MXNET_FUSION_TUNE_DIR", raising=False)
    rs = np.random.RandomState(seed)
    type_dict = {n: dtype for n in net.list_arguments()
                 if "label" not in n}
    ex = net.simple_bind(mx.cpu(), grad_req="write", type_dict=type_dict,
                         **shapes)
    for name, arr in zip(net.list_arguments(), ex.arg_arrays):
        if "label" in name:
            arr[:] = rs.randint(0, 4, arr.shape).astype("f")
        else:
            arr[:] = rs.uniform(-0.5, 0.5, arr.shape).astype("f")
    outs = ex.forward(is_train=is_train)
    host = [o.asnumpy().astype("f") for o in outs]
    grads = {}
    if is_train:
        ex.backward()
        grads = {n: (g.asnumpy().astype("f") if g is not None else None)
                 for n, g in ex.grad_dict.items()}
    return host, grads


def _assert_parity(ref, got, dtype, what, tol=None):
    r_outs, r_grads = ref
    g_outs, g_grads = got
    tol = tol if tol is not None else _tol(dtype)
    for a, b in zip(r_outs, g_outs):
        denom = np.max(np.abs(a)) + 1e-9
        assert np.max(np.abs(a - b)) / denom <= tol, (what, "outputs")
    for k in r_grads:
        if r_grads[k] is None:
            continue
        denom = np.max(np.abs(r_grads[k])) + 1e-9
        err = np.max(np.abs(r_grads[k] - g_grads[k])) / denom
        assert err <= tol, (what, "grad", k, err)


# ---------------------------------------------------------- matmul_bias_act
def _mba_net(act="relu"):
    sym = mx.sym
    x = sym.Variable("data")
    h = sym.FullyConnected(x, num_hidden=128, name="fc1")
    h = sym.Activation(h, act_type=act, name="act1")
    h = sym.FullyConnected(h, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(h, name="softmax")


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("act", ["relu", "tanh"])
def test_matmul_bias_act_parity(monkeypatch, dtype, act):
    net = _mba_net(act)
    shapes = {"data": (8, 32), "softmax_label": (8,)}
    ref = _run(net, shapes, dtype, "0", monkeypatch)
    got = _run(net, shapes, dtype, "matmul_bias_act=1", monkeypatch)
    tol = None
    if dtype == "bfloat16" and act == "relu":
        # relu-at-~0 elements can take DIFFERENT branches: the unfused
        # chain masks on the bf16-rounded pre-activation, the fused kernel
        # on the f32 accumulator — a boundary element flips its whole
        # gradient contribution. The autotuner's own 2e-2 parity check
        # rejects such sites in auto mode; this forced test only bounds
        # the divergence.
        tol = 1e-1
    _assert_parity(ref, got, dtype, "matmul_bias_act/" + act, tol=tol)


def test_matmul_bias_act_inference_parity(monkeypatch):
    net = _mba_net()
    shapes = {"data": (8, 32), "softmax_label": (8,)}
    ref = _run(net, shapes, "float32", "0", monkeypatch, is_train=False)
    got = _run(net, shapes, "float32", "matmul_bias_act=1", monkeypatch,
               is_train=False)
    _assert_parity(ref, got, "float32", "matmul_bias_act/infer")


# ------------------------------------------------------------ norm_residual
def _ln_net(dim=32, seq=8):
    """The transformer zoo's LayerNorm composition, standalone."""
    sym = mx.sym
    x = sym.Variable("data")
    mean = sym.mean(x, axis=-1, keepdims=True)
    cent = sym.broadcast_sub(x, mean, name="cent")
    var = sym.mean(sym.square(cent), axis=-1, keepdims=True)
    inv = sym.rsqrt(var + 1e-5)
    normed = sym.broadcast_mul(cent, inv)
    gamma = sym.Variable("ln_gamma", shape=(dim,))
    beta = sym.Variable("ln_beta", shape=(dim,))
    out = sym.broadcast_add(sym.broadcast_mul(normed, gamma), beta,
                            name="ln")
    fc = sym.FullyConnected(out, num_hidden=4, flatten=True, name="head")
    return sym.SoftmaxOutput(fc, name="softmax")


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_norm_residual_parity(monkeypatch, dtype):
    net = _ln_net()
    shapes = {"data": (4, 8, 32), "softmax_label": (4,)}
    ref = _run(net, shapes, dtype, "0", monkeypatch)
    got = _run(net, shapes, dtype, "norm_residual=1", monkeypatch)
    _assert_parity(ref, got, dtype, "norm_residual")


def test_norm_residual_inference_parity(monkeypatch):
    net = _ln_net()
    shapes = {"data": (4, 8, 32), "softmax_label": (4,)}
    ref = _run(net, shapes, "float32", "0", monkeypatch, is_train=False)
    got = _run(net, shapes, "float32", "norm_residual=1", monkeypatch,
               is_train=False)
    _assert_parity(ref, got, "float32", "norm_residual/infer")


# ---------------------------------------------------------------- attention
def _att_net(seq=64, dim=32, heads=2):
    sym = mx.sym
    x = sym.Variable("data")  # (B, H, T, D) head-major, as the op takes
    att = sym.MultiHeadAttention(query=x, key=x, value=x, causal=True,
                                 name="att")
    fc = sym.FullyConnected(sym.Flatten(att), num_hidden=4, name="head")
    return sym.SoftmaxOutput(fc, name="softmax")


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_attention_block_causal_parity(monkeypatch, dtype):
    net = _att_net()
    shapes = {"data": (2, 2, 64, 16), "softmax_label": (2,)}
    ref = _run(net, shapes, dtype, "0", monkeypatch)
    got = _run(net, shapes, dtype, "attention=1", monkeypatch)
    _assert_parity(ref, got, dtype, "attention/block_causal")


# ------------------------------------------ decode / cross-attention shapes
def _cross_att_net(causal=False):
    """Rectangular attention: T_q != T_kv (the serving decode / encoder-
    decoder cross-attention shape)."""
    sym = mx.sym
    q = sym.Variable("q")
    kv = sym.Variable("kv")
    att = sym.MultiHeadAttention(query=q, key=kv, value=kv, causal=causal,
                                 name="xatt")
    fc = sym.FullyConnected(sym.Flatten(att), num_hidden=4, name="head")
    return sym.SoftmaxOutput(fc, name="softmax")


_CROSS_SHAPES = {"q": (2, 2, 8, 16), "kv": (2, 2, 64, 16),
                 "softmax_label": (2,)}


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("lowering", ["chunked_kv", "pallas_flash"])
def test_attention_rectangular_fwd_parity(monkeypatch, causal, lowering):
    """Satellite: the matcher covers decode/cross-attention shapes
    (T_q != T_kv, causal bottom-right or no mask) — every candidate
    lowering is fwd-parity with the dense op."""
    net = _cross_att_net(causal=causal)
    ref = _run(net, _CROSS_SHAPES, "float32", "0", monkeypatch,
               is_train=False)
    got = _run(net, _CROSS_SHAPES, "float32", "attention=%s" % lowering,
               monkeypatch, is_train=False)
    _assert_parity(ref, got, "float32",
                   "attention/%s causal=%s" % (lowering, causal), tol=1e-5)


def test_attention_rectangular_train_parity(monkeypatch):
    """chunked_kv is plain traced XLA (scan) — fwd AND bwd parity on the
    cross-attention shape."""
    net = _cross_att_net(causal=True)
    ref = _run(net, _CROSS_SHAPES, "float32", "0", monkeypatch)
    got = _run(net, _CROSS_SHAPES, "float32", "attention=chunked_kv",
               monkeypatch)
    _assert_parity(ref, got, "float32", "attention/chunked_kv train",
                   tol=1e-5)


# -------------------------------------------- flash-attention training path
def _run_tf(net, shapes, env, monkeypatch, seed=5):
    """Token-data runner for the transformer zoo model."""
    monkeypatch.setenv("MXNET_FUSED_PATTERNS", env)
    monkeypatch.delenv("MXNET_FUSION_TUNE_DIR", raising=False)
    rs = np.random.RandomState(seed)
    ex = net.simple_bind(mx.cpu(), grad_req="write", **shapes)
    for name, arr in zip(net.list_arguments(), ex.arg_arrays):
        if name in ("data", "softmax_label"):
            arr[:] = rs.randint(1, 50, arr.shape).astype("f")
        else:
            arr[:] = rs.uniform(-0.5, 0.5, arr.shape).astype("f")
    outs = ex.forward(is_train=True)
    host = [o.asnumpy() for o in outs]
    ex.backward()
    grads = {n: (g.asnumpy() if g is not None else None)
             for n, g in ex.grad_dict.items()}
    return host, grads


def test_attention_flash_training_parity_transformer(monkeypatch):
    """Acceptance (ISSUE 15 tentpole): training fwd+bwd through the flash
    attention path (custom_vjp online-softmax recompute backward,
    interpret mode on CPU) on the transformer zoo model — gradient parity
    vs the unfused composition at f32 atol 1e-5."""
    from mxnet_tpu import models

    net = models.get_symbol("transformer", vocab_size=50, model_dim=32,
                            num_heads=2, num_layers=1, seq_len=8)
    shapes = {"data": (2, 8), "softmax_label": (2, 8)}
    ref = _run_tf(net, shapes, "0", monkeypatch)
    got = _run_tf(net, shapes, "attention=pallas_flash", monkeypatch)
    _assert_parity(ref, got, "float32", "attention/flash-train", tol=1e-5)


def test_memory_plan_elides_flash_attention_scores(monkeypatch):
    """Acceptance (ISSUE 15): with the flash training path statically
    engaged, the (B, H, T, S) score tensor is ABSENT from the memory
    plan's stash accounting and the GL5xx predicted peak drops on the
    attention site."""
    from mxnet_tpu import analysis, models

    net = models.get_symbol("transformer", vocab_size=50, model_dim=64,
                            num_heads=2, num_layers=2, seq_len=64)
    shapes = {"data": (2, 64), "softmax_label": (2, 64)}
    monkeypatch.setenv("MXNET_FUSED_PATTERNS", "auto")
    monkeypatch.delenv("MXNET_FUSION_TUNE_DIR", raising=False)
    dense = analysis.lint(net, shapes=shapes, train=True).memory_plan
    assert dense["attention"]["sites"] == 2
    # f32 (B, H, T, S) per site: 2*2*64*64*4 bytes
    assert dense["attention"]["score_bytes"] == 2 * (2 * 2 * 64 * 64 * 4)
    assert dense["attention"]["flash_elided_sites"] == 0

    monkeypatch.setenv("MXNET_FUSED_PATTERNS", "attention=pallas_flash")
    flash = analysis.lint(net, shapes=shapes, train=True).memory_plan
    assert flash["attention"]["flash_elided_sites"] == 2
    assert flash["attention"]["score_bytes"] == 0
    assert (flash["per_device"]["peak"] < dense["per_device"]["peak"])


# ----------------------------------------------------------- elemwise_chain
def test_elemwise_chain_parity(monkeypatch):
    sym = mx.sym
    x = sym.Variable("data")
    h = sym.exp(x * 0.1)
    h = sym.tanh(h)
    h = sym.Activation(h, act_type="sigmoid", name="sig")
    fc = sym.FullyConnected(h, num_hidden=4, name="head")
    net = sym.SoftmaxOutput(fc, name="softmax")
    shapes = {"data": (4, 16), "softmax_label": (4,)}
    ref = _run(net, shapes, "float32", "0", monkeypatch)
    got = _run(net, shapes, "float32", "elemwise_chain=1", monkeypatch)
    _assert_parity(ref, got, "float32", "elemwise_chain")


# ------------------------------------------------------- auto-mode fallback
def test_auto_mode_without_cache_is_bit_identical(monkeypatch):
    """auto mode with no tune cache: every gate declines (no measured
    verdict) and the step must be BIT-identical to the engine being off."""
    net = _mba_net()
    shapes = {"data": (8, 32), "softmax_label": (8,)}
    ref = _run(net, shapes, "float32", "0", monkeypatch)
    got = _run(net, shapes, "float32", "auto", monkeypatch)
    for a, b in zip(ref[0], got[0]):
        assert np.array_equal(a, b)
    for k in ref[1]:
        if ref[1][k] is not None:
            assert np.array_equal(ref[1][k], got[1][k]), k


# ------------------------------------------------------------ plan coverage
def test_plan_roots_transformer_patterns(monkeypatch):
    """The transformer zoo graph roots attention and matmul_bias_act as
    written; its LayerNorm sites are deliberately the NAIVE frontend
    composition (recomputed mean/center, self-multiply square — see
    models/transformer.py), so norm_residual cannot root until the
    bind-time rewrite pipeline (MXNET_GRAPHREWRITE) canonicalizes the
    graph — and then roots every LN site."""
    monkeypatch.setenv("MXNET_FUSED_PATTERNS", "auto")
    from mxnet_tpu import analysis, models

    net = models.get_symbol("transformer", vocab_size=50, model_dim=32,
                            num_heads=2, num_layers=1, seq_len=8)
    sites = analysis.pattern_site_counts(net)
    assert sites.get("attention") == 1
    assert sites.get("matmul_bias_act", 0) >= 1
    assert sites.get("norm_residual", 0) == 0  # sloppy frontend spelling
    rewritten = analysis.rewrite(net).symbol
    assert analysis.pattern_site_counts(rewritten) \
        .get("norm_residual") == 3  # ln1, ln2, final_ln


def test_patterns_off_plan_has_no_pattern_directives(monkeypatch):
    monkeypatch.setenv("MXNET_FUSED_PATTERNS", "0")
    net = _mba_net()
    topo = net._topo()
    plan = fusion.plan(topo, output_ids={id(n) for n, _ in net._outputs})
    assert not any(d["kind"] in ("pattern", "lazy") for d in plan.values())


def test_infer_env_override_plans_pattern(monkeypatch):
    """MXNET_FUSED_PATTERNS_INFER can enable a pattern the training map
    disabled — the plan is the union, the per-execution gate filters."""
    monkeypatch.setenv("MXNET_FUSED_PATTERNS", "0")
    monkeypatch.setenv("MXNET_FUSED_PATTERNS_INFER", "matmul_bias_act")
    net = _mba_net()
    topo = net._topo()
    plan = fusion.plan(topo, output_ids={id(n) for n, _ in net._outputs})
    assert any(d["kind"] == "pattern" for d in plan.values())
    # and the training-mode gate still reports the pattern disabled
    assert fusion.enabled_patterns()["matmul_bias_act"] == "0"
    assert fusion.enabled_patterns(infer=True)["matmul_bias_act"] == "auto"


# ----------------------------------------------------------------- GL303
def test_gl303_reports_pattern_sites_and_near_misses(monkeypatch):
    monkeypatch.setenv("MXNET_FUSED_PATTERNS", "auto")
    from mxnet_tpu.analysis import lint

    sym = mx.sym
    x = sym.Variable("data")
    # near-miss: FullyConnected consumed twice -> not rooted
    fc = sym.FullyConnected(x, num_hidden=8, name="fc_shared")
    a = sym.Activation(fc, act_type="relu", name="relu_a")
    out = a + fc
    net = sym.SoftmaxOutput(
        sym.FullyConnected(sym.Flatten(out), num_hidden=4, name="head"),
        name="softmax")
    rep = lint(net, shapes={"data": (4, 16)}, passes=["fusion_explain"])
    gl303 = [d for d in rep if d.code == "GL303"]
    assert any("consumers" in d.message for d in gl303), \
        [d.message for d in gl303]

    # and a graph where the pattern cleanly roots reports NO GL303 noise
    net2 = _mba_net()
    rep2 = lint(net2, shapes={"data": (8, 32)}, passes=["fusion_explain"])
    assert not [d for d in rep2 if d.code == "GL303"]


def test_memory_plan_reports_fusion_interiors(monkeypatch):
    monkeypatch.setenv("MXNET_FUSED_PATTERNS", "auto")
    from mxnet_tpu.analysis import lint

    net = _mba_net()
    rep = lint(net, shapes={"data": (8, 32)},
               passes=["shape_lint", "memory_plan"])
    plan = rep.memory_plan
    assert plan is not None and "fusion" in plan
    assert plan["fusion"]["pattern_sites"].get("matmul_bias_act") == 1
    assert plan["fusion"]["interior_bytes"] > 0
