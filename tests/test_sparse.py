"""Row-sparse gradients + lazy updates: the recommender subsystem
(docs/SPARSE.md).

Single-process coverage of what the 2-process smoke
(tests/nightly/dist_sparse_kvstore.py) exercises end to end: the
``row_sparse`` storage kind and its conversions, the Embedding segment-sum
backward, the lazy-update contract (untouched rows keep bit-identical
weight AND optimizer state — including through a dense-wire fallback
round), the KVStore sparse round on a local store, the
``row_sparse_embedding`` shard-rule category + GL405 table hint, and the
autoplan acceptance gate: a budget-armed 8-device search shards the
recommender's embedding tables over the model axis.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sparse
from mxnet_tpu.base import MXNetError
from mxnet_tpu.sparse import (RowSparseNDArray, RowSparseState,
                              embedding_backward, from_dense,
                              row_sparse_array, sparse_param_names)

V, D = 20, 4


def _rsp(rs, rows, scale=1.0):
    rows = np.asarray(sorted(set(rows)), np.int64)
    vals = (rs.rand(rows.size, D).astype("float32") - 0.5) * scale
    return row_sparse_array((vals, rows), (V, D)), rows, vals


# ------------------------------------------------------------ storage kind
def test_roundtrip_to_dense_from_dense():
    rs = np.random.RandomState(0)
    r, rows, vals = _rsp(rs, [3, 7, 11])
    dense = r.to_dense()
    assert dense.shape == (V, D)
    np.testing.assert_array_equal(dense.asnumpy()[rows], vals)
    back = from_dense(dense)
    np.testing.assert_array_equal(back.indices.asnumpy(), rows)
    np.testing.assert_array_equal(back.values.asnumpy(), vals)


def test_from_dense_with_row_hint_skips_scan():
    """With the batch's ids supplied, rows outside the hint are dropped
    even if dense happens to hold junk there — the O(nnz) boundary path."""
    rs = np.random.RandomState(1)
    dense = mx.nd.array(rs.rand(V, D).astype("float32"))
    r = from_dense(dense, rows=[5, 2, 5])
    assert r.indices.asnumpy().tolist() == [2, 5]
    np.testing.assert_array_equal(r.values.asnumpy(),
                                  dense.asnumpy()[[2, 5]])


def test_retain():
    rs = np.random.RandomState(2)
    r, rows, vals = _rsp(rs, [1, 4, 9, 15])
    kept = r.retain([4, 15, 19])
    assert kept.indices.asnumpy().tolist() == [4, 15]
    np.testing.assert_array_equal(kept.values.asnumpy(), vals[[1, 3]])


def test_add_merges_index_union():
    rs = np.random.RandomState(3)
    a, arows, avals = _rsp(rs, [2, 6])
    b, brows, bvals = _rsp(rs, [6, 13])
    c = a + b
    assert c.indices.asnumpy().tolist() == [2, 6, 13]
    np.testing.assert_allclose(c.to_dense().asnumpy(),
                               a.to_dense().asnumpy()
                               + b.to_dense().asnumpy(), atol=1e-6)


def test_invalid_indices_rejected():
    with pytest.raises(MXNetError):
        RowSparseNDArray([3, 1], np.zeros((2, D), "f"), (V, D))  # unsorted
    with pytest.raises(MXNetError):
        RowSparseNDArray([1, V], np.zeros((2, D), "f"), (V, D))  # range
    with pytest.raises(MXNetError):
        RowSparseNDArray([1], np.zeros((2, D), "f"), (V, D))  # shape


def test_zero_nnz_valid():
    r = row_sparse_array((np.zeros((0, D), "f"), np.zeros((0,), np.int64)),
                         (V, D))
    assert r.nnz == 0 and r.size == 0
    assert not np.any(r.to_dense().asnumpy())


# --------------------------------------------------- segment-sum backward
def test_embedding_backward_matches_dense_reference():
    rs = np.random.RandomState(4)
    ids = rs.randint(0, V, (3, 5))  # repeated ids must accumulate
    og = rs.rand(3, 5, D).astype("float32")
    g = embedding_backward(ids, mx.nd.array(og), V)
    ref = np.zeros((V, D), "float32")
    for i, o in zip(ids.reshape(-1), og.reshape(-1, D)):
        ref[i] += o
    assert g.nnz == np.unique(ids).size
    np.testing.assert_allclose(g.to_dense().asnumpy(), ref, atol=1e-5)


def test_embedding_backward_matches_executor_grad():
    """The segment-sum backward must equal the dense autodiff gradient the
    executor computes for the same lookup."""
    rs = np.random.RandomState(5)
    data = mx.sym.Variable("data")
    net = mx.sym.LinearRegressionOutput(
        mx.sym.SparseEmbedding(data=data, input_dim=V, output_dim=D,
                               name="emb"),
        label=mx.sym.Variable("label"), name="out")
    ex = net.simple_bind(mx.cpu(), data=(6,), label=(6, D))
    ids = rs.randint(0, V, (6,))
    ex.arg_dict["data"][:] = ids.astype("float32")
    ex.arg_dict["emb_weight"][:] = rs.rand(V, D).astype("float32")
    ex.arg_dict["label"][:] = rs.rand(6, D).astype("float32")
    out = ex.forward(is_train=True)[0].asnumpy()
    ex.backward()
    dense_grad = ex.grad_dict["emb_weight"].asnumpy()
    # the output-op backward is (out - label) / D
    og = (out - ex.arg_dict["label"].asnumpy()) / D
    g = embedding_backward(ids, mx.nd.array(og), V)
    np.testing.assert_allclose(g.to_dense().asnumpy(), dense_grad, atol=1e-5)


def test_sparse_embedding_forward_matches_embedding():
    rs = np.random.RandomState(6)
    w = rs.rand(V, D).astype("float32")
    ids = rs.randint(0, V, (7,)).astype("float32")
    a = mx.nd.Embedding(mx.nd.array(ids), mx.nd.array(w),
                        input_dim=V, output_dim=D)
    b = mx.nd.SparseEmbedding(mx.nd.array(ids), mx.nd.array(w),
                              input_dim=V, output_dim=D)
    np.testing.assert_array_equal(a.asnumpy(), b.asnumpy())


# ------------------------------------------------------- lazy-update contract
def _fit_rounds(opt, rounds, fallback_pct=None):
    """Run sparse push rounds through a local kvstore; returns (w0, kv)."""
    env = {}
    if fallback_pct is not None:
        env["MXNET_SPARSE_DENSE_FALLBACK_PCT"] = str(fallback_pct)
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        rs = np.random.RandomState(7)
        kv = mx.kv.create("local")
        kv.set_optimizer(opt)
        w0 = rs.rand(V, D).astype("float32")
        kv.init("emb", mx.nd.array(w0))
        for rows in rounds:
            r, _, _ = _rsp(rs, rows)
            kv.push("emb", r)
        return w0, kv
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def test_lazy_sgd_momentum_parity_with_dense_on_touched_rows():
    """Touched rows must match the dense momentum-SGD math exactly; rows
    outside the round's set keep bit-identical weight."""
    rs = np.random.RandomState(8)
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=1e-3)
    kv = mx.kv.create("local")
    kv.set_optimizer(opt)
    w0 = rs.rand(V, D).astype("float32")
    kv.init("emb", mx.nd.array(w0))
    r, rows, vals = _rsp(rs, [0, 5, 19])
    kv.push("emb", r)
    out = mx.nd.zeros((V, D))
    kv.pull("emb", out=out)
    w1 = out.asnumpy()
    # dense reference on the touched rows
    mom = 0.9 * 0 - 0.1 * (vals + 1e-3 * w0[rows])
    np.testing.assert_allclose(w1[rows], w0[rows] + mom, atol=1e-6)
    unt = np.setdiff1d(np.arange(V), rows)
    np.testing.assert_array_equal(w1[unt], w0[unt])


def test_lazy_adam_untouched_state_bit_identical_to_seed():
    """THE regression the lazy contract exists for: after rounds touching
    different row sets, a row never touched must have optimizer state
    bit-identical to seed — for the row-sparse state that means NO stored
    row at all (a dense fallback would have decayed Adam's mean/var with
    phantom zero-gradient steps)."""
    opt = mx.optimizer.Adam(learning_rate=0.01)
    _, kv = _fit_rounds(opt, [[1, 3], [3, 8], [1, 15]])
    st = kv._updater.states["emb"]
    assert isinstance(st, RowSparseState)
    touched = {1, 3, 8, 15}
    assert set(st.indices.tolist()) == touched
    # update counts still tick per key per round (lr schedules match dense)
    assert opt._index_update_count["emb"] == 3


def test_dense_wire_fallback_preserves_lazy_state():
    """Force every round through the dense-wire fallback
    (MXNET_SPARSE_DENSE_FALLBACK_PCT at its floor): the WIRE strategy
    changes, the update must stay row-lazy — untouched rows still have no
    state row."""
    opt = mx.optimizer.Adam(learning_rate=0.01)
    _, kv = _fit_rounds(opt, [[2, 9], [9, 12]], fallback_pct=1e-6)
    st = kv._updater.states["emb"]
    assert isinstance(st, RowSparseState)
    assert set(st.indices.tolist()) == {2, 9, 12}


def test_sparse_vs_dense_fallback_same_weights():
    """Wire strategy must not change the math: identical rounds through the
    sparse wire and the forced dense fallback give identical weights."""
    w_a, kv_a = _fit_rounds(mx.optimizer.Adam(learning_rate=0.01),
                            [[1, 4], [4, 11]], fallback_pct=100.0)
    w_b, kv_b = _fit_rounds(mx.optimizer.Adam(learning_rate=0.01),
                            [[1, 4], [4, 11]], fallback_pct=1e-6)
    a = mx.nd.zeros((V, D))
    kv_a.pull("emb", out=a)
    b = mx.nd.zeros((V, D))
    kv_b.pull("emb", out=b)
    np.testing.assert_array_equal(a.asnumpy(), b.asnumpy())


def test_push_without_updater_replaces_touched_rows_only():
    rs = np.random.RandomState(9)
    kv = mx.kv.create("local")
    w0 = rs.rand(V, D).astype("float32")
    kv.init("emb", mx.nd.array(w0))
    r, rows, vals = _rsp(rs, [6, 17])
    kv.push("emb", r)
    out = mx.nd.zeros((V, D))
    kv.pull("emb", out=out)
    got = out.asnumpy()
    np.testing.assert_array_equal(got[rows], vals)
    unt = np.setdiff1d(np.arange(V), rows)
    np.testing.assert_array_equal(got[unt], w0[unt])


def test_row_sparse_pull():
    rs = np.random.RandomState(10)
    kv = mx.kv.create("local")
    w0 = rs.rand(V, D).astype("float32")
    kv.init("emb", mx.nd.array(w0))
    r = kv.row_sparse_pull("emb", [7, 2, 7])
    assert r.indices.asnumpy().tolist() == [2, 7]
    np.testing.assert_array_equal(r.values.asnumpy(), w0[[2, 7]])


def test_optimizer_without_flat_spec_densifies_with_warning():
    """Optimizers with no flat lowering stay correct (dense math), just not
    lazy — and say so once."""
    rs = np.random.RandomState(11)
    kv = mx.kv.create("local")
    kv.set_optimizer(mx.optimizer.RMSProp(learning_rate=0.01))
    w0 = rs.rand(V, D).astype("float32")
    kv.init("emb", mx.nd.array(w0))
    r, rows, _ = _rsp(rs, [3])
    kv.push("emb", r)
    out = mx.nd.zeros((V, D))
    kv.pull("emb", out=out)
    assert not np.allclose(out.asnumpy()[rows], w0[rows])
    assert not isinstance(kv._updater.states["emb"], RowSparseState)


def test_flat_kernels_shared_with_bucket_engine():
    """One expression tree for sharded, replicated and lazy-sparse: the
    bucket engine's kernel table IS the optimizer module's."""
    from mxnet_tpu import kvstore_bucket, optimizer

    assert kvstore_bucket._FLAT_KERNELS is optimizer.FLAT_KERNELS


# ------------------------------------------------- shard rules / lint / plan
def test_shard_rule_category_registered():
    from mxnet_tpu.ops.infer_meta import (EMBEDDING_RULES, SHARD_RULES,
                                          get_meta)

    assert "row_sparse_embedding" in SHARD_RULES
    assert get_meta("SparseEmbedding").shard_rule == "row_sparse_embedding"
    assert get_meta("SparseEmbedding").param_slots == ("weight",)
    assert set(EMBEDDING_RULES) == {"embedding", "row_sparse_embedding"}


def test_sparse_param_names():
    net = mx.models.get_symbol("recommender")
    assert sorted(sparse_param_names(net)) == ["item_embed_weight",
                                               "user_embed_weight"]
    # the Embedding sparse_grad=True spelling is recognized too
    d = mx.sym.Variable("data")
    e = mx.sym.Embedding(data=d, input_dim=V, output_dim=D,
                         sparse_grad=True, name="emb")
    assert sparse_param_names(e) == ["emb_weight"]
    e2 = mx.sym.Embedding(data=d, input_dim=V, output_dim=D, name="emb2")
    assert sparse_param_names(e2) == []


def test_gl405_hint_names_embedding_table_pspec():
    """Satellite: the GL405 fix hint for a replicated embedding table must
    name the table's param_pspec placement, not the generic rank-2 advice."""
    from jax.sharding import PartitionSpec as P

    from mxnet_tpu import analysis
    from mxnet_tpu.parallel import ShardingRules, parse_mesh_spec

    mesh = parse_mesh_spec("dp=2,model=2")
    rules = ShardingRules.infer_axes(mesh,
                                     param_rule=lambda name, shape: P())
    ids = mx.sym.Variable("ids")
    net = mx.sym.SparseEmbedding(data=ids, input_dim=4096, output_dim=64,
                                 name="table")
    report = analysis.lint(net, shapes={"ids": (8,)}, types={"ids": "int32"},
                           mesh=mesh, rules=rules)
    gl405 = [d for d in report.diagnostics if d.code == "GL405"]
    assert gl405, report.codes()
    hint = gl405[0].fix_hint
    assert "embedding table" in hint and "param_pspec" in hint
    assert "table_weight" in hint and "row-sparse" in hint


def test_autoplan_recommender_shards_embedding_over_model_axis():
    """Acceptance gate: at 8 devices with the realistic constraint that
    replicated tables blow the HBM budget, the planner's per-param search
    lands a model-axis-sharded embedding spec and beats naive all-dp on
    predicted comm."""
    from mxnet_tpu.parallel import autoplan

    net = mx.models.get_symbol("recommender")
    shapes = {"user": (64,), "item": (64,), "dense": (64, 16),
              "label": (64,)}
    plan = autoplan.plan_parallel(net, shapes,
                                  types={"user": "int32", "item": "int32"},
                                  devices=8, budget_gb=0.0625,
                                  label="recommender")
    assert plan.feasible
    assert plan.mesh.get("model", 1) > 1
    sharded_tables = [n for n in ("user_embed_weight", "item_embed_weight")
                      if any(plan.param_specs.get(n, []))]
    assert sharded_tables, plan.param_specs
    assert plan.predicted["comm_bytes"] < plan.naive["comm_bytes"]


def test_module_fit_routes_sparse_grad_params(monkeypatch):
    """The Module glue resolves sparse-grad params (sparse_param_names) and
    routes their pushes through the KVStore sparse round: after a fit, the
    embedding key's optimizer state is row-sparse and the sparse counters
    ticked — no hand-rolled from_dense at the call site."""
    from mxnet_tpu import telemetry

    monkeypatch.setenv("MXNET_TELEMETRY", "counters")
    rs = np.random.RandomState(12)
    data = mx.sym.Variable("data")
    emb = mx.sym.SparseEmbedding(data=data, input_dim=64, output_dim=8,
                                 name="emb")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(emb, num_hidden=4, name="fc"), name="softmax")
    it = mx.io.NDArrayIter(
        rs.randint(0, 64, (24,)).astype("float32"),
        rs.randint(0, 4, (24,)).astype("float32"), batch_size=8)
    kv = mx.kv.create("local")
    pre = telemetry.counter("kvstore.sparse_rows_pushed").value
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=2, kvstore=kv, optimizer="sgd",
            optimizer_params=(("learning_rate", 0.05),))
    idx = next(i for i, n in enumerate(mod._param_names)
               if n == "emb_weight")
    assert isinstance(kv._updater.states.get(idx), RowSparseState)
    assert telemetry.counter("kvstore.sparse_rows_pushed").value > pre


def test_updater_dense_grad_on_sparse_state_stays_lazy():
    """A key that trained row-sparse then receives a DENSE gradient (e.g. a
    sparse-resumed table fed by a dense producer) must keep the lazy
    contract — its nonzero rows are its touched set — not crash the dense
    update on the foreign state type."""
    rs = np.random.RandomState(13)
    kv = mx.kv.create("local")
    kv.set_optimizer(mx.optimizer.Adam(learning_rate=0.01))
    w0 = rs.rand(V, D).astype("float32")
    kv.init("emb", mx.nd.array(w0))
    r, rows, _ = _rsp(rs, [2, 7])
    kv.push("emb", r)
    dense = np.zeros((V, D), "float32")
    dense[[7, 11]] = rs.rand(2, D).astype("float32")
    kv.push("emb", mx.nd.array(dense))  # dense grad, sparse state
    st = kv._updater.states["emb"]
    assert isinstance(st, RowSparseState)
    assert set(st.indices.tolist()) == {2, 7, 11}
    out = mx.nd.zeros((V, D))
    kv.pull("emb", out=out)
    unt = np.setdiff1d(np.arange(V), [2, 7, 11])
    np.testing.assert_array_equal(out.asnumpy()[unt], w0[unt])


def test_recommender_in_zoo_and_lints_clean():
    from mxnet_tpu import analysis
    from mxnet_tpu.analysis.cli import DEFAULT_SHAPES, DEFAULT_TYPES

    assert "recommender" in DEFAULT_SHAPES and "dlrm" in DEFAULT_SHAPES
    net = mx.models.get_symbol("dlrm")
    report = analysis.lint(net, shapes=DEFAULT_SHAPES["recommender"],
                           types=DEFAULT_TYPES["recommender"])
    errors = [d for d in report.diagnostics if d.severity == "error"]
    assert not errors, [d.format() for d in errors]
