"""Schedule-search autotuning (docs/PERF.md §15): the v2 cache schema with
both-direction version handling (v1 binary verdicts load and serve with
zero re-tunes; unknown future versions are cleanly invalidated with one
warning — never a crash, never a silent stale winner), schedule-annotated
records, the bounded per-kernel schedule spaces, and the measured-stripe
override threading into the conv kernel."""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import fusion, fusion_tune, telemetry


@pytest.fixture(autouse=True)
def _fresh(monkeypatch, tmp_path):
    saved = telemetry.current_override()
    monkeypatch.setenv("MXNET_FUSION_TUNE_DIR", str(tmp_path))
    monkeypatch.setenv("MXNET_FUSION_TUNE_ITERS", "2")
    monkeypatch.setenv("MXNET_TELEMETRY", "counters")
    telemetry.set_mode("counters")
    fusion_tune.reset()
    telemetry.reset()
    yield
    fusion_tune.reset()
    telemetry.reset()
    telemetry.set_mode(saved)


def _write_cache(version, entries):
    path = fusion_tune.cache_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump({"version": version,
                   "device_kind": fusion_tune.device_kind(),
                   "digest": fusion_tune.entries_digest(entries),
                   "entries": entries}, f)
    return path


# ------------------------------------------------------- schema both ways
def test_v1_binary_verdict_cache_loads_with_zero_retunes(caplog):
    """Direction 1: a PR 9 (version-1) cache file LOADS under the v2
    schema — its records serve as default-schedule verdicts, the warm run
    never re-tunes, and nothing crashes or warns."""
    rec = {"engage": False, "engage_fwd": False, "lowering": None,
           "base_fwd_us": 10.0, "base_bwd_us": 20.0, "measured": {}}
    _write_cache(1, {"k1": rec})
    with caplog.at_level("WARNING", logger="mxnet_tpu"):
        got = fusion_tune.peek("k1")
    assert got == rec
    assert not any("ignoring cache file" in r.message
                   for r in caplog.records)

    def boom():
        raise AssertionError("a loaded v1 verdict must never re-tune")

    assert fusion_tune.verdict("k1", boom) == rec
    assert telemetry.counter("fusion.tune").value == 0
    # a v1 record is never misread as a searched winner
    assert "schedule" not in got


def test_future_version_cache_invalidated_with_one_warning(caplog):
    """Direction 2: an UNKNOWN (future) schema version is cleanly
    invalidated — one warning, no crash, and the next tune rewrites the
    file at the current version."""
    _write_cache(99, {"k2": {"engage": True, "lowering": "pallas"}})
    with caplog.at_level("WARNING", logger="mxnet_tpu"):
        assert fusion_tune.peek("k2") is None
        assert fusion_tune.peek("k2") is None  # warned ONCE, not per read
    warns = [r for r in caplog.records
             if "unknown schema version" in r.message]
    assert len(warns) == 1
    # the miss re-tunes and persists at the CURRENT version
    rec = fusion_tune.verdict("k2", lambda: {"engage": False,
                                             "lowering": None})
    assert rec["engage"] is False
    payload = json.load(open(fusion_tune.cache_path()))
    assert payload["version"] == 2


def test_v1_record_never_a_silent_stale_winner():
    """A v1 engaged record whose lowering no longer exists at the site
    falls back with a reason, not a crash or a phantom engage."""
    from mxnet_tpu.ops.fusion_patterns import MatmulBiasAct

    pat = MatmulBiasAct()
    meta = {"act": "relu", "flatten": True, "no_bias": False}
    rs = np.random.RandomState(0)
    import jax.numpy as jnp

    args = (jnp.asarray(rs.randn(8, 32).astype("f")),
            jnp.asarray(rs.randn(128, 32).astype("f")),
            jnp.asarray(rs.randn(128).astype("f")))
    key = fusion._tune_key(pat, meta, args)
    _write_cache(1, {key: {"engage": True, "lowering": "gone-lowering"}})
    engaged, chosen, reason = fusion.gate_pattern_explain(pat, meta, args)
    assert engaged is False
    assert "unavailable" in reason


# ------------------------------------------------------ schedule records
def test_verdict_annotates_schedule_and_search_width():
    rec = fusion_tune.verdict("s1", lambda: {
        "engage": True, "lowering": "pallas@bm=256,bn=128",
        "measured": {"pallas": {"fwd_us": 9.0},
                     "pallas@bm=256,bn=128": {"fwd_us": 5.0}}})
    assert rec["schedule"] == {"bm": 256, "bn": 128}
    assert rec["schedules_searched"] == 1


def test_default_winner_schedule_is_default():
    rec = fusion_tune.verdict("s2", lambda: {
        "engage": True, "lowering": "pallas",
        "measured": {"pallas": {"fwd_us": 5.0}}})
    assert rec["schedule"] == "default"
    assert rec["schedules_searched"] == 0


def test_sched_name_parse_roundtrip():
    name = fusion_tune.sched_name("block_causal", bq=64)
    assert name == "block_causal@bq=64"
    assert fusion_tune.parse_schedule(name) == {"bq": 64}
    assert fusion_tune.parse_schedule("pallas") == "default"
    assert fusion_tune.parse_schedule(None) is None


def test_schedule_budget_knob(monkeypatch):
    monkeypatch.setenv("MXNET_FUSION_TUNE_SCHEDULES", "0")
    assert fusion_tune.schedule_budget() == 0
    monkeypatch.setenv("MXNET_FUSION_TUNE_SCHEDULES", "7")
    assert fusion_tune.schedule_budget() == 7
    monkeypatch.setenv("MXNET_FUSION_TUNE_SCHEDULES", "junk")
    assert fusion_tune.schedule_budget() == 4
    monkeypatch.delenv("MXNET_FUSION_TUNE_SCHEDULES")
    assert fusion_tune.schedule_budget() == 4


def test_losers_note_quotes_runners_up():
    rec = {"measured": {
        "pallas": {"fwd_us": 5.0, "bwd_us": 5.0},
        "pallas@bm=256,bn=128": {"fwd_us": 20.0, "bwd_us": 20.0},
        "pallas@bm=128,bn=256": {"fwd_us": 12.0, "bwd_us": 10.0}}}
    note = fusion.losers_note(rec, "pallas")
    assert "beat" in note
    # fastest loser first
    assert note.index("bm=128") < note.index("bm=256")


# --------------------------------------------------- bounded spaces per kernel
def test_matmul_block_candidates_bounded_and_supported():
    from mxnet_tpu.ops import pallas_matmul_bias_act as pk

    cands = pk.block_candidates(1024, 128, 2048, "relu", itemsize=4)
    assert cands and cands[0] == (512, 256)  # planner default first
    assert len(cands) == len(set(cands))
    for bm, bn in cands:
        assert pk.supported(1024, 128, 2048, "relu", bm, bn, itemsize=4)


def test_attention_block_schedules_distinct_effective():
    from mxnet_tpu.ops import pallas_attention as pa

    q = (2, 4, 512, 32)
    scheds = pa.block_schedules(q, q, causal=True)
    assert scheds and scheds[0] == (128, 128)
    assert len(scheds) == len(set(scheds))
    # a tiny T collapses every block_q to T: exactly one effective tiling
    # per distinct block_k survives
    small = pa.block_schedules((2, 2, 8, 16), (2, 2, 64, 16), causal=False)
    assert len({s for s in small}) == len(small)


def test_norm_residual_block_candidates():
    from mxnet_tpu.ops import pallas_norm_residual as pn

    cands = pn.block_candidates((4, 64, 128), itemsize=4)
    assert cands and cands[0] == max(cands)  # largest = planner default
    assert all(256 % br == 0 or 256 // br for br in cands)
    assert pn.block_candidates((4, 64, 100)) == []  # D not lane-aligned


def test_conv_bn_candidates_and_stripe_override_parity():
    """bn_candidates enumerates every tiling (default first) and the
    conv_block bn override computes the same numbers as the planner
    default — a schedule changes the grid, never the math."""
    import jax.numpy as jnp

    from mxnet_tpu.ops.pallas_conv_bn import bn_candidates, conv_block

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(2, 8, 8, 8).astype("f"))
    w = jnp.asarray(rs.randn(16, 8, 1, 1).astype("f") * 0.1)
    scale = jnp.asarray(rs.uniform(0.5, 1.5, (8,)).astype("f"))
    shift = jnp.asarray(rs.uniform(-0.2, 0.2, (8,)).astype("f"))
    cands = bn_candidates(2, 8, 16, 64, 4, taps=1, prologue=True)
    assert cands[0] == 16 and 8 in cands
    ref = conv_block(x, w, scale, shift, None, (1, 1), (1, 1), True, True,
                     "xla")
    got = conv_block(x, w, scale, shift, None, (1, 1), (1, 1), True, True,
                     "xla", 8)
    for a, b in zip(ref, got):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=1e-5)
    # an INVALID override silently demotes to the planner pick
    bad = conv_block(x, w, scale, shift, None, (1, 1), (1, 1), True, True,
                     "xla", 3)
    for a, b in zip(ref, bad):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_conv_schedule_reads_tuned_stripe():
    kernel, stride = (1, 1), (1, 1)
    x_shape, w_shape = (2, 8, 8, 8), (16, 8, 1, 1)
    key = fusion._conv_bn_key(kernel, stride, x_shape, w_shape,
                              np.float32, False)
    fusion_tune.verdict(key, lambda: {
        "engage": True, "lowering": "pallas:recompute@bn=8",
        "measured": {"pallas:recompute@bn=8": {"fwd_us": 1.0}}})
    assert fusion.conv_schedule(kernel, stride, x_shape, w_shape,
                                np.float32, False) == 8
    # and bwd_mode still parses the policy through the @-suffix
    import jax.numpy as jnp

    assert fusion.bwd_mode(kernel, stride, x_shape, w_shape, jnp.float32,
                           True) in ("recompute", "xla")


# -------------------------------------------------- cold-tune integration
def _mba_fit(monkeypatch, env_patterns="matmul_bias_act"):
    # (256, 32) @ (256, 32)ᵀ: large enough that the (bm, bn) fan-out has
    # >1 DISTINCT effective tiling (a tiny site collapses every variant
    # onto the clamped default and legitimately searches nothing)
    monkeypatch.setenv("MXNET_FUSED_PATTERNS", env_patterns)
    rs = np.random.RandomState(0)
    sym = mx.sym
    x = sym.Variable("data")
    h = sym.FullyConnected(x, num_hidden=256, name="fc1")
    h = sym.Activation(h, act_type="relu", name="act1")
    net = sym.SoftmaxOutput(
        sym.FullyConnected(h, num_hidden=4, name="fc2"), name="softmax")
    ex = net.simple_bind(mx.cpu(), data=(256, 32), softmax_label=(256,),
                         grad_req="write")
    for name, arr in zip(net.list_arguments(), ex.arg_arrays):
        arr[:] = (rs.randint(0, 4, arr.shape) if "label" in name
                  else rs.uniform(-0.5, 0.5, arr.shape)).astype("f")
    ex.forward(is_train=True)
    ex.backward()


def test_cold_tune_searches_and_persists_schedules(monkeypatch):
    """The CI schedule-cache contract: a cold tune under the default
    schedule budget measures ≥1 schedule variant and persists the
    annotated record; the warm read re-tunes zero times."""
    _mba_fit(monkeypatch)
    assert telemetry.counter("fusion.tune").value == 1
    payload = json.load(open(fusion_tune.cache_path()))
    assert payload["version"] == 2
    [rec] = list(payload["entries"].values())
    assert rec["schedules_searched"] >= 1
    assert any("@" in n for n in rec["measured"])
    fusion_tune.reset()
    telemetry.reset()
    _mba_fit(monkeypatch)
    assert telemetry.counter("fusion.tune").value == 0


def test_schedules_zero_restores_binary_verdicts(monkeypatch):
    """MXNET_FUSION_TUNE_SCHEDULES=0 is the PR 9 engine: only the
    planner-default candidate is measured."""
    monkeypatch.setenv("MXNET_FUSION_TUNE_SCHEDULES", "0")
    _mba_fit(monkeypatch)
    payload = json.load(open(fusion_tune.cache_path()))
    [rec] = list(payload["entries"].values())
    assert rec["schedules_searched"] == 0
    assert not any("@" in n for n in rec["measured"])
