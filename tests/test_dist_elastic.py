"""Elastic fault tolerance (mxnet_tpu/dist.py elastic layer,
docs/FAULT_TOLERANCE.md): num_dead_nodes edge cases (clock skew, grace
boundary, dir races, transition counter), heartbeat drain, membership-plan
validation (coordinator death / min-workers / self-eviction), and the
2-process sharded optimizer-state save/load parity contract."""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401
from mxnet_tpu import dist, telemetry
from mxnet_tpu.base import EvictedError, MXNetError

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def hb(tmp_path, monkeypatch):
    """A heartbeat dir with 2 configured workers and a pinned job-start
    anchor; yields (dir, touch(rank, age))."""
    d = str(tmp_path / "hb")
    os.makedirs(d)
    monkeypatch.setenv("MXNET_TPU_HEARTBEAT_DIR", d)
    monkeypatch.setenv("MXNET_TPU_NUM_WORKERS", "2")
    monkeypatch.setattr(dist, "_start_time", time.time() - 3600)
    monkeypatch.setattr(dist, "_last_dead", 0)
    # the dir itself was just created; its mtime must not re-anchor the
    # grace window forward of the pinned start
    old = time.time() - 3600
    os.utime(d, (old, old))

    def touch(rank, age=0.0):
        path = os.path.join(d, "worker-%d" % rank)
        with open(path, "a"):
            pass
        t = time.time() - age
        os.utime(path, (t, t))

    return d, touch


# ------------------------------------------------------------ num_dead_nodes
def test_dead_nodes_future_mtime_is_alive(hb):
    """Clock skew: a heartbeat file stamped in the FUTURE (NFS/skewed
    writer) has negative age and must count alive, not dead."""
    _, touch = hb
    touch(0, age=-300.0)  # 5 minutes in the future
    touch(1, age=0.0)
    assert dist.num_dead_nodes(timeout=60) == 0


def test_dead_nodes_exact_grace_boundary(hb, monkeypatch):
    """A worker that never heartbeated is alive AT the grace boundary
    (<=) and dead one instant past it. The clock is pinned so elapsed
    is EXACTLY the grace, not grace + scan latency."""
    _, touch = hb
    touch(0, age=0.0)  # worker 1 never wrote a file
    now = time.time()
    monkeypatch.setattr(time, "time", lambda: now)
    monkeypatch.setattr(dist, "_start_time", now - 30.0)
    assert dist.num_dead_nodes(timeout=60, startup_grace=30.0) == 0
    monkeypatch.setattr(dist, "_start_time", now - 30.001)
    assert dist.num_dead_nodes(timeout=60, startup_grace=30.0) == 1


def test_dead_nodes_dir_removed_mid_scan(hb, monkeypatch):
    """The launcher tears the heartbeat dir down at job end — a scan
    racing that returns 0 dead instead of raising/false-positive."""
    d, touch = hb
    touch(0)
    touch(1)
    assert dist.num_dead_nodes(timeout=60) == 0
    import shutil

    shutil.rmtree(d)
    assert dist.num_dead_nodes(timeout=60) == 0
    # ...and a dir that vanishes BETWEEN getmtime calls: the per-file
    # OSError path counts the missing file dead only past grace
    os.makedirs(d)
    os.utime(d, (time.time() - 3600,) * 2)
    touch(0)
    real_getmtime = os.path.getmtime

    def racing_getmtime(path):
        if path.endswith("worker-1"):
            raise OSError("vanished mid-scan")
        return real_getmtime(path)

    monkeypatch.setattr(os.path, "getmtime", racing_getmtime)
    assert dist.num_dead_nodes(timeout=60) == 1  # past grace: counts dead
    assert dist.num_dead_nodes(timeout=60, startup_grace=10 ** 9) == 0


def test_dead_alive_dead_transition_counter(hb):
    """The transition counter ticks on every dead-count CHANGE —
    dead->alive->dead is 2 changes after the first death, 3 total."""
    _, touch = hb
    telemetry.reset()
    saved = telemetry.current_override()
    telemetry.set_mode("counters")
    try:
        touch(0)
        touch(1, age=300.0)                     # stale -> dead
        assert dist.num_dead_nodes(timeout=60) == 1
        touch(1, age=0.0)                       # back alive
        assert dist.num_dead_nodes(timeout=60) == 0
        touch(1, age=300.0)                     # dead again
        assert dist.num_dead_nodes(timeout=60) == 1
        assert dist.num_dead_nodes(timeout=60) == 1  # no change, no tick
        assert telemetry.counter(
            "dist.dead_node_transitions").value == 3
    finally:
        telemetry.set_mode(saved)
        telemetry.reset()


# ------------------------------------------------------------- drain protocol
def test_stop_heartbeat_removes_file(hb, monkeypatch):
    d, _ = hb
    monkeypatch.setenv("MXNET_TPU_WORKER_ID", "0")
    monkeypatch.setattr(dist, "_initialized", True)
    monkeypatch.setattr(dist, "_heartbeat_thread", None)
    monkeypatch.setattr(dist, "_heartbeat_stop", None)
    monkeypatch.setenv("MXNET_TPU_HEARTBEAT_INTERVAL", "0.05")
    dist._start_heartbeat(0)
    assert dist.is_heartbeating()
    deadline = time.time() + 5
    path = os.path.join(d, "worker-0")
    while not os.path.exists(path) and time.time() < deadline:
        time.sleep(0.01)
    assert os.path.exists(path)
    dist.stop_heartbeat(remove=True)
    assert not dist.is_heartbeating()
    assert not os.path.exists(path)


# ------------------------------------------------------------- plan validation
@pytest.fixture
def elastic_world(monkeypatch):
    """Fake a 4-worker elastic membership (no coordination service needed
    for the pure plan logic)."""
    monkeypatch.setattr(dist, "_elastic", True)
    monkeypatch.setattr(dist, "_members", [0, 1, 2, 3])
    monkeypatch.setattr(dist, "_orig_rank", 1)
    monkeypatch.setattr(dist, "_orig_world", 4)
    monkeypatch.setattr(dist, "_generation", 0)


def test_plan_reform_survivor_set(elastic_world):
    plan = dist.plan_reform(dead=[3])
    assert plan == {"generation": 1, "members": [0, 1, 2], "dead": [3],
                    "rank": 1, "world": 3}


def test_plan_reform_coordinator_death_unrecoverable(elastic_world):
    with pytest.raises(MXNetError, match="coordinator"):
        dist.plan_reform(dead=[0, 3])


def test_plan_reform_min_workers(elastic_world, monkeypatch):
    monkeypatch.setenv("MXNET_ELASTIC_MIN_WORKERS", "3")
    with pytest.raises(MXNetError, match="MIN_WORKERS"):
        dist.plan_reform(dead=[2, 3])


def test_plan_reform_nothing_dead_raises(elastic_world):
    with pytest.raises(MXNetError, match="no dead"):
        dist.plan_reform(dead=[])


def test_plan_from_pause_evicts_self(elastic_world):
    with pytest.raises(EvictedError):
        dist.plan_from_pause({"generation": 1, "dead": [1],
                              "pause_at": 5, "proposer": 1})


def test_plan_from_pause_generation_mismatch(elastic_world):
    with pytest.raises(MXNetError, match="generation"):
        dist.plan_from_pause({"generation": 7, "dead": [3],
                              "pause_at": 5, "proposer": 0})


def test_evicted_error_is_mxnet_error():
    assert issubclass(EvictedError, MXNetError)


def test_elastic_enabled_env(monkeypatch):
    monkeypatch.delenv("MXNET_ELASTIC", raising=False)
    assert not dist.elastic_enabled()
    monkeypatch.setenv("MXNET_ELASTIC", "1")
    assert dist.elastic_enabled()
    monkeypatch.setenv("MXNET_ELASTIC", "off")
    assert not dist.elastic_enabled()


# --------------------------------------------- pause KV protocol (subprocess)
PAUSE_PROBE = r"""
import os, sys, json
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["MXNET_ELASTIC"] = "1"
os.environ["MXNET_TPU_COORDINATOR"] = "127.0.0.1:%(port)d"
os.environ["MXNET_TPU_NUM_WORKERS"] = "1"
os.environ["MXNET_TPU_WORKER_ID"] = "0"
sys.path.insert(0, %(root)r)
from mxnet_tpu import dist
dist.init()
assert dist.poll_pause() is None
p1 = dist.propose_pause([0], round_no=10, margin=2)
assert p1["pause_at"] == 12 and p1["dead"] == [0], p1
# first-write-wins: a second proposal adopts the FIRST payload
p2 = dist.propose_pause([0], round_no=99)
assert p2 == p1, (p1, p2)
seen = dist.poll_pause()
assert seen == p1, seen
print("PAUSE_PROTO_OK")
"""


def test_pause_kv_protocol_first_write_wins(tmp_path):
    """propose/poll over a real coordination service (1-proc, subprocess
    so the pytest process's jax state stays clean): first-write-wins,
    poll is non-blocking, payload round-trips."""
    import socket

    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    script = tmp_path / "pause_probe.py"
    script.write_text(PAUSE_PROBE % {"port": port, "root": ROOT})
    r = subprocess.run([sys.executable, str(script)], capture_output=True,
                       text=True, timeout=120)
    assert "PAUSE_PROTO_OK" in r.stdout, (r.stdout + r.stderr)[-800:]


# ------------------------------------- 2-proc sharded state save/load parity
SHARDED_WORKER = r"""
import os, sys, json
import numpy as np
sys.path.insert(0, %(root)r)
os.environ.setdefault("MXNET_KVSTORE_BUCKET_MB", "0.001")
os.environ["MXNET_KVSTORE_UPDATE"] = "sharded"
import mxnet_tpu as mx

SHAPES = [(40, 4), (40,), (16, 40), (16,)]
workdir = sys.argv[1]

def run(n_rounds, kv=None, start=0):
    if kv is None:
        kv = mx.kv.create("dist_tpu_sync")
        opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=1e-4,
                               rescale_grad=1.0 / 8)
        kv.set_optimizer(opt)
        rs = np.random.RandomState(5)
        for i, s in enumerate(SHAPES):
            kv.init(i, mx.nd.array(rs.rand(*s).astype("float32")))
    rank = kv.rank
    outs = {i: mx.nd.zeros(s) for i, s in enumerate(SHAPES)}
    for step in range(start, start + n_rounds):
        rs = np.random.RandomState(1000 + step)
        for i in reversed(range(len(SHAPES))):
            g = rs.rand(*SHAPES[i]).astype("float32") - 0.5
            kv.push(i, mx.nd.array(g * (rank + 1)), priority=-i)
        for i in range(len(SHAPES)):
            kv.pull(i, out=outs[i], priority=-i)
    kv._barrier()
    return kv, {i: o.asnumpy() for i, o in outs.items()}

# (a) continuous 6 rounds -> reference weights
kv, ref = run(6)
state_file = os.path.join(workdir, "opt.states")

# (b) 3 rounds, save, RELOAD into the same engine (same-W shard-direct:
#     plan hash matches -> preload path, momentum bit-parity), 3 more
kv2 = mx.kv.create("dist_tpu_sync")
opt2 = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=1e-4,
                        rescale_grad=1.0 / 8)
kv2.set_optimizer(opt2)
rs = np.random.RandomState(5)
for i, s in enumerate(SHAPES):
    kv2.init(i, mx.nd.array(rs.rand(*s).astype("float32")))
kv2, mid = run(3, kv=kv2)
if kv2.rank == 0:
    ws = {i: kv2._store[i].asnumpy() for i in range(len(SHAPES))}
kv2.save_optimizer_states(state_file)
kv2._barrier()
assert os.path.exists(state_file)
from mxnet_tpu import checkpoint as ckpt
assert ckpt.read_sharded_pointer(state_file) is not None, \
    "sharded save must write a pointer file"
kv2.load_optimizer_states(state_file)          # same-W shard-direct
kv2, direct = run(3, kv=kv2, start=3)
for i in ref:
    np.testing.assert_array_equal(direct[i], ref[i])  # BIT parity

# (c) fresh store with a DIFFERENT bucket plan -> re-flatten path
os.environ["MXNET_KVSTORE_BUCKET_MB"] = "0.0005"
kv3 = mx.kv.create("dist_tpu_sync")
opt3 = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=1e-4,
                        rescale_grad=1.0 / 8)
kv3.set_optimizer(opt3)
rs = np.random.RandomState(5)
for i, s in enumerate(SHAPES):
    kv3.init(i, mx.nd.array(rs.rand(*s).astype("float32")))
# replay rounds 0-2 to rebuild the weights at the save point, then load
# the step-3 states (different plan hash -> re-flattened per-key states)
kv3, _ = run(3, kv=kv3)
kv3.load_optimizer_states(state_file)
kv3, reflat = run(3, kv=kv3, start=3)
for i in ref:
    np.testing.assert_allclose(reflat[i], ref[i], atol=1e-6, rtol=0)

# (d) optimizer-kind guard: loading sgd states into adam raises
kv4 = mx.kv.create("dist_tpu_sync")
kv4.set_optimizer(mx.optimizer.Adam(learning_rate=0.01))
for i, s in enumerate(SHAPES):
    kv4.init(i, mx.nd.array(np.zeros(s, "float32")))
try:
    kv4.load_optimizer_states(state_file)
    raise AssertionError("kind mismatch must raise")
except mx.base.MXNetError as e:
    assert "not portable" in str(e), e
kv4._barrier()
print("SHARDED_STATES_OK rank", kv2.rank)
"""


@pytest.mark.slow
def test_sharded_optimizer_states_2proc_parity(tmp_path):
    """Acceptance: sharded-mode save/load no longer raises — same-W
    resume is momentum-BIT-parity (shard-direct preload), different-plan
    resume matches within fp32 tolerance (re-flatten), and cross-kind
    loads raise the structured portability error. 2 processes under the
    local launcher."""
    script = tmp_path / "sharded_worker.py"
    script.write_text(SHARDED_WORKER % {"root": ROOT})
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu"})
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", "--cpu-devices", "1",
         sys.executable, str(script), str(tmp_path)],
        capture_output=True, text=True, timeout=420, env=env, cwd=ROOT)
    assert r.returncode == 0 and "SHARDED_STATES_OK" in r.stdout, \
        (r.stdout + r.stderr)[-2000:]


# --------------------------------------- same-W fit(resume=True) bit parity
RESUME_WORKER = r"""
import os, sys
import numpy as np
sys.path.insert(0, %(root)r)
os.environ.setdefault("MXNET_KVSTORE_BUCKET_MB", "0.002")
os.environ["MXNET_KVSTORE_UPDATE"] = "sharded"
import mxnet_tpu as mx
from mxnet_tpu import dist

workdir = sys.argv[1]
BATCH, BATCHES = 8, 6

def mlp():
    s = mx.sym.Variable("data")
    s = mx.sym.FullyConnected(s, num_hidden=24, name="fc1")
    s = mx.sym.Activation(s, act_type="relu")
    s = mx.sym.FullyConnected(s, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(s, name="softmax")

mx.kv.create("dist_tpu_sync")
rank = int(os.environ.get("MXNET_TPU_WORKER_ID", "0"))
rs = np.random.RandomState(100 + rank)
x = rs.rand(BATCHES * BATCH, 8).astype("float32")
y = rs.randint(0, 4, (BATCHES * BATCH,)).astype("float32")

def fit(ckpt_dir, num_epoch, resume, period):
    mx.random.seed(7)  # identical init across the three runs
    it = mx.io.NDArrayIter(x, y, batch_size=BATCH)
    mod = mx.mod.Module(mlp(), context=mx.cpu(), fused_step=False)
    mod.fit(it, num_epoch=num_epoch, kvstore="dist_tpu_sync",
            optimizer="sgd",
            optimizer_params=(("learning_rate", 0.05), ("momentum", 0.9)),
            elastic={"checkpoint_dir": ckpt_dir, "checkpoint_period": period,
                     "resume": resume})
    a, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in a.items()}

# A: 4 epochs, a sharded checkpoint at every epoch end (period = rounds per
#    epoch) -- the final save IS the final state
dir_a = os.path.join(workdir, "ckpt-a")
fit(dir_a, 4, False, BATCHES)
# B: fresh module, same W + same plan -> load_sharded_checkpoint takes the
#    shard-direct-from-flats branch; train 2 more epochs
got_b = fit(dir_a, 6, True, BATCHES)
# C: uninterrupted 6-epoch reference
got_c = fit(os.path.join(workdir, "ckpt-c"), 6, False, BATCHES)
for k in got_c:
    np.testing.assert_array_equal(got_b[k], got_c[k])  # BIT parity
print("RESUME_PARITY_OK rank", rank)

# shard-direct-from-flats branch: a kv with a COMMITTED plan matching the
# manifest loads via the already-assembled flat buckets (no second read of
# our own shard file) -- the preloaded slices must bit-match the shard file
from mxnet_tpu import checkpoint as ckpt
step, manifest = ckpt.latest_complete(dir_a)
kv = mx.kv.create("dist_tpu_sync")
kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.05, momentum=0.9))
shapes = [(24, 8), (24,), (4, 24), (4,)]   # the mlp's params, in push order
for i, s in enumerate(shapes):
    kv.init(i, mx.nd.zeros(s))
outs = {i: mx.nd.zeros(s) for i, s in enumerate(shapes)}
for i in reversed(range(len(shapes))):
    kv.push(i, mx.nd.ones(shapes[i]), priority=-i)
for i in range(len(shapes)):
    kv.pull(i, out=outs[i], priority=-i)
kv._barrier()
eng = kv._bucket_engine
assert eng.plan is not None and eng.plan.hash == manifest["plan_hash"], \
    (eng.plan and eng.plan.hash, manifest["plan_hash"])
step2, _w = kv.load_sharded_checkpoint(dir_a)
assert step2 == step
local = ckpt.read_local_shard(dir_a, step, manifest, kv.rank)
n_states = manifest["optimizer"]["n_states"]
for b in manifest["plan"]["buckets"]:
    idx = int(b["index"])
    for i in range(n_states):
        np.testing.assert_array_equal(
            np.asarray(eng._preloaded_shards[idx][i]),
            local["b%%d.s%%d" %% (idx, i)])
kv._barrier()
print("FLATS_SLICE_OK rank", kv.rank)
"""


@pytest.mark.slow
def test_same_world_fit_resume_bit_parity(tmp_path):
    """``fit(elastic=..., resume=True)`` at the SAME world size + bucket
    plan takes ``load_sharded_checkpoint``'s shard-direct branch (flat
    shards sliced from the already-verified assembled buckets) and must be
    momentum-bit-parity: resumed training matches an uninterrupted run
    bit-for-bit. 2 processes under the local launcher."""
    script = tmp_path / "resume_worker.py"
    script.write_text(RESUME_WORKER % {"root": ROOT})
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu"})
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", "--cpu-devices", "1",
         sys.executable, str(script), str(tmp_path)],
        capture_output=True, text=True, timeout=420, env=env, cwd=ROOT)
    assert r.returncode == 0 and "RESUME_PARITY_OK" in r.stdout \
        and "FLATS_SLICE_OK" in r.stdout, (r.stdout + r.stderr)[-2000:]


@pytest.mark.slow
def test_elastic_chaos_smoke_small(tmp_path):
    """3-proc end-to-end drain/re-form/reseed parity (the 8-proc version
    runs in tools/ci_check.sh)."""
    r = subprocess.run(
        [sys.executable,
         os.path.join(ROOT, "tests", "nightly", "dist_elastic_chaos.py"),
         "--orchestrate", str(tmp_path / "chaos"), "--world", "3"],
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=ROOT)
    assert r.returncode == 0 and "dist_elastic_chaos" in r.stdout, \
        (r.stdout + r.stderr)[-2000:]
