"""Pallas matmul+BN-stats kernel (ops/pallas_matmul_stats.py): interpret-mode
correctness against numpy on CPU; the on-TPU timing story lives in
tools/fused_stats_bench.py and docs/PERF.md."""
import numpy as np
import pytest

from mxnet_tpu.ops.pallas_matmul_stats import matmul_with_stats, supported


@pytest.mark.parametrize("M,K,N,bm,bn", [
    (256, 64, 128, 64, 128),
    (1024, 32, 256, 512, 256),   # multi-tile both axes
    (512, 128, 128, 128, 128),
])
def test_matmul_with_stats_matches_numpy(M, K, N, bm, bn):
    import jax.numpy as jnp

    rs = np.random.RandomState(0)
    a = rs.randn(M, K).astype("float32")
    b = rs.randn(K, N).astype("float32")
    c, s, q = matmul_with_stats(jnp.asarray(a), jnp.asarray(b),
                                block_m=bm, block_n=bn, interpret=True)
    ref = a @ b
    np.testing.assert_allclose(np.asarray(c), ref, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(s), ref.sum(0), rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(np.asarray(q), (ref * ref).sum(0),
                               rtol=1e-4, atol=1e-3)
    assert s.dtype == np.float32 and q.dtype == np.float32


def test_supported_gates_tiling():
    assert supported(1024, 64, 256)
    assert not supported(1000, 64, 256)        # M not tileable
    assert not supported(1024, 64, 200)        # N not lane-aligned
