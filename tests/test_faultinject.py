"""Fault-injection subsystem (mxnet_tpu/faultinject.py,
docs/RESILIENCE.md): env parsing, the four kinds, determinism of the
seeded decision streams, the scoped context-manager API, zero-overhead
no-op path, and per-site counters."""
import errno
import os
import time

import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401
from mxnet_tpu import faultinject as fi
from mxnet_tpu import telemetry
from mxnet_tpu.base import MXNetError


@pytest.fixture(autouse=True)
def _clean_env():
    saved = os.environ.pop(fi.ENV_FAULTINJECT, None)
    fi.refresh()
    fi.reset_stats()
    yield
    if saved is None:
        os.environ.pop(fi.ENV_FAULTINJECT, None)
    else:
        os.environ[fi.ENV_FAULTINJECT] = saved
    fi.refresh()
    fi.reset_stats()


# ------------------------------------------------------------- no-op path
def test_unset_is_noop_and_allocation_free():
    for _ in range(100):
        fi.fire("serving.dispatch")
        assert fi.torn_fraction("checkpoint.write") is None
    assert fi.stats() == {}
    # the parse cache was never populated: the fast path bailed before it
    assert fi._env_cache == (None, {})


def test_empty_and_malformed_entries_do_not_raise():
    os.environ[fi.ENV_FAULTINJECT] = \
        "bogus,only:two,a:b:c:d,site:raise:2.0:1,,x:raise:notafloat:1"
    fi.refresh()
    fi.fire("site")  # every entry malformed -> no plans, no exception
    assert fi.stats() == {}


# ---------------------------------------------------------------- parsing
def test_env_plan_fires_and_counts():
    os.environ[fi.ENV_FAULTINJECT] = "my.site:raise:1.0:5"
    fi.refresh()
    with pytest.raises(fi.FaultInjected) as ei:
        fi.fire("my.site")
    assert ei.value.site == "my.site"
    assert isinstance(ei.value, MXNetError)
    fi.fire("other.site")  # plans are per-site
    assert fi.stats() == {"my.site:raise": 1}


def test_env_multiple_plans_and_arg():
    os.environ[fi.ENV_FAULTINJECT] = \
        "a.site:delay_ms:1.0:1:30,b.site:raise:1.0:2"
    fi.refresh()
    t0 = time.perf_counter()
    fi.fire("a.site")
    assert time.perf_counter() - t0 >= 0.025
    with pytest.raises(fi.FaultInjected):
        fi.fire("b.site")


def test_raise_with_errno_arg_is_a_real_oserror():
    with fi.inject("s", "raise", prob=1.0, seed=0, arg="ENOSPC"):
        with pytest.raises(OSError) as ei:
            fi.fire("s")
    assert ei.value.errno == errno.ENOSPC


def test_hang_kind_sleeps_arg_seconds():
    with fi.inject("s", "hang", prob=1.0, seed=0, arg=0.05, times=1):
        t0 = time.perf_counter()
        fi.fire("s")
        assert time.perf_counter() - t0 >= 0.04


# ------------------------------------------------------------ determinism
def _sequence(n):
    """Which of n fire() calls raise, for the current env config."""
    fired = []
    for i in range(n):
        try:
            fi.fire("det.site")
        except fi.FaultInjected:
            fired.append(i)
    return fired


def test_same_seed_same_injected_event_sequence():
    os.environ[fi.ENV_FAULTINJECT] = "det.site:raise:0.3:1234"
    fi.refresh()
    first = _sequence(200)
    fi.refresh()  # fresh RNG stream, same seed
    second = _sequence(200)
    assert first == second
    assert 20 < len(first) < 100  # prob 0.3 actually drew


def test_different_seed_different_sequence():
    os.environ[fi.ENV_FAULTINJECT] = "det.site:raise:0.3:1234"
    fi.refresh()
    first = _sequence(200)
    os.environ[fi.ENV_FAULTINJECT] = "det.site:raise:0.3:99"
    fi.refresh()
    assert _sequence(200) != first


def test_context_manager_determinism():
    seqs = []
    for _ in range(2):
        fired = []
        with fi.inject("c.site", "raise", prob=0.5, seed=7):
            for i in range(100):
                try:
                    fi.fire("c.site")
                except fi.FaultInjected:
                    fired.append(i)
        seqs.append(fired)
    assert seqs[0] == seqs[1]


# -------------------------------------------------------- context manager
def test_inject_times_cap_and_scope():
    with fi.inject("t.site", "raise", prob=1.0, seed=0, times=2) as plan:
        for _ in range(2):
            with pytest.raises(fi.FaultInjected):
                fi.fire("t.site")
        fi.fire("t.site")  # capped: no more fires
        assert plan.fired == 2
    fi.fire("t.site")  # out of scope: clean
    assert fi.stats() == {"t.site:raise": 2}


def test_inject_nests_and_overlays_env():
    os.environ[fi.ENV_FAULTINJECT] = "n.site:delay_ms:1.0:1:5"
    fi.refresh()
    with fi.inject("n.site", "raise", prob=1.0, seed=0, times=1):
        with pytest.raises(fi.FaultInjected):
            fi.fire("n.site")  # ctx plan evaluates before the env plan
    t0 = time.perf_counter()
    fi.fire("n.site")  # env delay plan still live after the ctx exits
    assert time.perf_counter() - t0 >= 0.004
    counts = fi.stats()
    assert counts["n.site:raise"] == 1 and counts["n.site:delay_ms"] >= 1


# -------------------------------------------------------------- torn_write
def test_torn_write_truncates_and_raises_eio(tmp_path):
    from mxnet_tpu.checkpoint import atomic_write_bytes

    path = str(tmp_path / "blob.bin")
    atomic_write_bytes(path, b"x" * 100)
    with fi.inject("checkpoint.write", "torn_write", prob=1.0, seed=0,
                   arg=0.25, times=1):
        with pytest.raises(OSError) as ei:
            atomic_write_bytes(path, b"y" * 100)
    assert ei.value.errno == errno.EIO
    # the FINAL file is untouched (atomicity survives the injector)...
    with open(path, "rb") as f:
        assert f.read() == b"x" * 100
    # ...and the torn prefix landed in the temp file
    torn = [p for p in os.listdir(str(tmp_path)) if ".tmp." in p]
    assert torn and os.path.getsize(str(tmp_path / torn[0])) == 25


def test_torn_fraction_none_at_other_sites():
    with fi.inject("checkpoint.write", "torn_write", prob=1.0, seed=0):
        assert fi.torn_fraction("io.prefetch") is None
        # and fire() at the torn site does nothing (torn is write-only)
        fi.fire("checkpoint.write")
        assert "checkpoint.write:raise" not in fi.stats()


# --------------------------------------------------------------- counters
def test_telemetry_counters_per_site(tm_counters=None):
    telemetry.reset()
    saved = telemetry.current_override()
    try:
        telemetry.set_mode("counters")
        with fi.inject("cnt.site", "raise", prob=1.0, seed=0, times=3):
            for _ in range(3):
                with pytest.raises(fi.FaultInjected):
                    fi.fire("cnt.site")
        c = telemetry.counters()
        assert c["faultinject.fired"] == 3
        assert c["faultinject.cnt.site.raise"] == 3
    finally:
        telemetry.set_mode(saved)
        telemetry.reset()


def test_prefetch_site_surfaces_to_consumer():
    from mxnet_tpu.io import NDArrayIter, PrefetchingIter

    base = NDArrayIter(np.zeros((8, 3), "float32"),
                       np.zeros((8,), "float32"), batch_size=4)
    with fi.inject("io.prefetch", "raise", prob=1.0, seed=0, times=1):
        it = PrefetchingIter(base)
        with pytest.raises(fi.FaultInjected):
            for _ in it:
                pass
